"""Core value types shared by the protocol, the ports, and applications.

Parity: reference pkg/types/types.go:18-123 (Proposal, Signature, Decision,
RequestInfo, Checkpoint, Reconfig, SyncResponse).  The digest construction is
deterministic SHA-256 over a length-prefixed field encoding (the reference
uses ASN.1 + SHA-256, pkg/types/types.go:50-62; byte-compatibility with the Go
wire is a non-goal — shape compatibility is).
"""

from __future__ import annotations

import hashlib
import struct
import threading
from dataclasses import dataclass, field, replace
from typing import Optional, Sequence


def _lp(buf: bytes) -> bytes:
    """Length-prefix a byte string (u64 big-endian) for deterministic hashing."""
    return struct.pack(">Q", len(buf)) + buf


@dataclass(frozen=True)
class RequestInfo:
    """Identity of a client request: (client id, request id).

    Parity: reference pkg/types/types.go:44-48.
    """

    client_id: str
    request_id: str

    def key(self) -> str:
        return self.client_id + "\x00" + self.request_id

    def __str__(self) -> str:  # used in logs
        return f"{self.client_id}/{self.request_id}"


@dataclass(frozen=True)
class Proposal:
    """A batch of requests assembled by the leader, plus consensus metadata.

    ``payload`` carries the application batch, ``header`` application framing,
    ``metadata`` the serialized ViewMetadata stamped by the leader, and
    ``verification_sequence`` the membership/config epoch under which the
    proposal must be verified.  Parity: reference pkg/types/types.go:18-30.
    """

    payload: bytes = b""
    header: bytes = b""
    metadata: bytes = b""
    verification_sequence: int = 0

    def digest(self) -> str:
        """Deterministic content digest (hex), cached per instance — the hot
        protocol paths (prepare/commit digest matching, WAL records) call
        this repeatedly on the same immutable proposal.

        Parity: reference pkg/types/types.go:50-62 (ASN.1+SHA-256 there).
        """
        cached = getattr(self, "_digest_cache", None)
        if cached is not None:
            return cached
        h = hashlib.sha256()
        h.update(struct.pack(">Q", self.verification_sequence))
        h.update(_lp(self.header))
        h.update(_lp(self.payload))
        h.update(_lp(self.metadata))
        value = h.hexdigest()
        # Frozen dataclass: bypass the immutability guard for the memo only
        # (not a field — equality/repr/replace are unaffected).
        object.__setattr__(self, "_digest_cache", value)
        return value


@dataclass(frozen=True)
class Signature:
    """A consenter's signature over a proposal.

    ``msg`` is auxiliary signed payload (the reference threads the
    prepare-sender id list through it for blacklist redemption voting —
    internal/bft/view.go:472-481).  Parity: reference pkg/types/types.go:32-37.
    """

    id: int
    value: bytes = b""
    msg: bytes = b""


@dataclass(frozen=True)
class Decision:
    """A committed proposal together with its quorum of signatures.

    ``signatures`` is either a plain tuple of :class:`Signature` (the full
    cert, ``cert_mode="full"``) or a :class:`QuorumCert` — which quacks like
    that tuple (len / iteration / indexing yield per-signer ``Signature``
    views) so cert-shape-agnostic consumers need no branch.
    Parity: reference pkg/types/types.go:39-42.
    """

    proposal: Proposal
    signatures: "tuple[Signature, ...] | QuorumCert" = ()


@dataclass(frozen=True)
class QuorumCert:
    """Half-aggregated Ed25519 quorum certificate (arXiv:2302.00418).

    Instead of n full 64-byte signatures, the cert keeps each signer's
    32-byte nonce commitment ``Rᵢ`` plus ONE aggregate scalar
    ``s_agg = Σ zᵢ·sᵢ mod L`` under transcript-derived Fiat–Shamir
    coefficients — ~64n bytes shrink to ~32n + 32.  ``aux_table`` holds the
    deduplicated per-signer auxiliary payloads (Signature.msg), indexed by
    ``aux_index`` so the common all-identical-aux case costs one entry.

    The sequence protocol (``len`` / iteration / indexing) yields
    per-component :class:`Signature` views with ``value=Rᵢ`` — enough for
    every signer-identity consumer (quorum counting, blacklists, epoch
    checks).  Those views do NOT verify individually; a cert only verifies
    as a whole through ``Verifier.verify_aggregate_cert``.
    """

    signer_ids: tuple[int, ...] = ()
    rs: tuple[bytes, ...] = ()
    s_agg: bytes = b""
    aux_table: tuple[bytes, ...] = ()
    aux_index: tuple[int, ...] = ()

    def __len__(self) -> int:
        return len(self.signer_ids)

    def __iter__(self):
        return (self[i] for i in range(len(self.signer_ids)))

    def __getitem__(self, i):
        if isinstance(i, slice):
            return tuple(
                self[j] for j in range(*i.indices(len(self.signer_ids)))
            )
        return Signature(
            id=self.signer_ids[i],
            value=self.rs[i],
            msg=self.aux_table[self.aux_index[i]],
        )


def as_cert(signatures):
    """Preserve a :class:`QuorumCert` through call sites that historically
    flattened signature sequences with ``tuple(...)`` — flattening a cert
    to its component views would silently discard ``s_agg``."""
    if isinstance(signatures, QuorumCert):
        return signatures
    return tuple(signatures)


@dataclass(frozen=True)
class Reconfig:
    """Signals that the latest decision changed membership or configuration.

    Parity: reference pkg/types/types.go:107-111.
    """

    in_latest_decision: bool = False
    current_nodes: tuple[int, ...] = ()
    current_config: Optional["object"] = None  # Configuration; avoid cycle
    #: Optional membership.MembershipConfig for the epoch this decision
    #: opens (held opaque: types must not import the membership package).
    #: None preserves the pre-epoch Reconfig shape — consumers that only
    #: need the node set keep reading current_nodes.
    membership: Optional["object"] = None


@dataclass(frozen=True)
class SyncResponse:
    """Result of Synchronizer.sync(): the latest decision plus any reconfig.

    Parity: reference pkg/types/types.go:113-116.
    """

    latest: Optional[Decision] = None
    reconfig: Reconfig = field(default_factory=Reconfig)


@dataclass(frozen=True)
class ViewSequence:
    """A replica's current (view, proposal sequence) and whether the view is
    active.  Exchanged in state-transfer responses.

    Parity: reference internal/bft types (ViewSequence in controller.go).
    """

    view_active: bool = False
    view: int = 0
    seq: int = 0


class Checkpoint:
    """Thread-safe holder of the last decided proposal + its signature quorum.

    Fed on every decision and by sync; anchors view changes (the last-decision
    proof inside ViewData) and the leader's proposal metadata.
    Parity: reference pkg/types/types.go:71-105.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._proposal: Proposal = Proposal()
        self._signatures: tuple[Signature, ...] = ()

    def get(self) -> tuple[Proposal, tuple[Signature, ...]]:
        with self._lock:
            return self._proposal, self._signatures

    def set(self, proposal: Proposal, signatures: Sequence[Signature]) -> None:
        with self._lock:
            self._proposal = proposal
            self._signatures = as_cert(signatures)


__all__ = [
    "RequestInfo",
    "Proposal",
    "Signature",
    "Decision",
    "QuorumCert",
    "as_cert",
    "Reconfig",
    "SyncResponse",
    "ViewSequence",
    "Checkpoint",
    "replace",
]
