"""The Controller: owns the current View, routes messages, runs leader
duties, drives sync, and anchors failure detection.

Parity: reference internal/bft/controller.go (965 LoC).  Structural
deviations, all consequences of the single-threaded runtime:

* The reference's channel plumbing (``decisionChan`` / ``deliverChan`` /
  ``leaderToken`` / ``syncChan``, controller.go:489-526) collapses into plain
  method calls and scheduler posts — the View calls ``decide`` synchronously,
  and delivery happens inline before the next message is processed, which is
  exactly the serialization ``MutuallyExclusiveDeliver`` + ``deliverChan``
  reconstruct with locks (controller.go:873-890, 928-965).  The
  sequence-already-synced guard inside the reference's wrapper is kept
  (``_deliver_checked``).
* The leader token (controller.go:748-761) becomes a boolean + a scheduled
  ``_propose`` continuation; the batcher hands batches back via callback.
* ``sync()`` (controller.go:576-680) becomes a state-machine step chain:
  synchronizer → state-fetch window (collector callback) → view math.
"""

from __future__ import annotations

import logging
from typing import Callable, Optional, Protocol, Sequence

from consensus_tpu.api.deps import (
    Application,
    Assembler,
    Comm,
    Signer,
    Synchronizer,
    Verifier,
)
from consensus_tpu.config import Configuration
from consensus_tpu.core.batcher import Batcher
from consensus_tpu.core.collector import StateCollector
from consensus_tpu.core.heartbeat import HeartbeatMonitor, Role
from consensus_tpu.core.pool import RequestPool
from consensus_tpu.core.state import InFlightData, PersistedState, ProposalMaker
from consensus_tpu.core.view import Phase, View
from consensus_tpu.metrics import Metrics
from consensus_tpu.runtime.scheduler import Scheduler
from consensus_tpu.trace.tracer import NOOP_TRACER
from consensus_tpu.types import Checkpoint, Proposal, Reconfig, RequestInfo, Signature
from consensus_tpu.utils.leader import get_leader_id
from consensus_tpu.utils.quorum import compute_quorum
from consensus_tpu.wire import (
    Commit,
    ConsensusMessage,
    HeartBeat,
    HeartBeatResponse,
    NewView,
    PrePrepare,
    Prepare,
    SavedNewView,
    SignedViewData,
    StateTransferRequest,
    StateTransferResponse,
    ViewChange,
    ViewMetadata,
    decode_view_metadata,
    msg_to_string,
)

logger = logging.getLogger("consensus_tpu.controller")

#: TEST-ONLY seeded bug: when True, a replica IGNORES a decision that carried
#: a reconfiguration — no rebuild, no eviction, no epoch advance — so the
#: retired committee keeps certifying decisions after its removal.  The
#: epoch-aware invariant monitor (testing/invariants.py) must catch the
#: resulting quorum certs signed by evicted members.  Never set outside
#: tests (see tests for the fixture that arms and disarms it).
SENTINEL_STALE_MEMBERSHIP = False

#: TEST-ONLY seeded bug: when True, a replica that quarantined a corrupt WAL
#: suffix skips the learner fence entirely — it keeps voting from its
#: amnesiac state before verified sync has carried it past the last intact
#: record.  The learner-fence invariant (testing/invariants.py via the chaos
#: engine's delivery hooks) must catch the resulting votes, because a vote
#: the replica already persisted-and-sent from the quarantined suffix could
#: be re-issued differently (SAFETY.md §13).  Never set outside tests.
SENTINEL_EAGER_UNFENCE = False


class ViewChangerPort(Protocol):
    """What the controller needs from the view changer (it is also the
    failure detector: a complaint is a vote to change views)."""

    def handle_message(self, sender: int, msg: ConsensusMessage) -> None: ...

    def handle_view_message(self, sender: int, msg: ConsensusMessage) -> None:
        """Feed 3-phase traffic to the embedded in-flight view (if any)."""

    def start_view_change(self, view: int, stop_view: bool) -> None: ...

    def inform_new_view(self, view: int) -> None: ...


class Controller:
    def __init__(
        self,
        *,
        scheduler: Scheduler,
        config: Configuration,
        nodes: Sequence[int],
        comm: Comm,
        application: Application,
        assembler: Assembler,
        verifier: Verifier,
        signer: Signer,
        synchronizer: Synchronizer,
        pool: RequestPool,
        batcher: Batcher,
        leader_monitor: HeartbeatMonitor,
        collector: StateCollector,
        state: PersistedState,
        in_flight: InFlightData,
        checkpoint: Checkpoint,
        proposer_builder: ProposalMaker,
        view_changer: Optional[ViewChangerPort] = None,
        on_reconfig: Optional[Callable[[Reconfig], None]] = None,
        metrics: Optional[Metrics] = None,
        tracer=None,
    ) -> None:
        self._sched = scheduler
        self._config = config
        self.id = config.self_id
        self.nodes = tuple(nodes)
        self.n = len(self.nodes)
        self.quorum, self.f = compute_quorum(self.n)
        self._comm = comm
        self._application = application
        self._assembler = assembler
        self._verifier = verifier
        self._signer = signer
        self._synchronizer = synchronizer
        self.pool = pool
        self.batcher = batcher
        self.leader_monitor = leader_monitor
        self.collector = collector
        self._state = state
        self.in_flight = in_flight
        self.checkpoint = checkpoint
        self._proposer_builder = proposer_builder
        self.view_changer = view_changer
        self._on_reconfig = on_reconfig
        self.metrics = metrics or Metrics()
        self._tracer = tracer if tracer is not None else NOOP_TRACER

        self.curr_view_number = 0
        self.curr_decisions_in_view = 0
        self.curr_view: Optional[View] = None
        self._verification_sequence = 0
        self._leader_token = False
        self._propose_pending = False
        self._batch_outstanding = False
        self._sync_in_progress = False
        self._stopped = True
        #: Membership epoch this controller serves (the facade stamps it
        #: after construction; a reconfiguration builds a NEW controller).
        self.membership_epoch = 0
        # Set the moment a reconfiguration surfaces (decide or sync) and
        # never cleared: the rebuild discards this instance.  While pending,
        # queued commits for higher slots must NOT deliver — their certs
        # belong to the retired membership (SAFETY.md §8).
        self._reconfig_pending = False
        # Storage fence: while _fence_height is set this replica is a
        # NON-VOTING LEARNER — it quarantined a corrupt WAL suffix and may
        # have forgotten votes it already sent, so it must not vote again
        # until verified sync carries its checkpoint past _fence_release
        # (SAFETY.md §13).  _wal_degraded suspends proposing/voting while
        # the WAL refuses appends (persist-before-send has nothing durable
        # to stand on) but needs no fence: nothing was forgotten.
        self._fence_height: Optional[int] = None
        self._fence_release: Optional[int] = None
        self._fence_resync_timer = None
        self._wal_degraded = False

    # ------------------------------------------------------------ identity

    def leader_id(self) -> int:
        """Deterministic leader for the current position.

        Parity: reference controller.go:169-183 + util.go:79-107."""
        blacklist: tuple[int, ...] = ()
        if self._config.leader_rotation:
            proposal, _ = self.checkpoint.get()
            if proposal.metadata:
                blacklist = tuple(decode_view_metadata(proposal.metadata).black_list)
        return get_leader_id(
            self.curr_view_number,
            self.n,
            self.nodes,
            leader_rotation=self._config.leader_rotation,
            decisions_in_view=self.curr_decisions_in_view,
            decisions_per_leader=self._config.decisions_per_leader,
            blacklist=blacklist,
        )

    def i_am_the_leader(self) -> bool:
        return self.leader_id() == self.id

    def latest_seq(self) -> int:
        """Sequence of the last checkpointed decision (0 if none)."""
        proposal, _ = self.checkpoint.get()
        if not proposal.metadata:
            return 0
        return decode_view_metadata(proposal.metadata).latest_sequence

    def view_sequence(self) -> tuple[bool, int]:
        """(view_active, in-progress sequence) — for heartbeats and state
        transfer responses."""
        v = self.curr_view
        if v is None or v.stopped:
            return False, 0
        return True, v.proposal_sequence

    def health(self) -> dict:
        """Derived health snapshot for the observability sampler
        (consensus_tpu/obs/): everything is a plain read of existing state,
        so sampling cannot perturb the protocol."""
        active, seq = self.view_sequence()
        v = self.curr_view
        return {
            "view": self.curr_view_number,
            "leader": self.leader_id(),
            "seq": seq,
            "view_active": active,
            "decisions_in_view": self.curr_decisions_in_view,
            "in_flight": v.in_flight_depth() if v is not None else 0,
            "syncing": self._sync_in_progress,
            "epoch": self.membership_epoch,
            "fenced": self.fence_required(),
            "wal_degraded": self._wal_degraded,
        }

    # ----------------------------------------------------------- lifecycle

    def start(
        self,
        start_view_number: int,
        start_proposal_sequence: int,
        start_decisions_in_view: int,
        sync_on_start: bool = False,
    ) -> None:
        """Parity: reference controller.go:781-811."""
        self._stopped = False
        self._verification_sequence = self._verifier.verification_sequence()
        if sync_on_start:
            def after(view: int, seq: int, decisions: int) -> None:
                v, s, d = start_view_number, start_proposal_sequence, start_decisions_in_view
                if view > v:
                    v, d = view, decisions
                if seq > s:
                    s, d = seq, decisions
                self.curr_view_number = v
                self.curr_decisions_in_view = d
                self._start_view(s)

            self._do_sync(on_complete=after)
            return
        self.curr_view_number = start_view_number
        self.curr_decisions_in_view = start_decisions_in_view
        self._start_view(start_proposal_sequence)

    def stop(self, *, pool_pause_only: bool = False) -> None:
        """Parity: reference controller.go:834-871 (Stop/StopWithPoolPause)."""
        self._stopped = True
        self._leader_token = False
        if self._fence_resync_timer is not None:
            self._fence_resync_timer.cancel()
            self._fence_resync_timer = None
        self.batcher.close()
        if pool_pause_only:
            self.pool.stop_timers()
        else:
            self.pool.close()
        self.leader_monitor.close()
        self.collector.close()
        if self.curr_view is not None:
            self.curr_view.abort()

    @property
    def stopped(self) -> bool:
        return self._stopped

    def _start_view(self, proposal_sequence: int) -> None:
        """Parity: reference controller.go:375-396."""
        view, init_phase = self._proposer_builder.new_proposer(
            self.leader_id(),
            proposal_sequence,
            self.curr_view_number,
            self.curr_decisions_in_view,
        )
        self.curr_view = view
        view.start()
        if self.i_am_the_leader():
            if init_phase in (Phase.COMMITTED, Phase.ABORT):
                self._acquire_leader_token()
            self.leader_monitor.change_role(
                Role.LEADER, self.curr_view_number, self.leader_id()
            )
        else:
            self.leader_monitor.change_role(
                Role.FOLLOWER, self.curr_view_number, self.leader_id()
            )
        logger.info(
            "%d: started view %d at seq %d (leader %d)",
            self.id, self.curr_view_number, proposal_sequence, self.leader_id(),
        )
        if self._tracer.enabled:
            self._tracer.instant(
                "controller",
                "viewchange.exit",
                view=self.curr_view_number,
                seq=proposal_sequence,
                leader=self.leader_id(),
            )

    def change_view(
        self, new_view_number: int, new_proposal_sequence: int, new_decisions: int
    ) -> None:
        """Parity: reference controller.go:398-426."""
        if self.curr_view_number > new_view_number:
            return
        if (
            self.curr_view is not None
            and not self.curr_view.stopped
            and self.curr_view_number == new_view_number
            and self.curr_view.leader_id == self.leader_id()
            and self.curr_decisions_in_view == new_decisions
        ):
            return
        self._abort_view(self.curr_view_number)
        self.curr_view_number = new_view_number
        self.curr_decisions_in_view = new_decisions
        self._start_view(new_proposal_sequence)
        if self.i_am_the_leader():
            self.batcher.reset()

    def _abort_view(self, view: int) -> bool:
        if view < self.curr_view_number:
            return False
        if self._tracer.enabled:
            self._tracer.instant("controller", "viewchange.enter", view=view)
        self._leader_token = False
        if self.curr_view is not None:
            self.curr_view.abort()
        # Abandon pipelined slots above the oldest undecided one: the view
        # change may only ever adopt the oldest (SAFETY.md §5), and a stale
        # higher entry would otherwise shadow it after the next decide.
        # No-op at depth 1 (at most one entry in flight).
        self.in_flight.drop_above_oldest()
        # Slots that will never decide must hand their requests back to the
        # batcher (the new view's leader re-batches them from the pool).
        self.pool.release_reservations()
        return True

    # ------------------------------------------------------------- ingress

    def process_message(self, sender: int, msg: ConsensusMessage) -> None:
        """Top-level message router.

        Parity: reference controller.go:321-373 (ProcessMessages)."""
        if self._stopped:
            return
        if isinstance(msg, (PrePrepare, Prepare, Commit)):
            if self._voting_suspended():
                # Fenced learner / degraded WAL: drop 3-phase traffic (we
                # must not vote), but still count leader traffic as a
                # heartbeat so the monitor doesn't manufacture complaints
                # about a leader that is in fact making progress.
                if sender == self.leader_id():
                    self.leader_monitor.inject_artificial_heartbeat(
                        sender, HeartBeat(view=msg.view, seq=msg.seq)
                    )
                return
            if self.curr_view is not None:
                self.curr_view.handle_message(sender, msg)
            if self.view_changer is not None:
                self.view_changer.handle_view_message(sender, msg)
            if sender == self.leader_id():
                self.leader_monitor.inject_artificial_heartbeat(
                    sender, HeartBeat(view=msg.view, seq=msg.seq)
                )
        elif isinstance(msg, (ViewChange, SignedViewData, NewView)):
            if self._voting_suspended():
                # View-change participation is also a vote (and carries our
                # possibly-amnesiac state); the fenced replica re-learns
                # view math from verified sync instead.
                return
            if self.view_changer is not None:
                self.view_changer.handle_message(sender, msg)
        elif isinstance(msg, (HeartBeat, HeartBeatResponse)):
            self.leader_monitor.process_msg(sender, msg)
        elif isinstance(msg, StateTransferRequest):
            active, seq = self.view_sequence()
            self._comm.send_consensus(
                sender,
                StateTransferResponse(
                    view_num=self.curr_view_number,
                    sequence=seq if active else self.latest_seq(),
                ),
            )
        elif isinstance(msg, StateTransferResponse):
            self.collector.handle_response(sender, msg)
        else:
            logger.warning("%d: unknown message %s from %d", self.id, msg, sender)

    # --------------------------------------------------------- requests

    def submit_request(self, raw: bytes, on_done=None) -> None:
        """Client ingress.  Parity: reference controller.go:249-264."""
        if self._stopped:
            if on_done:
                on_done("not running")
            return
        self.pool.submit(raw, on_done)

    def handle_request(self, sender: int, raw: bytes) -> None:
        """A follower forwarded a request to us (the presumed leader):
        verify, then pool it.  Parity: reference controller.go:233-246."""
        if not self.i_am_the_leader():
            logger.warning("%d: got forwarded request but not leader", self.id)
            return
        try:
            self._verifier.verify_request(raw)
        except Exception as e:
            logger.warning("%d: forwarded request failed verification: %s", self.id, e)
            return
        self.pool.submit(raw)

    # Pool timeout cascade (RequestTimeoutHandler).
    def on_request_timeout(self, raw: bytes, info: RequestInfo) -> None:
        leader = self.leader_id()
        if leader == self.id:
            return
        logger.debug("%d: forwarding %s to leader %d", self.id, info, leader)
        self._comm.send_transaction(leader, raw)

    def on_leader_fwd_request_timeout(self, raw: bytes, info: RequestInfo) -> None:
        logger.warning("%d: complaining about leader (request %s)", self.id, info)
        self.complain(self.curr_view_number, stop_view=False)

    def on_auto_remove_timeout(self, info: RequestInfo) -> None:
        pass  # pool already dropped it

    # Heartbeat events (HeartbeatEventHandler).
    def on_heartbeat_timeout(self, view: int, leader_id: int) -> None:
        if view != self.curr_view_number:
            return
        logger.warning("%d: heartbeat timeout on leader %d", self.id, leader_id)
        self.complain(view, stop_view=False)

    def complain(self, view: int, stop_view: bool) -> None:
        """FailureDetector seam.  Parity: consensus.go wires the view changer
        here (pkg/consensus/consensus.go:69-73)."""
        if self._voting_suspended():
            # A complaint is a vote to change views; a fenced learner (or a
            # replica whose WAL refuses appends) must not cast it.
            return
        if self.view_changer is not None:
            self.view_changer.start_view_change(view, stop_view)

    # --------------------------------------- storage fence / degraded WAL

    def fence_as_learner(self, intact_height: int) -> None:
        """Suspend voting after WAL corruption was quarantined: this replica
        may have forgotten votes it already sent from the quarantined
        suffix, so re-voting those slots could equivocate.  It keeps
        serving reads and state transfer, and resumes voting only once a
        verified sync carries its checkpoint past a release bound above the
        last intact record (SAFETY.md §13)."""
        if self._fence_height is not None:
            return  # already fenced; keep the original intact height
        self._fence_height = max(0, int(intact_height))
        self._fence_release = None
        logger.warning(
            "%d: fencing as non-voting learner (intact height %d)",
            self.id, self._fence_height,
        )
        if self._tracer.enabled:
            self._tracer.instant(
                "controller", "fence.enter", intact=self._fence_height
            )
        self._leader_token = False
        self.batcher.close()
        if not self._stopped:
            self.sync()

    def fence_required(self) -> bool:
        """Ground truth for the invariant monitor: True whenever the fence
        bookkeeping says this replica must not vote — deliberately
        independent of the SENTINEL_EAGER_UNFENCE enforcement bypass, so a
        seeded eager-unfence bug is observable from the outside."""
        return self._fence_height is not None

    def _fence_active(self) -> bool:
        if SENTINEL_EAGER_UNFENCE:
            return False
        return self._fence_height is not None

    def _voting_suspended(self) -> bool:
        return self._wal_degraded or self._fence_active()

    def set_wal_degraded(self, degraded: bool) -> None:
        """WAL degrade hook (wal/log.py degrade_hooks): while the log
        refuses appends, persist-before-send has nothing durable to stand
        on, so stop proposing and voting; auto-resume when it heals."""
        degraded = bool(degraded)
        if degraded == self._wal_degraded:
            return
        self._wal_degraded = degraded
        if degraded:
            logger.warning(
                "%d: WAL degraded; suspending proposing/voting", self.id
            )
            self._leader_token = False
            return
        logger.info("%d: WAL recovered; resuming consensus duties", self.id)
        if not self._stopped and self.i_am_the_leader():
            self._acquire_leader_token()

    def _maybe_release_fence(self) -> None:
        """Called whenever the checkpoint advances.  The first verified
        sync after fencing pins the release bound: any vote this replica
        sent from the quarantined suffix was persisted first
        (persist-before-send), so its slot sits at most ``pipeline_depth``
        above what the cluster had decided when we crashed — which is at
        most the synced height.  Once the checkpoint passes that bound,
        every slot we could have voted on is decided and certified by
        others, and re-joining the voter set cannot equivocate."""
        if self._fence_height is None:
            return
        latest = self.latest_seq()
        if self._fence_release is None:
            self._fence_release = (
                max(latest, self._fence_height)
                + max(1, self._config.pipeline_depth)
            )
            logger.info(
                "%d: fence release bound set at seq %d (synced %d)",
                self.id, self._fence_release, latest,
            )
        if latest >= self._fence_release:
            logger.info(
                "%d: fence released at seq %d; resuming voting",
                self.id, latest,
            )
            if self._tracer.enabled:
                self._tracer.instant(
                    "controller", "fence.exit",
                    seq=latest, release=self._fence_release,
                )
            self._fence_height = None
            self._fence_release = None
            if self._fence_resync_timer is not None:
                self._fence_resync_timer.cancel()
                self._fence_resync_timer = None
            if (
                not self._stopped
                and self.i_am_the_leader()
                and not self._voting_suspended()
            ):
                self._acquire_leader_token()
            return
        # Still short of the bound: keep pulling verified state.
        if self._fence_resync_timer is None and not self._stopped:
            self._fence_resync_timer = self._sched.call_later(
                self._config.view_change_resend_interval,
                self._fence_resync,
                name="fence-resync",
            )

    def _fence_resync(self) -> None:
        self._fence_resync_timer = None
        if self._stopped or self._fence_height is None:
            return
        self.sync()

    # ------------------------------------------------------------ proposing

    def _acquire_leader_token(self) -> None:
        """Parity: reference controller.go:748-755 — but as a scheduled
        continuation instead of a channel token."""
        if self._leader_token or self._voting_suspended():
            return
        self._leader_token = True
        if not self._propose_pending:
            self._propose_pending = True
            self._sched.post(self._propose, name="leader-propose")

    def _propose(self) -> None:
        self._propose_pending = False
        if not self._leader_token or self._stopped or self._batch_outstanding:
            return
        self._leader_token = False
        if self.batcher.closed:
            # View change / sync in progress: the token is re-acquired when
            # the next view starts (parity: reference controller.go:476).
            return
        self._batch_outstanding = True
        self.batcher.next_batch(self._on_batch)

    def _on_batch(self, batch: list[bytes]) -> None:
        self._batch_outstanding = False
        if self._stopped:
            return
        if not batch:
            if not self.batcher.closed:
                self._acquire_leader_token()  # try again later
            return
        if self.curr_view is None or self.curr_view.stopped:
            return
        metadata = self.curr_view.get_metadata()
        proposal = self._assembler.assemble_proposal(metadata, batch)
        if self._tracer.enabled:
            # Stamped with the slot this proposal will occupy (read before
            # propose() advances it) so the report can join seal -> phases.
            self._tracer.instant(
                "controller",
                "batch.seal",
                seq=self.curr_view.next_propose_seq,
                view=self.curr_view_number,
                count=len(batch),
            )
        self.curr_view.propose(proposal)
        if self.curr_view.effective_depth > 1:
            # The batch now rides an in-flight slot while still pooled
            # (removal only happens at delivery): hide it from the batcher
            # or the NEXT slot would re-propose the same requests.
            self.pool.reserve_raws(batch)
        if self.curr_view.can_propose():
            # Pipelined window still has slot room: immediately pull the
            # next batch instead of waiting for decide() to hand the
            # leader token back (depth 1 never takes this — can_propose
            # is always False there).
            self._acquire_leader_token()

    # ------------------------------------------------------------- deciding

    def decide(
        self,
        proposal: Proposal,
        signatures: Sequence[Signature],
        requests: Sequence[RequestInfo],
    ) -> None:
        """Called synchronously by the View once a quorum committed.

        Parity: reference controller.go:528-558 (decide) + 873-890 (Decide)
        + the MutuallyExclusiveDeliver guard (928-965)."""
        if self._reconfig_pending:
            # A reconfiguration already surfaced at a lower slot: commits
            # queued for slots above it carry the RETIRED membership's
            # certs.  Those slots are abandoned and re-proposed under the
            # new epoch (the rebuild releases their pool reservations).
            return
        reconfig = self._deliver_checked(proposal, signatures)
        self.pool.remove_requests(requests)
        self.curr_decisions_in_view += 1

        if reconfig.in_latest_decision:
            logger.info("%d: decision carried a reconfiguration", self.id)
            self.metrics.consensus.count_consensus_reconfig.add(1)
            if SENTINEL_STALE_MEMBERSHIP:
                # Seeded bug: pretend the decision was ordinary.  The old
                # committee keeps running — and keeps certifying.
                logger.warning(
                    "%d: SENTINEL_STALE_MEMBERSHIP armed; ignoring reconfig",
                    self.id,
                )
            else:
                self._reconfig_pending = True
                if self._on_reconfig is not None:
                    self._on_reconfig(reconfig)
                return

        md = decode_view_metadata(proposal.metadata)
        self.metrics.blacklist.count.set(len(md.black_list))
        self.metrics.blacklist.node_id_in_blacklist.set(
            1 if self.id in md.black_list else 0
        )
        if self._check_if_rotate(md.black_list):
            logger.info("%d: rotating leader after seq %d", self.id, md.latest_sequence)
            self.change_view(
                self.curr_view_number, md.latest_sequence + 1, self.curr_decisions_in_view
            )
            self.pool.restart_timers()
        self.maybe_prune_revoked_requests()
        if self.i_am_the_leader():
            self._acquire_leader_token()

    def _deliver_checked(
        self, proposal: Proposal, signatures: Sequence[Signature]
    ) -> Reconfig:
        """Deliver unless this sequence was already obtained via sync.

        Parity: reference controller.go:928-965."""
        md = decode_view_metadata(proposal.metadata)
        latest = self.latest_seq()
        if latest != 0 and latest >= md.latest_sequence:
            logger.info(
                "%d: seq %d already synced (latest %d); syncing instead of delivering",
                self.id, md.latest_sequence, latest,
            )
            response = self._synchronizer.sync()
            if response.latest is not None:
                self.checkpoint.set(
                    response.latest.proposal, response.latest.signatures
                )
            self._state.prune_decided(latest)
            # Synced-past slots never hit the per-delivery removal path, so
            # their reservations would pin pooled requests forever.
            self.pool.release_reservations()
            self._maybe_release_fence()
            return response.reconfig
        tracing = self._tracer.enabled
        if tracing:
            self._tracer.begin(
                "view", "phase.deliver", seq=md.latest_sequence, view=md.view_id
            )
        begin = self._sched.now()
        reconfig = self._application.deliver(proposal, signatures)
        self.metrics.view.latency_batch_save.observe(self._sched.now() - begin)
        if tracing:
            self._tracer.end(
                "view", "phase.deliver", seq=md.latest_sequence, view=md.view_id
            )
            self._tracer.end(
                "view", "decision", seq=md.latest_sequence, view=md.view_id
            )
        self.checkpoint.set(proposal, signatures)
        # Forget the delivered slot's mem-window/in-flight entries: with a
        # pipelined window the view changer must only ever see the OLDEST
        # undecided slot, and the persist-before-sign coupling check must
        # not match against an already-delivered entry.
        self._state.prune_decided(md.latest_sequence)
        self._maybe_release_fence()
        return reconfig

    def deliver(self, proposal: Proposal, signatures: Sequence[Signature]) -> Reconfig:
        """Checked delivery for the view changer (its ``Application`` is the
        reference's MutuallyExclusiveDeliver wrapper — same guard here)."""
        if self._reconfig_pending:
            return Reconfig()
        return self._deliver_checked(proposal, signatures)

    def _check_if_rotate(self, blacklist: Sequence[int]) -> bool:
        """Parity: reference controller.go:560-574 (called post-increment)."""
        if not self._config.leader_rotation:
            return False
        curr = get_leader_id(
            self.curr_view_number, self.n, self.nodes,
            leader_rotation=True,
            decisions_in_view=self.curr_decisions_in_view - 1,
            decisions_per_leader=self._config.decisions_per_leader,
            blacklist=blacklist,
        )
        nxt = get_leader_id(
            self.curr_view_number, self.n, self.nodes,
            leader_rotation=True,
            decisions_in_view=self.curr_decisions_in_view,
            decisions_per_leader=self._config.decisions_per_leader,
            blacklist=blacklist,
        )
        return curr != nxt

    def maybe_prune_revoked_requests(self) -> None:
        """Parity: reference controller.go:733-746 — on a verification-
        sequence change, re-validate the whole pool (a sig-heavy burst the
        TPU verifier absorbs as batches)."""
        new_vseq = self._verifier.verification_sequence()
        if new_vseq == self._verification_sequence:
            return
        logger.info(
            "%d: verification sequence %d -> %d; pruning pool",
            self.id, self._verification_sequence, new_vseq,
        )
        self._verification_sequence = new_vseq

        def keep_batch(raws: list) -> list:
            try:
                results = self._verifier.verify_requests_batch(raws)
            except Exception:
                # Infrastructure failure (e.g. the verify device dropped
                # out) is not "every request is invalid": keep the pool and
                # let per-proposal verification catch stale requests.
                logger.exception(
                    "%d: batch re-validation failed; deferring prune", self.id
                )
                return [True] * len(raws)
            if len(results) != len(raws):
                logger.error(
                    "%d: verifier returned %d results for %d requests; "
                    "deferring prune", self.id, len(results), len(raws),
                )
                return [True] * len(raws)
            return [r is not None for r in results]

        self.pool.prune_batch(keep_batch)

    # ----------------------------------------------------------------- sync

    def sync(self) -> None:
        """Schedule a synchronization (idempotent while one is running).

        Parity: reference controller.go:449-454 + syncChan."""
        if self._sync_in_progress or self._stopped:
            return
        if self.i_am_the_leader():
            self.batcher.close()
        self._sched.post(lambda: self._do_sync(), name="controller-sync")

    def _do_sync(
        self, on_complete: Optional[Callable[[int, int, int], None]] = None
    ) -> None:
        """Parity: reference controller.go:576-680 (sync)."""
        if self._sync_in_progress:
            return
        self._sync_in_progress = True
        sync_begin = self._sched.now()

        if self._tracer.enabled:
            self._tracer.begin("controller", "sync")
        response = self._synchronizer.sync()
        if self._tracer.enabled:
            self._tracer.end("controller", "sync")
        if response.reconfig.in_latest_decision:
            self._sync_in_progress = False
            self._reconfig_pending = True
            if self._on_reconfig is not None:
                self._on_reconfig(response.reconfig)
            return

        latest = response.latest
        latest_md: Optional[ViewMetadata] = None
        if latest is not None and latest.proposal.metadata:
            latest_md = decode_view_metadata(latest.proposal.metadata)

        controller_seq = self.latest_seq()
        new_view = self.curr_view_number
        new_seq = controller_seq + 1
        new_decisions = 0

        if latest_md is not None and latest_md.latest_sequence > controller_seq:
            logger.info(
                "%d: sync advanced us to seq %d (was %d)",
                self.id, latest_md.latest_sequence, controller_seq,
            )
            self.checkpoint.set(latest.proposal, latest.signatures)
            self._verification_sequence = latest.proposal.verification_sequence
            new_seq = latest_md.latest_sequence + 1
            new_decisions = latest_md.decisions_in_view + 1
        elif (
            latest_md is not None
            and latest_md.latest_sequence == controller_seq
            and latest_md.view_id == self.curr_view_number
        ):
            # We already hold this view's latest decision: carry its
            # decisions-in-view forward.  When our counter is already right,
            # change_view's early-return makes this a no-op; when a
            # late-processed NewView reset it to 0 while the cluster kept
            # deciding, this repairs it — otherwise every future proposal is
            # rejected ("decisions-in-view N != 0") forever.
            new_decisions = latest_md.decisions_in_view + 1
            if new_decisions != self.curr_decisions_in_view:
                logger.info(
                    "%d: repairing decisions-in-view %d -> %d from checkpoint",
                    self.id, self.curr_decisions_in_view, new_decisions,
                )
        if latest_md is not None and latest_md.view_id > self.curr_view_number:
            new_view = latest_md.view_id

        def on_state(result: Optional[tuple[int, int]]) -> None:
            nonlocal new_view, new_decisions
            self._sync_in_progress = False
            self.metrics.consensus.latency_sync.observe(self._sched.now() - sync_begin)
            latest_decision_seq = (
                latest_md.latest_sequence if latest_md is not None else 0
            )
            latest_decision_view = latest_md.view_id if latest_md is not None else 0
            if result is None:
                logger.info("%d: state fetch failed", self.id)
                if latest_md is None or latest_decision_view < self.curr_view_number:
                    self._finish_sync(0, 0, 0, on_complete)
                    return
            else:
                view, seq = result
                if (
                    view <= self.curr_view_number
                    and latest_decision_view < self.curr_view_number
                ):
                    self._finish_sync(0, 0, 0, on_complete)
                    return
                if view > new_view and seq == latest_decision_seq + 1:
                    logger.info(
                        "%d: cluster is at view %d seq %d", self.id, view, seq
                    )
                    self._state.save(
                        SavedNewView(
                            view_metadata=ViewMetadata(
                                view_id=view,
                                latest_sequence=latest_decision_seq,
                                decisions_in_view=0,
                            )
                        )
                    )
                    new_view = view
                    new_decisions = 0
            if latest_md is not None:
                self._maybe_prune_in_flight(latest_md)
            if new_view > self.curr_view_number and self.view_changer is not None:
                self.view_changer.inform_new_view(new_view)
            self._finish_sync(new_view, new_seq, new_decisions, on_complete)

        self.collector.begin(on_state)
        self.broadcast(StateTransferRequest())

    def _finish_sync(
        self,
        view: int,
        seq: int,
        decisions: int,
        on_complete: Optional[Callable[[int, int, int], None]],
    ) -> None:
        self._maybe_release_fence()
        self.maybe_prune_revoked_requests()
        if on_complete is not None:
            # start(sync_on_start=True) path: caller decides what to start.
            on_complete(view, seq, decisions)
            return
        if view > 0 or seq > 0:
            self.change_view(view, seq, decisions)
        else:
            active, vseq = self.view_sequence()
            self.change_view(
                self.curr_view_number,
                vseq if active else self.latest_seq() + 1,
                self.curr_decisions_in_view,
            )

    def _maybe_prune_in_flight(self, synced_md: ViewMetadata) -> None:
        """Parity: reference controller.go:682-705."""
        proposal = self.in_flight.proposal()
        if proposal is None:
            return
        in_flight_md = decode_view_metadata(proposal.metadata)
        if synced_md.latest_sequence < in_flight_md.latest_sequence:
            return
        logger.info(
            "%d: synced past in-flight seq %d; clearing it",
            self.id, in_flight_md.latest_sequence,
        )
        self.in_flight.clear()

    # --------------------------------------------------------------- egress

    def broadcast(self, msg: ConsensusMessage) -> None:
        """Send to all peers (not self); protocol traffic doubles as our
        heartbeat.  Parity: reference controller.go:912-926."""
        for node in self.nodes:
            if node == self.id:
                continue
            self._comm.send_consensus(node, msg)
        if isinstance(msg, (PrePrepare, Prepare, Commit)) and self.i_am_the_leader():
            self.leader_monitor.heartbeat_was_sent()

    # View-facing comm adapter (View broadcasts through the controller so
    # heartbeat suppression and self-exclusion apply uniformly).
    def send(self, target_id: int, msg: ConsensusMessage) -> None:
        self._comm.send_consensus(target_id, msg)

    # ViewChanged hook (called by the ViewChanger).
    def view_changed(self, new_view_number: int, new_proposal_sequence: int) -> None:
        """Parity: reference controller.go:466-473."""
        if self.i_am_the_leader():
            self.batcher.close()
        self.change_view(new_view_number, new_proposal_sequence, 0)

    def abort_view(self, view: int) -> None:
        """Parity: reference controller.go:457-464."""
        self.batcher.close()
        self._abort_view(view)


__all__ = ["Controller", "ViewChangerPort"]
