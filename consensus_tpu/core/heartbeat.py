"""Heartbeat monitor: leader liveness beacons + follower failure detection.

Parity: reference internal/bft/heartbeatmonitor.go:80-415.  Tick-driven role
machine on the injected scheduler (the reference injects a ``<-chan
time.Time``; here a repeating timer with period ``timeout / count``).

Leader: broadcasts ``HeartBeat(view, seq)`` every tick window unless a real
protocol message already went out (``heartbeat_was_sent``).  Collects
HeartBeatResponses — f+1 responses naming a higher view mean the cluster
moved on without us → sync.

Follower: complains when no (real or artificial) heartbeat arrived within the
timeout; detects being exactly one sequence behind the leader for
``num_of_ticks_behind_before_syncing`` consecutive ticks → sync; answers
stale-view heartbeats with a HeartBeatResponse.
"""

from __future__ import annotations

import logging
from enum import Enum
from typing import Callable, Optional, Protocol

from consensus_tpu.runtime.scheduler import Scheduler, TimerHandle
from consensus_tpu.utils.quorum import compute_quorum
from consensus_tpu.wire import ConsensusMessage, HeartBeat, HeartBeatResponse

logger = logging.getLogger("consensus_tpu.heartbeat")


class Role(Enum):
    LEADER = "leader"
    FOLLOWER = "follower"


class HeartbeatEventHandler(Protocol):
    """Parity: reference internal/bft/heartbeatmonitor.go:23-34."""

    def on_heartbeat_timeout(self, view: int, leader_id: int) -> None: ...

    def sync(self) -> None: ...


class HeartbeatComm(Protocol):
    def broadcast(self, msg: ConsensusMessage) -> None: ...

    def send(self, target_id: int, msg: ConsensusMessage) -> None: ...


class HeartbeatMonitor:
    def __init__(
        self,
        scheduler: Scheduler,
        *,
        comm: HeartbeatComm,
        handler: HeartbeatEventHandler,
        n: int,
        heartbeat_timeout: float,
        heartbeat_count: int,
        num_of_ticks_behind_before_syncing: int,
        view_sequence: Callable[[], tuple[bool, int]],
    ) -> None:
        """``view_sequence()`` returns (view_active, current_seq) — the
        reference threads the same through an atomic ViewSequences value."""
        self._sched = scheduler
        self._comm = comm
        self._handler = handler
        self._n = n
        self._timeout = heartbeat_timeout
        self._tick_period = heartbeat_timeout / heartbeat_count
        self._ticks_behind_limit = num_of_ticks_behind_before_syncing
        self._view_sequence = view_sequence

        self._role = Role.FOLLOWER
        self._view = 0
        self._leader_id = 0
        self._suppress_leader_sends = False

        self._last_heartbeat: Optional[float] = None
        self._sent_since_tick = False
        self._timed_out = False
        self._follower_behind = False
        self._behind_seq = -1
        self._behind_counter = 0
        self._responses: dict[int, int] = {}
        self._sync_requested = False

        self._timer: Optional[TimerHandle] = None
        self._running = False

    # --- lifecycle ---------------------------------------------------------

    def change_role(self, role: Role, view: int, leader_id: int) -> None:
        """Parity: reference heartbeatmonitor.go:174-195 + handleCommand."""
        logger.debug("heartbeat role=%s view=%d leader=%d", role.value, view, leader_id)
        self._role = role
        self._view = view
        self._leader_id = leader_id
        self._suppress_leader_sends = False
        self._timed_out = False
        self._last_heartbeat = self._sched.now()
        self._responses = {}
        self._sync_requested = False
        if not self._running:
            self._running = True
            self._schedule_tick()

    def stop_leader_sends(self) -> None:
        """Keep monitoring but stop emitting heartbeats (used while a view
        change is pending against us as leader)."""
        self._suppress_leader_sends = True

    def close(self) -> None:
        self._running = False
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        self._last_heartbeat = None

    def _schedule_tick(self) -> None:
        self._timer = self._sched.call_later(
            self._tick_period, self._tick, name="heartbeat-tick"
        )

    # --- ticking -----------------------------------------------------------

    def _tick(self) -> None:
        if not self._running:
            return
        now = self._sched.now()
        if self._last_heartbeat is None:
            self._last_heartbeat = now
        if self._role == Role.LEADER and not self._suppress_leader_sends:
            self._leader_tick(now)
        else:
            self._follower_tick(now)
        self._schedule_tick()

    def _leader_tick(self, now: float) -> None:
        if (now - self._last_heartbeat) * 1.0 < self._tick_period:
            return
        if self._sent_since_tick:
            # A protocol message doubled as the heartbeat this window.
            self._sent_since_tick = False
            self._last_heartbeat = now
            return
        active, seq = self._view_sequence()
        if not active:
            return
        self._comm.broadcast(HeartBeat(view=self._view, seq=seq))
        self._last_heartbeat = now

    def _follower_tick(self, now: float) -> None:
        if self._timed_out:
            return
        delta = now - self._last_heartbeat
        if delta >= self._timeout:
            logger.warning(
                "heartbeat timeout: leader %d silent for %.3fs", self._leader_id, delta
            )
            self._timed_out = True
            self._handler.on_heartbeat_timeout(self._view, self._leader_id)
            return
        if not self._follower_behind:
            return
        self._behind_counter += 1
        if self._behind_counter >= self._ticks_behind_limit:
            logger.warning(
                "follower stuck one seq behind leader for %d ticks — syncing",
                self._behind_counter,
            )
            self._behind_counter = 0
            self._handler.sync()

    # --- ingress -----------------------------------------------------------

    def process_msg(self, sender: int, msg: ConsensusMessage) -> None:
        if isinstance(msg, HeartBeat):
            self._handle_heartbeat(sender, msg, artificial=False)
        elif isinstance(msg, HeartBeatResponse):
            self._handle_response(sender, msg)

    def inject_artificial_heartbeat(self, sender: int, msg: HeartBeat) -> None:
        """The controller converts the leader's protocol traffic into
        heartbeats so an active leader never looks dead.

        Parity: reference controller.go:330-331,362-373."""
        self._handle_heartbeat(sender, msg, artificial=True)

    def _handle_heartbeat(self, sender: int, hb: HeartBeat, *, artificial: bool) -> None:
        if hb.view < self._view:
            self._comm.send(sender, HeartBeatResponse(view=self._view))
            return
        # Only the current leader's heartbeats reset the follower timeout —
        # even while suppress_leader_sends has this (leader) node monitoring
        # as a follower, or a Byzantine non-leader could keep feeding the
        # timer and mute the complaint path.
        if sender != self._leader_id:
            return
        if hb.view > self._view:
            self._handler.sync()
            return
        active, our_seq = self._view_sequence()
        if active and not artificial:
            if our_seq + 1 < hb.seq:
                self._handler.sync()
                return
            if our_seq + 1 == hb.seq:
                self._follower_behind = True
                if our_seq > self._behind_seq:
                    self._behind_seq = our_seq
                    self._behind_counter = 0
            else:
                self._follower_behind = False
        else:
            self._follower_behind = False
        self._last_heartbeat = self._sched.now()
        self._timed_out = False

    def _handle_response(self, sender: int, hbr: HeartBeatResponse) -> None:
        if self._role != Role.LEADER or self._sync_requested:
            return
        if self._view >= hbr.view:
            return
        self._responses[sender] = hbr.view
        _, f = compute_quorum(self._n)
        if len(self._responses) >= f + 1:
            logger.info(
                "f+1 heartbeat responses claim views above %d — syncing", self._view
            )
            self._sync_requested = True
            self._handler.sync()

    def heartbeat_was_sent(self) -> None:
        """Parity: reference heartbeatmonitor.go:409-415."""
        self._sent_since_tick = True


__all__ = ["HeartbeatMonitor", "HeartbeatEventHandler", "Role"]
