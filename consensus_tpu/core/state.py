"""Persisted protocol state: WAL encode/decode and crash-restore-into-phase.

Parity: reference internal/bft/state.go:31-247 (PersistedState), util.go:191-254
(InFlightData), util.go:257-336 (ProposalMaker).
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Callable, Optional, Sequence

from consensus_tpu.api.deps import WriteAheadLog
from consensus_tpu.core.view import Phase, View
from consensus_tpu.wal.log import WALError
from consensus_tpu.types import Proposal, Signature
from consensus_tpu.wire import (
    Commit,
    Prepare,
    ProposedRecord,
    SavedCommit,
    SavedMessage,
    SavedNewView,
    SavedViewChange,
    ViewChange,
    ViewMetadata,
    decode_saved,
    decode_view_metadata,
    encode_saved,
)

logger = logging.getLogger("consensus_tpu.state")


def restore_requests_best_effort(view: "View", proposal: Proposal) -> None:
    """Populate ``view.in_flight_requests`` from the application's
    ``requests_from_proposal`` during phase re-entry, so a restored replica
    that goes on to commit still removes the batch from its pool and counts
    it in the tx metrics.  Best-effort: a restored view with an empty
    request list commits correctly; only that cleanup/accounting is lost."""
    try:
        view.in_flight_requests = tuple(
            view._verifier.requests_from_proposal(proposal)
        )
    except Exception:
        logger.exception(
            "requests_from_proposal failed during restore; "
            "continuing with an empty request list"
        )


class InFlightData:
    """Holder of the proposals currently moving through the 3-phase
    pipeline, plus whether each reached the PREPARED stage.

    Parity: reference internal/bft/util.go:191-254 (lock dropped — the
    runtime is single-threaded per replica), WINDOWED for decision
    pipelining: one entry per in-flight sequence.  ``proposal()`` /
    ``is_prepared()`` report the OLDEST undecided entry — the only slot a
    view change can ever need to adopt (nothing above the oldest can have
    been commit-signed anywhere; see SAFETY.md §5) — so the view changer's
    single-slot reading stays correct at any depth.  Decided sequences are
    dropped by ``prune_decided`` (the controller calls it on delivery).
    """

    def __init__(self) -> None:
        #: seq (or None when the proposal carries no decodable metadata,
        #: which sorts as the oldest) -> [proposal, prepared].
        self._slots: dict[Optional[int], list] = {}

    @staticmethod
    def _seq_of(proposal: Proposal) -> Optional[int]:
        if not proposal.metadata:
            return None
        try:
            return decode_view_metadata(proposal.metadata).latest_sequence
        except Exception:
            return None

    def _oldest(self) -> Optional[list]:
        if not self._slots:
            return None
        key = min(self._slots, key=lambda k: -1 if k is None else k)
        return self._slots[key]

    def proposal(self) -> Optional[Proposal]:
        slot = self._oldest()
        return slot[0] if slot is not None else None

    def is_prepared(self) -> bool:
        slot = self._oldest()
        return bool(slot[1]) if slot is not None else False

    def store_proposal(self, proposal: Proposal) -> None:
        self._slots[self._seq_of(proposal)] = [proposal, False]

    def store_prepared(self, view: int, seq: int) -> bool:
        """Mark the entry whose metadata stamps match ``(view, seq)`` as
        prepared; returns whether one matched."""
        for slot in self._slots.values():
            prop = slot[0]
            md = (
                decode_view_metadata(prop.metadata)
                if prop.metadata
                else ViewMetadata()
            )
            if md.view_id == view and md.latest_sequence == seq:
                slot[1] = True
                return True
        return False

    def prune_decided(self, seq: int) -> None:
        """Drop every entry at or below a delivered sequence."""
        for key in [k for k in self._slots if k is not None and k <= seq]:
            del self._slots[key]

    def drop_above_oldest(self) -> None:
        """Abandon pipelined entries above the oldest undecided one (view
        aborts: higher slots are re-proposed in the next view, and our
        attestation must only ever cover the contested oldest slot)."""
        if len(self._slots) <= 1:
            return
        keep = min(self._slots, key=lambda k: -1 if k is None else k)
        self._slots = {keep: self._slots[keep]}

    def clear(self) -> None:
        self._slots.clear()


class PersistedState:
    """Bridges protocol records to the WAL and restores a View mid-protocol.

    Parity: reference internal/bft/state.go:31-247.
    """

    def __init__(
        self,
        wal: WriteAheadLog,
        in_flight: InFlightData,
        entries: Sequence[bytes] = (),
    ) -> None:
        self._wal = wal
        self._in_flight = in_flight
        #: Raw WAL entries read at boot (the restore source).
        self.entries = list(entries)
        #: In-memory WAL tail for MID-RUN view restarts (see
        #: reseed_if_inflight_matches), WINDOWED for decision pipelining:
        #: seq -> [ProposedRecord, Optional[SavedCommit]] for every sequence
        #: in the trailing run of protocol records.  At pipeline depth 1
        #: this holds at most the single legacy mem-tail pair.
        self._mem_window: dict[int, list] = {}
        #: The record object most recently appended this run — the guard for
        #: the verified-upgrade append (it must only ever replace the tail).
        self._last_written: Optional[SavedMessage] = None
        #: Proposals restore() abandoned above the oldest in-flight slot —
        #: the consensus layer re-admits their requests to the pool.
        self.abandoned: list[Proposal] = []
        try:
            for rec in self._trailing_protocol_records():
                if isinstance(rec, ProposedRecord):
                    # A later record at the same seq is the verified-upgrade
                    # twin; forward replay makes it win, like the legacy tail.
                    self._mem_window[rec.pre_prepare.seq] = [rec, None]
                else:  # SavedCommit
                    slot = self._mem_window.get(rec.commit.seq)
                    if (
                        slot is not None
                        and slot[0].pre_prepare.view == rec.commit.view
                    ):
                        slot[1] = rec
            last = self._last_record()
            if isinstance(last, ProposedRecord):
                slot = self._mem_window.get(last.pre_prepare.seq)
                if slot is not None:
                    # The restored tail counts as "last written" so a
                    # restore-time re-verification success upgrades the
                    # on-disk record too — without this, only the FIRST
                    # crash is protected and a second crash re-runs the
                    # spurious re-verify.  Keep object identity between the
                    # window slot and the tail guard.
                    self._last_written = slot[0]
        except Exception:
            # A torn/corrupt tail must not fail boot here: restore() has
            # its own tolerant handling ("starting clean"), and with no
            # mem-tail the reseed guard simply never fires.
            logger.exception("WAL mem-tail seeding failed; reseed disabled")

    def _trailing_protocol_records(self) -> list:
        """The contiguous run of ProposedRecord/SavedCommit entries at the
        WAL tail, in log order.  A run never spans views: any view install
        appends a SavedNewView (and the endorsement tail sits above its
        SavedViewChange), both of which stop the backward scan."""
        tail: list = []
        idx = len(self.entries) - 1
        while idx >= 0:
            rec = decode_saved(self.entries[idx])
            if not isinstance(rec, (ProposedRecord, SavedCommit)):
                break
            tail.append(rec)
            idx -= 1
        tail.reverse()
        return tail

    # --- saving ------------------------------------------------------------

    @staticmethod
    def _fault_point_of(record: SavedMessage) -> str:
        if isinstance(record, ProposedRecord):
            return "state.save.proposed"
        if isinstance(record, SavedCommit):
            return "state.save.commit"
        if isinstance(record, SavedViewChange):
            return "state.save.viewchange"
        return "state.save.newview"

    def save(self, record: SavedMessage, on_durable=None,
             truncate: Optional[bool] = None, fault_point: Optional[str] = None
             ) -> None:
        """Persist one protocol step; ``on_durable`` fires once the record
        is on stable storage (immediately for per-append fsync, deferred
        under group commit — the protocol defers its sends behind it).

        A new ProposedRecord doubles as a truncation point: the previous
        proposal is then stably decided (reference state.go:38-59).
        ``truncate`` overrides that default — the view changer's embedded
        in-flight endorsement appends a ProposedRecord that implies NO new
        decision (the sequence is the contested one), and truncating there
        would erase the pending-view-change vote the crash-restore rejoin
        depends on.

        ``fault_point`` relabels this save's crash points (the endorsement
        appends register under their own names); the seams fire only when
        the test harness armed a FaultPlan on the WAL — one ``is None``
        check otherwise."""
        plan = getattr(self._wal, "fault_plan", None)
        if plan is not None:
            point = fault_point or self._fault_point_of(record)
            # ".pre": the process dies before ANY effect of this step — the
            # in-memory mutations below never survive a real crash either.
            plan.crash(point + ".pre")
        if isinstance(record, ProposedRecord):
            self._in_flight.store_proposal(record.pre_prepare.proposal)
            self._mem_window[record.pre_prepare.seq] = [record, None]
        elif isinstance(record, SavedCommit):
            matched = self._in_flight.store_prepared(
                record.commit.view, record.commit.seq
            )
            if not matched:
                # Coupling invariant: a commit record is only ever persisted
                # for a proposal currently in flight (the commit signature
                # was minted against it).  If the (view, seq) stamps do not
                # line up, the check_in_flight "unprepared attestations are
                # no-argument" relaxation would be silently decoupled from
                # its persist-before-sign precondition — fail loudly instead.
                raise RuntimeError(
                    "persist-before-sign coupling violated: commit record at "
                    f"(view={record.commit.view}, seq={record.commit.seq}) "
                    "does not match an in-flight proposal"
                )
            slot = self._mem_window.get(record.commit.seq)
            if (
                slot is not None
                and slot[0].pre_prepare.view == record.commit.view
            ):
                slot[1] = record
        self._last_written = record
        try:
            self._wal.append(
                encode_saved(record),
                truncate_to=(
                    isinstance(record, ProposedRecord) if truncate is None else truncate
                ),
                on_durable=on_durable,
            )
        except WALError as err:
            if getattr(self._wal, "degraded", False):
                # The append was refused by a degraded WAL (ENOSPC, fsync
                # retry cap).  Swallow the failure WITHOUT firing
                # ``on_durable``: the dependent send never happens
                # (persist-before-send holds vacuously), and the degrade
                # hook already suspended this replica's proposing/voting
                # (core/controller.py::set_wal_degraded).
                logger.warning("WAL append refused while degraded: %s", err)
                return
            raise
        if plan is not None:
            plan.crash(point + ".post")

    # --- boot-time peeking (pkg/consensus setViewAndSeq equivalents) -------

    def _last_record(self) -> Optional[SavedMessage]:
        if not self.entries:
            return None
        return decode_saved(self.entries[-1])

    def load_new_view_if_applicable(self) -> Optional[tuple[int, int]]:
        """(view, seq) if the log ends with a finalized new-view record.

        Parity: reference state.go:80-95."""
        last = self._last_record()
        if isinstance(last, SavedNewView):
            md = last.view_metadata
            return md.view_id, md.latest_sequence
        return None

    def load_in_flight_view_if_applicable(self) -> Optional[tuple[int, int]]:
        """(view, decisions_in_view) of the WAL-tail in-flight pre-prepare,
        if the log ends in one (directly, or behind our commit).

        A proposal record at view v proves v was INSTALLED here before the
        crash (followers accept and leaders create proposals only inside an
        active view) — but the SavedNewView record that said so may be gone:
        the proposal append itself truncates the log.  Booting from the
        checkpoint's (older) view in that state strands the replica in a
        view the cluster left long ago, with its view changer blind to the
        regression (seed-3428 chaos wedge: two restored replicas idling at
        view 1 while holding (view 8) proposal records).

        Reads the mem-window ``__init__`` already seeded (same tail cases,
        and behind its torn-tail exception guard — a corrupt tail must not
        fail boot).  The NEWEST (max-seq) entry is the legacy "last
        ProposedRecord" — and since a trailing run never spans views, every
        window entry proves the same installed view anyway."""
        if not self._mem_window:
            return None
        rec = self._mem_window[max(self._mem_window)][0]
        pp = rec.pre_prepare
        dec = 0
        if pp.proposal.metadata:
            dec = decode_view_metadata(pp.proposal.metadata).decisions_in_view
        return pp.view, dec

    def load_view_change_if_applicable(self) -> Optional[ViewChange]:
        """The pending view-change vote if the log ends with one — directly,
        or buried under the view changer's in-flight endorsement tail.

        Parity: reference state.go:97-113, EXTENDED: after
        ``_commit_in_flight`` persists its endorsement the log reads
        ``[..., SavedViewChange, ProposedRecord, SavedCommit]`` (both
        endorsement appends use truncate=False precisely so the vote
        survives).  A replica that crashes there is still mid-view-change:
        only the vote's durability let it sign the ViewData attestation it
        broadcast, so on restart it MUST rejoin the pending change — booting
        from the bare in-flight tail would strand it in the contested view
        with its vote forgotten.  The bounded backward scan is safe because
        a *normal* ProposedRecord append truncates the log (clearing any
        older vote): a ProposedRecord sitting ABOVE a live SavedViewChange
        can only be a truncate=False append, i.e. the endorsement (or its
        verified-upgrade twin), and a crash between the two endorsement
        appends leaves the ``[..., SavedViewChange, ProposedRecord]``
        prefix this scan also handles."""
        idx = len(self.entries) - 1
        if idx < 0:
            return None
        rec = decode_saved(self.entries[idx])
        # Walk back over the SavedCommit run: under cert_mode="half-agg" the
        # endorsement commit may be followed by its cert-bearing twin at the
        # same (view, seq) — both truncate-free appends, both ours.
        while isinstance(rec, SavedCommit) and idx >= 1:
            idx -= 1
            rec = decode_saved(self.entries[idx])
        if isinstance(rec, ProposedRecord) and idx >= 1:
            idx -= 1
            rec = decode_saved(self.entries[idx])
        if isinstance(rec, SavedViewChange):
            return rec.view_change
        return None

    # --- restore-into-phase (state.go:115-247) -----------------------------

    def restore(self, view: View) -> None:
        """Re-enter the phase the replica crashed in: PROPOSED if the last
        record is a proposal, PREPARED if it is our commit (with our own
        signature resurrected).

        With decision pipelining the WAL tail can hold records from SEVERAL
        sequences.  Only the oldest undecided slot (``view.proposal_sequence``,
        anchored by the application's delivered height) is re-entered; every
        proposal above it is ABANDONED into :attr:`abandoned` for pool
        re-admission — by the in-order commit rule nothing above the oldest
        can have been commit-signed anywhere (SAFETY.md §5), so dropping
        those slots cannot contradict any commit quorum.  A single-sequence
        tail takes the exact legacy path."""
        view.phase = Phase.COMMITTED
        last = self._last_record()
        if last is None:
            logger.info("nothing to restore")
            return
        tail = self._trailing_protocol_records()
        seqs = {
            r.pre_prepare.seq if isinstance(r, ProposedRecord) else r.commit.seq
            for r in tail
        }
        if len(seqs) > 1:
            self._restore_windowed(view)
            return
        if isinstance(last, ProposedRecord):
            self._recover_proposed(last, view)
        elif isinstance(last, SavedCommit):
            self._recover_prepared(last, view)
        # SavedNewView / SavedViewChange need no phase recovery.

    def _restore_windowed(self, view: View) -> None:
        """Multi-sequence (pipelined) tail restore.  ``_mem_window`` was
        seeded from the same trailing run; the target slot is the oldest
        undecided sequence the caller booted the view at."""
        target = view.proposal_sequence
        for seq, slot in self._mem_window.items():
            if slot[1] is not None and seq > target:
                # A commit of ours above the delivered height would mean
                # the in-order gate was breached (or the app state is
                # behind a WAL from someone else's future) — refuse to
                # guess, like the legacy "WAL seq ahead" path.
                raise ValueError(
                    f"WAL commit at seq {seq} is ahead of our last "
                    f"committed {target}"
                )
        slot = self._mem_window.get(target)
        if slot is not None:
            rec, commit = slot
            pp = rec.pre_prepare
            view.number = pp.view
            if commit is not None:
                self._enter_prepared(rec, commit.commit, view)
                logger.info(
                    "restored into PREPARED at seq %d (pipelined tail)", pp.seq
                )
            else:
                self._enter_proposed(rec, view)
                logger.info(
                    "restored into PROPOSED at seq %d (pipelined tail)", pp.seq
                )
        dropped = sorted(s for s in self._mem_window if s > target)
        for seq in dropped:
            self.abandoned.append(self._mem_window[seq][0].pre_prepare.proposal)
        if dropped:
            logger.info(
                "abandoned %d pipelined slot(s) above seq %d: %s",
                len(dropped), target, dropped,
            )

    def take_abandoned(self) -> list[Proposal]:
        """Drain the proposals restore() abandoned above the oldest slot."""
        out, self.abandoned = self.abandoned, []
        return out

    def prune_decided(self, seq: int) -> None:
        """Forget mem-window and in-flight entries at or below a delivered
        sequence (the controller calls this on every delivery)."""
        for key in [k for k in self._mem_window if k <= seq]:
            del self._mem_window[key]
        self._in_flight.prune_decided(seq)

    def _recover_proposed(self, record: ProposedRecord, view: View) -> None:
        pp = record.pre_prepare
        self._in_flight.store_proposal(pp.proposal)
        view.number = pp.view
        view.proposal_sequence = pp.seq
        self._enter_proposed(record, view)
        logger.info("restored into PROPOSED at seq %d", pp.seq)

    def mark_proposed_verified(self, view_number: int, seq: int) -> None:
        """Flip the in-memory ProposedRecord to verified once the (leader's)
        deferred verification succeeds, so a mid-run view restart
        (reseed_if_inflight_matches) does not re-verify a proposal this
        process already verified.

        If the unverified record is still the WAL tail, an upgraded copy is
        appended so a CRASH-restore skips the re-verify too: re-running
        verification after a crash is conservative, but it spuriously fails
        when verifier state (e.g. verification_sequence) legitimately
        advanced between the write and the restore — deposing a leader that
        had already verified the proposal pre-crash (ADVICE r3).  The
        append is best-effort and tail-guarded: if ANY record followed (a
        commit, a view-change), the upgrade is skipped — a commit makes it
        moot (PREPARED restore doesn't re-verify) and anything else must
        stay the tail the restore logic sees.

        The append deliberately does NOT truncate: restore only decodes the
        last record(s), so the older verified=False copy on disk is
        harmless, and truncate_to=True would force an eager fsync outside
        any group-commit window — a second synchronous fsync on the
        leader's critical path per decision (ADVICE r4).  Losing an
        unflushed upgrade in a crash just re-verifies: the documented
        best-effort behavior."""
        slot = self._mem_window.get(seq)
        rec = slot[0] if slot is not None else None
        if (
            rec is not None
            and not rec.verified
            and rec.pre_prepare.view == view_number
            and rec.pre_prepare.seq == seq
        ):
            upgraded = dataclasses.replace(rec, verified=True)
            slot[0] = upgraded
            if self._last_written is rec:
                try:
                    self._wal.append(encode_saved(upgraded), truncate_to=False)
                    self._last_written = upgraded
                except Exception:
                    logger.exception(
                        "verified-upgrade append failed; a crash-restore "
                        "will re-verify (liveness-only cost)"
                    )

    def _enter_proposed(self, record: ProposedRecord, view: View) -> None:
        """Shared phase-reentry: seed ``view`` into PROPOSED from a
        persisted pre-prepare (used by boot restore AND the mid-run
        reseed guard — one body for the safety-critical invariant)."""
        pp = record.pre_prepare
        self._in_flight.store_proposal(pp.proposal)
        view.in_flight_proposal = pp.proposal
        md = decode_view_metadata(pp.proposal.metadata)
        view.decisions_in_view = md.decisions_in_view
        view.phase = Phase.PROPOSED
        if not record.verified:
            # The record was persisted BEFORE its verification completed —
            # only the leader's own reveal-before-verify path writes such
            # records (view.py::_try_process_proposal).  Durability does not
            # imply verification here, so re-run it before re-arming the
            # prepare: the prepare is an endorsement and must never outlive
            # a failed verification via restore.  On failure we stay pinned
            # to the proposal (no equivocation) but never endorse it — the
            # prepare stays un-armed AND the PREPARED transition (commit
            # signing) is blocked; the complaint cascade deposes us.
            try:
                requests = view._verify_proposal(
                    pp.proposal, pp.prev_commit_signatures
                )
            except Exception as err:
                logger.warning(
                    "restored own proposal at (%d, %d) fails verification "
                    "(%s); staying pinned without endorsing it",
                    pp.view, pp.seq, err,
                )
                view.endorsement_blocked = True
                return
            view.in_flight_requests = tuple(requests)
            # Re-verification succeeded: flip the in-memory copy so later
            # mid-run reseeds at this (view, seq) don't verify a third time.
            self.mark_proposed_verified(pp.view, pp.seq)
        else:
            restore_requests_best_effort(view, pp.proposal)
        p = record.prepare
        view._curr_prepare_sent = Prepare(
            view=p.view, seq=p.seq, digest=p.digest, assist=True
        )

    def _enter_prepared(self, record: ProposedRecord, commit, view: View) -> None:
        """Shared phase-reentry: seed ``view`` into PREPARED from a
        persisted pre-prepare + our commit."""
        pp = record.pre_prepare
        self._in_flight.store_proposal(pp.proposal)
        self._in_flight.store_prepared(commit.view, commit.seq)
        view.in_flight_proposal = pp.proposal
        restore_requests_best_effort(view, pp.proposal)
        md = decode_view_metadata(pp.proposal.metadata)
        view.decisions_in_view = md.decisions_in_view
        view.my_commit_signature = commit.signature
        view.phase = Phase.PREPARED
        view._curr_commit_sent = Commit(
            view=commit.view,
            seq=commit.seq,
            digest=commit.digest,
            signature=commit.signature,
            assist=True,
        )

    def _recover_prepared(self, record: SavedCommit, view: View) -> None:
        commit = record.commit
        if len(self.entries) < 2:
            raise ValueError("commit record without a preceding pre-prepare")
        # Under cert_mode="half-agg" the decide path appends a cert-bearing
        # SavedCommit twin after the endorsement commit — walk back over any
        # same-(view, seq) SavedCommit run to the anchoring ProposedRecord.
        idx = len(self.entries) - 2
        prev = decode_saved(self.entries[idx])
        while (
            isinstance(prev, SavedCommit)
            and prev.commit.view == commit.view
            and prev.commit.seq == commit.seq
            and idx >= 1
        ):
            idx -= 1
            prev = decode_saved(self.entries[idx])
        if not isinstance(prev, ProposedRecord):
            raise ValueError(
                f"expected ProposedRecord before commit, got {type(prev).__name__}"
            )
        pp = prev.pre_prepare
        if view.proposal_sequence < pp.seq:
            raise ValueError(
                f"WAL seq {pp.seq} is ahead of our last committed {view.proposal_sequence}"
            )
        if view.proposal_sequence > pp.seq:
            logger.info("seq %d already safely committed", view.proposal_sequence)
            return
        view.number = pp.view
        view.proposal_sequence = pp.seq
        self._enter_prepared(prev, commit, view)
        logger.info("restored into PREPARED at seq %d", pp.seq)


    def reseed_if_inflight_matches(self, view: "View") -> None:
        """Equivocation guard for MID-RUN view restarts (the boot restore
        runs once; this runs on every later view start): if the view being
        started sits at EXACTLY the (view, seq) we persisted a pre-prepare
        (and possibly our commit) for, the fresh View object must resume
        from that state.  Starting clean would let this replica prepare a
        DIFFERENT proposal at the same (view, seq) — and a sync-with-
        nothing-new restarting the current view does exactly that on every
        stalled replica at once, which is a quorum of equivocators and a
        forked ledger (found by the targeted-chaos soak, seed 114: two
        proposals both "committed" at the same view/seq with overlapping
        signers).  Restarts at a different view or sequence are untouched:
        cross-view safety belongs to the view-change protocol
        (check_in_flight + the embedded re-commit view)."""
        slot = self._mem_window.get(view.proposal_sequence)
        if slot is None:
            return
        rec, commit = slot
        pp = rec.pre_prepare
        if pp.view != view.number or pp.seq != view.proposal_sequence:
            return
        if commit is not None and (
            commit.commit.view != pp.view or commit.commit.seq != pp.seq
        ):
            commit = None
        if commit is None:
            self._enter_proposed(rec, view)
            logger.info(
                "reseeded restarted view into PROPOSED at (%d, %d)", pp.view, pp.seq
            )
        else:
            self._enter_prepared(rec, commit.commit, view)
            logger.info(
                "reseeded restarted view into PREPARED at (%d, %d)", pp.view, pp.seq
            )


class ProposalMaker:
    """Builds each View, restoring protocol state from the WAL exactly once
    (the first view created after boot).

    Parity: reference internal/bft/util.go:257-336 (ProposalMaker).
    """

    def __init__(
        self,
        *,
        state: PersistedState,
        view_factory: Callable[..., View],
    ) -> None:
        self._state = state
        self._factory = view_factory
        self._restored_once = False

    def new_proposer(
        self,
        leader_id: int,
        proposal_sequence: int,
        view_number: int,
        decisions_in_view: int,
    ) -> tuple[View, Phase]:
        view = self._factory(
            leader_id=leader_id,
            proposal_sequence=proposal_sequence,
            number=view_number,
            decisions_in_view=decisions_in_view,
        )
        if self._restored_once:
            self._state.reseed_if_inflight_matches(view)
        else:
            self._restored_once = True
            try:
                self._state.restore(view)
            except Exception:
                logger.exception("WAL restore failed; starting clean")
        return view, view.phase


__all__ = ["InFlightData", "PersistedState", "ProposalMaker"]
