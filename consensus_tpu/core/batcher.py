"""Batch builder: turns the request pool into leader proposals.

Parity: reference internal/bft/batcher.go:40-92.  The reference's
``NextBatch`` blocks its goroutine until the pool can fill a batch or the
batch timeout elapses; here the leader *asks* for a batch and gets a callback
— either immediately (pool already full enough), early (a submission tops the
pool up), or when ``request_batch_max_interval`` expires with whatever is
there.  This is the scheduler-driven design the reference left as a TODO
(reference internal/bft/batcher.go:46).
"""

from __future__ import annotations

import logging
from typing import Callable, Optional

from consensus_tpu.core.pool import RequestPool
from consensus_tpu.runtime.scheduler import Scheduler, TimerHandle
from consensus_tpu.trace.tracer import NOOP_TRACER

logger = logging.getLogger("consensus_tpu.batcher")


class Batcher:
    """Single-consumer batch source for the leader."""

    def __init__(
        self,
        scheduler: Scheduler,
        pool: RequestPool,
        *,
        batch_max_count: int,
        batch_max_bytes: int,
        batch_max_interval: float,
        tracer=None,
    ) -> None:
        self._sched = scheduler
        self._pool = pool
        self._max_count = batch_max_count
        self._max_bytes = batch_max_bytes
        self._max_interval = batch_max_interval
        self._pending_cb: Optional[Callable[[list[bytes]], None]] = None
        self._timer: Optional[TimerHandle] = None
        self._closed = False
        self._tracer = tracer if tracer is not None else NOOP_TRACER

    def next_batch(self, on_batch: Callable[[list[bytes]], None]) -> None:
        """Request the next batch; at most one outstanding request.

        ``on_batch`` fires with a possibly-empty list (empty only after
        ``close``).  Parity: reference batcher.go:40-63.
        """
        if self._pending_cb is not None:
            raise RuntimeError("a batch request is already outstanding")
        if self._closed:
            on_batch([])
            return
        if self._pool.available_count >= self._max_count:
            on_batch(self._take())
            return
        self._pending_cb = on_batch
        self._timer = self._sched.call_later(
            self._max_interval, self._interval_expired, name="batch-interval"
        )

    def pool_changed(self) -> None:
        """Pool notification hook: complete an outstanding request early once
        a full batch is available."""
        if self._pending_cb is None or self._closed:
            return
        if self._pool.available_count >= self._max_count:
            self._complete()

    def _interval_expired(self) -> None:
        if self._pending_cb is None or self._closed:
            return
        self._complete()

    def _complete(self) -> None:
        cb = self._pending_cb
        self._pending_cb = None
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        cb(self._take())

    def _take(self) -> list[bytes]:
        batch = self._pool.next_requests(self._max_count, self._max_bytes)
        if batch and self._tracer.enabled:
            self._tracer.instant("batcher", "batch.take", count=len(batch))
        return batch

    def cancel(self) -> None:
        """Abandon any outstanding request without calling back."""
        self._pending_cb = None
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None

    def close(self) -> None:
        """Shut down: an outstanding request completes with an empty batch.

        Parity: reference batcher.go:66-78 (Close unblocks NextBatch).
        """
        self._closed = True
        cb = self._pending_cb
        self._pending_cb = None
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        if cb is not None:
            cb([])

    def reset(self) -> None:
        """Reopen after a view change.  Parity: reference batcher.go:81-92."""
        self._closed = False

    @property
    def closed(self) -> bool:
        """Parity: reference batcher.go Closed()."""
        return self._closed


__all__ = ["Batcher"]
