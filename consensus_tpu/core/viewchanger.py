"""ViewChanger: the BFT view-change protocol.

Parity: reference internal/bft/viewchanger.go (1364 LoC).  Flow:

1. A complaint broadcasts ``ViewChange{next_view}``; replicas join at f+1
   (with ``speed_up_view_change``) or quorum−1 votes, persist a ViewChange
   record, abort the current view, and send ``SignedViewData`` — their last
   decision + its signature quorum + any prepared in-flight proposal — to
   the next leader (viewchanger.go:364-431).
2. The new leader validates each ViewData (``checkLastDecision``: the sender
   may be one decision ahead, in which case the leader *delivers* that
   decision itself), collects a quorum, runs ``check_in_flight`` (condition
   A: an in-flight proposal f+1 saw prepared and a quorum doesn't contradict
   → must re-commit it; condition B: a quorum says no in-flight → safe to
   skip), then broadcasts ``NewView`` (viewchanger.go:501-785).
3. Followers re-validate everything the leader claimed, possibly delivering
   one decision or syncing, persist a NewView record, and install the view
   via ``controller.view_changed`` (viewchanger.go:932-1168).
4. If an in-flight proposal must be re-committed, an **embedded View** is
   started directly in PREPARED phase with our own commit signature, with
   ourselves as leader, so the cluster re-runs the commit round for it
   (viewchanger.go:1187-1307).  The reference blocks its goroutine waiting
   for that view; here the pending transition is stashed and completed from
   the embedded view's ``decide`` callback.

Liveness: a resend timer re-broadcasts our ViewChange, and a view-change
timeout with exponential backoff syncs + escalates to the next view.

Signature-heavy spots (``validate_last_decision`` is quorum × consenter-sig,
per ViewData, per NewView) run through ``verify_consenter_sigs_batch`` — on
the TPU verifier an entire NewView validates in a few kernel launches.
"""

from __future__ import annotations

import logging
from typing import Callable, Optional, Protocol, Sequence

from consensus_tpu.api.deps import Signer, Verifier
from consensus_tpu.core.state import (
    InFlightData,
    PersistedState,
    restore_requests_best_effort,
)
from consensus_tpu.core.view import Phase, View
from consensus_tpu.metrics import MetricsViewChange, NoopProvider
from consensus_tpu.runtime.scheduler import Scheduler, TimerHandle
from consensus_tpu.types import (
    Checkpoint,
    Proposal,
    QuorumCert,
    RequestInfo,
    Signature,
    as_cert,
)
from consensus_tpu.utils.leader import get_leader_id
from consensus_tpu.utils.quorum import compute_quorum
from consensus_tpu.wire import (
    Commit,
    ConsensusMessage,
    NewView,
    PrePrepare,
    Prepare,
    ProposedRecord,
    SavedCommit,
    SavedNewView,
    SavedViewChange,
    SignedViewData,
    ViewChange,
    ViewData,
    ViewMetadata,
    decode_view_data,
    decode_view_metadata,
    encode_view_data,
)

logger = logging.getLogger("consensus_tpu.viewchanger")

#: Ceiling for the view-change timeout backoff factor: rounds lengthen
#: T, 2T, ... up to this multiple and stay there.
_BACKOFF_CAP = 8


class ControllerPort(Protocol):
    """What the view changer needs from the controller."""

    def abort_view(self, view: int) -> None: ...

    def view_changed(self, new_view_number: int, new_proposal_sequence: int) -> None: ...

    def sync(self) -> None: ...

    def deliver(self, proposal: Proposal, signatures: Sequence[Signature]): ...

    def maybe_prune_revoked_requests(self) -> None: ...

    def broadcast(self, msg: ConsensusMessage) -> None: ...

    def send(self, target_id: int, msg: ConsensusMessage) -> None: ...


class RequestsTimer(Protocol):
    def stop_timers(self) -> None: ...

    def restart_timers(self) -> None: ...

    def remove_request(self, info: RequestInfo) -> bool: ...

    def remove_requests(self, infos) -> int: ...


def validate_last_decision(
    vd: ViewData, quorum: int, verifier: Verifier
) -> Optional[int]:
    """Validate a ViewData's last-decision proof; returns its sequence.

    Raises on failure.  Parity: reference viewchanger.go:681-727
    (ValidateLastDecision) — the quorum of consenter signatures is verified
    as one batch instead of a loop."""
    if vd.last_decision is None:
        raise ValueError("last decision is not set")
    if not vd.last_decision.metadata:
        return 0  # genesis: nothing to validate
    md = decode_view_metadata(vd.last_decision.metadata)
    if md.view_id >= vd.next_view:
        raise ValueError(
            f"last decision view {md.view_id} >= requested next view {vd.next_view}"
        )
    if isinstance(vd.last_decision_signatures, QuorumCert):
        # Half-aggregated proof: signer uniqueness is structural (one R per
        # signer id slot) — check count, then verify the whole cert in one
        # aggregate launch.  A verifier without aggregation support returns
        # None and the ViewData is rejected, same as an invalid signature.
        cert = vd.last_decision_signatures
        if len(set(cert.signer_ids)) < quorum:
            raise ValueError(
                f"only {len(set(cert.signer_ids))} last-decision cert signers"
            )
        vac = getattr(verifier, "verify_aggregate_cert", None)
        aux = vac(cert, vd.last_decision) if vac is not None else None
        if aux is None:
            raise ValueError("invalid last-decision quorum cert")
        return md.latest_sequence
    # Dedup by signer, then batch-verify.
    seen: set[int] = set()
    unique: list[Signature] = []
    for sig in vd.last_decision_signatures:
        if sig.id in seen:
            continue
        seen.add(sig.id)
        unique.append(sig)
    if len(unique) < quorum:
        raise ValueError(f"only {len(unique)} last-decision signatures")
    results = verifier.verify_consenter_sigs_batch(unique, vd.last_decision)
    valid = sum(1 for r in results if r is not None)
    if valid < len(unique):
        raise ValueError("invalid last-decision signature")
    return md.latest_sequence


def validate_in_flight(in_flight: Optional[Proposal], last_sequence: int) -> None:
    """Parity: reference viewchanger.go:760-777 (ValidateInFlight)."""
    if in_flight is None:
        return
    if not in_flight.metadata:
        raise ValueError("in-flight proposal metadata is empty")
    md = decode_view_metadata(in_flight.metadata)
    if md.latest_sequence != last_sequence + 1:
        raise ValueError(
            f"in-flight seq {md.latest_sequence} != last decision {last_sequence} + 1"
        )


def check_in_flight(
    messages: Sequence[ViewData], f: int, quorum: int
) -> tuple[bool, bool, Optional[Proposal]]:
    """The agreement rule for a possibly-committed in-flight proposal.

    Returns (ok, no_in_flight, proposal).  Parity: reference
    viewchanger.go:815-909 (CheckInFlight), conditions:
    A2 — some proposal at the expected sequence was seen prepared by ≥ f+1;
    A1 — ≥ quorum don't contradict it (no *different* prepared proposal);
    B  — ≥ quorum report no prepared in-flight at the expected sequence.

    One deliberate difference from the reference's A1: an UNPREPARED
    attestation of a *different* proposal counts as no-argument.  The
    reference counts it as a contradiction for A while counting the very
    same entry toward "no prepared in-flight" for B — incoherent, and the
    A-side reading wedges the cluster forever when mixed-view crash
    restores leave attestations split (seed-1268 chaos hunt: P@v10
    prepared on two replicas, later views' unprepared proposals on the
    other two — every change unsatisfiable).  An unprepared attestation
    means that replica never commit-signed anything at the sequence, so
    no decision it participated in is endangered by adopting the prepared
    candidate; only a prepared certificate can argue (classic PBFT's
    max-view-prepared rule has the same character).

    The consolidated quorum-intersection argument for this deviation —
    why the relaxation is safe with f byzantine replicas, and why the
    residual sub-f+1 split below stays unresolvable — lives in
    docs/inflight-safety.md (the standalone writeup, with the seed-1268
    wedge walked number by number) and in SAFETY.md at the repository
    root."""
    expected_seq = (
        max(
            (
                decode_view_metadata(vd.last_decision.metadata).latest_sequence
                for vd in messages
                if vd.last_decision is not None and vd.last_decision.metadata
            ),
            default=0,
        )
        + 1
    )
    no_in_flight_count = 0
    entries: list[tuple[Optional[Proposal], Optional[ViewMetadata], bool]] = []
    possible: list[Proposal] = []
    for vd in messages:
        p = vd.in_flight_proposal
        if p is None:
            no_in_flight_count += 1
            entries.append((None, None, False))
            continue
        if not p.metadata:
            raise ValueError("in-flight proposal without metadata")
        md = decode_view_metadata(p.metadata)
        entries.append((p, md, vd.in_flight_prepared))
        if md.latest_sequence != expected_seq or not vd.in_flight_prepared:
            no_in_flight_count += 1
            continue
        if p not in possible:
            possible.append(p)

    for candidate in possible:
        preprepared = 0
        no_argument = 0
        for p, md, prepared in entries:
            if p is None or md is None or md.latest_sequence != expected_seq:
                no_argument += 1
                continue
            if p == candidate:
                no_argument += 1
                preprepared += 1
            elif not prepared:
                # A different-but-UNPREPARED attestation asserts "nothing
                # prepared here" (condition B already counts it that way);
                # it carries no commit signature and so cannot argue.  This
                # relaxation only ever helps ADOPT an f+1-corroborated
                # candidate — the safe direction — and can never flip
                # condition B, so a lone byzantine claim gains nothing.
                no_argument += 1
        if preprepared >= f + 1 and no_argument >= quorum:
            return True, False, candidate  # condition A

    if no_in_flight_count >= quorum:
        return True, True, None  # condition B

    # KNOWN UNRESOLVABLE SPLIT (kept deliberately, matching the reference):
    # sub-f+1 prepared attestations of different proposals (e.g. P@v10 on
    # one replica, P'@v82 on another, rest silent) satisfy neither A nor B
    # and stall every change until sync or new evidence.  A "supersession"
    # rule discarding the lower-view attestation is TEMPTING and sound
    # crash-only, but unsound with f byzantine: attestations are unproven
    # claims, so a commit-quorum member can deny its signature and
    # fabricate a higher-view claim, flipping a committed sequence into a
    # fresh proposal — a fork.  Without carried prepare CERTIFICATES
    # (which this protocol family, like the reference, does not ship in
    # ViewData) the stall is the safe outcome.
    return False, False, None


class _NextViews:
    """(view -> voters) bookkeeping for laggard help.

    Parity: reference internal/bft/util.go:145-163 (nextViews), with one
    liveness-critical difference and one runtime-model adaptation:

    * The help gate RE-FIRES like the reference's ``sendRecv`` (true
      whenever the examined vote is the sender's latest) — an earlier
      once-per-(view, sender) guard wedged a healed cluster forever: the
      single help broadcast happened while the chaos was still dropping
      messages, and nothing ever re-fired (seed-1234 targeted-chaos hunt:
      three replicas collecting for views 19/22/23, no two alike).
    * Re-fires are rate-limited per (view, sender).  Helps are broadcasts
      that other eligible helpers may respond to in turn; the reference
      dampens that amplification with its bounded incoming-message queue
      (InMsgQSize drops excess), which this event-driven runtime does not
      have — the time gate is the equivalent backpressure, sized by the
      caller to the vote-resend cadence so a post-heal wedge still
      resolves within one resend period."""

    def __init__(self) -> None:
        self._votes: dict[int, set[int]] = {}
        self._latest: dict[int, int] = {}
        self._last_help: dict[tuple[int, int], float] = {}

    def register(self, view: int, sender: int) -> None:
        self._votes.setdefault(view, set()).add(sender)
        if view > self._latest.get(sender, -1):
            self._latest[sender] = view

    def send_recv(self, view: int, sender: int, now: float,
                  min_interval: float) -> bool:
        """True while ``view`` is the newest vote seen from ``sender`` and
        this (view, sender) hasn't been helped within ``min_interval``."""
        if self._latest.get(sender) != view:
            return False
        key = (view, sender)
        last = self._last_help.get(key)
        if last is not None and now - last < min_interval:
            return False
        self._last_help[key] = now
        return True

    def views_above(self, view: int) -> list[int]:
        """Views > ``view`` with at least one registered voter, ascending."""
        return sorted(v for v, senders in self._votes.items() if v > view and senders)

    def voters_of(self, view: int) -> set[int]:
        return set(self._votes.get(view, ()))

    def clear(self) -> None:
        self._votes.clear()
        self._latest.clear()
        self._last_help.clear()


class ViewChanger:
    def __init__(
        self,
        *,
        scheduler: Scheduler,
        self_id: int,
        n: int,
        nodes: Sequence[int],
        comm,
        signer: Signer,
        verifier: Verifier,
        checkpoint: Checkpoint,
        in_flight: InFlightData,
        state: PersistedState,
        controller: ControllerPort,
        requests_timer: RequestsTimer,
        synchronizer,
        application,
        speed_up_view_change: bool = False,
        resend_timeout: float = 5.0,
        view_change_timeout: float = 20.0,
        leader_rotation: bool = True,
        decisions_per_leader: int = 3,
        tick_period: float = 1.0,
        on_reconfig: Optional[Callable] = None,
        metrics: Optional[MetricsViewChange] = None,
        cert_mode: str = "full",
    ) -> None:
        self._sched = scheduler
        self.self_id = self_id
        self.n = n
        self.nodes = tuple(nodes)
        self.quorum, self.f = compute_quorum(n)
        self._comm = comm
        self._signer = signer
        self._verifier = verifier
        self._checkpoint = checkpoint
        self._in_flight = in_flight
        self._state = state
        self._controller = controller
        self._requests_timer = requests_timer
        self._synchronizer = synchronizer
        self._application = application
        self._speed_up = speed_up_view_change
        self._resend_timeout = resend_timeout
        self._vc_timeout = view_change_timeout
        self._leader_rotation = leader_rotation
        self._decisions_per_leader = decisions_per_leader
        self._tick_period = tick_period
        self._on_reconfig = on_reconfig
        self.cert_mode = cert_mode

        self.curr_view = 0
        #: Last view actually installed (realView in the reference).
        self.real_view = 0
        self.next_view = 0
        self._nvs = _NextViews()
        self._view_change_votes: dict[int, ViewChange] = {}
        self._view_data_votes: dict[int, SignedViewData] = {}
        self._committed_during_view_change: Optional[ViewMetadata] = None

        self._check_timeout = False
        self._start_change_time = 0.0
        self._last_resend = 0.0
        self._backoff_factor = 1

        self._in_flight_view: Optional[View] = None
        self._pending_transition = False
        self._pending_join_target: Optional[int] = None
        #: Distinct senders whose ViewData we rejected as too far
        #: ahead this collection round — f+1 of them prove WE are the
        #: behind party (see _check_last_decision).
        self._far_ahead_senders: set[int] = set()

        self._timer: Optional[TimerHandle] = None
        self._stopped = True
        self.metrics = metrics or MetricsViewChange(NoopProvider())

    # ----------------------------------------------------------- lifecycle

    def start(self, view: int, *, restore_view_change: Optional[ViewChange] = None) -> None:
        """Parity: reference viewchanger.go Start + the Restore channel."""
        self._stopped = False
        self.curr_view = view
        self.real_view = view
        self.next_view = view
        self._update_view_gauges()
        self._last_resend = self._sched.now()
        self._schedule_tick()
        if restore_view_change is not None:
            # We voted to leave this view before crashing: rejoin it.
            self._sched.post(
                lambda: self._process_view_change_votes(restore=True),
                name="viewchange-restore",
            )

    def stop(self) -> None:
        self._stopped = True
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        if self._in_flight_view is not None:
            self._in_flight_view.abort()
            self._in_flight_view = None

    def _schedule_tick(self) -> None:
        if self._stopped:
            return
        self._timer = self._sched.call_later(
            self._tick_period, self._tick, name="viewchanger-tick"
        )

    def _tick(self) -> None:
        if self._stopped:
            return
        now = self._sched.now()
        self._check_if_resend(now)
        self._check_if_timeout(now)
        self._schedule_tick()

    def _check_if_resend(self, now: float) -> None:
        """Parity: reference viewchanger.go:235-252."""
        if now < self._last_resend + self._resend_timeout:
            return
        if self._check_timeout:
            self._comm.broadcast(ViewChange(next_view=self.next_view))
            self._last_resend = now

    def _check_if_timeout(self, now: float) -> bool:
        """Parity: reference viewchanger.go:254-270."""
        if not self._check_timeout:
            return False
        if now < self._start_change_time + self._vc_timeout * self._backoff_factor:
            return False
        logger.warning(
            "%d: view change to %d timed out (backoff %d)",
            self.self_id, self.next_view, self._backoff_factor,
        )
        self._check_timeout = False
        # Grow the round length (anti-thrash) but CAP it: an uncapped
        # factor accumulated during a long fault storm turns into a
        # minutes-long recovery stall after the network heals (a healed
        # cluster should converge within a few bounded rounds).
        self._backoff_factor = min(self._backoff_factor + 1, _BACKOFF_CAP)
        # Start each round from a FRESH view of peers' votes: corrupt or
        # stale next-view registrations otherwise poison the laggard-help
        # gate forever (a phantom high "latest vote" recorded during a
        # fault storm makes send_recv reject the sender's genuine resends
        # for eternity — the seed-171 corruption-chaos wedge).  Genuine
        # votes re-register within one resend interval.
        self._nvs.clear()
        if self._in_flight_view is not None:
            # The embedded in-flight view failed to commit in time.
            self._abandon_in_flight_view()
        self._synchronizer.sync()
        self.start_view_change(self.curr_view, stop_view=False)
        # The new timeout ROUND starts now: start_view_change's
        # already-changing early path re-arms the flag but keeps the old
        # _start_change_time, so without this reset every subsequent tick
        # "times out" again instantly and the backoff factor runs away
        # (observed at 150+ during a long corruption storm — a 1,500 s
        # recovery delay after the network healed).  The reference has the
        # same latent runaway (viewchanger.go:370 re-arms without touching
        # startViewChangeTime).
        self._start_change_time = self._sched.now()
        return True

    # ------------------------------------------------------------ identity

    def _update_view_gauges(self) -> None:
        self.metrics.current_view.set(self.curr_view)
        self.metrics.next_view.set(self.next_view)
        self.metrics.real_view.set(self.real_view)

    def _get_leader(self) -> int:
        proposal, _ = self._checkpoint.get()
        blacklist: tuple[int, ...] = ()
        if proposal.metadata:
            blacklist = tuple(decode_view_metadata(proposal.metadata).black_list)
        return get_leader_id(
            self.curr_view,
            self.n,
            self.nodes,
            leader_rotation=self._leader_rotation,
            decisions_in_view=0,
            decisions_per_leader=self._decisions_per_leader,
            blacklist=blacklist,
        )

    def _extract_current_sequence(self) -> tuple[int, Proposal]:
        proposal, _ = self._checkpoint.get()
        if not proposal.metadata:
            return 0, proposal
        return decode_view_metadata(proposal.metadata).latest_sequence, proposal

    # -------------------------------------------------------------- ingress

    def start_view_change(self, view: int, stop_view: bool) -> None:
        """A complaint arrived (pool cascade, heartbeat, bad proposal).

        Parity: reference viewchanger.go:356-391."""
        if self._stopped:
            return
        if view < self.curr_view:
            return
        if self.next_view == self.curr_view + 1:
            self._check_timeout = True  # already changing; keep the clock on
            return
        # ADVANCING to a new change: a live embedded in-flight view belongs
        # to the change being left behind and must not keep committing
        # concurrently with it (the reference's commitInFlightProposal
        # blocks the whole view changer and `defer Abort()`s the embedded
        # view on every exit, so it can never coexist with the next change
        # — viewchanger.go:1187,1287; an embedded view that survived here
        # delivered a stale decision AFTER the next view re-proposed the
        # same sequence: the seed-1144/1427 chaos-hunt fork).
        self._abandon_in_flight_view()
        self.next_view = self.curr_view + 1
        self._update_view_gauges()
        self._requests_timer.stop_timers()
        self._comm.broadcast(ViewChange(next_view=self.next_view))
        logger.info(
            "%d: started view change %d -> %d", self.self_id, self.curr_view, self.next_view
        )
        if stop_view:
            self._controller.abort_view(self.curr_view)
        self._start_change_time = self._sched.now()
        self._check_timeout = True

    def inform_new_view(self, view: int) -> None:
        """Sync discovered the cluster moved to ``view``.

        Parity: reference viewchanger.go:327-353."""
        if self._stopped or view < self.curr_view:
            return
        # Same rule as an advancing start_view_change: the embedded view
        # belongs to the change sync just moved us past.
        self._abandon_in_flight_view()
        self.curr_view = view
        self.real_view = view
        self.next_view = view
        self._update_view_gauges()
        self._nvs.clear()
        self._view_change_votes = {}
        self._view_data_votes = {}
        self._far_ahead_senders.clear()
        self._check_timeout = False
        self._backoff_factor = 1
        self._requests_timer.restart_timers()

    def handle_message(self, sender: int, msg: ConsensusMessage) -> None:
        """Parity: reference viewchanger.go:273-325 (processMsg)."""
        if self._stopped:
            return
        if isinstance(msg, ViewChange):
            self._handle_view_change(sender, msg)
        elif isinstance(msg, SignedViewData):
            self._handle_view_data(sender, msg)
        elif isinstance(msg, NewView):
            leader = self._get_leader()
            if sender != leader:
                logger.warning(
                    "%d: NewView from %d but expected leader %d",
                    self.self_id, sender, leader,
                )
                return
            self._process_new_view(msg)

    def handle_view_message(self, sender: int, msg: ConsensusMessage) -> None:
        """Feed 3-phase traffic to the embedded in-flight view.

        Parity: reference viewchanger.go:1348-1356."""
        if self._in_flight_view is not None:
            self._in_flight_view.handle_message(sender, msg)

    def _handle_view_change(self, sender: int, vc: ViewChange) -> None:
        self._nvs.register(vc.next_view, sender)
        if vc.next_view == self.curr_view + 1:
            if sender not in self._view_change_votes:
                self._view_change_votes[sender] = vc
            self._process_view_change_votes(restore=False)
            return
        if (
            self.next_view == self.curr_view + 1
            and self.real_view < vc.next_view < self.curr_view + 1
            and self._nvs.send_recv(
                vc.next_view, sender, self._sched.now(), self._resend_timeout
            )
        ):
            # Help lagging nodes converge on the earlier view change.
            self._comm.broadcast(ViewChange(next_view=vc.next_view))
            return
        if self._maybe_jump_ahead():
            return
        logger.debug(
            "%d: view change to %d from %d ignored (expecting %d)",
            self.self_id, vc.next_view, sender, self.curr_view + 1,
        )

    def _maybe_jump_ahead(self) -> bool:
        """PBFT laggard rule: f+1 distinct nodes voting for the SAME view
        beyond our next prove at least one honest replica wants that exact
        view — adopt the smallest such view, so diverged next-views
        re-converge instead of each replica escalating alone (a stall the
        randomized soak found: next-views 6/15/15/16 — view 15 carries the
        f+1 votes).  The threshold is per-view, not a union across views:
        f Byzantine votes for view X plus one honest vote for a different
        view Y must not drag us to X, a view with zero honest support
        (recovery from that relies solely on timeout escalation)."""
        target = None
        for view in self._nvs.views_above(self.next_view):
            voters = self._nvs.voters_of(view)
            voters.discard(self.self_id)
            if len(voters) >= self.f + 1:
                target = view
                break
        if target is None:
            return False
        logger.info(
            "%d: f+1 nodes vote for view %d beyond %d — jumping ahead",
            self.self_id, target, self.next_view,
        )
        # A live embedded in-flight view belongs to the abandoned change; a
        # late decide from it must not install the jumped-to view without a
        # NewView quorum (the timeout escalation path does the same).
        self._abandon_in_flight_view()
        self.curr_view = target - 1
        self.next_view = self.curr_view  # start_view_change bumps to target
        self._update_view_gauges()
        self._view_change_votes = {}  # all stale: they were for an older view+1
        self._view_data_votes = {}
        self._far_ahead_senders.clear()
        self.start_view_change(self.curr_view, stop_view=True)
        # Count any already-registered votes for the target view.
        for voter in self._nvs.voters_of(target):
            if voter != self.self_id:
                self._view_change_votes.setdefault(voter, ViewChange(next_view=target))
        self._process_view_change_votes(restore=False)
        return True

    def _process_view_change_votes(self, *, restore: bool) -> None:
        """Join + advance rules.  Parity: reference viewchanger.go:393-431.

        ``restore`` (crash recovery with a persisted ViewChange vote) joins
        unconditionally — it must re-arm the broadcast/timeout machinery just
        like a fresh join, or the replica stalls in the dead view."""
        votes = len(self._view_change_votes)
        if (votes == self.f + 1 and self._speed_up) or restore:
            self.start_view_change(self.curr_view, stop_view=True)
        if votes < self.quorum - 1 and not restore:
            return
        if not self._speed_up:
            self.start_view_change(self.curr_view, stop_view=True)
        # Snapshot the transition: under group commit the fsync window can
        # overlap state changes (inform_new_view, a jump, another quorum) —
        # the deferred continuation must no-op if it is no longer current,
        # and must not run twice for the same target.
        target = self.next_view
        prior_view = self.curr_view
        if self._pending_join_target == target and not restore:
            return
        self._pending_join_target = target

        def continue_after_durable() -> None:
            if self._pending_join_target == target:
                self._pending_join_target = None
            if self._stopped:
                return
            if self.curr_view != prior_view or self.next_view != target:
                return  # superseded while awaiting durability
            self._controller.abort_view(prior_view)
            # Installing the joined change: an embedded view still running
            # for the PREVIOUS change must not survive it (its late decide
            # would install this view without a NewView quorum) — covers
            # the path where start_view_change's already-changing guard
            # returned before its own abandon.
            self._abandon_in_flight_view()
            self.curr_view = target
            self._update_view_gauges()
            self._view_change_votes = {}
            self._view_data_votes = {}
            self._far_ahead_senders.clear()  # fresh evidence window per round
            svd = self._prepare_view_data()
            leader = self._get_leader()
            if leader == self.self_id:
                self._view_data_votes[self.self_id] = svd
                self._process_view_data_votes()
            else:
                self._comm.send(leader, svd)

        if restore:
            continue_after_durable()  # the vote is already in the WAL
        else:
            self._state.save(
                SavedViewChange(view_change=ViewChange(next_view=prior_view)),
                on_durable=continue_after_durable,
            )

    def _prepare_view_data(self) -> SignedViewData:
        """Parity: reference viewchanger.go:433-456."""
        last_decision, last_sigs = self._checkpoint.get()
        in_flight = self._get_in_flight(last_decision)
        vd = ViewData(
            next_view=self.curr_view,
            last_decision=last_decision,
            last_decision_signatures=as_cert(last_sigs),
            in_flight_proposal=in_flight,
            in_flight_prepared=self._in_flight.is_prepared(),
        )
        raw = encode_view_data(vd)
        return SignedViewData(
            raw_view_data=raw, signer=self.self_id, signature=self._signer.sign(raw)
        )

    def _get_in_flight(self, last_decision: Proposal) -> Optional[Proposal]:
        """Parity: reference viewchanger.go:458-499."""
        in_flight = self._in_flight.proposal()
        if in_flight is None:
            return None
        in_flight_md = decode_view_metadata(in_flight.metadata)
        if not last_decision.metadata:
            return in_flight  # first proposal after genesis
        last_md = decode_view_metadata(last_decision.metadata)
        if in_flight_md.latest_sequence == last_md.latest_sequence:
            return None  # already decided; not actually in flight
        if (
            in_flight_md.latest_sequence + 1 == last_md.latest_sequence
            and self._committed_during_view_change is not None
            and self._committed_during_view_change.latest_sequence
            == last_md.latest_sequence
        ):
            return None  # committed it during the view change itself
        return in_flight

    # ------------------------------------------- new-leader side (ViewData)

    def _handle_view_data(self, sender: int, svd: SignedViewData) -> None:
        if not self._validate_view_data(svd, sender):
            return
        if sender not in self._view_data_votes:
            self._view_data_votes[sender] = svd
        self._process_view_data_votes()

    def _validate_view_data(self, svd: SignedViewData, sender: int) -> bool:
        """Parity: reference viewchanger.go:501-533."""
        if self._get_leader() != self.self_id:
            logger.warning(
                "%d: got ViewData from %d but I am not the next leader",
                self.self_id, sender,
            )
            return False
        try:
            vd = decode_view_data(svd.raw_view_data)
        except Exception as e:
            logger.warning("%d: undecodable ViewData from %d: %s", self.self_id, sender, e)
            return False
        if vd.next_view != self.curr_view:
            logger.warning(
                "%d: ViewData for view %d from %d, but current is %d",
                self.self_id, vd.next_view, sender, self.curr_view,
            )
            return False
        ok, last_seq = self._check_last_decision(svd, vd, sender)
        if not ok:
            return False
        try:
            validate_in_flight(vd.in_flight_proposal, last_seq)
        except ValueError as e:
            logger.warning("%d: bad in-flight in ViewData from %d: %s", self.self_id, sender, e)
            return False
        return True

    def _check_last_decision(
        self, svd: SignedViewData, vd: ViewData, sender: int
    ) -> tuple[bool, int]:
        """Parity: reference viewchanger.go:535-666 — sender may be behind
        (reject), equal (compare decisions), or one ahead (validate quorum +
        deliver that decision ourselves)."""
        if vd.last_decision is None:
            return False, 0
        my_seq, my_last_decision = self._extract_current_sequence()

        def signature_valid() -> bool:
            if svd.signer != sender:
                return False
            try:
                self._verifier.verify_signature(
                    Signature(id=svd.signer, value=svd.signature, msg=svd.raw_view_data)
                )
                return True
            except Exception as e:
                logger.warning(
                    "%d: bad ViewData signature from %d: %s", self.self_id, sender, e
                )
                return False

        if not vd.last_decision.metadata:  # genesis
            if my_seq > 0:
                return False, 0
            return signature_valid(), 0

        last_md = decode_view_metadata(vd.last_decision.metadata)
        if last_md.view_id >= vd.next_view:
            return False, 0
        if last_md.latest_sequence > my_seq + 1:
            # Too far ahead to validate (might lack the config): reject the
            # vote, like the reference — ONE such sender might be lying.
            # But f+1 DISTINCT far-ahead senders contain an honest one, so
            # WE are provably behind: sync now.  The reference leaves this
            # to the view-change timeout's sync; that starves when every
            # vote-driven join resets the timeout clock faster than it can
            # fire (seed-1144 chaos livelock: the behind leader's ViewData
            # was rejected each cycle, CheckInFlight stayed unsatisfiable,
            # and the cluster churned view changes forever).
            self._far_ahead_senders.add(sender)
            if len(self._far_ahead_senders) >= self.f + 1:
                logger.warning(
                    "%d: %d senders report decisions far ahead of our seq "
                    "%d — we are behind; syncing",
                    self.self_id, len(self._far_ahead_senders), my_seq,
                )
                self._far_ahead_senders.clear()
                self._synchronizer.sync()
            return False, 0
        if last_md.latest_sequence < my_seq:
            return False, 0  # behind us; might lack config to validate
        if last_md.latest_sequence == my_seq:
            if not signature_valid():
                return False, 0
            if vd.last_decision != my_last_decision:
                logger.warning(
                    "%d: same-sequence last decisions differ (from %d)",
                    self.self_id, sender,
                )
                return False, 0
            return True, last_md.latest_sequence

        # Sender is exactly one decision ahead: validate and deliver it.
        try:
            validate_last_decision(vd, self.quorum, self._verifier)
        except ValueError as e:
            logger.warning(
                "%d: invalid last decision from %d: %s", self.self_id, sender, e
            )
            return False, 0
        self._deliver_decision(vd.last_decision, vd.last_decision_signatures)
        # my_seq just advanced: far-ahead evidence gathered against the old
        # sequence no longer proves anything — start fresh.
        self._far_ahead_senders.clear()
        self._committed_during_view_change = last_md
        if self._stopped:  # delivery carried a reconfig
            return False, 0
        if not signature_valid():
            return False, 0
        return True, last_md.latest_sequence

    def _process_view_data_votes(self) -> None:
        """Parity: reference viewchanger.go:747-785."""
        if len(self._view_data_votes) < self.quorum:
            return
        # Assemble the ACTUAL broadcast set first — a fresh own ViewData
        # (it may have changed since registration, e.g. a one-ahead decision
        # delivered in between) plus the other registered votes — and run
        # check_in_flight on exactly that set.  Followers recompute the check
        # on the broadcast contents, so checking anything else (like the
        # registered set with the stale own vote) could assemble a NewView
        # every follower rejects, wasting the round.
        my_msg = self._prepare_view_data()
        signed = [my_msg] + [
            svd for s, svd in self._view_data_votes.items() if s != self.self_id
        ]
        final_msgs = [decode_view_data(svd.raw_view_data) for svd in signed]
        ok, _, _ = check_in_flight(final_msgs, self.f, self.quorum)
        if not ok:
            logger.info("%d: in-flight check not yet satisfiable", self.self_id)
            return
        new_view = NewView(signed_view_data=tuple(signed))
        self._comm.broadcast(new_view)
        self._view_data_votes = {}
        self._process_new_view(new_view)  # leader installs it too

    # ------------------------------------------- follower side (NewView)

    def _process_new_view(self, msg: NewView) -> None:
        """Parity: reference viewchanger.go:1111-1168."""
        if self.next_view == self.curr_view + 1:
            # A NEWER change is already in progress: this NewView is for
            # the change we moved past.  Acting on it (worst case starting
            # an embedded in-flight view whose late decide would install
            # the newer view without its own NewView quorum) re-opens the
            # stale-decide hole — the reference cannot reach this state at
            # all because its view-changer loop blocks while a NewView is
            # being acted on.
            logger.info(
                "%d: ignoring NewView for view %d — already changing to %d",
                self.self_id, self.curr_view, self.next_view,
            )
            return
        while True:
            valid, called_sync, called_deliver = self._validate_new_view(msg)
            if not called_deliver:
                break
        if not valid:
            return
        if called_sync:
            return

        messages = []
        for svd in msg.signed_view_data:
            try:
                messages.append(decode_view_data(svd.raw_view_data))
            except Exception:
                return
        ok, no_in_flight, proposal = check_in_flight(messages, self.f, self.quorum)
        if not ok:
            logger.info("%d: NewView in-flight check failed", self.self_id)
            return
        if not no_in_flight:
            self._commit_in_flight(proposal)
            return  # transition completes from the embedded view's decide
        self._finish_new_view()

    def _validate_new_view(self, msg: NewView) -> tuple[bool, bool, bool]:
        """Parity: reference viewchanger.go:932-1096 (validateNewViewMsg).

        Returns (valid, called_sync, called_deliver)."""
        seen: set[int] = set()
        valid_count = 0
        my_seq, my_last_decision = self._extract_current_sequence()
        for svd in msg.signed_view_data:
            if svd.signer in seen:
                continue
            seen.add(svd.signer)
            try:
                vd = decode_view_data(svd.raw_view_data)
            except Exception:
                return False, False, False
            if vd.next_view != self.curr_view:
                logger.warning(
                    "%d: NewView contains ViewData for view %d, current is %d",
                    self.self_id, vd.next_view, self.curr_view,
                )
                return False, False, False
            if vd.last_decision is None:
                return False, False, False

            def svd_signature_valid() -> bool:
                try:
                    self._verifier.verify_signature(
                        Signature(
                            id=svd.signer, value=svd.signature, msg=svd.raw_view_data
                        )
                    )
                    return True
                except Exception:
                    return False

            if not vd.last_decision.metadata:  # genesis
                if my_seq == 0 and not svd_signature_valid():
                    return False, False, False
                try:
                    validate_in_flight(vd.in_flight_proposal, 0)
                except ValueError:
                    return False, False, False
                valid_count += 1
                continue

            last_md = decode_view_metadata(vd.last_decision.metadata)
            if last_md.view_id >= vd.next_view:
                return False, False, False
            if last_md.latest_sequence > my_seq + 1:
                self._synchronizer.sync()
                return True, True, False
            if last_md.latest_sequence < my_seq:
                try:
                    validate_in_flight(vd.in_flight_proposal, last_md.latest_sequence)
                except ValueError:
                    return False, False, False
                valid_count += 1
                continue
            if last_md.latest_sequence == my_seq:
                if not svd_signature_valid():
                    return False, False, False
                if vd.last_decision != my_last_decision:
                    return False, False, False
                try:
                    validate_in_flight(vd.in_flight_proposal, last_md.latest_sequence)
                except ValueError:
                    return False, False, False
                valid_count += 1
                continue

            # One ahead of us: validate + deliver, then re-walk the message.
            try:
                validate_last_decision(vd, self.quorum, self._verifier)
            except ValueError as e:
                logger.warning("%d: NewView last decision invalid: %s", self.self_id, e)
                return False, False, False
            self._deliver_decision(vd.last_decision, vd.last_decision_signatures)
            if self._stopped:
                return False, False, False
            if not svd_signature_valid():
                return False, False, False
            try:
                validate_in_flight(vd.in_flight_proposal, last_md.latest_sequence)
            except ValueError:
                return False, False, False
            return True, False, True

        if valid_count < self.quorum:
            logger.warning(
                "%d: NewView has only %d valid ViewData (quorum %d)",
                self.self_id, valid_count, self.quorum,
            )
            return False, False, False
        return True, False, False

    def _finish_new_view(self) -> None:
        """Install the new view (after any in-flight re-commit).

        Parity: reference viewchanger.go:1141-1168."""
        self._pending_transition = False
        my_seq, _ = self._extract_current_sequence()
        self._state.save(
            SavedNewView(
                view_metadata=ViewMetadata(
                    view_id=self.curr_view, latest_sequence=my_seq
                )
            )
        )
        if self._stopped:
            return
        self.real_view = self.curr_view
        self._update_view_gauges()
        self._nvs.clear()
        self._controller.view_changed(self.curr_view, my_seq + 1)
        self._requests_timer.restart_timers()
        self._check_timeout = False
        self._backoff_factor = 1
        logger.info("%d: installed view %d at seq %d", self.self_id, self.curr_view, my_seq + 1)

    def _deliver_decision(
        self, proposal: Proposal, signatures: Sequence[Signature]
    ) -> None:
        """Parity: reference viewchanger.go:1170-1185."""
        reconfig = self._application.deliver(proposal, signatures)
        if reconfig.in_latest_decision:
            self.stop()
            if self._on_reconfig is not None:
                self._on_reconfig(reconfig)
            return
        self._requests_timer.remove_requests(
            self._verifier.requests_from_proposal(proposal)
        )
        self._controller.maybe_prune_revoked_requests()

    # --------------------------------------- in-flight re-commit (embedded)

    def _commit_in_flight(self, proposal: Proposal) -> None:
        """Spin up a View already in PREPARED, seeded with our own commit
        signature and ourselves as leader, so the cluster re-commits the
        in-flight proposal.  Parity: reference viewchanger.go:1187-1307."""
        my_last_decision, _ = self._checkpoint.get()
        md = decode_view_metadata(proposal.metadata)
        if my_last_decision.metadata:
            last_md = decode_view_metadata(my_last_decision.metadata)
            if last_md.latest_sequence == md.latest_sequence:
                if my_last_decision != proposal:
                    logger.warning(
                        "%d: already decided seq %d differently than the in-flight",
                        self.self_id, md.latest_sequence,
                    )
                    return
                self._finish_new_view()  # already committed it
                return
            if last_md.latest_sequence != md.latest_sequence - 1:
                logger.error(
                    "%d: in-flight seq %d does not follow our last %d",
                    self.self_id, md.latest_sequence, last_md.latest_sequence,
                )
                return

        view = View(
            scheduler=self._sched,
            self_id=self.self_id,
            number=md.view_id,
            leader_id=self.self_id,  # no byzantine leader can trigger complaints
            proposal_sequence=md.latest_sequence,
            decisions_in_view=md.decisions_in_view,
            n=self.n,
            nodes=self.nodes,
            comm=self._comm,
            verifier=self._verifier,
            signer=self._signer,
            state=self._state,
            decider=_InFlightDecider(self),
            failure_detector=_InFlightFailureDetector(),
            sync_requester=_InFlightSync(self),
            checkpoint=self._checkpoint,
            decisions_per_leader=self._decisions_per_leader if self._leader_rotation else 0,
            cert_mode=self.cert_mode,
        )
        view.phase = Phase.PREPARED
        view.in_flight_proposal = proposal
        # Best-effort, shared with the WAL restore paths: an application
        # exception here must not stall the view change (the requests only
        # feed pool cleanup at decide time).
        restore_requests_best_effort(view, proposal)
        view.my_commit_signature = self._signer.sign_proposal(proposal, b"")
        commit = Commit(
            view=view.number,
            seq=view.proposal_sequence,
            digest=proposal.digest(),
            signature=view.my_commit_signature,
            assist=True,
        )
        view._curr_commit_sent = commit
        self._in_flight_view = view
        self._pending_transition = True
        # PERSIST THE ENDORSEMENT BEFORE THE SIGNATURE LEAVES THIS PROCESS
        # (the normal 3-phase discipline, core/view.py): the commit
        # signature minted above can complete a 2f+1 quorum at ANY later
        # time, so from this point every future ViewData of ours must
        # attest (proposal, prepared) — otherwise a subsequent view change
        # can conclude "no in-flight", re-propose this sequence fresh, and
        # fork against whoever assembles the quorum (the second half of
        # the seed-1144/1427 chaos-hunt fork).  Saving the records also
        # updates InFlightData (store_proposal + store_prepared) and gives
        # a crash-restore the standard [proposed, commit] tail to resurrect
        # the endorsement from.
        self._state.save(
            ProposedRecord(
                pre_prepare=PrePrepare(
                    view=view.number,
                    seq=view.proposal_sequence,
                    proposal=proposal,
                ),
                prepare=Prepare(
                    view=view.number,
                    seq=view.proposal_sequence,
                    digest=proposal.digest(),
                ),
                verified=True,
            ),
            # No truncation: this record implies no newly-decided sequence,
            # and the default truncate-on-proposal would erase the pending
            # SavedViewChange/SavedNewView history a crash-restore needs —
            # load_view_change_if_applicable scans back over exactly this
            # [vote, proposed, commit] tail to rejoin the pending change.
            truncate=False,
            fault_point="state.save.endorsement_proposed",
        )

        def start_after_durable() -> None:
            if self._stopped or self._in_flight_view is not view:
                return  # abandoned while the record was flushing
            view.start()
            # Peers that started their embedded view later missed our
            # commit broadcast: re-send it every tick until the view
            # decides (the reference instead delays its start by two ticks
            # and relies on the run-loop re-broadcast,
            # viewchanger.go:1277-1280 + view.go:285-288).
            self._rebroadcast_in_flight_commit(view, commit)
            logger.info(
                "%d: started embedded in-flight view %d for seq %d",
                self.self_id, view.number, view.proposal_sequence,
            )

        self._state.save(
            SavedCommit(commit=commit),
            on_durable=start_after_durable,
            fault_point="state.save.endorsement_commit",
        )

    def _rebroadcast_in_flight_commit(self, view: View, commit: Commit) -> None:
        if self._stopped or self._in_flight_view is not view or view.stopped:
            return
        self._comm.broadcast(commit)
        self._sched.call_later(
            self._tick_period,
            lambda: self._rebroadcast_in_flight_commit(view, commit),
            name="in-flight-commit-rebroadcast",
        )

    def _abandon_in_flight_view(self) -> None:
        if self._in_flight_view is not None:
            self._in_flight_view.abort()
            self._in_flight_view = None
        self._pending_transition = False

    # Embedded-view callbacks ------------------------------------------------

    def _in_flight_decided(
        self,
        proposal: Proposal,
        signatures: Sequence[Signature],
        requests: Sequence[RequestInfo],
    ) -> None:
        """Parity: reference viewchanger.go:1310-1332 (Decide)."""
        if self._in_flight_view is not None:
            self._in_flight_view.abort()
            self._in_flight_view = None
        self._deliver_decision(proposal, signatures)
        if self._stopped:
            return
        if self._pending_transition:
            self._finish_new_view()

    def _in_flight_sync(self) -> None:
        """Parity: reference viewchanger.go:1340-1345."""
        self._abandon_in_flight_view()
        self._synchronizer.sync()


class _InFlightDecider:
    def __init__(self, vc: ViewChanger) -> None:
        self._vc = vc

    def decide(self, proposal, signatures, requests) -> None:
        self._vc._in_flight_decided(proposal, signatures, requests)


class _InFlightFailureDetector:
    def complain(self, view: int, stop_view: bool) -> None:
        # The embedded view's leader is ourselves; a complaint here would be
        # a protocol bug (the reference panics).
        logger.error("complaint raised inside the in-flight re-commit view")


class _InFlightSync:
    def __init__(self, vc: ViewChanger) -> None:
        self._vc = vc

    def sync(self) -> None:
        self._vc._in_flight_sync()


__all__ = [
    "ViewChanger",
    "validate_last_decision",
    "validate_in_flight",
    "check_in_flight",
]
