"""State collector: gathers StateTransferResponse votes until f+1 agree on a
(view, seq), or the collection window closes.

Parity: reference internal/bft/statecollector.go:18-148.  The reference
blocks the calling goroutine on ``CollectStateResponses`` with a timeout;
here collection is a window opened by ``begin`` and closed by either an
f+1 agreement or the ``collect_timeout`` timer — the result arrives via
callback on the replica loop.
"""

from __future__ import annotations

import logging
from typing import Callable, Optional

from consensus_tpu.runtime.scheduler import Scheduler, TimerHandle
from consensus_tpu.utils.quorum import compute_quorum
from consensus_tpu.wire import StateTransferResponse

logger = logging.getLogger("consensus_tpu.collector")


class StateCollector:
    def __init__(
        self, scheduler: Scheduler, *, n: int, collect_timeout: float
    ) -> None:
        self._sched = scheduler
        self._n = n
        self._timeout = collect_timeout
        _, self._f = compute_quorum(n)
        self._votes: dict[int, tuple[int, int]] = {}
        self._callback: Optional[Callable[[Optional[tuple[int, int]]], None]] = None
        self._timer: Optional[TimerHandle] = None

    def begin(self, on_result: Callable[[Optional[tuple[int, int]]], None]) -> None:
        """Open a collection window.  ``on_result`` receives the agreed
        (view, seq) or ``None`` on timeout.  A new ``begin`` supersedes any
        window still open (its callback gets ``None``)."""
        self._finish(None)
        self._votes = {}
        self._callback = on_result
        self._timer = self._sched.call_later(
            self._timeout, lambda: self._finish(None), name="state-collect-timeout"
        )

    def handle_response(self, sender: int, msg: StateTransferResponse) -> None:
        if self._callback is None:
            return  # no window open; late response
        self._votes[sender] = (msg.view_num, msg.sequence)
        counts: dict[tuple[int, int], int] = {}
        for vote in self._votes.values():
            counts[vote] = counts.get(vote, 0) + 1
        for vote, count in counts.items():
            if count >= self._f + 1:
                logger.debug("state agreement: view=%d seq=%d (%d votes)", *vote, count)
                self._finish(vote)
                return

    def _finish(self, result) -> None:
        cb = self._callback
        if cb is None:
            return
        self._callback = None
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        cb(result)

    def close(self) -> None:
        self._finish(None)


__all__ = ["StateCollector"]
