"""The consensus protocol state machines (single-threaded, event-driven)."""

from consensus_tpu.core.batcher import Batcher
from consensus_tpu.core.pool import PoolOptions, RequestPool, RequestTimeoutHandler

__all__ = [
    "RequestPool",
    "PoolOptions",
    "RequestTimeoutHandler",
    "Batcher",
]
