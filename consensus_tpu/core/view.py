"""The View: one instance of the 3-phase ordering pipeline.

Parity: reference internal/bft/view.go (the 1085-LoC hot loop).  A View is
created per (view number, leader) and restarted on every decision, rotation,
or view change.  Phases walk COMMITTED → PROPOSED → PREPARED → (decide) →
COMMITTED, with ABORT as the exit.

Architectural deviations (deliberate, TPU-first):

* **Event-driven, not goroutine-driven.**  The reference's ``run`` loop
  blocks on channels (view.go:262-299); here ``handle_message`` mutates vote
  state and ``_advance`` replays the phase logic until it stalls waiting for
  more input.  Decisions hand off through the scheduler (``post``) so deep
  decide→next-seq chains never recurse.
* **Batched commit verification.**  The reference spawns a goroutine per
  commit vote and verifies signatures one by one (view.go:537-541,820-849).
  Here incoming commit votes are *buffered unverified*; once enough are
  pending to possibly reach quorum they are verified in a single
  ``verify_consenter_sigs_batch`` call — the seam the TPU engine implements
  as one vmap'd kernel launch.  The same batch seam covers the leader-carried
  previous-commit signatures in ``verify_proposal``.
"""

from __future__ import annotations

import dataclasses
import logging
from enum import IntEnum
from typing import Callable, Optional, Protocol, Sequence

from consensus_tpu.api.deps import MembershipNotifier, Signer, Verifier
from consensus_tpu.metrics import MetricsConsensus, MetricsView, NoopProvider
from consensus_tpu.runtime.scheduler import Scheduler
from consensus_tpu.trace.tracer import NOOP_TRACER
from consensus_tpu.types import Proposal, QuorumCert, RequestInfo, Signature, as_cert
from consensus_tpu.utils.digests import commit_signatures_digest
from consensus_tpu.utils.blacklist import compute_blacklist_update
from consensus_tpu.utils.quorum import compute_quorum
from consensus_tpu.wire import (
    Commit,
    ConsensusMessage,
    PrePrepare,
    Prepare,
    PreparesFrom,
    ProposedRecord,
    SavedCommit,
    ViewMetadata,
    decode_prepares_from,
    decode_view_metadata,
    encode_prepares_from,
    encode_view_metadata,
    encoded_cert_size,
    msg_to_string,
)

logger = logging.getLogger("consensus_tpu.view")

#: TEST-ONLY sentinel (chaos-engine end-to-end validation;
#: tests/test_chaos_engine.py): when flipped, any view installed by a view
#: change (number > 0) collects only a SINGLE peer commit before deciding —
#: a deliberately mis-wired quorum check.  The delivered decision then
#: carries fewer than ``2f + 1`` consenter signatures, which the invariant
#: monitor's commit-implies-quorum-cert check must flag AT DELIVERY TIME,
#: and the delta-debugging shrinker must reduce any failing schedule down
#: to the disruptive action(s) that forced the view change.  Never set
#: outside tests; production constructors cannot reach it.
SENTINEL_MISWIRED_QUORUM = False


class Phase(IntEnum):
    """Parity: reference internal/bft/view.go:23-46."""

    COMMITTED = 0
    PROPOSED = 1
    PREPARED = 2
    ABORT = 3


class Decider(Protocol):
    """Receives a decided proposal (the Controller).

    Parity: reference internal/bft/controller.go:22-24.
    """

    def decide(
        self,
        proposal: Proposal,
        signatures: Sequence[Signature],
        requests: Sequence[RequestInfo],
    ) -> None: ...


class FailureDetector(Protocol):
    """Parity: reference internal/bft/controller.go:29-31."""

    def complain(self, view: int, stop_view: bool) -> None: ...


class SyncRequester(Protocol):
    def sync(self) -> None: ...


class ViewComm(Protocol):
    """Outbound messaging as the view sees it (Controller provides it)."""

    def broadcast(self, msg: ConsensusMessage) -> None: ...

    def send(self, target_id: int, msg: ConsensusMessage) -> None: ...


class ViewState(Protocol):
    """WAL persistence seam (PersistedState implements it).  ``save`` also
    accepts a ``truncate`` keyword (pipelined future-slot records pass
    ``truncate=False`` so only the oldest slot marks restore points); it is
    omitted here so depth-1 fakes need not accept it."""

    def save(self, record, on_durable=None) -> None: ...

    def mark_proposed_verified(self, view_number: int, seq: int) -> None: ...


class CheckpointReader(Protocol):
    def get(self) -> tuple[Proposal, tuple[Signature, ...]]: ...


class _FutureSlot:
    """Per-sequence state for one in-flight slot ABOVE the oldest undecided
    sequence (pipeline_depth > 1 only).  A future slot runs pre-prepare and
    prepare — verify the proposal, persist the ProposedRecord, broadcast our
    Prepare, collect peers' votes — but NEVER signs a commit: the in-order
    commit gate lives in the promotion path (_start_next_seq), which folds
    the slot into the View's legacy current-sequence fields only after every
    lower sequence has decided."""

    __slots__ = (
        "pre_prepare", "proposal", "requests", "prepares", "commits",
        "prepare_sent", "processed", "valid_commit_sigs", "rejected", "begin",
    )

    def __init__(self) -> None:
        self.pre_prepare: Optional[tuple[int, PrePrepare]] = None
        self.proposal: Optional[Proposal] = None
        self.requests: Sequence[RequestInfo] = ()
        self.prepares: dict[int, Prepare] = {}
        self.commits: dict[int, Commit] = {}
        self.prepare_sent: Optional[Prepare] = None
        self.processed = False
        self.valid_commit_sigs: dict[int, Signature] = {}
        self.rejected: set[int] = set()
        self.begin = 0.0


class View:
    """A single view's ordering state machine."""

    def __init__(
        self,
        *,
        scheduler: Scheduler,
        self_id: int,
        number: int,
        leader_id: int,
        proposal_sequence: int,
        decisions_in_view: int,
        n: int,
        nodes: Sequence[int],
        comm: ViewComm,
        verifier: Verifier,
        signer: Signer,
        state: ViewState,
        decider: Decider,
        failure_detector: FailureDetector,
        sync_requester: SyncRequester,
        checkpoint: CheckpointReader,
        decisions_per_leader: int = 0,
        membership_notifier: Optional[MembershipNotifier] = None,
        blacklist_supported: bool = False,
        metrics: Optional[MetricsView] = None,
        pipeline_depth: int = 1,
        consensus_metrics: Optional[MetricsConsensus] = None,
        tracer=None,
        cert_mode: str = "full",
    ) -> None:
        self._sched = scheduler
        self.self_id = self_id
        self.number = number
        self.leader_id = leader_id
        self.proposal_sequence = proposal_sequence
        self.decisions_in_view = decisions_in_view
        self.n = n
        self.nodes = tuple(nodes)
        self.quorum, self.f = compute_quorum(n)
        self._comm = comm
        self._verifier = verifier
        self._signer = signer
        self._state = state
        self._decider = decider
        self._failure_detector = failure_detector
        self._sync = sync_requester
        self._checkpoint = checkpoint
        self.decisions_per_leader = decisions_per_leader
        self._membership_notifier = membership_notifier
        self._blacklist_supported = blacklist_supported

        self.phase = Phase.COMMITTED
        self.in_flight_proposal: Optional[Proposal] = None
        self.in_flight_requests: Sequence[RequestInfo] = ()
        self.my_commit_signature: Optional[Signature] = None

        #: Bounded in-flight window (config `pipeline_depth`).  The legacy
        #: single-slot fields below always describe the OLDEST undecided
        #: sequence; sequences strictly above it (up to the window edge) live
        #: in `_future` and only ever reach the prepare phase there — the
        #: commit gate is promotion-ordered (see _FutureSlot).
        self.pipeline_depth = max(1, pipeline_depth)
        self._future: dict[int, _FutureSlot] = {}
        self._consensus_metrics = consensus_metrics
        #: Configuration.cert_mode — "half-agg" compresses each decided
        #: quorum into a half-aggregated QuorumCert (models/aggregate.py)
        #: when the verifier supports it; "full" keeps signature tuples
        #: bit-for-bit.
        self.cert_mode = cert_mode

        # Pipelining buffers: current sequence + the next one (depth 1),
        # parity: reference view.go:107-113,860-894.
        self._pending_pre_prepare: Optional[tuple[int, PrePrepare]] = None
        self._next_pre_prepare: Optional[tuple[int, PrePrepare]] = None
        self._prepares: dict[int, Prepare] = {}
        self._next_prepares: dict[int, Prepare] = {}
        self._commits: dict[int, Commit] = {}
        self._next_commits: dict[int, Commit] = {}
        #: Commit signatures proven valid for the in-flight proposal.
        self._valid_commit_sigs: dict[int, Signature] = {}
        #: Commit senders whose signature failed batch verification.
        self._rejected_commit_senders: set[int] = set()

        # Retransmission help (previous sequence), view.go:718-756.
        self._prev_prepare_sent: Optional[Prepare] = None
        self._prev_commit_sent: Optional[Commit] = None
        self._curr_prepare_sent: Optional[Prepare] = None
        self._curr_commit_sent: Optional[Commit] = None

        # Censorship / partition detection, view.go:758-818.
        self._last_voted_proposal_by_id: dict[int, Commit] = {}

        self.stopped = False
        #: Set when a restore re-verification of our own proposal failed
        #: (state.py::_enter_proposed): we stay pinned to the proposal (no
        #: equivocation) but must never endorse it — no prepare was armed,
        #: and the PROPOSED->PREPARED transition (which signs a commit, a
        #: stronger endorsement) is blocked until a view change resolves it.
        self.endorsement_blocked = False
        self._begin_pre_prepare = 0.0
        self._tracer = tracer if tracer is not None else NOOP_TRACER
        self.metrics = metrics or MetricsView(NoopProvider())
        self.metrics.view_number.set(number)
        self.metrics.leader_id.set(leader_id)
        self.metrics.proposal_sequence.set(proposal_sequence)
        self.metrics.decisions_in_view.set(decisions_in_view)

    # ------------------------------------------------------------------ API

    def start(self) -> None:
        """Kick a (possibly WAL-restored) view into action: re-broadcast the
        message implied by the restored phase (reference resurrects
        ``lastBroadcastSent``, internal/bft/state.go:163-247)."""
        if self.phase != Phase.COMMITTED and self._begin_pre_prepare == 0.0:
            # Restored mid-protocol: latency measures from the restart, not
            # from clock epoch.
            self._begin_pre_prepare = self._sched.now()
        self.metrics.phase.set(int(self.phase))
        # The recovery rebroadcast goes out WITHOUT the assist flag: peers
        # that already moved past this sequence reply to a non-assist
        # message with their own prev-seq assist copies (that reply is how
        # a commit-starved replica closes its gap), but deliberately ignore
        # assist-marked ones to avoid reply loops.  The stored *_sent copies
        # keep assist=True for their other job, straggler retransmission
        # help.  Parity: reference view.go:285-288 ("broadcast here serves
        # also recovery") vs the assist copies of view.go:417,512.
        if self.phase == Phase.PROPOSED and self._curr_prepare_sent is not None:
            self._comm.broadcast(
                dataclasses.replace(self._curr_prepare_sent, assist=False)
            )
        elif self.phase == Phase.PREPARED and self._curr_commit_sent is not None:
            self._comm.broadcast(
                dataclasses.replace(self._curr_commit_sent, assist=False)
            )

    @property
    def effective_depth(self) -> int:
        """Window width actually in force.  Rotation counts decisions per
        leader against checkpoint certificates a pipelined window does not
        produce in order, so depth collapses to 1 under rotation (config
        validation rejects the combination outright)."""
        return self.pipeline_depth if self.decisions_per_leader == 0 else 1

    @property
    def next_propose_seq(self) -> int:
        """First sequence in the window with no accepted or pending
        proposal — the slot the leader's next pre-prepare targets."""
        if (
            self.phase == Phase.COMMITTED
            and self._pending_pre_prepare is None
            and self.in_flight_proposal is None
        ):
            return self.proposal_sequence
        s = self.proposal_sequence + 1
        while True:
            slot = self._future.get(s)
            if slot is None or slot.pre_prepare is None:
                return s
            s += 1

    def can_propose(self) -> bool:
        """Whether the leader still has window room for another proposal
        (always False at depth 1: the controller's decide-driven token flow
        already covers the single-slot cadence)."""
        if self.stopped or self.effective_depth <= 1:
            return False
        return self.next_propose_seq < self.proposal_sequence + self.effective_depth

    def propose(self, proposal: Proposal) -> None:
        """Leader entry point: wrap ``proposal`` in a PrePrepare carrying the
        previous decision's commit signatures, and pre-prepare *ourselves*
        first (the broadcast to others happens after we persist — parity:
        reference view.go:951-974, 421-423).

        With a pipelined window the pre-prepare targets the first free slot,
        and carries NO previous-decision signatures: a follower verifying a
        future slot has not delivered the preceding decisions yet, so its
        checkpoint cannot match whatever certificate the leader would attach
        (pipelining requires rotation off, where the certificate is unused
        and `_verify_prev_commit_signatures` accepts an empty set)."""
        pipelined = self.effective_depth > 1
        _, prev_sigs = self._checkpoint.get()
        prev_cert = () if pipelined else as_cert(prev_sigs)
        pp = PrePrepare(
            view=self.number,
            seq=self.next_propose_seq if pipelined else self.proposal_sequence,
            proposal=proposal,
            prev_commit_signatures=prev_cert,
        )
        if isinstance(prev_cert, QuorumCert) and self._consensus_metrics is not None:
            self._consensus_metrics.net_cert_bytes.add(encoded_cert_size(prev_cert))
        self.handle_message(self.leader_id, pp)

    def abort(self) -> None:
        """Parity: reference view.go Abort/stop."""
        if not self.stopped and self._tracer.enabled:
            self._tracer.instant(
                "view", "view.abort", seq=self.proposal_sequence, view=self.number
            )
        self.stopped = True
        self.phase = Phase.ABORT
        self.metrics.phase.set(int(self.phase))

    @property
    def view_sequence(self) -> tuple[int, int]:
        return self.number, self.proposal_sequence

    # ----------------------------------------------------------- ingress

    def handle_message(self, sender: int, msg: ConsensusMessage) -> None:
        """Route one consensus message into the view.

        Parity: reference view.go:194-259 (processMsg).
        """
        if self.stopped:
            return
        if not isinstance(msg, (PrePrepare, Prepare, Commit)):
            return

        msg_view = msg.view
        msg_seq = msg.seq

        if msg_view != self.number:
            if sender != self.leader_id:
                self._discover_if_sync_needed(sender, msg)
                return
            # Wrong view *from the leader* is evidence of a sick leader.
            logger.warning(
                "%d: leader %d sent view %d, expected %d — complaining",
                self.self_id, sender, msg_view, self.number,
            )
            self._failure_detector.complain(self.number, False)
            if msg_view > self.number:
                self._sync.sync()
            self.abort()
            return

        if msg_seq == self.proposal_sequence - 1 and self.proposal_sequence > 0:
            self._handle_prev_seq_message(sender, msg)
            return

        depth = self.effective_depth
        if depth > 1 and self.proposal_sequence < msg_seq <= self.proposal_sequence + depth:
            # Windowed mode: anything above the oldest slot (up to one past
            # the window edge, for a leader one decision ahead of us) lands
            # in a future slot.  Depth 1 keeps the legacy ps/ps+1 routing
            # below untouched.
            self._handle_future_slot_message(sender, msg, msg_seq)
            return
        if depth > 1 and msg_seq < self.proposal_sequence - 1:
            # Replicas spread over several sequences routinely deliver
            # assist votes for slots the window has already decided and
            # advanced past — stale by construction, not sync evidence.
            return

        if msg_seq not in (self.proposal_sequence, self.proposal_sequence + 1):
            logger.warning(
                "%d: got %s from %d at seq %d, ours is %d",
                self.self_id, msg_to_string(msg), sender, msg_seq, self.proposal_sequence,
            )
            self._discover_if_sync_needed(sender, msg)
            return

        for_next = msg_seq == self.proposal_sequence + 1

        if isinstance(msg, PrePrepare):
            self._accept_pre_prepare(sender, msg, for_next)
        elif sender == self.self_id:
            return  # own votes are implicit
        elif isinstance(msg, Prepare):
            votes = self._next_prepares if for_next else self._prepares
            votes.setdefault(sender, msg)
            if not for_next:
                self._advance()
        else:  # Commit
            if msg.signature.id != sender:
                return  # vote must be signed by its sender
            votes = self._next_commits if for_next else self._commits
            votes.setdefault(sender, msg)
            if not for_next:
                self._advance()

    def _accept_pre_prepare(self, sender: int, pp: PrePrepare, for_next: bool) -> None:
        if sender != self.leader_id:
            logger.warning(
                "%d: pre-prepare from %d but leader is %d",
                self.self_id, sender, self.leader_id,
            )
            return
        if for_next:
            if self._next_pre_prepare is None:
                self._next_pre_prepare = (sender, pp)
            return
        if self._pending_pre_prepare is None:
            self._pending_pre_prepare = (sender, pp)
            self._advance()

    # ------------------------------------------- pipelined window (depth > 1)

    def _handle_future_slot_message(
        self, sender: int, msg: ConsensusMessage, seq: int
    ) -> None:
        """Buffer/process a message for a sequence above the oldest slot.

        Sequences strictly inside the window run pre-prepare/prepare
        immediately; the slot one past the window edge is buffer-only until
        a decision slides the window over it."""
        slot = self._future.get(seq)
        if slot is None:
            slot = self._future[seq] = _FutureSlot()
        if isinstance(msg, PrePrepare):
            if sender != self.leader_id:
                logger.warning(
                    "%d: pre-prepare from %d but leader is %d",
                    self.self_id, sender, self.leader_id,
                )
                return
            if slot.pre_prepare is None:
                slot.pre_prepare = (sender, msg)
                if seq < self.proposal_sequence + self.effective_depth:
                    self._process_future_slot(seq, slot)
            return
        if sender == self.self_id:
            return  # own votes are implicit
        if isinstance(msg, Prepare):
            slot.prepares.setdefault(sender, msg)
        else:  # Commit
            if msg.signature.id != sender:
                return  # vote must be signed by its sender
            slot.commits.setdefault(sender, msg)

    def _process_future_slot(self, seq: int, slot: _FutureSlot) -> None:
        """Run pre-prepare + prepare for a future slot: verify, persist the
        ProposedRecord (truncate-free — only the oldest slot marks a stable
        restore point), and broadcast our Prepare once durable AND verified.
        Mirrors _try_process_proposal but never advances the legacy phase
        machine — commits stay gated on promotion."""
        assert slot.pre_prepare is not None
        _, pp = slot.pre_prepare
        proposal = pp.proposal
        i_am_leader = self.self_id == self.leader_id
        prepare = Prepare(view=self.number, seq=seq, digest=proposal.digest())
        gate = {"durable": False, "verified": False, "prepare_sent": False}
        tracer = self._tracer
        if tracer.enabled:
            tracer.begin("view", "decision", seq=seq, view=self.number)
            tracer.begin("view", "phase.pre_prepare", seq=seq, view=self.number)

        def maybe_send_prepare() -> None:
            if not (gate["durable"] and gate["verified"]) or gate["prepare_sent"]:
                return
            gate["prepare_sent"] = True
            if self.stopped:
                return  # aborted view: never utter stale-view votes
            assist_copy = Prepare(
                view=prepare.view, seq=prepare.seq, digest=prepare.digest, assist=True
            )
            # A late flush may land after this slot was promoted (it became
            # the current sequence) or even decided; park the assist copy
            # wherever the retransmission machinery now looks for it.
            if self.proposal_sequence == seq and self._curr_prepare_sent is None:
                self._curr_prepare_sent = assist_copy
            elif self.proposal_sequence == seq + 1 and self._prev_prepare_sent is None:
                self._prev_prepare_sent = assist_copy
            else:
                slot.prepare_sent = assist_copy
            self._comm.broadcast(prepare)

        def send_after_durable() -> None:
            if gate["durable"]:
                return
            gate["durable"] = True
            if self.stopped:
                return
            if i_am_leader:
                # Reveal-before-verify, same rationale as the oldest slot.
                self._comm.broadcast(pp)
            maybe_send_prepare()

        if i_am_leader:
            self._state.save(
                ProposedRecord(pre_prepare=pp, prepare=prepare, verified=False),
                on_durable=send_after_durable,
                truncate=False,
            )
        try:
            requests = self._verify_proposal(
                proposal,
                pp.prev_commit_signatures,
                expected_seq=seq,
                expected_decisions=self.decisions_in_view
                + (seq - self.proposal_sequence),
            )
        except Exception as err:
            logger.warning(
                "%d: bad pipelined proposal from leader %d at seq %d: %s",
                self.self_id, self.leader_id, seq, err,
            )
            if tracer.enabled:
                tracer.instant(
                    "view", "proposal.rejected", seq=seq, view=self.number
                )
                tracer.end("view", "phase.pre_prepare", seq=seq, view=self.number)
                tracer.end("view", "decision", seq=seq, view=self.number)
            self._failure_detector.complain(self.number, False)
            self._sync.sync()
            self.abort()
            return

        slot.proposal = proposal
        slot.requests = tuple(requests)
        slot.processed = True
        slot.begin = self._sched.now()
        if tracer.enabled:
            tracer.end(
                "view",
                "phase.pre_prepare",
                seq=seq,
                view=self.number,
                txs=len(requests),
            )
            tracer.begin("view", "phase.prepare", seq=seq, view=self.number)
        if i_am_leader:
            self._state.mark_proposed_verified(self.number, seq)
        else:
            self._state.save(
                ProposedRecord(pre_prepare=pp, prepare=prepare),
                on_durable=send_after_durable,
                truncate=False,
            )
        gate["verified"] = True
        maybe_send_prepare()
        self._update_inflight_depth()
        logger.info(
            "%d: pipelined seq %d in view %d (oldest %d)",
            self.self_id, seq, self.number, self.proposal_sequence,
        )

    def in_flight_depth(self) -> int:
        """Proposal slots currently moving through the 3-phase pipeline:
        the oldest slot (when past PROPOSED) plus every processed pipelined
        slot above it.  The same number the ``consensus_in_flight_depth``
        gauge reports; public so the observability sampler can read it
        without an in-memory metrics provider."""
        depth = 1 if self.phase in (Phase.PROPOSED, Phase.PREPARED) else 0
        return depth + sum(1 for slot in self._future.values() if slot.processed)

    def _update_inflight_depth(self) -> None:
        if self._consensus_metrics is None:
            return
        self._consensus_metrics.in_flight_depth.set(self.in_flight_depth())

    # ------------------------------------------------------ phase machine

    def _advance(self) -> None:
        """Re-run the phase logic until it stalls waiting for input.

        Parity: reference view.go:282-299 (doPhase), minus the blocking.
        """
        if self.stopped:
            return
        if self.phase == Phase.COMMITTED:
            self._try_process_proposal()
        if self.phase == Phase.PROPOSED:
            self._try_process_prepares()
        if self.phase == Phase.PREPARED:
            self._try_process_commits()

    # --- COMMITTED -> PROPOSED (view.go:351-427) ---------------------------

    def _try_process_proposal(self) -> None:
        if self._pending_pre_prepare is None:
            return
        _, pp = self._pending_pre_prepare
        self._pending_pre_prepare = None
        proposal = pp.proposal
        i_am_leader = self.self_id == self.leader_id
        tracer = self._tracer
        if (
            isinstance(pp.prev_commit_signatures, QuorumCert)
            and self._consensus_metrics is not None
        ):
            # Every replica WALs this pre-prepare exactly once (leader before
            # verification, follower after); account the cert's share here.
            self._consensus_metrics.wal_cert_bytes.add(
                encoded_cert_size(pp.prev_commit_signatures)
            )
        if tracer.enabled:
            tracer.begin(
                "view", "decision", seq=self.proposal_sequence, view=self.number
            )
            tracer.begin(
                "view",
                "phase.pre_prepare",
                seq=self.proposal_sequence,
                view=self.number,
            )

        prepare = Prepare(
            view=self.number, seq=self.proposal_sequence, digest=proposal.digest()
        )
        # The prepare may only go out once BOTH gates pass: the ProposedRecord
        # is durable (WAL-before-send, view.go:404-414) and the proposal is
        # verified.  All callbacks run on the replica's scheduler thread
        # (group-commit flushes are scheduler events), so the gates need no
        # lock; gate["prepare_sent"] is the sent-once guard (late flushes
        # may fire after _start_next_seq reset _curr_prepare_sent).
        gate = {"durable": False, "verified": False, "prepare_sent": False}

        def maybe_send_prepare() -> None:
            if not (gate["durable"] and gate["verified"]) or gate["prepare_sent"]:
                return
            gate["prepare_sent"] = True
            if self.stopped:
                # Aborted view: do NOT utter stale-view votes.  A late
                # flush firing after a view change would broadcast a
                # wrong-view message — and if this replica is the NEW
                # view's leader, peers treat wrong-view-from-leader as
                # leader sickness (handle_message) and abort the view they
                # just installed.
                return
            if self.proposal_sequence != prepare.seq:
                # LATE but durable AND verified (a group-commit flush that
                # landed after this view advanced a sequence): still reveal
                # it — skipping the send can wedge peers that are still
                # collecting this quorum (found by the multi-process
                # disk-group bench: a replica that decided via its peers'
                # votes before its own flush fired never uttered its vote,
                # and a laggard starved forever).  Safety is unchanged —
                # the endorsement is durably pinned and carries its own
                # (view, seq).  The CURRENT-sequence assist slot is
                # off-limits, but a flush exactly one sequence late may arm
                # the PREV-seq assist copy (empty precisely because the
                # send was deferred), so the retransmission machinery
                # covers loss of this one late broadcast.
                if (
                    self.proposal_sequence == prepare.seq + 1
                    and self._prev_prepare_sent is None
                ):
                    self._prev_prepare_sent = Prepare(
                        view=prepare.view, seq=prepare.seq,
                        digest=prepare.digest, assist=True,
                    )
                self._comm.broadcast(prepare)
                return
            # The assist copy is only armed here — retransmission help must
            # never reveal an un-persisted message either.
            self._curr_prepare_sent = Prepare(
                view=prepare.view, seq=prepare.seq, digest=prepare.digest, assist=True
            )
            self._comm.broadcast(prepare)

        def send_after_durable() -> None:
            # Under group commit this fires from the batched fsync event;
            # default mode fires inline during save().  Idempotent: a retried
            # flush must not re-reveal the pre-prepare (durability is a fact
            # once achieved — the flush layer fires each callback exactly
            # once, and the gate guards the rest).
            if gate["durable"]:
                return
            gate["durable"] = True
            if self.stopped:
                # Aborted view: reveal nothing (a stale-view pre-prepare
                # from a replica that leads the NEW view too would read as
                # leader sickness to its peers — see maybe_send_prepare).
                return
            if i_am_leader:
                # Reveal the proposal the moment it is durable — BEFORE our
                # own verification completes.  This departs from the
                # reference's ordering (view.go:421-423 echoes the
                # pre-prepare only after verifyProposal) deliberately: the
                # followers' proposal verification then overlaps the
                # leader's, and on the batch-verify engine all n replicas'
                # request sweeps coalesce into ONE device launch instead of
                # the leader's solo launch serializing before everyone
                # else's.  Safety is unaffected: a pre-prepare carries no
                # endorsement (prepares/commits do, and ours still waits for
                # verification), and the durable ProposedRecord already
                # pins us to this proposal at this (view, seq) across
                # crashes, so no equivocation window opens.
                self._comm.broadcast(pp)
            maybe_send_prepare()

        if i_am_leader:
            # verified=False: this record is written BEFORE our own
            # verification completes, and any restore from it must re-verify
            # (state.py::_enter_proposed) before re-arming the prepare.
            self._state.save(
                ProposedRecord(pre_prepare=pp, prepare=prepare, verified=False),
                on_durable=send_after_durable,
            )

        try:
            requests = self._verify_proposal(proposal, pp.prev_commit_signatures)
        except Exception as err:
            logger.warning(
                "%d: bad proposal from leader %d: %s", self.self_id, self.leader_id, err
            )
            if tracer.enabled:
                # Close the spans so rejected slots cannot corrupt nesting.
                tracer.instant(
                    "view",
                    "proposal.rejected",
                    seq=self.proposal_sequence,
                    view=self.number,
                )
                tracer.end(
                    "view",
                    "phase.pre_prepare",
                    seq=self.proposal_sequence,
                    view=self.number,
                )
                tracer.end(
                    "view", "decision", seq=self.proposal_sequence, view=self.number
                )
            self._failure_detector.complain(self.number, False)
            self._sync.sync()
            self.abort()
            return

        self.in_flight_proposal = proposal
        self.in_flight_requests = tuple(requests)
        self.metrics.count_txs_in_batch.set(len(requests))
        # Stamped post-verification on every replica, keeping
        # latency_batch_processing's definition (prepare/commit exchange
        # only) identical to the pre-reordering numbers in BASELINE.md.
        self._begin_pre_prepare = self._sched.now()
        self.phase = Phase.PROPOSED
        self.metrics.phase.set(int(self.phase))
        if tracer.enabled:
            tracer.end(
                "view",
                "phase.pre_prepare",
                seq=self.proposal_sequence,
                view=self.number,
                txs=len(requests),
            )
            tracer.begin(
                "view", "phase.prepare", seq=self.proposal_sequence, view=self.number
            )
        if i_am_leader:
            # Verification succeeded: flip the in-memory record so a mid-run
            # view restart (reseed_if_inflight_matches) does not pay a
            # redundant re-verify.  The on-disk record keeps verified=False —
            # a crash-restore re-verifies, which is the conservative side.
            self._state.mark_proposed_verified(self.number, prepare.seq)
        else:
            # Followers keep the reference's strict order: verify first,
            # then persist, then speak (view.go:351-427).
            self._state.save(
                ProposedRecord(pre_prepare=pp, prepare=prepare),
                on_durable=send_after_durable,
            )
        gate["verified"] = True
        maybe_send_prepare()
        self._update_inflight_depth()
        logger.info("%d: proposed seq %d in view %d", self.self_id, prepare.seq, self.number)

    # --- PROPOSED -> PREPARED (view.go:441-517) ----------------------------

    def _try_process_prepares(self) -> None:
        assert self.in_flight_proposal is not None
        if self.endorsement_blocked:
            return
        expected = self.in_flight_proposal.digest()
        voters = [s for s, p in self._prepares.items() if p.digest == expected]
        if len(voters) < self.quorum - 1:
            return

        if self._tracer.enabled:
            self._tracer.end(
                "view",
                "phase.prepare",
                seq=self.proposal_sequence,
                view=self.number,
                prepares=len(voters),
            )
            self._tracer.begin(
                "view", "phase.commit", seq=self.proposal_sequence, view=self.number
            )
        aux = encode_prepares_from(PreparesFrom(ids=tuple(sorted(voters))))
        self.my_commit_signature = self._signer.sign_proposal(
            self.in_flight_proposal, aux
        )
        commit = Commit(
            view=self.number,
            seq=self.proposal_sequence,
            digest=expected,
            signature=self.my_commit_signature,
        )

        def send_after_durable() -> None:
            if self._tracer.enabled:
                self._tracer.instant(
                    "view", "commit.durable", seq=commit.seq, view=commit.view
                )
            if self.stopped:
                return  # aborted view: never utter stale-view votes
            assist_copy = Commit(
                view=commit.view,
                seq=commit.seq,
                digest=commit.digest,
                signature=commit.signature,
                assist=True,
            )
            if self.proposal_sequence == commit.seq:
                self._curr_commit_sent = assist_copy
            elif (
                self.proposal_sequence == commit.seq + 1
                and self._prev_commit_sent is None
            ):
                # One sequence late: arm the prev-seq assist slot (empty
                # precisely because this send was deferred) so loss of the
                # single late broadcast is retransmittable.
                self._prev_commit_sent = assist_copy
            # Broadcast even when the flush landed late (same view, next
            # sequence): the commit is durable and peers still assembling
            # this quorum need it — a skipped send can starve a laggard
            # forever (the group-commit wedge; see maybe_send_prepare
            # above).
            self._comm.broadcast(commit)

        self.phase = Phase.PREPARED
        self.metrics.phase.set(int(self.phase))
        # WAL before send again: the commit we are about to utter.
        self._state.save(SavedCommit(commit=commit), on_durable=send_after_durable)
        logger.info("%d: prepared seq %d (%d prepares)", self.self_id, commit.seq, len(voters))

    # --- PREPARED -> decide (view.go:519-551, batched) ---------------------

    def _try_process_commits(self) -> None:
        assert self.in_flight_proposal is not None
        needed = self.quorum - 1
        if SENTINEL_MISWIRED_QUORUM and self.number > 0:
            needed = 1  # test-only mis-wiring; see the module-level sentinel
        if len(self._valid_commit_sigs) < needed:
            self._batch_verify_pending_commits(needed)
        if len(self._valid_commit_sigs) < needed:
            return

        signatures = list(self._valid_commit_sigs.values())[:needed]
        proposal = self.in_flight_proposal
        requests = self.in_flight_requests
        assert self.my_commit_signature is not None
        signatures.append(self.my_commit_signature)
        logger.info(
            "%d: collected %d commits for seq %d",
            self.self_id, len(signatures), self.proposal_sequence,
        )
        self.metrics.count_batch_all.add(1)
        self.metrics.count_txs_all.add(len(requests))
        size = len(proposal.payload) + len(proposal.header) + len(proposal.metadata)
        size += sum(len(s.value) + len(s.msg) for s in signatures)
        self.metrics.size_of_batch.add(size)
        self.metrics.latency_batch_processing.observe(
            self._sched.now() - self._begin_pre_prepare
        )
        if self._tracer.enabled:
            self._tracer.end(
                "view",
                "phase.commit",
                seq=self.proposal_sequence,
                view=self.number,
                commits=len(signatures),
            )
        decided_sigs = self._maybe_aggregate_cert(proposal, signatures)
        self._start_next_seq()
        self._decider.decide(proposal, decided_sigs, requests)

    def _maybe_aggregate_cert(self, proposal: Proposal, signatures: list[Signature]):
        """Half-aggregate the decided quorum into a compact ``QuorumCert``.

        Active only under ``cert_mode="half-agg"`` with an aggregation-capable
        verifier; otherwise the full signature list flows through untouched
        (bit-for-bit identical to the pre-cert behaviour).  Aggregation
        failure — a component signature the aggregator's self-check rejects,
        localized by bisection — degrades gracefully back to the full tuple:
        compactness is a perf optimisation, never a liveness dependency.

        On success the cert is persisted alongside the already-WAL'd commit
        (a second SavedCommit twin at the same (view, seq); recovery scans
        tolerate the duplicate and prefer the cert-bearing record), so a
        restarted leader can re-serve the compact cert without re-running
        aggregation over signatures it no longer holds.
        """
        if self.cert_mode != "half-agg":
            return signatures
        aggregate = getattr(self._verifier, "aggregate_cert", None)
        if aggregate is None or not getattr(
            self._verifier, "supports_cert_aggregation", False
        ):
            return signatures
        cm = self._consensus_metrics
        if self._tracer.enabled:
            self._tracer.begin(
                "view", "cert.aggregate", seq=self.proposal_sequence, view=self.number
            )
        cert = None
        try:
            cert = aggregate(proposal, tuple(signatures))
        finally:
            if self._tracer.enabled:
                self._tracer.end(
                    "view",
                    "cert.aggregate",
                    seq=self.proposal_sequence,
                    view=self.number,
                    aggregated=cert is not None,
                )
        if cert is None:
            logger.warning(
                "%d: cert aggregation fell back to full signatures at seq %d",
                self.self_id, self.proposal_sequence,
            )
            if cm is not None:
                cm.cert_fallback_bisections.add(1)
            return signatures
        if cm is not None:
            nbytes = encoded_cert_size(cert)
            cm.cert_aggregate_launches.add(1)
            cm.cert_bytes_per_cert.observe(nbytes)
            cm.wal_cert_bytes.add(nbytes)
        if self._curr_commit_sent is not None:
            self._state.save(
                SavedCommit(
                    commit=dataclasses.replace(self._curr_commit_sent, assist=False),
                    cert=cert,
                )
            )
        return cert

    def _batch_verify_pending_commits(self, needed: int) -> None:
        """Verify buffered commit votes in one batch call (the TPU seam).

        Waits until enough unverified votes are pending to possibly reach
        quorum, then verifies them all at once — one kernel launch per
        decision in the common case, versus the reference's
        goroutine-per-vote (view.go:537-541)."""
        assert self.in_flight_proposal is not None
        expected = self.in_flight_proposal.digest()
        pending: list[Commit] = []
        for sender, commit in self._commits.items():
            if sender in self._valid_commit_sigs or sender in self._rejected_commit_senders:
                continue
            if commit.digest != expected:
                continue
            pending.append(commit)
        if len(self._valid_commit_sigs) + len(pending) < needed:
            return  # not enough to possibly decide; keep buffering

        sigs = [c.signature for c in pending]
        results = self._verify_commits_coalesced(sigs, pending)
        for commit, result in zip(pending, results):
            if result is None:
                logger.warning(
                    "%d: invalid commit signature from %d",
                    self.self_id, commit.signature.id,
                )
                self._rejected_commit_senders.add(commit.signature.id)
            else:
                self._valid_commit_sigs[commit.signature.id] = commit.signature

    def _verify_commits_coalesced(
        self, sigs: list[Signature], pending: list[Commit]
    ) -> Sequence[Optional[bytes]]:
        """One verification launch for the oldest slot's pending commits —
        and, when pipelined, for every future slot's buffered commits too.
        Peers that decided ahead of us send their commit for seq n+k the
        moment it is THEIR oldest, so under a saturated window the votes a
        promoted slot will need are already verified by the time it signs:
        launches-per-decision drops below one.  Results for future slots are
        cached on the slot (valid_commit_sigs / rejected)."""
        cm = self._consensus_metrics
        future_groups: list[tuple[_FutureSlot, list[Commit]]] = []
        if self.effective_depth > 1:
            for s in sorted(self._future):
                slot = self._future[s]
                if not slot.processed or slot.proposal is None:
                    continue
                want = slot.proposal.digest()
                extra = [
                    c
                    for sender, c in slot.commits.items()
                    if sender not in slot.valid_commit_sigs
                    and sender not in slot.rejected
                    and c.digest == want
                ]
                if extra:
                    future_groups.append((slot, extra))

        multi = getattr(self._verifier, "verify_consenter_sigs_multi_batch", None)
        if not future_groups or multi is None:
            self.metrics.count_batch_sig_verifications.add(len(sigs))
            if cm is not None:
                cm.count_verify_launches.add(1)
                cm.cross_slot_verify_batch.observe(len(sigs))
            if self._tracer.enabled:
                # Same value the cross_slot_verify_batch histogram observes:
                # the trace and metrics views of launch batching must agree.
                self._tracer.instant("view", "verify.launch", size=len(sigs))
            return self._verifier.verify_consenter_sigs_batch(
                sigs, self.in_flight_proposal
            )

        groups = [(self.in_flight_proposal, sigs)]
        groups.extend(
            (slot.proposal, [c.signature for c in extra])
            for slot, extra in future_groups
        )
        total = sum(len(g[1]) for g in groups)
        self.metrics.count_batch_sig_verifications.add(total)
        if cm is not None:
            cm.count_verify_launches.add(1)
            cm.cross_slot_verify_batch.observe(total)
        if self._tracer.enabled:
            self._tracer.instant(
                "view", "verify.launch", size=total, slots=len(groups)
            )
        all_results = multi(groups)
        for (slot, extra), slot_results in zip(future_groups, all_results[1:]):
            for commit, result in zip(extra, slot_results):
                if result is None:
                    slot.rejected.add(commit.signature.id)
                else:
                    slot.valid_commit_sigs[commit.signature.id] = commit.signature
        return all_results[0]

    # --- sequence pipelining (view.go:851-894) -----------------------------

    def _start_next_seq(self) -> None:
        self.proposal_sequence += 1
        self.decisions_in_view += 1
        self.metrics.proposal_sequence.set(self.proposal_sequence)
        self.metrics.decisions_in_view.set(self.decisions_in_view)
        self.phase = Phase.COMMITTED
        self.metrics.phase.set(int(self.phase))
        self.in_flight_proposal = None
        self.in_flight_requests = ()
        self.my_commit_signature = None

        self._prev_prepare_sent = self._curr_prepare_sent
        self._prev_commit_sent = self._curr_commit_sent
        self._curr_prepare_sent = None
        self._curr_commit_sent = None

        self._pending_pre_prepare = self._next_pre_prepare
        self._next_pre_prepare = None
        self._prepares = self._next_prepares
        self._next_prepares = {}
        self._commits = self._next_commits
        self._next_commits = {}
        self._valid_commit_sigs = {}
        self._rejected_commit_senders = set()

        kick = False
        if self.effective_depth > 1:
            kick = self._promote_future_slot()

        # Continue with any buffered next-sequence traffic on a fresh stack.
        if (
            kick
            or self._pending_pre_prepare is not None
            or self._prepares
            or self._commits
        ):
            self._sched.post(self._advance, name=f"view-{self.number}-advance")

    def _promote_future_slot(self) -> bool:
        """Fold the future slot at the (just advanced) oldest sequence into
        the legacy current-slot fields.  This is the in-order commit gate:
        only here — strictly after every lower sequence decided, and on the
        scheduler event AFTER the prior decision was delivered — does a
        pipelined slot become eligible to sign and persist a Commit.
        Returns whether _advance should be (re)posted."""
        slot = self._future.pop(self.proposal_sequence, None)
        kick = False
        if slot is not None:
            if slot.processed:
                # Pre-prepare/prepare already ran in the future slot: seed
                # the current-slot state directly and let _advance drive
                # PROPOSED -> PREPARED -> decide on the collected votes.
                self.in_flight_proposal = slot.proposal
                self.in_flight_requests = slot.requests
                self.metrics.count_txs_in_batch.set(len(slot.requests))
                self._begin_pre_prepare = slot.begin or self._sched.now()
                self.phase = Phase.PROPOSED
                self.metrics.phase.set(int(self.phase))
                self._curr_prepare_sent = slot.prepare_sent
                self._valid_commit_sigs = slot.valid_commit_sigs
                self._rejected_commit_senders = slot.rejected
                kick = True
            elif slot.pre_prepare is not None:
                self._pending_pre_prepare = slot.pre_prepare
            self._prepares = slot.prepares
            self._commits = slot.commits
        # The window slid: the previously buffer-only edge slot may now be
        # inside processing range with a parked pre-prepare.
        edge = self.proposal_sequence + self.effective_depth - 1
        edge_slot = self._future.get(edge)
        if (
            edge_slot is not None
            and edge_slot.pre_prepare is not None
            and not edge_slot.processed
        ):
            self._process_future_slot(edge, edge_slot)
        self._update_inflight_depth()
        return kick

    # --- verification (view.go:553-716) ------------------------------------

    def _verify_proposal(
        self,
        proposal: Proposal,
        prev_commits: Sequence[Signature],
        *,
        expected_seq: Optional[int] = None,
        expected_decisions: Optional[int] = None,
    ) -> Sequence[RequestInfo]:
        """Verify a proposal against this view.  ``expected_seq`` /
        ``expected_decisions`` default to the oldest slot's position; future
        slots pass their own (the decisions offset is seq-relative: both
        counters advance together on every decide)."""
        if expected_seq is None:
            expected_seq = self.proposal_sequence
        if expected_decisions is None:
            expected_decisions = self.decisions_in_view
        # The pre-prepare carries two signature waves: the proposal's
        # request signatures and the previous decision's commit-signature
        # quorum.  The previous cert only applies when no reconfiguration
        # happened in between (reference view.go:606-647 skips otherwise);
        # routing both waves through one port call lets verifiers that
        # share an engine fuse them into a single launch.  A request
        # failure still raises here, before any cert result is consumed.
        prev_proposal, _ = self._checkpoint.get()
        expected_vseq = self._verifier.verification_sequence()
        certs_apply = bool(prev_commits) and (
            prev_proposal.verification_sequence == expected_vseq
        )
        requests, cert_results = self._verifier.verify_proposal_and_prev_commits(
            proposal, prev_commits if certs_apply else (), prev_proposal
        )
        if certs_apply and isinstance(prev_commits, QuorumCert):
            # Follower-side accounting of the leader's compact cert: one
            # aggregate-verify launch, and the cert's wire footprint.
            cm = self._consensus_metrics
            if cm is not None:
                cm.cert_aggregate_launches.add(1)
                cm.cert_bytes_per_cert.observe(encoded_cert_size(prev_commits))

        md = decode_view_metadata(proposal.metadata)
        if md.view_id != self.number:
            raise ValueError(f"metadata view {md.view_id} != {self.number}")
        if md.latest_sequence != expected_seq:
            raise ValueError(
                f"metadata seq {md.latest_sequence} != {expected_seq}"
            )
        if md.decisions_in_view != expected_decisions:
            raise ValueError(
                f"metadata decisions-in-view {md.decisions_in_view} != {expected_decisions}"
            )
        if proposal.verification_sequence != expected_vseq:
            raise ValueError(
                f"verification sequence {proposal.verification_sequence} != {expected_vseq}"
            )

        prepare_acks = (
            self._decode_prev_commit_acks(prev_commits, cert_results)
            if certs_apply
            else {}
        )
        self._verify_blacklist(prev_commits, expected_vseq, md, prepare_acks)

        # The metadata must commit to the exact previous-signature set.
        if self.decisions_per_leader > 0:
            if commit_signatures_digest(prev_commits) != md.prev_commit_signature_digest:
                raise ValueError("prev commit signatures mismatch metadata digest")
        return requests

    def _verify_prev_commit_signatures(
        self, prev_commits: Sequence[Signature], curr_vseq: int
    ) -> dict[int, PreparesFrom]:
        """Verify the leader-carried previous-decision signatures *as a
        batch* and decode each one's prepare-acknowledgement vouch list.

        Parity: reference view.go:606-647 (sequential loop there)."""
        prev_proposal, _ = self._checkpoint.get()
        if prev_proposal.verification_sequence != curr_vseq:
            # Reconfiguration happened in between: signatures were made under
            # another config — skip (the reference does the same).
            return {}
        if not prev_commits:
            return {}
        results = self._verifier.verify_consenter_sigs_batch(
            prev_commits, prev_proposal
        )
        return self._decode_prev_commit_acks(prev_commits, results)

    @staticmethod
    def _decode_prev_commit_acks(
        prev_commits: Sequence[Signature], results: Sequence[Optional[bytes]]
    ) -> dict[int, PreparesFrom]:
        """Turn a cert wave's verdicts into the per-signer prepare-ack map,
        raising on the first invalid signature or malformed vouch payload."""
        acks: dict[int, PreparesFrom] = {}
        for sig, aux in zip(prev_commits, results):
            if aux is None:
                raise ValueError(f"invalid prev commit signature from {sig.id}")
            try:
                acks[sig.id] = decode_prepares_from(aux) if aux else PreparesFrom()
            except Exception as e:
                raise ValueError(f"bad prepare-ack payload from {sig.id}: {e}") from e
        return acks

    def _verify_blacklist(
        self,
        prev_commits: Sequence[Signature],
        curr_vseq: int,
        md: ViewMetadata,
        prepare_acks: dict[int, PreparesFrom],
    ) -> None:
        """Follower-side re-derivation of the leader's blacklist update.

        Parity: reference view.go:649-716."""
        if self.decisions_per_leader == 0:
            if md.black_list:
                raise ValueError(
                    f"rotation inactive but blacklist is {list(md.black_list)}"
                )
            return

        prev_proposal, my_last_sigs = self._checkpoint.get()
        prev_md = self._decode_prev_metadata(prev_proposal)

        if prev_proposal.verification_sequence != curr_vseq:
            if tuple(prev_md.black_list) != tuple(md.black_list):
                raise ValueError("blacklist changed during reconfiguration")
            return
        if self._membership_notifier is not None and self._membership_notifier.membership_change():
            if tuple(prev_md.black_list) != tuple(md.black_list):
                raise ValueError("blacklist changed during membership change")
            return

        if self._blacklisting_supported(my_last_sigs) and len(prev_commits) < len(
            my_last_sigs
        ):
            raise ValueError(
                f"only {len(prev_commits)} of {len(my_last_sigs)} previous commits included"
            )

        expected = compute_blacklist_update(
            prev_view=prev_md.view_id,
            prev_seq=prev_md.latest_sequence,
            prev_decisions_in_view=prev_md.decisions_in_view,
            prev_blacklist=list(prev_md.black_list),
            current_view=self.number,
            current_leader=self.leader_id,
            n=self.n,
            f=self.f,
            nodes=self.nodes,
            leader_rotation=self.decisions_per_leader > 0,
            decisions_per_leader=self.decisions_per_leader,
            prepares_from={i: list(pf.ids) for i, pf in prepare_acks.items()},
        )
        if tuple(md.black_list) != tuple(expected):
            raise ValueError(
                f"proposed blacklist {list(md.black_list)} != expected {expected}"
            )

    def _decode_prev_metadata(self, prev_proposal: Proposal) -> ViewMetadata:
        if not prev_proposal.metadata:
            return ViewMetadata()
        return decode_view_metadata(prev_proposal.metadata)

    def _blacklisting_supported(self, my_last_sigs: Sequence[Signature]) -> bool:
        """f+1 of the previous commit signatures carrying auxiliary data is
        the rolling-upgrade witness that blacklisting is active.

        Parity: reference view.go:1061-1085."""
        if self._blacklist_supported:
            return True
        count = sum(
            1 for sig in my_last_sigs if self._verifier.auxiliary_data(sig.msg)
        )
        if count > self.f:
            self._blacklist_supported = True
        return self._blacklist_supported

    # --- leader metadata (view.go:896-989) ---------------------------------

    def get_metadata(self) -> bytes:
        """The ViewMetadata the leader stamps into its next proposal: current
        position, updated blacklist, and the binding digest over the previous
        commit signatures."""
        prev_proposal, prev_sigs = self._checkpoint.get()
        prev_md = self._decode_prev_metadata(prev_proposal)
        # Rotation off clears any inherited blacklist (a downgraded cluster
        # may still carry entries from its rotation era; followers reject
        # rotation-inactive proposals with a non-empty blacklist).
        # Parity: reference view.go:1019-1023.
        black_list = tuple(prev_md.black_list) if self.decisions_per_leader > 0 else ()

        vseq = self._verifier.verification_sequence()
        membership_change = (
            self._membership_notifier is not None
            and self._membership_notifier.membership_change()
        )
        if (
            prev_proposal.verification_sequence == vseq
            and not membership_change
            and self.decisions_per_leader > 0
        ):
            acks: dict[int, list[int]] = {}
            for sig in prev_sigs:
                aux = self._verifier.auxiliary_data(sig.msg)
                if aux:
                    try:
                        acks[sig.id] = list(decode_prepares_from(aux).ids)
                    except Exception:
                        logger.warning("undecodable prepare-acks from %d", sig.id)
            black_list = tuple(
                compute_blacklist_update(
                    prev_view=prev_md.view_id,
                    prev_seq=prev_md.latest_sequence,
                    prev_decisions_in_view=prev_md.decisions_in_view,
                    prev_blacklist=list(prev_md.black_list),
                    current_view=self.number,
                    current_leader=self.leader_id,
                    n=self.n,
                    f=self.f,
                    nodes=self.nodes,
                    leader_rotation=True,
                    decisions_per_leader=self.decisions_per_leader,
                    prepares_from=acks,
                )
            )

        prev_digest = (
            commit_signatures_digest(prev_sigs)
            if self.decisions_per_leader > 0
            else b""
        )
        if self.effective_depth > 1:
            # Pipelined: stamp the slot this proposal will actually occupy.
            # The decisions offset is seq-relative (both counters advance
            # together on every decide), so followers verifying the future
            # slot recompute the same number.
            target = self.next_propose_seq
            md = ViewMetadata(
                view_id=self.number,
                latest_sequence=target,
                decisions_in_view=self.decisions_in_view
                + (target - self.proposal_sequence),
                black_list=black_list,
                prev_commit_signature_digest=prev_digest,
            )
            return encode_view_metadata(md)
        md = ViewMetadata(
            view_id=self.number,
            latest_sequence=self.proposal_sequence,
            decisions_in_view=self.decisions_in_view,
            black_list=black_list,
            prev_commit_signature_digest=prev_digest,
        )
        return encode_view_metadata(md)

    # --- stragglers + censorship (view.go:718-818) --------------------------

    def _handle_prev_seq_message(self, sender: int, msg: ConsensusMessage) -> None:
        if isinstance(msg, PrePrepare):
            return
        if isinstance(msg, Prepare):
            if msg.assist:
                return
            if self._prev_prepare_sent is not None:
                self._comm.send(sender, self._prev_prepare_sent)
        elif isinstance(msg, Commit):
            if msg.assist:
                return
            if self._prev_commit_sent is not None:
                self._comm.send(sender, self._prev_commit_sent)

    def _discover_if_sync_needed(self, sender: int, msg: ConsensusMessage) -> None:
        """f+1 distinct nodes voting to commit a (view, seq) ahead of ours
        means we missed a proposal — trigger a sync."""
        if not isinstance(msg, Commit):
            return
        self._last_voted_proposal_by_id[sender] = msg
        threshold = self.f + 1
        if len(self._last_voted_proposal_by_id) < threshold:
            return
        counts: dict[tuple[str, int, int], int] = {}
        for vote in self._last_voted_proposal_by_id.values():
            key = (vote.digest, vote.view, vote.seq)
            counts[key] = counts.get(key, 0) + 1
        for (digest, view, seq), count in counts.items():
            if count < threshold:
                continue
            if view < self.number:
                continue
            if seq <= self.proposal_sequence and view == self.number:
                continue
            logger.warning(
                "%d: %d votes for (view=%d, seq=%d) vs our (view=%d, seq=%d) — syncing",
                self.self_id, count, view, seq, self.number, self.proposal_sequence,
            )
            self.abort()
            self._sync.sync()
            return


__all__ = [
    "View",
    "Phase",
    "Decider",
    "FailureDetector",
    "SyncRequester",
    "ViewComm",
    "ViewState",
    "CheckpointReader",
]
