"""Request pool: FIFO admission, dedup, back-pressure, and the three-stage
timeout cascade that drives failure detection.

Parity: reference internal/bft/requestpool.go:52-567.  Differences by design:

* **Event-driven back-pressure** — the reference blocks the submitting
  goroutine on a weighted semaphore with ``SubmitTimeout``
  (requestpool.go:191-284); here a full pool *parks* the submission and
  completes its callback when space frees or the timeout fires.  Nothing
  blocks the replica loop.
* **No background GC goroutine** — the reference garbage-collects its
  recently-deleted dedup map every 5 s on a goroutine (requestpool.go:128-141);
  here the retention window is enforced opportunistically on mutation, which
  keeps simulations quiescence-detectable (no perpetual timer).

The cascade (requestpool.go:493-567): after ``request_forward_timeout`` the
request is forwarded to the leader (stage 1); after a further
``request_complain_timeout`` the replica complains, triggering a view change
(stage 2); after ``request_auto_remove_timeout`` more the request is dropped
(stage 3).  ``stop_timers`` / ``restart_timers`` flip the whole pool around
view changes (requestpool.go:456-490).
"""

from __future__ import annotations

import logging
from collections import OrderedDict, deque
from dataclasses import dataclass
from typing import Callable, Iterable, Optional, Protocol, Sequence

from consensus_tpu.api.deps import RequestInspector
from consensus_tpu.metrics import MetricsRequestPool, NoopProvider
from consensus_tpu.runtime.scheduler import Scheduler, TimerHandle
from consensus_tpu.trace.tracer import NOOP_TRACER
from consensus_tpu.types import RequestInfo

logger = logging.getLogger("consensus_tpu.pool")

#: How long a deleted request's identity is remembered for dedup purposes.
DELETED_RETENTION_SECONDS = 5.0


class RequestTimeoutHandler(Protocol):
    """Callbacks for the cascade stages (implemented by the Controller).

    Parity: reference internal/bft/requestpool.go:30-44.
    """

    def on_request_timeout(self, raw_request: bytes, info: RequestInfo) -> None:
        """Stage 1: forward the request to the current leader."""

    def on_leader_fwd_request_timeout(self, raw_request: bytes, info: RequestInfo) -> None:
        """Stage 2: the leader ignored the forwarded request — complain."""

    def on_auto_remove_timeout(self, info: RequestInfo) -> None:
        """Stage 3: the request outlived all patience — it was dropped."""


@dataclass
class PoolOptions:
    """Pool tuning (split out of Configuration for standalone use)."""

    pool_size: int = 400
    request_max_bytes: int = 10 * 1024
    submit_timeout: float = 5.0
    forward_timeout: float = 2.0
    complain_timeout: float = 20.0
    auto_remove_timeout: float = 180.0


class _Entry:
    __slots__ = ("raw", "info", "arrived_at", "timer", "stage")

    def __init__(self, raw: bytes, info: RequestInfo, arrived_at: float):
        self.raw = raw
        self.info = info
        self.arrived_at = arrived_at
        self.timer: Optional[TimerHandle] = None
        self.stage = 0  # 0=armed-forward, 1=armed-complain, 2=armed-remove


class _Parked:
    __slots__ = ("raw", "info", "on_done", "timer")

    def __init__(self, raw: bytes, info: RequestInfo, on_done, timer):
        self.raw = raw
        self.info = info
        self.on_done = on_done
        self.timer = timer


class RequestPool:
    """FIFO of pending client requests keyed by :class:`RequestInfo`."""

    def __init__(
        self,
        scheduler: Scheduler,
        inspector: RequestInspector,
        options: PoolOptions,
        *,
        timeout_handler: Optional[RequestTimeoutHandler] = None,
        on_submitted: Optional[Callable[[], None]] = None,
        metrics: Optional[MetricsRequestPool] = None,
        tracer=None,
    ) -> None:
        self._sched = scheduler
        self._inspector = inspector
        self._opts = options
        self._handler = timeout_handler
        #: Notified after every successful admission (the batcher listens).
        self._on_submitted = on_submitted
        # Insertion-ordered map == FIFO + O(1) lookup (the reference keeps a
        # list.List plus a separate existMap; one OrderedDict does both).
        self._fifo: "OrderedDict[str, _Entry]" = OrderedDict()
        self._bytes = 0
        self._parked: deque[_Parked] = deque()
        # Recently-deleted identities -> deletion time (dedup of stragglers).
        self._deleted: "OrderedDict[str, float]" = OrderedDict()
        # Identities the leader has batched into a still-in-flight pipelined
        # proposal: hidden from next_requests until decided (removed) or the
        # view aborts (released).  Always empty at pipeline_depth=1.
        self._reserved: set[str] = set()
        self._timers_stopped = False
        self._closed = False
        self._metrics = metrics or MetricsRequestPool(NoopProvider())
        self._tracer = tracer if tracer is not None else NOOP_TRACER

    # --- admission ---------------------------------------------------------

    def submit(
        self, raw_request: bytes, on_done: Optional[Callable[[Optional[str]], None]] = None
    ) -> None:
        """Admit a request; ``on_done(error)`` fires with ``None`` on success
        or a reason string on rejection/timeout.

        Parity: reference requestpool.go:191-284 (Submit).
        """

        def done(err: Optional[str]) -> None:
            if err is not None:
                self._metrics.count_of_fail_add_request.add(1)
            if on_done is not None:
                on_done(err)

        if self._closed:
            done("pool closed")
            return
        if len(raw_request) > self._opts.request_max_bytes:
            done(
                f"request size {len(raw_request)} exceeds max {self._opts.request_max_bytes}"
            )
            return
        try:
            info = self._inspector.request_id(raw_request)
        except Exception as e:  # inspector is app code
            done(f"request rejected by inspector: {e}")
            return
        self._gc_deleted()
        key = info.key()
        if key in self._fifo or key in self._deleted:
            done("request already exists")
            return
        if len(self._fifo) < self._opts.pool_size:
            self._admit(raw_request, info)
            done(None)
            return
        # Pool full: park until space frees or the submit timeout expires.
        parked = _Parked(raw_request, info, done, None)
        parked.timer = self._sched.call_later(
            self._opts.submit_timeout,
            lambda: self._park_expired(parked),
            name=f"submit-timeout {info}",
        )
        self._parked.append(parked)

    def _park_expired(self, parked: _Parked) -> None:
        try:
            self._parked.remove(parked)
        except ValueError:
            return  # already admitted
        parked.on_done("submit timed out: pool is full")

    def _admit(self, raw: bytes, info: RequestInfo) -> None:
        entry = _Entry(raw, info, self._sched.now())
        if self._tracer.enabled:
            self._tracer.instant("pool", "pool.admit")
        self._fifo[info.key()] = entry
        self._bytes += len(raw)
        self._metrics.count_of_elements.set(len(self._fifo))
        self._metrics.count_of_elements_all.add(1)
        if not self._timers_stopped:
            self._arm_stage(entry, 0)
        if self._on_submitted is not None:
            self._on_submitted()

    def _drain_parked(self) -> None:
        while self._parked and len(self._fifo) < self._opts.pool_size:
            parked = self._parked.popleft()
            if parked.timer is not None:
                parked.timer.cancel()
            key = parked.info.key()
            if key in self._fifo or key in self._deleted:
                parked.on_done("request already exists")
                continue
            self._admit(parked.raw, parked.info)
            parked.on_done(None)

    # --- timeout cascade ---------------------------------------------------

    def _arm_stage(self, entry: _Entry, stage: int) -> None:
        entry.stage = stage
        delays = (
            self._opts.forward_timeout,
            self._opts.complain_timeout,
            self._opts.auto_remove_timeout,
        )
        entry.timer = self._sched.call_later(
            delays[stage],
            lambda: self._stage_fired(entry),
            name=f"request-stage{stage} {entry.info}",
        )

    def _stage_fired(self, entry: _Entry) -> None:
        if self._timers_stopped or entry.info.key() not in self._fifo:
            return
        if entry.stage == 0:
            logger.debug("request %s forward timeout", entry.info)
            self._metrics.count_leader_forward_request.add(1)
            if self._handler is not None:
                self._handler.on_request_timeout(entry.raw, entry.info)
            self._arm_stage(entry, 1)
        elif entry.stage == 1:
            self._metrics.count_timeout_two_step.add(1)
            logger.warning("request %s leader-forward timeout: complaining", entry.info)
            if self._handler is not None:
                self._handler.on_leader_fwd_request_timeout(entry.raw, entry.info)
            self._arm_stage(entry, 2)
        else:
            logger.warning("request %s auto-removed", entry.info)
            self._delete(entry.info.key())
            if self._handler is not None:
                self._handler.on_auto_remove_timeout(entry.info)

    def stop_timers(self) -> None:
        """Freeze the cascade (view change in progress).

        Parity: reference requestpool.go:456-469.
        """
        self._timers_stopped = True
        for entry in self._fifo.values():
            if entry.timer is not None:
                entry.timer.cancel()
                entry.timer = None

    def restart_timers(self) -> None:
        """Re-arm every request at stage 1 of the cascade.

        Parity: reference requestpool.go:471-490.
        """
        self._timers_stopped = False
        for entry in self._fifo.values():
            if entry.timer is not None:
                entry.timer.cancel()
            self._arm_stage(entry, 0)

    # --- consumption -------------------------------------------------------

    def next_requests(self, max_count: int, max_size_bytes: int) -> list[bytes]:
        """A prefix batch of raw requests within the count/byte budget.

        Parity: reference requestpool.go:297-332.
        """
        out: list[bytes] = []
        total = 0
        for key, entry in self._fifo.items():
            if key in self._reserved:
                continue  # already riding an in-flight pipelined slot
            if len(out) >= max_count:
                break
            if out and total + len(entry.raw) > max_size_bytes:
                break
            out.append(entry.raw)
            total += len(entry.raw)
        return out

    def reserve_raws(self, raw_requests: Iterable[bytes]) -> None:
        """Hide pooled requests from subsequent :meth:`next_requests` while
        they ride an in-flight pipelined proposal.  Without this a depth>1
        leader would re-batch the pool front into the next slot (removal
        only happens at delivery) and decide every request twice."""
        if self._tracer.enabled:
            raw_requests = list(raw_requests)
            self._tracer.instant("pool", "pool.reserve", count=len(raw_requests))
        for raw in raw_requests:
            try:
                key = self._inspector.request_id(raw).key()
            except Exception:
                continue  # unidentifiable requests were never pooled
            if key in self._fifo:
                self._reserved.add(key)

    def release_reservations(self) -> None:
        """Forget all reservations (view abort/sync): slots that will never
        decide must hand their requests back to the batcher."""
        self._reserved.clear()

    def remove_request(self, info: RequestInfo) -> bool:
        """Remove a delivered/invalid request.  Returns whether it was here.

        Parity: reference requestpool.go:357-401.
        """
        return self._delete(info.key())

    def remove_requests(self, infos: Iterable[RequestInfo]) -> int:
        """Bulk removal for a delivered batch: one parked-queue drain and
        dedup GC for the whole batch instead of per request (the per-decision
        hot path removes ``request_batch_max_count`` at once)."""
        removed = 0
        now = self._sched.now()
        for info in infos:
            key = info.key()
            if self._delete_entry(key):
                removed += 1
            else:
                # Delivered but not pooled here (e.g. still parked): mark it
                # recently-deleted anyway so the trailing drain cannot
                # re-admit a copy of an already-committed request.  Pop
                # first: a refresh must move to the end, or the GC's
                # stop-at-first-fresh scan retains expired entries behind it.
                self._deleted.pop(key, None)
                self._deleted[key] = now
        self._gc_deleted()
        self._drain_parked()
        return removed

    def _delete(self, key: str) -> bool:
        present = self._delete_entry(key)
        if not present:
            # Same delivered-while-parked guard as the bulk path (pop first
            # to keep the OrderedDict in timestamp order for the GC).
            self._deleted.pop(key, None)
            self._deleted[key] = self._sched.now()
        self._gc_deleted()
        self._drain_parked()
        return present

    def _delete_entry(self, key: str) -> bool:
        self._reserved.discard(key)
        entry = self._fifo.pop(key, None)
        if entry is None:
            return False
        if entry.timer is not None:
            entry.timer.cancel()
        self._bytes -= len(entry.raw)
        self._metrics.count_of_delete_request.add(1)
        self._metrics.count_of_elements.set(len(self._fifo))
        self._metrics.latency_of_elements.observe(self._sched.now() - entry.arrived_at)
        self._deleted[key] = self._sched.now()
        return True

    def _gc_deleted(self) -> None:
        horizon = self._sched.now() - DELETED_RETENTION_SECONDS
        while self._deleted:
            key, when = next(iter(self._deleted.items()))
            if when >= horizon:
                break
            del self._deleted[key]

    def prune(self, keep: Callable[[bytes], bool]) -> None:
        """Per-request :meth:`prune_batch`.  Parity: reference
        requestpool.go:335-354."""
        self.prune_batch(lambda raws: [keep(r) for r in raws])

    def prune_batch(self, keep_batch: Callable[[list], "list[bool]"]) -> None:
        """Like :meth:`prune` but validates the whole pool in ONE call —
        the controller drains the re-validation burst into the batch
        verifier instead of the reference's per-request loop (the sig-heavy
        burst of reference controller.go:733-746)."""
        entries = list(self._fifo.values())
        if not entries:
            return
        mask = keep_batch([e.raw for e in entries])
        doomed = [
            e.info for e, ok in zip(entries, mask, strict=True) if not ok
        ]
        for info in doomed:
            logger.info("pruning request %s (failed re-validation)", info)
        self.remove_requests(doomed)

    def change_options(
        self,
        timeout_handler: Optional[RequestTimeoutHandler] = None,
        options: Optional[PoolOptions] = None,
    ) -> None:
        """Re-point the pool at a new handler/config across reconfiguration,
        keeping every queued request.

        Parity: reference requestpool.go ChangeOptions (used by
        pkg/consensus/consensus.go:231)."""
        if timeout_handler is not None:
            self._handler = timeout_handler
        if options is not None:
            self._opts = options
        self._closed = False

    def close(self) -> None:
        self._closed = True
        self.stop_timers()
        while self._parked:
            parked = self._parked.popleft()
            if parked.timer is not None:
                parked.timer.cancel()
            parked.on_done("pool closed")

    # --- introspection -----------------------------------------------------

    @property
    def count(self) -> int:
        return len(self._fifo)

    @property
    def available_count(self) -> int:
        """Pooled requests NOT riding an in-flight pipelined slot — what
        :meth:`next_requests` can actually hand out.  Equals :attr:`count`
        at pipeline_depth=1 (reservations never happen there)."""
        return len(self._fifo) - len(self._reserved)

    @property
    def size_bytes(self) -> int:
        return self._bytes


__all__ = [
    "RequestPool",
    "PoolOptions",
    "RequestTimeoutHandler",
    "DELETED_RETENTION_SECONDS",
]
