"""Trace exporters: Chrome/Perfetto trace-event JSON and JSONL.

Both formats are byte-deterministic for a given event stream: dict keys
are sorted, separators are fixed, and timestamps come from the scheduler
clock — two identically seeded ``SimScheduler`` runs export identical
bytes.

Chrome mapping (load in ``ui.perfetto.dev`` or ``chrome://tracing``):

- Per-decision spans (``seq`` set) become *async nestable* events
  (``ph="b"``/``"e"``) with ``id=seq`` and ``cat=track``, so overlapping
  decisions under pipelining render as separate nested tracks instead of
  corrupting one thread's begin/end stack.
- Spans without a ``seq`` (e.g. sync chunk fetches) become thread-scoped
  duration events (``ph="B"``/``"E"``).
- Instants map to ``ph="i"`` with thread scope.
- Each tracer ``track`` gets its own tid plus a ``thread_name`` metadata
  record; ``pid`` is the node id.
"""

from __future__ import annotations

import json
from typing import Iterable

_ASYNC_PH = {"B": "b", "E": "e"}


def chrome_trace_events(events: Iterable[tuple], *, pid: int = 0) -> list:
    """Convert tracer event tuples to Chrome trace-event dicts."""
    events = list(events)
    tracks = sorted({ev[1] for ev in events})
    tid_of = {track: i + 1 for i, track in enumerate(tracks)}
    out = [
        {
            "ph": "M",
            "name": "thread_name",
            "pid": pid,
            "tid": tid_of[track],
            "args": {"name": track},
        }
        for track in tracks
    ]
    for ph, track, name, ts, seq, view, args in events:
        ev = {
            "name": name,
            "cat": track,
            "pid": pid,
            "tid": tid_of[track],
            # Chrome wants microseconds; round so float noise can't leak
            # into the export bytes.
            "ts": round(ts * 1e6, 3),
        }
        merged = dict(args) if args else {}
        if seq is not None:
            merged["seq"] = seq
        if view is not None:
            merged["view"] = view
        if merged:
            ev["args"] = merged
        if ph == "i":
            ev["ph"] = "i"
            ev["s"] = "t"
        elif seq is not None:
            ev["ph"] = _ASYNC_PH[ph]
            ev["id"] = seq
        else:
            ev["ph"] = ph
        out.append(ev)
    return out


def to_chrome_json(events: Iterable[tuple], *, pid: int = 0) -> str:
    doc = {
        "displayTimeUnit": "ms",
        "traceEvents": chrome_trace_events(events, pid=pid),
    }
    return json.dumps(doc, sort_keys=True, separators=(",", ":"))


def write_chrome_trace(path, events: Iterable[tuple], *, pid: int = 0) -> None:
    with open(path, "w", encoding="utf-8") as f:
        f.write(to_chrome_json(events, pid=pid))


def to_jsonl(events: Iterable[tuple], *, pid: int = 0) -> str:
    """One JSON object per raw tracer event, in append order."""
    lines = []
    for ph, track, name, ts, seq, view, args in events:
        rec = {"ph": ph, "track": track, "name": name, "ts": ts, "pid": pid}
        if seq is not None:
            rec["seq"] = seq
        if view is not None:
            rec["view"] = view
        if args:
            rec["args"] = args
        lines.append(json.dumps(rec, sort_keys=True, separators=(",", ":")))
    return "\n".join(lines) + ("\n" if lines else "")


def write_jsonl(path, events: Iterable[tuple], *, pid: int = 0) -> None:
    with open(path, "w", encoding="utf-8") as f:
        f.write(to_jsonl(events, pid=pid))
