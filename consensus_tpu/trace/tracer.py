"""Span tracer over a fixed-capacity ring buffer, clocked externally.

The tracer never reads wall clock: its timestamps come from the injected
``clock`` callable (``scheduler.now``), so a trace recorded under
``SimScheduler`` is bit-identical across replays of the same seed — crash
schedules from fault plans included.

Hot-path contract: instrumented components hold a tracer that is either a
real ``Tracer`` or the module-level ``NOOP_TRACER`` and guard every emit
site with ``if tracer.enabled:``.  With tracing off the guard is a single
attribute load and branch — no kwargs dict, no tuple, no ring append.
``Tracer.total_appends`` (class-level) counts ring appends across all live
tracers, which is what the overhead regression guard asserts stays flat.

Events are tuples ``(ph, track, name, ts, seq, view, args)``:

- ``ph``: ``"B"``/``"E"`` span begin/end, ``"i"`` instant.
- ``track``: coarse source category (``"view"``, ``"wal"``, ``"pool"``,
  ``"sync"``, ``"net"``, ``"fault"``, ...) — becomes the Chrome tid.
- ``ts``: scheduler-clock seconds (float).
- ``seq``/``view``: decision key for per-decision spans, else ``None``.
- ``args``: extra payload dict or ``None``.

Appends take a single lock, so threads outside the consensus loop (sidecar
probe/verify threads, WAL waiters) may post events safely.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional


class Tracer:
    """Fixed-capacity ring of trace events; oldest events are overwritten."""

    #: Class-level count of ring appends across every Tracer instance.
    #: The disabled-overhead guard snapshots this around a run.
    total_appends = 0

    def __init__(
        self,
        clock: Callable[[], float],
        *,
        capacity: int = 65536,
        pid: int = 0,
    ):
        if capacity < 1:
            raise ValueError(f"tracer capacity must be >= 1, got {capacity}")
        self._clock = clock
        self._capacity = capacity
        self._ring: list = [None] * capacity
        self._count = 0  # total events ever appended
        self._lock = threading.Lock()
        self.enabled = True
        self.pid = pid  # exported Chrome pid; conventionally the node id

    # -- emit ----------------------------------------------------------

    def begin(self, track, name, *, seq=None, view=None, **args) -> None:
        self._append("B", track, name, seq, view, args or None)

    def end(self, track, name, *, seq=None, view=None, **args) -> None:
        self._append("E", track, name, seq, view, args or None)

    def instant(self, track, name, *, seq=None, view=None, **args) -> None:
        self._append("i", track, name, seq, view, args or None)

    def _append(self, ph, track, name, seq, view, args) -> None:
        ev = (ph, track, name, self._clock(), seq, view, args)
        with self._lock:
            self._ring[self._count % self._capacity] = ev
            self._count += 1
            Tracer.total_appends += 1

    # -- read ----------------------------------------------------------

    def events(self) -> list:
        """Surviving events, oldest first (at most ``capacity``)."""
        with self._lock:
            n, cap = self._count, self._capacity
            if n <= cap:
                return [e for e in self._ring[:n]]
            cut = n % cap
            return self._ring[cut:] + self._ring[:cut]

    @property
    def dropped(self) -> int:
        """Events overwritten by ring wrap-around."""
        with self._lock:
            return max(0, self._count - self._capacity)

    @property
    def appended(self) -> int:
        """Total events ever appended to this tracer."""
        with self._lock:
            return self._count


class NoopTracer:
    """Disabled tracer: same surface as ``Tracer``, does nothing.

    Deliberately *not* a ``Tracer`` subclass — it owns no ring and can
    never bump ``Tracer.total_appends``, which is what makes the
    zero-append overhead guard airtight.
    """

    enabled = False
    pid = 0

    def begin(self, track, name, *, seq=None, view=None, **args) -> None:
        pass

    def end(self, track, name, *, seq=None, view=None, **args) -> None:
        pass

    def instant(self, track, name, *, seq=None, view=None, **args) -> None:
        pass

    def events(self) -> list:
        return []

    @property
    def dropped(self) -> int:
        return 0

    @property
    def appended(self) -> int:
        return 0


#: Shared default for every instrumented component.  ``enabled`` is False
#: forever; call sites guard on it so the disabled hot path allocates
#: nothing.
NOOP_TRACER = NoopTracer()


def tracer_from_config(trace_config, clock, *, pid: int = 0):
    """Build the tracer a component stack should use for ``trace_config``
    (a ``config.TraceConfig``): a live ``Tracer`` when enabled, else the
    shared ``NOOP_TRACER``."""
    if trace_config is not None and trace_config.enabled:
        return Tracer(clock, capacity=trace_config.capacity, pid=pid)
    return NOOP_TRACER
