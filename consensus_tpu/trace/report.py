"""Per-decision critical-path reconstruction from a tracer's event stream.

``build_report`` keys decisions by ``(seq, view)`` and attributes each
decision's latency to the pipeline phases::

    pool_wait    pool.admit  -> batch.seal      (first admitted request)
    seal_wait    batch.seal  -> phase.pre_prepare begin
    pre_prepare  pre-prepare processing (verify + persist admission)
    prepare      pre-prepare done -> prepare quorum
    commit       prepare quorum -> commit quorum
    deliver      application delivery

plus the cross-cutting attribution streams: verify-launch batch sizes and
WAL records-per-fsync.

``pool_wait`` uses FIFO matching: each leader ``batch.seal`` instant with
``count=k`` consumes the ``k`` oldest unconsumed ``pool.admit`` instants,
and the decision's pool wait is measured from the first of those.  This is
exact for the FIFO request pool and needs no per-request ids on the hot
path.
"""

from __future__ import annotations

from typing import Iterable

PHASES = (
    "pool_wait",
    "seal_wait",
    "pre_prepare",
    "prepare",
    "commit",
    "deliver",
)

_PHASE_SPANS = {
    "phase.pre_prepare": "pre_prepare",
    "phase.prepare": "prepare",
    "phase.commit": "commit",
    "phase.deliver": "deliver",
}


def percentile(sorted_values: list, q: float) -> float:
    if not sorted_values:
        return 0.0
    idx = min(len(sorted_values) - 1, int(round(q * (len(sorted_values) - 1))))
    return sorted_values[idx]


def build_report(events: Iterable[tuple]) -> dict:
    events = list(events)
    admits: list = []
    seals: list = []  # (ts, seq, view, count)
    spans: dict = {}  # (seq, view) -> {name: [begin_ts, end_ts]}
    verify_sizes: list = []
    fsync_records: list = []

    for ph, track, name, ts, seq, view, args in events:
        if ph == "i":
            if name == "pool.admit":
                admits.append(ts)
            elif name == "batch.seal" and seq is not None:
                seals.append((ts, seq, view, (args or {}).get("count", 1)))
            elif name == "verify.launch":
                verify_sizes.append((args or {}).get("size", 0))
            elif name == "wal.fsync":
                fsync_records.append((args or {}).get("records", 0))
        elif seq is not None and (name == "decision" or name in _PHASE_SPANS):
            slot = spans.setdefault((seq, view), {}).setdefault(
                name, [None, None]
            )
            if ph == "B":
                slot[0] = ts
            elif ph == "E":
                slot[1] = ts

    # FIFO-match admits to seals, in seal order.
    seal_of: dict = {}  # (seq, view) -> (seal_ts, first_admit_ts | None)
    cursor = 0
    for ts, seq, view, count in sorted(seals):
        first = admits[cursor] if cursor < len(admits) else None
        cursor += count
        seal_of[(seq, view)] = (ts, first)

    decisions: dict = {}
    for key in sorted(spans):
        named = spans[key]
        phases: dict = {}
        for span_name, phase in _PHASE_SPANS.items():
            pair = named.get(span_name)
            if pair and pair[0] is not None and pair[1] is not None:
                phases[phase] = pair[1] - pair[0]
        seal = seal_of.get(key)
        pre = named.get("phase.pre_prepare")
        if seal is not None and pre and pre[0] is not None:
            seal_ts, first_admit = seal
            phases["seal_wait"] = pre[0] - seal_ts
            if first_admit is not None:
                phases["pool_wait"] = seal_ts - first_admit
        decision = named.get("decision", [None, None])
        decisions[key] = {
            "phases": phases,
            "begin": decision[0],
            "end": decision[1],
            "complete": all(
                phase in phases
                for phase in ("pre_prepare", "prepare", "commit", "deliver")
            ),
        }

    phase_percentiles: dict = {}
    for phase in PHASES:
        values = sorted(
            d["phases"][phase]
            for d in decisions.values()
            if phase in d["phases"]
        )
        phase_percentiles[phase] = {
            "n": len(values),
            "p50": percentile(values, 0.50),
            "p99": percentile(values, 0.99),
        }

    return {
        "n_decisions": len(decisions),
        "n_complete": sum(1 for d in decisions.values() if d["complete"]),
        "decisions": decisions,
        "phase_percentiles": phase_percentiles,
        "verify_launch_sizes": verify_sizes,
        "fsync_records": fsync_records,
    }


def format_table(report: dict) -> str:
    """Human-readable phase breakdown (milliseconds)."""
    lines = [
        f"{'phase':<14} {'n':>6} {'p50_ms':>10} {'p99_ms':>10}",
        "-" * 43,
    ]
    for phase in PHASES:
        cell = report["phase_percentiles"][phase]
        lines.append(
            f"{phase:<14} {cell['n']:>6} "
            f"{cell['p50'] * 1000:>10.3f} {cell['p99'] * 1000:>10.3f}"
        )
    sizes = report["verify_launch_sizes"]
    records = report["fsync_records"]
    lines.append("-" * 43)
    lines.append(
        f"decisions: {report['n_decisions']} "
        f"(complete chains: {report['n_complete']})"
    )
    if sizes:
        lines.append(
            f"verify launches: {len(sizes)} "
            f"(mean batch {sum(sizes) / len(sizes):.2f})"
        )
    if records:
        lines.append(
            f"fsyncs: {len(records)} "
            f"(mean records/fsync {sum(records) / len(records):.2f})"
        )
    return "\n".join(lines)
