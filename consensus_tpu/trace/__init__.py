"""Decision-lifecycle tracing: deterministic spans over the scheduler clock.

Always compiled, default off.  See tracer.py (ring-buffer Tracer + the
module-level no-op), export.py (Chrome/Perfetto JSON + JSONL), report.py
(per-decision critical-path reconstruction).
"""

from consensus_tpu.trace.tracer import NOOP_TRACER, NoopTracer, Tracer
from consensus_tpu.trace.export import (
    chrome_trace_events,
    to_chrome_json,
    to_jsonl,
    write_chrome_trace,
    write_jsonl,
)
from consensus_tpu.trace.report import build_report, format_table

__all__ = [
    "Tracer",
    "NoopTracer",
    "NOOP_TRACER",
    "chrome_trace_events",
    "to_chrome_json",
    "to_jsonl",
    "write_chrome_trace",
    "write_jsonl",
    "build_report",
    "format_table",
]
