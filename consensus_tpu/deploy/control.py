"""Per-process control socket: the deploy rig's health/scrape/chaos channel.

Every child process (replica, sidecar) runs one :class:`ControlServer` on
its spec'd control port.  The protocol is deliberately tiny — one JSON
object per connection, one JSON reply — because three very different
callers share it:

* the :class:`~consensus_tpu.deploy.supervisor.NodeSupervisor` health
  probe (``{"op": "ping"}``),
* the soak driver's obs scraper (``{"op": "prom"}`` returns the process's
  Prometheus text body, ``{"op": "health"}`` / ``{"op": "metrics"}`` the
  structured forms), and
* the chaos vocabulary's in-process arms (``net_pause`` / ``net_resume``
  for listener-port drop, ``storage_fault`` for the PR-14 injector).

This channel is the deploy-rig equivalent of the in-process
``controller.health()`` read the obs sampler does: handlers must be plain
reads (or explicit chaos arms) so probing cannot perturb the protocol.

This module is inherently real-time (sockets, I/O deadlines); the audited
``# wallclock-ok`` escapes below are the deploy-plane exception the
no-wallclock lint pins.
"""

from __future__ import annotations

import json
import logging
import socket
import threading
import time
from typing import Callable, Mapping, Optional, Tuple

from consensus_tpu.net.framing import ListenerGuard

logger = logging.getLogger("consensus_tpu.deploy")

_MAX_LINE = 16 * 1024 * 1024


class ControlServer:
    """One-request-one-reply JSON control endpoint on a daemon thread.

    ``handlers`` maps op name -> ``fn(request_dict) -> reply_dict``.  A
    handler exception answers ``{"error": ...}`` and keeps serving; an
    unknown op answers ``{"error": "unknown op ..."}`` — the control plane
    must never die under a confused or version-skewed prober.

    Hardened DEFAULT-ON via a :class:`~consensus_tpu.net.framing
    .ListenerGuard`: connections are admitted against quotas before a byte
    is read and served on their own daemon threads (one stalled prober no
    longer blocks the supervisor's health probe behind it); a request that
    never starts within the handshake deadline, stalls mid-line, overruns
    ``max_line`` without a newline, or fails to parse as JSON (the error
    is still answered) books strikes toward a temporary ban.  Pass a
    configured guard to tune, or ``guard=False`` for the pre-hardening
    serial behavior."""

    def __init__(
        self,
        handlers: Mapping[str, Callable[[dict], dict]],
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        guard=None,
        max_line: int = _MAX_LINE,
    ) -> None:
        self._handlers = dict(handlers)
        if guard is None:
            guard = ListenerGuard(name="control")
        self.guard = guard or None
        self._max_line = max_line
        self._sock = socket.create_server((host, port))
        self._sock.settimeout(0.2)
        self.address: Tuple[str, int] = self._sock.getsockname()[:2]
        self._closed = threading.Event()
        self._thread = threading.Thread(
            target=self._accept_loop,
            name=f"deploy-control-{self.address[1]}",
            daemon=True,
        )
        self._thread.start()

    def _accept_loop(self) -> None:
        while not self._closed.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            addr = "?"
            try:
                addr = conn.getpeername()[0]
            except OSError:
                pass
            guard = self.guard
            if guard is not None and not guard.admit(addr):
                try:
                    conn.close()
                except OSError:
                    pass
                continue
            threading.Thread(
                target=self._serve_conn, args=(conn, addr),
                name=f"deploy-control-serve-{self.address[1]}", daemon=True,
            ).start()

    def _serve_conn(self, conn: socket.socket, addr: str) -> None:
        guard = self.guard
        try:
            with conn:
                line = self._read_request(conn, addr)
                if line is None:
                    return
                try:
                    json.loads(line)
                except ValueError:
                    # Strike the garbage but STILL answer the structured
                    # error — the control plane never goes silent on a
                    # merely confused prober.
                    if guard is not None:
                        guard.strike(addr, "garbage")
                reply = self._handle(line)
                conn.settimeout(5.0)
                conn.sendall(reply + b"\n")
        except OSError:
            pass  # dead prober; keep serving
        finally:
            if guard is not None:
                guard.release(addr)

    def _read_request(self, conn: socket.socket, addr: str) -> Optional[bytes]:
        """One newline-terminated request with guard deadlines: the first
        byte must arrive within the handshake deadline, later chunks within
        the progress deadline, and the line must fit ``max_line``."""
        guard = self.guard
        first_deadline = (
            guard.handshake_timeout if guard is not None else 5.0
        )
        progress = guard.progress_timeout if guard is not None else 5.0
        buf = b""
        while len(buf) < self._max_line:
            try:
                conn.settimeout(progress if buf else first_deadline)
                part = conn.recv(65536)
            except socket.timeout:
                if guard is not None:
                    if buf:
                        guard.strike(addr, "stall")
                    else:
                        guard.handshake_timed_out(addr)
                return None
            except OSError:
                return None
            if not part:
                return None
            buf += part
            if b"\n" in buf:
                return buf.split(b"\n", 1)[0]
        if guard is not None:
            guard.strike(addr, "oversized")
        return None

    def _handle(self, line: bytes) -> bytes:
        try:
            request = json.loads(line)
            op = request.get("op")
            handler = self._handlers.get(op)
            if handler is None:
                reply = {"error": f"unknown op {op!r}"}
            else:
                reply = handler(request)
        except Exception as exc:  # control plane never dies on a handler
            logger.exception("control handler failed")
            reply = {"error": f"{type(exc).__name__}: {exc}"}
        return json.dumps(reply, sort_keys=True).encode()

    def close(self) -> None:
        self._closed.set()
        try:
            self._sock.close()
        except OSError:
            pass
        self._thread.join(timeout=2.0)


def _read_line(conn: socket.socket) -> Optional[bytes]:
    """One newline-terminated request, or None on EOF/timeout/overrun —
    mirroring the sync listener's fail-clean contract for partial frames."""
    buf = b""
    while len(buf) < _MAX_LINE:
        try:
            part = conn.recv(65536)
        except OSError:
            return None
        if not part:
            return None
        buf += part
        if b"\n" in buf:
            return buf.split(b"\n", 1)[0]
    return None


class ControlClient:
    """Blocking caller side: one connection per call, bounded by
    ``timeout`` — a frozen (SIGSTOP) or dead process yields None from
    :meth:`try_call`, never a hang."""

    def __init__(self, address: Tuple[str, int], *, timeout: float = 5.0) -> None:
        self.address = tuple(address)
        self.timeout = timeout

    def call(self, op: str, **kw) -> dict:
        request = dict(kw)
        request["op"] = op
        payload = json.dumps(request, sort_keys=True).encode() + b"\n"
        with socket.create_connection(self.address, timeout=self.timeout) as conn:
            conn.sendall(payload)
            line = _read_line(conn)
        if line is None:
            raise OSError(f"no control reply from {self.address}")
        return json.loads(line)

    def try_call(self, op: str, **kw) -> Optional[dict]:
        try:
            return self.call(op, **kw)
        except (OSError, ValueError):
            return None

    def wait_ready(self, timeout: float) -> bool:
        """Poll ``ping`` until the process answers or ``timeout`` elapses."""
        deadline = time.monotonic() + timeout  # wallclock-ok
        while time.monotonic() < deadline:  # wallclock-ok
            reply = self.try_call("ping")
            if reply is not None and "error" not in reply:
                return True
            time.sleep(0.05)
        return False


__all__ = ["ControlServer", "ControlClient"]
