"""Node supervisor: one OS process under spawn/probe/restart management.

The reference system earns its fault-tolerance story as separate OS
processes under an init-style supervisor (the Fabric orderer restarts and
replays its WAL); this is that layer for the rig.  One
:class:`NodeSupervisor` owns one child process:

* **spawn** — ``Popen`` with stderr teed into a bounded ring buffer (the
  last lines of a dying replica are the single most valuable artifact of
  a chaos run),
* **health-probe** over the child's control socket
  (:class:`~consensus_tpu.deploy.control.ControlClient`),
* **restart** with capped exponential backoff + jitter when the child
  dies and restart is enabled — a ``kill -9`` leader comes back as the
  same node id with the same config file and its intact WAL directory.
  ``max_restarts`` caps CONSECUTIVE failures, not lifetime restarts: a
  child that survives past ``healthy_uptime`` resets the failure count
  (and the backoff exponent), so a multi-hour soak can kill the same
  replica hundreds of times while a genuine crash loop (config error,
  port conflict — every incarnation dying within seconds) still gives
  up after ``max_restarts`` attempts,
* **flight-record capture on death**: every exit writes a JSON record
  (exit code / signal, uptime, restart count, stderr tail) under
  ``flight/`` so a multi-hour soak leaves a forensically useful trail
  even for deaths nobody was watching.

SIGSTOP freezes are NOT deaths: :meth:`suspend`/:meth:`resume` park the
child without triggering the restart path (the probe failing while frozen
is the observable symptom chaos wants).

Supervision is inherently real-time — backoff sleeps, uptime stamps, probe
deadlines — hence the audited ``# wallclock-ok`` escapes.
"""

from __future__ import annotations

import collections
import json
import logging
import os
import random
import signal
import subprocess
import threading
import time
from typing import Optional, Sequence, Tuple

from consensus_tpu.deploy.control import ControlClient

logger = logging.getLogger("consensus_tpu.deploy")


class NodeSupervisor:
    def __init__(
        self,
        name: str,
        argv: Sequence[str],
        control_address: Tuple[str, int],
        *,
        flight_dir: str,
        restart: bool = True,
        backoff_initial: float = 0.25,
        backoff_max: float = 5.0,
        max_restarts: int = 8,
        healthy_uptime: Optional[float] = None,
        stderr_tail_lines: int = 60,
        env: Optional[dict] = None,
        probe_timeout: float = 2.0,
    ) -> None:
        self.name = name
        self.argv = list(argv)
        self.control = ControlClient(control_address, timeout=probe_timeout)
        self.flight_dir = flight_dir
        self.restart_enabled = restart
        self._backoff_initial = backoff_initial
        self._backoff_max = backoff_max
        self._max_restarts = max_restarts
        #: Uptime past which an incarnation counts as healthy and resets
        #: the consecutive-failure budget.  Must sit well above interpreter
        #: boot (so an instant crash loop never resets) and well below the
        #: cadence of legitimate external kills (chaos, deploys).
        self._healthy_uptime = (
            healthy_uptime if healthy_uptime is not None
            else max(2.0 * backoff_max, 5.0)
        )
        self._tail_lines = stderr_tail_lines
        self._env = dict(env) if env is not None else None
        #: Lifetime restart count (reporting/flight records).
        self.restarts = 0
        #: Deaths since the last healthy incarnation — drives the cap and
        #: the backoff exponent.
        self.consecutive_failures = 0
        #: Every Popen this supervisor ever spawned, in spawn order.  The
        #: launcher's teardown orphan audit polls these handles instead of
        #: raw pids (a reaped pid can be recycled by an unrelated process).
        self.spawned: list = []
        self.flight_records: list = []
        self._proc: Optional[subprocess.Popen] = None
        self._tail: "collections.deque[str]" = collections.deque(
            maxlen=stderr_tail_lines
        )
        self._stopping = threading.Event()
        self._frozen = False
        self._lock = threading.Lock()
        self._waiter: Optional[threading.Thread] = None
        self._spawned_at = 0.0
        os.makedirs(flight_dir, exist_ok=True)

    # ------------------------------------------------------------- spawn

    def start(self) -> None:
        with self._lock:
            self._spawn_locked()

    def _spawn_locked(self) -> None:
        self._tail = collections.deque(maxlen=self._tail_lines)
        env = self._env if self._env is not None else os.environ.copy()
        proc = subprocess.Popen(
            self.argv,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.PIPE,
            env=env,
            text=True,
        )
        self._proc = proc
        self.spawned.append(proc)
        self._spawned_at = time.monotonic()  # wallclock-ok
        threading.Thread(
            target=self._stderr_pump, args=(proc,),
            name=f"sup-{self.name}-stderr", daemon=True,
        ).start()
        waiter = threading.Thread(
            target=self._wait_loop, args=(proc,),
            name=f"sup-{self.name}-wait", daemon=True,
        )
        self._waiter = waiter
        waiter.start()
        logger.info("%s: spawned pid %d", self.name, proc.pid)

    def _stderr_pump(self, proc: subprocess.Popen) -> None:
        try:
            for line in proc.stderr:
                self._tail.append(line.rstrip("\n"))
        except (OSError, ValueError):
            pass

    # ----------------------------------------------------------- restart

    def _wait_loop(self, proc: subprocess.Popen) -> None:
        rc = proc.wait()
        uptime = time.monotonic() - self._spawned_at  # wallclock-ok
        if uptime >= self._healthy_uptime:
            # This incarnation ran long enough to count as healthy: an
            # external kill (chaos, operator), not a crash loop.  Reset
            # the consecutive-failure budget and the backoff exponent so
            # a multi-hour soak never exhausts a lifetime cap.
            self.consecutive_failures = 0
        record = self._flight_record(rc, uptime)
        if self._stopping.is_set():
            return
        logger.warning(
            "%s: pid %d died (%s) after %.1fs", self.name, proc.pid,
            record["cause"], uptime,
        )
        if (
            not self.restart_enabled
            or self.consecutive_failures >= self._max_restarts
        ):
            return
        delay = min(
            self._backoff_initial * (2.0 ** self.consecutive_failures),
            self._backoff_max,
        )
        delay *= 0.5 + random.random() / 2.0  # jitter: fleet desync
        if self._stopping.wait(delay):
            return
        with self._lock:
            if self._stopping.is_set() or self._proc is not proc:
                return
            self.restarts += 1
            self.consecutive_failures += 1
            self._spawn_locked()

    def _flight_record(self, rc: int, uptime: float) -> dict:
        if rc >= 0:
            sig_name = None
            cause = f"exit {rc}"
        else:
            try:
                sig_name = signal.Signals(-rc).name
            except ValueError:  # platform-specific / real-time signal
                sig_name = f"signal {-rc}"
            cause = (
                f"signal {sig_name}" if not sig_name.startswith("signal ")
                else sig_name
            )
        record = {
            "name": self.name,
            "pid": self._proc.pid if self._proc else None,
            "exit_code": rc if rc >= 0 else None,
            "signal": sig_name,
            "cause": cause,
            "uptime_secs": round(uptime, 3),
            "restarts": self.restarts,
            "t": time.time(),  # wallclock-ok
            "stderr_tail": list(self._tail),
        }
        self.flight_records.append(record)
        path = os.path.join(
            self.flight_dir, f"{self.name}-{len(self.flight_records)}.json"
        )
        try:
            with open(path, "w", encoding="utf-8") as fh:
                json.dump(record, fh, indent=2)
        except OSError:
            logger.exception("%s: failed writing flight record", self.name)
        return record

    # ------------------------------------------------------------- probes

    @property
    def pid(self) -> Optional[int]:
        proc = self._proc
        return proc.pid if proc is not None else None

    @property
    def alive(self) -> bool:
        proc = self._proc
        return proc is not None and proc.poll() is None

    def probe(self) -> Optional[dict]:
        """The child's ``health`` answer, or None when unreachable."""
        return self.control.try_call("health")

    def wait_healthy(self, timeout: float) -> bool:
        return self.control.wait_ready(timeout)

    # -------------------------------------------------------------- chaos

    def kill(self, sig: int = signal.SIGKILL) -> None:
        """Deliver ``sig`` to the child (kill -9 chaos rides through here).
        Death is observed by the waiter thread, which restarts per policy."""
        proc = self._proc
        if proc is not None and proc.poll() is None:
            os.kill(proc.pid, sig)

    def suspend(self) -> None:
        """SIGSTOP freeze — not a death; no restart fires."""
        proc = self._proc
        if proc is not None and proc.poll() is None:
            self._frozen = True
            os.kill(proc.pid, signal.SIGSTOP)

    def resume(self) -> None:
        proc = self._proc
        if proc is not None and proc.poll() is None and self._frozen:
            self._frozen = False
            os.kill(proc.pid, signal.SIGCONT)

    # ----------------------------------------------------------- shutdown

    def stop(self, timeout: float = 10.0) -> None:
        """Graceful-then-forceful: control ``exit``, SIGTERM, SIGKILL.
        Guarantees the child is reaped (no orphan survives a teardown)."""
        self._stopping.set()
        while True:
            proc = self._proc
            if proc is None:
                return
            if self._frozen:
                try:
                    os.kill(proc.pid, signal.SIGCONT)
                except OSError:
                    pass
                self._frozen = False
            if proc.poll() is None:
                self.control.try_call("exit")
                try:
                    proc.wait(timeout=timeout / 2)
                except subprocess.TimeoutExpired:
                    proc.terminate()
                    try:
                        proc.wait(timeout=timeout / 2)
                    except subprocess.TimeoutExpired:
                        proc.kill()
                        proc.wait(timeout=5.0)
            else:
                proc.wait()
            # A restart racing this stop may have swapped in a fresh child
            # between the event set and the lock: stop that one too.
            if self._proc is proc:
                break
        waiter = self._waiter
        if waiter is not None:
            waiter.join(timeout=2.0)

    def assert_reaped(self) -> None:
        proc = self._proc
        if proc is None:
            return
        if proc.poll() is None:
            raise AssertionError(f"{self.name}: pid {proc.pid} still running")


__all__ = ["NodeSupervisor"]
