"""Replica process entry for the deployment rig.

``python -m consensus_tpu.deploy.replica_main --config cluster.json
--node-id N`` boots ONE consensus replica as its own OS process: real TCP
consensus links (hardened reconnect path), a real SyncListener serving its
ledger on the spec'd port, a file-backed WAL under the spec'd directory
(recovered with ``initialize_and_read_all`` + quarantine on every boot, so
a ``kill -9`` restart resumes from its intact durable prefix), signature
verification through the sidecar fleet when one is configured (with
placement-aware reroute on sidecar death), and a control socket answering
health probes, Prometheus scrapes, and chaos arms.

Everything this process IS comes from the config file plus its WAL
directory — which is exactly the restart contract the supervisor relies
on.

A child process lives on the real clock by definition; the audited
``# wallclock-ok`` escapes below cover its serving loop and scrape
timestamps.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys
import threading
import time


class _StubCluster:
    """Cross-process deployments have no in-process ledger registry: the
    toy sync shortcut answers empty (real catch-up rides the verified
    LedgerSynchronizer below)."""

    nodes: dict = {}

    def longest_ledger(self, *, exclude):
        return []

    def reconfig_of(self, proposal):
        from consensus_tpu.types import Reconfig

        return Reconfig()


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", required=True)
    ap.add_argument("--node-id", type=int, required=True)
    args = ap.parse_args()

    logging.basicConfig(
        level=logging.INFO,
        stream=sys.stderr,
        format=f"[replica-{args.node_id}] %(name)s %(levelname)s %(message)s",
    )

    from consensus_tpu.consensus import Consensus
    from consensus_tpu.deploy.control import ControlServer
    from consensus_tpu.deploy.identity import (
        make_client_keyring,
        make_node_signer,
        make_sig_verifier,
    )
    from consensus_tpu.deploy.spec import ClusterSpec
    from consensus_tpu.ingress.placement import SidecarFleet
    from consensus_tpu.metrics import InMemoryProvider, Metrics
    from consensus_tpu.models.ed25519 import Ed25519BatchVerifier
    from consensus_tpu.net import SidecarVerifierClient, TcpComm
    from consensus_tpu.obs.export import sample_to_prometheus
    from consensus_tpu.runtime import RealtimeScheduler
    from consensus_tpu.sync import (
        LedgerDecisionStore,
        LedgerSynchronizer,
        SyncListener,
        SyncServer,
        TcpSyncTransport,
    )
    from consensus_tpu.testing.crypto_app import SignedRequestApp
    from consensus_tpu.testing.storage import StorageFaultInjector
    from consensus_tpu.wal.log import initialize_and_read_all

    spec = ClusterSpec.load(args.config)
    me = spec.replica(args.node_id)
    node_ids = spec.node_ids()
    secret = spec.auth_secret

    # --- identity + engine ------------------------------------------------
    host_engine = Ed25519BatchVerifier(min_device_batch=10**9)
    fleet = None
    if spec.sidecars:
        fleet = SidecarFleet(
            spec.sidecar_addresses(),
            client_factory=lambda addr: SidecarVerifierClient(
                tuple(addr),
                local_engine=host_engine,
                bypass_below=spec.sidecar_bypass_below,
                request_timeout=spec.sidecar_request_timeout,
                auth_secret=secret,
            ),
        )
        primary = fleet.assign(f"replica-{args.node_id}")
        engine = SidecarVerifierClient(
            spec.sidecar_addresses()[primary],
            local_engine=host_engine,
            bypass_below=spec.sidecar_bypass_below,
            request_timeout=spec.sidecar_request_timeout,
            auth_secret=secret,
            fleet=fleet,
            fleet_id=primary,
        )
    else:
        engine = host_engine

    signer = make_node_signer(spec.key_namespace, args.node_id)
    verifier = make_sig_verifier(spec.key_namespace, node_ids, engine=engine)
    clients = make_client_keyring(spec.key_namespace, spec.clients)

    cluster = _StubCluster()
    app = SignedRequestApp(
        args.node_id, cluster, signer, verifier,
        client_keys=clients.public_keys, engine=engine, sig_len=64,
    )

    # --- runtime + transports --------------------------------------------
    provider = InMemoryProvider()
    metrics = Metrics(provider)
    rt = RealtimeScheduler()
    rt.start(thread_name=f"replica-{args.node_id}")
    consensus_holder: list = [None]

    member_ids = set(node_ids)

    def route(sender, payload, is_request):
        c = consensus_holder[0]
        if c is None:
            return
        if is_request:
            if sender in member_ids:
                # Replica-to-replica forward (pool timeout cascade).
                c.handle_request(sender, payload)
            else:
                # Client ingress over the request channel (the deploy
                # driver): verify before pooling, same hygiene as the
                # leader-forward path.
                try:
                    app.verify_request(payload)
                except Exception:
                    return
                c.submit_request(payload)
        else:
            c.handle_message(sender, payload)

    comm = TcpComm(
        args.node_id, spec.comm_addresses(), route,
        reconnect_backoff=0.05, auth_secret=secret, metrics=metrics.network,
    )
    comm.start()

    store = LedgerDecisionStore(app.ledger)
    sync_listener = SyncListener(
        SyncServer(store), host=me.host, port=me.sync_port
    )
    synchronizer = LedgerSynchronizer(
        node_id=args.node_id,
        store=store,
        transport=TcpSyncTransport(
            args.node_id,
            {i: a for i, a in spec.sync_addresses().items()
             if i != args.node_id},
        ),
        verifier=app,
        nodes=node_ids,
        reconfig_of=cluster.reconfig_of,
    )

    # --- WAL: recover the durable prefix on every boot --------------------
    wal, entries = initialize_and_read_all(me.wal_dir, quarantine_corrupt=True)
    injector = StorageFaultInjector(seed=args.node_id)
    injector.install(wal)
    restarted = bool(entries)

    # Rejoin flow after a restart: catch up through verified sync before
    # contending (Configuration is frozen — set at construction).
    config = spec.make_configuration(
        args.node_id, **({"sync_on_start": True} if restarted else {})
    )

    consensus = Consensus(
        config=config,
        scheduler=rt,
        comm=comm,
        application=app,
        assembler=app,
        wal=wal,
        signer=app,
        verifier=app,
        request_inspector=app.inspector,
        synchronizer=synchronizer,
        wal_initial_content=entries,
        metrics=metrics,
    )
    consensus.start()
    consensus_holder[0] = consensus

    # --- control socket ---------------------------------------------------
    stop_event = threading.Event()
    scrape_count = [0]

    def _health(_request) -> dict:
        h = dict(consensus.controller.health()) if consensus.controller else {}
        h.update(
            ok=True, role="replica", node_id=args.node_id, pid=os.getpid(),
            running=True, ledger=len(app.ledger), restarted=restarted,
            wal_recovery=bool(getattr(wal, "recovery", None)),
        )
        return h

    def _ledger(request) -> dict:
        start = int(request.get("from", 0))
        digests = [d.proposal.digest() for d in list(app.ledger)]
        return {"height": len(digests), "digests": digests[start:]}

    def _prom(_request) -> dict:
        h = _health({})
        health = {
            "running": True,
            "view": h.get("view", -1),
            "leader": h.get("leader", -1),
            "seq": h.get("seq", -1),
            "in_flight": h.get("in_flight", 0),
            "syncing": bool(h.get("syncing", False)),
            "pool": 0,
            "wal_entries": len(getattr(wal, "entries", ()) or ()) or -1,
            "wal_fsyncs": getattr(wal, "fsync_count", -1),
            "ledger": len(app.ledger),
            "sync_lag": 0,
            "epoch": h.get("epoch", 0),
        }
        sample = {
            "t": round(time.time(), 6),  # wallclock-ok
            "i": scrape_count[0],
            "nodes": {str(args.node_id): {
                "health": health, "metrics": provider.dump(),
            }},
            "anomalies": [],
        }
        scrape_count[0] += 1
        return {"ok": True, "text": sample_to_prometheus(sample)}

    def _storage_fault(request) -> dict:
        kind = request["kind"]
        injector.arm(
            kind,
            budget=request.get("budget"),
            count=int(request.get("count", 1)),
        )
        return {"ok": True, "armed": kind}

    handlers = {
        "ping": lambda r: {"ok": True, "pid": os.getpid(),
                           "role": "replica", "node_id": args.node_id},
        "health": _health,
        "ledger": _ledger,
        "metrics": lambda r: {"ok": True, "metrics": provider.dump()},
        "prom": _prom,
        "net_pause": lambda r: (comm.pause_listener(), {"ok": True})[1],
        "net_resume": lambda r: (comm.resume_listener(), {"ok": True})[1],
        "storage_fault": _storage_fault,
        "storage_heal": lambda r: (injector.heal(), {"ok": True})[1],
        "exit": lambda r: (stop_event.set(), {"ok": True})[1],
    }
    control = ControlServer(
        handlers, host=me.host, port=me.control_port
    )
    print(json.dumps({"ready": True, "node_id": args.node_id,
                      "pid": os.getpid()}), flush=True)

    while not stop_event.wait(0.5):
        pass

    consensus.stop()
    comm.stop()
    sync_listener.close()
    control.close()
    try:
        rt.stop(timeout=2.0)
    except RuntimeError:
        pass
    try:
        wal.close()
    except Exception:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
