"""Ingress driver process for the deployment rig.

``python -m consensus_tpu.deploy.driver_main --config cluster.json
--seconds S`` is the PR-12 ingress plane running as its own OS process:
it generates the deterministic client trace
(:func:`~consensus_tpu.ingress.workload.generate_trace` — the same
million-client generator the sim driver replays), pushes every arrival
through a real :class:`~consensus_tpu.ingress.admission.AdmissionController`,
signs admitted requests with the cluster's derived client keys, and
broadcasts them to every replica over its own authenticated ``TcpComm``
link (open-loop: a slow cluster never back-pressures the arrival
process).

On exit it prints ONE JSON summary line on stdout: offered / admitted /
submitted counts plus the final replica heights it observed over the
control sockets — the soak driver's load-side ground truth.

Replay happens on the real clock by definition (the trace's sim arrival
times are mapped onto wall time): hence the audited ``# wallclock-ok``
escapes.
"""

from __future__ import annotations

import argparse
import json
import logging
import sys
import time
import zlib

#: The driver's node id on the consensus transport: far outside the
#: replica id range, pinned by HELLO like any other peer.
DRIVER_NODE_ID = 900


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", required=True)
    ap.add_argument("--seconds", type=float, default=10.0)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--trace-clients", type=int, default=64,
                    help="trace cohort size (the generator scales to "
                    "millions; CI uses a small cohort)")
    ap.add_argument("--rate", type=float, default=40.0,
                    help="approximate offered events/sec after time scaling")
    args = ap.parse_args()

    logging.basicConfig(
        level=logging.WARNING, stream=sys.stderr,
        format="[driver] %(name)s %(levelname)s %(message)s",
    )

    from consensus_tpu.deploy.control import ControlClient
    from consensus_tpu.deploy.identity import make_client_keyring
    from consensus_tpu.deploy.spec import ClusterSpec, free_ports
    from consensus_tpu.ingress.admission import AdmissionController
    from consensus_tpu.ingress.workload import clean_spec, generate_trace
    from consensus_tpu.net import TcpComm
    from consensus_tpu.types import RequestInfo

    spec = ClusterSpec.load(args.config)
    keyring = make_client_keyring(spec.key_namespace, spec.clients)

    # Deterministic trace, scaled to the requested wall duration/rate.
    wspec = clean_spec(
        clients=args.trace_clients,
        tenants=4,
        duration=max(1.0, args.seconds),
    )
    trace = generate_trace(args.seed, wspec)
    if not trace:
        print(json.dumps({"error": "empty trace"}))
        return 1
    # Map trace sim-time onto [0, seconds].
    t_max = max(e.t for e in trace) or 1.0
    scale = args.seconds / t_max

    # Per-client token buckets sized so the offered wall rate spread over
    # the signing cohort mostly clears admission (some rate-limiting under
    # bursts is the PR-12 semantics this plane exists to exercise).
    per_client = max(2.0, 2.0 * args.rate / max(1, spec.clients))
    admission = AdmissionController(rate=per_client, burst=2 * per_client)

    addresses = dict(spec.comm_addresses())
    addresses[DRIVER_NODE_ID] = ("127.0.0.1", free_ports(1)[0])
    comm = TcpComm(
        DRIVER_NODE_ID, addresses, lambda *a: None,
        reconnect_backoff=0.05, auth_secret=spec.auth_secret,
    )
    comm.start()

    offered = admitted = submitted = 0
    seq_per_client: dict = {}
    start = time.monotonic()  # wallclock-ok
    deadline = start + args.seconds
    for event in trace:
        target = start + event.t * scale
        now = time.monotonic()  # wallclock-ok
        if now >= deadline:
            break
        if target > now:
            time.sleep(min(target - now, 0.25))
        offered += 1
        # Trace client names ('h000007', 'a00003', ...) map stably onto
        # the cluster's derived client-key cohort.
        client_idx = zlib.crc32(event.client.encode()) % spec.clients
        info = RequestInfo(
            client_id=str(client_idx),
            request_id=f"{event.rid}",
        )
        verdict = admission.admit(
            time.monotonic() - start, info, size=1  # wallclock-ok
        )
        if verdict != "admitted":
            continue
        admitted += 1
        seq = seq_per_client.get(client_idx, 0)
        seq_per_client[client_idx] = seq + 1
        raw = keyring.make_request(client_idx, (client_idx << 32) | seq)
        for node_id in spec.node_ids():
            comm.send_transaction(node_id, raw)
        submitted += 1

    elapsed = time.monotonic() - start  # wallclock-ok
    # Final heights over the control plane (best effort).
    heights = {}
    for r in spec.replicas:
        reply = ControlClient(
            (r.host, r.control_port), timeout=2.0
        ).try_call("health")
        if reply is not None and "ledger" in reply:
            heights[str(r.node_id)] = reply["ledger"]
    comm.stop()
    print(
        json.dumps({
            "offered": offered,
            "admitted": admitted,
            "submitted": submitted,
            "elapsed_secs": round(elapsed, 2),
            "heights": heights,
        }, sort_keys=True),
        flush=True,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
