"""Cross-process safety invariants for the deployment rig.

The in-process harnesses assert ledger equality by reading shared Python
lists; a process-per-replica cluster only exposes what each process
*reports* over its control socket.  The monitor therefore checks the two
properties that survive any amount of process death:

* **Prefix agreement** — every ledger digest list any replica has EVER
  reported must be prefix-consistent with every other report: position i
  holds the same digest everywhere it is populated.  Two replicas
  disagreeing at any height is a safety violation, full stop.

* **Durable-before-visible** — once ANY replica has reported a digest at
  height i, that digest is pinned: no later report (including one from a
  replica restarted after ``kill -9``) may show a different digest at i.
  A replica that lost acknowledged state to amnesia and re-ordered
  different decisions over the same heights fails exactly this check.
  (A restarted replica reporting a SHORTER ledger is fine — it rebuilds
  through verified sync — it just must re-extend the same chain.)

``observe`` is pure bookkeeping over reported digest lists, so the soak
driver can feed it from control-socket scrapes at any cadence.
"""

from __future__ import annotations

from typing import Sequence


class DeployInvariantMonitor:
    def __init__(self) -> None:
        #: The agreed chain: digest at height i, pinned by first report.
        self.agreed: list = []
        #: node_id -> greatest height that node has reported.
        self.reported_height: dict = {}
        self.violations: list = []
        self.observations = 0

    def observe(self, node_id, digests: Sequence[str]) -> None:
        self.observations += 1
        digests = list(digests)
        for i, digest in enumerate(digests):
            if i < len(self.agreed):
                if self.agreed[i] != digest:
                    self.violations.append(
                        f"node {node_id} reports {digest!r} at height {i}, "
                        f"but {self.agreed[i]!r} was already visible there "
                        "(prefix agreement / durable-before-visible broken)"
                    )
                    return  # one divergence poisons the suffix; stop here
            else:
                self.agreed.append(digest)
        previous = self.reported_height.get(node_id, 0)
        self.reported_height[node_id] = max(previous, len(digests))

    @property
    def clean(self) -> bool:
        return not self.violations

    def assert_clean(self) -> None:
        if self.violations:
            raise AssertionError(
                "deploy invariants violated:\n  "
                + "\n  ".join(self.violations)
            )

    def summary(self) -> dict:
        return {
            "agreed_height": len(self.agreed),
            "observations": self.observations,
            "violations": list(self.violations),
            "reported_height": {
                str(k): v for k, v in sorted(self.reported_height.items())
            },
        }


__all__ = ["DeployInvariantMonitor"]
