"""Fleet autoscaler: add/drain sidecar verifier processes on load signals.

The decision function consumes exactly the two signals the earlier PRs
defined:

* **admission overload** (PR 12): the fleet-wide reject fraction over the
  last evaluation window crosses the same bar as the obs
  ``admission_overload`` detector — rejects/offered >= 0.5 with at least
  ``min_offered`` offered — meaning clients are being turned away, so a
  sidecar is ADDED (up to ``max_sidecars``).
* **engine degraded** (PR 13): a sidecar reporting its supervised engine
  below its top rung is serving correct-but-slow verdicts from its host
  twin; it is DRAINED (and, when draining would take the fleet below
  ``min_sidecars``, a replacement is added first).

A calm fleet (reject fraction under ``calm_reject_fraction``) above
``min_sidecars`` drains the newest sidecar.  ``decide()`` is a pure
function of the signals — unit-testable with zero processes — and
``run_once()`` wires it to a live
:class:`~consensus_tpu.deploy.launcher.ClusterLauncher`.  A cooldown of
``cooldown_evals`` evaluations between actions keeps restarts-in-progress
from double-triggering.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence


@dataclass
class AutoscaleDecision:
    action: Optional[str]  # "scale_up" | "drain" | None
    target: Optional[str]  # sidecar id for drain
    reason: str


class FleetAutoscaler:
    def __init__(
        self,
        *,
        min_sidecars: int = 1,
        max_sidecars: int = 4,
        overload_reject_fraction: float = 0.5,
        min_offered: int = 20,
        calm_reject_fraction: float = 0.05,
        cooldown_evals: int = 3,
    ) -> None:
        self.min_sidecars = min_sidecars
        self.max_sidecars = max_sidecars
        self.overload_reject_fraction = overload_reject_fraction
        self.min_offered = min_offered
        self.calm_reject_fraction = calm_reject_fraction
        self.cooldown_evals = cooldown_evals
        self._cooldown = 0
        #: Applied decisions, newest last (soak summary material).
        self.history: list = []

    # ------------------------------------------------------------- policy

    def decide(self, signals: Sequence[dict]) -> AutoscaleDecision:
        """``signals``: one dict per live sidecar with ``sidecar_id``,
        ``offered``, ``rejected``, and ``engine_degraded`` (window-relative
        offered/rejected counts)."""
        if self._cooldown > 0:
            self._cooldown -= 1
            return AutoscaleDecision(None, None, "cooldown")
        fleet = len(signals)
        degraded = [s for s in signals if s.get("engine_degraded")]
        if degraded:
            target = degraded[0]["sidecar_id"]
            if fleet <= self.min_sidecars:
                return self._fire("scale_up", None,
                                  f"{target} engine_degraded at min fleet: "
                                  "add replacement before draining")
            return self._fire("drain", target, f"{target} engine_degraded")
        offered = sum(int(s.get("offered", 0)) for s in signals)
        rejected = sum(int(s.get("rejected", 0)) for s in signals)
        if offered >= self.min_offered:
            fraction = rejected / offered
            if (fraction >= self.overload_reject_fraction
                    and fleet < self.max_sidecars):
                return self._fire(
                    "scale_up", None,
                    f"admission_overload: {rejected}/{offered} rejected",
                )
        if (fleet > self.min_sidecars
                and (offered == 0
                     or rejected / offered <= self.calm_reject_fraction)):
            target = signals[-1]["sidecar_id"]
            return self._fire("drain", target,
                              f"calm fleet ({rejected}/{offered} rejected)")
        return AutoscaleDecision(None, None, "steady")

    def _fire(self, action, target, reason) -> AutoscaleDecision:
        self._cooldown = self.cooldown_evals
        decision = AutoscaleDecision(action, target, reason)
        self.history.append(decision)
        return decision

    # --------------------------------------------------------------- live

    def run_once(self, launcher) -> AutoscaleDecision:
        """Scrape signals from the launcher's live sidecars, decide, apply."""
        signals = launcher.sidecar_signals()
        decision = self.decide(signals)
        if decision.action == "scale_up":
            launcher.add_sidecar()
        elif decision.action == "drain" and decision.target is not None:
            launcher.drain_sidecar(decision.target)
        return decision


__all__ = ["FleetAutoscaler", "AutoscaleDecision"]
