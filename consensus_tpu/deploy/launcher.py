"""Cluster launcher: the one object that owns a process-per-replica rig.

``start()`` writes the cluster spec to disk (config/key distribution),
boots the sidecar fleet first (replicas dial it at verify time), then the
replicas — every process under its own
:class:`~consensus_tpu.deploy.supervisor.NodeSupervisor` — and waits for
each control socket to answer.  From there the launcher is the rig's
operator console:

* health/leader probes and Prometheus scrapes across every process,
* ledger-digest collection feeding the
  :class:`~consensus_tpu.deploy.invariants.DeployInvariantMonitor`,
* the chaos verbs (`kill -9`, SIGSTOP freeze, listener-port drop,
  storage-fault arming) addressed by node id / sidecar id,
* autoscaler hooks (``add_sidecar`` / ``drain_sidecar`` re-write the spec
  so restarted replicas see the grown fleet), and
* ``stop()`` — graceful teardown that ASSERTS zero orphaned processes and
  zero leaked listen ports before returning its summary.

Real-time by nature (process lifecycles, socket deadlines): the audited
``# wallclock-ok`` escapes cover its waits.
"""

from __future__ import annotations

import logging
import os
import signal
import socket
import sys
import time
from typing import Dict, Optional

from consensus_tpu.deploy.control import ControlClient
from consensus_tpu.deploy.invariants import DeployInvariantMonitor
from consensus_tpu.deploy.spec import ClusterSpec
from consensus_tpu.deploy.supervisor import NodeSupervisor

logger = logging.getLogger("consensus_tpu.deploy")


class ClusterLauncher:
    def __init__(
        self,
        spec: ClusterSpec,
        *,
        restart: bool = True,
        python: str = sys.executable,
        backoff_initial: float = 0.25,
        max_restarts: int = 8,
        spawn_sidecars: bool = True,
    ) -> None:
        #: ``spawn_sidecars=False`` — consensus sharding: the spec's
        #: sidecars are a SHARED fleet owned by another launcher (the
        #: first group's), so this launcher neither boots, audits, nor
        #: port-checks them; replicas still dial them at verify time.
        self.spawn_sidecars = spawn_sidecars
        self.spec = spec
        self.python = python
        self.restart = restart
        self.backoff_initial = backoff_initial
        self.max_restarts = max_restarts
        self.monitor = DeployInvariantMonitor()
        self.replicas: Dict[int, NodeSupervisor] = {}
        self.sidecars: Dict[str, NodeSupervisor] = {}
        self.flight_dir = os.path.join(spec.base_dir, "flight")
        #: Every supervisor this launcher ever created, including drained
        #: sidecars (orphan audit at stop() walks their Popen handles).
        self._all_sups: list = []
        self._sidecar_window: Dict[str, dict] = {}
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        self._env = os.environ.copy()
        self._env["PYTHONPATH"] = (
            repo_root + os.pathsep + self._env.get("PYTHONPATH", "")
        ).rstrip(os.pathsep)

    # ------------------------------------------------------------- boot

    def _make_supervisor(self, name, argv, control_addr) -> NodeSupervisor:
        sup = NodeSupervisor(
            name,
            argv,
            control_addr,
            flight_dir=self.flight_dir,
            restart=self.restart,
            backoff_initial=self.backoff_initial,
            max_restarts=self.max_restarts,
            env=self._env,
        )
        self._all_sups.append(sup)
        return sup

    def _replica_argv(self, node_id: int) -> list:
        return [
            self.python, "-m", "consensus_tpu.deploy.replica_main",
            "--config", self.spec.config_path, "--node-id", str(node_id),
        ]

    def _sidecar_argv(self, sidecar_id: str) -> list:
        return [
            self.python, "-m", "consensus_tpu.deploy.sidecar_main",
            "--config", self.spec.config_path, "--sidecar-id", sidecar_id,
        ]

    def start(self, timeout: float = 120.0) -> None:
        self.spec.write()
        # Ports reserved at generate time (hold_ports=True) stay BOUND
        # until this moment: release just before spawn, so no concurrent
        # launcher could have drawn them in the meantime (spec.py
        # PortReservation — the free_ports TOCTOU fix).
        self.spec.release_ports()
        deadline = time.monotonic() + timeout  # wallclock-ok
        sidecars = self.spec.sidecars if self.spawn_sidecars else []
        for sc in sidecars:
            sup = self._make_supervisor(
                sc.sidecar_id,
                self._sidecar_argv(sc.sidecar_id),
                (sc.host, sc.control_port),
            )
            self.sidecars[sc.sidecar_id] = sup
            sup.start()
        for r in self.spec.replicas:
            sup = self._make_supervisor(
                f"replica-{r.node_id}",
                self._replica_argv(r.node_id),
                (r.host, r.control_port),
            )
            self.replicas[r.node_id] = sup
            sup.start()
        for sup in list(self.sidecars.values()) + list(self.replicas.values()):
            remaining = deadline - time.monotonic()  # wallclock-ok
            if remaining <= 0 or not sup.wait_healthy(remaining):
                raise TimeoutError(f"{sup.name} failed to come up")

    # ------------------------------------------------------------ probes

    def health(self) -> dict:
        out = {}
        for node_id, sup in self.replicas.items():
            out[f"replica-{node_id}"] = sup.probe()
        for sid, sup in self.sidecars.items():
            out[sid] = sup.probe()
        return out

    def leader_id(self) -> Optional[int]:
        """The leader per the most-advanced view any replica reports."""
        best_view, leader = -1, None
        for sup in self.replicas.values():
            h = sup.probe()
            if h and "view" in h and h["view"] > best_view:
                best_view, leader = h["view"], h.get("leader")
        return leader

    def scrape(self) -> dict:
        """Prometheus text body per live replica (the soak obs plane)."""
        bodies = {}
        for node_id, sup in self.replicas.items():
            reply = sup.control.try_call("prom")
            if reply and reply.get("ok"):
                bodies[f"replica-{node_id}"] = reply["text"]
        return bodies

    def ledger_digests(self, node_id: int) -> Optional[list]:
        sup = self.replicas.get(node_id)
        if sup is None:
            return None
        reply = sup.control.try_call("ledger")
        if reply is None or "digests" not in reply:
            return None
        return reply["digests"]

    def observe_invariants(self) -> None:
        """One monitor pass: collect every live replica's digest list."""
        for node_id in self.replicas:
            digests = self.ledger_digests(node_id)
            if digests is not None:
                self.monitor.observe(node_id, digests)

    def heights(self) -> dict:
        out = {}
        for node_id, sup in self.replicas.items():
            h = sup.probe()
            if h is not None and "ledger" in h:
                out[node_id] = h["ledger"]
        return out

    def wait_height(
        self, height: int, timeout: float, *, min_nodes: Optional[int] = None
    ) -> bool:
        """Until >= ``min_nodes`` replicas (default: all) report ledger
        height >= ``height``."""
        want = min_nodes if min_nodes is not None else len(self.replicas)
        deadline = time.monotonic() + timeout  # wallclock-ok
        while time.monotonic() < deadline:  # wallclock-ok
            reached = sum(
                1 for h in self.heights().values() if h >= height
            )
            if reached >= want:
                return True
            time.sleep(0.1)
        return False

    # ------------------------------------------------------------- chaos

    def kill_replica(self, node_id: int, sig: int = signal.SIGKILL) -> None:
        self.replicas[node_id].kill(sig)

    def kill_sidecar(self, sidecar_id: str, sig: int = signal.SIGKILL) -> None:
        self.sidecars[sidecar_id].kill(sig)

    def freeze_replica(self, node_id: int) -> None:
        self.replicas[node_id].suspend()

    def thaw_replica(self, node_id: int) -> None:
        self.replicas[node_id].resume()

    def drop_listener(self, node_id: int) -> None:
        self.replicas[node_id].control.try_call("net_pause")

    def restore_listener(self, node_id: int) -> None:
        self.replicas[node_id].control.try_call("net_resume")

    def arm_storage_fault(self, node_id: int, kind: str, **kw) -> Optional[dict]:
        return self.replicas[node_id].control.try_call(
            "storage_fault", kind=kind, **kw
        )

    # -------------------------------------------------------- autoscaling

    def sidecar_signals(self) -> list:
        """Window-relative (since last call) offered/rejected per live
        sidecar — the FleetAutoscaler's input."""
        signals = []
        for sid, sup in self.sidecars.items():
            h = sup.probe()
            if h is None:
                continue
            prev = self._sidecar_window.get(sid, {})
            signals.append({
                "sidecar_id": sid,
                "offered": max(0, h.get("offered", 0)
                               - prev.get("offered", 0)),
                "rejected": max(0, h.get("rejected", 0)
                                - prev.get("rejected", 0)),
                "engine_degraded": bool(h.get("engine_degraded")),
            })
            self._sidecar_window[sid] = h
        return signals

    def add_sidecar(self, timeout: float = 60.0) -> str:
        sc = self.spec.add_sidecar()
        self.spec.write()
        sup = self._make_supervisor(
            sc.sidecar_id,
            self._sidecar_argv(sc.sidecar_id),
            (sc.host, sc.control_port),
        )
        self.sidecars[sc.sidecar_id] = sup
        sup.start()
        if not sup.wait_healthy(timeout):
            raise TimeoutError(f"{sc.sidecar_id} failed to come up")
        logger.info("autoscaler: added %s", sc.sidecar_id)
        return sc.sidecar_id

    def drain_sidecar(self, sidecar_id: str) -> None:
        sup = self.sidecars.pop(sidecar_id, None)
        if sup is None:
            return
        sup.stop()
        self.spec.sidecars = [
            s for s in self.spec.sidecars if s.sidecar_id != sidecar_id
        ]
        self.spec.write()
        self._sidecar_window.pop(sidecar_id, None)
        logger.info("autoscaler: drained %s", sidecar_id)

    # ----------------------------------------------------------- teardown

    def _listen_ports(self) -> list:
        ports = []
        for r in self.spec.replicas:
            ports += [r.port, r.sync_port, r.control_port]
        if self.spawn_sidecars:
            # A shared fleet (spawn_sidecars=False) is audited by the
            # launcher that owns it — its ports are legitimately busy here.
            for s in self.spec.sidecars:
                ports += [s.port, s.control_port]
        return ports

    def stop(self) -> dict:
        """Tear everything down; ASSERT no orphaned process and no leaked
        listen port survives.  Returns the teardown summary."""
        for sup in list(self.replicas.values()) + list(self.sidecars.values()):
            sup.stop()
        # Belt and braces: every process EVER spawned — including
        # pre-restart incarnations and drained sidecars — must be gone.
        # Audit Popen handles, not raw pids: poll() answers for exactly
        # the child we spawned, whereas a reaped pid can be recycled by
        # an unrelated same-user process over a multi-hour soak and make
        # os.kill(pid, 0) report a false orphan.
        orphans = []
        for sup in self._all_sups:
            for proc in sup.spawned:
                if proc.poll() is None:
                    orphans.append(f"{sup.name} pid {proc.pid} still running")
        leaked = []
        for port in self._listen_ports():
            probe = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            probe.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            try:
                probe.bind(("127.0.0.1", port))
            except OSError:
                leaked.append(port)
            finally:
                probe.close()
        summary = {
            "orphans": orphans,
            "leaked_ports": leaked,
            "restarts": {
                sup.name: sup.restarts
                for sup in list(self.replicas.values())
                + list(self.sidecars.values())
            },
            "invariants": self.monitor.summary(),
        }
        if orphans:
            raise AssertionError(f"orphaned processes at teardown: {orphans}")
        if leaked:
            raise AssertionError(f"leaked listen ports at teardown: {leaked}")
        return summary


__all__ = ["ClusterLauncher"]
