"""Cluster specification: the one JSON document the launcher distributes.

``ClusterSpec.generate`` mints everything a process-per-replica deployment
needs — consensus/sync/control ports for every replica, sidecar fleet
addresses, a fresh ``auth_secret`` (TCP handshake HMAC for both the
consensus links and the sidecar service), the ``key_namespace`` all
processes derive their Ed25519 identities from, and per-replica WAL
directories — and ``write()`` drops it as ``cluster.json`` under the
cluster's base directory.  Child processes are started with nothing but
``--config <cluster.json> --node-id N`` (or ``--sidecar-id``): config and
key distribution is exactly this one file, which is also what a restart
after ``kill -9`` re-reads.

Consensus tuning knobs ride along in ``config_overrides`` (plain
``Configuration`` field values) so tests can shrink view-change timeouts
without a second distribution channel.
"""

from __future__ import annotations

import json
import os
import secrets
import socket
from dataclasses import asdict, dataclass, field
from typing import Optional


class PortReservation:
    """``n`` localhost ports, BOUND AND HELD until :meth:`release`.

    The old ``free_ports`` picked ports by bind-then-close, leaving a
    TOCTOU window from spec generation all the way to child spawn: two
    launchers generating specs concurrently could each draw the other's
    just-closed ports and collide at boot.  A reservation keeps the
    sockets bound, so the kernel itself arbitrates — while one launcher
    holds its reservation, no other ``PortReservation``/``free_ports``
    call (or anything else binding an ephemeral port) can be handed any
    of its ports.  The launcher releases JUST BEFORE spawning children
    (``ClusterLauncher.start``), shrinking the race window from
    "generate -> spawn" to the microseconds between ``close()`` and the
    child's own ``bind()`` — and that residual race is against random
    ephemeral allocation, not against another launcher's deliberate
    reuse of the same port list.
    """

    def __init__(self, n: int, host: str = "127.0.0.1") -> None:
        self._socks = []
        try:
            for _ in range(n):
                s = socket.socket()
                s.bind((host, 0))
                self._socks.append(s)
        except OSError:
            self.release()
            raise
        #: The reserved port numbers, stable for the reservation's life.
        self.ports = [s.getsockname()[1] for s in self._socks]

    @property
    def held(self) -> bool:
        return bool(self._socks)

    def release(self) -> None:
        """Close every held socket (idempotent) — call immediately before
        handing the ports to child processes."""
        socks, self._socks = self._socks, []
        for s in socks:
            try:
                s.close()
            except OSError:
                pass

    def __enter__(self) -> "PortReservation":
        return self

    def __exit__(self, *exc) -> None:
        self.release()


def free_ports(n: int) -> list:
    """``n`` currently-free localhost ports (bind-then-close, released
    immediately).  Callers that go on to spawn processes on these ports
    should prefer :class:`PortReservation` + ``hold_ports=True`` on
    ``ClusterSpec.generate``: a released port can be claimed by anyone
    between this call and the child's own bind."""
    with PortReservation(n) as reservation:
        return list(reservation.ports)


@dataclass
class ReplicaSpec:
    node_id: int
    host: str
    port: int          # consensus TcpComm listen port
    sync_port: int     # SyncListener (verified catch-up fetch channel)
    control_port: int  # ControlServer (health probe / scrape / chaos ops)
    wal_dir: str


@dataclass
class SidecarSpec:
    sidecar_id: str
    host: str
    port: int          # VerifySidecarServer TCP port
    control_port: int


@dataclass
class ClusterSpec:
    n: int
    base_dir: str
    auth_secret_hex: str
    key_namespace: str
    clients: int = 8
    replicas: list = field(default_factory=list)
    sidecars: list = field(default_factory=list)
    #: Plain Configuration field overrides applied to every replica.
    config_overrides: dict = field(default_factory=dict)
    #: Sidecar-client knobs on the replica side.
    sidecar_bypass_below: int = 64
    sidecar_request_timeout: float = 10.0

    # ------------------------------------------------------------- factory

    @classmethod
    def generate(
        cls,
        n: int,
        n_sidecars: int,
        base_dir: str,
        *,
        clients: int = 8,
        host: str = "127.0.0.1",
        config_overrides: Optional[dict] = None,
        hold_ports: bool = False,
    ) -> "ClusterSpec":
        """Mint a spec on fresh localhost ports.  ``hold_ports=True`` keeps
        the ports BOUND (a :class:`PortReservation` attached to the spec)
        until the launcher releases them right before spawn — the fix for
        the generate-to-spawn TOCTOU; two concurrent launchers holding
        reservations can never draw overlapping port sets."""
        os.makedirs(base_dir, exist_ok=True)
        reservation = PortReservation(3 * n + 2 * n_sidecars, host=host)
        ports = reservation.ports
        spec = cls(
            n=n,
            base_dir=os.path.abspath(base_dir),
            auth_secret_hex=secrets.token_hex(16),
            key_namespace=secrets.token_hex(8),
            clients=clients,
            config_overrides=dict(config_overrides or {}),
        )
        for i in range(n):
            node_id = i + 1
            spec.replicas.append(
                ReplicaSpec(
                    node_id=node_id,
                    host=host,
                    port=ports[3 * i],
                    sync_port=ports[3 * i + 1],
                    control_port=ports[3 * i + 2],
                    wal_dir=os.path.join(
                        spec.base_dir, f"node-{node_id}", "wal"
                    ),
                )
            )
        for k in range(n_sidecars):
            spec.sidecars.append(
                SidecarSpec(
                    sidecar_id=f"sc-{k}",
                    host=host,
                    port=ports[3 * n + 2 * k],
                    control_port=ports[3 * n + 2 * k + 1],
                )
            )
        if hold_ports:
            spec.attach_reservation(reservation)
        else:
            reservation.release()
        return spec

    # Deliberately UNANNOTATED class attribute — not a dataclass field, so
    # reservations stay process-local: never serialized into cluster.json,
    # never survive a load().
    _reservation = None

    def attach_reservation(self, reservation: PortReservation) -> None:
        self._reservation = reservation

    def release_ports(self) -> None:
        """Release a held :class:`PortReservation` (idempotent; no-op for
        specs generated without ``hold_ports``) — the launcher calls this
        immediately before spawning children."""
        reservation = self._reservation
        if reservation is not None:
            reservation.release()

    @property
    def ports_held(self) -> bool:
        return self._reservation is not None and self._reservation.held

    def add_sidecar(self) -> SidecarSpec:
        """Mint a spec for one more sidecar process (autoscaler scale-up).
        The launcher re-writes cluster.json so restarted replicas see the
        grown fleet."""
        taken = {int(s.sidecar_id.split("-", 1)[1]) for s in self.sidecars}
        k = 0
        while k in taken:
            k += 1
        port, control_port = free_ports(2)
        sc = SidecarSpec(
            sidecar_id=f"sc-{k}",
            host=self.replicas[0].host if self.replicas else "127.0.0.1",
            port=port,
            control_port=control_port,
        )
        self.sidecars.append(sc)
        return sc

    # --------------------------------------------------------------- views

    @property
    def auth_secret(self) -> bytes:
        return bytes.fromhex(self.auth_secret_hex)

    @property
    def config_path(self) -> str:
        return os.path.join(self.base_dir, "cluster.json")

    def node_ids(self) -> list:
        return [r.node_id for r in self.replicas]

    def replica(self, node_id: int) -> ReplicaSpec:
        for r in self.replicas:
            if r.node_id == node_id:
                return r
        raise KeyError(f"no replica {node_id} in spec")

    def sidecar(self, sidecar_id: str) -> SidecarSpec:
        for s in self.sidecars:
            if s.sidecar_id == sidecar_id:
                return s
        raise KeyError(f"no sidecar {sidecar_id} in spec")

    def comm_addresses(self) -> dict:
        return {r.node_id: (r.host, r.port) for r in self.replicas}

    def sync_addresses(self) -> dict:
        return {r.node_id: (r.host, r.sync_port) for r in self.replicas}

    def sidecar_addresses(self) -> dict:
        return {s.sidecar_id: (s.host, s.port) for s in self.sidecars}

    # ----------------------------------------------------------------- io

    def write(self) -> str:
        payload = asdict(self)
        path = self.config_path
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(payload, fh, indent=2, sort_keys=True)
        os.replace(tmp, path)
        return path

    @classmethod
    def load(cls, path: str) -> "ClusterSpec":
        with open(path, "r", encoding="utf-8") as fh:
            payload = json.load(fh)
        payload["replicas"] = [ReplicaSpec(**r) for r in payload["replicas"]]
        payload["sidecars"] = [SidecarSpec(**s) for s in payload["sidecars"]]
        return cls(**payload)

    def make_configuration(self, node_id: int, **extra):
        """Per-replica ``Configuration`` (frozen dataclass — boot-time
        extras like ``sync_on_start`` must be passed here, not assigned)."""
        from consensus_tpu.config import Configuration

        defaults = dict(
            self_id=node_id,
            leader_rotation=False,
            decisions_per_leader=0,
            request_batch_max_count=20,
            request_batch_max_interval=0.05,
            request_pool_size=2000,
        )
        defaults.update(self.config_overrides)
        defaults.update(extra)
        return Configuration(**defaults)


__all__ = [
    "ClusterSpec",
    "PortReservation",
    "ReplicaSpec",
    "SidecarSpec",
    "free_ports",
]
