"""Cluster specification: the one JSON document the launcher distributes.

``ClusterSpec.generate`` mints everything a process-per-replica deployment
needs — consensus/sync/control ports for every replica, sidecar fleet
addresses, a fresh ``auth_secret`` (TCP handshake HMAC for both the
consensus links and the sidecar service), the ``key_namespace`` all
processes derive their Ed25519 identities from, and per-replica WAL
directories — and ``write()`` drops it as ``cluster.json`` under the
cluster's base directory.  Child processes are started with nothing but
``--config <cluster.json> --node-id N`` (or ``--sidecar-id``): config and
key distribution is exactly this one file, which is also what a restart
after ``kill -9`` re-reads.

Consensus tuning knobs ride along in ``config_overrides`` (plain
``Configuration`` field values) so tests can shrink view-change timeouts
without a second distribution channel.
"""

from __future__ import annotations

import json
import os
import secrets
import socket
from dataclasses import asdict, dataclass, field
from typing import Optional


def free_ports(n: int) -> list:
    """``n`` currently-free localhost ports, picked by bind-then-close.

    This is inherently TOCTOU: between close and the child's own bind
    another process can claim a port.  Acceptable for a localhost test
    rig — a lost race surfaces loudly (child bind failure -> supervisor
    flight record + bounded restarts; resume_listener keeps the paused
    flag on rebind failure so the heal retries) rather than corrupting
    anything.  All sockets are held open until every port is drawn so
    one call never hands out duplicates.
    """
    socks = [socket.socket() for _ in range(n)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


@dataclass
class ReplicaSpec:
    node_id: int
    host: str
    port: int          # consensus TcpComm listen port
    sync_port: int     # SyncListener (verified catch-up fetch channel)
    control_port: int  # ControlServer (health probe / scrape / chaos ops)
    wal_dir: str


@dataclass
class SidecarSpec:
    sidecar_id: str
    host: str
    port: int          # VerifySidecarServer TCP port
    control_port: int


@dataclass
class ClusterSpec:
    n: int
    base_dir: str
    auth_secret_hex: str
    key_namespace: str
    clients: int = 8
    replicas: list = field(default_factory=list)
    sidecars: list = field(default_factory=list)
    #: Plain Configuration field overrides applied to every replica.
    config_overrides: dict = field(default_factory=dict)
    #: Sidecar-client knobs on the replica side.
    sidecar_bypass_below: int = 64
    sidecar_request_timeout: float = 10.0

    # ------------------------------------------------------------- factory

    @classmethod
    def generate(
        cls,
        n: int,
        n_sidecars: int,
        base_dir: str,
        *,
        clients: int = 8,
        host: str = "127.0.0.1",
        config_overrides: Optional[dict] = None,
    ) -> "ClusterSpec":
        os.makedirs(base_dir, exist_ok=True)
        ports = free_ports(3 * n + 2 * n_sidecars)
        spec = cls(
            n=n,
            base_dir=os.path.abspath(base_dir),
            auth_secret_hex=secrets.token_hex(16),
            key_namespace=secrets.token_hex(8),
            clients=clients,
            config_overrides=dict(config_overrides or {}),
        )
        for i in range(n):
            node_id = i + 1
            spec.replicas.append(
                ReplicaSpec(
                    node_id=node_id,
                    host=host,
                    port=ports[3 * i],
                    sync_port=ports[3 * i + 1],
                    control_port=ports[3 * i + 2],
                    wal_dir=os.path.join(
                        spec.base_dir, f"node-{node_id}", "wal"
                    ),
                )
            )
        for k in range(n_sidecars):
            spec.sidecars.append(
                SidecarSpec(
                    sidecar_id=f"sc-{k}",
                    host=host,
                    port=ports[3 * n + 2 * k],
                    control_port=ports[3 * n + 2 * k + 1],
                )
            )
        return spec

    def add_sidecar(self) -> SidecarSpec:
        """Mint a spec for one more sidecar process (autoscaler scale-up).
        The launcher re-writes cluster.json so restarted replicas see the
        grown fleet."""
        taken = {int(s.sidecar_id.split("-", 1)[1]) for s in self.sidecars}
        k = 0
        while k in taken:
            k += 1
        port, control_port = free_ports(2)
        sc = SidecarSpec(
            sidecar_id=f"sc-{k}",
            host=self.replicas[0].host if self.replicas else "127.0.0.1",
            port=port,
            control_port=control_port,
        )
        self.sidecars.append(sc)
        return sc

    # --------------------------------------------------------------- views

    @property
    def auth_secret(self) -> bytes:
        return bytes.fromhex(self.auth_secret_hex)

    @property
    def config_path(self) -> str:
        return os.path.join(self.base_dir, "cluster.json")

    def node_ids(self) -> list:
        return [r.node_id for r in self.replicas]

    def replica(self, node_id: int) -> ReplicaSpec:
        for r in self.replicas:
            if r.node_id == node_id:
                return r
        raise KeyError(f"no replica {node_id} in spec")

    def sidecar(self, sidecar_id: str) -> SidecarSpec:
        for s in self.sidecars:
            if s.sidecar_id == sidecar_id:
                return s
        raise KeyError(f"no sidecar {sidecar_id} in spec")

    def comm_addresses(self) -> dict:
        return {r.node_id: (r.host, r.port) for r in self.replicas}

    def sync_addresses(self) -> dict:
        return {r.node_id: (r.host, r.sync_port) for r in self.replicas}

    def sidecar_addresses(self) -> dict:
        return {s.sidecar_id: (s.host, s.port) for s in self.sidecars}

    # ----------------------------------------------------------------- io

    def write(self) -> str:
        payload = asdict(self)
        path = self.config_path
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(payload, fh, indent=2, sort_keys=True)
        os.replace(tmp, path)
        return path

    @classmethod
    def load(cls, path: str) -> "ClusterSpec":
        with open(path, "r", encoding="utf-8") as fh:
            payload = json.load(fh)
        payload["replicas"] = [ReplicaSpec(**r) for r in payload["replicas"]]
        payload["sidecars"] = [SidecarSpec(**s) for s in payload["sidecars"]]
        return cls(**payload)

    def make_configuration(self, node_id: int, **extra):
        """Per-replica ``Configuration`` (frozen dataclass — boot-time
        extras like ``sync_on_start`` must be passed here, not assigned)."""
        from consensus_tpu.config import Configuration

        defaults = dict(
            self_id=node_id,
            leader_rotation=False,
            decisions_per_leader=0,
            request_batch_max_count=20,
            request_batch_max_interval=0.05,
            request_pool_size=2000,
        )
        defaults.update(self.config_overrides)
        defaults.update(extra)
        return Configuration(**defaults)


__all__ = ["ClusterSpec", "ReplicaSpec", "SidecarSpec", "free_ports"]
