"""Deterministic cross-process identity for the deployment rig.

Separate OS processes share no in-process key registry, so every process
derives the SAME keys from the cluster spec's ``key_namespace`` (a random
hex string minted once by the launcher and distributed in the config
file).  Derivation is pure SHA-256 over namespaced tags — restarting a
killed replica re-derives its identity bit-for-bit, which is what lets it
rejoin the cluster after a ``kill -9`` with nothing but its config file
and its WAL directory.

Ed25519 only: the pure-Python RFC 8032 fallback in
``consensus_tpu/models`` keeps the rig dependency-free (the ``cryptography``
package is not required).
"""

from __future__ import annotations

import hashlib


def _seed32(namespace: str, tag: str, i: int) -> bytes:
    return hashlib.sha256(
        b"ctpu-deploy:%s:%s:%d" % (namespace.encode(), tag.encode(), i)
    ).digest()


def make_node_signer(namespace: str, node_id: int):
    from consensus_tpu.models import Ed25519Signer

    return Ed25519Signer(
        node_id, private_key_bytes=_seed32(namespace, "node", node_id)
    )


def make_node_keys(namespace: str, node_ids) -> dict:
    return {
        i: make_node_signer(namespace, i).public_bytes for i in node_ids
    }


def make_client_keyring(namespace: str, n_clients: int):
    from consensus_tpu.models import Ed25519Signer
    from consensus_tpu.testing.crypto_app import ClientKeyring

    return ClientKeyring(
        [
            Ed25519Signer(
                10_000 + i, private_key_bytes=_seed32(namespace, "client", i)
            )
            for i in range(n_clients)
        ]
    )


def make_sig_verifier(namespace: str, node_ids, *, engine):
    """The signature half of the Verifier port (app half lives in
    SignedRequestApp)."""
    from consensus_tpu.models import Ed25519VerifierMixin

    class _SigVerifier(Ed25519VerifierMixin):
        def verify_proposal(self, proposal):
            raise NotImplementedError

        def verify_request(self, raw):
            raise NotImplementedError

        def verification_sequence(self):
            return 0

        def requests_from_proposal(self, proposal):
            return []

    return _SigVerifier(make_node_keys(namespace, node_ids), engine=engine)


__all__ = [
    "make_node_signer",
    "make_node_keys",
    "make_client_keyring",
    "make_sig_verifier",
]
