"""Sidecar verifier process entry for the deployment rig.

``python -m consensus_tpu.deploy.sidecar_main --config cluster.json
--sidecar-id sc-K`` serves signature verification over authenticated TCP
(:class:`~consensus_tpu.net.sidecar.VerifySidecarServer`) as one member of
the horizontally scaled fleet.  Replicas reach it through
:class:`~consensus_tpu.ingress.placement.SidecarFleet`; killing this
process mid-run exercises the client's structured reroute path (the
PR-12/13 fleet story), and the autoscaler drains/adds members by
stopping/spawning these processes.

The control socket exposes wave counters (offered/rejected) and an
``engine_degraded`` flag — the two autoscaler input signals — plus a
``degrade`` chaos arm that makes the engine wrapper report degraded
without changing verdicts (the PR-13 shape: degraded means slow-but-
correct, served from the host twin).
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys
import threading


class _CountingEngine:
    """Engine wrapper: counts waves for the autoscaler signals and honors
    a chaos-armed degraded flag (verdicts never change — degraded is a
    health report, not a correctness state)."""

    def __init__(self, inner) -> None:
        self._inner = inner
        self.offered = 0
        self.degraded = False
        self._lock = threading.Lock()

    def verify_batch(self, messages, signatures, public_keys):
        with self._lock:
            self.offered += len(messages)
        return self._inner.verify_batch(messages, signatures, public_keys)

    def verify_host(self, messages, signatures, public_keys):
        with self._lock:
            self.offered += len(messages)
        return self._inner.verify_host(messages, signatures, public_keys)

    def __getattr__(self, name):
        return getattr(self._inner, name)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", required=True)
    ap.add_argument("--sidecar-id", required=True)
    args = ap.parse_args()

    logging.basicConfig(
        level=logging.INFO,
        stream=sys.stderr,
        format=f"[{args.sidecar_id}] %(name)s %(levelname)s %(message)s",
    )

    from consensus_tpu.deploy.control import ControlServer
    from consensus_tpu.deploy.spec import ClusterSpec
    from consensus_tpu.models.ed25519 import Ed25519BatchVerifier
    from consensus_tpu.net.sidecar import VerifySidecarServer

    spec = ClusterSpec.load(args.config)
    me = spec.sidecar(args.sidecar_id)

    # Host path: on a machine without an accelerator the sidecar still
    # serves real Ed25519 verification (pure host batches); with one, drop
    # min_device_batch to route big waves to the device.
    engine = _CountingEngine(Ed25519BatchVerifier(min_device_batch=10**9))
    server = VerifySidecarServer(
        (me.host, me.port), engine, auth_secret=spec.auth_secret
    )
    server.start()

    stop_event = threading.Event()
    rejected = [0]

    def _health(_request) -> dict:
        return {
            "ok": True,
            "role": "sidecar",
            "sidecar_id": args.sidecar_id,
            "pid": os.getpid(),
            "offered": engine.offered,
            "rejected": rejected[0],
            "engine_degraded": engine.degraded,
        }

    def _degrade(request) -> dict:
        engine.degraded = bool(request.get("degraded", True))
        return {"ok": True, "engine_degraded": engine.degraded}

    control = ControlServer(
        {
            "ping": lambda r: {"ok": True, "pid": os.getpid(),
                               "role": "sidecar",
                               "sidecar_id": args.sidecar_id},
            "health": _health,
            "degrade": _degrade,
            "exit": lambda r: (stop_event.set(), {"ok": True})[1],
        },
        host=me.host,
        port=me.control_port,
    )
    print(json.dumps({"ready": True, "sidecar_id": args.sidecar_id,
                      "pid": os.getpid()}), flush=True)

    while not stop_event.wait(0.5):
        pass

    server.stop()
    control.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
