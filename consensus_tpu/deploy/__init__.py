"""Process-per-replica deployment rig.

Everything before this package runs the cluster as threads inside one
Python process.  This package runs it the way the reference system ships:
N consensus replicas, a horizontally scaled sidecar verifier fleet, and
the ingress driver as **separate OS processes** over the real TCP
transports and file-backed WALs — under an init-style supervisor with
``kill -9`` chaos, fleet autoscaling, and an invariant monitor that holds
across restarts.

Layers:

* :mod:`~consensus_tpu.deploy.spec` — ``cluster.json``: the one document
  that distributes ports, keys, and config to every process,
* :mod:`~consensus_tpu.deploy.control` — JSON-line control sockets
  (health probes, scrapes, chaos arms),
* :mod:`~consensus_tpu.deploy.supervisor` — spawn / probe / restart with
  capped backoff, flight-record capture on death,
* :mod:`~consensus_tpu.deploy.launcher` — the operator console: boots the
  fleet, scrapes it, runs the chaos verbs, asserts clean teardown,
* :mod:`~consensus_tpu.deploy.autoscaler` — sidecar fleet sizing on
  overload / degraded signals,
* :mod:`~consensus_tpu.deploy.invariants` — prefix agreement and
  durable-before-visible across process restarts,
* :mod:`~consensus_tpu.deploy.chaos` — the seeded process-chaos schedule,
* ``replica_main`` / ``sidecar_main`` / ``driver_main`` — the child
  process entry points.
"""

from consensus_tpu.deploy.autoscaler import AutoscaleDecision, FleetAutoscaler
from consensus_tpu.deploy.chaos import (
    DEFAULT_ACTION_WEIGHTS,
    STORAGE_FAULT_KINDS,
    ProcessChaosSchedule,
)
from consensus_tpu.deploy.control import ControlClient, ControlServer
from consensus_tpu.deploy.invariants import DeployInvariantMonitor
from consensus_tpu.deploy.launcher import ClusterLauncher
from consensus_tpu.deploy.spec import (
    ClusterSpec,
    ReplicaSpec,
    SidecarSpec,
    free_ports,
)
from consensus_tpu.deploy.supervisor import NodeSupervisor

__all__ = [
    "AutoscaleDecision",
    "ClusterLauncher",
    "ClusterSpec",
    "ControlClient",
    "ControlServer",
    "DEFAULT_ACTION_WEIGHTS",
    "DeployInvariantMonitor",
    "FleetAutoscaler",
    "NodeSupervisor",
    "ProcessChaosSchedule",
    "ReplicaSpec",
    "SidecarSpec",
    "STORAGE_FAULT_KINDS",
    "free_ports",
]
