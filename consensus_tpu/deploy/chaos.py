"""Process-level chaos vocabulary for the deployment rig.

The earlier chaos planes speak in dropped frames (PR 5), corrupted sectors
(PR 14), and faulted device calls (PR 13).  This one speaks in *processes*
— the unit an operator actually loses:

* ``kill9_leader`` / ``kill9_follower`` — SIGKILL the current leader (the
  view-change path) or a random non-leader (the quorum-margin path); the
  supervisor restarts the victim, which rejoins through verified sync off
  its intact WAL,
* ``kill9_sidecar`` — SIGKILL a verifier fleet member; replicas reroute
  through the placement layer's structured fleet path,
* ``freeze`` / (auto-``thaw``) — SIGSTOP a replica: alive to the kernel,
  dead to the protocol, exactly the grey-failure shape restarts don't fix,
* ``listener_drop`` / (auto-``restore``) — close a replica's consensus
  listen port so inbound peers see connection-refused while its outbound
  links stay up (asymmetric partition), exercising the hardened reconnect
  path,
* ``storage_fault`` — arm one PR-14 storage fault (torn write, fsync lie,
  ENOSPC…) on a replica's WAL through its control socket.

:class:`ProcessChaosSchedule` draws these from a seeded RNG so a soak run
is replayable: same seed, same victim sequence.  All state transitions go
through the launcher, which is the single holder of process handles.
"""

from __future__ import annotations

import logging
import random
from typing import Optional

logger = logging.getLogger("consensus_tpu.deploy")

#: The process-chaos vocabulary, weighted roughly by how often the
#: corresponding outage shape occurs in the wild (crashes dominate).
DEFAULT_ACTION_WEIGHTS = {
    "kill9_leader": 3,
    "kill9_follower": 4,
    "kill9_sidecar": 2,
    "freeze": 2,
    "listener_drop": 2,
    "storage_fault": 2,
    # Adversarial wire battery (testing/adversary.py) against one
    # follower's comm listener.  Weight 0 by default: step() filters
    # zero-weight actions, so existing seeded soak schedules replay
    # byte-identically; chaos_sweep --adversarial-net (and soaks that
    # opt in) raise it.
    "net_abuse": 0,
}

#: PR-14 storage fault classes safe to arm while a replica keeps running
#: (the injector self-heals after ``count`` operations).
STORAGE_FAULT_KINDS = ("bit_flip", "torn_mid", "fsync_lie", "slow_fsync")


class ProcessChaosSchedule:
    """Seeded sequence of process-chaos actions against a launcher.

    ``step()`` performs one action and returns a record of what it did;
    transient states (freeze, listener drop) are healed on the *next*
    step so the cluster is never left wedged by the schedule itself.
    """

    def __init__(
        self,
        launcher,
        *,
        seed: int = 0,
        weights: Optional[dict] = None,
        freeze_only_followers: bool = True,
    ) -> None:
        self.launcher = launcher
        self.rng = random.Random(seed)
        self.weights = dict(weights or DEFAULT_ACTION_WEIGHTS)
        self.freeze_only_followers = freeze_only_followers
        self.history: list = []
        #: Pending heals (callables) applied at the start of the next step.
        self._pending_heals: list = []

    # ------------------------------------------------------------ victims

    def _replica_ids(self) -> list:
        return sorted(self.launcher.replicas)

    def _pick_follower(self) -> Optional[int]:
        leader = self.launcher.leader_id()
        followers = [i for i in self._replica_ids() if i != leader]
        return self.rng.choice(followers) if followers else None

    # ------------------------------------------------------------ actions

    def _heal_pending(self) -> None:
        heals, self._pending_heals = self._pending_heals, []
        for heal in heals:
            try:
                heal()
            except Exception:
                logger.exception("chaos heal failed")

    def step(self) -> dict:
        """Heal last step's transient state, then perform one action."""
        self._heal_pending()
        actions = [a for a in self.weights if self.weights[a] > 0]
        if not self.launcher.sidecars:
            actions = [a for a in actions if a != "kill9_sidecar"]
        action = self.rng.choices(
            actions, weights=[self.weights[a] for a in actions]
        )[0]
        record = {"action": action, "target": None}

        if action == "kill9_leader":
            leader = self.launcher.leader_id()
            if leader is not None and leader in self.launcher.replicas:
                self.launcher.kill_replica(leader)
                record["target"] = leader
        elif action == "kill9_follower":
            victim = self._pick_follower()
            if victim is not None:
                self.launcher.kill_replica(victim)
                record["target"] = victim
        elif action == "kill9_sidecar":
            sids = sorted(self.launcher.sidecars)
            if sids:
                victim = self.rng.choice(sids)
                self.launcher.kill_sidecar(victim)
                record["target"] = victim
        elif action == "freeze":
            victim = (
                self._pick_follower()
                if self.freeze_only_followers
                else self.rng.choice(self._replica_ids())
            )
            if victim is not None:
                self.launcher.freeze_replica(victim)
                record["target"] = victim
                self._pending_heals.append(
                    lambda v=victim: self.launcher.thaw_replica(v)
                )
        elif action == "listener_drop":
            victim = self._pick_follower()
            if victim is not None:
                self.launcher.drop_listener(victim)
                record["target"] = victim
                self._pending_heals.append(
                    lambda v=victim: self.launcher.restore_listener(v)
                )
        elif action == "storage_fault":
            victim = self.rng.choice(self._replica_ids())
            kind = self.rng.choice(STORAGE_FAULT_KINDS)
            self.launcher.arm_storage_fault(victim, kind, count=1)
            record["target"] = victim
            record["kind"] = kind
        elif action == "net_abuse":
            # Real-socket byzantine battery against one follower's comm
            # listener: the hardened guard must shed it (strikes, quota
            # rejections, at most a temporary ban of this host's address)
            # while the soak's liveness probes keep passing.  Nothing to
            # heal — batteries self-terminate and bans expire.
            from consensus_tpu.testing.adversary import AdversarialPeer

            victim = self._pick_follower()
            if victim is not None:
                addr = self.launcher.spec.comm_addresses()[victim]
                peer = AdversarialPeer(addr, "comm")
                provoked = {}
                for name in ("oversized_length", "wrong_hmac_flood"):
                    try:
                        for k, v in getattr(peer, name)(1).items():
                            provoked[k] = provoked.get(k, 0) + v
                    except OSError:
                        pass  # victim mid-restart: the battery found no ear
                record["target"] = victim
                record["provoked"] = provoked

        self.history.append(record)
        logger.info("chaos: %s -> %s", action, record.get("target"))
        return record

    def quiesce(self) -> None:
        """Heal all transient states (end-of-run cleanup)."""
        self._heal_pending()


__all__ = [
    "ProcessChaosSchedule",
    "DEFAULT_ACTION_WEIGHTS",
    "STORAGE_FAULT_KINDS",
]
