"""Leader blacklist maintenance.

A deterministic function of committed metadata, so every replica computes the
same blacklist: leaders that were skipped over by view changes get
blacklisted; blacklisted nodes observed sending prepares by more than ``f``
commit-signers get redeemed; the list is capped at ``f`` (oldest evicted).

Parity: reference internal/bft/util.go:436-548 (blacklist.computeUpdate,
pruneBlacklist); follower-side validation lives in the view
(reference internal/bft/view.go:649-716).
"""

from __future__ import annotations

from typing import Mapping, Sequence

from consensus_tpu.utils.leader import get_leader_id


def prune_blacklist(
    prev_blacklist: Sequence[int],
    prepares_from: Mapping[int, Sequence[int]],
    f: int,
    nodes: Sequence[int],
) -> list[int]:
    """Drop blacklist entries that no longer deserve it.

    ``prepares_from`` maps a commit-signer id to the list of node ids it
    attested to have received prepares from (carried in the auxiliary signed
    payload of commit signatures).  A blacklisted node vouched for by more
    than ``f`` distinct signers is redeemed (each signer counts once, however
    many times it repeats a node in its vouch list); nodes removed from
    membership are purged unconditionally.
    """
    if not prev_blacklist:
        return []

    member = frozenset(nodes)
    ack_count: dict[int, int] = {}
    for _, vouched in prepares_from.items():
        for prepare_sender in set(vouched):
            ack_count[prepare_sender] = ack_count.get(prepare_sender, 0) + 1

    kept: list[int] = []
    for node in prev_blacklist:
        if node not in member:
            continue  # removed by reconfiguration
        if ack_count.get(node, 0) > f:
            continue  # redeemed: observed alive by > f signers
        kept.append(node)
    return kept


def compute_blacklist_update(
    *,
    prev_view: int,
    prev_seq: int,
    prev_decisions_in_view: int,
    prev_blacklist: Sequence[int],
    current_view: int,
    current_leader: int,
    n: int,
    f: int,
    nodes: Sequence[int],
    leader_rotation: bool,
    decisions_per_leader: int,
    prepares_from: Mapping[int, Sequence[int]],
) -> list[int]:
    """Compute the blacklist to stamp into the next proposal's metadata.

    If the view advanced since the previous committed proposal, every leader
    of a skipped view (computed exactly as followers would) is blacklisted —
    it failed to drive a proposal.  If the view is unchanged, redemption
    pruning applies instead.  The result is capped at ``f`` entries by
    evicting the oldest.
    """
    updated = list(prev_blacklist)

    if prev_view != current_view:
        # Leadership moved via view change(s): blacklist each skipped leader.
        # For any proposal after the first in a view, the would-have-been
        # leader is computed one decision past the last committed one.
        offset = 0 if prev_seq == 0 else 1
        for skipped_view in range(prev_view, current_view):
            leader = get_leader_id(
                skipped_view,
                n,
                nodes,
                leader_rotation=leader_rotation,
                decisions_in_view=prev_decisions_in_view + offset,
                decisions_per_leader=decisions_per_leader,
                blacklist=prev_blacklist,
            )
            if leader == current_leader:
                continue  # never blacklist the node now driving progress
            updated.append(leader)
    else:
        updated = prune_blacklist(updated, prepares_from, f, nodes)

    while len(updated) > f:
        updated.pop(0)
    return updated


__all__ = ["prune_blacklist", "compute_blacklist_update"]
