"""Pure protocol math: quorum sizes, leader selection, blacklist maintenance,
vote bookkeeping, and deterministic digests.  No I/O, no clocks — everything
here is table-testable.
"""

from consensus_tpu.utils.quorum import compute_quorum  # noqa: F401
from consensus_tpu.utils.leader import get_leader_id  # noqa: F401
from consensus_tpu.utils.blacklist import (  # noqa: F401
    compute_blacklist_update,
    prune_blacklist,
)
from consensus_tpu.utils.digests import commit_signatures_digest  # noqa: F401
