"""Deterministic digests binding signature sets into proposal metadata.

Parity: reference internal/bft/util.go:564-586 (CommitSignaturesDigest,
ASN.1 + SHA-256 there; here a length-prefixed encoding + SHA-256 — the wire
is ours, only the binding property matters: the digest commits to the exact
ordered (signer, value, msg) triples).
"""

from __future__ import annotations

import hashlib
import struct
from typing import Sequence

from consensus_tpu.types import Signature


def commit_signatures_digest(sigs: Sequence[Signature]) -> bytes:
    """Digest of an ordered list of commit signatures; empty input -> b''.

    A half-aggregated ``types.QuorumCert`` (duck-typed via its ``s_agg``
    attribute) is bound through its component view — ordered
    (signer, R, aux) triples — PLUS the aggregate scalar, so two certs over
    the same components but different ``s_agg`` bytes digest differently.
    """
    if not sigs:
        return b""
    h = hashlib.sha256()
    for sig in sigs:
        h.update(struct.pack(">Q", sig.id))
        h.update(struct.pack(">Q", len(sig.value)))
        h.update(sig.value)
        h.update(struct.pack(">Q", len(sig.msg)))
        h.update(sig.msg)
    s_agg = getattr(sigs, "s_agg", None)
    if s_agg is not None:
        h.update(b"\x00s_agg")
        h.update(struct.pack(">Q", len(s_agg)))
        h.update(s_agg)
    return h.digest()


__all__ = ["commit_signatures_digest"]
