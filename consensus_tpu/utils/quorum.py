"""BFT quorum arithmetic.

Parity: reference internal/bft/util.go:166-187 (computeQuorum).
"""

from __future__ import annotations

import math


def compute_quorum(n: int) -> tuple[int, int]:
    """Return ``(q, f)`` for a cluster of ``n`` replicas.

    ``f`` is the maximum number of Byzantine faults tolerated
    (``f = argmax(n >= 3f+1)``), and ``q`` is the smallest quorum size such
    that any two quorums intersect in at least ``f + 1`` replicas:
    ``q = ceil((n + f + 1) / 2)``.
    """
    if n <= 0:
        raise ValueError("cluster size must be positive")
    f = (n - 1) // 3
    q = int(math.ceil((n + f + 1) / 2.0))
    return q, f


__all__ = ["compute_quorum"]
