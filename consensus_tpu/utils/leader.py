"""Deterministic leader selection, with optional rotation and blacklist skip.

Parity: reference internal/bft/util.go:79-107 (getLeaderID).
"""

from __future__ import annotations

from typing import Sequence


def get_leader_id(
    view: int,
    n: int,
    nodes: Sequence[int],
    *,
    leader_rotation: bool = False,
    decisions_in_view: int = 0,
    decisions_per_leader: int = 1,
    blacklist: Sequence[int] = (),
) -> int:
    """Return the leader for ``view`` given the (sorted) node list.

    Without rotation the leader is static per view: ``nodes[view % n]``.
    With rotation, leadership additionally advances every
    ``decisions_per_leader`` decisions inside the view, and blacklisted
    nodes are skipped (scanning forward around the ring).
    """
    if not leader_rotation:
        return nodes[view % n]

    banned = frozenset(blacklist)
    base = view + decisions_in_view // decisions_per_leader
    for hop in range(len(nodes)):
        candidate = nodes[(base + hop) % n]
        if candidate not in banned:
            return candidate
    raise RuntimeError(f"all {len(nodes)} nodes are blacklisted")


__all__ = ["get_leader_id"]
