"""Vote bookkeeping: dedup-by-sender vote sets and next-view tracking.

Parity: reference internal/bft/util.go:109-163 (voteSet, nextViews).  Unlike
the reference (which buffers votes on a channel consumed by a goroutine),
votes here are plain lists inspected synchronously by the owning state
machine — the runtime is single-threaded per replica.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Optional


@dataclass
class Vote:
    sender: int
    msg: Any


class VoteSet:
    """Collects at most one vote per sender, subject to a validity predicate."""

    def __init__(self, valid_vote: Optional[Callable[[int, Any], bool]] = None):
        self._valid = valid_vote or (lambda sender, msg: True)
        self.voted: set[int] = set()
        self.votes: list[Vote] = []

    def clear(self) -> None:
        self.voted.clear()
        self.votes.clear()

    def register(self, sender: int, msg: Any) -> bool:
        """Record the vote; returns True if it was fresh and valid."""
        if sender in self.voted or not self._valid(sender, msg):
            return False
        self.voted.add(sender)
        self.votes.append(Vote(sender, msg))
        return True

    def __len__(self) -> int:
        return len(self.votes)


class NextViews:
    """Tracks the highest next-view each sender announced (view-change help)."""

    def __init__(self) -> None:
        self._next: dict[int, int] = {}

    def clear(self) -> None:
        self._next.clear()

    def register(self, next_view: int, sender: int) -> None:
        if next_view > self._next.get(sender, 0):
            self._next[sender] = next_view

    def matches(self, next_view: int, sender: int) -> bool:
        return self._next.get(sender, 0) == next_view


__all__ = ["Vote", "VoteSet", "NextViews"]
