"""Ingress hardening ahead of pool insertion: rate limiting + dedup.

Two layers, both deterministic functions of the injected clock:

* :class:`TokenBucket` — per-client refill at ``rate`` tokens per
  sim-second up to ``burst``; a client inside its budget is never touched
  by any other client's traffic (the non-censorship argument,
  SAFETY.md §11).
* :class:`DedupCache` — bounded LRU over ``RequestInfo.key()`` (client id
  AND request id — a flooding client cannot pre-insert another client's
  future request ids, so dedup can absorb retry storms without giving
  anyone a censorship lever).

:class:`AdmissionController` composes them — dedup FIRST, so a client's
own retries don't drain its token budget — and triple-books every decision
the established way: pinned ``ingress_*`` counters
(:data:`~consensus_tpu.metrics.PINNED_METRIC_KEYS`), ``ingress.<outcome>``
trace instants, and cumulative stats the obs detectors
(``admission_overload`` / ``dedup_storm``) read through health snapshots.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Optional

from consensus_tpu.types import RequestInfo

#: The three admission outcomes, in the order summaries report them.
ADMISSION_OUTCOMES = ("admitted", "rate_limited", "duplicate")


class TokenBucket:
    """Classic token bucket on an injected clock (no wall-clock reads)."""

    __slots__ = ("rate", "burst", "tokens", "_last")

    def __init__(self, rate: float, burst: float) -> None:
        if rate <= 0 or burst < 1:
            raise ValueError("token bucket needs rate > 0 and burst >= 1")
        self.rate = rate
        self.burst = burst
        self.tokens = burst
        self._last: Optional[float] = None

    def allow(self, now: float, cost: float = 1.0) -> bool:
        if self._last is not None and now > self._last:
            self.tokens = min(
                self.burst, self.tokens + (now - self._last) * self.rate
            )
        self._last = max(now, self._last or now)
        if self.tokens >= cost:
            self.tokens -= cost
            return True
        return False


class DedupCache:
    """Bounded seen-request LRU keyed on the FULL RequestInfo."""

    def __init__(self, capacity: int) -> None:
        if capacity < 1:
            raise ValueError("dedup capacity must be >= 1")
        self.capacity = capacity
        self._seen: OrderedDict[str, None] = OrderedDict()

    def seen(self, info: RequestInfo) -> bool:
        """True if ``info`` was already admitted recently; records it (and
        refreshes its recency) either way."""
        key = info.key()
        hit = key in self._seen
        if hit:
            self._seen.move_to_end(key)
        else:
            self._seen[key] = None
            while len(self._seen) > self.capacity:
                self._seen.popitem(last=False)
        return hit

    def __len__(self) -> int:
        return len(self._seen)


class AdmissionController:
    """Per-client token buckets + one shared dedup cache.

    ``rate``/``burst`` apply per client id (buckets are created lazily);
    ``dedup_capacity`` bounds the shared LRU.  ``metrics`` is a
    :class:`~consensus_tpu.metrics.MetricsIngress` bundle (or None).
    """

    def __init__(
        self,
        *,
        rate: float = 2.0,
        burst: float = 4.0,
        dedup_capacity: int = 65536,
        metrics=None,
        tracer=None,
    ) -> None:
        self.rate = rate
        self.burst = burst
        self.dedup = DedupCache(dedup_capacity)
        self.metrics = metrics
        self.tracer = tracer
        self._buckets: dict[str, TokenBucket] = {}
        self.offered = 0
        self.admitted = 0
        self.rate_limited = 0
        self.dedup_hits = 0

    def bucket(self, client_id: str) -> TokenBucket:
        b = self._buckets.get(client_id)
        if b is None:
            b = self._buckets[client_id] = TokenBucket(self.rate, self.burst)
        return b

    def admit(self, now: float, info: RequestInfo, size: int = 1) -> str:
        """One admission decision: ``"admitted"`` / ``"rate_limited"`` /
        ``"duplicate"``.  Dedup runs BEFORE the bucket so a client's own
        retry storm is absorbed without draining its token budget."""
        self.offered += 1
        if self.dedup.seen(info):
            self.dedup_hits += 1
            outcome = "duplicate"
        elif not self.bucket(info.client_id).allow(now):
            self.rate_limited += 1
            outcome = "rate_limited"
        else:
            self.admitted += 1
            outcome = "admitted"
        m = self.metrics
        if m is not None:
            m.count_offered.add(1)
            if outcome == "admitted":
                m.count_admitted.add(1)
            elif outcome == "rate_limited":
                m.count_rate_limited.add(1)
            else:
                m.count_dedup_hits.add(1)
        tracer = self.tracer
        if tracer is not None and tracer.enabled:
            tracer.instant(
                "ingress", f"ingress.{outcome}",
                client=info.client_id, request=info.request_id, size=size,
            )
        return outcome

    def health(self) -> dict:
        """Cumulative ingress counters in the health-snapshot shape the
        ``admission_overload`` / ``dedup_storm`` detectors read (absent
        fields keep cluster-only samples silent)."""
        return {
            "running": True,
            "ingress_offered": self.offered,
            "ingress_admitted": self.admitted,
            "ingress_rate_limited": self.rate_limited,
            "ingress_dedup_hits": self.dedup_hits,
        }


__all__ = [
    "ADMISSION_OUTCOMES",
    "AdmissionController",
    "DedupCache",
    "TokenBucket",
]
