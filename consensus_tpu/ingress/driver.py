"""Open-loop trace replay against an admission layer + sidecar fleet.

OPEN-LOOP means arrivals are scheduled from the trace alone — a slow or
rejecting fleet never back-pressures the arrival process, which is exactly
how a million independent clients behave (they do not politely wait for
each other's completions).  Closed-loop load generators hide collapse;
this driver is built to expose it: it records offered vs admitted vs
committed load separately, plus commit-latency percentiles on the sim
clock, and feeds per-sample ingress health to the obs
:class:`~consensus_tpu.obs.detectors.DetectorBank` so
``admission_overload`` and ``dedup_storm`` fire on the same edge-triggered
contract as the cluster detectors.

Two fleet backends:

* :class:`SimSidecarFleet` — N simulated verify servers on the shared
  SimScheduler (bounded queues, deterministic service times).  The whole
  replay is a pure function of (trace, config): ``summary_json()`` is
  byte-identical per seed.
* a real :class:`~consensus_tpu.net.sidecar.VerifySidecarServer` fleet —
  reached through :class:`~consensus_tpu.ingress.placement.SidecarFleet`
  and the client's structured reroute path; exercised by the integration
  tests rather than this driver (real sockets live on wall-clock threads).
"""

from __future__ import annotations

import json
from typing import Optional

from consensus_tpu.ingress.admission import AdmissionController
from consensus_tpu.ingress.placement import PlacementRing
from consensus_tpu.ingress.workload import TraceEvent, WorkloadSpec
from consensus_tpu.metrics import InMemoryProvider, Metrics
from consensus_tpu.obs.detectors import DetectorBank, DetectorThresholds
from consensus_tpu.runtime.scheduler import SimScheduler


def _percentile(sorted_values: list, q: float) -> float:
    """Nearest-rank percentile over an already-sorted list (0 when empty)."""
    if not sorted_values:
        return 0.0
    rank = max(0, min(len(sorted_values) - 1,
                      int(round(q * (len(sorted_values) - 1)))))
    return sorted_values[rank]


class _SimServer:
    """One simulated verify server: a bounded FIFO with deterministic
    service times on the shared sim clock."""

    __slots__ = ("server_id", "depth", "busy_until", "accepted", "rejected")

    def __init__(self, server_id: str) -> None:
        self.server_id = server_id
        self.depth = 0
        self.busy_until = 0.0
        self.accepted = 0
        self.rejected = 0


class SimSidecarFleet:
    """N simulated sidecar servers behind rendezvous placement.

    ``service_rate`` is requests per sim-second per server at the reference
    size; larger requests take proportionally longer
    (``(1 + size/4096) / service_rate``).  ``queue_limit`` bounds each
    server's backlog — an enqueue past it is a structured admission reject,
    the sim twin of the real server's status-2
    ``TenantAdmissionReject``."""

    def __init__(
        self,
        scheduler: SimScheduler,
        server_ids,
        *,
        service_rate: float = 2000.0,
        queue_limit: int = 512,
    ) -> None:
        if len(server_ids) < 1:
            raise ValueError("fleet needs at least one server")
        self.scheduler = scheduler
        self.service_rate = service_rate
        self.queue_limit = queue_limit
        self.servers = {sid: _SimServer(sid) for sid in server_ids}

    def try_enqueue(self, server_id: str, event: TraceEvent, on_done) -> bool:
        """False = structured reject (queue full); True = accepted, with
        ``on_done(event, commit_time)`` scheduled at service completion."""
        srv = self.servers[server_id]
        if srv.depth >= self.queue_limit:
            srv.rejected += 1
            return False
        now = self.scheduler.now()
        service = (1.0 + event.size / 4096.0) / self.service_rate
        start = max(now, srv.busy_until)
        srv.busy_until = start + service
        srv.depth += 1
        srv.accepted += 1
        done_at = srv.busy_until

        def complete() -> None:
            srv.depth -= 1
            on_done(event, done_at)

        self.scheduler.call_later(
            done_at - now, complete, name=f"ingress svc {server_id}"
        )
        return True

    def total_depth(self) -> int:
        return sum(s.depth for s in self.servers.values())


class IngressDriver:
    """Replays one trace open-loop and reports the ledgered truth."""

    #: Sim-time allowed after the last arrival for queues to drain.
    DRAIN_BUDGET = 30.0

    def __init__(
        self,
        trace,
        spec: WorkloadSpec,
        *,
        seed: int = 0,
        servers: int = 4,
        scheduler: Optional[SimScheduler] = None,
        metrics: Optional[Metrics] = None,
        tracer=None,
        thresholds: Optional[DetectorThresholds] = None,
        sample_interval: float = 1.0,
        service_rate: float = 2000.0,
        queue_limit: int = 512,
        groups: int = 0,
    ) -> None:
        if servers < 1:
            raise ValueError("driver needs at least one fleet server")
        self.trace = tuple(trace)
        self.spec = spec
        self.seed = seed
        self.scheduler = scheduler or SimScheduler()
        self.metrics = metrics or Metrics(InMemoryProvider())
        self.tracer = tracer
        self.sample_interval = sample_interval
        self.server_ids = tuple(f"sidecar-{i}" for i in range(servers))
        self.ring = PlacementRing(self.server_ids)
        self.fleet = SimSidecarFleet(
            self.scheduler, self.server_ids,
            service_rate=service_rate, queue_limit=queue_limit,
        )
        self.admission = AdmissionController(
            rate=spec.admission_rate, burst=spec.admission_burst,
            metrics=self.metrics.ingress, tracer=tracer,
        )
        #: ``groups >= 1`` turns on consensus sharding: every ADMITTED
        #: request is also routed to its owning consensus group
        #: (admit-then-route — admission stays global so a flooder cannot
        #: escape its budget by hashing into a quiet group).  Off by
        #: default; summaries without groups stay byte-identical.
        self.group_router = None
        if groups:
            from consensus_tpu.groups.directory import GroupDirectory
            from consensus_tpu.groups.router import GroupRouter

            self.group_router = GroupRouter(
                GroupDirectory.of_size(groups),
                metrics=self.metrics.groups,
                tracer=tracer,
            )
        self.detectors = DetectorBank(thresholds)
        self.anomalies: list = []
        self.offered_honest = 0
        self.admitted_honest = 0
        self.committed = 0
        self.committed_honest = 0
        self.fleet_rejected = 0
        self.reroutes = 0
        self._latencies: list[float] = []
        self.metrics.ingress.fleet_size.set(float(servers))

    # -- per-event flow ----------------------------------------------------

    def _on_done(self, event: TraceEvent, commit_time: float) -> None:
        self.committed += 1
        if event.honest:
            self.committed_honest += 1
        latency = commit_time - event.t
        self._latencies.append(latency)
        self.metrics.ingress.commit_latency.observe(latency)

    def _arrive(self, event: TraceEvent) -> None:
        now = self.scheduler.now()
        if event.honest:
            self.offered_honest += 1
        outcome = self.admission.admit(now, event.info(), event.size)
        if outcome != "admitted":
            return
        if event.honest:
            self.admitted_honest += 1
        if self.group_router is not None:
            self.group_router.route(event.tenant)
        hops = 0
        for server_id in self.ring.candidates(event.tenant):
            if self.fleet.try_enqueue(server_id, event, self._on_done):
                if hops:
                    self.reroutes += hops
                    self.metrics.ingress.count_reroutes.add(hops)
                    tracer = self.tracer
                    if tracer is not None and tracer.enabled:
                        tracer.instant(
                            "ingress", "ingress.reroute",
                            tenant=event.tenant, dst=server_id, hops=hops,
                        )
                return
            hops += 1
        self.fleet_rejected += 1

    # -- sampling ----------------------------------------------------------

    def _sample(self) -> None:
        t = self.scheduler.now()
        health = dict(self.admission.health())
        health["ingress_fleet_depth"] = self.fleet.total_depth()
        for anomaly in self.detectors.evaluate(t, {0: health}):
            self.anomalies.append(anomaly)
            self.metrics.obs.anomaly_counter(anomaly.kind).add(1)
            tracer = self.tracer
            if tracer is not None and tracer.enabled:
                tracer.instant(
                    "obs", "obs.anomaly",
                    kind=anomaly.kind, node=anomaly.node,
                    detail=anomaly.detail,
                )

    # -- the run -----------------------------------------------------------

    def run(self) -> dict:
        sched = self.scheduler
        start = sched.now()
        for ev in self.trace:
            sched.call_later(
                max(0.0, start + ev.t - sched.now()),
                lambda e=ev: self._arrive(e),
                name="ingress arrival",
            )
        horizon = self.spec.duration + self.DRAIN_BUDGET
        ticks = int(horizon / self.sample_interval) + 1
        for i in range(1, ticks + 1):
            sched.call_later(
                i * self.sample_interval, self._sample, name="ingress sample"
            )
        sched.advance(horizon + self.sample_interval)
        return self.summary()

    def summary(self) -> dict:
        lat = sorted(self._latencies)
        counts: dict[str, int] = {}
        for a in self.anomalies:
            counts[a.kind] = counts.get(a.kind, 0) + 1
        adm = self.admission
        out = {
            "seed": self.seed,
            "clients": self.spec.clients,
            "servers": len(self.server_ids),
            "events": len(self.trace),
            "duration": self.spec.duration,
            "offered": adm.offered,
            "admitted": adm.admitted,
            "rate_limited": adm.rate_limited,
            "dedup_hits": adm.dedup_hits,
            "offered_honest": self.offered_honest,
            "admitted_honest": self.admitted_honest,
            "committed": self.committed,
            "committed_honest": self.committed_honest,
            "fleet_rejected": self.fleet_rejected,
            "reroutes": self.reroutes,
            "latency_p50": round(_percentile(lat, 0.50), 9),
            "latency_p90": round(_percentile(lat, 0.90), 9),
            "latency_p99": round(_percentile(lat, 0.99), 9),
            "anomalies": dict(sorted(counts.items())),
        }
        if self.group_router is not None:
            # Keys appear ONLY in groups mode so a non-sharded summary is
            # byte-identical to every pre-sharding run of the same seed.
            out["groups"] = len(self.group_router.directory)
            out["group_routed"] = self.group_router.counts()
        return out

    def summary_json(self) -> str:
        """Sorted-key JSON — the byte-identical same-seed artifact."""
        return json.dumps(self.summary(), sort_keys=True)


__all__ = ["IngressDriver", "SimSidecarFleet"]
