"""Ingress plane: the demand side of "millions of users".

The verification plane (multi-tenant sidecar, fused on-device engines) is
fast; this package models and hardens the path that feeds it:

* :mod:`consensus_tpu.ingress.workload` — deterministic, seed-pure client
  traces (heavy-tailed sizes, Poisson/bursty arrivals, diurnal ramps,
  hot-tenant skew, duplicate-retry storms) anchored to the sim clock.
* :mod:`consensus_tpu.ingress.admission` — per-client token-bucket rate
  limiting plus a bounded dedup LRU keyed on the full
  :class:`~consensus_tpu.types.RequestInfo`, ahead of pool insertion.
* :mod:`consensus_tpu.ingress.placement` — consistent-hash (rendezvous)
  tenant→sidecar placement over a horizontally scaled verifier fleet,
  with deterministic ~1/N remap on server join/leave.
* :mod:`consensus_tpu.ingress.driver` — an OPEN-LOOP trace replayer
  (arrivals never gated on completions) recording offered vs admitted vs
  committed load and latency percentiles, byte-identical per seed.

Everything runs on the injected scheduler clock — no wall-clock reads
(scripts/check_no_wallclock.py walks this tree; tests/test_no_wallclock.py
pins the coverage).
"""

from consensus_tpu.ingress.admission import (
    AdmissionController,
    DedupCache,
    TokenBucket,
)
from consensus_tpu.ingress.driver import IngressDriver, SimSidecarFleet
from consensus_tpu.ingress.placement import PlacementRing, SidecarFleet
from consensus_tpu.ingress.workload import (
    TraceEvent,
    WorkloadSpec,
    clean_spec,
    duplicate_storm_spec,
    flood_spec,
    generate_trace,
)

__all__ = [
    "AdmissionController",
    "DedupCache",
    "IngressDriver",
    "PlacementRing",
    "SidecarFleet",
    "SimSidecarFleet",
    "TokenBucket",
    "TraceEvent",
    "WorkloadSpec",
    "clean_spec",
    "duplicate_storm_spec",
    "flood_spec",
    "generate_trace",
]
