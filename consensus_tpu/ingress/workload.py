"""Seeded, open-loop client traces on the sim clock.

A trace is generated UP FRONT from one ``random.Random(seed)`` stream —
arrival times are absolute sim-times, so replaying the same seed yields a
byte-identical event sequence no matter how the consumer schedules it
(the same discipline as :class:`~consensus_tpu.testing.chaos.ChaosSchedule`).

The population splits into HONEST clients and FLOOD clients:

* honest clients pace themselves inside the admission budget by
  construction — inter-arrival gaps are drawn uniform and never shorter
  than ``1 / (admission_rate * honest_rate)`` with ``honest_rate <= 1``,
  so a per-client token bucket refilling at ``admission_rate`` can never
  reject them.  That makes "admitted-honest == offered-honest" a testable
  non-starvation claim, not a tautology.
* flood clients offer a Poisson stream at ``flood_rate_x`` times the
  admission rate, optionally diurnally modulated (thinning against the
  peak rate), bursty (geometric back-to-back clumps), and tenant-skewed
  (a ``hot_tenant_bias`` fraction of flood arrivals pile onto tenant 0).

Duplicate-retry storms re-emit ALREADY-SENT flood requests
(``duplicate=True``) inside configured windows — the dedup cache's load,
distinct from fresh-request floods which are the token bucket's load.

Request sizes are heavy-tailed (bounded Pareto) for everyone.
"""

from __future__ import annotations

import dataclasses
import math
import random
from typing import Iterable

from consensus_tpu.types import RequestInfo


@dataclasses.dataclass(frozen=True)
class TraceEvent:
    """One open-loop arrival, anchored to the sim clock."""

    t: float
    client: str
    tenant: str
    rid: int
    size: int
    honest: bool
    duplicate: bool = False

    def info(self) -> RequestInfo:
        return RequestInfo(client_id=self.client, request_id=str(self.rid))


@dataclasses.dataclass(frozen=True)
class WorkloadSpec:
    """Trace-shape knobs; every field is deterministic input to the
    generator (no knob consults the clock or ambient RNG)."""

    clients: int = 1000
    tenants: int = 8
    duration: float = 30.0
    #: Reference admission budget, tokens per client per sim-second — the
    #: spec travels with the trace so driver and admission agree on it.
    admission_rate: float = 2.0
    admission_burst: float = 4.0
    #: Fraction of clients that are honest (paced inside the budget).
    honest_fraction: float = 0.9
    #: Honest offered rate as a fraction of ``admission_rate`` (<= 1).
    honest_rate: float = 0.5
    #: Flood offered rate as a multiple of ``admission_rate``.
    flood_rate_x: float = 6.0
    #: Bounded-Pareto request sizes: min, tail exponent, cap.
    size_min: int = 64
    size_alpha: float = 1.3
    size_cap: int = 16384
    #: 0..1 peak-to-trough modulation of flood arrivals over ``duration``.
    diurnal_amplitude: float = 0.0
    #: Probability a flood arrival extends into a 2-5 event burst clump.
    burstiness: float = 0.0
    #: 0..1: fraction of flood arrivals redirected to tenant 0.
    hot_tenant_bias: float = 0.0
    #: Duplicate-retry storm windows: ((t0, t1, rate_x), ...) — inside
    #: [t0, t1) each flood client re-emits already-sent requests as a
    #: Poisson stream at ``rate_x * admission_rate``.
    duplicate_storms: tuple = ()

    def validate(self) -> None:
        errors = []
        if self.clients < 1:
            errors.append("clients must be >= 1")
        if self.tenants < 1:
            errors.append("tenants must be >= 1")
        if self.duration <= 0:
            errors.append("duration must be positive")
        if self.admission_rate <= 0 or self.admission_burst < 1:
            errors.append("admission_rate > 0 and admission_burst >= 1 required")
        if not 0.0 <= self.honest_fraction <= 1.0:
            errors.append("honest_fraction must be in [0, 1]")
        if not 0.0 < self.honest_rate <= 1.0:
            errors.append("honest_rate must be in (0, 1]")
        if self.flood_rate_x <= 0:
            errors.append("flood_rate_x must be positive")
        if self.size_min < 1 or self.size_cap < self.size_min:
            errors.append("size_min >= 1 and size_cap >= size_min required")
        if self.size_alpha <= 0:
            errors.append("size_alpha must be positive")
        if not 0.0 <= self.diurnal_amplitude <= 1.0:
            errors.append("diurnal_amplitude must be in [0, 1]")
        if not 0.0 <= self.burstiness <= 1.0:
            errors.append("burstiness must be in [0, 1]")
        if not 0.0 <= self.hot_tenant_bias <= 1.0:
            errors.append("hot_tenant_bias must be in [0, 1]")
        for storm in self.duplicate_storms:
            t0, t1, rate_x = storm
            if not (0.0 <= t0 < t1 <= self.duration) or rate_x <= 0:
                errors.append(f"bad duplicate storm window {storm!r}")
        if errors:
            raise ValueError("; ".join(errors))


def clean_spec(**overrides) -> WorkloadSpec:
    """All-honest soak: every detector must stay silent on this."""
    base = dict(honest_fraction=1.0, flood_rate_x=1.0)
    base.update(overrides)
    return WorkloadSpec(**base)


def flood_spec(**overrides) -> WorkloadSpec:
    """Admission-overload scenario: a flood cohort far past its budget."""
    base = dict(
        honest_fraction=0.7, flood_rate_x=10.0,
        burstiness=0.3, hot_tenant_bias=0.5,
    )
    base.update(overrides)
    return WorkloadSpec(**base)


def duplicate_storm_spec(duration: float = 30.0, **overrides) -> WorkloadSpec:
    """Dedup-storm scenario: retry storms across the middle of the run."""
    base = dict(
        duration=duration,
        honest_fraction=0.7,
        flood_rate_x=2.0,
        duplicate_storms=(
            (duration * 0.3, duration * 0.8, 8.0),
        ),
    )
    base.update(overrides)
    return WorkloadSpec(**base)


def _pareto_size(rng: random.Random, spec: WorkloadSpec) -> int:
    u = 1.0 - rng.random()  # (0, 1]
    size = spec.size_min * u ** (-1.0 / spec.size_alpha)
    return int(min(size, spec.size_cap))


def _diurnal_keep(rng: random.Random, spec: WorkloadSpec, t: float) -> bool:
    """Thinning against the peak: keep an arrival with probability
    rate(t)/peak where rate(t) rides one sine period over the duration."""
    if spec.diurnal_amplitude <= 0.0:
        return True
    phase = math.sin(2.0 * math.pi * t / spec.duration)
    keep = (1.0 + spec.diurnal_amplitude * phase) / (
        1.0 + spec.diurnal_amplitude
    )
    return rng.random() < keep


def generate_trace(
    seed: int, spec: WorkloadSpec | None = None
) -> tuple[TraceEvent, ...]:
    """The full trace for ``seed``, sorted by arrival time (ties break on
    client id then rid, so the order is total and replay-stable)."""
    spec = spec or WorkloadSpec()
    spec.validate()
    rng = random.Random(seed ^ 0x1264E55)
    n_honest = int(round(spec.clients * spec.honest_fraction))
    events: list[TraceEvent] = []
    #: Per flood client: rids already emitted (the storm's replay pool).
    flood_history: dict[str, list[int]] = {}

    for idx in range(spec.clients):
        honest = idx < n_honest
        client = f"{'h' if honest else 'f'}{idx:06d}"
        tenant_i = idx % spec.tenants
        if honest:
            # Paced inside the budget BY CONSTRUCTION: gap >= 1/rate of the
            # admission bucket, so honest traffic can never be rate-limited.
            client_rate = spec.admission_rate * spec.honest_rate
            t = rng.uniform(0.0, 1.0 / client_rate)
            rid = 0
            while t < spec.duration:
                events.append(TraceEvent(
                    t=t, client=client, tenant=f"t{tenant_i}", rid=rid,
                    size=_pareto_size(rng, spec), honest=True,
                ))
                rid += 1
                t += rng.uniform(1.0, 2.0) / client_rate
        else:
            lam = spec.admission_rate * spec.flood_rate_x
            history = flood_history[client] = []
            t = rng.expovariate(lam)
            rid = 0
            while t < spec.duration:
                if _diurnal_keep(rng, spec, t):
                    if (spec.hot_tenant_bias
                            and rng.random() < spec.hot_tenant_bias):
                        tenant = "t0"
                    else:
                        tenant = f"t{tenant_i}"
                    burst = 1
                    if spec.burstiness and rng.random() < spec.burstiness:
                        burst += rng.randrange(1, 5)
                    for b in range(burst):
                        bt = t + b * 1e-4
                        if bt >= spec.duration:
                            break
                        events.append(TraceEvent(
                            t=bt, client=client, tenant=tenant, rid=rid,
                            size=_pareto_size(rng, spec), honest=False,
                        ))
                        history.append(rid)
                        rid += 1
                t += rng.expovariate(lam)

    # Duplicate-retry storms: flood clients re-offer ALREADY-SENT rids.
    for (t0, t1, rate_x) in spec.duplicate_storms:
        lam = spec.admission_rate * rate_x
        for client in sorted(flood_history):
            history = flood_history[client]
            tenant_i = int(client[1:]) % spec.tenants
            t = t0 + rng.expovariate(lam)
            while t < t1:
                prior = [r for r in history if r is not None]
                if prior:
                    events.append(TraceEvent(
                        t=t, client=client, tenant=f"t{tenant_i}",
                        rid=rng.choice(prior),
                        size=_pareto_size(rng, spec),
                        honest=False, duplicate=True,
                    ))
                t += rng.expovariate(lam)

    events.sort(key=lambda e: (e.t, e.client, e.rid))
    return tuple(events)


def honest_counts(events: Iterable[TraceEvent]) -> tuple[int, int]:
    """(honest events, flood+duplicate events) — summary bookkeeping."""
    honest = flood = 0
    for ev in events:
        if ev.honest:
            honest += 1
        else:
            flood += 1
    return honest, flood


__all__ = [
    "TraceEvent",
    "WorkloadSpec",
    "clean_spec",
    "duplicate_storm_spec",
    "flood_spec",
    "generate_trace",
    "honest_counts",
]
