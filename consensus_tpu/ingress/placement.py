"""Consistent-hash tenant→sidecar placement over a horizontally scaled fleet.

Rendezvous (highest-random-weight) hashing: every (server, tenant) pair gets
a deterministic 64-bit score derived from SHA-256, and a tenant lives on the
highest-scoring server.  The property the fleet leans on: removing one
server moves ONLY the tenants whose top candidate was that server (~1/N of
them, exactly — every other tenant's ranking among the survivors is
untouched), and adding a server steals only the tenants it now outscores.
No ring state, no virtual-node tuning, no RNG — placement is a pure
function of the (server id, tenant id) strings, so every ingress process
computes the same map independently.

:class:`SidecarFleet` packages a ring over live
:class:`~consensus_tpu.net.sidecar.VerifySidecarServer` addresses with a
per-server client cache — the structured retry path
(``SidecarVerifierClient(fleet=...)``) walks ``candidates()`` order when a
fleet member answers with a ``TenantAdmissionReject``, bumping the pinned
``ingress_reroute_total`` counter through :meth:`SidecarFleet.on_reroute`.
"""

from __future__ import annotations

import hashlib
from typing import Callable, Iterable, Optional


def _score(server: str, tenant: str) -> int:
    """64-bit rendezvous weight for placing ``tenant`` on ``server``."""
    digest = hashlib.sha256(
        b"ctpu/ingress/placement/v1\x00"
        + server.encode() + b"\x00" + tenant.encode()
    ).digest()
    return int.from_bytes(digest[:8], "big")


class PlacementRing:
    """Rendezvous-hash placement over a mutable server set."""

    def __init__(self, servers: Iterable[str] = ()) -> None:
        self._servers: set[str] = set()
        for s in servers:
            self.add(s)

    def add(self, server: str) -> None:
        if not server:
            raise ValueError("server id must be non-empty")
        self._servers.add(server)

    def remove(self, server: str) -> None:
        self._servers.discard(server)

    def servers(self) -> tuple[str, ...]:
        return tuple(sorted(self._servers))

    def __len__(self) -> int:
        return len(self._servers)

    def candidates(self, tenant: str) -> list[str]:
        """Every server, best placement first.  Ties (astronomically
        unlikely) break on the server id so the order is total."""
        if not self._servers:
            raise ValueError("placement ring has no servers")
        return sorted(
            self._servers, key=lambda s: (-_score(s, tenant), s)
        )

    def assign(self, tenant: str) -> str:
        return self.candidates(tenant)[0]

    def assignment_map(self, tenants: Iterable[str]) -> dict[str, str]:
        """tenant -> server for a whole tenant population (the remap tests
        diff two of these across a join/leave)."""
        return {t: self.assign(t) for t in tenants}


class SidecarFleet:
    """A placement ring bound to concrete fleet addresses.

    ``client_factory(address)`` builds the transport used for rerouted
    batches (tests pass a factory closing over auth secrets); clients are
    cached per server id.  ``metrics`` is a
    :class:`~consensus_tpu.metrics.MetricsIngress` bundle (or None) —
    every reroute hop bumps the pinned ``ingress_reroute_total`` counter
    and, with a tracer attached, an ``ingress.reroute`` instant.
    """

    def __init__(
        self,
        addresses: dict[str, object],
        *,
        client_factory: Callable[[object], object],
        metrics=None,
        tracer=None,
    ) -> None:
        if not addresses:
            raise ValueError("fleet needs at least one server")
        self.ring = PlacementRing(addresses)
        self.addresses = dict(addresses)
        self._client_factory = client_factory
        self._clients: dict[str, object] = {}
        self.metrics = metrics
        self.tracer = tracer
        #: (tenant, from_server, to_server) reroute hops, in order.
        self.reroutes: list[tuple[str, str, str]] = []
        #: Servers currently answering with status 3 (their supervised
        #: engine is below its top rung).  Fed by
        #: :class:`~consensus_tpu.net.sidecar.SidecarVerifierClient` at
        #: response time; cleared by the first status-0 answer.
        self._degraded: set[str] = set()

    def candidates(self, tenant: Optional[str]) -> list[str]:
        """Rendezvous order, but NON-DEGRADED servers first: a degraded
        server still serves correct verdicts (its supervisor's host twin is
        ground truth), so it stays a candidate — just the last resort.  The
        sort is stable, so within each health class the deterministic ring
        order is preserved."""
        order = self.ring.candidates(tenant or "")
        if not self._degraded:
            return order
        return sorted(order, key=lambda s: s in self._degraded)

    def note_degraded(self, server_id: str, degraded: bool = True) -> None:
        """Record ``server_id``'s engine health as seen on the wire (the
        status byte of its last verify answer).  Unknown ids are accepted —
        health is an observation, not a membership operation."""
        if degraded:
            self._degraded.add(server_id)
        else:
            self._degraded.discard(server_id)

    def is_degraded(self, server_id: str) -> bool:
        return server_id in self._degraded

    def assign(self, tenant: Optional[str]) -> str:
        return self.ring.assign(tenant or "")

    def client_for(self, server_id: str):
        client = self._clients.get(server_id)
        if client is None:
            client = self._clients[server_id] = self._client_factory(
                self.addresses[server_id]
            )
        return client

    def on_reroute(
        self, tenant: Optional[str], from_id: str, to_id: str
    ) -> None:
        self.reroutes.append((tenant or "", from_id, to_id))
        if self.metrics is not None:
            self.metrics.count_reroutes.add(1)
        tracer = self.tracer
        if tracer is not None and tracer.enabled:
            tracer.instant(
                "ingress", "ingress.reroute",
                tenant=tenant or "", src=from_id, dst=to_id,
            )

    def close(self) -> None:
        for client in self._clients.values():
            close = getattr(client, "close", None)
            if close is not None:
                close()
        self._clients.clear()


__all__ = ["PlacementRing", "SidecarFleet"]
