"""TCP transport: a ``Comm`` implementation over real sockets.

The reference ships no in-tree transport — Fabric supplies a gRPC/mTLS
cluster service and the tests use channel maps (reference
pkg/api/dependencies.go:22-30, test/network.go).  This module provides the
socket transport piece: length-framed messages over TCP between replica
hosts (BFT traffic rides the datacenter network — DCN; ICI is for the
co-located accelerator, not inter-replica consensus).

Contract fidelity: ``Comm`` is *fire-and-forget, unordered, unreliable*
(the protocol tolerates loss).  Accordingly: sends never block the replica
loop (a bounded per-peer queue + writer thread), connection failures drop
messages silently and trigger lazy reconnection with backoff, and inbound
frames are posted onto the replica's scheduler (thread-safe with
``RealtimeScheduler``).

Identity: every connection opens with a HELLO frame that *pins* the peer id
for that connection; later frames claiming another sender kill the link.
With ``auth_secret`` set, the acceptor issues a fresh challenge nonce and
the HELLO carries an HMAC-SHA256 proof over it, so only live holders of the
cluster secret can claim an identity (observed handshakes don't replay).  This is connection-
level replica authentication, NOT transport encryption — for adversarial
networks, terminate TLS in front (stunnel/envoy) or swap in an mTLS
transport behind the same ``Comm`` port.  (Protocol-level safety does not
rest on the transport: consenter signatures are verified end-to-end.)

Frame: u32 length | u64 sender id | u8 kind (0 = consensus, 1 = request,
2 = hello) | payload (``wire.encode_message`` bytes, raw request bytes, or
the HELLO proof).
"""

from __future__ import annotations

import hashlib
import hmac
import logging
import os
import queue
import socket
import struct
import threading
from typing import Callable, Mapping, Optional, Sequence, Tuple

from consensus_tpu.api.deps import Comm
from consensus_tpu.wire import ConsensusMessage, decode_message, encode_message

logger = logging.getLogger("consensus_tpu.net")

_HEADER = struct.Struct(">IQB")
_KIND_CONSENSUS = 0
_KIND_REQUEST = 1
_KIND_HELLO = 2
_HELLO_CONTEXT = b"consensus-tpu/hello/v1"
_NONCE_BYTES = 16


def _hello_proof(secret: Optional[bytes], nonce: bytes, sender: int) -> bytes:
    """Per-connection proof: binds the cluster secret to the acceptor's
    fresh nonce, so observed handshakes cannot be replayed."""
    if not secret:
        return b""
    return hmac.new(
        secret, _HELLO_CONTEXT + nonce + struct.pack(">Q", sender), hashlib.sha256
    ).digest()
#: Frames larger than this are assumed corrupt and kill the connection.
MAX_FRAME_BYTES = 64 * 1024 * 1024


class TcpComm(Comm):
    """``Comm`` over TCP for one replica.

    ``on_message(sender, payload, is_request)`` is invoked from receiver
    threads — pass a function that posts into the replica scheduler (the
    ``Consensus`` facade's ``handle_message``/``handle_request`` already
    do).
    """

    def __init__(
        self,
        self_id: int,
        addresses: Mapping[int, Tuple[str, int]],
        on_message: Callable[[int, object, bool], None],
        *,
        send_queue_depth: int = 1000,
        reconnect_backoff: float = 0.5,
        connect_timeout: float = 2.0,
        auth_secret: Optional[bytes] = None,
        fault_plan=None,
    ) -> None:
        #: Optional testing FaultPlan (consensus_tpu/testing/faults.py):
        #: arms the net.send.io_error / net.recv.short_read seams below.
        #: A single ``is None`` check when unarmed.
        self.fault_plan = fault_plan
        self.self_id = self_id
        self._addresses = dict(addresses)
        self._on_message = on_message
        self._queue_depth = send_queue_depth
        self._backoff = reconnect_backoff
        self._connect_timeout = connect_timeout
        self._auth_secret = auth_secret
        # One-slot encode memo: broadcasts send the same message object to
        # n-1 peers back to back; encode it once (single-threaded caller).
        self._encode_memo: tuple[Optional[object], bytes] = (None, b"")
        self._peers: dict[int, "_Peer"] = {}
        self._listener: Optional[socket.socket] = None
        self._inbound: set[socket.socket] = set()
        self._inbound_lock = threading.Lock()
        self._stopped = threading.Event()

    # --- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        """Bind our listen address and spin up per-peer sender threads."""
        host, port = self._addresses[self.self_id]
        listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        listener.bind((host, port))
        listener.listen(16)
        self._listener = listener
        accept_thread = threading.Thread(
            target=self._accept_loop, name=f"comm-{self.self_id}-accept", daemon=True
        )
        accept_thread.start()
        for node_id, addr in self._addresses.items():
            if node_id == self.self_id:
                continue
            peer = _Peer(self, node_id, addr)
            self._peers[node_id] = peer
            peer.start()

    def stop(self) -> None:
        self._stopped.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        for peer in self._peers.values():
            peer.close()
        # Unblock receiver threads parked in recv() and stop late dispatches.
        with self._inbound_lock:
            inbound = list(self._inbound)
            self._inbound.clear()
        for conn in inbound:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass

    @property
    def bound_port(self) -> int:
        """The actual listen port (useful with port 0 = ephemeral)."""
        assert self._listener is not None
        return self._listener.getsockname()[1]

    # --- Comm port ---------------------------------------------------------

    def send_consensus(self, target_id: int, message: ConsensusMessage) -> None:
        memo_obj, memo_bytes = self._encode_memo
        if memo_obj is message:
            payload = memo_bytes
        else:
            payload = encode_message(message)
            self._encode_memo = (message, payload)
        self._send(target_id, _KIND_CONSENSUS, payload)

    def send_transaction(self, target_id: int, request: bytes) -> None:
        self._send(target_id, _KIND_REQUEST, bytes(request))

    def nodes(self) -> Sequence[int]:
        return sorted(self._addresses)

    def _send(self, target_id: int, kind: int, payload: bytes) -> None:
        peer = self._peers.get(target_id)
        if peer is None:
            return
        if len(payload) > MAX_FRAME_BYTES:
            # Enforced on the send side too: an oversized frame would be
            # killed by every receiver (poisoning the link), and > 2^32
            # would crash the header pack — both violate fire-and-forget.
            logger.warning(
                "%d: dropping oversized %d-byte frame to %d",
                self.self_id, len(payload), target_id,
            )
            return
        frame = _HEADER.pack(len(payload), self.self_id, kind) + payload
        peer.enqueue(frame)  # drops when the queue is full (unreliable contract)

    # --- inbound -----------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stopped.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                if self._stopped.is_set():
                    return
                # Transient accept failure (ECONNABORTED, fd pressure):
                # keep serving — a dead accept loop would silently
                # partition this replica on the receive side.
                logger.warning("%d: accept failed; retrying", self.self_id, exc_info=True)
                self._stopped.wait(0.05)
                continue
            with self._inbound_lock:
                if self._stopped.is_set():
                    conn.close()
                    return
                self._inbound.add(conn)
            threading.Thread(
                target=self._receive_loop,
                args=(conn,),
                name=f"comm-{self.self_id}-recv",
                daemon=True,
            ).start()

    def _receive_loop(self, conn: socket.socket) -> None:
        pinned_sender: Optional[int] = None
        # Challenge: a fresh nonce per connection (replay protection).
        nonce = os.urandom(_NONCE_BYTES)
        try:
            conn.sendall(_HEADER.pack(len(nonce), self.self_id, _KIND_HELLO) + nonce)
        except OSError:
            return
        try:
            while not self._stopped.is_set():
                plan = self.fault_plan
                if plan is not None and plan.trip("net.recv.short_read"):
                    # Simulate the link dying mid-frame: the finally block
                    # closes the connection exactly as a real short read
                    # below would; the sender reconnects lazily.
                    return
                header = _read_exact(conn, _HEADER.size)
                if header is None:
                    return
                length, sender, kind = _HEADER.unpack(header)
                if length > MAX_FRAME_BYTES:
                    logger.warning("oversized frame from %d; dropping link", sender)
                    return
                payload = _read_exact(conn, length)
                if payload is None:
                    return
                if pinned_sender is None:
                    # First frame must be the HELLO that pins this
                    # connection's identity (optionally HMAC-proven).
                    if kind != _KIND_HELLO:
                        logger.warning(
                            "%d: connection sent %d before HELLO; dropping link",
                            self.self_id, kind,
                        )
                        return
                    expected = _hello_proof(self._auth_secret, nonce, sender)
                    if not hmac.compare_digest(payload, expected):
                        logger.warning(
                            "%d: bad HELLO proof for claimed sender %d; dropping link",
                            self.self_id, sender,
                        )
                        return
                    pinned_sender = sender
                    continue
                if sender != pinned_sender:
                    logger.warning(
                        "%d: frame claims sender %d on connection pinned to %d; dropping link",
                        self.self_id, sender, pinned_sender,
                    )
                    return
                self._dispatch(sender, kind, payload)
        finally:
            with self._inbound_lock:
                self._inbound.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _dispatch(self, sender: int, kind: int, payload: bytes) -> None:
        if self._stopped.is_set():
            return
        try:
            if kind == _KIND_CONSENSUS:
                self._on_message(sender, decode_message(payload), False)
            elif kind == _KIND_REQUEST:
                self._on_message(sender, payload, True)
            else:
                logger.warning("unknown frame kind %d from %d", kind, sender)
        except Exception:
            # A malformed message must not kill the receive loop.
            logger.exception("failed dispatching frame from %d", sender)


class _Peer:
    """Outbound side for one peer: bounded queue + writer thread with lazy
    (re)connection."""

    def __init__(self, comm: TcpComm, node_id: int, addr: Tuple[str, int]) -> None:
        self._comm = comm
        self.node_id = node_id
        self.addr = addr
        self._queue: "queue.Queue[bytes]" = queue.Queue(maxsize=comm._queue_depth)
        self._sock: Optional[socket.socket] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._writer_loop,
            name=f"comm-{self._comm.self_id}->{self.node_id}",
            daemon=True,
        )
        self._thread.start()

    def enqueue(self, frame: bytes) -> None:
        try:
            self._queue.put_nowait(frame)
        except queue.Full:
            pass  # fire-and-forget: backpressure drops, protocol recovers

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass

    def _writer_loop(self) -> None:
        stopped = self._comm._stopped
        while not stopped.is_set():
            try:
                frame = self._queue.get(timeout=0.2)
            except queue.Empty:
                continue
            sock = self._ensure_connected()
            if sock is None:
                continue  # drop the frame; peer unreachable right now
            try:
                plan = self._comm.fault_plan
                if plan is not None:
                    plan.io_error("net.send.io_error")
                sock.sendall(frame)
            except OSError:
                self._drop_connection()

    def _ensure_connected(self) -> Optional[socket.socket]:
        if self._sock is not None:
            return self._sock
        if self._comm._stopped.is_set():
            return None
        try:
            sock = socket.create_connection(
                self.addr, timeout=self._comm._connect_timeout
            )
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            # Read the acceptor's challenge nonce, answer with the proof.
            sock.settimeout(self._comm._connect_timeout)
            header = _read_exact(sock, _HEADER.size)
            if header is None:
                raise OSError("peer closed during handshake")
            length, _, kind = _HEADER.unpack(header)
            if kind != _KIND_HELLO or length != _NONCE_BYTES:
                raise OSError("bad handshake challenge")
            nonce = _read_exact(sock, length)
            if nonce is None:
                raise OSError("peer closed during handshake")
            sock.settimeout(None)
            proof = _hello_proof(
                self._comm._auth_secret, nonce, self._comm.self_id
            )
            sock.sendall(
                _HEADER.pack(len(proof), self._comm.self_id, _KIND_HELLO) + proof
            )
            self._sock = sock
            logger.info(
                "%d: connected to peer %d at %s:%d",
                self._comm.self_id, self.node_id, *self.addr,
            )
            return sock
        except OSError:
            self._comm._stopped.wait(self._comm._backoff)
            return None

    def _drop_connection(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None


def _read_exact(conn: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        try:
            chunk = conn.recv(n - len(buf))
        except OSError:
            return None
        if not chunk:
            return None
        buf += chunk
    return buf


__all__ = ["TcpComm", "MAX_FRAME_BYTES"]
