"""TCP transport: a ``Comm`` implementation over real sockets.

The reference ships no in-tree transport — Fabric supplies a gRPC/mTLS
cluster service and the tests use channel maps (reference
pkg/api/dependencies.go:22-30, test/network.go).  This module provides the
socket transport piece: length-framed messages over TCP between replica
hosts (BFT traffic rides the datacenter network — DCN; ICI is for the
co-located accelerator, not inter-replica consensus).

Contract fidelity: ``Comm`` is *fire-and-forget, unordered, unreliable*
(the protocol tolerates loss).  Accordingly: sends never block the replica
loop (a bounded per-peer queue + writer thread), connection failures trip
bounded in-writer retry (exponential backoff + jitter) before the frame is
dropped silently, and inbound frames are posted onto the replica's
scheduler (thread-safe with ``RealtimeScheduler``).

Reconnect hardening (deploy rig): a connection-refused peer (killed and
not yet restarted) or a mid-frame abrupt close (killed while we were
writing) never surfaces to the caller — the writer thread retries the
connect up to ``connect_attempts`` times with capped exponential backoff
and jitter, and re-sends an abruptly interrupted frame up to
``send_retries`` times over a fresh connection.  Only after both budgets
are exhausted is the frame dropped (the unreliable contract).  Every
outcome is booked on the pinned ``net_reconnect_*`` / ``net_send_*``
counters when a :class:`~consensus_tpu.metrics.MetricsNetwork` bundle is
attached, so a soak scraper can attribute chaos-induced churn per process.

Identity: every connection opens with a HELLO frame that *pins* the peer id
for that connection; later frames claiming another sender kill the link.
With ``auth_secret`` set, the acceptor issues a fresh challenge nonce and
the HELLO carries an HMAC-SHA256 proof over it, so only live holders of the
cluster secret can claim an identity (observed handshakes don't replay).  This is connection-
level replica authentication, NOT transport encryption — for adversarial
networks, terminate TLS in front (stunnel/envoy) or swap in an mTLS
transport behind the same ``Comm`` port.  (Protocol-level safety does not
rest on the transport: consenter signatures are verified end-to-end.)

Frame: u32 length | u64 sender id | u8 kind (0 = consensus, 1 = request,
2 = hello) | payload (``wire.encode_message`` bytes, raw request bytes, or
the HELLO proof).
"""

from __future__ import annotations

import hashlib
import hmac
import logging
import os
import queue
import random
import socket
import struct
import threading
from typing import Callable, Mapping, Optional, Sequence, Tuple

from consensus_tpu.api.deps import Comm
from consensus_tpu.net.framing import FrameStall, ListenerGuard, recv_exact
from consensus_tpu.wire import ConsensusMessage, decode_message, encode_message

logger = logging.getLogger("consensus_tpu.net")

_HEADER = struct.Struct(">IQB")
_KIND_CONSENSUS = 0
_KIND_REQUEST = 1
_KIND_HELLO = 2
_HELLO_CONTEXT = b"consensus-tpu/hello/v1"
_NONCE_BYTES = 16


def _hello_proof(secret: Optional[bytes], nonce: bytes, sender: int) -> bytes:
    """Per-connection proof: binds the cluster secret to the acceptor's
    fresh nonce, so observed handshakes cannot be replayed."""
    if not secret:
        return b""
    return hmac.new(
        secret, _HELLO_CONTEXT + nonce + struct.pack(">Q", sender), hashlib.sha256
    ).digest()
#: Frames larger than this are assumed corrupt and kill the connection.
MAX_FRAME_BYTES = 64 * 1024 * 1024


class TcpComm(Comm):
    """``Comm`` over TCP for one replica.

    ``on_message(sender, payload, is_request)`` is invoked from receiver
    threads — pass a function that posts into the replica scheduler (the
    ``Consensus`` facade's ``handle_message``/``handle_request`` already
    do).
    """

    def __init__(
        self,
        self_id: int,
        addresses: Mapping[int, Tuple[str, int]],
        on_message: Callable[[int, object, bool], None],
        *,
        send_queue_depth: int = 1000,
        reconnect_backoff: float = 0.5,
        reconnect_backoff_max: float = 5.0,
        connect_attempts: int = 3,
        send_retries: int = 2,
        connect_timeout: float = 2.0,
        auth_secret: Optional[bytes] = None,
        metrics=None,
        fault_plan=None,
        guard=None,
    ) -> None:
        #: Optional testing FaultPlan (consensus_tpu/testing/faults.py):
        #: arms the net.send.io_error / net.recv.short_read seams below.
        #: A single ``is None`` check when unarmed.
        self.fault_plan = fault_plan
        #: Optional MetricsNetwork bundle booking reconnect/retry outcomes.
        self.metrics = metrics
        self.self_id = self_id
        self._addresses = dict(addresses)
        self._on_message = on_message
        self._queue_depth = send_queue_depth
        self._backoff = reconnect_backoff
        self._backoff_max = reconnect_backoff_max
        self._connect_attempts = max(1, connect_attempts)
        self._send_retries = max(0, send_retries)
        self._connect_timeout = connect_timeout
        self._auth_secret = auth_secret
        #: Listener hardening (net/framing.py), DEFAULT-ON: quotas at
        #: accept, handshake + mid-frame progress deadlines, strike/ban
        #: accounting.  Pass a configured :class:`ListenerGuard` to tune,
        #: or ``guard=False`` for the pre-hardening listener (bench
        #: baseline only — honest traffic behaves identically either way).
        if guard is None:
            guard = ListenerGuard(name=f"comm-{self_id}", metrics=metrics)
        self.guard: Optional[ListenerGuard] = guard or None
        # One-slot encode memo: broadcasts send the same message object to
        # n-1 peers back to back; encode it once (single-threaded caller).
        self._encode_memo: tuple[Optional[object], bytes] = (None, b"")
        self._peers: dict[int, "_Peer"] = {}
        self._listener: Optional[socket.socket] = None
        self._inbound: set[socket.socket] = set()
        self._inbound_lock = threading.Lock()
        self._stopped = threading.Event()
        self._listener_paused = False
        self._listener_lock = threading.Lock()
        # resume_listener rebind retry bounds (chaos heal vs FIN_WAIT).
        self._rebind_attempts = 100
        self._rebind_delay = 0.05

    # --- lifecycle ---------------------------------------------------------

    def _bind_listener(self) -> None:
        host, port = self._addresses[self.self_id]
        listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        try:
            listener.bind((host, port))
            listener.listen(16)
        except OSError:
            listener.close()
            raise
        self._listener = listener
        threading.Thread(
            target=self._accept_loop, args=(listener,),
            name=f"comm-{self.self_id}-accept", daemon=True,
        ).start()

    def start(self) -> None:
        """Bind our listen address and spin up per-peer sender threads."""
        self._bind_listener()
        for node_id, addr in self._addresses.items():
            if node_id == self.self_id:
                continue
            peer = _Peer(self, node_id, addr)
            self._peers[node_id] = peer
            peer.start()

    def pause_listener(self) -> None:
        """Chaos hook (deploy rig: "listener-port drop"): close the listen
        socket and sever inbound connections.  Outbound sending is
        untouched; peers see connection-refused and ride the bounded-retry
        path until :meth:`resume_listener` rebinds the same address."""
        with self._listener_lock:
            if self._listener_paused or self._stopped.is_set():
                return
            self._listener_paused = True
            if self._listener is not None:
                # shutdown() before close(): on Linux, close() alone does
                # not wake a thread blocked in accept(), and the parked
                # accept keeps the kernel socket in LISTEN — pinning the
                # port against the rebind in resume_listener().
                try:
                    self._listener.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    self._listener.close()
                except OSError:
                    pass
                self._listener = None
        with self._inbound_lock:
            inbound = list(self._inbound)
            self._inbound.clear()
        for conn in inbound:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass

    def resume_listener(self) -> None:
        """Undo :meth:`pause_listener`: rebind the listen address and start
        a fresh accept thread."""
        with self._listener_lock:
            if not self._listener_paused or self._stopped.is_set():
                return
            # Sockets severed by pause_listener can linger in FIN_WAIT on
            # the listen port until the remote notices; retry the rebind
            # briefly rather than fail the heal.
            attempts = self._rebind_attempts
            for attempt in range(attempts):
                try:
                    self._bind_listener()
                    break
                except OSError:
                    if (
                        attempt == attempts - 1
                        or self._stopped.wait(self._rebind_delay)
                    ):
                        # Still paused: the flag only clears on a
                        # successful rebind, so a later resume_listener
                        # (e.g. the chaos heal re-issued over the control
                        # socket) retries instead of silently no-opping
                        # into a permanent inbound partition.
                        raise
            self._listener_paused = False

    def stop(self) -> None:
        self._stopped.set()
        if self._listener is not None:
            try:
                self._listener.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._listener.close()
            except OSError:
                pass
        for peer in self._peers.values():
            peer.close()
        # Unblock receiver threads parked in recv() and stop late dispatches.
        with self._inbound_lock:
            inbound = list(self._inbound)
            self._inbound.clear()
        for conn in inbound:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass

    @property
    def bound_port(self) -> int:
        """The actual listen port (useful with port 0 = ephemeral)."""
        assert self._listener is not None
        return self._listener.getsockname()[1]

    # --- Comm port ---------------------------------------------------------

    def send_consensus(self, target_id: int, message: ConsensusMessage) -> None:
        memo_obj, memo_bytes = self._encode_memo
        if memo_obj is message:
            payload = memo_bytes
        else:
            payload = encode_message(message)
            self._encode_memo = (message, payload)
        self._send(target_id, _KIND_CONSENSUS, payload)

    def send_transaction(self, target_id: int, request: bytes) -> None:
        self._send(target_id, _KIND_REQUEST, bytes(request))

    def nodes(self) -> Sequence[int]:
        return sorted(self._addresses)

    def _send(self, target_id: int, kind: int, payload: bytes) -> None:
        peer = self._peers.get(target_id)
        if peer is None:
            return
        if len(payload) > MAX_FRAME_BYTES:
            # Enforced on the send side too: an oversized frame would be
            # killed by every receiver (poisoning the link), and > 2^32
            # would crash the header pack — both violate fire-and-forget.
            logger.warning(
                "%d: dropping oversized %d-byte frame to %d",
                self.self_id, len(payload), target_id,
            )
            return
        frame = _HEADER.pack(len(payload), self.self_id, kind) + payload
        peer.enqueue(frame)  # drops when the queue is full (unreliable contract)

    # --- inbound -----------------------------------------------------------

    def _accept_loop(self, listener: socket.socket) -> None:
        while not self._stopped.is_set():
            try:
                conn, _ = listener.accept()
            except OSError:
                if self._stopped.is_set():
                    return
                if self._listener is not listener:
                    return  # paused/replaced: this accept loop retires
                # Transient accept failure (ECONNABORTED, fd pressure):
                # keep serving — a dead accept loop would silently
                # partition this replica on the receive side.
                logger.warning("%d: accept failed; retrying", self.self_id, exc_info=True)
                self._stopped.wait(0.05)
                continue
            # Accepted sockets share the listen port as their local addr;
            # without SO_REUSEADDR a severed-but-lingering one (FIN_WAIT
            # after pause_listener) would block the rebind on resume.
            try:
                conn.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            except OSError:
                pass
            addr = "?"
            try:
                addr = conn.getpeername()[0]
            except OSError:
                pass
            guard = self.guard
            if guard is not None and not guard.admit(addr):
                # Banned peer or full quota: refuse before reading a byte.
                try:
                    conn.close()
                except OSError:
                    pass
                continue
            with self._inbound_lock:
                if self._stopped.is_set():
                    conn.close()
                    if guard is not None:
                        guard.release(addr)
                    return
                self._inbound.add(conn)
            threading.Thread(
                target=self._receive_loop,
                args=(conn, addr),
                name=f"comm-{self.self_id}-recv",
                daemon=True,
            ).start()

    def _receive_loop(self, conn: socket.socket, addr: str = "?") -> None:
        pinned_sender: Optional[int] = None
        guard = self.guard

        def strike(kind: str) -> None:
            if guard is not None:
                guard.strike(addr, kind)

        # Challenge: a fresh nonce per connection (replay protection).
        nonce = os.urandom(_NONCE_BYTES)
        try:
            conn.sendall(_HEADER.pack(len(nonce), self.self_id, _KIND_HELLO) + nonce)
        except OSError:
            with self._inbound_lock:
                self._inbound.discard(conn)
            if guard is not None:
                guard.release(addr)
            try:
                conn.close()
            except OSError:
                pass
            return
        try:
            while not self._stopped.is_set():
                plan = self.fault_plan
                if plan is not None and plan.trip("net.recv.short_read"):
                    # Simulate the link dying mid-frame: the finally block
                    # closes the connection exactly as a real short read
                    # below would; the sender reconnects lazily.
                    return
                # Until the HELLO pins an identity, every read runs under
                # the handshake deadline; after it, the header read waits
                # patiently (an idle honest peer) but any started frame
                # must keep making progress (slow-loris defense).
                if guard is None:
                    timeout, patient, preset = None, False, False
                elif pinned_sender is None:
                    timeout, patient, preset = (
                        guard.handshake_timeout, False, False
                    )
                else:
                    # Pinned connections read non-blocking (set below):
                    # preset reads try recv first and enforce the
                    # progress deadline only when a read actually blocks.
                    timeout, patient, preset = (
                        guard.progress_timeout, True, True
                    )
                try:
                    header = recv_exact(
                        conn, _HEADER.size,
                        progress_timeout=timeout, patient_first=patient,
                        preset=preset,
                    )
                except FrameStall as stall:
                    if pinned_sender is None and stall.received == 0:
                        # Never sent a byte: connect-and-idle, not a frame.
                        if guard is not None:
                            guard.handshake_timed_out(addr)
                    else:
                        strike("stall")
                    return
                if header is None:
                    return
                length, sender, kind = _HEADER.unpack(header)
                if length > MAX_FRAME_BYTES:
                    logger.warning("oversized frame from %d; dropping link", sender)
                    strike("oversized")
                    return
                try:
                    payload = recv_exact(
                        conn, length, progress_timeout=timeout, preset=preset,
                    )
                except FrameStall:
                    strike("stall")
                    return
                if payload is None:
                    return
                if pinned_sender is None:
                    # First frame must be the HELLO that pins this
                    # connection's identity (optionally HMAC-proven).
                    if kind != _KIND_HELLO:
                        logger.warning(
                            "%d: connection sent %d before HELLO; dropping link",
                            self.self_id, kind,
                        )
                        strike("pre_hello")
                        return
                    expected = _hello_proof(self._auth_secret, nonce, sender)
                    if not hmac.compare_digest(payload, expected):
                        logger.warning(
                            "%d: bad HELLO proof for claimed sender %d; dropping link",
                            self.self_id, sender,
                        )
                        strike("bad_hello")
                        return
                    pinned_sender = sender
                    if guard is not None:
                        # Pinned: go non-blocking for the connection's
                        # lifetime — preset reads try recv first and pay
                        # for a readiness wait only when a read actually
                        # blocks, so honest line rate matches unguarded.
                        try:
                            conn.setblocking(False)
                        except OSError:
                            return
                    continue
                if sender != pinned_sender:
                    logger.warning(
                        "%d: frame claims sender %d on connection pinned to %d; dropping link",
                        self.self_id, sender, pinned_sender,
                    )
                    strike("sender_pin")
                    return
                self._dispatch(sender, kind, payload)
        finally:
            with self._inbound_lock:
                self._inbound.discard(conn)
            if guard is not None:
                guard.release(addr)
            try:
                conn.close()
            except OSError:
                pass

    def _dispatch(self, sender: int, kind: int, payload: bytes) -> None:
        if self._stopped.is_set():
            return
        try:
            if kind == _KIND_CONSENSUS:
                self._on_message(sender, decode_message(payload), False)
            elif kind == _KIND_REQUEST:
                self._on_message(sender, payload, True)
            else:
                logger.warning("unknown frame kind %d from %d", kind, sender)
        except Exception:
            # A malformed message must not kill the receive loop.
            logger.exception("failed dispatching frame from %d", sender)


class _Peer:
    """Outbound side for one peer: bounded queue + writer thread with lazy
    (re)connection."""

    def __init__(self, comm: TcpComm, node_id: int, addr: Tuple[str, int]) -> None:
        self._comm = comm
        self.node_id = node_id
        self.addr = addr
        self._queue: "queue.Queue[bytes]" = queue.Queue(maxsize=comm._queue_depth)
        self._sock: Optional[socket.socket] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._writer_loop,
            name=f"comm-{self._comm.self_id}->{self.node_id}",
            daemon=True,
        )
        self._thread.start()

    def enqueue(self, frame: bytes) -> None:
        try:
            self._queue.put_nowait(frame)
        except queue.Full:
            pass  # fire-and-forget: backpressure drops, protocol recovers

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass

    def _writer_loop(self) -> None:
        stopped = self._comm._stopped
        while not stopped.is_set():
            try:
                frame = self._queue.get(timeout=0.2)
            except queue.Empty:
                continue
            self._send_with_retry(frame)

    def _send_with_retry(self, frame: bytes) -> None:
        """Deliver one frame, riding out a peer killed mid-frame: an abrupt
        close during ``sendall`` reconnects and re-sends the SAME frame up
        to ``send_retries`` times before the fire-and-forget drop."""
        metrics = self._comm.metrics
        for attempt in range(self._comm._send_retries + 1):
            sock = self._ensure_connected()
            if sock is None:
                break  # connect budget exhausted; drop below
            try:
                plan = self._comm.fault_plan
                if plan is not None:
                    plan.io_error("net.send.io_error")
                sock.sendall(frame)
                return
            except OSError:
                self._drop_connection()
                if attempt < self._comm._send_retries:
                    if metrics is not None:
                        metrics.count_send_retried.add(1)
                    continue
        if metrics is not None:
            metrics.count_send_dropped.add(1)

    def _ensure_connected(self) -> Optional[socket.socket]:
        """Bounded connect: up to ``connect_attempts`` tries with capped
        exponential backoff + jitter (desynchronizes a fleet reconnecting
        to a restarted peer), then give up on THIS frame — the next frame
        starts a fresh budget, so a peer that stays down costs bounded
        writer time and a peer that comes back is re-reached quickly."""
        if self._sock is not None:
            return self._sock
        comm = self._comm
        metrics = comm.metrics
        for attempt in range(comm._connect_attempts):
            if comm._stopped.is_set():
                return None
            if attempt:
                delay = min(
                    comm._backoff * (2.0 ** (attempt - 1)), comm._backoff_max
                )
                delay *= 0.5 + random.random() / 2.0  # jitter: 50-100%
                if comm._stopped.wait(delay):
                    return None
            if metrics is not None:
                metrics.count_reconnect_attempts.add(1)
            try:
                sock = socket.create_connection(
                    self.addr, timeout=comm._connect_timeout
                )
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                # Read the acceptor's challenge nonce, answer with the proof.
                sock.settimeout(comm._connect_timeout)
                header = recv_exact(sock, _HEADER.size)
                if header is None:
                    raise OSError("peer closed during handshake")
                length, _, kind = _HEADER.unpack(header)
                if kind != _KIND_HELLO or length != _NONCE_BYTES:
                    raise OSError("bad handshake challenge")
                nonce = recv_exact(sock, length)
                if nonce is None:
                    raise OSError("peer closed during handshake")
                sock.settimeout(None)
                proof = _hello_proof(comm._auth_secret, nonce, comm.self_id)
                sock.sendall(
                    _HEADER.pack(len(proof), comm.self_id, _KIND_HELLO) + proof
                )
                self._sock = sock
                if metrics is not None:
                    metrics.count_reconnect_success.add(1)
                logger.info(
                    "%d: connected to peer %d at %s:%d",
                    comm.self_id, self.node_id, *self.addr,
                )
                return sock
            except OSError:
                continue
        # Budget exhausted: brief pause so a hard-down peer cannot spin the
        # writer thread at full speed frame after frame.
        comm._stopped.wait(comm._backoff)
        return None

    def _drop_connection(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None


__all__ = ["TcpComm", "MAX_FRAME_BYTES"]
