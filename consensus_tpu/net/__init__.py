"""Network transports: production Comm implementations (TCP over DCN)."""

from consensus_tpu.net.transport import MAX_FRAME_BYTES, TcpComm
from consensus_tpu.net.sidecar import SidecarVerifierClient, VerifySidecarServer

__all__ = [
    "TcpComm",
    "MAX_FRAME_BYTES",
    "VerifySidecarServer",
    "SidecarVerifierClient",
]
