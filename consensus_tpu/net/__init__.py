"""Network transports: production Comm implementations (TCP over DCN)."""

from consensus_tpu.net.transport import MAX_FRAME_BYTES, TcpComm

__all__ = ["TcpComm", "MAX_FRAME_BYTES"]
