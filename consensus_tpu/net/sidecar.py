"""Verification sidecar: n replica *processes* sharing one TPU.

The reference always deploys replicas as separate OS processes (its Comm
contract is a network transport, reference pkg/api/dependencies.go:22-30);
each Go process burns its own cores verifying signatures.  The TPU-native
deployment shape (SURVEY §7 step 9) keeps one device per host and lets all
co-located replica processes drain their signature sweeps into it through a
tiny socket front: the sidecar process owns the engine (and the one
compiled kernel shape) and coalesces concurrent requests from any number of
replica processes into single device launches via
:class:`consensus_tpu.models.engine.ThreadCoalescingVerifier`.

Client side, :class:`SidecarVerifierClient` is a drop-in ``engine`` for the
``Verifier`` mixins (same ``verify_batch`` contract).  With a
``local_engine`` supplied it also inherits the wedged-device escape hatch:
a sidecar that dies or stalls past ``request_timeout`` fails over to local
host verification (slower, still correct) instead of wedging the replica.

Framing (both directions, all integers big-endian):

    u32 payload_len | u64 req_id | payload

Request payload:  u32 count | count * (u32 mlen u32 slen u32 klen m s k)
Response payload: u8 status (0=ok, 1=error) | count result bytes / utf-8 error

Addresses: a ``(host, port)`` tuple serves TCP (cross-container), a string
serves a unix domain socket (same-host, lower latency — the common shape).
"""

from __future__ import annotations

import logging
import os
import socket
import struct
import threading
import time
from typing import Optional, Sequence, Union

import numpy as np

logger = logging.getLogger("consensus_tpu.net.sidecar")

_FRAME = struct.Struct(">IQ")
_ITEM = struct.Struct(">III")
_MAX_FRAME = 256 * 1024 * 1024

Address = Union[tuple, str]


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("sidecar connection closed")
        buf.extend(chunk)
    return bytes(buf)


def _read_frame(sock: socket.socket) -> tuple[int, bytes]:
    header = _recv_exact(sock, _FRAME.size)
    length, req_id = _FRAME.unpack(header)
    if length > _MAX_FRAME:
        raise ConnectionError(f"sidecar frame too large: {length}")
    return req_id, _recv_exact(sock, length)


def _write_frame(sock: socket.socket, req_id: int, payload: bytes) -> None:
    sock.sendall(_FRAME.pack(len(payload), req_id) + payload)


def encode_request(messages, signatures, keys) -> bytes:
    parts = [struct.pack(">I", len(messages))]
    for m, s, k in zip(messages, signatures, keys):
        parts.append(_ITEM.pack(len(m), len(s), len(k)))
        parts.append(bytes(m))
        parts.append(bytes(s))
        parts.append(bytes(k))
    return b"".join(parts)


def decode_request(payload: bytes) -> tuple[list, list, list]:
    (count,) = struct.unpack_from(">I", payload, 0)
    offset = 4
    messages, signatures, keys = [], [], []
    for _ in range(count):
        mlen, slen, klen = _ITEM.unpack_from(payload, offset)
        offset += _ITEM.size
        messages.append(payload[offset : offset + mlen]); offset += mlen
        signatures.append(payload[offset : offset + slen]); offset += slen
        keys.append(payload[offset : offset + klen]); offset += klen
    if offset != len(payload):
        raise ValueError("trailing bytes in sidecar request")
    return messages, signatures, keys


class VerifySidecarServer:
    """Socket front on a verification engine (typically a
    ``ThreadCoalescingVerifier`` so concurrent replica processes merge into
    one device launch).  One thread per connection reads requests; each
    request is served on its own worker thread — a replica pipelining
    decisions can have several requests in flight on one connection, and a
    blocking coalescer call must not serialize them."""

    def __init__(self, address: Address, engine) -> None:
        self._address = address
        self._engine = engine
        self._listener: Optional[socket.socket] = None
        self._stopping = False

    @property
    def address(self) -> Address:
        """The bound address (with the real port once started)."""
        return self._address

    def start(self) -> None:
        if isinstance(self._address, str):
            try:
                os.unlink(self._address)
            except OSError:
                pass
            listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            listener.bind(self._address)
        else:
            listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            listener.bind(tuple(self._address))
            self._address = listener.getsockname()
        listener.listen(64)
        self._listener = listener
        threading.Thread(
            target=self._accept_loop, daemon=True, name="sidecar-accept"
        ).start()

    def stop(self) -> None:
        self._stopping = True
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        if isinstance(self._address, str):
            try:
                os.unlink(self._address)
            except OSError:
                pass

    def _accept_loop(self) -> None:
        while not self._stopping:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            if conn.family == socket.AF_INET:
                conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            # Daemon threads, deliberately untracked: connections churn for
            # the life of the sidecar and holding dead Thread objects would
            # grow without bound; stop() only needs the listener.
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True,
                name="sidecar-conn",
            ).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        write_lock = threading.Lock()
        try:
            while True:
                req_id, payload = _read_frame(conn)
                threading.Thread(
                    target=self._serve_request,
                    args=(conn, write_lock, req_id, payload),
                    daemon=True,
                    name="sidecar-verify",
                ).start()
        except (ConnectionError, OSError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _serve_request(self, conn, write_lock, req_id: int, payload: bytes) -> None:
        try:
            messages, signatures, keys = decode_request(payload)
            results = np.asarray(self._engine.verify_batch(messages, signatures, keys))
            if len(results) != len(messages):
                raise ValueError("engine returned wrong result count")
            body = b"\x00" + np.asarray(results, dtype=np.uint8).tobytes()
        except Exception as exc:  # serve the error, keep the connection
            logger.exception("sidecar verify request %d failed", req_id)
            body = b"\x01" + repr(exc).encode()
        try:
            with write_lock:
                _write_frame(conn, req_id, body)
        except OSError:
            pass  # client went away; its loss


class SidecarVerifierClient:
    """Drop-in ``engine`` (the ``verify_batch`` contract) that forwards
    batches to a :class:`VerifySidecarServer` over one multiplexed
    connection.  Thread-safe: concurrent calls are tagged with request ids
    and a single reader thread routes responses.

    ``local_engine``: optional engine whose ``verify_host`` serves as the
    escape hatch — if the sidecar is unreachable, errors, or stalls past
    ``request_timeout``, verification falls back to the local host path
    (logged loudly) instead of wedging the replica.

    ``bypass_below``: batches smaller than this verify locally (via
    ``local_engine.verify_host``) without a socket round trip — quorum-sized
    checks and single signatures gain nothing from the device and shouldn't
    pay the sidecar RTT + coalescing window.
    """

    def __init__(
        self,
        address: Address,
        *,
        local_engine=None,
        request_timeout: float = 60.0,
        connect_timeout: float = 5.0,
        bypass_below: int = 0,
        probe_interval: float = 10.0,
    ) -> None:
        self._address = address
        self._timeout = request_timeout
        self._connect_timeout = connect_timeout
        self._local = local_engine
        self._bypass_below = bypass_below if local_engine is not None else 0
        self._probe_interval = probe_interval
        self._lock = threading.Lock()  # guards socket create + sends
        self._sock: Optional[socket.socket] = None
        self._pending: dict[int, dict] = {}
        self._next_id = 0
        self._reader: Optional[threading.Thread] = None
        #: Set after a request TIMES OUT (sidecar wedged, not just dead):
        #: later calls skip the stall and go straight to the local fallback
        #: while a background probe watches for recovery.
        self._suspect = False
        self._closed = False

    # -- engine contract ---------------------------------------------------

    def verify_batch(self, messages, signatures, public_keys) -> np.ndarray:
        n = len(messages)
        if not (n == len(signatures) == len(public_keys)):
            raise ValueError("batch length mismatch")
        if n == 0:
            return np.zeros(0, dtype=bool)
        if self._suspect and self._local is not None:
            # Wedged sidecar: don't stall request_timeout on every call —
            # the background probe clears the flag when it recovers.
            return np.asarray(
                self._local.verify_host(messages, signatures, public_keys)
            )
        if n < self._bypass_below:
            return np.asarray(
                self._local.verify_host(messages, signatures, public_keys)
            )
        try:
            result = self._roundtrip(messages, signatures, public_keys)
        except Exception as exc:
            if self._local is None:
                raise
            if isinstance(exc, TimeoutError):
                self._mark_suspect()
            logger.error(
                "sidecar verify failed (%r) — falling back to LOCAL host "
                "verification for %d signatures",
                exc,
                n,
            )
            return np.asarray(
                self._local.verify_host(messages, signatures, public_keys)
            )
        return result

    def _mark_suspect(self) -> None:
        """A timed-out request means the sidecar is wedged (its device call
        hung), not merely dead: drop the socket so other in-flight waiters
        fail over immediately, and probe for recovery in the background."""
        with self._lock:
            if self._suspect or self._closed:
                already = True
            else:
                self._suspect = True
                already = False
            sock = self._sock
        if already:
            return
        logger.error(
            "sidecar did not answer within %.1fs — marking it suspect; "
            "verification continues on the LOCAL host path until a probe "
            "succeeds",
            self._timeout,
        )
        if sock is not None:
            self._drop_socket(sock)
        threading.Thread(
            target=self._probe_loop, daemon=True, name="sidecar-probe"
        ).start()

    def _probe_loop(self) -> None:
        while True:
            time.sleep(self._probe_interval)
            with self._lock:
                if self._closed or not self._suspect:
                    return
            try:
                # An empty batch exercises the full socket + server + engine
                # dispatch path cheaply.
                self._roundtrip([], [], [], timeout=self._probe_interval)
            except Exception:
                continue
            with self._lock:
                self._suspect = False
            logger.warning("sidecar recovered — resuming sidecar verification")
            return

    def verify_host(self, messages, signatures, public_keys) -> np.ndarray:
        """Escape-hatch seam (used if this client is itself wrapped in a
        coalescer): local host verification, bypassing the sidecar."""
        if self._local is None:
            raise RuntimeError("no local_engine configured")
        return np.asarray(
            self._local.verify_host(messages, signatures, public_keys)
        )

    # -- plumbing ----------------------------------------------------------

    def _ensure_connected(self) -> socket.socket:
        if self._sock is not None:
            return self._sock
        if isinstance(self._address, str):
            sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        else:
            sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sock.settimeout(self._connect_timeout)
        sock.connect(
            self._address if isinstance(self._address, str)
            else tuple(self._address)
        )
        sock.settimeout(None)
        if sock.family == socket.AF_INET:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock = sock
        self._reader = threading.Thread(
            target=self._read_loop, args=(sock,), daemon=True,
            name="sidecar-client-reader",
        )
        self._reader.start()
        return sock

    def _roundtrip(
        self, messages, signatures, keys, *, timeout: Optional[float] = None
    ) -> np.ndarray:
        payload = encode_request(messages, signatures, keys)
        waiter = {"event": threading.Event(), "body": None}
        send_error: Optional[OSError] = None
        with self._lock:
            sock = self._ensure_connected()
            req_id = self._next_id
            self._next_id += 1
            self._pending[req_id] = waiter
            try:
                _write_frame(sock, req_id, payload)
            except OSError as exc:
                self._pending.pop(req_id, None)
                send_error = exc
        if send_error is not None:
            # Outside the lock: _drop_socket re-acquires it (calling it
            # while held would self-deadlock and wedge every verify).
            self._drop_socket(sock)
            raise send_error
        if not waiter["event"].wait(timeout if timeout is not None else self._timeout):
            self._pending.pop(req_id, None)
            raise TimeoutError(
                f"sidecar did not answer within {self._timeout}s"
            )
        body = waiter["body"]
        if body is None:
            raise ConnectionError("sidecar connection lost mid-request")
        if body[0] != 0:
            raise RuntimeError(f"sidecar error: {body[1:].decode(errors='replace')}")
        results = np.frombuffer(body[1:], dtype=np.uint8).astype(bool)
        if len(results) != len(messages):
            raise ValueError("sidecar returned wrong result count")
        return results

    def _read_loop(self, sock: socket.socket) -> None:
        try:
            while True:
                req_id, body = _read_frame(sock)
                waiter = self._pending.pop(req_id, None)
                if waiter is not None:
                    waiter["body"] = body
                    waiter["event"].set()
        except (ConnectionError, OSError):
            pass
        finally:
            self._drop_socket(sock)

    def _drop_socket(self, sock: socket.socket) -> None:
        """Fail every in-flight request and let the next call reconnect."""
        with self._lock:
            if self._sock is sock:
                self._sock = None
            pending, self._pending = dict(self._pending), {}
        try:
            sock.close()
        except OSError:
            pass
        for waiter in pending.values():
            waiter["event"].set()  # body stays None -> ConnectionError

    def close(self) -> None:
        with self._lock:
            self._closed = True
        sock = self._sock
        if sock is not None:
            self._drop_socket(sock)


__all__ = [
    "VerifySidecarServer",
    "SidecarVerifierClient",
    "encode_request",
    "decode_request",
]
