"""Verification service: many replica *processes* — and many replica
CLUSTERS — sharing one device mesh.

The reference always deploys replicas as separate OS processes (its Comm
contract is a network transport, reference pkg/api/dependencies.go:22-30);
each Go process burns its own cores verifying signatures.  The TPU-native
deployment shape (SURVEY §7 step 9) keeps one device (or mesh) per host and
lets every co-located replica process drain its signature sweeps into it
through a tiny socket front: the sidecar process owns the engine (and the
one compiled kernel shape) and coalesces concurrent requests into single
device launches.

**Single-tenant mode** (no ``tenants`` map): exactly the PR-4 behavior —
one shared secret, requests served straight on the engine (typically a
:class:`consensus_tpu.models.engine.ThreadCoalescingVerifier`).

**Multi-tenant mode** (``tenants`` = tenant id -> secret): one server
serves many replica clusters/channels.  Each connection authenticates AS a
tenant (per-tenant secret, same wire format as the legacy handshake), and
requests flow through a :class:`consensus_tpu.models.engine
.FairShareWaveFormer`: per-tenant bounded queues with admission control
(structured reject — status 2 — never a stall), round-robin fair-share
draining, and deadline-aware cross-tenant coalescing so four channels'
quorum certs ride ONE mesh launch.  Over a mesh engine the former learns
the engine's ``preferred_wave_size`` (the padded shard-multiple that
saturates the whole slice, not one chip) and launches as soon as the
slice is full rather than waiting out the window.  Per-tenant metrics land in a
:class:`consensus_tpu.metrics.MetricsSidecar` bundle and per-tenant kernel
attribution in :data:`consensus_tpu.obs.kernels.TENANT_KERNELS`.

Client side, :class:`SidecarVerifierClient` is a drop-in ``engine`` for the
``Verifier`` mixins (same ``verify_batch`` contract).  With a
``local_engine`` supplied it also inherits the wedged-device escape hatch:
a sidecar that dies or stalls past ``request_timeout`` fails over to local
host verification (slower, still correct) instead of wedging the replica.
An admission reject surfaces as :class:`TenantAdmissionReject` (structured:
tenant, queue depth, limit) and falls back locally WITHOUT marking the
sidecar suspect — the service is healthy, the tenant is over quota.

Framing (both directions, all integers big-endian):

    u32 payload_len | u64 req_id | payload

Request payload:  u32 count | count * (u32 mlen u32 slen u32 klen m s k)
Response payload: u8 status | body
    status 0: count result bytes
    status 1: utf-8 error text
    status 2: u32 queue_depth | u32 limit | utf-8 tenant  (admission reject)
    status 3: count result bytes, served by a DEGRADED engine (the server's
              supervised verifier is below its top ladder rung — verdicts
              are still ground-truth correct, but a fleet-aware client
              deprioritizes this server on the placement ring until a
              status-0 answer clears it)

Addresses: a ``(host, port)`` tuple serves TCP (cross-container), a string
serves a unix domain socket (same-host, lower latency — the common shape).
TCP mode REQUIRES authentication (``auth_secret`` and/or ``tenants``): the
handshake is MUTUAL (both ends prove knowledge of the secret over a
domain-separated nonce pair) and derives a per-connection session key that
MACs every frame in both directions — a verification verdict is consensus
input, so a peer in path must not be able to forge "all valid" responses
(it can still drop the connection; that is the failover path, not a safety
hole).  Unix sockets rely on filesystem permissions instead but honour the
secrets when given.  The tenant handshake is wire-compatible with the
legacy one (same byte counts in each direction); the server distinguishes
tenants by WHICH secret validates the proof, with the tenant id bound into
the proof/session-key derivations so two tenants sharing a secret value
still get distinct sessions.
"""

from __future__ import annotations

import hashlib
import hmac
import logging
import os
import socket
import struct
import threading
import time
from typing import Optional, Sequence, Union

import numpy as np

# jax-free (models/engine.py is pure numpy/threading), so importing the
# sidecar module still never drags in the accelerator stack.
from consensus_tpu.models.engine import AdmissionReject as _AdmissionReject
from consensus_tpu.net.framing import RECV_CHUNK_BYTES, ListenerGuard

logger = logging.getLogger("consensus_tpu.net.sidecar")

_FRAME = struct.Struct(">IQ")
_ITEM = struct.Struct(">III")
#: Default frame-size ceiling.  64 MiB comfortably fits the largest real
#: sweep (a 16k-signature wave is < 2 MiB) while bounding what one
#: misbehaving peer can make the server buffer (ADVICE r4).
_MAX_FRAME = 64 * 1024 * 1024
_NONCE_LEN = 32
_MAC_LEN = 16
_HANDSHAKE_TIMEOUT = 5.0
#: Domain separation for the three HMAC uses (client proof, server proof,
#: session-key derivation) so a transcript from one role can never stand in
#: for another.
_CLIENT_PROOF = b"ctpu-sidecar-client-v1"
_SERVER_PROOF = b"ctpu-sidecar-server-v1"
_SESSION_KEY = b"ctpu-sidecar-session-v1"
#: Tenant-mode client proof: a distinct domain tag (and the tenant id bound
#: into every derivation) so a legacy transcript can never double as a
#: tenant proof or vice versa.
_TENANT_PROOF = b"ctpu-sidecar-tenant-v1"

Address = Union[tuple, str]


class QueueStallTimeout(TimeoutError):
    """The per-request budget expired while the request was still QUEUED
    behind other senders — the wire itself was never observed to stall, so
    callers must not treat this as evidence the sidecar is wedged."""


class SidecarQueueStall(QueueStallTimeout):
    """A :class:`QueueStallTimeout` with structure: WHICH tenant gave up,
    how many requests were locally queued ahead of it, and the budget that
    expired — so a multi-tenant operator can tell one tenant's local send
    pressure from a service-wide stall."""

    def __init__(
        self, reason: str, *, tenant: str = "", queue_depth: int = 0,
        deadline: float = 0.0,
    ) -> None:
        super().__init__(reason)
        self.tenant = tenant
        self.queue_depth = queue_depth
        self.deadline = deadline


class TenantAdmissionReject(RuntimeError):
    """The server REJECTED the batch at admission (tenant queue full,
    status 2) — structured, immediate, and deliberately NOT a
    ``TimeoutError``: the service is healthy, so the client must fall back
    locally without marking the sidecar suspect or disturbing other
    tenants' waves."""

    def __init__(self, tenant: str, queue_depth: int, limit: int) -> None:
        super().__init__(
            f"sidecar admission rejected tenant {tenant!r}: "
            f"{queue_depth} signatures queued, limit {limit}"
        )
        self.tenant = tenant
        self.queue_depth = queue_depth
        self.limit = limit


def _with_tenant(instrument, tenant: str):
    """The per-tenant child series of a pinned instrument, or the base
    instrument when the bundle was built without a tenant label (metrics
    must never break the serve path)."""
    try:
        return instrument.with_labels(tenant)
    except Exception:
        return instrument


def _hmac256(key: bytes, *parts: bytes) -> bytes:
    mac = hmac.new(key, digestmod=hashlib.sha256)
    for p in parts:
        mac.update(p)
    return mac.digest()


def _frame_mac(key: bytes, direction: bytes, req_id: int, payload: bytes) -> bytes:
    return _hmac256(key, direction, req_id.to_bytes(8, "big"), payload)[:_MAC_LEN]


class _MidFrameStall(ConnectionError):
    """A peer stopped sending mid-frame (the server books a ``stall``)."""


class _FrameTooLarge(ConnectionError):
    """A peer claimed a frame beyond the cap (booked as ``oversized``)."""


class _MacMismatch(ConnectionError):
    """A frame MAC failed verification (booked as ``bad_hello``)."""


def _recv_exact(sock: socket.socket, n: int, patient: bool = False) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        try:
            # Chunked (cap-check-before-allocate): allocation tracks bytes
            # actually received, never the peer's claimed length.
            chunk = sock.recv(min(n - len(buf), RECV_CHUNK_BYTES))
        except TimeoutError:
            if patient:
                # The CLIENT reader trusts its one sidecar and must not
                # tear a healthy connection down over a slow frame (another
                # thread may also shorten the shared socket's deadline
                # transiently); liveness comes from the per-request budget,
                # whose expiry closes the socket and ends this recv.
                continue
            if buf:
                # A stall MID-frame loses protocol sync; only an idle
                # timeout at a frame boundary is benign (re-raised for the
                # caller to swallow).
                raise _MidFrameStall("sidecar stalled mid-frame")
            raise
        if not chunk:
            raise ConnectionError("sidecar connection closed")
        buf.extend(chunk)
    return bytes(buf)


def _read_frame(
    sock: socket.socket,
    max_frame: int = _MAX_FRAME,
    mac_key: Optional[bytes] = None,
    direction: bytes = b"",
    patient: bool = False,
) -> tuple[int, bytes]:
    """Read one frame; with a session ``mac_key``, verify the trailing MAC
    (keyed on direction + req_id + payload) and drop the connection on any
    mismatch — an in-path forger must not be able to mint verdicts."""
    header = _recv_exact(sock, _FRAME.size, patient)
    length, req_id = _FRAME.unpack(header)
    if length > max_frame:
        raise _FrameTooLarge(f"sidecar frame too large: {length}")
    try:
        payload = _recv_exact(sock, length, patient)
        if mac_key is not None:
            mac = _recv_exact(sock, _MAC_LEN, patient)
            if not hmac.compare_digest(
                mac, _frame_mac(mac_key, direction, req_id, payload)
            ):
                raise _MacMismatch("sidecar frame MAC mismatch")
    except TimeoutError:
        raise _MidFrameStall("sidecar stalled mid-frame") from None
    return req_id, payload


def _write_frame(
    sock: socket.socket,
    req_id: int,
    payload: bytes,
    mac_key: Optional[bytes] = None,
    direction: bytes = b"",
) -> None:
    buf = _FRAME.pack(len(payload), req_id) + payload
    if mac_key is not None:
        buf += _frame_mac(mac_key, direction, req_id, payload)
    sock.sendall(buf)


def encode_request(messages, signatures, keys) -> bytes:
    parts = [struct.pack(">I", len(messages))]
    for m, s, k in zip(messages, signatures, keys):
        parts.append(_ITEM.pack(len(m), len(s), len(k)))
        parts.append(bytes(m))
        parts.append(bytes(s))
        parts.append(bytes(k))
    return b"".join(parts)


def decode_request(payload: bytes) -> tuple[list, list, list]:
    (count,) = struct.unpack_from(">I", payload, 0)
    offset = 4
    messages, signatures, keys = [], [], []
    for _ in range(count):
        mlen, slen, klen = _ITEM.unpack_from(payload, offset)
        offset += _ITEM.size
        messages.append(payload[offset : offset + mlen]); offset += mlen
        signatures.append(payload[offset : offset + slen]); offset += slen
        keys.append(payload[offset : offset + klen]); offset += klen
    if offset != len(payload):
        raise ValueError("trailing bytes in sidecar request")
    return messages, signatures, keys


class VerifySidecarServer:
    """Socket front on a verification engine (typically a
    ``ThreadCoalescingVerifier`` so concurrent replica processes merge into
    one device launch).  One thread per connection reads requests; each
    request is served on its own worker thread — a replica pipelining
    decisions can have several requests in flight on one connection, and a
    blocking coalescer call must not serialize them.

    ``auth_secret`` (REQUIRED for TCP): shared secret for the per-connection
    challenge-response — the server sends a random nonce, the peer must
    answer ``HMAC-SHA256(secret, nonce)`` within ``_HANDSHAKE_TIMEOUT`` or
    the connection is dropped before any frame is read.  Unix sockets may
    omit it (filesystem permissions are the perimeter) but honour it when
    given.

    ``max_inflight`` bounds the worker threads PER CONNECTION: when a peer
    has that many requests outstanding the connection's read loop blocks,
    pushing backpressure into the peer's socket instead of spawning
    unbounded threads (ADVICE r4 flood surface).

    ``io_timeout`` is the per-connection socket timeout: a peer that stops
    READING its responses stalls a worker's send for at most this long,
    after which the connection is torn down and its worker slots recovered —
    otherwise a connect-flood-abandon peer would park ``max_inflight``
    threads per connection forever.

    ``guard``: hardened DEFAULT-ON via a :class:`~consensus_tpu.net.framing
    .ListenerGuard` — per-peer/global connection quotas checked at accept
    (before the handshake spends a nonce), plus strikes toward a temporary
    ban for provably-malformed traffic: a failed auth proof or frame-MAC
    mismatch (``bad_hello``), an oversized length claim, a mid-frame stall.
    A peer that connects and never attempts the handshake books a
    handshake timeout.  Pass a configured guard to tune, or ``guard=False``
    for the pre-hardening behavior."""

    def __init__(
        self,
        address: Address,
        engine,
        *,
        auth_secret: Optional[bytes] = None,
        tenants: Optional[dict] = None,
        max_inflight: int = 32,
        max_frame: int = _MAX_FRAME,
        io_timeout: float = 60.0,
        wave_window: float = 0.005,
        max_wave: int = 8192,
        tenant_queue_limit: int = 4096,
        metrics=None,
        tenant_accounting=None,
        guard=None,
    ) -> None:
        self._address = address
        self._engine = engine
        self._secret = auth_secret
        if guard is None:
            guard = ListenerGuard(name="sidecar")
        self.guard = guard or None
        self._tenants = dict(tenants) if tenants else None
        self._max_inflight = max_inflight
        self._max_frame = max_frame
        self._io_timeout = io_timeout
        self._metrics = metrics
        self._accounting = tenant_accounting
        self._former = None
        if self._tenants is not None:
            from consensus_tpu.models.engine import FairShareWaveFormer

            if self._accounting is None:
                from consensus_tpu.obs.kernels import TENANT_KERNELS

                self._accounting = TENANT_KERNELS
            self._former = FairShareWaveFormer(
                engine,
                window=wave_window,
                max_wave=max_wave,
                tenant_queue_limit=tenant_queue_limit,
                on_wave=self._record_wave,
                name="sidecar-waves",
            )
        self._listener: Optional[socket.socket] = None
        self._stopping = False

    def _record_wave(self, tenant_counts: dict, total: int) -> None:
        """FairShareWaveFormer hook: per-tenant kernel attribution + the
        pinned wave metrics (one launch, its signature volume, how many
        tenants shared it)."""
        if self._accounting is not None:
            for tenant, count in tenant_counts.items():
                self._accounting.record_wave(tenant, count)
        m = self._metrics
        if m is not None:
            m.count_wave_launches.add(1)
            m.count_wave_signatures.add(total)
            m.count_wave_tenants.add(len(tenant_counts))
            for tenant, count in tenant_counts.items():
                _with_tenant(m.count_wave_signatures, tenant).add(count)

    @property
    def address(self) -> Address:
        """The bound address (with the real port once started)."""
        return self._address

    def start(self) -> None:
        if isinstance(self._address, str):
            try:
                os.unlink(self._address)
            except OSError:
                pass
            listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            listener.bind(self._address)
        elif self._secret is None and self._tenants is None:
            raise ValueError(
                "TCP sidecar mode requires auth_secret or tenants: an "
                "unauthenticated TCP listener hands free verification "
                "cycles to anyone who can reach the port (use a unix "
                "socket for same-host deployments)"
            )
        else:
            listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            listener.bind(tuple(self._address))
            self._address = listener.getsockname()
        listener.listen(64)
        self._listener = listener
        threading.Thread(
            target=self._accept_loop, daemon=True, name="sidecar-accept"
        ).start()

    def stop(self) -> None:
        self._stopping = True
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        if self._former is not None:
            self._former.close()
        if isinstance(self._address, str):
            try:
                os.unlink(self._address)
            except OSError:
                pass

    def _accept_loop(self) -> None:
        while not self._stopping:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            addr = "local"  # AF_UNIX peers have no address; quota them as one
            if conn.family == socket.AF_INET:
                conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                try:
                    addr = conn.getpeername()[0]
                except OSError:
                    addr = "?"
            guard = self.guard
            if guard is not None and not guard.admit(addr):
                try:
                    conn.close()
                except OSError:
                    pass
                continue
            # Daemon threads, deliberately untracked: connections churn for
            # the life of the sidecar and holding dead Thread objects would
            # grow without bound; stop() only needs the listener.
            threading.Thread(
                target=self._serve_conn, args=(conn, addr), daemon=True,
                name="sidecar-conn",
            ).start()

    def _handshake(
        self, conn: socket.socket, addr: str = "?"
    ) -> Optional[tuple[bytes, str]]:
        """MUTUAL challenge-response: the peer proves knowledge of A secret
        over (server_nonce, client_nonce), the server proves it back, and
        both derive the per-connection session key that MACs every frame.
        Returns ``(session_key, tenant_id)`` — tenant ``""`` for the legacy
        shared secret — or None to drop the peer.  The tenant variant is
        byte-compatible on the wire: the server identifies the tenant by
        WHICH secret validates the proof (the tenant id is bound inside the
        HMACs, not sent in clear).  Runs under a deadline so an idle
        connect cannot park a thread."""
        conn.settimeout(
            self.guard.handshake_timeout
            if self.guard is not None else _HANDSHAKE_TIMEOUT
        )
        try:
            server_nonce = os.urandom(_NONCE_LEN)
            conn.sendall(server_nonce)
            client_nonce = _recv_exact(conn, _NONCE_LEN)
            answer = _recv_exact(conn, hashlib.sha256().digest_size)
            matched: Optional[tuple[bytes, str, bytes, bytes]] = None
            if self._secret is not None:
                expect = _hmac256(
                    self._secret, _CLIENT_PROOF, server_nonce, client_nonce
                )
                if hmac.compare_digest(answer, expect):
                    matched = (
                        self._secret,
                        "",
                        _hmac256(
                            self._secret, _SERVER_PROOF,
                            server_nonce, client_nonce,
                        ),
                        _hmac256(
                            self._secret, _SESSION_KEY,
                            server_nonce, client_nonce,
                        ),
                    )
            if matched is None and self._tenants:
                for tenant, secret in self._tenants.items():
                    tid = tenant.encode()
                    expect = _hmac256(
                        secret, _TENANT_PROOF, tid, server_nonce, client_nonce
                    )
                    if hmac.compare_digest(answer, expect):
                        matched = (
                            secret,
                            tenant,
                            _hmac256(
                                secret, _SERVER_PROOF, tid,
                                server_nonce, client_nonce,
                            ),
                            _hmac256(
                                secret, _SESSION_KEY, tid,
                                server_nonce, client_nonce,
                            ),
                        )
                        break
            if matched is None:
                # A wrong proof (wrong secret, or a replayed transcript
                # against this connection's fresh nonce) is provably
                # malformed: strike toward a ban.
                if self.guard is not None:
                    self.guard.strike(addr, "bad_hello")
                logger.warning("sidecar: rejected peer with bad auth answer")
                return None
            _, tenant, server_proof, session_key = matched
            conn.sendall(server_proof)
            return session_key, tenant
        except socket.timeout:
            # Connect-and-idle: the peer never attempted the handshake.
            if self.guard is not None:
                self.guard.handshake_timed_out(addr)
            logger.warning("sidecar: peer failed to complete auth handshake")
            return None
        except (ConnectionError, OSError):
            # EOF mid-handshake: a crashed honest client looks the same, so
            # this path books nothing (quotas still bound connect-floods).
            logger.warning("sidecar: peer failed to complete auth handshake")
            return None

    def _serve_conn(self, conn: socket.socket, addr: str = "local") -> None:
        write_lock = threading.Lock()
        # Per-connection in-flight bound: acquire before dispatch, release
        # when the worker answers; a saturated peer blocks HERE (TCP
        # backpressure) instead of growing the thread count.
        slots = threading.BoundedSemaphore(self._max_inflight)
        guard = self.guard
        mac_key: Optional[bytes] = None
        tenant = ""
        try:
            if self._secret is not None or self._tenants is not None:
                outcome = self._handshake(conn, addr)
                if outcome is None:
                    return
                mac_key, tenant = outcome
            # Socket timeout bounds worker SENDS to a non-reading peer; the
            # read loop below treats frame-boundary timeouts as idle.
            conn.settimeout(self._io_timeout)
            while True:
                try:
                    req_id, payload = _read_frame(
                        conn, self._max_frame, mac_key, b"c2s"
                    )
                except _FrameTooLarge:
                    if guard is not None:
                        guard.strike(addr, "oversized")
                    return
                except _MacMismatch:
                    if guard is not None:
                        guard.strike(addr, "bad_hello")
                    return
                except _MidFrameStall:
                    if guard is not None:
                        guard.strike(addr, "stall")
                    return
                except TimeoutError:
                    continue  # idle peer at a frame boundary
                slots.acquire()
                threading.Thread(
                    target=self._serve_request,
                    args=(
                        conn, write_lock, slots, mac_key, tenant,
                        req_id, payload,
                    ),
                    daemon=True,
                    name="sidecar-verify",
                ).start()
        except (ConnectionError, OSError):
            pass
        finally:
            if guard is not None:
                guard.release(addr)
            try:
                conn.close()
            except OSError:
                pass

    def _verify(self, tenant: str, messages, signatures, keys):
        """Single-tenant mode serves straight on the engine (PR-4 path);
        multi-tenant mode goes through the fair-share wave former, which may
        raise :class:`consensus_tpu.models.engine.AdmissionReject`."""
        if self._former is None:
            return self._engine.verify_batch(messages, signatures, keys)
        results = self._former.submit(tenant, messages, signatures, keys)
        m = self._metrics
        if m is not None:
            m.count_admission_accepted.add(1)
            _with_tenant(m.count_admission_accepted, tenant).add(1)
            m.admission_queue_depth.set(self._former.pending_count)
        return results

    def _serve_request(
        self, conn, write_lock, slots, mac_key, tenant: str, req_id: int,
        payload: bytes,
    ) -> None:
        try:
            messages, signatures, keys = decode_request(payload)
            results = np.asarray(self._verify(tenant, messages, signatures, keys))
            if len(results) != len(messages):
                raise ValueError("engine returned wrong result count")
            # Degraded-health surfacing: sampled at answer time so the
            # status tracks the supervisor's CURRENT rung (and the
            # coalescer's suspect flag), not the state when the request
            # was queued.
            degraded = bool(
                getattr(self._engine, "degraded", False)
                or getattr(self._engine, "device_suspect", False)
            )
            status = b"\x03" if degraded else b"\x00"
            body = status + np.asarray(results, dtype=np.uint8).tobytes()
        except _AdmissionReject as rej:
            # Structured, immediate, and NOT an error to log at exception
            # level: the tenant is over quota, the service is fine.
            logger.warning(
                "sidecar admission reject: tenant %r depth %d limit %d",
                tenant, rej.queue_depth, rej.limit,
            )
            body = (
                b"\x02"
                + struct.pack(">II", rej.queue_depth, rej.limit)
                + tenant.encode()
            )
            m = self._metrics
            if m is not None:
                m.count_admission_rejects.add(1)
                _with_tenant(m.count_admission_rejects, tenant).add(1)
        except Exception as exc:  # serve the error, keep the connection
            logger.exception("sidecar verify request %d failed", req_id)
            body = b"\x01" + repr(exc).encode()
        try:
            with write_lock:
                try:
                    _write_frame(conn, req_id, body, mac_key, b"s2c")
                except OSError:
                    # Client gone OR not reading (send timed out): close
                    # WHILE STILL HOLDING write_lock — a partial frame may
                    # be on the wire, and the next writer interleaving into
                    # it would splice its header bytes into this frame's
                    # declared payload (a forged verdict on un-MAC'd unix
                    # connections).  A dead fd makes every queued writer
                    # fail fast and recovers the read loop's slots.
                    try:
                        conn.close()
                    except OSError:
                        pass
                    raise
        except OSError:
            pass
        finally:
            slots.release()


class SidecarVerifierClient:
    """Drop-in ``engine`` (the ``verify_batch`` contract) that forwards
    batches to a :class:`VerifySidecarServer` over one multiplexed
    connection.  Thread-safe: concurrent calls are tagged with request ids
    and a single reader thread routes responses.

    ``local_engine``: optional engine whose ``verify_host`` serves as the
    escape hatch — if the sidecar is unreachable, errors, or stalls past
    ``request_timeout``, verification falls back to the local host path
    (logged loudly) instead of wedging the replica.

    ``bypass_below``: batches smaller than this verify locally (via
    ``local_engine.verify_host``) without a socket round trip — quorum-sized
    checks and single signatures gain nothing from the device and shouldn't
    pay the sidecar RTT + coalescing window.

    ``auth_secret``: shared secret answering the server's TCP
    challenge-response handshake (must match the server's).

    ``tenant``: authenticate as this tenant on a multi-tenant server —
    ``auth_secret`` then holds the PER-TENANT secret and the handshake
    binds the tenant id into every derivation.  Leave None for the legacy
    single-tenant handshake.

    ``fleet`` / ``fleet_id``: placement-aware retry.  ``fleet`` is a
    :class:`~consensus_tpu.ingress.placement.SidecarFleet` and ``fleet_id``
    this client's own server id on its ring.  A structured
    :class:`TenantAdmissionReject` then means THIS server's tenant queue is
    full, not that the fleet is — the batch is handed to the ring's next
    candidate for the tenant (pinned ``ingress_reroute_total`` counts the
    handoffs) before any local fallback.
    """

    def __init__(
        self,
        address: Address,
        *,
        local_engine=None,
        request_timeout: float = 60.0,
        connect_timeout: float = 5.0,
        bypass_below: int = 0,
        probe_interval: float = 10.0,
        auth_secret: Optional[bytes] = None,
        tenant: Optional[str] = None,
        fault_plan=None,
        tracer=None,
        fleet=None,
        fleet_id: Optional[str] = None,
    ) -> None:
        #: Optional testing FaultPlan (consensus_tpu/testing/faults.py):
        #: arms the sidecar.send.io_error / sidecar.recv.short_read seams.
        self.fault_plan = fault_plan
        #: Optional decision-lifecycle tracer.  verify_batch runs on caller
        #: threads, so posted instants rely on the tracer's internal lock.
        self._tracer = tracer
        self._address = address
        self._timeout = request_timeout
        self._connect_timeout = connect_timeout
        self._local = local_engine
        self._bypass_below = bypass_below if local_engine is not None else 0
        self._probe_interval = probe_interval
        self._secret = auth_secret
        self._tenant = tenant
        if tenant is not None and auth_secret is None:
            raise ValueError("tenant mode requires auth_secret (the tenant secret)")
        self._fleet = fleet
        self._fleet_id = fleet_id
        if fleet is not None and fleet_id is None:
            raise ValueError("fleet mode requires fleet_id (this server's ring id)")
        self._mac_key: Optional[bytes] = None  # per-connection session key
        self._lock = threading.Lock()  # guards socket create + pending map
        self._sock: Optional[socket.socket] = None
        #: Serializes SENDS on the current socket, separately from
        #: ``_lock``: a send that stalls (wedged sidecar, full kernel
        #: buffer) must not block verify calls that only need the pending
        #: map (ADVICE r4 medium).  Replaced together with the socket.
        self._wlock = threading.Lock()
        self._pending: dict[int, dict] = {}
        self._next_id = 0
        self._reader: Optional[threading.Thread] = None
        #: Set after a request TIMES OUT (sidecar wedged, not just dead):
        #: later calls skip the stall and go straight to the local fallback
        #: while a background probe watches for recovery.
        self._suspect = False
        self._closed = False

    # -- engine contract ---------------------------------------------------

    def verify_batch(self, messages, signatures, public_keys) -> np.ndarray:
        n = len(messages)
        if not (n == len(signatures) == len(public_keys)):
            raise ValueError("batch length mismatch")
        if n == 0:
            return np.zeros(0, dtype=bool)
        tracer = self._tracer
        if tracer is not None and tracer.enabled:
            tracer.instant("net", "sidecar.verify", n=n)
        if self._suspect and self._local is not None:
            # Wedged sidecar: don't stall request_timeout on every call —
            # the background probe clears the flag when it recovers.
            return np.asarray(
                self._local.verify_host(messages, signatures, public_keys)
            )
        if n < self._bypass_below:
            return np.asarray(
                self._local.verify_host(messages, signatures, public_keys)
            )
        try:
            result = self._roundtrip(messages, signatures, public_keys)
        except TenantAdmissionReject as reject:
            rerouted = self._fleet_reroute(
                messages, signatures, public_keys, reject
            )
            if rerouted is not None:
                return rerouted
            if self._local is None:
                raise
            logger.error(
                "sidecar admission reject (%r) with no accepting fleet peer "
                "— falling back to LOCAL host verification for %d signatures",
                reject,
                n,
            )
            if tracer is not None and tracer.enabled:
                tracer.instant("net", "sidecar.fallback", n=n)
            return np.asarray(
                self._local.verify_host(messages, signatures, public_keys)
            )
        except Exception as exc:
            if self._local is None:
                raise
            if isinstance(exc, TimeoutError) and not isinstance(
                exc, QueueStallTimeout
            ):
                self._mark_suspect()
            logger.error(
                "sidecar verify failed (%r) — falling back to LOCAL host "
                "verification for %d signatures",
                exc,
                n,
            )
            if tracer is not None and tracer.enabled:
                tracer.instant("net", "sidecar.fallback", n=n)
            return np.asarray(
                self._local.verify_host(messages, signatures, public_keys)
            )
        return result

    def _fleet_reroute(self, messages, signatures, keys, reject):
        """Placement-aware retry: walk the hash ring's remaining candidates
        for our tenant and hand the batch to the first peer that accepts
        it.  Per-tenant admission pressure is a PER-SERVER property, so the
        rendezvous order gives every tenant the same deterministic failover
        chain.  Returns None when no fleet is configured or every peer
        refuses (the caller then falls back locally / re-raises)."""
        fleet = self._fleet
        if fleet is None:
            return None
        tenant = self._tenant or ""
        for server_id in fleet.candidates(tenant):
            if server_id == self._fleet_id:
                continue
            peer = fleet.client_for(server_id)
            if peer is self:
                continue
            try:
                result = peer.verify_batch(messages, signatures, keys)
            except Exception:
                continue  # rejected or unreachable peer: try the next
            fleet.on_reroute(tenant, self._fleet_id, server_id)
            logger.warning(
                "tenant %r admission-rejected by %r (depth %d/%d) — "
                "rerouted batch to fleet peer %r",
                tenant, self._fleet_id, reject.queue_depth, reject.limit,
                server_id,
            )
            return result
        return None

    def _mark_suspect(self) -> None:
        """A timed-out request means the sidecar is wedged (its device call
        hung), not merely dead: drop the socket so other in-flight waiters
        fail over immediately, and probe for recovery in the background."""
        with self._lock:
            if self._suspect or self._closed:
                already = True
            else:
                self._suspect = True
                already = False
            sock = self._sock
        if already:
            return
        logger.error(
            "sidecar did not answer within %.1fs — marking it suspect; "
            "verification continues on the LOCAL host path until a probe "
            "succeeds",
            self._timeout,
        )
        if sock is not None:
            self._drop_socket(sock)
        threading.Thread(
            target=self._probe_loop, daemon=True, name="sidecar-probe"
        ).start()

    def _probe_loop(self) -> None:
        while True:
            time.sleep(self._probe_interval)
            with self._lock:
                if self._closed or not self._suspect:
                    return
            try:
                # An empty batch exercises the full socket + server + engine
                # dispatch path cheaply.
                self._roundtrip([], [], [], timeout=self._probe_interval)
            except Exception:
                continue
            with self._lock:
                self._suspect = False
            logger.warning("sidecar recovered — resuming sidecar verification")
            return

    def verify_host(self, messages, signatures, public_keys) -> np.ndarray:
        """Escape-hatch seam (used if this client is itself wrapped in a
        coalescer): local host verification, bypassing the sidecar."""
        if self._local is None:
            raise RuntimeError("no local_engine configured")
        return np.asarray(
            self._local.verify_host(messages, signatures, public_keys)
        )

    # -- plumbing ----------------------------------------------------------

    def _ensure_connected(self) -> socket.socket:
        if self._sock is not None:
            return self._sock
        if isinstance(self._address, str):
            sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        else:
            sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sock.settimeout(self._connect_timeout)
        sock.connect(
            self._address if isinstance(self._address, str)
            else tuple(self._address)
        )
        self._mac_key = None
        if self._secret is not None:
            # Legacy and tenant handshakes are byte-identical on the wire;
            # tenant mode swaps the proof domain tag and binds the tenant id
            # into every derivation.
            tid = None if self._tenant is None else self._tenant.encode()
            try:
                server_nonce = _recv_exact(sock, _NONCE_LEN)
                client_nonce = os.urandom(_NONCE_LEN)
                if tid is None:
                    answer = _hmac256(
                        self._secret, _CLIENT_PROOF, server_nonce, client_nonce
                    )
                    expect = _hmac256(
                        self._secret, _SERVER_PROOF, server_nonce, client_nonce
                    )
                else:
                    answer = _hmac256(
                        self._secret, _TENANT_PROOF, tid,
                        server_nonce, client_nonce,
                    )
                    expect = _hmac256(
                        self._secret, _SERVER_PROOF, tid,
                        server_nonce, client_nonce,
                    )
                sock.sendall(client_nonce + answer)
                proof = _recv_exact(sock, hashlib.sha256().digest_size)
                if not hmac.compare_digest(proof, expect):
                    raise ConnectionError(
                        "sidecar failed mutual auth (bad server proof)"
                    )
            except BaseException:
                # Close on EVERY failed-handshake path (rejection, EOF,
                # timeout) — each verify retry would otherwise abandon an
                # open fd to the GC.
                sock.close()
                raise
            if tid is None:
                self._mac_key = _hmac256(
                    self._secret, _SESSION_KEY, server_nonce, client_nonce
                )
            else:
                self._mac_key = _hmac256(
                    self._secret, _SESSION_KEY, tid, server_nonce, client_nonce
                )
        # A real timeout (not None) so a blocked sendall on a wedged sidecar
        # surfaces as TimeoutError instead of hanging the sender forever;
        # the reader treats frame-boundary timeouts as idle (ADVICE r4).
        sock.settimeout(self._timeout)
        if sock.family == socket.AF_INET:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock = sock
        self._wlock = threading.Lock()
        self._reader = threading.Thread(
            target=self._read_loop, args=(sock, self._mac_key), daemon=True,
            name="sidecar-client-reader",
        )
        self._reader.start()
        return sock

    def _roundtrip(
        self, messages, signatures, keys, *, timeout: Optional[float] = None
    ) -> np.ndarray:
        payload = encode_request(messages, signatures, keys)
        waiter = {"event": threading.Event(), "body": None}
        with self._lock:
            sock = self._ensure_connected()
            wlock = self._wlock
            mac_key = self._mac_key
            req_id = self._next_id
            self._next_id += 1
            waiter["sock"] = sock
            self._pending[req_id] = waiter
        # OUTSIDE self._lock: a send that stalls on a full kernel buffer
        # (wedged sidecar) must not block other verify calls — they only
        # need the pending map.  The per-socket wlock keeps frames whole;
        # the socket's timeout turns a dead stall into TimeoutError, which
        # verify_batch maps to suspect + local failover.  ONE absolute
        # deadline covers every stage (wlock queueing, the send itself, the
        # response wait) so a call behind a stalled sender still fails over
        # within its own budget rather than 3x it.
        budget = timeout if timeout is not None else self._timeout
        # Real-thread I/O deadline: this path runs outside the scheduler.
        deadline = time.monotonic() + budget  # wallclock-ok

        def _give_up_queued(reason: str):
            # Budget spent without touching the wire: the socket is healthy,
            # so concurrent waiters keep it — only this call bows out, and
            # the distinct type keeps verify_batch from marking the sidecar
            # suspect over what is only local queueing pressure.  Structured
            # so a multi-tenant operator sees WHO gave up and behind how
            # many locally queued requests.
            with self._lock:
                self._pending.pop(req_id, None)
                depth = len(self._pending)
            return SidecarQueueStall(
                reason, tenant=self._tenant or "", queue_depth=depth,
                deadline=budget,
            )

        if not wlock.acquire(timeout=budget):
            raise _give_up_queued(f"sidecar send queue stalled for {budget}s")
        try:
            if waiter["event"].is_set():
                raise ConnectionError("sidecar connection lost before send")
            if deadline - time.monotonic() <= 0:  # wallclock-ok
                raise _give_up_queued(
                    f"sidecar send queue stalled for {budget}s"
                )
            # The send runs under the socket's FIXED timeout (per-call
            # shrinking would race the reader thread recv'ing on the same
            # socket mid-frame), so the true worst case is queue-wait +
            # one socket timeout.  A timeout DURING sendall leaves a
            # partial frame on the wire, so that path drops the socket.
            try:
                plan = self.fault_plan
                if plan is not None:
                    plan.io_error("sidecar.send.io_error")
                _write_frame(sock, req_id, payload, mac_key, b"c2s")
            except OSError as exc:
                with self._lock:
                    self._pending.pop(req_id, None)
                self._drop_socket(sock)
                raise exc
        except ConnectionError:
            with self._lock:
                self._pending.pop(req_id, None)
            raise
        finally:
            wlock.release()
        if not waiter["event"].wait(max(0.0, deadline - time.monotonic())):  # wallclock-ok
            with self._lock:
                self._pending.pop(req_id, None)
            raise TimeoutError(f"sidecar did not answer within {budget}s")
        body = waiter["body"]
        if body is None:
            raise ConnectionError("sidecar connection lost mid-request")
        if body[0] == 2:
            depth, limit = struct.unpack_from(">II", body, 1)
            raise TenantAdmissionReject(
                body[9:].decode(errors="replace"), depth, limit
            )
        if body[0] == 1:
            raise RuntimeError(f"sidecar error: {body[1:].decode(errors='replace')}")
        if body[0] not in (0, 3):
            raise RuntimeError(f"unknown sidecar status byte {body[0]}")
        if self._fleet is not None and self._fleet_id is not None:
            # Status 3: results from a DEGRADED engine — verdicts are
            # correct (the supervisor's host twin is ground truth) but the
            # ring should steer reroutes at healthy peers first; a status-0
            # answer means the supervisor re-promoted, clearing the mark.
            self._fleet.note_degraded(self._fleet_id, body[0] == 3)
        results = np.frombuffer(body[1:], dtype=np.uint8).astype(bool)
        if len(results) != len(messages):
            raise ValueError("sidecar returned wrong result count")
        return results

    def _read_loop(self, sock: socket.socket, mac_key: Optional[bytes]) -> None:
        try:
            while True:
                plan = self.fault_plan
                if plan is not None and plan.trip("sidecar.recv.short_read"):
                    # Simulate the response link dying mid-frame: the finally
                    # block drops the socket, failing in-flight waiters over
                    # to the local path exactly as a real short read would.
                    return
                try:
                    req_id, body = _read_frame(
                        sock, _MAX_FRAME, mac_key, b"s2c", patient=True
                    )
                except TimeoutError:
                    continue  # unreachable with patient=True; belt-and-braces
                with self._lock:
                    waiter = self._pending.pop(req_id, None)
                if waiter is not None:
                    waiter["body"] = body
                    waiter["event"].set()
        except (ConnectionError, OSError):
            pass
        finally:
            self._drop_socket(sock)

    def _drop_socket(self, sock: socket.socket) -> None:
        """Fail THIS socket's in-flight requests and let the next call
        reconnect.  Waiters registered on a newer socket are left alone — a
        stale reader thread's teardown racing a reconnect must not wipe
        fresh requests (ADVICE r4)."""
        with self._lock:
            if self._sock is sock:
                self._sock = None
            stale = {
                rid: w for rid, w in self._pending.items()
                if w.get("sock") is sock
            }
            for rid in stale:
                del self._pending[rid]
        try:
            sock.close()
        except OSError:
            pass
        for waiter in stale.values():
            waiter["event"].set()  # body stays None -> ConnectionError

    def close(self) -> None:
        with self._lock:
            self._closed = True
        sock = self._sock
        if sock is not None:
            self._drop_socket(sock)


__all__ = [
    "VerifySidecarServer",
    "SidecarVerifierClient",
    "QueueStallTimeout",
    "SidecarQueueStall",
    "TenantAdmissionReject",
    "encode_request",
    "decode_request",
]
