"""Shared hardened framing for every raw-TCP listener.

Four listener families speak length-prefixed frames over real sockets: the
consensus transport (net/transport.py), the sync catch-up listener
(sync/transport.py), the multi-tenant verify sidecar (net/sidecar.py), and
the deploy-rig control servers (deploy/control.py).  Before this module,
each carried its own copy of ``recv_exact`` — and the copies drifted: the
consensus transport checked the frame cap before reading, the sync/control
copies called ``conn.recv(n)`` with the ATTACKER'S claimed length, which
CPython turns into an n-byte buffer allocation before a single payload
byte arrives.  A peer that writes ``\\x80\\x00\\x00\\x00`` as a length
header could cost a replica 2 GiB of transient allocations for 4 sent
bytes.

This module is the single copy:

* :func:`recv_exact` — reads in bounded chunks into a growing buffer, so
  allocation is proportional to bytes actually RECEIVED, never to bytes
  claimed.  Optional per-chunk progress deadline (slow-loris defense):
  once a frame has started arriving, each successive chunk must land
  within ``progress_timeout`` or :class:`FrameStall` is raised —
  ``patient_first`` lets the FIRST byte wait indefinitely, which is what
  an honest-but-idle consensus connection between frames looks like.
* :class:`ListenerGuard` — per-listener abuse accounting shared by all
  four families: per-peer + global inbound connection quotas (checked at
  accept, before any read), a per-peer malformed-frame strike counter,
  and temporary bans.  Every defense event is triple-booked when the
  hooks are attached: a pinned metric (``net_malformed_total{kind}`` /
  ``net_handshake_timeout_total`` / ``net_peer_banned_total`` /
  ``net_conn_rejected_total``), a ``net.abuse`` trace instant, and an
  ``on_ban`` callback the deploy rig points at the flight recorder.

Censorship-safety (SAFETY.md §16): quotas bound CONCURRENCY, not
identity — an honest peer holds one connection per direction and never
approaches the per-peer cap.  Strikes only accrue on frames that are
*provably* malformed before any protocol state is touched (oversized
length claim, failed HELLO/HMAC proof, pre-HELLO traffic, a violated
sender pin, mid-frame stalls past the progress deadline) — events an
honest implementation of the wire format cannot produce, whatever the
network does to it, because TCP delivers its bytes intact and in order or
kills the connection.  Bans are temporary (``ban_seconds``) and the Comm
contract is unreliable fire-and-forget: frames lost to a ban window are
frames the protocol already tolerates losing, and the sender's bounded
reconnect/backoff path outlives any ban, so a mistakenly banned honest
peer regains service after expiry without operator action.

Real sockets mean real time: deadlines and ban expiries below are audited
``# wallclock-ok`` escapes, same as the rest of the deploy plane.
"""

from __future__ import annotations

import logging
import select
import socket
import threading
import time
from typing import Callable, Dict, Optional

logger = logging.getLogger("consensus_tpu.net")

#: recv() granularity: allocation per read is bounded by this, not by the
#: peer's claimed frame length.
RECV_CHUNK_BYTES = 64 * 1024

#: Strike kinds a listener may book (the ``kind`` label on
#: ``net_malformed_total``).  Pinned here so the four families cannot
#: invent divergent vocabularies.
MALFORMED_KINDS = (
    "oversized",    # claimed frame length beyond the listener's cap
    "bad_hello",    # HELLO/HMAC proof failed verification
    "pre_hello",    # payload traffic before the handshake completed
    "sender_pin",   # frame claimed a different sender than the pinned one
    "stall",        # mid-frame progress deadline exceeded (slow-loris)
    "garbage",      # frame payload failed structural validation
)


class FrameStall(OSError):
    """A peer stopped making progress mid-frame (slow-loris).

    ``received`` is how many bytes of the read had arrived when the
    deadline fired: 0 means the peer never started this frame (a listener
    in its handshake phase books that as a handshake timeout, not a
    strike), > 0 means a frame stalled mid-flight (provably malformed)."""

    def __init__(self, message: str, received: int = 0) -> None:
        super().__init__(message)
        self.received = received


def recv_exact(
    conn: socket.socket,
    n: int,
    *,
    progress_timeout: Optional[float] = None,
    patient_first: bool = False,
    preset: bool = False,
) -> Optional[bytes]:
    """Read exactly ``n`` bytes or fail cleanly.

    Cap-check-before-allocate: the buffer grows with bytes actually
    received (bounded :data:`RECV_CHUNK_BYTES` reads), never with the
    claimed length — callers validate ``n`` against their frame cap
    before calling, and even an unvalidated huge ``n`` costs memory only
    as the attacker actually sends it.

    Returns None on EOF / reset / (when no progress deadline is armed)
    timeout, exactly like the per-listener copies this replaces.  With
    ``progress_timeout`` set, every chunk must arrive within the deadline
    or :class:`FrameStall` is raised so the caller can book the stall;
    ``patient_first=True`` exempts the wait for the FIRST byte (an idle
    connection between frames is honest, a stalled frame is not).

    ``preset=True`` means the caller has put the socket in NON-BLOCKING
    mode for the connection's lifetime: ``recv`` is attempted first (one
    syscall when bytes are already waiting — the honest hot path), and
    the progress deadline is enforced with a ``select`` only when the
    read would actually block.  An armed socket timeout makes CPython
    poll readiness before EVERY recv, which the ``net_abuse`` bench
    family measures as a double-digit per-frame tax at honest line rate;
    try-first pays it only on the reads that actually wait.
    """
    buf = bytearray()
    first = True
    while len(buf) < n:
        if progress_timeout is not None and not preset:
            try:
                conn.settimeout(
                    None if (patient_first and first) else progress_timeout
                )
            except OSError:
                return None
        try:
            chunk = conn.recv(min(n - len(buf), RECV_CHUNK_BYTES))
        except BlockingIOError:
            # preset non-blocking lane: nothing waiting — block on
            # readiness, patiently for a frame's first byte, under the
            # progress deadline once one has started.
            wait = None if (patient_first and first) else progress_timeout
            try:
                ready = select.select([conn], [], [], wait)[0]
            except (OSError, ValueError):
                return None
            if not ready:
                raise FrameStall(
                    f"no progress for {progress_timeout:g}s mid-frame",
                    received=len(buf),
                )
            continue
        except socket.timeout as exc:
            if patient_first and first:
                return None
            if progress_timeout is not None:
                raise FrameStall(
                    f"no progress for {progress_timeout:g}s mid-frame",
                    received=len(buf),
                ) from exc
            return None
        except OSError:
            return None
        if not chunk:
            return None
        buf += chunk
        first = False
    return bytes(buf)


class GuardStats:
    """Cumulative per-listener abuse counters — the health surface the obs
    sampler reads (``wire_abuse`` detector fires on per-sample deltas)."""

    __slots__ = ("malformed", "handshake_timeouts", "bans", "rejected")

    def __init__(self) -> None:
        self.malformed = 0
        self.handshake_timeouts = 0
        self.bans = 0
        self.rejected = 0

    def total(self) -> int:
        return (
            self.malformed + self.handshake_timeouts
            + self.bans + self.rejected
        )


class ListenerGuard:
    """Abuse accounting for one listener: quotas, strikes, temporary bans.

    Thread-safe: accept loops and per-connection receiver threads call in
    concurrently.  Booking hooks (``metrics``: a
    :class:`~consensus_tpu.metrics.MetricsNetwork` bundle; ``tracer``: a
    decision tracer; ``on_ban(addr, kind)``) are all optional and invoked
    outside the lock.
    """

    def __init__(
        self,
        *,
        name: str = "net",
        max_conns_per_peer: int = 32,
        max_conns_total: int = 256,
        strike_limit: int = 3,
        ban_seconds: float = 2.0,
        handshake_timeout: float = 5.0,
        progress_timeout: float = 10.0,
        metrics=None,
        tracer=None,
        on_ban: Optional[Callable[[str, str], None]] = None,
        clock: Optional[Callable[[], float]] = None,
    ) -> None:
        if max_conns_per_peer < 1 or max_conns_total < 1:
            raise ValueError("connection quotas must be >= 1")
        if strike_limit < 1:
            raise ValueError("strike_limit must be >= 1")
        self.name = name
        self.max_conns_per_peer = max_conns_per_peer
        self.max_conns_total = max_conns_total
        self.strike_limit = strike_limit
        self.ban_seconds = ban_seconds
        #: Handshake deadline: a connection must complete HELLO/HMAC within
        #: this budget of being accepted or be dropped.
        self.handshake_timeout = handshake_timeout
        #: Mid-frame progress deadline handed to :func:`recv_exact`.
        self.progress_timeout = progress_timeout
        self.metrics = metrics
        self.tracer = tracer
        self.on_ban = on_ban
        self._clock = clock if clock is not None else time.monotonic  # wallclock-ok
        self._lock = threading.Lock()
        self._conns: Dict[str, int] = {}
        self._total = 0
        self._strikes: Dict[str, int] = {}
        self._bans: Dict[str, float] = {}  # addr -> expiry
        self.stats = GuardStats()

    # --- admission ---------------------------------------------------------

    def admit(self, addr: str) -> bool:
        """Accept-time gate: False (and one ``net_conn_rejected_total``
        booking) when ``addr`` is banned or a quota is full.  Callers MUST
        pair every True with exactly one :meth:`release`."""
        now = self._clock()
        reason = None
        with self._lock:
            expiry = self._bans.get(addr)
            if expiry is not None:
                if now < expiry:
                    reason = "banned"
                else:
                    # Ban expired: a fresh start, strikes forgiven.
                    del self._bans[addr]
                    self._strikes.pop(addr, None)
            if reason is None:
                if self._total >= self.max_conns_total:
                    reason = "global_quota"
                elif self._conns.get(addr, 0) >= self.max_conns_per_peer:
                    reason = "peer_quota"
            if reason is None:
                self._conns[addr] = self._conns.get(addr, 0) + 1
                self._total += 1
            else:
                self.stats.rejected += 1
        if reason is None:
            return True
        self._book_rejected(addr, reason)
        return False

    def release(self, addr: str) -> None:
        """Connection closed: return its quota slot."""
        with self._lock:
            left = self._conns.get(addr, 0) - 1
            if left > 0:
                self._conns[addr] = left
            else:
                self._conns.pop(addr, None)
            if self._total > 0:
                self._total -= 1

    # --- strikes and bans --------------------------------------------------

    def strike(self, addr: str, kind: str) -> bool:
        """Book one malformed frame from ``addr``; returns True when the
        strike crossed the limit and ``addr`` is now temporarily banned.
        ``kind`` must come from :data:`MALFORMED_KINDS`."""
        if kind not in MALFORMED_KINDS:
            raise ValueError(f"unknown malformed kind {kind!r}")
        now = self._clock()
        with self._lock:
            strikes = self._strikes.get(addr, 0) + 1
            self._strikes[addr] = strikes
            self.stats.malformed += 1
            banned = strikes >= self.strike_limit
            if banned:
                self._bans[addr] = now + self.ban_seconds
                self._strikes.pop(addr, None)
                self.stats.bans += 1
        self._book_malformed(addr, kind)
        if banned:
            self._book_ban(addr, kind)
        return banned

    def handshake_timed_out(self, addr: str) -> None:
        """A connection never completed HELLO/HMAC within the deadline."""
        with self._lock:
            self.stats.handshake_timeouts += 1
        metrics = self.metrics
        if metrics is not None:
            metrics.count_handshake_timeout.add(1)
        tracer = self.tracer
        if tracer is not None and tracer.enabled:
            tracer.instant(
                "net", "net.abuse", event="handshake_timeout", peer=addr,
            )
        logger.warning(
            "%s: connection from %s never completed handshake; dropped",
            self.name, addr,
        )

    def is_banned(self, addr: str) -> bool:
        now = self._clock()
        with self._lock:
            expiry = self._bans.get(addr)
            return expiry is not None and now < expiry

    # --- booking (outside the lock) ----------------------------------------

    def _book_rejected(self, addr: str, reason: str) -> None:
        metrics = self.metrics
        if metrics is not None:
            metrics.count_conn_rejected.add(1)
        tracer = self.tracer
        if tracer is not None and tracer.enabled:
            tracer.instant(
                "net", "net.abuse", event="conn_rejected", peer=addr,
                reason=reason,
            )
        logger.warning(
            "%s: rejected connection from %s (%s)", self.name, addr, reason
        )

    def _book_malformed(self, addr: str, kind: str) -> None:
        metrics = self.metrics
        if metrics is not None:
            metrics.count_malformed.with_labels(kind).add(1)
        tracer = self.tracer
        if tracer is not None and tracer.enabled:
            tracer.instant(
                "net", "net.abuse", event="malformed", peer=addr, kind=kind,
            )
        logger.warning(
            "%s: malformed frame (%s) from %s", self.name, kind, addr
        )

    def _book_ban(self, addr: str, kind: str) -> None:
        metrics = self.metrics
        if metrics is not None:
            metrics.count_peer_banned.add(1)
        tracer = self.tracer
        if tracer is not None and tracer.enabled:
            tracer.instant(
                "net", "net.abuse", event="peer_banned", peer=addr, kind=kind,
            )
        on_ban = self.on_ban
        if on_ban is not None:
            try:
                on_ban(addr, kind)
            except Exception:
                logger.exception("%s: on_ban hook failed", self.name)
        logger.warning(
            "%s: peer %s banned for %gs after %d strikes (last: %s)",
            self.name, addr, self.ban_seconds, self.strike_limit, kind,
        )


__all__ = [
    "FrameStall",
    "GuardStats",
    "ListenerGuard",
    "MALFORMED_KINDS",
    "RECV_CHUNK_BYTES",
    "recv_exact",
]
