"""The Consensus facade: wires and owns every internal component of one
replica, and is the only object an application touches.

Parity: reference pkg/consensus/consensus.go (522 LoC): lifecycle
(``start``/``stop``), request ingress (``submit_request``), message ingress
(``handle_message``/``handle_request``), crash-restore point computation
(consensus.go:464-504), and dynamic reconfiguration (consensus.go:166-252).

The replica runs entirely on the injected scheduler: transport and
application threads must hand work in via the facade, which posts onto the
scheduler (in tests the SimScheduler is driven directly, so posts execute
deterministically).
"""

from __future__ import annotations

import logging
from typing import Callable, Optional, Sequence

from consensus_tpu.api.deps import (
    Application,
    Assembler,
    Comm,
    MembershipNotifier,
    RequestInspector,
    Signer,
    Synchronizer,
    Verifier,
    WriteAheadLog,
)
from consensus_tpu.config import Configuration
from consensus_tpu.core.batcher import Batcher
from consensus_tpu.core.collector import StateCollector
from consensus_tpu.core.controller import Controller
from consensus_tpu.core.heartbeat import HeartbeatMonitor
from consensus_tpu.core.pool import PoolOptions, RequestPool
from consensus_tpu.core.state import InFlightData, PersistedState, ProposalMaker
from consensus_tpu.core.view import View
from consensus_tpu.metrics import Metrics
from consensus_tpu.runtime.scheduler import Scheduler
from consensus_tpu.trace.tracer import tracer_from_config
from consensus_tpu.types import Checkpoint, Proposal, Reconfig, Signature
from consensus_tpu.wire import (
    ConsensusMessage,
    EpochTagged,
    ViewMetadata,
    decode_view_metadata,
)

logger = logging.getLogger("consensus_tpu.consensus")


class Consensus:
    """One BFT replica."""

    def __init__(
        self,
        *,
        config: Configuration,
        scheduler: Scheduler,
        comm: Comm,
        application: Application,
        assembler: Assembler,
        wal: WriteAheadLog,
        signer: Signer,
        verifier: Verifier,
        request_inspector: RequestInspector,
        synchronizer: Synchronizer,
        wal_initial_content: Sequence[bytes] = (),
        last_proposal: Optional[Proposal] = None,
        last_signatures: Sequence[Signature] = (),
        membership_notifier: Optional[MembershipNotifier] = None,
        metrics: Optional[Metrics] = None,
        tracer=None,
    ) -> None:
        self.config = config
        self.scheduler = scheduler
        self.comm = comm
        #: The membership epoch this replica believes it is in.  Epoch 0 is
        #: the boot membership; every applied membership-change Reconfig that
        #: carries a ``membership`` config advances it.
        self.membership_epoch = 0
        if config.epoch_tagging:
            # Stamp outbound consensus traffic with our epoch; ingress
            # (handle_message) drops other epochs before they reach the
            # collectors.  The wrapper reads membership_epoch live, so the
            # post-reconfig rebuild needs no re-wiring.
            self.comm = _EpochStampingComm(comm, self)
        self.application = application
        self.assembler = assembler
        self.wal = wal
        self.signer = signer
        self.verifier = verifier
        self.request_inspector = request_inspector
        self.synchronizer = synchronizer
        self.wal_initial_content = list(wal_initial_content)
        self.last_proposal = last_proposal or Proposal()
        self.last_signatures = tuple(last_signatures)
        self.membership_notifier = membership_notifier
        self.metrics = metrics or Metrics()
        # Decision-lifecycle tracing: default-off (the shared no-op keeps
        # every instrumented site to one attribute check).  An embedder may
        # inject a tracer to share one event stream across components it
        # builds itself (e.g. the sync client).
        if tracer is None:
            tracer = tracer_from_config(
                config.trace, scheduler.now, pid=config.self_id
            )
        self.tracer = tracer
        if hasattr(synchronizer, "attach_tracer"):
            synchronizer.attach_tracer(tracer)
        # The WAL is constructed by the embedder (it may pre-exist restart);
        # attach the facade's WAL bundle here so wal_count_of_files is live
        # without the embedder threading metrics twice.  Parity: reference
        # pkg/wal NewMetrics wiring in consensus.go.
        if (
            hasattr(wal, "attach_metrics")
            and getattr(wal, "_metrics", None) is None
        ):
            wal.attach_metrics(self.metrics.wal)
        if hasattr(wal, "attach_consensus_metrics"):
            wal.attach_consensus_metrics(self.metrics.consensus)
        if hasattr(wal, "attach_tracer"):
            wal.attach_tracer(tracer)

        self.nodes: tuple[int, ...] = ()
        self.controller: Optional[Controller] = None
        self.view_changer = None  # set by _create_components when available
        self.checkpoint = Checkpoint()
        self._running = False

    # --------------------------------------------------------------- config

    def validate_configuration(self, nodes: Sequence[int]) -> None:
        """Parity: reference consensus.go:341-363."""
        self.config.validate()
        node_set = set()
        for node in nodes:
            if node == 0:
                raise ValueError(f"node id 0 is not permitted: {nodes}")
            node_set.add(node)
        if self.config.self_id not in node_set:
            raise ValueError(
                f"nodes {list(nodes)} do not contain self id {self.config.self_id}"
            )
        if len(node_set) != len(nodes):
            raise ValueError(f"nodes contain duplicate ids: {list(nodes)}")

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        """Boot (or re-boot after a crash): restore protocol position from
        the last decision's metadata + the WAL tail, then start components.

        Parity: reference consensus.go:107-164."""
        nodes = list(self.comm.nodes())
        self.validate_configuration(nodes)
        self.nodes = tuple(sorted(nodes))

        self.in_flight = InFlightData()
        self.state = PersistedState(
            self.wal, self.in_flight, entries=self.wal_initial_content
        )
        self.checkpoint.set(self.last_proposal, self.last_signatures)

        md = (
            decode_view_metadata(self.last_proposal.metadata)
            if self.last_proposal.metadata
            else ViewMetadata()
        )
        view, seq, dec = self._set_view_and_seq(
            md.view_id, md.latest_sequence, md.decisions_in_view
        )

        self._create_components()
        # Sequence i was delivered -> we expect proposal i+1 next.
        self._start_components(view, seq + 1, dec)
        self._readmit_abandoned()
        if getattr(self.wal, "recovery", None) is not None:
            # Boot quarantined a corrupt WAL suffix: votes this replica
            # already sent may be gone from its durable state, so it joins
            # as a NON-VOTING LEARNER and re-enters the voter set only once
            # verified sync carries its checkpoint past the release bound
            # (SAFETY.md §13).
            self.controller.fence_as_learner(self.controller.latest_seq())
        self._running = True

    def _readmit_abandoned(self) -> None:
        """Re-admit the requests of pipelined slots the WAL restore abandoned
        above the oldest undecided sequence.  Those batches were pre-prepared
        but never commit-signed anywhere (SAFETY.md §5), so the only cost of
        dropping the slots is losing the requests — unless we hand them back
        to the pool here.  Dedup/removal in the pool makes this idempotent:
        a request that was meanwhile decided (or re-submitted) is refused."""
        abandoned = self.state.take_abandoned()
        if not abandoned:
            return
        raws: list[bytes] = []
        for proposal in abandoned:
            try:
                raws.extend(self.verifier.raw_requests_from_proposal(proposal))
            except Exception:
                logger.exception(
                    "%d: could not unpack an abandoned pipelined proposal; "
                    "its requests must be re-submitted by clients",
                    self.config.self_id,
                )
        logger.info(
            "%d: re-admitting %d request(s) from %d abandoned pipelined slot(s)",
            self.config.self_id, len(raws), len(abandoned),
        )
        for raw in raws:
            self.scheduler.post(
                lambda raw=raw: self.pool.submit(raw), name="readmit-abandoned"
            )

    def _set_view_and_seq(self, view: int, seq: int, dec: int) -> tuple[int, int, int]:
        """Compute the restore point, honoring trailing ViewChange/NewView
        WAL records.  Parity: reference consensus.go:464-504."""
        new_view, new_seq = view, seq
        # Decisions-in-view increments after delivery; genesis starts at 0.
        new_dec = dec + 1 if seq != 0 else 0

        self._restore_view_change = None
        view_change = self.state.load_view_change_if_applicable()
        if view_change is not None and view_change.next_view >= view:
            logger.info("restoring pending view change to view %d", view_change.next_view)
            new_view = view_change.next_view
            self._restore_view_change = view_change

        view_seq = self.state.load_new_view_if_applicable()
        if view_seq is not None:
            nv_view, nv_seq = view_seq
            if nv_seq >= seq:
                logger.info("restoring from new-view record (view %d, seq %d)", nv_view, nv_seq)
                new_view, new_seq, new_dec = nv_view, nv_seq, 0

        # A tail in-flight proposal from a HIGHER view proves that view was
        # installed here pre-crash even though its SavedNewView record was
        # truncated away by the proposal append itself — boot there, not in
        # the checkpoint's stale view (extension beyond reference
        # consensus.go:464-504, which has the same blind spot).
        #
        # Endorsement view-stamping: the _commit_in_flight endorsement tail
        # [vote, proposed, commit] stamps its ProposedRecord with the
        # proposal's ORIGINAL view, not the view change's target.  That is
        # safe here and deliberate: (a) the original view is <= the vote's
        # next_view (the proposal predates the change the vote joined), so
        # with the buried vote restored above this tail check can never
        # drag new_view backwards; (b) the PREPARED pin requires the
        # attestation to carry the proposal EXACTLY as commit-signed —
        # peers match it by equality in check_in_flight, so restamping the
        # embedded records with the target view would fork our own
        # attestation from the signature we already minted against the
        # original-view metadata.  The rejoin to the change's target is
        # carried by _restore_view_change (the vote), not by this record.
        # Pinned by tests/test_restart_recovery.py and the crash matrix.
        tail = self.state.load_in_flight_view_if_applicable()
        if tail is not None and tail[0] > new_view:
            logger.info("restoring view %d from the in-flight WAL tail", tail[0])
            new_view = tail[0]
            new_dec = tail[1]
        return new_view, new_seq, new_dec

    def _create_components(self) -> None:
        """Parity: reference consensus.go:386-462."""
        cfg = self.config
        self.collector = StateCollector(
            self.scheduler, n=len(self.nodes), collect_timeout=cfg.collect_timeout
        )
        controller = Controller(
            scheduler=self.scheduler,
            config=cfg,
            nodes=self.nodes,
            comm=self.comm,
            application=self.application,
            assembler=self.assembler,
            verifier=self.verifier,
            signer=self.signer,
            synchronizer=self.synchronizer,
            pool=None,  # plugged below (pool needs the controller as handler)
            batcher=None,
            leader_monitor=None,
            collector=self.collector,
            state=self.state,
            in_flight=self.in_flight,
            checkpoint=self.checkpoint,
            proposer_builder=None,
            view_changer=None,
            on_reconfig=self._on_reconfig,
            metrics=self.metrics,
            tracer=self.tracer,
        )
        self.controller = controller
        controller.membership_epoch = self.membership_epoch

        pool_options = PoolOptions(
            pool_size=cfg.request_pool_size,
            request_max_bytes=cfg.request_max_bytes,
            submit_timeout=cfg.submit_timeout,
            forward_timeout=cfg.request_forward_timeout,
            complain_timeout=cfg.request_complain_timeout,
            auto_remove_timeout=cfg.request_auto_remove_timeout,
        )
        if getattr(self, "pool", None) is not None:
            # Reconfiguration keeps the pool (and its queued requests),
            # re-pointed at the new controller.  Parity: reference
            # pkg/consensus/consensus.go:231 (Pool.ChangeOptions).
            pool = self.pool
            pool.change_options(timeout_handler=controller, options=pool_options)
        else:
            pool = RequestPool(
                self.scheduler,
                self.request_inspector,
                pool_options,
                timeout_handler=controller,
                on_submitted=self._on_pool_submitted,
                metrics=self.metrics.request_pool,
                tracer=self.tracer,
            )
        self.pool = pool
        batcher = Batcher(
            self.scheduler,
            pool,
            batch_max_count=cfg.request_batch_max_count,
            batch_max_bytes=cfg.request_batch_max_bytes,
            batch_max_interval=cfg.request_batch_max_interval,
            tracer=self.tracer,
        )
        self.batcher = batcher
        leader_monitor = HeartbeatMonitor(
            self.scheduler,
            comm=_CommAdapter(controller),
            handler=controller,
            n=len(self.nodes),
            heartbeat_timeout=cfg.leader_heartbeat_timeout,
            heartbeat_count=cfg.leader_heartbeat_count,
            num_of_ticks_behind_before_syncing=cfg.num_of_ticks_behind_before_syncing,
            view_sequence=controller.view_sequence,
        )
        controller.pool = pool
        controller.batcher = batcher
        controller.leader_monitor = leader_monitor

        proposer_builder = ProposalMaker(
            state=self.state, view_factory=self._make_view
        )
        controller._proposer_builder = proposer_builder

        self._create_view_changer()
        self._wire_storage_guard()

    def _wire_storage_guard(self) -> None:
        """Couple the durable-storage self-healing layer (wal/log.py) to the
        controller: while the WAL refuses appends (ENOSPC, fsync retry cap)
        the replica must not propose or vote — persist-before-send has
        nothing durable to stand on — and it auto-resumes when the log
        heals.  Only file-backed WALs carry ``degrade_hooks``; in-memory
        test WALs skip the wiring entirely."""
        hooks = getattr(self.wal, "degrade_hooks", None)
        if hooks is None:
            return
        # A reconfiguration rebuilds the controller: drop the hook pointed
        # at the retired instance before installing the new one.
        prev = getattr(self, "_wal_degrade_hook", None)
        if prev is not None and prev in hooks:
            hooks.remove(prev)
        hook = self.controller.set_wal_degraded
        hooks.append(hook)
        self._wal_degrade_hook = hook
        if getattr(self.wal, "degraded", False):
            self.controller.set_wal_degraded(True)

    def _create_view_changer(self) -> None:
        """Plug in the view changer (split out so the happy-path slice works
        before the failure path exists)."""
        try:
            from consensus_tpu.core.viewchanger import ViewChanger
        except ImportError:
            self.view_changer = None
            return
        cfg = self.config
        self.view_changer = ViewChanger(
            scheduler=self.scheduler,
            self_id=cfg.self_id,
            n=len(self.nodes),
            nodes=self.nodes,
            comm=_CommAdapter(self.controller),
            signer=self.signer,
            verifier=self.verifier,
            checkpoint=self.checkpoint,
            in_flight=self.in_flight,
            state=self.state,
            controller=self.controller,
            requests_timer=self.pool,
            synchronizer=self.controller,
            application=self.controller,
            speed_up_view_change=cfg.speed_up_view_change,
            resend_timeout=cfg.view_change_resend_interval,
            view_change_timeout=cfg.view_change_timeout,
            leader_rotation=cfg.leader_rotation,
            decisions_per_leader=cfg.decisions_per_leader,
            on_reconfig=self._on_reconfig,
            metrics=self.metrics.view_change,
            cert_mode=cfg.cert_mode,
        )
        self.controller.view_changer = self.view_changer

    def _make_view(
        self, *, leader_id: int, proposal_sequence: int, number: int, decisions_in_view: int
    ) -> View:
        """View factory handed to the ProposalMaker.

        Parity: reference consensus.go:318-339 (proposalMaker)."""
        controller = self.controller
        return View(
            scheduler=self.scheduler,
            self_id=self.config.self_id,
            number=number,
            leader_id=leader_id,
            proposal_sequence=proposal_sequence,
            decisions_in_view=decisions_in_view,
            n=len(self.nodes),
            nodes=self.nodes,
            comm=_CommAdapter(controller),
            verifier=self.verifier,
            signer=self.signer,
            state=self.state,
            decider=controller,
            failure_detector=_FailureDetectorAdapter(controller),
            sync_requester=controller,
            checkpoint=self.checkpoint,
            decisions_per_leader=(
                self.config.decisions_per_leader if self.config.leader_rotation else 0
            ),
            membership_notifier=self.membership_notifier,
            metrics=self.metrics.view,
            pipeline_depth=self.config.pipeline_depth,
            consensus_metrics=self.metrics.consensus,
            tracer=self.tracer,
            cert_mode=self.config.cert_mode,
        )

    def _start_components(self, view: int, seq: int, dec: int) -> None:
        """Parity: reference consensus.go:512-522."""
        if self.view_changer is not None:
            self.view_changer.start(
                view, restore_view_change=self._restore_view_change
            )
        self.controller.start(view, seq, dec, sync_on_start=self.config.sync_on_start)

    def stop(self) -> None:
        self._running = False
        if self.view_changer is not None:
            self.view_changer.stop()
        if self.controller is not None:
            self.controller.stop()

    # ------------------------------------------------------- reconfiguration

    def _on_reconfig(self, reconfig: Reconfig) -> None:
        """A delivered decision changed membership/config: rebuild.

        Parity: reference consensus.go:166-252 (run + reconfig)."""
        self.scheduler.post(lambda: self._reconfig(reconfig), name="reconfig")

    def _reconfig(self, reconfig: Reconfig) -> None:
        logger.info("%d: reconfiguring", self.config.self_id)
        new_nodes = tuple(sorted(reconfig.current_nodes or self.comm.nodes()))
        if self.config.self_id not in new_nodes:
            logger.info("%d: evicted by reconfiguration; shutting down", self.config.self_id)
            self.stop()
            return
        if reconfig.current_config is not None:
            self.config = reconfig.current_config
        membership = getattr(reconfig, "membership", None)
        if membership is not None:
            self.membership_epoch = membership.epoch
            self.metrics.membership.epoch.set(membership.epoch)
            logger.info(
                "%d: entering membership epoch %d (nodes %s)",
                self.config.self_id, membership.epoch, list(new_nodes),
            )

        # Stop the old machinery, but only pause pool timers (requests
        # survive reconfiguration).
        if self.view_changer is not None:
            self.view_changer.stop()
        self.controller.stop(pool_pause_only=True)
        # Pipelined slots above the reconfig decision are abandoned (the new
        # epoch's leader re-proposes their batches); hand their pool
        # reservations back or those requests are stuck until auto-remove.
        self.pool.release_reservations()
        self.collector.close()

        self.nodes = new_nodes
        proposal, signatures = self.checkpoint.get()
        self.last_proposal, self.last_signatures = proposal, tuple(signatures)
        md = (
            decode_view_metadata(proposal.metadata)
            if proposal.metadata
            else ViewMetadata()
        )
        self.wal_initial_content = []  # records predate the new epoch
        self._restore_view_change = None
        self.in_flight = InFlightData()
        self.state = PersistedState(self.wal, self.in_flight, entries=[])
        new_dec = md.decisions_in_view + 1 if md.latest_sequence != 0 else 0
        self._create_components()
        self.pool.restart_timers()
        self._start_components(md.view_id, md.latest_sequence + 1, new_dec)

    # --------------------------------------------------------------- ingress

    def submit_request(self, raw: bytes, on_done: Optional[Callable[[Optional[str]], None]] = None) -> None:
        """Parity: reference consensus.go:302-316."""
        if not self._running:
            if on_done:
                on_done("not running")
            return
        self.scheduler.post(
            lambda: self.controller.submit_request(raw, on_done), name="submit"
        )

    def handle_message(self, sender: int, msg: ConsensusMessage) -> None:
        """Consensus traffic ingress (quorum-membership + epoch guarded).

        Parity: reference consensus.go:282-300 (the epoch gate is ours)."""
        if isinstance(msg, EpochTagged):
            if self.config.epoch_tagging and msg.epoch != self.membership_epoch:
                # Traffic from another epoch — a removed node that has not
                # yet learned of its eviction, or a lagging replica.  Drop
                # it HERE, counted and traced, so it can never corrupt the
                # collectors or provoke a spurious view change.
                self._drop_stale_epoch(sender, msg.epoch)
                return
            msg = msg.msg
        if not self._running:
            return
        if sender not in self.nodes:
            if self.config.epoch_tagging:
                self._drop_stale_epoch(sender, None)
            return
        self.scheduler.post(
            lambda: self.controller.process_message(sender, msg), name="handle-msg"
        )

    def _drop_stale_epoch(self, sender: int, epoch: Optional[int]) -> None:
        self.metrics.membership.count_stale_epoch_dropped.add(1)
        self.tracer.instant(
            "membership", "membership.stale_drop", sender=sender, epoch=epoch
        )
        if (
            epoch is not None
            and epoch > self.membership_epoch
            and self._running
            and self.controller is not None
        ):
            # The SENDER is ahead of us: a membership change we have not
            # delivered yet.  Nudge sync (idempotent) so we catch up instead
            # of silently discarding the future.
            self.scheduler.post(self.controller.sync, name="stale-epoch-sync")

    def handle_request(self, sender: int, raw: bytes) -> None:
        if not self._running or sender not in self.nodes:
            return
        self.scheduler.post(
            lambda: self.controller.handle_request(sender, raw), name="handle-req"
        )

    def get_leader_id(self) -> int:
        if not self._running or self.controller is None:
            return 0
        return self.controller.leader_id()

    def _on_pool_submitted(self) -> None:
        if self.controller is not None and not self.controller.stopped:
            self.batcher.pool_changed()


class _EpochStampingComm:
    """Comm decorator stamping outbound consensus traffic with the owner's
    current membership epoch (``wire.EpochTagged``).

    Reads ``consensus.membership_epoch`` at send time, so the stamp tracks
    reconfigurations without re-wiring; transactions and the node roster
    pass through untouched (request forwarding is epoch-agnostic — a request
    is valid in any epoch that still pools it)."""

    def __init__(self, inner: Comm, consensus: "Consensus") -> None:
        self._inner = inner
        self._consensus = consensus

    def send_consensus(self, target_id: int, message) -> None:
        self._inner.send_consensus(
            target_id,
            EpochTagged(epoch=self._consensus.membership_epoch, msg=message),
        )

    def send_transaction(self, target_id: int, request: bytes) -> None:
        self._inner.send_transaction(target_id, request)

    def nodes(self):
        return self._inner.nodes()


class _CommAdapter:
    """View/heartbeat-facing broadcast/send backed by the controller."""

    def __init__(self, controller: Controller) -> None:
        self._controller = controller

    def broadcast(self, msg: ConsensusMessage) -> None:
        self._controller.broadcast(msg)

    def send(self, target_id: int, msg: ConsensusMessage) -> None:
        self._controller.send(target_id, msg)


class _FailureDetectorAdapter:
    def __init__(self, controller: Controller) -> None:
        self._controller = controller

    def complain(self, view: int, stop_view: bool) -> None:
        self._controller.complain(view, stop_view)


__all__ = ["Consensus"]
