"""Real-cluster tests for the process-per-replica deployment rig.

Every test here boots actual OS processes (``python -m
consensus_tpu.deploy.replica_main`` et al.) over real TCP sockets and
file-backed WALs.  The file sorts alphabetically LAST on purpose: the
tier-1 suite is time-budget-bound, and these subprocess tests must not
displace the faster suite's coverage inside that budget.

* ``test_cluster_smoke_orders_decisions`` — tier-1: 3 replicas + 1
  sidecar as subprocesses, ~20 decisions through real sockets, clean
  shutdown with zero orphaned processes.
* ``test_acceptance_kill9_leader_sidecar_and_rejoin`` (@slow) — the
  5-replica (f=1) acceptance run: kill -9 the leader (view change
  completes, ordering resumes), kill -9 a sidecar (verification reroutes
  through the fleet), supervisor restart of the killed replica (rejoins
  via verified sync off its intact WAL) — invariant monitor clean, no
  orphans or leaked ports at teardown.
* ``test_soak_ci_scale`` (@slow) — ``scripts/soak.py --minutes 2``:
  trace-driven load + the seeded process-chaos loop end to end, rc 0
  with a JSON summary line.  The multi-hour soak is the same entry point
  run manually (README's deployment runbook).
"""

import json
import os
import subprocess
import sys
import time

import pytest

from consensus_tpu.deploy import ClusterLauncher, ClusterSpec
from consensus_tpu.deploy.identity import make_client_keyring
from consensus_tpu.deploy.spec import free_ports
from consensus_tpu.net import TcpComm

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: The ingress driver's transport id (outside the replica id range).
_CLIENT_ID = 900


class _Injector:
    """Driver-side request source: signs with the cluster's derived client
    keys and broadcasts over an authenticated TcpComm, like driver_main."""

    def __init__(self, spec):
        self.spec = spec
        self.keyring = make_client_keyring(spec.key_namespace, spec.clients)
        addresses = dict(spec.comm_addresses())
        addresses[_CLIENT_ID] = ("127.0.0.1", free_ports(1)[0])
        self.comm = TcpComm(
            _CLIENT_ID, addresses, lambda *a: None,
            reconnect_backoff=0.05, auth_secret=spec.auth_secret,
        )
        self.comm.start()
        self._seq = 0

    def submit(self, n, pace=0.02):
        for _ in range(n):
            s = self._seq
            self._seq += 1
            client = s % self.spec.clients
            raw = self.keyring.make_request(client, (client << 32) | s)
            for node_id in self.spec.node_ids():
                self.comm.send_transaction(node_id, raw)
            time.sleep(pace)

    def stop(self):
        self.comm.stop()


def test_cluster_smoke_orders_decisions(tmp_path):
    """3 replicas + 1 sidecar as real subprocesses order ~20 decisions
    through real sockets; teardown leaves zero orphans / leaked ports."""
    spec = ClusterSpec.generate(
        3, 1, str(tmp_path),
        config_overrides={"request_batch_max_count": 1},  # 1 request = 1 decision
    )
    launcher = ClusterLauncher(spec)
    injector = None
    try:
        launcher.start(timeout=120)
        health = launcher.health()
        assert health["sc-0"]["role"] == "sidecar"
        assert all(
            health[f"replica-{i}"]["ok"] for i in spec.node_ids()
        )
        injector = _Injector(spec)
        injector.submit(20)
        assert launcher.wait_height(20, timeout=60), (
            f"cluster never reached height 20: {launcher.heights()}"
        )
        # Prefix agreement across every process's reported ledger.
        launcher.observe_invariants()
        launcher.monitor.assert_clean()
        assert len(launcher.monitor.agreed) >= 20
        # The obs plane scrapes every replica over its control socket.
        bodies = launcher.scrape()
        assert set(bodies) == {f"replica-{i}" for i in spec.node_ids()}
        assert all("obs_sample_time" in b for b in bodies.values())
    finally:
        if injector is not None:
            injector.stop()
        summary = launcher.stop()  # raises on orphans / leaked ports
    assert summary["orphans"] == [] and summary["leaked_ports"] == []


@pytest.mark.slow
def test_acceptance_kill9_leader_sidecar_and_rejoin(tmp_path):
    """The ISSUE-16 acceptance run on a 5-replica (f=1) cluster."""
    spec = ClusterSpec.generate(
        5, 2, str(tmp_path),
        config_overrides={
            "view_change_timeout": 3.0,
            "view_change_resend_interval": 1.0,
            "leader_heartbeat_timeout": 2.0,
            "leader_heartbeat_count": 8,
        },
    )
    # Supervisor backoff well past the view-change window: the killed
    # leader must come back AFTER the survivors elected a successor, so
    # the run proves the view change rather than a fast restart.
    launcher = ClusterLauncher(spec, backoff_initial=8.0)
    injector = None
    try:
        launcher.start(timeout=180)
        injector = _Injector(spec)
        injector.submit(5)
        assert launcher.wait_height(1, timeout=30)
        old_leader = launcher.leader_id()
        assert old_leader is not None

        # --- leg 1: kill -9 the current leader -> view change completes,
        # ordering resumes among the surviving 4 (quorum with f=1).
        launcher.kill_replica(old_leader)
        view_advanced = False
        deadline = time.monotonic() + 40.0
        while time.monotonic() < deadline:
            views = [
                h["view"]
                for i, sup in launcher.replicas.items()
                if i != old_leader and (h := sup.probe()) is not None
            ]
            if views and max(views) >= 1:
                view_advanced = True
                break
            time.sleep(0.2)
        assert view_advanced, "view change never completed after leader kill"
        h0 = max(launcher.heights().values())
        resumed = False
        deadline = time.monotonic() + 40.0
        while time.monotonic() < deadline:
            injector.submit(2)
            reached = sum(
                1 for v in launcher.heights().values() if v >= h0 + 1
            )
            if reached >= 4:
                resumed = True
                break
            time.sleep(0.5)
        assert resumed, f"ordering did not resume: {launcher.heights()}"
        new_leader = launcher.leader_id()
        assert new_leader != old_leader

        # --- leg 2: kill -9 one sidecar -> replicas reroute verification
        # through the surviving fleet member; ordering continues.
        launcher.kill_sidecar("sc-0")
        h1 = max(launcher.heights().values())
        ok = False
        deadline = time.monotonic() + 40.0
        while time.monotonic() < deadline:
            injector.submit(2)
            if sum(1 for v in launcher.heights().values() if v >= h1 + 1) >= 4:
                ok = True
                break
            time.sleep(0.5)
        assert ok, f"ordering stalled after sidecar kill: {launcher.heights()}"

        # --- leg 3: the supervisor restarts the killed replica; it rejoins
        # through verified sync off its intact WAL and catches up.
        target = max(launcher.heights().values())
        rejoined = False
        deadline = time.monotonic() + 90.0
        while time.monotonic() < deadline:
            h = launcher.replicas[old_leader].probe()
            if (h is not None and h.get("restarted")
                    and h.get("ledger", 0) >= target):
                rejoined = True
                break
            time.sleep(0.5)
        assert rejoined, (
            f"killed replica never rejoined: "
            f"{launcher.replicas[old_leader].probe()}"
        )
        assert launcher.replicas[old_leader].restarts >= 1

        launcher.observe_invariants()
        launcher.monitor.assert_clean()
    finally:
        if injector is not None:
            injector.stop()
        summary = launcher.stop()  # raises on orphans / leaked ports
    assert summary["orphans"] == [] and summary["leaked_ports"] == []


@pytest.mark.slow
def test_soak_ci_scale(tmp_path):
    """scripts/soak.py --minutes 2: trace-driven load + process chaos,
    obs scraping, invariant gating — rc 0 and a JSON summary line."""
    env = os.environ.copy()
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [
            sys.executable, os.path.join(_REPO, "scripts", "soak.py"),
            "--minutes", "2", "--replicas", "3", "--sidecars", "1",
            "--period", "8", "--seed", "7",
            "--base-dir", str(tmp_path / "soak"),
        ],
        capture_output=True,
        text=True,
        env=env,
        timeout=600,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    summary = json.loads(proc.stdout.strip().splitlines()[-1])
    assert summary["ok"] is True
    assert summary["invariants"]["violations"] == []
    assert summary["end_height"] > summary["start_height"]
    assert summary["chaos"], "chaos loop never fired"
    assert summary["scrapes"] > 0
    assert summary["teardown"]["orphans"] == []
    assert summary["teardown"]["leaked_ports"] == []
