"""Small-n byzantine-corruption chaos against REAL host Ed25519.

Satellite of the crash-matrix PR.  test_soak's byzantine family runs toy
crypto (ByteInspector); here the same corruption shapes must be shed by
actual Ed25519 verification on the engine's host path (ref_sign/ref_verify
pure-Python fallback when ``cryptography`` is absent), proving the
protocol's rejection of forgeries doesn't depend on the toy verifier's
shortcuts.

Each case is pinned: re-run a failure with
``pytest tests/test_crypto_chaos.py -k <mode>`` — the corruption stream is
derived from ``random.Random(SEED + hash-of-mode)`` and the scheduler from
``Cluster(seed=...)``, so replays are exact.
"""

import dataclasses
import random
import zlib

import pytest

from consensus_tpu.models import (
    Ed25519BatchVerifier,
    Ed25519Signer,
    Ed25519VerifierMixin,
)
from consensus_tpu.models.verifier import commit_message
from consensus_tpu.testing import Cluster, make_request
from consensus_tpu.testing.crypto_app import CryptoApp
from consensus_tpu.wire import Commit

FAST = {
    "request_forward_timeout": 1.0,
    "request_complain_timeout": 4.0,
    "request_auto_remove_timeout": 120.0,
    "view_change_resend_interval": 2.0,
    "view_change_timeout": 10.0,
    "leader_heartbeat_timeout": 20.0,
}

SEED = 60493
BYZANTINE = 4  # follower in view 0: corruption can't stall the leader
HONEST = (1, 2, 3)
DECISIONS = 3


class _SigVerifier(Ed25519VerifierMixin):
    def verify_proposal(self, proposal):
        raise NotImplementedError  # app half lives in CryptoApp

    def verify_request(self, raw):
        raise NotImplementedError

    def verification_sequence(self):
        return 0

    def requests_from_proposal(self, proposal):
        return []


def _flip_signature(rng, msg):
    value = bytearray(msg.signature.value)
    i = rng.randrange(len(value))
    value[i] ^= 0xFF
    return dataclasses.replace(
        msg, signature=dataclasses.replace(msg.signature, value=bytes(value))
    )


def _claim_other_signer(rng, msg):
    # Keeps the byzantine node's REAL signature bytes but claims an honest
    # id: verification against the claimed id's registered key must fail.
    other = rng.choice(HONEST)
    return dataclasses.replace(
        msg, signature=dataclasses.replace(msg.signature, id=other)
    )


def _zero_signature(rng, msg):
    return dataclasses.replace(
        msg,
        signature=dataclasses.replace(
            msg.signature, value=bytes(len(msg.signature.value))
        ),
    )


MODES = {
    "flip_byte": _flip_signature,
    "claim_other_signer": _claim_other_signer,
    "zero_signature": _zero_signature,
}


@pytest.mark.parametrize("mode", sorted(MODES))
def test_byzantine_commit_corruption_shed_by_real_ed25519(mode):
    seed = SEED + zlib.crc32(mode.encode()) % 1000
    rng = random.Random(seed)
    cluster = Cluster(4, seed=seed, config_tweaks=dict(FAST))
    engine = Ed25519BatchVerifier(min_device_batch=10**9)  # host path: exact
    signers = {i: Ed25519Signer(i) for i in cluster.nodes}
    keys = {i: s.public_bytes for i, s in signers.items()}
    for node_id, node in cluster.nodes.items():
        node.app = CryptoApp(
            node_id, cluster, signers[node_id], _SigVerifier(keys, engine=engine)
        )

    corrupt = MODES[mode]
    corrupted = [0]

    def mutate(sender, target, msg):
        if sender == BYZANTINE and isinstance(msg, Commit):
            corrupted[0] += 1
            return corrupt(rng, msg)
        return msg

    cluster.network.mutate_send = mutate
    cluster.start()

    for i in range(DECISIONS):
        cluster.submit_to_all(make_request("chaos", i))
        assert cluster.run_until_ledger(
            i + 1, node_ids=list(HONEST), max_time=600.0
        ), f"[{mode} seed={seed}] block {i} stalled behind corrupted commits"
    assert corrupted[0] > 0, "byzantine node never sent a commit to corrupt"
    cluster.assert_ledgers_consistent()

    # Decision quorums on honest replicas must exclude the corrupted
    # signatures entirely (claim_other_signer forgeries land under an
    # honest id but invalid bytes — so re-verify EVERY quorum signature
    # against the registered keys, not just the claimed ids).
    checker = Ed25519BatchVerifier(min_device_batch=10**9)
    for node_id in HONEST:
        for decision in cluster.nodes[node_id].app.ledger:
            assert len(decision.signatures) >= 3
            assert BYZANTINE not in {s.id for s in decision.signatures}, (
                f"[{mode} seed={seed}] corrupted signature entered a quorum"
            )
            msgs = [
                commit_message(decision.proposal, s.msg)
                for s in decision.signatures
            ]
            ok = checker.verify_batch(
                msgs,
                [s.value for s in decision.signatures],
                [keys[s.id] for s in decision.signatures],
            )
            assert ok.all(), (
                f"[{mode} seed={seed}] ledger carries an invalid signature"
            )
