"""Byzantine-fault and dynamic-reconfiguration scenarios.

Parity model: reference test/basic_test.go (TestLeaderModifiesPreprepare:1134
and partition scenarios) and test/reconfig_test.go (TestAddRemoveAddNodes:231).
"""

from consensus_tpu.testing import (
    Cluster,
    boot_node,
    install_reconfig_hook,
    make_request,
    reconfig_request,
)
from consensus_tpu.wire import Commit, PrePrepare, Prepare

FAST = {
    "request_forward_timeout": 1.0,
    "request_complain_timeout": 4.0,
    "request_auto_remove_timeout": 60.0,
    "view_change_resend_interval": 2.0,
    "view_change_timeout": 10.0,
}


def test_byzantine_leader_mutates_pre_prepare_gets_deposed():
    # The leader sends a different proposal to each follower: digests can
    # never match across prepares, no quorum forms, the complaint cascade
    # deposes the leader, and the honest new leader orders the request.
    cluster = Cluster(4, config_tweaks=FAST)
    cluster.start()

    def mutate(sender, target, msg):
        if sender == 1 and isinstance(msg, PrePrepare):
            tampered = msg.proposal.__class__(
                payload=msg.proposal.payload + b"|evil-for-%d" % target,
                header=msg.proposal.header,
                metadata=msg.proposal.metadata,
                verification_sequence=msg.proposal.verification_sequence,
            )
            return PrePrepare(
                view=msg.view, seq=msg.seq, proposal=tampered,
                prev_commit_signatures=msg.prev_commit_signatures,
            )
        return msg

    cluster.network.mutate_send = mutate
    cluster.submit_to_all(make_request("c", 0))
    # Nothing commits while the byzantine mutation is active (the followers
    # prepare different digests).
    cluster.scheduler.advance(3.0)
    assert all(len(n.app.ledger) == 0 for n in cluster.nodes.values())

    # The view change deposes node 1; the new leader is honest.
    cluster.network.mutate_send = None
    assert cluster.run_until_ledger(1, node_ids=[2, 3, 4], max_time=600.0)
    cluster.assert_ledgers_consistent()
    assert all(
        cluster.nodes[i].consensus.controller.curr_view_number >= 1
        for i in (2, 3, 4)
    )


def test_partitioned_minority_catches_up_after_heal():
    cluster = Cluster(4, config_tweaks=FAST)
    cluster.start()
    cluster.submit_to_all(make_request("c", 0))
    assert cluster.run_until_ledger(1)

    # Partition node 4 away; the majority keeps ordering.
    cluster.network.partition([4])
    for i in range(1, 4):
        cluster.submit_to_all(make_request("c", i))
        assert cluster.run_until_ledger(i + 1, node_ids=[1, 2, 3], max_time=300.0)
    assert len(cluster.nodes[4].app.ledger) == 1

    # Heal: the straggler must catch up (censorship detection / heartbeat gap).
    cluster.network.heal()
    cluster.submit_to_all(make_request("c", 9))
    assert cluster.run_until_ledger(5, node_ids=[1, 2, 3], max_time=300.0)
    cluster.scheduler.advance(120.0)
    assert len(cluster.nodes[4].app.ledger) >= 4
    cluster.assert_ledgers_consistent()


def test_lossy_network_still_orders():
    # 20% loss on every link: retransmission help + timeouts must still
    # drive the cluster to order (the protocol tolerates loss by contract).
    cluster = Cluster(4, seed=3, config_tweaks=FAST)
    cluster.start()
    for a in range(1, 5):
        for b in range(1, 5):
            if a != b:
                cluster.network.set_loss(a, b, 0.2)
    for i in range(3):
        cluster.submit_to_all(make_request("c", i))
        assert cluster.run_until_ledger(i + 1, max_time=900.0), f"block {i} stalled"
    cluster.assert_ledgers_consistent()


# --- dynamic reconfiguration ------------------------------------------------
# reconfig_request / install_reconfig_hook / boot_node are the shared
# harness (consensus_tpu/testing/membership.py), lifted from this file.


def test_reconfig_removes_node_and_cluster_continues():
    cluster = Cluster(5, config_tweaks=FAST)
    install_reconfig_hook(cluster)
    cluster.start()
    cluster.submit_to_all(make_request("c", 0))
    assert cluster.run_until_ledger(1)

    # Commit a reconfiguration that evicts node 5.
    cluster.submit_to_all(reconfig_request("rm5", [1, 2, 3, 4]))
    assert cluster.run_until_ledger(2, node_ids=[1, 2, 3, 4], max_time=300.0)
    cluster.scheduler.advance(30.0)

    # The evicted node shut itself down.
    assert cluster.nodes[5].consensus is None or not cluster.nodes[5].consensus._running

    # The remaining 4 (quorum 3) keep ordering.
    cluster.nodes[5].running = False  # exclude from ledger checks
    cluster.submit_to_all(make_request("c", 1))
    assert cluster.run_until_ledger(3, node_ids=[1, 2, 3, 4], max_time=300.0)
    cluster.assert_ledgers_consistent()


def test_reconfig_adds_node_which_catches_up():
    cluster = Cluster(4, config_tweaks=FAST)
    install_reconfig_hook(cluster)
    cluster.start()
    for i in range(2):
        cluster.submit_to_all(make_request("c", i))
        assert cluster.run_until_ledger(i + 1)

    # Commit the add-node-5 reconfiguration.
    cluster.submit_to_all(reconfig_request("add5", [1, 2, 3, 4, 5]))
    assert cluster.run_until_ledger(3, node_ids=[1, 2, 3, 4], max_time=300.0)
    cluster.scheduler.advance(5.0)

    # Boot the new node; it must sync the existing ledger and participate.
    node5 = boot_node(cluster, 5)
    cluster.scheduler.advance(120.0)  # heartbeat gap detection + sync

    cluster.submit_to_all(make_request("c", 9))
    assert cluster.run_until_ledger(4, node_ids=[1, 2, 3, 4], max_time=600.0)
    cluster.scheduler.advance(120.0)
    assert len(node5.app.ledger) >= 3, f"new node at {len(node5.app.ledger)}"
    cluster.assert_ledgers_consistent()


def test_reconfig_evicts_current_leader():
    """A committed reconfiguration whose new membership excludes the
    CURRENT LEADER: the evicted leader shuts itself down after delivering
    its own eviction, and the survivors resume under a leader recomputed
    over the new node set without needing a view change.  Models the
    leader-unavailable-after-reconfig situation of reference
    test/reconfig_test.go:483 (TestViewChangeAfterReconfig)."""
    cluster = Cluster(5, config_tweaks=FAST)
    install_reconfig_hook(cluster)
    cluster.start()
    cluster.submit_to_all(make_request("c", 0))
    assert cluster.run_until_ledger(1)

    cluster.submit_to_all(reconfig_request("rm-leader", [2, 3, 4, 5]))
    assert cluster.run_until_ledger(2, node_ids=[2, 3, 4, 5], max_time=300.0)
    cluster.scheduler.advance(30.0)

    assert cluster.nodes[1].consensus is None or not cluster.nodes[1].consensus._running, (
        "evicted ex-leader did not shut down"
    )
    cluster.nodes[1].running = False  # exclude from ledger checks
    cluster.submit_to_all(make_request("c", 1))
    assert cluster.run_until_ledger(3, node_ids=[2, 3, 4, 5], max_time=600.0), (
        "survivors did not resume ordering under the recomputed leader"
    )
    cluster.assert_ledgers_consistent()
    # Leadership was recomputed over the new node set — no view change ran.
    assert all(
        cluster.nodes[i].consensus.controller.curr_view_number == 0
        for i in (2, 3, 4, 5)
    )


def test_view_change_right_after_reconfig():
    """The leader dies immediately after a reconfiguration commits: the
    ensuing view change must run under the NEW membership and quorum
    (n=4 after removing a follower from 5), not the old one.  Parity
    family: reference test/reconfig_test.go:483."""
    cluster = Cluster(5, config_tweaks=FAST)
    install_reconfig_hook(cluster)
    cluster.start()
    cluster.submit_to_all(make_request("c", 0))
    assert cluster.run_until_ledger(1)

    # Shrink membership to {1,2,3,4} (drops follower 5)...
    cluster.submit_to_all(reconfig_request("rm5", [1, 2, 3, 4]))
    assert cluster.run_until_ledger(2, node_ids=[1, 2, 3, 4], max_time=300.0)
    cluster.scheduler.advance(10.0)
    # The eviction must actually have taken node 5 down — otherwise the
    # ensuing view change could reach the OLD n=5 quorum through it and
    # this test would prove nothing about the new membership.
    n5 = cluster.nodes[5].consensus
    assert n5 is None or not n5._running, "evicted node 5 did not shut down"
    cluster.nodes[5].running = False

    # ...then kill the leader at once.  The view change needs quorum 3 of
    # the new n=4 — exactly the three survivors.
    cluster.nodes[1].crash()
    cluster.submit_to_all(make_request("c", 1))
    assert cluster.run_until_ledger(3, node_ids=[2, 3, 4], max_time=900.0), (
        "view change under the post-reconfig membership stalled"
    )
    cluster.assert_ledgers_consistent()
    assert all(
        cluster.nodes[i].consensus.controller.curr_view_number >= 1
        for i in (2, 3, 4)
    )
