"""ECDSA-P256 model family: field/point correctness (covered in ops tests
below), batch verification against OpenSSL, the consensus port adapters,
and a live cluster ordering blocks under real P-256 signatures.
"""

import random

import numpy as np
import pytest

import jax.numpy as jnp

from consensus_tpu.models import EcdsaP256BatchVerifier, EcdsaP256Signer, EcdsaP256VerifierMixin
from consensus_tpu.models.ecdsa_p256 import N, raw_signature_from_der
from consensus_tpu.ops import field_p256 as fp
from consensus_tpu.ops import p256
from consensus_tpu.testing import Cluster, make_request
from consensus_tpu.types import Proposal, Signature


def limbs_of(values):
    return jnp.asarray(np.stack([fp.int_to_limbs(v) for v in values], axis=1))


def ints_of(arr):
    frozen = np.asarray(fp.freeze(arr))
    return [fp.limbs_to_int(frozen[:, i]) for i in range(frozen.shape[1])]


class TestFieldP256:
    def test_ops_match_bigint(self):
        rng = random.Random(7)
        a_vals = [rng.randrange(fp.P) for _ in range(8)] + [0, 1, fp.P - 1]
        b_vals = [rng.randrange(fp.P) for _ in range(8)] + [fp.P - 1, 2, fp.P - 1]
        a, b = limbs_of(a_vals), limbs_of(b_vals)
        assert ints_of(fp.mul(a, b)) == [(x * y) % fp.P for x, y in zip(a_vals, b_vals)]
        assert ints_of(fp.add(a, b)) == [(x + y) % fp.P for x, y in zip(a_vals, b_vals)]
        assert ints_of(fp.sub(a, b)) == [(x - y) % fp.P for x, y in zip(a_vals, b_vals)]
        assert ints_of(fp.square(a)) == [x * x % fp.P for x in a_vals]

    def test_deep_chain(self):
        rng = random.Random(9)
        vals = [rng.randrange(fp.P) for _ in range(4)]
        other = [rng.randrange(fp.P) for _ in range(4)]
        x, y = limbs_of(vals), limbs_of(other)
        w = list(vals)
        for i in range(45):
            if i % 3 == 0:
                x = fp.mul(x, y); w = [(u * v) % fp.P for u, v in zip(w, other)]
            elif i % 3 == 1:
                x = fp.sub(x, y); w = [(u - v) % fp.P for u, v in zip(w, other)]
            else:
                x = fp.square(x); w = [u * u % fp.P for u in w]
        assert ints_of(x) == w


class TestPointsP256:
    def _affine(self, pt, idx=0):
        X = ints_of(pt.x)[idx]
        Y = ints_of(pt.y)[idx]
        Z = ints_of(pt.z)[idx]
        if Z == 0:
            return None
        zi = pow(Z, fp.P - 2, fp.P)
        return (X * zi) % fp.P, (Y * zi) % fp.P

    def test_double_add_identity_inverse(self):
        ref = jnp.zeros((32, 1), dtype=jnp.float32)
        g = p256.base_point_like(ref)
        # Integer multiples of G via the host-side table helper.
        table = [None, (p256.GX, p256.GY)]
        for _ in range(3):
            table.append(p256._add_int(table[-1], (p256.GX, p256.GY)))
        table = [(0, 0) if e is None else e for e in table]
        assert self._affine(p256.double(g)) == table[2]
        assert self._affine(p256.add(g, g)) == table[2]
        assert self._affine(p256.add(p256.double(g), g)) == table[3]
        ident = p256.identity_like(ref)
        assert self._affine(p256.add(g, ident)) == table[1]
        neg = p256.Point(x=g.x, y=fp.sub(g.y * 0, g.y), z=g.z)
        assert self._affine(p256.add(g, neg)) is None

    def test_on_curve(self):
        ref = jnp.zeros((32, 1), dtype=jnp.float32)
        g = p256.base_point_like(ref)
        assert bool(p256.on_curve(g.x, g.y)[0])
        assert not bool(p256.on_curve(fp.constant_like(5, ref), g.y)[0])


def make_sigs(n):
    pytest.importorskip("cryptography", reason="reference signer unavailable")
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import ec

    msgs, sigs, keys = [], [], []
    for i in range(n):
        sk = ec.generate_private_key(ec.SECP256R1())
        pk = sk.public_key().public_bytes(
            serialization.Encoding.X962, serialization.PublicFormat.UncompressedPoint
        )
        m = b"p256-%d" % i
        msgs.append(m)
        sigs.append(raw_signature_from_der(sk.sign(m, ec.ECDSA(hashes.SHA256()))))
        keys.append(pk)
    return msgs, sigs, keys


class TestBatchVerifier:
    def test_valid_and_corruption_modes(self):
        msgs, sigs, keys = make_sigs(8)
        v = EcdsaP256BatchVerifier()
        assert v.verify_batch(msgs, sigs, keys).all()

        bad = list(sigs)
        bad[0] = bytes([sigs[0][0] ^ 1]) + sigs[0][1:]       # flipped r
        bad[1] = sigs[1][:32] + bytes(32)                    # s = 0
        bad[2] = sigs[2][:32] + N.to_bytes(32, "big")        # s = n
        bad[3] = b"short"
        ok = v.verify_batch(msgs, bad, keys)
        assert not ok[:4].any() and ok[4:].all()

        wrong_msg = [b"x" + m for m in msgs]
        assert not v.verify_batch(wrong_msg, sigs, keys).any()
        swapped = keys[1:] + keys[:1]
        assert not v.verify_batch(msgs, sigs, swapped).any()

    def test_bad_key_encodings_rejected(self):
        msgs, sigs, keys = make_sigs(2)
        bad_keys = list(keys)
        bad_keys[0] = b"\x02" + keys[0][1:33]            # compressed form
        bad_keys[1] = b"\x04" + bytes(64)                # not on curve
        ok = EcdsaP256BatchVerifier().verify_batch(msgs, sigs, bad_keys)
        assert not ok.any()

    def test_device_matches_host_fallback(self):
        msgs, sigs, keys = make_sigs(4)
        bad = list(sigs)
        bad[2] = bytes(64)
        device = EcdsaP256BatchVerifier(min_device_batch=1).verify_batch(msgs, bad, keys)
        host = EcdsaP256BatchVerifier(min_device_batch=10**9).verify_batch(msgs, bad, keys)
        assert (device == host).all()


class _SigOnly(EcdsaP256VerifierMixin):
    def verify_proposal(self, proposal):
        return []

    def verify_request(self, raw):
        raise NotImplementedError

    def verification_sequence(self):
        return 0

    def requests_from_proposal(self, proposal):
        return []


class TestPortAdapters:
    def test_sign_and_batch_verify_quorum(self):
        pytest.importorskip(
            "cryptography", reason="EcdsaP256Signer needs a real signer"
        )
        signers = {i: EcdsaP256Signer(i) for i in (1, 2, 3)}
        verifier = _SigOnly({i: s.public_bytes for i, s in signers.items()})
        proposal = Proposal(payload=b"batch")
        sigs = [signers[i].sign_proposal(proposal, b"aux-%d" % i) for i in (1, 2, 3)]
        assert verifier.verify_consenter_sigs_batch(sigs, proposal) == [
            b"aux-1", b"aux-2", b"aux-3"
        ]
        tampered = Signature(id=1, value=sigs[0].value, msg=b"other-aux")
        assert verifier.verify_consenter_sigs_batch([tampered], proposal) == [None]

    def test_raw_signature_path(self):
        pytest.importorskip(
            "cryptography", reason="EcdsaP256Signer needs a real signer"
        )
        signer = EcdsaP256Signer(5)
        verifier = _SigOnly({5: signer.public_bytes})
        data = b"view-data"
        verifier.verify_signature(Signature(id=5, value=signer.sign(data), msg=data))
        with pytest.raises(ValueError):
            verifier.verify_signature(Signature(id=5, value=bytes(64), msg=data))


def test_cluster_orders_with_real_p256_signatures():
    # The protocol running entirely on ECDSA-P256: decisions carry verifying
    # quorums under the registered keys.
    pytest.importorskip(
        "cryptography", reason="EcdsaP256Signer needs a real signer"
    )
    from consensus_tpu.models.verifier import commit_message
    from consensus_tpu.testing import TestApp

    class CryptoApp(TestApp):
        def __init__(self, node_id, cluster, signer, verifier):
            super().__init__(node_id, cluster)
            self._signer = signer
            self._verifier = verifier

        def sign(self, data):
            return self._signer.sign(data)

        def sign_proposal(self, proposal, aux=b""):
            return self._signer.sign_proposal(proposal, aux)

        def verify_consenter_sig(self, signature, proposal):
            return self._verifier.verify_consenter_sig(signature, proposal)

        def verify_consenter_sigs_batch(self, signatures, proposal):
            return self._verifier.verify_consenter_sigs_batch(signatures, proposal)

        def verify_signature(self, signature):
            return self._verifier.verify_signature(signature)

        def auxiliary_data(self, msg):
            return self._verifier.auxiliary_data(msg)

    cluster = Cluster(4)
    signers = {i: EcdsaP256Signer(i) for i in cluster.nodes}
    keys = {i: s.public_bytes for i, s in signers.items()}
    for node_id, node in cluster.nodes.items():
        node.app = CryptoApp(node_id, cluster, signers[node_id], _SigOnly(keys))
    cluster.start()
    for i in range(2):
        cluster.submit_to_all(make_request("c", i))
        assert cluster.run_until_ledger(i + 1, max_time=300.0), f"block {i} stalled"
    cluster.assert_ledgers_consistent()
    for node in cluster.nodes.values():
        for decision in node.app.ledger:
            assert len(decision.signatures) >= 3
            msgs = [commit_message(decision.proposal, s.msg) for s in decision.signatures]
            ok = EcdsaP256BatchVerifier(min_device_batch=10**9).verify_batch(
                msgs,
                [s.value for s in decision.signatures],
                [keys[s.id] for s in decision.signatures],
            )
            assert ok.all(), "ledger carries an invalid P-256 signature"


def test_sharded_p256_matches_single_device():
    import jax

    from consensus_tpu.parallel import ShardedEcdsaP256Verifier, make_mesh

    msgs, sigs, keys = make_sigs(12)
    bad = list(sigs)
    bad[5] = bytes(64)
    mesh = make_mesh()
    assert mesh.devices.size == 8
    sharded = ShardedEcdsaP256Verifier(mesh).verify_batch(msgs, bad, keys)
    single = EcdsaP256BatchVerifier().verify_batch(msgs, bad, keys)
    assert (sharded == single).all()
    assert sharded.sum() == 11 and not sharded[5]
