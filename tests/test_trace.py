"""Decision-lifecycle tracing (consensus_tpu/trace/): determinism,
completeness, overhead, and metrics parity.

The tracer is clocked by the injected Scheduler, so two cluster runs with
the same seed must export byte-identical span streams — that is the
property that makes a trace attached to a bug report replayable.  The
export must be a valid Chrome/Perfetto trace whose per-decision spans nest
correctly, and every committed sequence must carry a complete
pre-prepare -> prepare -> commit -> deliver chain.  With tracing disabled
(the default), the protocol must perform ZERO ring-buffer appends.
"""

import json

from consensus_tpu.config import TraceConfig
from consensus_tpu.metrics import (
    VERIFY_LAUNCH_BATCH_KEY,
    WAL_RECORDS_PER_FSYNC_KEY,
    InMemoryProvider,
    Metrics,
)
from consensus_tpu.testing.app import Cluster, make_request
from consensus_tpu.testing.faults import FaultPlan, SimulatedCrash
from consensus_tpu.trace import (
    NOOP_TRACER,
    Tracer,
    build_report,
    format_table,
    to_chrome_json,
    to_jsonl,
)

DECISIONS = 50


def _traced_tweaks(**extra):
    tweaks = {
        "trace": TraceConfig(enabled=True),
        "request_batch_max_count": 1,
        "request_batch_max_interval": 0.01,
    }
    tweaks.update(extra)
    return tweaks


def _run_cluster(seed=7, decisions=DECISIONS, **cluster_kwargs):
    cluster = Cluster(
        4, seed=seed, config_tweaks=_traced_tweaks(), **cluster_kwargs
    )
    cluster.start()
    for i in range(decisions):
        cluster.submit_to_all(make_request("trace", i))
    assert cluster.run_until_ledger(decisions)
    return cluster


# --- unit: the ring buffer -------------------------------------------------


def test_ring_buffer_wraps_without_unbounded_growth():
    t = Tracer(lambda: 0.0, capacity=16)
    for i in range(100):
        t.instant("unit", "tick", n=i)
    events = t.events()
    assert len(events) == 16  # bounded: old events evicted, not accumulated
    assert t.appended == 100
    assert t.dropped == 84
    # Oldest-first, and the survivors are exactly the newest 16.
    assert [ev[6]["n"] for ev in events] == list(range(84, 100))


def test_tracer_rejects_zero_capacity():
    try:
        Tracer(lambda: 0.0, capacity=0)
    except ValueError:
        return
    raise AssertionError("capacity=0 must be rejected")


def test_noop_tracer_never_appends():
    before = Tracer.total_appends
    NOOP_TRACER.begin("x", "y", seq=1)
    NOOP_TRACER.instant("x", "z")
    NOOP_TRACER.end("x", "y", seq=1)
    assert Tracer.total_appends == before
    assert NOOP_TRACER.events() == []
    assert not NOOP_TRACER.enabled


# --- determinism: same seed, byte-identical exports ------------------------


def test_same_seed_exports_byte_identical_span_streams():
    streams = []
    for _ in range(2):
        cluster = _run_cluster(seed=7)
        tracer = cluster.nodes[1].consensus.tracer
        streams.append(
            (to_chrome_json(tracer.events()), to_jsonl(tracer.events()))
        )
    assert streams[0][0] == streams[1][0], "Chrome export diverged"
    assert streams[0][1] == streams[1][1], "JSONL export diverged"


# --- export validity + span nesting + chain completeness -------------------


def test_chrome_export_valid_spans_nest_and_chains_complete():
    cluster = _run_cluster(seed=11)
    tracer = cluster.nodes[1].consensus.tracer
    doc = json.loads(to_chrome_json(tracer.events()))
    assert doc["displayTimeUnit"] == "ms"
    records = doc["traceEvents"]
    assert records, "empty trace"

    # Async span streams pair by (cat, id, name): walk each stream and
    # require strict b/e alternation ending balanced — that is what makes
    # the spans NEST correctly when Perfetto reassembles them.
    open_spans = {}
    for ev in records:
        ph = ev["ph"]
        if ph not in ("b", "e"):
            continue
        key = (ev["cat"], ev["id"], ev["name"])
        depth = open_spans.get(key, 0)
        if ph == "b":
            assert depth == 0, f"double-begin for {key}"
            open_spans[key] = 1
        else:
            assert depth == 1, f"end-without-begin for {key}"
            open_spans[key] = 0
        # Timestamps are microseconds on the sim clock: monotone per spec
        # is guaranteed by the scheduler; just require non-negative.
        assert ev["ts"] >= 0
    dangling = [k for k, d in open_spans.items() if d]
    assert not dangling, f"unclosed spans: {dangling}"

    # Every committed sequence has the complete phase chain.
    report = build_report(tracer.events())
    assert report["n_decisions"] == DECISIONS
    assert report["n_complete"] == DECISIONS
    seqs = sorted(seq for (seq, _view) in report["decisions"])
    assert seqs == list(range(1, DECISIONS + 1))
    for phase in ("pre_prepare", "prepare", "commit", "deliver"):
        stats = report["phase_percentiles"][phase]
        assert stats["n"] == DECISIONS
        assert stats["p50"] >= 0.0 and stats["p99"] >= stats["p50"]
    # The human-readable table renders every phase row.
    table = format_table(report)
    for phase in report["phase_percentiles"]:
        assert phase in table


def test_jsonl_export_one_valid_object_per_event():
    cluster = _run_cluster(seed=13, decisions=5)
    tracer = cluster.nodes[1].consensus.tracer
    lines = to_jsonl(tracer.events()).splitlines()
    assert len(lines) == len(tracer.events())
    for line in lines:
        obj = json.loads(line)
        assert obj["ph"] in ("B", "E", "i")
        assert isinstance(obj["ts"], float)


# --- critical path under pipelining ----------------------------------------


def test_critical_path_report_with_pipelined_decisions_in_flight():
    """The report's FIFO pool-admit -> batch-seal matching must stay exact
    when ``pipeline_depth > 1`` keeps several decisions in flight: every
    decision still gets a ``pool_wait``/``seal_wait`` attribution, seals
    never consume more admits than the leader recorded, and the chains all
    complete."""
    decisions = 24
    cluster = Cluster(
        4,
        seed=41,
        config_tweaks=_traced_tweaks(
            pipeline_depth=4,
            request_batch_max_count=2,
            request_batch_max_interval=0.005,
        ),
    )
    cluster.start()
    for i in range(decisions * 2):  # two requests per sealed batch
        cluster.submit_to_all(make_request("pipe", i))
    assert cluster.run_until_ledger(decisions, max_time=120.0)

    events = cluster.nodes[1].consensus.tracer.events()  # the static leader
    # The window genuinely overlapped: decision spans were concurrently
    # open, so FIFO matching ran against interleaved admits and seals.
    open_now = max_open = 0
    for ph, _track, name, _ts, _seq, _view, _args in events:
        if name == "decision":
            open_now += 1 if ph == "B" else -1
            max_open = max(max_open, open_now)
    assert max_open > 1, "depth=4 run never pipelined"

    report = build_report(events)
    assert report["n_decisions"] == decisions
    assert report["n_complete"] == decisions
    percentiles = report["phase_percentiles"]
    for phase in ("pool_wait", "seal_wait"):
        assert percentiles[phase]["n"] == decisions
        assert percentiles[phase]["p50"] >= 0.0
    for d in report["decisions"].values():
        assert d["phases"]["pool_wait"] >= 0.0
        assert d["phases"]["seal_wait"] >= 0.0
    admits = sum(
        1 for ev in events if ev[0] == "i" and ev[2] == "pool.admit"
    )
    sealed = sum(
        (ev[6] or {}).get("count", 1)
        for ev in events
        if ev[0] == "i" and ev[2] == "batch.seal"
    )
    assert sealed <= admits, "seals consumed admits that never happened"


# --- crash-matrix visibility ----------------------------------------------


def test_crash_trace_contains_fired_fault_instant():
    cluster = Cluster(4, seed=23, config_tweaks=_traced_tweaks())
    cluster.start()
    victim = cluster.nodes[2]
    point = "state.save.commit.pre"
    plan = FaultPlan(point, label="trace-visibility")
    victim.arm_fault_plan(plan)
    tracer = victim.consensus.tracer  # ref survives the node teardown

    for i in range(3):
        cluster.submit_to_all(make_request("crash", i))
    survivors = [1, 3, 4]
    assert cluster.run_until_ledger(1, node_ids=survivors)
    assert plan.fired == (point, 1)

    fired = [
        ev
        for ev in tracer.events()
        if ev[0] == "i" and ev[1] == "fault" and ev[2] == "fault.fired"
    ]
    assert len(fired) == 1
    assert fired[0][6] == {"point": point, "hit": 1}


# --- overhead guard: disabled tracing is allocation-free -------------------


def test_disabled_tracing_makes_zero_ring_appends():
    decisions = 200
    before = Tracer.total_appends
    cluster = Cluster(  # default config: TraceConfig(enabled=False)
        4,
        seed=31,
        config_tweaks={
            "request_batch_max_count": 1,
            "request_batch_max_interval": 0.01,
        },
    )
    cluster.start()
    assert cluster.nodes[1].consensus.tracer is NOOP_TRACER
    for i in range(decisions):
        cluster.submit_to_all(make_request("off", i))
    assert cluster.run_until_ledger(decisions)
    assert Tracer.total_appends == before, (
        "disabled tracing must never touch a ring buffer"
    )

    # Parity: the same schedule with tracing ON commits the same count —
    # instrumentation must not perturb the protocol.
    traced = _run_cluster(seed=31, decisions=decisions)
    assert len(traced.nodes[1].app.ledger) == decisions
    assert len(cluster.nodes[1].app.ledger) == decisions


# --- metrics parity: tracer and histograms see the same values -------------


def test_dump_keys_pinned_and_trace_feeds_same_values():
    # The documented key names are a contract; renaming breaks loudly here.
    assert VERIFY_LAUNCH_BATCH_KEY == "consensus_cross_slot_verify_batch"
    assert WAL_RECORDS_PER_FSYNC_KEY == "consensus_wal_records_per_fsync"

    provider = InMemoryProvider()
    cluster = Cluster(
        4,
        seed=17,
        config_tweaks=_traced_tweaks(),
        durability_window=0.02,  # group commit: records coalesce per fsync
    )
    cluster.nodes[1].metrics = Metrics(provider)
    cluster.start()
    for i in range(20):
        cluster.submit_to_all(make_request("par", i))
    assert cluster.run_until_ledger(20)

    tracer = cluster.nodes[1].consensus.tracer
    report = build_report(tracer.events())
    dump = provider.dump()
    assert VERIFY_LAUNCH_BATCH_KEY in dump
    assert WAL_RECORDS_PER_FSYNC_KEY in dump

    # verify.launch instants carry exactly what the histogram observed.
    assert report["verify_launch_sizes"] == (
        dump[VERIFY_LAUNCH_BATCH_KEY]["observations"]
    )
    # wal.fsync instants end on the same value the coalescing gauge holds.
    assert report["fsync_records"], "group-commit run must record fsyncs"
    assert report["fsync_records"][-1] == (
        dump[WAL_RECORDS_PER_FSYNC_KEY]["value"]
    )


def test_net_injected_event_keys_pinned_and_mirror_trace_instants():
    """The chaos engine's injected network events (testing/network.py) are
    triple-booked: the SimNetwork.injected counter, the pinned-key metrics
    counters, and per-event ``net.<kind>`` tracer instants.  All three
    must agree event-for-event, and the key names are a contract."""
    from collections import Counter

    from consensus_tpu.metrics import (
        NET_DROPPED_KEY,
        NET_DUPLICATED_KEY,
        NET_INJECTED_KEYS,
        NET_REORDERED_KEY,
        NET_REPLAYED_KEY,
    )
    from consensus_tpu.runtime.scheduler import SimScheduler
    from consensus_tpu.testing.network import INJECTED_EVENT_KINDS, SimNetwork

    assert NET_DROPPED_KEY == "net_injected_dropped"
    assert NET_DUPLICATED_KEY == "net_injected_duplicated"
    assert NET_REORDERED_KEY == "net_injected_reordered"
    assert NET_REPLAYED_KEY == "net_injected_replayed"
    assert NET_INJECTED_KEYS == tuple(
        f"net_injected_{kind}" for kind in INJECTED_EVENT_KINDS
    )

    provider = InMemoryProvider()
    sched = SimScheduler()
    net = SimNetwork(sched, seed=3)
    net.metrics = Metrics(provider).network
    tracer = Tracer(sched.now, capacity=8192)
    net.tracer = tracer
    net.register(1, lambda s, p, r: None)
    net.register(2, lambda s, p, r: None)
    net.set_loss(1, 2, 0.3)
    net.set_duplicate(1, 2, 0.3)
    net.set_reorder(1, 2, 0.3)
    net.set_replay(1, 2, 0.3)
    for i in range(300):
        net.send(1, 2, b"m%d" % i, is_request=True)
        sched.advance(0.002)
    sched.advance(1.0)

    assert sum(net.injected.values()) > 0, "seeded run must inject"
    dump = provider.dump()
    instants = Counter(
        ev[2] for ev in tracer.events() if ev[0] == "i" and ev[1] == "net"
    )
    for kind in INJECTED_EVENT_KINDS:
        assert dump[f"net_injected_{kind}"]["value"] == net.injected[kind]
        assert instants[f"net.{kind}"] == net.injected[kind]
