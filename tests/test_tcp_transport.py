"""Live TCP transport tests: framing round trips, and a real 4-replica
cluster over localhost sockets with realtime schedulers ordering blocks in
wall-clock time (the production deployment shape, minus TLS).
"""

import socket
import threading
import time

import pytest

from consensus_tpu.config import Configuration
from consensus_tpu.consensus import Consensus
from consensus_tpu.net import TcpComm
from consensus_tpu.runtime import RealtimeScheduler
from consensus_tpu.testing.app import MemWAL, make_request
from consensus_tpu.testing.app import TestApp as PortsApp
from consensus_tpu.types import Decision, Reconfig
from consensus_tpu.wire import HeartBeat, Prepare


def free_ports(n):
    socks = [socket.socket() for _ in range(n)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def test_tcp_comm_frames_consensus_and_requests():
    ports = free_ports(2)
    addrs = {1: ("127.0.0.1", ports[0]), 2: ("127.0.0.1", ports[1])}
    received = []
    got = threading.Event()

    def on_message_2(sender, payload, is_request):
        received.append((sender, payload, is_request))
        if len(received) >= 2:
            got.set()

    comm1 = TcpComm(1, addrs, lambda *a: None)
    comm2 = TcpComm(2, addrs, on_message_2)
    comm1.start()
    comm2.start()
    try:
        comm1.send_consensus(2, Prepare(view=1, seq=2, digest="abcd"))
        comm1.send_transaction(2, b"raw-request-bytes")
        assert got.wait(timeout=10.0), f"only received {received}"
        kinds = {(s, type(p).__name__, r) for s, p, r in received}
        assert (1, "Prepare", False) in kinds
        assert (1, "bytes", True) in kinds
        assert comm1.nodes() == [1, 2]
    finally:
        comm1.stop()
        comm2.stop()


def test_tcp_send_to_dead_peer_drops_silently():
    ports = free_ports(2)
    addrs = {1: ("127.0.0.1", ports[0]), 2: ("127.0.0.1", ports[1])}
    comm1 = TcpComm(1, addrs, lambda *a: None, reconnect_backoff=0.05)
    comm1.start()
    try:
        # Peer 2 never starts: sends must not raise or block.
        for _ in range(50):
            comm1.send_consensus(2, HeartBeat(view=0, seq=0))
        time.sleep(0.2)
    finally:
        comm1.stop()


class _RealCluster:
    """Shared ledger registry for TestApp.sync across real replicas."""

    def __init__(self):
        self.nodes = {}

    def longest_ledger(self, *, exclude):
        best = []
        for node_id, holder in self.nodes.items():
            if node_id == exclude or not holder.running:
                continue
            ledger = holder.app.ledger
            if len(ledger) > len(best):
                best = ledger
        return list(best)

    def reconfig_of(self, proposal):
        return Reconfig()


class _Holder:
    def __init__(self, app):
        self.app = app
        self.running = True


def test_four_replicas_over_real_tcp_sockets():
    n = 4
    ports = free_ports(n)
    addrs = {i + 1: ("127.0.0.1", ports[i]) for i in range(n)}
    cluster = _RealCluster()
    replicas = {}
    comms = {}
    schedulers = {}

    try:
        for node_id in addrs:
            app = PortsApp(node_id, cluster)
            cluster.nodes[node_id] = _Holder(app)
            rt = RealtimeScheduler()
            rt.start(thread_name=f"replica-{node_id}")
            schedulers[node_id] = rt

            def make_router(nid):
                def route(sender, payload, is_request):
                    consensus = replicas.get(nid)
                    if consensus is None:
                        return
                    if is_request:
                        consensus.handle_request(sender, payload)
                    else:
                        consensus.handle_message(sender, payload)
                return route

            comm = TcpComm(node_id, addrs, make_router(node_id),
                           reconnect_backoff=0.05)
            comm.start()
            comms[node_id] = comm

            consensus = Consensus(
                config=Configuration(
                    self_id=node_id,
                    leader_rotation=False,
                    decisions_per_leader=0,
                    request_batch_max_interval=0.02,
                ),
                scheduler=rt,
                comm=comm,
                application=app,
                assembler=app,
                wal=MemWAL([]),
                signer=app,
                verifier=app,
                request_inspector=app.inspector,
                synchronizer=app,
            )
            consensus.start()
            replicas[node_id] = consensus

        # Order 5 blocks through real sockets, in real time.
        for i in range(5):
            raw = make_request("cli", i)
            for consensus in replicas.values():
                consensus.submit_request(raw)
            deadline = time.time() + 30.0
            while time.time() < deadline:
                if all(
                    len(cluster.nodes[nid].app.ledger) >= i + 1 for nid in replicas
                ):
                    break
                time.sleep(0.02)
            else:
                raise AssertionError(f"block {i} not ordered over TCP")

        ledgers = {
            nid: [d.proposal.digest() for d in cluster.nodes[nid].app.ledger]
            for nid in replicas
        }
        reference = next(iter(ledgers.values()))
        assert all(l == reference for l in ledgers.values()), "ledger divergence"
        for nid in replicas:
            for decision in cluster.nodes[nid].app.ledger:
                assert len(decision.signatures) >= 3
    finally:
        for consensus in replicas.values():
            consensus.stop()
        for comm in comms.values():
            comm.stop()
        for rt in schedulers.values():
            try:
                rt.stop(timeout=2.0)
            except RuntimeError:
                pass


def test_hello_pins_sender_and_rejects_impersonation():
    import struct

    from consensus_tpu.net.transport import _HEADER, _KIND_HELLO

    ports = free_ports(2)
    addrs = {1: ("127.0.0.1", ports[0]), 2: ("127.0.0.1", ports[1])}
    received = []
    comm2 = TcpComm(2, addrs, lambda s, m, r: received.append((s, m)))
    comm2.start()
    try:
        # A raw client claiming sender 1 in HELLO, then forging sender 3 in
        # a later frame: the link must be dropped, nothing dispatched.
        sock = socket.create_connection(("127.0.0.1", ports[1]), timeout=5)
        sock.sendall(_HEADER.pack(0, 1, _KIND_HELLO))
        from consensus_tpu.wire import encode_message

        forged = encode_message(HeartBeat(view=0, seq=0))
        sock.sendall(_HEADER.pack(len(forged), 3, 0) + forged)
        time.sleep(0.3)
        assert received == [], "forged-sender frame was dispatched"
        # And a frame before HELLO is also rejected.
        sock2 = socket.create_connection(("127.0.0.1", ports[1]), timeout=5)
        sock2.sendall(_HEADER.pack(len(forged), 1, 0) + forged)
        time.sleep(0.3)
        assert received == []
        sock.close()
        sock2.close()
    finally:
        comm2.stop()


def test_auth_secret_rejects_wrong_key():
    ports = free_ports(2)
    addrs = {1: ("127.0.0.1", ports[0]), 2: ("127.0.0.1", ports[1])}
    received = []
    got = threading.Event()
    comm2 = TcpComm(2, addrs, lambda s, m, r: (received.append(m), got.set()),
                    auth_secret=b"cluster-secret")
    comm2.start()
    bad = TcpComm(1, addrs, lambda *a: None, auth_secret=b"wrong-secret",
                  reconnect_backoff=0.05)
    bad.start()
    try:
        bad.send_consensus(2, HeartBeat(view=1, seq=1))
        time.sleep(0.4)
        assert received == [], "wrong-secret peer got through"
        bad.stop()

        # Fresh listen port for node 1 (the old listener may still be in
        # teardown); only node 2's address matters for this direction.
        addrs_good = {1: ("127.0.0.1", free_ports(1)[0]), 2: addrs[2]}
        good = TcpComm(1, addrs_good, lambda *a: None, auth_secret=b"cluster-secret")
        good.start()
        try:
            good.send_consensus(2, HeartBeat(view=2, seq=2))
            assert got.wait(5.0), "right-secret peer was rejected"
            assert received[0].view == 2
        finally:
            good.stop()
    finally:
        comm2.stop()
