"""Live TCP transport tests: framing round trips, and a real 4-replica
cluster over localhost sockets with realtime schedulers ordering blocks in
wall-clock time (the production deployment shape, minus TLS).
"""

import socket
import threading
import time

import pytest

from consensus_tpu.config import Configuration
from consensus_tpu.consensus import Consensus
from consensus_tpu.net import TcpComm
from consensus_tpu.runtime import RealtimeScheduler
from consensus_tpu.testing.app import MemWAL, make_request
from consensus_tpu.testing.app import TestApp as PortsApp
from consensus_tpu.types import Decision, Reconfig
from consensus_tpu.wire import HeartBeat, Prepare


def free_ports(n):
    socks = [socket.socket() for _ in range(n)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def test_tcp_comm_frames_consensus_and_requests():
    ports = free_ports(2)
    addrs = {1: ("127.0.0.1", ports[0]), 2: ("127.0.0.1", ports[1])}
    received = []
    got = threading.Event()

    def on_message_2(sender, payload, is_request):
        received.append((sender, payload, is_request))
        if len(received) >= 2:
            got.set()

    comm1 = TcpComm(1, addrs, lambda *a: None)
    comm2 = TcpComm(2, addrs, on_message_2)
    comm1.start()
    comm2.start()
    try:
        comm1.send_consensus(2, Prepare(view=1, seq=2, digest="abcd"))
        comm1.send_transaction(2, b"raw-request-bytes")
        assert got.wait(timeout=10.0), f"only received {received}"
        kinds = {(s, type(p).__name__, r) for s, p, r in received}
        assert (1, "Prepare", False) in kinds
        assert (1, "bytes", True) in kinds
        assert comm1.nodes() == [1, 2]
    finally:
        comm1.stop()
        comm2.stop()


def test_tcp_send_to_dead_peer_drops_silently():
    ports = free_ports(2)
    addrs = {1: ("127.0.0.1", ports[0]), 2: ("127.0.0.1", ports[1])}
    comm1 = TcpComm(1, addrs, lambda *a: None, reconnect_backoff=0.05)
    comm1.start()
    try:
        # Peer 2 never starts: sends must not raise or block.
        for _ in range(50):
            comm1.send_consensus(2, HeartBeat(view=0, seq=0))
        time.sleep(0.2)
    finally:
        comm1.stop()


class _RealCluster:
    """Shared ledger registry for TestApp.sync across real replicas."""

    def __init__(self):
        self.nodes = {}

    def longest_ledger(self, *, exclude):
        best = []
        for node_id, holder in self.nodes.items():
            if node_id == exclude or not holder.running:
                continue
            ledger = holder.app.ledger
            if len(ledger) > len(best):
                best = ledger
        return list(best)

    def reconfig_of(self, proposal):
        return Reconfig()


class _Holder:
    def __init__(self, app):
        self.app = app
        self.running = True


def test_four_replicas_over_real_tcp_sockets():
    n = 4
    ports = free_ports(n)
    addrs = {i + 1: ("127.0.0.1", ports[i]) for i in range(n)}
    cluster = _RealCluster()
    replicas = {}
    comms = {}
    schedulers = {}

    try:
        for node_id in addrs:
            app = PortsApp(node_id, cluster)
            cluster.nodes[node_id] = _Holder(app)
            rt = RealtimeScheduler()
            rt.start(thread_name=f"replica-{node_id}")
            schedulers[node_id] = rt

            def make_router(nid):
                def route(sender, payload, is_request):
                    consensus = replicas.get(nid)
                    if consensus is None:
                        return
                    if is_request:
                        consensus.handle_request(sender, payload)
                    else:
                        consensus.handle_message(sender, payload)
                return route

            comm = TcpComm(node_id, addrs, make_router(node_id),
                           reconnect_backoff=0.05)
            comm.start()
            comms[node_id] = comm

            consensus = Consensus(
                config=Configuration(
                    self_id=node_id,
                    leader_rotation=False,
                    decisions_per_leader=0,
                    request_batch_max_interval=0.02,
                ),
                scheduler=rt,
                comm=comm,
                application=app,
                assembler=app,
                wal=MemWAL([]),
                signer=app,
                verifier=app,
                request_inspector=app.inspector,
                synchronizer=app,
            )
            consensus.start()
            replicas[node_id] = consensus

        # Order 5 blocks through real sockets, in real time.
        for i in range(5):
            raw = make_request("cli", i)
            for consensus in replicas.values():
                consensus.submit_request(raw)
            deadline = time.time() + 30.0
            while time.time() < deadline:
                if all(
                    len(cluster.nodes[nid].app.ledger) >= i + 1 for nid in replicas
                ):
                    break
                time.sleep(0.02)
            else:
                raise AssertionError(f"block {i} not ordered over TCP")

        ledgers = {
            nid: [d.proposal.digest() for d in cluster.nodes[nid].app.ledger]
            for nid in replicas
        }
        reference = next(iter(ledgers.values()))
        assert all(l == reference for l in ledgers.values()), "ledger divergence"
        for nid in replicas:
            for decision in cluster.nodes[nid].app.ledger:
                assert len(decision.signatures) >= 3
    finally:
        for consensus in replicas.values():
            consensus.stop()
        for comm in comms.values():
            comm.stop()
        for rt in schedulers.values():
            try:
                rt.stop(timeout=2.0)
            except RuntimeError:
                pass


def test_hello_pins_sender_and_rejects_impersonation():
    import struct

    from consensus_tpu.net.transport import _HEADER, _KIND_HELLO

    ports = free_ports(2)
    addrs = {1: ("127.0.0.1", ports[0]), 2: ("127.0.0.1", ports[1])}
    received = []
    comm2 = TcpComm(2, addrs, lambda s, m, r: received.append((s, m)))
    comm2.start()
    try:
        # A raw client claiming sender 1 in HELLO, then forging sender 3 in
        # a later frame: the link must be dropped, nothing dispatched.
        sock = socket.create_connection(("127.0.0.1", ports[1]), timeout=5)
        sock.sendall(_HEADER.pack(0, 1, _KIND_HELLO))
        from consensus_tpu.wire import encode_message

        forged = encode_message(HeartBeat(view=0, seq=0))
        sock.sendall(_HEADER.pack(len(forged), 3, 0) + forged)
        time.sleep(0.3)
        assert received == [], "forged-sender frame was dispatched"
        # And a frame before HELLO is also rejected.
        sock2 = socket.create_connection(("127.0.0.1", ports[1]), timeout=5)
        sock2.sendall(_HEADER.pack(len(forged), 1, 0) + forged)
        time.sleep(0.3)
        assert received == []
        sock.close()
        sock2.close()
    finally:
        comm2.stop()


def test_auth_secret_rejects_wrong_key():
    ports = free_ports(2)
    addrs = {1: ("127.0.0.1", ports[0]), 2: ("127.0.0.1", ports[1])}
    received = []
    got = threading.Event()
    comm2 = TcpComm(2, addrs, lambda s, m, r: (received.append(m), got.set()),
                    auth_secret=b"cluster-secret")
    comm2.start()
    bad = TcpComm(1, addrs, lambda *a: None, auth_secret=b"wrong-secret",
                  reconnect_backoff=0.05)
    bad.start()
    try:
        bad.send_consensus(2, HeartBeat(view=1, seq=1))
        time.sleep(0.4)
        assert received == [], "wrong-secret peer got through"
        bad.stop()

        # Fresh listen port for node 1 (the old listener may still be in
        # teardown); only node 2's address matters for this direction.
        addrs_good = {1: ("127.0.0.1", free_ports(1)[0]), 2: addrs[2]}
        good = TcpComm(1, addrs_good, lambda *a: None, auth_secret=b"cluster-secret")
        good.start()
        try:
            good.send_consensus(2, HeartBeat(view=2, seq=2))
            assert got.wait(5.0), "right-secret peer was rejected"
            assert received[0].view == 2
        finally:
            good.stop()
    finally:
        comm2.stop()


# --------------------------------------------------------------------------
# Deploy-rig hardening regressions: abrupt peer death on both channels.


def test_sync_listener_survives_partial_frames_and_rst():
    """A peer killed mid-frame (kill -9 shape: EOF after a partial header,
    a truncated payload, or a hard RST) must not hang the SyncListener or
    half-apply a chunk — and the listener must keep serving afterwards."""
    import struct as _struct

    from consensus_tpu.sync import LedgerDecisionStore, SyncListener, SyncServer
    from consensus_tpu.sync.transport import TcpSyncTransport
    from consensus_tpu.types import Proposal

    ledger = [
        Decision(proposal=Proposal(payload=f"block-{i}".encode()))
        for i in range(1, 4)
    ]
    listener = SyncListener(SyncServer(LedgerDecisionStore(ledger)))
    try:
        # 1) EOF after a partial u32 header.
        c = socket.create_connection(listener.address, timeout=5)
        c.sendall(b"\x00\x00")
        c.close()
        # 2) Header promises 100 bytes, connection dies after 10 (RST).
        c = socket.create_connection(listener.address, timeout=5)
        c.sendall(_struct.pack(">I", 100) + b"x" * 10)
        c.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                     _struct.pack("ii", 1, 0))  # RST on close
        c.close()
        time.sleep(0.1)
        # 3) The listener still answers a well-formed fetch.
        transport = TcpSyncTransport(9, {1: listener.address}, timeout=5.0)
        from consensus_tpu.wire import SyncRequest

        reply = transport.fetch(1, SyncRequest(from_seq=1, to_seq=3))
        assert reply is not None and len(reply.decisions) == 3
    finally:
        listener.close()


def test_sync_fetch_fails_clean_when_server_dies_mid_reply():
    """The client half of the same contract: a server that accepts and then
    closes without a full reply yields None (no hang, no partial chunk)."""
    from consensus_tpu.sync.transport import TcpSyncTransport
    from consensus_tpu.wire import SyncRequest

    server = socket.create_server(("127.0.0.1", 0))
    address = server.getsockname()
    done = threading.Event()

    def half_reply():
        conn, _ = server.accept()
        conn.recv(65536)          # swallow the request
        conn.sendall(b"\x00\x00\x00\x40" + b"y" * 5)  # promise 64, send 5
        conn.close()
        done.set()

    t = threading.Thread(target=half_reply, daemon=True)
    t.start()
    try:
        transport = TcpSyncTransport(9, {1: address}, timeout=2.0)
        t0 = time.monotonic()
        reply = transport.fetch(1, SyncRequest(from_seq=1, to_seq=1))
        assert reply is None
        assert time.monotonic() - t0 < 5.0, "fetch hung instead of failing"
        assert done.wait(2.0)
    finally:
        server.close()


def test_tcp_comm_reconnect_retry_metrics_and_recovery():
    """Satellite-1 hardening: connection-refused gets bounded retries with
    the pinned reconnect counters booked, and frames flow once the peer
    comes up (a supervisor-restarted process reuses its spec'd port)."""
    from consensus_tpu.metrics import InMemoryProvider, Metrics

    ports = free_ports(2)
    addrs = {1: ("127.0.0.1", ports[0]), 2: ("127.0.0.1", ports[1])}
    provider = Metrics(InMemoryProvider())
    comm1 = TcpComm(
        1, addrs, lambda *a: None,
        reconnect_backoff=0.02, connect_attempts=2, send_retries=1,
        metrics=provider.network,
    )
    comm1.start()
    received = []
    got = threading.Event()
    try:
        # Peer 2 is down: the frame rides the bounded retry path and is
        # dropped, with attempts and the drop booked.
        comm1.send_consensus(2, HeartBeat(view=1, seq=1))
        deadline = time.monotonic() + 5.0
        p = provider.provider
        while time.monotonic() < deadline:
            if p.value("net_send_dropped") >= 1:
                break
            time.sleep(0.02)
        assert p.value("net_send_dropped") >= 1
        assert p.value("net_reconnect_attempts") >= 2  # both budgeted tries
        assert p.value("net_reconnect_success") == 0

        # Peer restarts on the SAME port (the deploy restart contract):
        # the next frame reconnects and is delivered.
        comm2 = TcpComm(2, addrs, lambda s, m, r: (received.append(m), got.set()))
        comm2.start()
        try:
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline and not got.is_set():
                comm1.send_consensus(2, HeartBeat(view=7, seq=7))
                got.wait(0.2)
            assert got.is_set(), "no frame delivered after peer came back"
            assert received[0].view == 7
            assert p.value("net_reconnect_success") >= 1
        finally:
            comm2.stop()
    finally:
        comm1.stop()


def test_tcp_comm_resends_frame_after_midframe_abrupt_close():
    """A peer killed while we were writing (OSError from sendall) must not
    lose the frame: the writer reconnects and re-sends it, booking the
    pinned retry counter — the fire-and-forget drop fires only after the
    retry budget."""
    from consensus_tpu.metrics import InMemoryProvider, Metrics
    from consensus_tpu.testing.faults import FaultPlan

    ports = free_ports(2)
    addrs = {1: ("127.0.0.1", ports[0]), 2: ("127.0.0.1", ports[1])}
    received = []
    got = threading.Event()
    comm2 = TcpComm(2, addrs, lambda s, m, r: (received.append(m), got.set()))
    comm2.start()
    provider = Metrics(InMemoryProvider())
    # net.send.io_error armed for hit 1: the FIRST write dies exactly as if
    # the peer vanished mid-frame; the retry path must deliver it anyway.
    comm1 = TcpComm(
        1, addrs, lambda *a: None,
        reconnect_backoff=0.02, send_retries=2,
        metrics=provider.network,
        fault_plan=FaultPlan("net.send.io_error", on_hit=1),
    )
    comm1.start()
    try:
        comm1.send_consensus(2, HeartBeat(view=3, seq=9))
        assert got.wait(10.0), "frame lost to a mid-frame abrupt close"
        assert received[0].seq == 9
        assert provider.provider.value("net_send_retried") >= 1
        assert provider.provider.value("net_send_dropped") == 0
    finally:
        comm1.stop()
        comm2.stop()


def test_tcp_comm_listener_pause_resume():
    """The deploy chaos verb: pause_listener drops the listen port (inbound
    peers see refused + severed links), resume_listener rebinds the same
    address and frames flow again."""
    ports = free_ports(2)
    addrs = {1: ("127.0.0.1", ports[0]), 2: ("127.0.0.1", ports[1])}
    received = []
    comm2 = TcpComm(2, addrs, lambda s, m, r: received.append(m))
    comm2.start()
    comm1 = TcpComm(1, addrs, lambda *a: None, reconnect_backoff=0.02,
                    connect_attempts=1)
    comm1.start()
    try:
        comm1.send_consensus(2, HeartBeat(view=1, seq=1))
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and not received:
            time.sleep(0.02)
        assert received, "baseline frame not delivered"

        comm2.pause_listener()
        time.sleep(0.1)
        n = len(received)
        comm1.send_consensus(2, HeartBeat(view=2, seq=2))
        time.sleep(0.5)
        assert len(received) == n, "frame delivered through a dropped listener"

        comm2.resume_listener()
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and len(received) == n:
            comm1.send_consensus(2, HeartBeat(view=3, seq=3))
            time.sleep(0.2)
        assert len(received) > n, "no frames after listener resume"
        assert received[-1].view == 3
    finally:
        comm1.stop()
        comm2.stop()


def test_tcp_comm_resume_listener_failure_stays_healable():
    """A failed resume (port stolen during the pause window) must NOT
    clear the paused flag: the next resume_listener retries the rebind
    instead of silently no-opping into a permanent inbound partition."""
    ports = free_ports(2)
    addrs = {1: ("127.0.0.1", ports[0]), 2: ("127.0.0.1", ports[1])}
    received = []
    comm2 = TcpComm(2, addrs, lambda s, m, r: received.append(m))
    comm2.start()
    comm1 = TcpComm(1, addrs, lambda *a: None, reconnect_backoff=0.02,
                    connect_attempts=1)
    comm1.start()
    try:
        comm2.pause_listener()
        comm2._rebind_attempts = 3  # keep the failing resume fast
        comm2._rebind_delay = 0.01

        def stolen_port():
            raise OSError("port stolen during the pause window")

        real_bind = comm2._bind_listener
        comm2._bind_listener = stolen_port
        try:
            with pytest.raises(OSError):
                comm2.resume_listener()
        finally:
            comm2._bind_listener = real_bind
        # The paused flag survived the failure, so this retry (the chaos
        # heal re-issuing net_resume) actually rebinds and heals.
        comm2.resume_listener()
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and not received:
            comm1.send_consensus(2, HeartBeat(view=7, seq=7))
            time.sleep(0.2)
        assert received, "listener never healed after a failed resume"
        assert received[-1].view == 7
    finally:
        comm1.stop()
        comm2.stop()
