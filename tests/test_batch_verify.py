"""Randomized Ed25519 batch verification (models/ed25519.py:
Ed25519RandomizedBatchVerifier) — the shared-doubling aggregate check, its
bisection fallback, and the wiring that rides it.

The load-bearing contract is EXACT boolean-vector parity with the strict
verifier: for every input the strict path rejects-by-math (forged S, wrong
message, wrong key, undecodable R/A, non-canonical encodings), the
randomized verifier must return the bit-identical result vector — the
aggregate check only amortizes cost, it never changes verdicts.  The
adversarial cases below hide forgeries at every awkward position (single,
clustered, all, bisection boundaries) and assert that parity.

Also covered: the deps.py multi-batch coalescing seam (one engine launch
for many quorum groups when batch_verify_mode is on), the chaos-engine
crypto parity gate (strict vs randomized engines on the SAME schedule must
produce identical ledgers), the field-op counting shim that produced the
BASELINE.md amortization numbers, and bench.py's structured skip path for
the new batch-verify column.
"""

import hashlib
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from consensus_tpu.api.deps import Verifier
from consensus_tpu.models.ed25519 import (
    Ed25519BatchVerifier,
    Ed25519RandomizedBatchVerifier,
    _transcript_coefficients,
    ref_public_key,
    ref_sign,
)
from consensus_tpu.types import Proposal

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

N = 512


def _host_strict():
    return Ed25519BatchVerifier(min_device_batch=10**9)


def _host_randomized(**kw):
    kw.setdefault("min_device_batch", 10**9)
    return Ed25519RandomizedBatchVerifier(**kw)


@pytest.fixture(scope="module")
def corpus():
    """512 honest (message, signature, key) triples from 8 signers, pure
    deterministic ref crypto (no ambient RNG)."""
    seeds = [
        hashlib.sha512(b"ctpu/test-bv/%d" % i).digest()[:32] for i in range(8)
    ]
    pubs = [ref_public_key(s) for s in seeds]
    msgs, sigs, keys = [], [], []
    for i in range(N):
        m = b"batch-verify-%d" % i
        msgs.append(m)
        sigs.append(ref_sign(seeds[i % 8], m))
        keys.append(pubs[i % 8])
    return msgs, sigs, keys


@pytest.fixture(scope="module")
def strict_honest(corpus):
    """The strict host verifier's vector over the honest corpus — the
    ground truth every randomized run is compared against."""
    msgs, sigs, keys = corpus
    vec = _host_strict().verify_batch(msgs, sigs, keys)
    assert vec.all(), "honest corpus must verify strictly"
    return vec


def _forge(sig: bytes) -> bytes:
    # Flip a low byte of S: stays canonical (S < L), fails by math — the
    # case that MUST go through the aggregate-check + bisection machinery
    # rather than being shed by host pre-checks.
    f = bytearray(sig)
    f[33] ^= 0xFF
    return bytes(f)


def _strict_expected(strict_honest, corpus, forged_idx, sigs):
    """Strict vector for the corpus with ``sigs`` substituted — computed by
    running the strict verifier on exactly the substituted entries and
    splicing (strict verification is per-signature independent, so this IS
    the full strict vector, at a fraction of the cost)."""
    msgs, _, keys = corpus
    expected = strict_honest.copy()
    sub = _host_strict().verify_batch(
        [msgs[i] for i in forged_idx],
        [sigs[i] for i in forged_idx],
        [keys[i] for i in forged_idx],
    )
    for j, i in enumerate(forged_idx):
        expected[i] = sub[j]
    return expected


# --- adversarial bisection: exact parity with strict ------------------------


def test_honest_batch_matches_strict(corpus, strict_honest):
    msgs, sigs, keys = corpus
    got = _host_randomized().verify_batch(msgs, sigs, keys)
    assert got.dtype == np.bool_
    assert np.array_equal(got, strict_honest)


@pytest.mark.parametrize(
    "forged",
    [
        [137],                      # one forged hidden in 512
        [3, 77, 200, 201, 350, 508],  # several, incl. an adjacent pair
        [0, 255, 256, 511],         # bisection boundaries: ends + midpoint
    ],
    ids=["one-in-512", "multiple", "boundaries"],
)
def test_forged_signatures_localized_exactly(corpus, strict_honest, forged):
    msgs, sigs, keys = corpus
    sigs = list(sigs)
    for i in forged:
        sigs[i] = _forge(sigs[i])
    expected = _strict_expected(strict_honest, corpus, forged, sigs)
    assert not expected[forged].any(), "forgeries must fail strictly"
    got = _host_randomized().verify_batch(msgs, sigs, keys)
    assert np.array_equal(got, expected)


def test_all_forged(corpus):
    msgs, sigs, keys = corpus
    m, s, k = msgs[:64], [_forge(x) for x in sigs[:64]], keys[:64]
    expected = _host_strict().verify_batch(m, s, k)
    got = _host_randomized().verify_batch(m, s, k)
    assert not got.any()
    assert np.array_equal(got, expected)


def test_mixed_failure_classes_match_strict(corpus):
    """Every rejection class in one batch: math forgery, tampered message,
    wrong key, non-canonical S (host pre-check), undecodable A (non-QR y),
    undecodable R — the valid-mask re-check path and the host_ok path must
    both land exactly where strict lands."""
    msgs, sigs, keys = [list(x[:16]) for x in corpus]
    sigs[1] = _forge(sigs[1])
    msgs[3] = b"tampered"
    keys[5] = keys[6]                       # valid point, wrong signer
    sigs[7] = b"\xff" * 64                  # S >= L: non-canonical
    keys[9] = b"\x02" + b"\x00" * 31        # y=2 is not on the curve
    sigs[11] = b"\x02" + b"\x00" * 31 + sigs[11][32:]  # undecodable R
    expected = _host_strict().verify_batch(msgs, sigs, keys)
    got = _host_randomized().verify_batch(msgs, sigs, keys)
    assert np.array_equal(got, expected)
    assert not expected[[1, 3, 5, 7, 9, 11]].any()
    assert expected[[0, 2, 4, 6, 8, 10, 12, 13, 14, 15]].all()


def test_tiny_batches_delegate_to_strict(corpus):
    msgs, sigs, keys = corpus
    v = _host_randomized()
    assert v.verify_batch([], [], []).shape == (0,)
    one = v.verify_batch(msgs[:1], sigs[:1], keys[:1])
    assert one.tolist() == [True]
    bad = v.verify_batch(msgs[:1], [_forge(sigs[0])], keys[:1])
    assert bad.tolist() == [False]


def test_device_kernel_parity(corpus):
    """The shared-doubling device kernel (batch_verify_impl) agrees with
    the host big-int backend and with strict, through bisection.  pad_to
    pins every subset launch to one compiled shape."""
    msgs, sigs, keys = [list(x[:16]) for x in corpus]
    sigs[4] = _forge(sigs[4])
    keys[9] = b"\x02" + b"\x00" * 31
    expected = _host_strict().verify_batch(msgs, sigs, keys)
    v = Ed25519RandomizedBatchVerifier(min_device_batch=1, pad_to=16)
    got = v.verify_batch(msgs, sigs, keys)
    assert np.array_equal(np.asarray(got), expected)


def test_same_inputs_same_verdicts(corpus):
    """Determinism rule: no wallclock, no ambient RNG — two fresh verifier
    instances on the same bytes produce identical vectors (and the
    transcript coefficients behind them are pure functions of the batch)."""
    msgs, sigs, keys = [list(x[:32]) for x in corpus]
    sigs[10] = _forge(sigs[10])
    a = _host_randomized().verify_batch(msgs, sigs, keys)
    b = _host_randomized().verify_batch(msgs, sigs, keys)
    assert np.array_equal(a, b)

    z1 = _transcript_coefficients(msgs, sigs, keys)
    z2 = _transcript_coefficients(msgs, sigs, keys)
    assert z1 == z2
    assert all(1 <= z < 2**128 for z in z1)
    # The transcript binds content AND position: permuting the batch
    # changes every coefficient.
    z3 = _transcript_coefficients(msgs[::-1], sigs[::-1], keys[::-1])
    assert z3 != z1


@pytest.mark.slow
def test_batch_1024_parity(corpus):
    # Batch sizes beyond the 512 acceptance point ride the slow lane.
    msgs, sigs, keys = corpus
    m, s, k = msgs + msgs, list(sigs + sigs), keys + keys
    s[700] = _forge(s[700])
    expected = _host_strict().verify_batch(m, s, k)
    got = _host_randomized().verify_batch(m, s, k)
    assert np.array_equal(got, expected)


# --- field-op counting shim + the measured amortization claim ---------------


def test_counting_shim_weighs_lanes_and_scan_trips():
    import jax.numpy as jnp

    from consensus_tpu.ops import field25519 as fe
    from consensus_tpu.ops import limbs

    a = jnp.zeros((32, 4), jnp.float32)  # 4 batch lanes
    assert not limbs.counting()
    count = limbs.measure_field_ops(fe.mul, a, a)
    assert (count.muls, count.squares) == (4, 0)
    count = limbs.measure_field_ops(fe.square, a)
    assert (count.muls, count.squares) == (0, 4)
    assert count.m_equiv == pytest.approx(4 * limbs.SQUARE_M_RATIO)

    def scanned(x):
        def body(c, _):
            return fe.mul(c, x), None

        c, _ = limbs.counted_scan(body, x, None, length=5)
        return c

    # One traced mul body, weighted by 5 trips x 4 lanes.
    count = limbs.measure_field_ops(scanned, a)
    assert (count.muls, count.squares) == (20, 0)
    assert not limbs.counting()


@pytest.mark.slow
def test_amortized_field_muls_at_512_below_half_of_strict():
    """THE acceptance measurement (BASELINE.md records the numbers): at
    batch 512 the randomized aggregate path costs <= 50% of the strict
    kernel's field multiplications per signature.  Abstract tracing only
    (jax.eval_shape) — but tracing two batch-512 graphs still takes
    minutes, hence the slow marker; the committed BASELINE.md table is the
    tier-1-visible artifact of this claim."""
    import jax
    import jax.numpy as jnp

    from consensus_tpu.models import ed25519 as model
    from consensus_tpu.ops import limbs

    b = 512
    strict = limbs.measure_field_ops(
        model.verify_impl,
        jnp.zeros((32, b), jnp.uint8),
        jnp.zeros((b,), jnp.uint8),
        jnp.zeros((32, b), jnp.uint8),
        jnp.zeros((b,), jnp.uint8),
        jnp.zeros((32, b), jnp.uint8),
        jnp.zeros((64, b), jnp.uint8),
        jnp.zeros((b,), jnp.bool_),
    )
    batched = limbs.measure_field_ops(
        model.batch_verify_impl,
        jnp.zeros((32, b), jnp.uint8),
        jnp.zeros((b,), jnp.uint8),
        jnp.zeros((32, b), jnp.uint8),
        jnp.zeros((b,), jnp.uint8),
        jnp.zeros((32, 1), jnp.uint8),
        jnp.zeros((64, b), jnp.uint8),
        jnp.zeros((33, b), jnp.uint8),
        jnp.zeros((b,), jnp.bool_),
    )
    assert batched.muls / strict.muls <= 0.50
    assert batched.m_equiv / strict.m_equiv <= 0.50


# --- the multi-batch coalescing seam (api/deps.py) --------------------------


class _SpyMixin:
    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.launches = 0

    def verify_batch(self, msgs, sigs, keys):
        self.launches += 1
        return super().verify_batch(msgs, sigs, keys)


class _SpyRandomized(_SpyMixin, Ed25519RandomizedBatchVerifier):
    pass


class _SpyStrict(_SpyMixin, Ed25519BatchVerifier):
    pass


class _Facade(Verifier):
    """Minimal api.deps facade over an inner signature verifier — the shape
    of CryptoApp: implements only the per-group batch call and wires the
    delegate, leaving multi-batch to the Verifier ABC default."""

    def __init__(self, inner):
        self._inner = inner
        self.multi_batch_delegate = inner
        self.batch_verify_enabled = inner.batch_verify_enabled

    def verify_proposal(self, proposal):
        raise NotImplementedError

    def verify_request(self, raw):
        raise NotImplementedError

    def verify_consenter_sig(self, signature, proposal):
        return self._inner.verify_consenter_sig(signature, proposal)

    def verify_signature(self, signature):
        raise NotImplementedError

    def verification_sequence(self):
        return 0

    def requests_from_proposal(self, proposal):
        return []

    def verify_consenter_sigs_batch(self, signatures, proposal):
        return self._inner.verify_consenter_sigs_batch(signatures, proposal)


def _quorum_groups(n_groups=3):
    from consensus_tpu.models import Ed25519Signer
    from consensus_tpu.testing.crypto_app import SigOnlyVerifier

    signers = {
        i: Ed25519Signer(
            i, hashlib.sha512(b"ctpu/test-mb/%d" % i).digest()[:32]
        )
        for i in (1, 2, 3, 4)
    }
    keys = {i: s.public_bytes for i, s in signers.items()}
    groups = []
    for g in range(n_groups):
        proposal = Proposal(payload=b"blk-%d" % g, metadata=b"md")
        cert = [signers[i].sign_proposal(proposal, b"aux") for i in (1, 2, 3)]
        groups.append((proposal, cert))
    return keys, groups, SigOnlyVerifier


def test_multi_batch_default_coalesces_to_one_launch():
    """With batch_verify_mode's engine behind the verifier, the Verifier
    ABC's multi-batch default forwards the whole group list to the delegate
    — ONE engine launch for 3 quorum certs.  A strict engine keeps the
    bit-exact per-group loop."""
    keys, groups, SigOnlyVerifier = _quorum_groups()

    spy = _SpyRandomized(min_device_batch=10**9)
    facade = _Facade(SigOnlyVerifier(keys, engine=spy))
    out = facade.verify_consenter_sigs_multi_batch(groups)
    assert spy.launches == 1
    assert out == [[b"aux"] * 3] * 3

    strict_spy = _SpyStrict(min_device_batch=10**9)
    strict_facade = _Facade(SigOnlyVerifier(keys, engine=strict_spy))
    assert strict_facade.verify_consenter_sigs_multi_batch(groups) == out
    assert strict_spy.launches == 3


def test_multi_batch_coalesced_rejections_localized():
    keys, groups, SigOnlyVerifier = _quorum_groups()
    # Corrupt one signature inside the middle group.
    bad = groups[1][1][2]
    groups[1][1][2] = type(bad)(id=bad.id, value=_forge(bad.value), msg=bad.msg)
    spy = _SpyRandomized(min_device_batch=10**9)
    facade = _Facade(SigOnlyVerifier(keys, engine=spy))
    out = facade.verify_consenter_sigs_multi_batch(groups)
    assert spy.launches == 1
    assert out[0] == [b"aux"] * 3 and out[2] == [b"aux"] * 3
    assert out[1] == [b"aux", b"aux", None]


def test_engine_for_config_and_mixin_contradiction():
    from consensus_tpu.config import Configuration
    from consensus_tpu.models.verifier import (
        Ed25519VerifierMixin,
        engine_for_config,
    )

    assert Configuration().batch_verify_mode is False
    strict = engine_for_config(Configuration())
    assert type(strict) is Ed25519BatchVerifier
    randomized = engine_for_config(Configuration(batch_verify_mode=True))
    assert isinstance(randomized, Ed25519RandomizedBatchVerifier)

    from consensus_tpu.testing.crypto_app import SigOnlyVerifier

    v = SigOnlyVerifier({}, engine=randomized)
    assert v.batch_verify_enabled
    assert not SigOnlyVerifier({}, engine=strict).batch_verify_enabled
    assert SigOnlyVerifier({}, batch_verify_mode=True).batch_verify_enabled
    with pytest.raises(ValueError, match="randomized"):
        SigOnlyVerifier({}, engine=strict, batch_verify_mode=True)


# --- cluster integration: coalesced launches stay single-launch -------------


def test_cluster_verify_launch_histogram_with_batch_mode():
    """A live cluster running batch_verify_mode: the cross-slot verify
    instrumentation still records exactly one histogram observation per
    launch, decisions commit, and every decided quorum re-verifies
    strictly (randomized accept == strict accept on honest traffic)."""
    from consensus_tpu.metrics import InMemoryProvider, Metrics
    from consensus_tpu.models import Ed25519Signer
    from consensus_tpu.models.verifier import commit_message
    from consensus_tpu.testing import Cluster, make_request
    from consensus_tpu.testing.crypto_app import CryptoApp, SigOnlyVerifier

    provider = InMemoryProvider()
    cluster = Cluster(4, seed=913)
    engine = Ed25519RandomizedBatchVerifier(min_device_batch=10**9)
    signers = {
        i: Ed25519Signer(
            i, hashlib.sha512(b"ctpu/test-cl/%d" % i).digest()[:32]
        )
        for i in cluster.nodes
    }
    keys = {i: s.public_bytes for i, s in signers.items()}
    for node_id, node in cluster.nodes.items():
        node.app = CryptoApp(
            node_id, cluster, signers[node_id],
            SigOnlyVerifier(keys, engine=engine),
        )
    assert cluster.nodes[2].app.batch_verify_enabled
    cluster.nodes[2].metrics = Metrics(provider)
    cluster.start()
    for i in range(3):
        cluster.submit_to_all(make_request("bv", i))
        assert cluster.run_until_ledger(i + 1, max_time=600.0)
    cluster.assert_ledgers_consistent()

    launches = provider.value("consensus_verify_launches")
    batches = provider.observations("consensus_cross_slot_verify_batch")
    assert launches >= 3  # at least one coalesced launch per decision
    assert len(batches) == launches  # exactly one observation per launch
    assert all(b >= 1 for b in batches)

    checker = _host_strict()
    for decision in cluster.nodes[2].app.ledger:
        assert len(decision.signatures) >= 3
        ok = checker.verify_batch(
            [commit_message(decision.proposal, s.msg) for s in decision.signatures],
            [s.value for s in decision.signatures],
            [keys[s.id] for s in decision.signatures],
        )
        assert ok.all()


# --- chaos parity gate (strict vs randomized engine, same schedule) ---------


def test_chaos_byzantine_mutation_parity_strict_vs_batch():
    """One tier-1 byzantine-mutation schedule run twice — strict engine vs
    randomized batch engine — must produce identical ledgers AND identical
    event logs: flipping batch_verify_mode may never change a verdict, so
    the whole deterministic execution replays byte-for-byte."""
    from consensus_tpu.testing.chaos import ChaosAction, ChaosEngine, ChaosSchedule

    schedule = ChaosSchedule(
        seed=4117,
        n=4,
        actions=(
            ChaosAction(at=35.0, kind="byzantine", args={"node": 4, "rate": 0.6}),
            ChaosAction(at=70.0, kind="loss", args={"a": 2, "b": 3, "p": 0.2}),
            ChaosAction(at=95.0, kind="byzantine_stop", args={}),
            ChaosAction(at=110.0, kind="heal", args={}),
        ),
    )
    strict = ChaosEngine(schedule, crypto="ed25519").run()
    assert strict.ok, strict.violation
    batch = ChaosEngine(schedule, crypto="ed25519-batch").run()
    assert batch.ok, batch.violation
    assert strict.ledgers == batch.ledgers
    assert strict.event_log == batch.event_log
    assert max(len(d) for d in strict.ledgers.values()) >= 1


# --- bench.py structured skip path ------------------------------------------


def test_bench_skip_record_carries_batch_verify_column():
    """With the device unreachable (JAX_PLATFORMS=tpu on a TPU-less host,
    zero retry window) bench.py must exit 0 and emit the machine-readable
    skip record INCLUDING the batch_verify column's own skip + trail."""
    env = dict(os.environ, JAX_PLATFORMS="tpu", CTPU_BENCH_RETRY_WINDOW="0")
    proc = subprocess.run(
        [sys.executable, "bench.py"],
        cwd=_REPO, env=env, capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    line = [l for l in proc.stdout.splitlines() if l.startswith("{")][-1]
    record = json.loads(line)
    assert record["metric"] == "ed25519_verify_throughput"
    assert record["skipped"] == "device-unavailable"
    assert record["batch_verify"]["skipped"] == "device-unavailable"


def test_wallclock_lint_covers_batch_verify_modules():
    """scripts/check_no_wallclock.py walks the trees the randomized
    verifier lives in — the determinism rule (transcript-derived z, no
    wallclock) is enforced by lint, not convention."""
    script = os.path.join(_REPO, "scripts", "check_no_wallclock.py")
    proc = subprocess.run(
        [
            sys.executable,
            script,
            os.path.join(_REPO, "consensus_tpu", "models"),
            os.path.join(_REPO, "consensus_tpu", "ops"),
        ],
        capture_output=True, text=True,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
