"""Half-aggregated Ed25519 quorum certificates (models/aggregate.py): the
aggregate/verify unit surface, the adversarial rejection-class parity
matrix (device kernel and host big-int twin agreeing with STRICT
verification on every class), bisection localization, and the
one-MSM-launch-per-cert accounting gate.

Everything here runs on the in-repo reference implementation
(``ref_sign`` / ``ref_public_key``) so the file needs neither the
``cryptography`` package nor a TPU — the "device" path is the
shared-doubling kernel jitted on whatever backend JAX has.
"""

import numpy as np
import pytest

from consensus_tpu.models.aggregate import HalfAggregator, halfagg_coefficients
from consensus_tpu.models.ed25519 import (
    Ed25519BatchVerifier,
    L,
    _ref_decompress,
    ref_public_key,
    ref_sign,
)
from consensus_tpu.obs.kernels import KERNELS
from consensus_tpu.ops import field25519 as fe

N = 4  # quorum-sized; padded device batch stays tiny on the CPU backend


def make_quorum(n=N, tag=b"halfagg"):
    msgs, sigs, keys = [], [], []
    for i in range(n):
        seed = bytes([i + 1]) * 32
        m = b"ctpu/%s/%d" % (tag, i)
        msgs.append(m)
        sigs.append(ref_sign(seed, m))
        keys.append(ref_public_key(seed))
    return msgs, sigs, keys


def strict_verdicts(msgs, sigs, keys):
    return np.asarray(
        Ed25519BatchVerifier(min_device_batch=10**9).verify_batch(
            msgs, sigs, keys
        )
    )


DEVICE = HalfAggregator(min_device_batch=1)
HOST = HalfAggregator(min_device_batch=10**9)


def aggregate_parts(msgs, sigs, keys):
    agg, bad = HOST.aggregate(msgs, sigs, keys)
    assert agg is not None and bad == ()
    rs, s_agg = agg
    return list(rs), s_agg


def test_aggregate_verifies_on_both_backends():
    msgs, sigs, keys = make_quorum()
    rs, s_agg = aggregate_parts(msgs, sigs, keys)
    assert rs == [s[:32] for s in sigs]
    assert HOST.verify(msgs, rs, s_agg, keys)
    assert DEVICE.verify(msgs, rs, s_agg, keys)


def test_coefficients_deterministic_and_committing():
    msgs, sigs, keys = make_quorum()
    rs = [s[:32] for s in sigs]
    zs = halfagg_coefficients(msgs, rs, keys)
    assert zs == halfagg_coefficients(msgs, rs, keys)  # no ambient RNG
    assert zs[0] == 1 and all(z != 0 for z in zs)
    # The transcript commits to every (message, R, key) triple: perturbing
    # any one changes the downstream coefficients.
    other = halfagg_coefficients([b"x"] + msgs[1:], rs, keys)
    assert other[1:] != zs[1:]


# --- the adversarial rejection-class matrix --------------------------------
#
# Each case mutates one honest cert dimension; BOTH backends must reject.

def _tamper_s_agg(msgs, rs, s_agg, keys):
    bad = bytearray(s_agg)
    bad[0] ^= 0x01
    return msgs, rs, bytes(bad), keys


def _s_agg_above_l(msgs, rs, s_agg, keys):
    return msgs, rs, L.to_bytes(32, "little"), keys


def _s_agg_bad_length(msgs, rs, s_agg, keys):
    return msgs, rs, s_agg[:31], keys


def _forge_component_r(msgs, rs, s_agg, keys):
    bad = bytearray(rs[1])
    bad[3] ^= 0xFF
    return msgs, [rs[0], bytes(bad)] + rs[2:], s_agg, keys


def _wrong_key(msgs, rs, s_agg, keys):
    return msgs, rs, s_agg, [keys[1], keys[0]] + keys[2:]


def _wrong_message(msgs, rs, s_agg, keys):
    return [b"swapped"] + msgs[1:], rs, s_agg, keys


def _non_decodable_r_high_y(msgs, rs, s_agg, keys):
    # y-coordinate >= p: rejected by the canonical-encoding precheck.
    return msgs, [b"\xff" * 32] + rs[1:], s_agg, keys


def _non_decodable_r_off_curve(msgs, rs, s_agg, keys):
    # Smallest y < p whose decompression has no square root: exercises the
    # kernel's valid-mask (identity-masked inside the MSM) rather than the
    # host precheck.
    y = next(
        c for c in range(2, 64)
        if _ref_decompress(c.to_bytes(32, "little")) is None
    )
    assert (y & ((1 << 255) - 1)) < fe.P
    return msgs, [y.to_bytes(32, "little")] + rs[1:], s_agg, keys


REJECTION_CLASSES = {
    "tampered_s_agg": _tamper_s_agg,
    "s_agg_above_L": _s_agg_above_l,
    "s_agg_bad_length": _s_agg_bad_length,
    "forged_component_R": _forge_component_r,
    "wrong_key": _wrong_key,
    "wrong_message": _wrong_message,
    "non_decodable_R_high_y": _non_decodable_r_high_y,
    "non_decodable_R_off_curve": _non_decodable_r_off_curve,
}


@pytest.mark.parametrize("cls", sorted(REJECTION_CLASSES))
def test_rejection_class_parity_device_and_host(cls):
    msgs, sigs, keys = make_quorum()
    rs, s_agg = aggregate_parts(msgs, sigs, keys)
    m2, r2, s2, k2 = REJECTION_CLASSES[cls](msgs, list(rs), s_agg, list(keys))
    host = HOST.verify(m2, r2, s2, k2)
    device = DEVICE.verify(m2, r2, s2, k2)
    assert host is False and device is False, (
        f"{cls}: host={host} device={device} — backends must both reject"
    )
    # Control: the honest cert still passes on both backends.
    assert HOST.verify(msgs, rs, s_agg, keys)
    assert DEVICE.verify(msgs, rs, s_agg, keys)


def test_empty_cert_rejected():
    assert HOST.verify([], [], b"\x00" * 32, []) is False
    assert DEVICE.verify([], [], b"\x00" * 32, []) is False


# --- aggregation fallback: strict parity of the localized bad set ----------


@pytest.mark.parametrize("bad_indices", [(1,), (0, 3), (2,)])
def test_bisection_localizes_exactly_the_strict_invalid_set(bad_indices):
    msgs, sigs, keys = make_quorum(8)
    for i in bad_indices:
        flipped = bytearray(sigs[i])
        flipped[7] ^= 0xFF
        sigs[i] = bytes(flipped)
    agg = HalfAggregator(min_device_batch=10**9)
    cert, bad = agg.aggregate(msgs, sigs, keys)
    assert cert is None
    assert agg.fallback_bisections == 1
    strict = strict_verdicts(msgs, sigs, keys)
    assert set(bad) == {i for i in range(8) if not strict[i]} == set(bad_indices)


def test_component_scalar_above_l_localized_like_strict():
    msgs, sigs, keys = make_quorum(4)
    sigs[2] = sigs[2][:32] + L.to_bytes(32, "little")  # S >= L: non-canonical
    agg = HalfAggregator(min_device_batch=10**9)
    cert, bad = agg.aggregate(msgs, sigs, keys)
    assert cert is None
    strict = strict_verdicts(msgs, sigs, keys)
    assert set(bad) == {i for i in range(4) if not strict[i]} == {2}


def test_aggregate_counts_checks_and_rejects_length_mismatch():
    msgs, sigs, keys = make_quorum()
    agg = HalfAggregator(min_device_batch=10**9)
    before = agg.aggregate_checks
    assert agg.aggregate(msgs, sigs, keys)[0] is not None
    assert agg.aggregate_checks == before + 1  # ONE self-check per aggregate
    with pytest.raises(ValueError):
        agg.aggregate(msgs, sigs[:-1], keys)
    with pytest.raises(ValueError):
        agg.verify(msgs, [s[:32] for s in sigs][:-1], b"\x00" * 32, keys)


# --- launch accounting: exactly ONE MSM launch per aggregate cert ----------


def _halfagg_launches() -> int:
    return KERNELS.snapshot().get("ed25519.halfagg_verify", {}).get(
        "launches", 0
    )


def test_one_msm_launch_per_cert_verify():
    msgs, sigs, keys = make_quorum()
    rs, s_agg = aggregate_parts(msgs, sigs, keys)
    DEVICE.verify(msgs, rs, s_agg, keys)  # warmup: compile outside the count
    before = _halfagg_launches()
    for _ in range(5):
        assert DEVICE.verify(msgs, rs, s_agg, keys)
    assert _halfagg_launches() - before == 5, (
        "an aggregate cert verify must cost exactly one MSM launch"
    )
    # The host twin never touches the kernel.
    before = _halfagg_launches()
    assert HOST.verify(msgs, rs, s_agg, keys)
    assert _halfagg_launches() == before


def test_engine_knobs_inherited():
    engine = Ed25519BatchVerifier(min_device_batch=10**9)
    agg = HalfAggregator(engine=engine)
    assert agg._min_device_batch == 10**9  # rides the host twin like the engine


# --- bench.py cert_verify family: structured skip path ----------------------


@pytest.mark.slow  # the skip-path subprocess still pays the cpu-probe compile
def test_bench_cert_verify_skip_record_carries_stale_trail():
    """``bench.py cert_verify`` with the device unreachable must exit 0 and
    emit the structured skip record for the cert_verify family — metric
    name, skip reason, the stale last-good trail, and the cpu-probe kernel
    accounting — so the fleet dashboard keeps a column even when the TPU
    tunnel is wedged."""
    import json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="tpu", CTPU_BENCH_RETRY_WINDOW="0")
    proc = subprocess.run(
        [sys.executable, "bench.py", "cert_verify"],
        cwd=repo, env=env, capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    line = [l for l in proc.stdout.splitlines() if l.startswith("{")][-1]
    record = json.loads(line)
    assert record["metric"] == "cert_verify_throughput"
    assert record["skipped"] == "device-unavailable"
    assert record["last_good"]["stale"] is True
    assert record["last_good"]["unit"] == "sigs/sec"
    assert record["kernels"]["source"] == "cpu-probe"
