"""Randomized fault-injection soak: hours of virtual time with crashes,
restarts, partitions, and loss — safety (no fork, ever) checked after every
event, liveness checked once the cluster heals.

Parity model: the reference's randomized/long-running scenarios in
test/basic_test.go, compressed into deterministic virtual time.

The UNIFORM-fault families run on the chaos engine
(consensus_tpu/testing/chaos.py): a seed-derived ChaosSchedule executed
with the invariant monitor judging EVERY delivery (prefix agreement,
quorum certificates, durable-before-visible) instead of spot checks
between steps, plus the byzantine-network primitives (duplicate / reorder
/ stale replay) the old inline loop never exercised.  A failure prints a
paste-able reproducer; shrink it with ``consensus_tpu.testing.shrink``.
The message-TARGETED and byzantine-MUTATION families below keep their
inline loops: their pinned regression seeds (216, 1234, 1268, ...) replay
exact rng-derived corruption streams that only those loops produce.
"""

import random

import pytest

from consensus_tpu.testing import Cluster, make_request
from consensus_tpu.testing.chaos import ChaosEngine, ChaosSchedule, format_repro
from consensus_tpu.testing.invariants import is_known_unresolvable_split

FAST = {
    "request_forward_timeout": 1.0,
    "request_complain_timeout": 4.0,
    "request_auto_remove_timeout": 120.0,
    "view_change_resend_interval": 2.0,
    "view_change_timeout": 10.0,
    "leader_heartbeat_timeout": 20.0,
}


def _run_engine_soak(seed, *, n=4, steps=25, durability_window=0.0,
                     min_height=5):
    schedule = ChaosSchedule.generate(
        seed, n=n, steps=steps, durability_window=durability_window
    )
    result = ChaosEngine(schedule).run()
    assert result.ok, (
        f"{result.violation}\n\nreproduce with:\n{format_repro(result)}"
    )
    # Sanity: a meaningful amount of work actually got ordered during chaos.
    floor = max(len(digests) for digests in result.ledgers.values())
    assert floor >= min_height, f"only {floor} blocks ordered across the soak"
    return result


@pytest.mark.parametrize("seed", [20260728, 8, 17, 33])
def test_randomized_fault_soak(seed):
    _run_engine_soak(seed)


#: Same engine under GROUP-COMMIT durability semantics: every WAL append
#: becomes durable (and its deferred protocol send fires) only after a
#: window, and crashes LOSE unflushed records.  This is the regime that
#: hid the late-flush liveness wedge (view.py::maybe_send_prepare) — the
#: window (50 ms sim) is sized well above the sim network delays so
#: late-flush orderings actually occur.  min_height=4: losing unflushed
#: records on crash legitimately costs throughput on partition-heavy
#: schedules (seed 303 orders exactly 4).
@pytest.mark.parametrize("seed", [20260728, 8, 17, 33] + list(range(300, 316)))
def test_randomized_fault_soak_group_commit(seed):
    _run_engine_soak(seed, durability_window=0.05, min_height=4)


#: Wide sweep, gated unconditionally (VERDICT r3 #6): at ~0.1 s/run the
#: whole file stays fast, so the load-bearing "many seeds, zero failures"
#: claim is reproducible by plain ``pytest tests/test_soak.py`` — not
#: archaeology in commit messages.
@pytest.mark.parametrize("seed", list(range(100, 136)))
def test_randomized_fault_soak_sweep(seed):
    _run_engine_soak(seed)


def test_randomized_fault_soak_n7_two_faults():
    # f=2 cluster: the generator keeps up to two replicas simultaneously
    # down (crashed or armed-to-crash) while the schedule churns the live
    # set's membership.
    _run_engine_soak(777, n=7, steps=20)


def test_engine_soak_replayable():
    """The determinism contract the repro/shrink workflow rests on: the
    same schedule yields a BYTE-identical event log and identical final
    ledgers on every execution."""
    schedule = ChaosSchedule.generate(20260728, steps=25)
    r1 = ChaosEngine(schedule).run()
    r2 = ChaosEngine(schedule).run()
    assert r1.event_log == r2.event_log
    assert r1.ledgers == r2.ledgers


#: Kept as a module-level alias: the targeted/byzantine families below and
#: external callers referenced the helper here before it moved to
#: consensus_tpu/testing/invariants.py (the chaos engine needs it too).
_is_known_unresolvable_split = is_known_unresolvable_split


def _run_targeted_chaos(seed, n, durability_window=0.0,
                        leader_rotation=False):
    """Message-type-targeted chaos: random drop rules per wire kind (up to
    total loss of e.g. every NewView or every Commit), plus crashes and
    partitions — a sharper fault model than uniform loss, and the one that
    exposed the assist-flagged recovery-rebroadcast bug.

    ``leader_rotation=True`` runs the same storms with rotation on
    (decisions_per_leader=2): the rotation/blacklist machinery —
    prev-commit-signature carries, blacklist computation and follower
    validation, per-leader decision counting — under identical faults."""
    from consensus_tpu.wire import (
        Commit,
        HeartBeat,
        NewView,
        PrePrepare,
        Prepare,
        StateTransferRequest,
        StateTransferResponse,
        ViewChange,
    )

    kinds = [Prepare, Commit, PrePrepare, HeartBeat, NewView, ViewChange,
             StateTransferRequest, StateTransferResponse]
    rng = random.Random(seed)
    tweaks = dict(FAST, decisions_per_leader=2) if leader_rotation else FAST
    cluster = Cluster(
        n, seed=seed ^ (0x707A if leader_rotation else 0x5A5A),
        config_tweaks=tweaks, leader_rotation=leader_rotation,
        durability_window=durability_window,
    )
    cluster.start()
    submitted = 0
    crashed: set[int] = set()
    drop_rules: dict = {}

    def submit_some(k):
        nonlocal submitted
        for _ in range(k):
            cluster.submit_to_all(make_request("chaos", submitted))
            submitted += 1

    def mutate(sender, target, msg):
        p = drop_rules.get(type(msg))
        if p and rng.random() < p:
            return None
        return msg

    cluster.network.mutate_send = mutate
    submit_some(4)
    assert cluster.run_until_ledger(1, max_time=300.0)
    f = (n - 1) // 3
    for _ in range(30):
        roll = rng.random()
        if roll < 0.2 and len(crashed) < f:
            victim = rng.choice([i for i in cluster.nodes if i not in crashed])
            cluster.nodes[victim].crash()
            crashed.add(victim)
        elif roll < 0.4 and crashed:
            cluster.nodes[crashed.pop()].restart()
        elif roll < 0.6:
            drop_rules[rng.choice(kinds)] = rng.choice([0.3, 0.7, 1.0])
        elif roll < 0.75:
            drop_rules.clear()
        elif roll < 0.85 and not crashed:
            cluster.network.partition([rng.choice(list(cluster.nodes))])
        else:
            cluster.network.heal()
        submit_some(rng.randrange(1, 4))
        cluster.scheduler.advance(rng.uniform(5.0, 40.0))
        # SAFETY under every fault mix: no fork, no double delivery.
        cluster.assert_ledgers_consistent()
        for node in cluster.nodes.values():
            digests = [d.proposal.digest() for d in node.app.ledger]
            assert len(digests) == len(set(digests)), (
                f"replica {node.node_id} delivered a proposal twice"
            )
    #

    drop_rules.clear()
    cluster.network.heal()
    cluster.network.mutate_send = None
    for nid in list(crashed):
        cluster.nodes[nid].restart()
    cluster.scheduler.advance(60.0)
    floor = max(len(nd.app.ledger) for nd in cluster.nodes.values())
    submit_some(5)
    progressed = cluster.scheduler.run_until(
        lambda: sum(
            1 for nd in cluster.nodes.values()
            if len(nd.app.ledger) >= floor + 1
        ) >= n - f,
        max_time=1200.0,
    )
    if not progressed:
        # The one excuse: a prepared-split stall that is unresolvable BY
        # DESIGN (stalling is the safe outcome; see the helper).  Anything
        # else is a genuine liveness bug.
        assert _is_known_unresolvable_split(cluster, n), (
            "cluster failed to progress after the chaos healed"
        )
    cluster.assert_ledgers_consistent()


# Seed 1234 is the diverged-next-views wedge: post-heal, three replicas
# stuck collecting for views 19/22/23 (no two alike) with the fourth
# settled — convergence requires the laggard-help broadcast to RE-FIRE
# on vote resends (reference sendRecv semantics); a once-per-(view,
# sender) guard wedged it forever (round-5 hunt, 1600+ runs).
# Seed 1144: the diverged-backoff livelock — a behind replica whose
# view-change timeout is perpetually reset by vote-driven joins never
# syncs, its ViewData is rejected each round, and CheckInFlight stays
# unsatisfiable; fixed by the f+1-far-ahead-senders sync trigger.
@pytest.mark.parametrize("seed,n", [(1, 4), (2, 7), (3, 4), (5, 7), (1234, 4), (1144, 4), (1427, 4)])
def test_targeted_message_chaos(seed, n):
    _run_targeted_chaos(seed, n)


@pytest.mark.parametrize("seed", list(range(200, 220)))
@pytest.mark.parametrize("n", [4, 7])
def test_targeted_message_chaos_sweep(seed, n):
    _run_targeted_chaos(seed, n)


#: Message-kind-targeted chaos under group-commit durability (see
#: test_randomized_fault_soak_group_commit): drop rules x deferred
#: flushes x crashes that lose unflushed records.
# Seed 1268: mixed-view crash restores left split in-flight attestations
# (P@v10 prepared on two replicas, later views' unprepared proposals on
# the others) — unsatisfiable forever until check_in_flight stopped
# counting unprepared attestations as condition-A arguments.
# Seed 3428: a crash restored two replicas into a view whose SavedNewView
# record had been truncated away by the proposal append — they idled in
# view 1 holding (view 8) proposal records; fixed by booting from the
# in-flight WAL tail's view.
@pytest.mark.parametrize("seed,n", [(1, 4), (2, 7), (400, 4), (401, 7),
                                    (402, 4), (403, 7), (404, 4), (405, 7),
                                    (1268, 4), (3428, 4), (4305, 4)])
def test_targeted_message_chaos_group_commit(seed, n):
    _run_targeted_chaos(seed, n, durability_window=0.05)


def _run_byzantine_mutation_chaos(seed, n, durability_window=0.0,
                                  leader_rotation=False):
    """Message-CORRUPTION chaos (round 5): a byzantine network rewrites
    random fields of in-flight messages — wrong views/seqs/digests, cross-
    signer signature swaps, forged signature bytes, garbled SignedViewData,
    truncated/duplicated NewView sets, lying heartbeats and state-transfer
    claims — at rates up to total corruption of a message kind, mixed with
    crashes and partitions.  Validation must shed ALL of it: an unhandled
    exception in any replica, a ledger fork, or a double delivery is a bug.
    Progress is asserted only after the corruption stops (corrupting many
    senders' messages at once exceeds the f-byzantine-replica model, so
    only safety — never liveness — is required while it runs)."""
    import dataclasses

    from consensus_tpu.wire import (
        Commit,
        HeartBeat,
        HeartBeatResponse,
        NewView,
        PrePrepare,
        Prepare,
        SignedViewData,
        StateTransferResponse,
        ViewChange,
    )

    rng = random.Random(seed)

    def garble_bytes(b):
        if not b:
            return b"\xff"
        i = rng.randrange(len(b))
        return b[:i] + bytes([b[i] ^ 0xFF]) + b[i + 1:]

    def corrupt(msg):
        roll = rng.random()
        if isinstance(msg, Prepare):
            if roll < 0.4:
                return dataclasses.replace(msg, digest="corrupt-" + msg.digest[:8])
            if roll < 0.7:
                return dataclasses.replace(msg, view=msg.view + rng.choice([1, 2, 3]))
            return dataclasses.replace(msg, seq=msg.seq + rng.choice([-1, 1, 5]))
        if isinstance(msg, Commit):
            if roll < 0.3:
                return dataclasses.replace(msg, digest="corrupt-" + msg.digest[:8])
            if roll < 0.5:
                # Claim a different signer WITHOUT its key: the signature
                # bytes stay the original signer's, so verification against
                # the claimed id must fail.  (Minting another replica's
                # VALID signature — trivial under this harness's toy crypto
                # — would model n byzantine replicas, beyond the f-replica
                # threat model: real adversaries cannot forge signatures.)
                other = rng.randrange(1, n + 1)
                return dataclasses.replace(
                    msg, signature=dataclasses.replace(msg.signature, id=other)
                )
            if roll < 0.7:
                return dataclasses.replace(
                    msg,
                    signature=dataclasses.replace(
                        msg.signature, value=b"forged-bytes"
                    ),
                )
            return dataclasses.replace(msg, view=msg.view + rng.choice([1, 2]))
        if isinstance(msg, PrePrepare):
            # ROTATION runs use an extended layout with a prev-commit-
            # signature attack; non-rotation runs keep the ORIGINAL branch
            # probabilities so the pinned regression seeds (216, 171/306/
            # 396, 1109) replay the exact corruption streams they were
            # pinned under.
            if not leader_rotation:
                if roll < 0.4:
                    return dataclasses.replace(
                        msg,
                        proposal=dataclasses.replace(
                            msg.proposal, payload=msg.proposal.payload + b"EVIL"
                        ),
                    )
                if roll < 0.7:
                    return dataclasses.replace(
                        msg,
                        proposal=dataclasses.replace(
                            msg.proposal,
                            metadata=garble_bytes(msg.proposal.metadata),
                        ),
                    )
                return dataclasses.replace(
                    msg, view=msg.view + rng.choice([1, 3])
                )
            if roll < 0.3:
                return dataclasses.replace(
                    msg,
                    proposal=dataclasses.replace(
                        msg.proposal, payload=msg.proposal.payload + b"EVIL"
                    ),
                )
            if roll < 0.5:
                return dataclasses.replace(
                    msg,
                    proposal=dataclasses.replace(
                        msg.proposal, metadata=garble_bytes(msg.proposal.metadata)
                    ),
                )
            if roll < 0.8 and msg.prev_commit_signatures:
                # Attack the blacklist path: tamper the carried previous-
                # commit quorum (drop one, duplicate one, or forge bytes).
                sigs = list(msg.prev_commit_signatures)
                sub = rng.random()
                if sub < 0.4:
                    sigs.pop(rng.randrange(len(sigs)))
                elif sub < 0.7:
                    sigs.append(rng.choice(sigs))
                else:
                    i = rng.randrange(len(sigs))
                    sigs[i] = dataclasses.replace(sigs[i], value=b"forged")
                return dataclasses.replace(
                    msg, prev_commit_signatures=tuple(sigs)
                )
            return dataclasses.replace(msg, view=msg.view + rng.choice([1, 3]))
        if isinstance(msg, ViewChange):
            return dataclasses.replace(
                msg, next_view=max(0, msg.next_view + rng.choice([-2, -1, 1, 2, 3]))
            )
        if isinstance(msg, SignedViewData):
            if roll < 0.4:
                return dataclasses.replace(
                    msg, raw_view_data=garble_bytes(msg.raw_view_data)
                )
            if roll < 0.7:
                return dataclasses.replace(msg, signer=rng.randrange(1, n + 1))
            return dataclasses.replace(msg, signature=b"forged")
        if isinstance(msg, NewView):
            svds = list(msg.signed_view_data)
            if not svds:
                return msg
            if roll < 0.4 and len(svds) > 1:
                svds.pop(rng.randrange(len(svds)))  # truncate the quorum
            elif roll < 0.7:
                svds.append(rng.choice(svds))       # duplicate an entry
            else:
                i = rng.randrange(len(svds))
                svds[i] = dataclasses.replace(
                    svds[i], raw_view_data=garble_bytes(svds[i].raw_view_data)
                )
            return dataclasses.replace(msg, signed_view_data=tuple(svds))
        if isinstance(msg, HeartBeat):
            return dataclasses.replace(
                msg, view=msg.view + rng.choice([-1, 1, 4]),
                seq=max(0, msg.seq + rng.choice([-1, 1, 7])),
            )
        if isinstance(msg, HeartBeatResponse):
            return dataclasses.replace(msg, view=msg.view + rng.choice([1, 5]))
        if isinstance(msg, StateTransferResponse):
            return dataclasses.replace(
                msg,
                view_num=max(0, msg.view_num + rng.choice([-1, 1, 3])),
                sequence=max(0, msg.sequence + rng.choice([-1, 1, 2])),
            )
        return msg

    kinds = [Prepare, Commit, PrePrepare, HeartBeat, HeartBeatResponse,
             NewView, ViewChange, SignedViewData, StateTransferResponse]
    tweaks = dict(FAST, decisions_per_leader=2) if leader_rotation else FAST
    cluster = Cluster(
        n, seed=seed ^ 0xC0FF, config_tweaks=tweaks,
        leader_rotation=leader_rotation, durability_window=durability_window,
    )
    cluster.start()
    submitted = 0
    crashed: set[int] = set()
    corrupt_rules: dict = {}

    def submit_some(k):
        nonlocal submitted
        for _ in range(k):
            cluster.submit_to_all(make_request("byz", submitted))
            submitted += 1

    def mutate(sender, target, msg):
        p = corrupt_rules.get(type(msg))
        if p and rng.random() < p:
            return corrupt(msg)
        return msg

    cluster.network.mutate_send = mutate
    submit_some(4)
    assert cluster.run_until_ledger(1, max_time=300.0)
    f = (n - 1) // 3
    for _ in range(30):
        roll = rng.random()
        if roll < 0.15 and len(crashed) < f:
            victim = rng.choice([i for i in cluster.nodes if i not in crashed])
            cluster.nodes[victim].crash()
            crashed.add(victim)
        elif roll < 0.3 and crashed:
            cluster.nodes[crashed.pop()].restart()
        elif roll < 0.6:
            corrupt_rules[rng.choice(kinds)] = rng.choice([0.3, 0.7, 1.0])
        elif roll < 0.75:
            corrupt_rules.clear()
        elif roll < 0.85 and not crashed:
            cluster.network.partition([rng.choice(list(cluster.nodes))])
        else:
            cluster.network.heal()
        submit_some(rng.randrange(1, 4))
        cluster.scheduler.advance(rng.uniform(5.0, 40.0))
        # SAFETY under arbitrary corruption: no fork, no double delivery.
        cluster.assert_ledgers_consistent()
        for node in cluster.nodes.values():
            digests = [d.proposal.digest() for d in node.app.ledger]
            assert len(digests) == len(set(digests)), (
                f"replica {node.node_id} delivered a proposal twice"
            )

    corrupt_rules.clear()
    cluster.network.heal()
    cluster.network.mutate_send = None
    for nid in list(crashed):
        cluster.nodes[nid].restart()
    cluster.scheduler.advance(60.0)
    floor = max(len(nd.app.ledger) for nd in cluster.nodes.values())
    submit_some(5)
    progressed = cluster.scheduler.run_until(
        lambda: sum(
            1 for nd in cluster.nodes.values()
            if len(nd.app.ledger) >= floor + 1
        ) >= n - f,
        max_time=1200.0,
    )
    if not progressed:
        # The one excuse: a prepared-split stall that is unresolvable BY
        # DESIGN (stalling is the safe outcome; see the helper).  Anything
        # else is a genuine liveness bug.
        assert _is_known_unresolvable_split(cluster, n), (
            "cluster failed to progress after corruption stopped"
        )
    cluster.assert_ledgers_consistent()


# Seed 216: a long corruption storm accumulated an uncapped timeout
# backoff (150+ = a 1,500 s recovery stall after heal) via the stale
# _start_change_time re-arm runaway; fixed by restarting the timeout
# round at each firing and capping the factor.
@pytest.mark.parametrize("seed,n", [(11, 4), (12, 7), (13, 4), (14, 4), (15, 7), (216, 4)])
def test_byzantine_mutation_chaos(seed, n):
    _run_byzantine_mutation_chaos(seed, n)


# Seeds 171/306/396: corrupt next-view votes registered during the storm
# permanently poisoned the laggard-help "latest vote" gate (a phantom
# high registration outranks every genuine resend forever); fixed by
# clearing the next-view bookkeeping at each timeout round.
@pytest.mark.parametrize("seed,n", [(171, 4), (306, 4), (396, 4)])
def test_byzantine_mutation_chaos_group_commit(seed, n):
    _run_byzantine_mutation_chaos(seed, n, durability_window=0.05)


@pytest.mark.parametrize("seed,n", [(31, 4), (32, 7), (33, 4)])
def test_byzantine_mutation_chaos_rotation(seed, n):
    """Corruption storms against the ROTATION machinery — including
    tampered prev-commit-signature carries, the blacklist path's input."""
    _run_byzantine_mutation_chaos(seed, n, leader_rotation=True)


def test_byzantine_mutation_chaos_known_split_boundary():
    """The KNOWN-unresolvable sub-f+1 prepared split (check_in_flight
    docstring) is pinned DETERMINISTICALLY by the condition-table test
    test_three_way_split_not_enough_for_anything; a cluster trajectory
    manufacturing it is schedule-dependent and drifts as the protocol
    evolves (seed 1109 manufactured it at discovery time; later trees
    may resolve the run earlier).  This wrapper keeps the storm in the
    gate with the boundary's contract: SAFETY must hold throughout, and
    if the run wedges it may wedge ONLY on the final progress
    assertion."""
    try:
        _run_byzantine_mutation_chaos(1109, 4, durability_window=0.05)
    except AssertionError as e:
        assert "progress" in str(e), f"safety violated: {e}"


def _run_rotation_chaos(seed, n, durability_window=0.0):
    """Targeted chaos with LEADER ROTATION on — one loop, full safety
    checks (a rotation-specific double-delivery would otherwise slip past
    a diverged copy)."""
    _run_targeted_chaos(
        seed, n, durability_window=durability_window, leader_rotation=True
    )


@pytest.mark.parametrize("seed,n", [(21, 4), (22, 7), (23, 4), (24, 7)])
def test_rotation_chaos(seed, n):
    _run_rotation_chaos(seed, n)
