"""Decision pipelining: the bounded in-flight proposal window.

Covers the window end to end: multi-depth ordering/agreement, the
depth-1 cold path (bit-for-bit legacy semantics), crash restore at the
oldest undecided slot with pool re-admission of abandoned slots, the
live view-change rule (only the oldest slot is adopted), the boot-view
pin for the endorsement tail (ADVICE consensus.py gap), and the two
perf regression guards the window exists for: group-commit fsyncs per
decision and cross-slot verify launches per decision.
"""

import pytest

from consensus_tpu.config import Configuration
from consensus_tpu.core.view import Phase
from consensus_tpu.metrics import InMemoryProvider, Metrics
from consensus_tpu.testing import Cluster, FaultPlan, make_request
from consensus_tpu.testing.app import unpack_batch
from consensus_tpu.wire import (
    Commit,
    ProposedRecord,
    SavedCommit,
    SavedViewChange,
    decode_saved,
)

FAST = {
    "request_forward_timeout": 1.0,
    "request_complain_timeout": 4.0,
    "request_auto_remove_timeout": 120.0,
    "view_change_resend_interval": 2.0,
    "view_change_timeout": 10.0,
    "leader_heartbeat_timeout": 20.0,
}

VICTIM = 2  # a follower in view 0


def _delivered_raws(node) -> list[bytes]:
    out: list[bytes] = []
    for decision in node.app.ledger:
        out.extend(unpack_batch(decision.proposal.payload))
    return out


def _assert_exactly_once(cluster, submitted: list[bytes]) -> None:
    for node in cluster.nodes.values():
        raws = _delivered_raws(node)
        for raw in submitted:
            assert raws.count(raw) == 1, (
                f"node {node.node_id}: request {raw!r} delivered "
                f"{raws.count(raw)} times"
            )


# --- ordering and agreement across depths ---------------------------------


@pytest.mark.parametrize("depth", [2, 4, 8])
def test_pipelined_cluster_orders_and_agrees(depth):
    """A saturated window at every depth still yields one totally ordered,
    agreed ledger — commit and delivery stay sequence-ordered."""
    cluster = Cluster(
        4,
        seed=depth,
        config_tweaks=dict(
            pipeline_depth=depth,
            request_batch_max_count=2,
            request_batch_max_interval=0.005,
        ),
    )
    cluster.start()
    submitted = [make_request("pipe", i) for i in range(24)]
    for raw in submitted:
        cluster.submit_to_all(raw)
    assert cluster.run_until_ledger(12, max_time=120.0)
    cluster.assert_ledgers_consistent()
    _assert_exactly_once(cluster, submitted)


def test_depth_one_keeps_window_machinery_cold():
    """pipeline_depth=1 (the default) must be bit-for-bit the legacy
    protocol: the future-slot table never populates."""
    cluster = Cluster(4, seed=11)
    cluster.start()
    for i in range(3):
        cluster.submit_to_all(make_request("cold", i))
        assert cluster.run_until_ledger(i + 1)
    for node in cluster.nodes.values():
        view = node.consensus.controller.curr_view
        assert view.effective_depth == 1
        assert view._future == {}
    cluster.assert_ledgers_consistent()


def test_pipeline_depth_requires_static_leader():
    with pytest.raises(ValueError, match="pipeline_depth"):
        Configuration(
            self_id=1, pipeline_depth=2, leader_rotation=True
        ).validate()
    with pytest.raises(ValueError, match="pipeline_depth"):
        Configuration(
            self_id=1, pipeline_depth=0,
            leader_rotation=False, decisions_per_leader=0,
        ).validate()
    # Static leader + depth > 1 is the supported regime.
    Configuration(
        self_id=1, pipeline_depth=4,
        leader_rotation=False, decisions_per_leader=0,
    ).validate()


# --- crash restore: oldest slot boots, higher slots re-admit ---------------


def _stage_window_on_victim(cluster, submitted):
    """Drop commits inbound to the victim while peers decide: the victim is
    left with slot 1 PREPARED (its commit persisted) and slots 2..3 merely
    PROPOSED — three sequences in distinct phases across one WAL tail."""
    cluster.network.lose_messages = (
        lambda target, sender, msg: target == VICTIM
        and isinstance(msg, Commit)
    )
    for raw in submitted:
        cluster.submit_to_all(raw)
    ok = cluster.scheduler.run_until(
        lambda: all(
            len(cluster.nodes[n].app.ledger) >= 3 for n in (1, 3, 4)
        ),
        max_time=60.0,
    )
    assert ok, "peer trio failed to decide ahead of the victim"


def test_crash_with_window_in_distinct_phases_boots_oldest_and_readmits():
    cluster = Cluster(
        4,
        seed=31,
        config_tweaks=dict(
            FAST, pipeline_depth=3, request_batch_max_count=1
        ),
    )
    cluster.start()
    victim = cluster.nodes[VICTIM]
    submitted = [make_request("cw", i) for i in range(3)]
    _stage_window_on_victim(cluster, submitted)

    view = victim.consensus.controller.curr_view
    assert view.phase == Phase.PREPARED, "oldest slot should be PREPARED"
    assert view.proposal_sequence == 1
    assert {2, 3} <= set(view._future), "future slots 2,3 should be live"
    assert victim.app.ledger == []

    victim.crash()
    cluster.network.lose_messages = None
    victim.restart()

    # Boot lands at the OLDEST undecided slot, in its pre-crash phase.
    booted = victim.consensus.controller.curr_view
    assert victim.consensus.controller.curr_view_number == 0
    assert booted.proposal_sequence == 1
    assert booted.phase == Phase.PREPARED

    # The abandoned slots' requests are re-admitted to the pool.
    cluster.scheduler.advance(0.1)
    assert victim.consensus.pool.count == 2, (
        "requests of abandoned slots 2,3 should be back in the pool"
    )

    # Recovery: the victim catches up; nothing is lost or delivered twice.
    assert cluster.run_until_ledger(3, max_time=120.0)
    cluster.assert_ledgers_consistent()
    _assert_exactly_once(cluster, submitted)


def test_fault_plan_crash_mid_window_save_readmits():
    """Same staging, but death comes from the registered crash-point seam:
    the victim dies the instant its THIRD ProposedRecord hits the WAL, so
    the window is mid-save when the process vanishes."""
    cluster = Cluster(
        4,
        seed=37,
        config_tweaks=dict(
            FAST, pipeline_depth=3, request_batch_max_count=1
        ),
    )
    cluster.start()
    victim = cluster.nodes[VICTIM]
    plan = FaultPlan(
        "state.save.proposed.post", on_hit=3, label="pipeline:third-slot"
    )
    victim.arm_fault_plan(plan)
    submitted = [make_request("fp", i) for i in range(3)]
    _stage_window_on_victim(cluster, submitted)

    assert plan.fired == ("state.save.proposed.post", 3), (
        f"third slot save never crashed: hits={dict(plan.hits)}"
    )
    assert not victim.running

    cluster.network.lose_messages = None
    victim.restart()
    booted = victim.consensus.controller.curr_view
    assert booted.proposal_sequence == 1, (
        "restore must boot at the oldest undecided slot"
    )
    cluster.scheduler.advance(0.1)
    # PR3 was durable (post-seam), so BOTH higher slots re-admit.
    assert victim.consensus.pool.count == 2

    assert cluster.run_until_ledger(3, max_time=120.0)
    cluster.assert_ledgers_consistent()
    _assert_exactly_once(cluster, submitted)


# --- view change: only the oldest slot survives ----------------------------


def test_view_change_adopts_only_oldest_slot():
    """With a full window prepared but undecidable (commits dropped), the
    view change adopts ONLY the oldest slot; the higher slots' requests are
    simply still pooled and get re-proposed in the new view — no request is
    lost and none delivers twice."""
    cluster = Cluster(
        4,
        seed=41,
        config_tweaks=dict(
            FAST, pipeline_depth=3, request_batch_max_count=1
        ),
    )
    cluster.start()
    cluster.network.lose_messages = (
        lambda target, sender, msg: isinstance(msg, Commit)
    )
    submitted = [make_request("vc", i) for i in range(3)]
    for raw in submitted:
        cluster.submit_to_all(raw)
    cluster.scheduler.advance(3.0)  # propose + prepare the whole window

    staged = cluster.nodes[1].consensus.controller.curr_view
    assert staged.proposal_sequence == 1
    assert {2, 3} <= set(staged._future)

    cluster.scheduler.advance(30.0)  # complaints force the view change
    cluster.network.lose_messages = None
    cluster.scheduler.advance(30.0)

    assert cluster.run_until_ledger(3, max_time=300.0)
    cluster.assert_ledgers_consistent()
    _assert_exactly_once(cluster, submitted)
    for node in cluster.nodes.values():
        assert node.consensus.controller.curr_view_number >= 1


# --- the boot-view pin for the endorsement tail (ADVICE gap) ---------------


def test_crash_mid_recommit_boot_view_is_pinned():
    """Kill the victim right after ``_commit_in_flight`` persists its
    endorsement SavedCommit, then pin the BOOT VIEW choice consensus.py
    ``_set_view_and_seq`` documents: the endorsement's ProposedRecord keeps
    the proposal's ORIGINAL view stamp (restamping would fork our own
    attestation from the commit signature already minted), the replica
    boots in the view the buried vote abandoned — NOT above it — and the
    restored vote immediately rejoins the pending change (+1)."""
    cluster = Cluster(4, seed=43, config_tweaks=dict(FAST))
    cluster.start()
    victim = cluster.nodes[VICTIM]
    plan = FaultPlan(
        "state.save.endorsement_commit.post", label="pipeline:bootview"
    )
    victim.arm_fault_plan(plan)
    cluster.network.lose_messages = (
        lambda target, sender, msg: isinstance(msg, Commit)
    )
    cluster.submit_to_all(make_request("bv", 0))
    cluster.scheduler.advance(3.0)
    cluster.scheduler.advance(30.0)  # complaints -> view change -> endorsement
    assert plan.fired is not None, f"endorsement never fired: {dict(plan.hits)}"

    tail = [decode_saved(e) for e in victim.wal_backing[-3:]]
    assert isinstance(tail[0], SavedViewChange)
    assert isinstance(tail[1], ProposedRecord)
    assert isinstance(tail[2], SavedCommit)
    abandoned_view = tail[0].view_change.next_view
    original_view = tail[1].pre_prepare.view
    # The endorsement records carry the proposal's ORIGINAL view, which is
    # the very view the vote abandoned (the proposal predates the change).
    assert original_view == abandoned_view
    assert tail[2].commit.view == original_view

    cluster.network.lose_messages = None
    victim.restart()
    booted = victim.consensus.controller.curr_view_number
    assert booted == original_view, (
        f"boot view {booted}: the endorsement tail must NOT lift the boot "
        f"view above the proposal's original view {original_view}"
    )
    # ... but the buried vote was restored, so the replica immediately
    # rejoins a pending change instead of idling in the dead view (peers
    # may have escalated past +1 meanwhile; never below it).
    cluster.scheduler.advance(0.1)
    assert victim.consensus.view_changer.next_view >= original_view + 1, (
        "restored vote failed to rejoin the pending view change"
    )

    cluster.scheduler.advance(30.0)
    cluster.submit_to_all(make_request("bv", 1))
    assert cluster.run_until_ledger(1, max_time=600.0)
    cluster.assert_ledgers_consistent()
    assert victim.consensus.controller.curr_view_number > original_view, (
        "victim never advanced past the view it died voting to leave"
    )


# --- perf regression guards ------------------------------------------------


def test_group_commit_fsyncs_per_decision_guard():
    """Under group commit, a saturated depth-4 window coalesces the two
    protocol records per decision across slots: fsyncs per decision lands
    near 1 (measured ~1.01), where depth 1 pays exactly 2."""
    cluster = Cluster(
        4,
        seed=53,
        config_tweaks=dict(
            pipeline_depth=4,
            request_batch_max_count=2,
            request_batch_max_interval=0.005,
            request_pool_size=1000,
        ),
        durability_window=0.05,
    )
    cluster.start()
    for i in range(120):
        cluster.submit_to_all(make_request("fs", i))
    assert cluster.run_until_ledger(50, max_time=300.0)
    cluster.assert_ledgers_consistent()
    for node in cluster.nodes.values():
        decisions = len(node.app.ledger)
        ratio = node.wal.fsync_count / decisions
        assert ratio < 1.5, (
            f"node {node.node_id}: {node.wal.fsync_count} fsyncs for "
            f"{decisions} decisions (ratio {ratio:.2f}) — group-commit "
            f"coalescing regressed (depth 1 pays 2.0)"
        )


def test_cross_slot_verify_launches_per_decision_guard():
    """A replica that receives a window's worth of traffic in one burst
    (unordered transport — the oldest slot's commits arrive last) verifies
    every slot's commit votes in ONE coalesced launch and then decides the
    promoted slots from the cached results: launches per decision < 1."""
    cluster = Cluster(
        4,
        seed=59,
        config_tweaks=dict(
            pipeline_depth=4,
            request_batch_max_count=2,
            request_batch_max_interval=0.005,
            request_forward_timeout=5.0,
            request_complain_timeout=50.0,
            leader_heartbeat_timeout=100.0,
        ),
    )
    provider = InMemoryProvider()
    cluster.nodes[VICTIM].metrics = Metrics(provider)
    cluster.start()

    held = []

    def hold(target, sender, msg):
        if target == VICTIM and not isinstance(msg, bytes):
            held.append((sender, msg))
            return True
        return False

    cluster.network.lose_messages = hold
    for i in range(8):
        cluster.submit_to_all(make_request("cs", i))
    ok = cluster.scheduler.run_until(
        lambda: all(
            len(cluster.nodes[n].app.ledger) >= 4 for n in (1, 3, 4)
        ),
        max_time=60.0,
    )
    assert ok, "peer trio failed to race ahead of the victim"

    cluster.network.lose_messages = None
    handler = cluster.network._handlers[VICTIM]
    # Unordered transport (api.Comm contract): the oldest slot's commits
    # arrive last, after the future slots' votes are already buffered.
    oldest_commits = [
        (s, m) for s, m in held if isinstance(m, Commit) and m.seq == 1
    ]
    rest = [
        (s, m)
        for s, m in held
        if not (isinstance(m, Commit) and m.seq == 1)
    ]
    for sender, msg in rest + oldest_commits:
        handler(sender, msg, False)

    ok = cluster.scheduler.run_until(
        lambda: len(cluster.nodes[VICTIM].app.ledger) >= 4, max_time=60.0
    )
    assert ok, "victim failed to drain the burst"

    launches = provider.value("consensus_verify_launches")
    decisions = len(cluster.nodes[VICTIM].app.ledger)
    assert launches / decisions < 1.0, (
        f"{launches} verify launches for {decisions} decisions — cross-slot "
        f"coalescing regressed (promoted slots should decide from cache)"
    )
    batches = provider.observations("consensus_cross_slot_verify_batch")
    assert max(batches) > 2, (
        f"largest verify batch {max(batches)} never spanned slots: {batches}"
    )
    cluster.assert_ledgers_consistent()
