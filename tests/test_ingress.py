"""Million-client ingress plane: trace-driven load, admission hardening,
consistent-hash fleet placement, and the WAN scenario bank.

The claims under test are the ingress plane's contract (ROADMAP item /
COVERAGE row 44):

* traces are a pure function of (seed, spec) — byte-identical replays;
* honest (in-rate-limit) clients are NEVER starved, no matter how hard the
  flood or duplicate-retry storm leans on admission (non-starvation is by
  construction: honest pacing stays inside the token budget);
* admission decisions are triple-booked — summary counts, pinned
  ``ingress_*`` metrics, and the ``admission_overload`` / ``dedup_storm``
  detectors firing on seeded scenarios while clean soaks stay silent;
* rendezvous placement moves ONLY ~1/N tenants on a server leave;
* a real sidecar fleet reroutes a ``TenantAdmissionReject`` to the ring's
  next candidate (pinned ``ingress_reroute_total``);
* WAN schedules (``generate(wan=...)``) are deterministic and leave
  non-WAN schedules byte-identical.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from consensus_tpu.ingress import (
    AdmissionController,
    DedupCache,
    IngressDriver,
    PlacementRing,
    SidecarFleet,
    TokenBucket,
    clean_spec,
    duplicate_storm_spec,
    flood_spec,
    generate_trace,
)
from consensus_tpu.metrics import (
    INGRESS_ADMITTED_KEY,
    INGRESS_DEDUP_HITS_KEY,
    INGRESS_OFFERED_KEY,
    INGRESS_RATE_LIMITED_KEY,
    INGRESS_REROUTE_KEY,
    InMemoryProvider,
    Metrics,
)
from consensus_tpu.obs.detectors import DetectorBank
from consensus_tpu.types import RequestInfo

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# --- admission primitives ---------------------------------------------------


def test_token_bucket_refills_at_rate_and_caps_at_burst():
    tb = TokenBucket(rate=2.0, burst=4.0)
    # First call starts with a full burst.
    assert all(tb.allow(0.0) for _ in range(4))
    assert not tb.allow(0.0)
    # Half a second refills one token at rate=2.
    assert tb.allow(0.5)
    assert not tb.allow(0.5)
    # A long idle stretch caps at burst, not at elapsed * rate.
    assert all(tb.allow(100.0) for _ in range(4))
    assert not tb.allow(100.0)


def test_dedup_cache_is_a_bounded_lru_keyed_on_full_request_info():
    cache = DedupCache(capacity=2)
    a = RequestInfo(client_id="c1", request_id="r1")
    b = RequestInfo(client_id="c2", request_id="r1")  # same rid, other client
    assert not cache.seen(a)
    assert cache.seen(a)
    assert not cache.seen(b), "dedup must key on (client, rid), not rid"
    # Touch a (now MRU), insert a third: b is the LRU evicted.
    assert cache.seen(a)
    c = RequestInfo(client_id="c3", request_id="r9")
    assert not cache.seen(c)
    assert not cache.seen(b), "evicted entry must be forgotten"


def test_admission_checks_dedup_before_the_token_bucket():
    """A client's own retries must not drain its rate budget: duplicates
    are absorbed by the cache BEFORE the bucket is consulted."""
    ctrl = AdmissionController(rate=1.0, burst=2.0)
    info = RequestInfo(client_id="c", request_id="0")
    assert ctrl.admit(0.0, info) == "admitted"
    for _ in range(10):
        assert ctrl.admit(0.0, info) == "duplicate"
    # Budget untouched by the retries: one fresh token still there.
    fresh = RequestInfo(client_id="c", request_id="1")
    assert ctrl.admit(0.0, fresh) == "admitted"
    assert ctrl.admit(0.0, RequestInfo("c", "2")) == "rate_limited"
    assert (ctrl.offered, ctrl.admitted, ctrl.dedup_hits,
            ctrl.rate_limited) == (13, 2, 10, 1)


# --- placement --------------------------------------------------------------


def test_placement_is_deterministic_and_order_total():
    ring = PlacementRing([f"s{i}" for i in range(5)])
    for tenant in ("t0", "t7", "alpha", ""):
        first = ring.candidates(tenant)
        assert ring.candidates(tenant) == first
        assert sorted(first) == sorted(ring.servers())
    with pytest.raises(ValueError):
        PlacementRing().candidates("t0")


def test_server_leave_moves_only_its_own_tenants():
    """The rendezvous property the fleet leans on, pinned: removing one of
    N servers remaps EXACTLY the tenants whose top candidate it was, and
    that set is ~1/N of the population."""
    servers = [f"sidecar-{i}" for i in range(5)]
    tenants = [f"t{i}" for i in range(500)]
    ring = PlacementRing(servers)
    before = ring.assignment_map(tenants)
    victim = "sidecar-3"
    ring.remove(victim)
    after = ring.assignment_map(tenants)
    moved = {t for t in tenants if before[t] != after[t]}
    assert moved == {t for t in tenants if before[t] == victim}, (
        "a leave must move ONLY the departed server's tenants"
    )
    n = len(servers)
    assert 0.5 * len(tenants) / n <= len(moved) <= 2.0 * len(tenants) / n
    # Survivors keep their relative ranking: re-adding restores the map.
    ring.add(victim)
    assert ring.assignment_map(tenants) == before


# --- trace generation -------------------------------------------------------


def test_traces_are_byte_identical_per_seed_and_seed_sensitive():
    spec = flood_spec(clients=120, duration=5.0)
    t1 = generate_trace(11, spec)
    t2 = generate_trace(11, spec)
    assert t1 == t2
    assert t1 != generate_trace(12, spec)
    assert all(0.0 <= e.t < spec.duration for e in t1)
    assert all(spec.size_min <= e.size <= spec.size_cap for e in t1)


def test_duplicate_storm_reemits_already_sent_flood_requests():
    spec = duplicate_storm_spec(duration=10.0, clients=100)
    trace = generate_trace(3, spec)
    dupes = [e for e in trace if e.duplicate]
    assert dupes, "the storm window must re-emit requests"
    fresh = {(e.client, e.rid) for e in trace if not e.duplicate}
    assert all((d.client, d.rid) in fresh for d in dupes), (
        "storm events must replay ALREADY-SENT request ids"
    )
    assert all(not d.honest for d in dupes)


# --- the open-loop driver ---------------------------------------------------


def _run(seed, spec, **kw):
    return IngressDriver(generate_trace(seed, spec), spec, seed=seed, **kw)


def test_honest_clients_never_starved_under_flood_and_storm():
    """The acceptance claim: in-rate-limit clients see zero rejects while
    the flood cohort is shedding >80% of its offered load."""
    for spec in (
        flood_spec(clients=400, duration=10.0),
        duplicate_storm_spec(duration=10.0, clients=400),
    ):
        summary = _run(5, spec).run()
        assert summary["admitted_honest"] == summary["offered_honest"] > 0
        assert summary["committed_honest"] == summary["offered_honest"]
        assert summary["rate_limited"] > 0 or summary["dedup_hits"] > 0


def test_ten_thousand_client_replay_is_byte_identical_per_seed():
    """The scale acceptance gate: a 10k-client heavy-tailed trace against
    a 4-server hashed fleet, replayed twice, yields byte-identical
    summaries — and honest clients stay whole at that scale too."""
    spec = flood_spec(clients=10_000, duration=2.0)
    trace = generate_trace(42, spec)
    assert len(trace) > 100_000, "10k clients must offer real load"
    first = IngressDriver(trace, spec, seed=42, servers=4).summary_json()
    d2 = IngressDriver(trace, spec, seed=42, servers=4)
    d2.run()
    second_run = d2.summary_json()
    d1 = IngressDriver(trace, spec, seed=42, servers=4)
    d1.run()
    assert d1.summary_json() == second_run
    assert first != second_run  # pre-run summary differs: the run ran
    summary = d1.summary()
    assert summary["admitted_honest"] == summary["offered_honest"] > 0


def test_flood_fires_admission_overload_and_clean_soak_is_silent():
    flood = _run(0, flood_spec(clients=300, duration=10.0)).run()
    assert "admission_overload" in flood["anomalies"]
    assert "dedup_storm" not in flood["anomalies"]
    clean = _run(0, clean_spec(clients=300, duration=10.0)).run()
    assert clean["anomalies"] == {}
    assert clean["rate_limited"] == 0 and clean["dedup_hits"] == 0


def test_duplicate_storm_fires_dedup_storm_detector():
    summary = _run(1, duplicate_storm_spec(duration=12.0, clients=300)).run()
    assert "dedup_storm" in summary["anomalies"]
    assert summary["dedup_hits"] > 0


def test_ingress_detectors_ignore_cluster_health_samples():
    """Cluster health dicts never carry ingress fields; feeding them to the
    bank must not fire (or even arm) the ingress detectors — existing
    fixed-seed cluster anomaly streams stay untouched."""
    bank = DetectorBank()
    cluster_health = {"running": True, "ledger": 5, "pool": 0, "view": 0}
    for t in range(1, 50):
        fired = bank.evaluate(float(t), {1: dict(cluster_health)})
        assert not any(
            a.kind in ("admission_overload", "dedup_storm") for a in fired
        )


def test_driver_triple_books_admission_into_pinned_metrics():
    metrics = Metrics(InMemoryProvider())
    spec = flood_spec(clients=200, duration=8.0)
    driver = _run(2, spec, metrics=metrics)
    summary = driver.run()
    dump = metrics.provider.dump()
    assert dump[INGRESS_OFFERED_KEY]["value"] == summary["offered"]
    assert dump[INGRESS_ADMITTED_KEY]["value"] == summary["admitted"]
    assert dump[INGRESS_RATE_LIMITED_KEY]["value"] == summary["rate_limited"]
    assert dump[INGRESS_DEDUP_HITS_KEY]["value"] == summary["dedup_hits"]
    fired = sum(summary["anomalies"].values())
    assert fired > 0
    booked = sum(
        dump[f"obs_anomaly_{kind}"]["value"]
        for kind in ("admission_overload", "dedup_storm")
    )
    assert booked == fired


def test_fleet_queue_limit_reroutes_to_next_ring_candidate():
    """Sim-fleet twin of the sidecar status-2 reject: a one-slot fleet
    overflows its primary and the driver walks the ring, booking hops on
    the pinned reroute counter."""
    metrics = Metrics(InMemoryProvider())
    spec = flood_spec(clients=200, duration=8.0)
    summary = _run(
        2, spec, metrics=metrics, servers=4, queue_limit=1,
        service_rate=50.0,
    ).run()
    assert summary["reroutes"] > 0
    dump = metrics.provider.dump()
    assert dump[INGRESS_REROUTE_KEY]["value"] == summary["reroutes"]


# --- real sidecar fleet reroute --------------------------------------------


class _GoodEngine:
    def verify_batch(self, msgs, sigs, keys):
        return np.array([s == b"good" for s in sigs], dtype=bool)

    def verify_host(self, msgs, sigs, keys):
        return self.verify_batch(msgs, sigs, keys)


def test_real_fleet_reroutes_tenant_admission_reject():
    """End-to-end over real sockets: server A's tenant queue is too small
    for the batch, so the placement-aware client hands the batch to the
    ring's next candidate instead of falling back locally — pinned
    ``ingress_reroute_total`` counts the hop."""
    from consensus_tpu.net.sidecar import (
        SidecarVerifierClient,
        VerifySidecarServer,
    )

    tenants = {"alpha": b"alpha-secret"}
    metrics = Metrics(InMemoryProvider())
    srv_a = VerifySidecarServer(
        ("127.0.0.1", 0), _GoodEngine(), tenants=tenants,
        wave_window=0.02, tenant_queue_limit=16,
    )
    srv_b = VerifySidecarServer(
        ("127.0.0.1", 0), _GoodEngine(), tenants=tenants,
        wave_window=0.02, tenant_queue_limit=1024,
    )
    srv_a.start()
    srv_b.start()
    fleet = SidecarFleet(
        {"srv-a": srv_a.address, "srv-b": srv_b.address},
        client_factory=lambda addr: SidecarVerifierClient(
            addr, auth_secret=tenants["alpha"], tenant="alpha",
        ),
        metrics=metrics.ingress,
    )
    client = SidecarVerifierClient(
        srv_a.address, auth_secret=tenants["alpha"], tenant="alpha",
        fleet=fleet, fleet_id="srv-a",
    )
    try:
        out = client.verify_batch([b"m"] * 20, [b"good"] * 20, [b"k"] * 20)
        assert out.all() and len(out) == 20
        assert fleet.reroutes == [("alpha", "srv-a", "srv-b")]
        dump = metrics.provider.dump()
        assert dump[INGRESS_REROUTE_KEY]["value"] == 1
        assert not client._suspect, "admission reject must not mark suspect"
    finally:
        client.close()
        fleet.close()
        srv_a.stop()
        srv_b.stop()


class _CountingEngine(_GoodEngine):
    def __init__(self):
        self.host_calls = 0

    def verify_host(self, msgs, sigs, keys):
        self.host_calls += 1
        return self.verify_batch(msgs, sigs, keys)


def test_reroute_exhaustion_falls_back_locally_exactly_once():
    """Every ring candidate refuses the batch (all queues too small): the
    client walks the whole ring, then falls back to its LOCAL host engine
    exactly once — no reroute is booked (nothing was handed off) and no
    suspect flag is raised (admission pressure is not a wedged device)."""
    from consensus_tpu.net.sidecar import (
        SidecarVerifierClient,
        VerifySidecarServer,
    )

    tenants = {"alpha": b"alpha-secret"}
    metrics = Metrics(InMemoryProvider())
    srv_a = VerifySidecarServer(
        ("127.0.0.1", 0), _GoodEngine(), tenants=tenants,
        wave_window=0.02, tenant_queue_limit=1,
    )
    srv_b = VerifySidecarServer(
        ("127.0.0.1", 0), _GoodEngine(), tenants=tenants,
        wave_window=0.02, tenant_queue_limit=1,
    )
    srv_a.start()
    srv_b.start()
    local = _CountingEngine()
    fleet = SidecarFleet(
        {"srv-a": srv_a.address, "srv-b": srv_b.address},
        client_factory=lambda addr: SidecarVerifierClient(
            addr, auth_secret=tenants["alpha"], tenant="alpha",
        ),
        metrics=metrics.ingress,
    )
    client = SidecarVerifierClient(
        srv_a.address, auth_secret=tenants["alpha"], tenant="alpha",
        fleet=fleet, fleet_id="srv-a", local_engine=local,
    )
    try:
        out = client.verify_batch([b"m"] * 20, [b"good"] * 20, [b"k"] * 20)
        assert out.all() and len(out) == 20
        assert local.host_calls == 1
        assert fleet.reroutes == []
        dump = metrics.provider.dump()
        assert dump[INGRESS_REROUTE_KEY]["value"] == 0
        assert not client._suspect, "admission reject must not mark suspect"
    finally:
        client.close()
        fleet.close()
        srv_a.stop()
        srv_b.stop()


def test_degraded_server_surfaces_on_status_byte_and_demotes_in_ring():
    """A server whose supervised engine is below its top rung answers with
    status 3 (same verdict body — the host twin is still ground truth);
    the placement-aware client records the observation on the fleet, which
    moves that server to the BACK of every candidate walk until a status-0
    answer clears it."""
    from consensus_tpu.net.sidecar import (
        SidecarVerifierClient,
        VerifySidecarServer,
    )

    class _DegradedEngine(_GoodEngine):
        degraded = True

    engine = _DegradedEngine()
    tenants = {"alpha": b"alpha-secret"}
    srv_a = VerifySidecarServer(
        ("127.0.0.1", 0), engine, tenants=tenants, wave_window=0.02,
    )
    srv_b = VerifySidecarServer(
        ("127.0.0.1", 0), _GoodEngine(), tenants=tenants, wave_window=0.02,
    )
    srv_a.start()
    srv_b.start()
    fleet = SidecarFleet(
        {"srv-a": srv_a.address, "srv-b": srv_b.address},
        client_factory=lambda addr: SidecarVerifierClient(
            addr, auth_secret=tenants["alpha"], tenant="alpha",
        ),
    )
    client = SidecarVerifierClient(
        srv_a.address, auth_secret=tenants["alpha"], tenant="alpha",
        fleet=fleet, fleet_id="srv-a",
    )
    try:
        out = client.verify_batch([b"m"] * 4, [b"good"] * 4, [b"k"] * 4)
        assert out.all() and len(out) == 4  # verdicts unchanged by status 3
        assert fleet.is_degraded("srv-a")
        for tenant in ("alpha", "beta", "gamma"):
            assert fleet.candidates(tenant)[-1] == "srv-a"
        # Recovery: the engine re-promotes, the next answer is status 0,
        # and the ring restores pure rendezvous order.
        engine.degraded = False
        assert client.verify_batch([b"m"], [b"good"], [b"k"]).all()
        assert not fleet.is_degraded("srv-a")
        for tenant in ("alpha", "beta", "gamma"):
            assert fleet.candidates(tenant) == fleet.ring.candidates(tenant)
    finally:
        client.close()
        fleet.close()
        srv_a.stop()
        srv_b.stop()


# --- WAN scenario bank ------------------------------------------------------


def test_wan_schedules_are_deterministic_and_opt_in():
    from consensus_tpu.testing.chaos import ChaosSchedule, WAN_PROFILES

    base = ChaosSchedule.generate(7, steps=12)
    assert ChaosSchedule.generate(7, steps=12, wan=None) == base, (
        "wan=None must consume no RNG: pre-WAN schedules replay unchanged"
    )
    for profile in WAN_PROFILES:
        s1 = ChaosSchedule.generate(7, steps=12, wan=profile)
        assert s1 == ChaosSchedule.generate(7, steps=12, wan=profile)
        assert s1.wan == profile
    with pytest.raises(ValueError):
        ChaosSchedule.generate(7, wan="atlantis")


def test_region_partition_groups_match_the_geography():
    from consensus_tpu.testing.chaos import ChaosSchedule, region_map

    found = None
    for seed in range(40):
        sched = ChaosSchedule.generate(seed, steps=12, wan="3region")
        for a in sched.actions:
            if a.kind == "region_partition":
                found = (sched, a)
                break
        if found:
            break
    assert found, "40 seeds of 12 steps must draw one region_partition"
    sched, action = found
    rmap = region_map("3region", range(1, sched.n + 1))
    expect = tuple(sorted(
        i for i in range(1, sched.n + 1)
        if rmap[i] == action.args["region"]
    ))
    assert action.args["group"] == expect


def test_wan_chaos_run_is_safe_and_replay_identical():
    """Tier-1 WAN smoke: a geography-pinned schedule (jittered links,
    region cuts, leader shifts) runs clean and byte-identically twice."""
    from consensus_tpu.testing.chaos import ChaosEngine, ChaosSchedule

    sched = ChaosSchedule.generate(7, steps=8, wan="3region")
    r1 = ChaosEngine(sched).run()
    assert r1.ok, r1.violation
    r2 = ChaosEngine(sched).run()
    assert r1.event_log == r2.event_log
    assert r1.ledgers == r2.ledgers


def test_wan_links_cover_every_ordered_pair():
    from consensus_tpu.testing.chaos import WAN_PROFILES, wan_links

    for profile in WAN_PROFILES:
        links = wan_links(profile, [1, 2, 3, 4, 5])
        assert len(links) == 20  # 5 * 4 ordered pairs
        assert all(base > 0 and jitter >= 0 for _, _, base, jitter in links)


def test_format_repro_carries_the_wan_profile():
    from consensus_tpu.testing.chaos import (
        ChaosEngine, ChaosSchedule, format_repro,
    )

    sched = ChaosSchedule.generate(3, steps=4, wan="2region-lopsided")
    snippet = format_repro(ChaosEngine(sched).run())
    assert "wan='2region-lopsided'" in snippet


# --- network jitter knob ----------------------------------------------------


def _two_node_net(seed=0):
    from consensus_tpu.runtime.scheduler import SimScheduler
    from consensus_tpu.testing.network import SimNetwork

    sched = SimScheduler()
    net = SimNetwork(sched, seed=seed)
    arrivals = []
    net.register(1, lambda s, p, r: None)
    net.register(2, lambda s, p, r: arrivals.append(sched.now()))
    return sched, net, arrivals


def test_set_jitter_draws_within_the_distribution_and_heals_away():
    sched, net, arrivals = _two_node_net()
    net.set_jitter(1, 2, 0.1, 0.05)
    for _ in range(20):
        net.send(1, 2, b"x", is_request=False)
    sched.run_until_idle()
    assert len(arrivals) == 20
    assert all(0.1 <= t <= 0.15 + 1e-9 for t in arrivals)
    assert len(set(arrivals)) > 1, "spread must actually spread"
    # set_delay composes by max: a floor above the distribution wins.
    arrivals.clear()
    net.set_delay(1, 2, 0.5)
    net.send(1, 2, b"x", is_request=False)
    sched.run_until_idle()
    assert arrivals[-1] - sched.now() <= 0 and arrivals[-1] >= 0.5
    # heal() clears jitter along with every other knob.
    arrivals.clear()
    net.heal()
    base = sched.now()
    net.send(1, 2, b"x", is_request=False)
    sched.run_until_idle()
    assert arrivals == [base + net.default_delay]


def test_unarmed_jitter_consumes_no_rng():
    """Arming a zero-spread jitter link must not shift the loss stream on
    other links — the byte-identity discipline for non-WAN schedules."""
    outcomes = []
    for arm in (False, True):
        sched, net, arrivals = _two_node_net(seed=9)
        if arm:
            net.set_jitter(1, 2, 0.01, 0.0)  # spread 0: no draw
        net.set_loss(2, 1, 0.5)
        net.register(3, lambda s, p, r: None)
        for _ in range(30):
            net.send(2, 1, b"y", is_request=False)
        sched.run_until_idle()
        outcomes.append(net.injected["dropped"])
    assert outcomes[0] == outcomes[1]


# --- the sweep scripts ------------------------------------------------------


def _run_script(script, *argv):
    return subprocess.run(
        [sys.executable, os.path.join(_REPO, "scripts", script), *argv],
        capture_output=True, text=True, cwd=_REPO, timeout=600,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )


def test_ingress_sweep_emits_per_seed_and_summary_json(tmp_path):
    out = tmp_path / "sweep.json"
    proc = _run_script(
        "ingress_sweep.py", "--count", "2", "--clients", "150",
        "--duration", "6", "--scenario", "flood", "--json-out", str(out),
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    lines = [json.loads(l) for l in proc.stdout.splitlines()
             if l.startswith("{")]
    assert len(lines) == 3  # 2 per-seed + 1 summary
    assert all(l["ok"] for l in lines[:2])
    summary = lines[-1]
    assert summary["swept"] == 2 and summary["failed"] == 0
    assert summary["params"]["scenario"] == "flood"
    assert "admission_overload" in summary["anomalies"]
    assert json.loads(out.read_text())["swept"] == 2


def test_chaos_sweep_accepts_wan_profile():
    proc = _run_script(
        "chaos_sweep.py", "--start", "7", "--count", "1",
        "--steps", "6", "--wan", "3region",
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    summary = json.loads(proc.stdout.splitlines()[-1])
    assert summary["failed"] == 0
    assert summary["params"]["wan"] == "3region"
