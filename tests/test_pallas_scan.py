"""Parity gate for the whole-scan-in-VMEM Pallas kernel (interpret mode).

The Pallas path shares the field/point arithmetic with the XLA path, so
these tests pin the *scheduling* rewrite: same table, same digit walk,
bit-exact accumulator.  Mosaic lowering and the speed verdict run on the
real device (benchmarks/run_device_suite.sh records an A/B `bench.py`
pass with CTPU_PALLAS_SCAN=1); interpret mode keeps correctness CI-gated
on the CPU backend.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from consensus_tpu.ops import ed25519 as ed
from consensus_tpu.ops import field25519 as fe
from consensus_tpu.ops.pallas_scan import horner_scan


def _point_limbs(points_xy):
    """Affine int points -> stacked (x, y, z=1, t=xy) limb arrays
    of shape (32, n)."""
    xs = np.stack([fe.int_to_limbs(x) for x, _ in points_xy], axis=1)
    ys = np.stack([fe.int_to_limbs(y) for _, y in points_xy], axis=1)
    ts = np.stack(
        [fe.int_to_limbs(x * y % fe.P) for x, y in points_xy], axis=1
    )
    ones = np.stack([fe.int_to_limbs(1)] * len(points_xy), axis=1)
    return (
        jnp.asarray(xs), jnp.asarray(ys), jnp.asarray(ones), jnp.asarray(ts)
    )


def _digits_for(scalars):
    from consensus_tpu.models.ed25519 import _bits_to_signed_window_digits

    bits = np.zeros((len(scalars), 256), dtype=np.uint8)
    for i, k in enumerate(scalars):
        for b in range(256):
            bits[i, b] = (k >> b) & 1
    return jnp.asarray(_bits_to_signed_window_digits(bits).astype(np.int32))


def _xla_reference(nx, ny, nz, nt, k_digits):
    """The production XLA scan, verbatim shape (models/ed25519.py)."""
    neg_a = ed.Point(nx, ny, nz, nt)
    table = ed.multiples_table(neg_a, 9)
    lanes = jnp.arange(9, dtype=jnp.int32)[:, None]

    def step(acc, k_w):
        d = k_w - 8
        k_oh = (jnp.abs(d)[None] == lanes).astype(jnp.float32)
        for _ in range(3):
            acc = ed.double(acc, need_t=False)
        acc = ed.double(acc)
        q = ed.table_lookup(table, k_oh)
        q = ed.select(d < 0, ed.negate(q), q)
        acc = ed.add(acc, q)
        return acc, None

    acc, _ = jax.lax.scan(step, ed.identity_like(nx), k_digits)
    return acc


def _case_points_scalars(n, seed=7):
    rng = np.random.default_rng(seed)
    pts, cur = [], None
    base = (ed._BX, (4 * pow(5, fe.P - 2, fe.P)) % fe.P)
    cur = base
    for _ in range(n):
        pts.append(cur)
        cur = ed._edwards_add_int(cur, base)
    ell = 2**252 + 27742317777372353535851937790883648493  # group order
    scalars = [int.from_bytes(rng.bytes(32), "little") % ell for _ in range(n)]
    return pts, scalars


@pytest.mark.parametrize("tile", [4])  # 2 grid programs; interpret is slow
def test_pallas_scan_matches_xla_reference(tile):
    n = 8
    pts, scalars = _case_points_scalars(n)
    # Negate on host: (-x mod p, y), t = -xy.
    neg = [((fe.P - x) % fe.P, y) for x, y in pts]
    nx, ny, nz, nt = _point_limbs(neg)
    kd = _digits_for(scalars)

    got = horner_scan(nx, ny, nz, nt, kd, tile=tile, interpret=True)
    want = _xla_reference(nx, ny, nz, nt, kd)
    match = np.asarray(ed.equal(got, want))
    assert match.all(), f"projective mismatch at lanes {np.where(~match)[0]}"


def test_pallas_scan_zero_and_small_digits():
    """Scalar 0 (all digit rows = +8 i.e. 0) must land exactly on the
    identity; scalar 1 on the point itself."""
    pts, _ = _case_points_scalars(2)
    nx, ny, nz, nt = _point_limbs(pts)
    kd = _digits_for([0, 1])
    got = horner_scan(nx, ny, nz, nt, kd, tile=2, interpret=True)

    ident = ed.identity_like(nx)
    lane0 = ed.Point(*(c[:, :1] for c in got))
    lane1 = ed.Point(*(c[:, 1:] for c in got))
    assert np.asarray(ed.equal(lane0, ed.Point(*(c[:, :1] for c in ident)))).all()
    assert np.asarray(
        ed.equal(lane1, ed.Point(nx[:, 1:], ny[:, 1:], nz[:, 1:], nt[:, 1:]))
    ).all()


def test_full_verifier_parity_with_pallas_flag(monkeypatch):
    """End-to-end: verify_batch with CTPU_PALLAS_SCAN=1 (interpret mode on
    CPU) accepts valid signatures and rejects tampered ones, matching the
    default path bit-for-bit on the same inputs."""
    from cryptography.hazmat.primitives import serialization
    from cryptography.hazmat.primitives.asymmetric.ed25519 import Ed25519PrivateKey

    import consensus_tpu.models.ed25519 as model

    n = 8
    msgs, sigs, keys = [], [], []
    for i in range(n):
        sk = Ed25519PrivateKey.from_private_bytes(bytes([i + 1] * 32))
        pk = sk.public_key().public_bytes(
            serialization.Encoding.Raw, serialization.PublicFormat.Raw
        )
        m = b"pallas-%d" % i
        msgs.append(m)
        sigs.append(sk.sign(m))
        keys.append(pk)
    sigs[3] = sigs[3][:32] + bytes(32)  # corrupt one S half
    expected = [True, True, True, False, True, True, True, True]

    monkeypatch.setenv("CTPU_PALLAS_SCAN", "1")
    monkeypatch.setenv("CTPU_PALLAS_TILE", "8")
    # A fresh jit so the flag is read at trace time (the module-level
    # kernel may already be compiled without the flag).
    fresh = jax.jit(model.verify_impl)
    monkeypatch.setattr(model, "_verify_kernel", fresh)
    verifier = model.Ed25519BatchVerifier()
    out = list(np.asarray(verifier.verify_batch(msgs, sigs, keys)))
    assert out == expected
