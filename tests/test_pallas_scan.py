"""Parity gate for the whole-scan-in-VMEM Pallas kernel (interpret mode).

The Pallas path shares the field/point arithmetic with the XLA path, so
these tests pin the *scheduling* rewrite: same table, same digit walk,
bit-exact accumulator.  Mosaic lowering and the speed verdict run on the
real device (benchmarks/run_device_suite.sh records an A/B `bench.py`
pass with CTPU_PALLAS_SCAN=1); interpret mode keeps correctness CI-gated
on the CPU backend.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from consensus_tpu.ops import ed25519 as ed
from consensus_tpu.ops import field25519 as fe
from consensus_tpu.ops.pallas_scan import horner_scan


def _point_limbs(points_xy):
    """Affine int points -> stacked (x, y, z=1, t=xy) limb arrays
    of shape (32, n)."""
    xs = np.stack([fe.int_to_limbs(x) for x, _ in points_xy], axis=1)
    ys = np.stack([fe.int_to_limbs(y) for _, y in points_xy], axis=1)
    ts = np.stack(
        [fe.int_to_limbs(x * y % fe.P) for x, y in points_xy], axis=1
    )
    ones = np.stack([fe.int_to_limbs(1)] * len(points_xy), axis=1)
    return (
        jnp.asarray(xs), jnp.asarray(ys), jnp.asarray(ones), jnp.asarray(ts)
    )


def _digits_for(scalars):
    from consensus_tpu.models.ed25519 import _bits_to_signed_window_digits

    bits = np.zeros((len(scalars), 256), dtype=np.uint8)
    for i, k in enumerate(scalars):
        for b in range(256):
            bits[i, b] = (k >> b) & 1
    return jnp.asarray(_bits_to_signed_window_digits(bits).astype(np.int32))


def _xla_reference(nx, ny, nz, nt, k_digits):
    """The production XLA scan, verbatim shape (models/ed25519.py)."""
    neg_a = ed.Point(nx, ny, nz, nt)
    table = ed.multiples_table(neg_a, 9)
    lanes = jnp.arange(9, dtype=jnp.int32)[:, None]

    def step(acc, k_w):
        d = k_w - 8
        k_oh = (jnp.abs(d)[None] == lanes).astype(jnp.float32)
        for _ in range(3):
            acc = ed.double(acc, need_t=False)
        acc = ed.double(acc)
        q = ed.table_lookup(table, k_oh)
        q = ed.select(d < 0, ed.negate(q), q)
        acc = ed.add(acc, q)
        return acc, None

    acc, _ = jax.lax.scan(step, ed.identity_like(nx), k_digits)
    return acc


def _case_points_scalars(n, seed=7):
    rng = np.random.default_rng(seed)
    pts, cur = [], None
    base = (ed._BX, (4 * pow(5, fe.P - 2, fe.P)) % fe.P)
    cur = base
    for _ in range(n):
        pts.append(cur)
        cur = ed._edwards_add_int(cur, base)
    ell = 2**252 + 27742317777372353535851937790883648493  # group order
    scalars = [int.from_bytes(rng.bytes(32), "little") % ell for _ in range(n)]
    return pts, scalars


@pytest.mark.parametrize("tile", [4])  # 2 grid programs; interpret is slow
def test_pallas_scan_matches_xla_reference(tile):
    n = 8
    pts, scalars = _case_points_scalars(n)
    # Negate on host: (-x mod p, y), t = -xy.
    neg = [((fe.P - x) % fe.P, y) for x, y in pts]
    nx, ny, nz, nt = _point_limbs(neg)
    kd = _digits_for(scalars)

    got = horner_scan(nx, ny, nz, nt, kd, tile=tile, interpret=True)
    want = _xla_reference(nx, ny, nz, nt, kd)
    match = np.asarray(ed.equal(got, want))
    assert match.all(), f"projective mismatch at lanes {np.where(~match)[0]}"


def test_pallas_scan_zero_and_small_digits():
    """Scalar 0 (all digit rows = +8 i.e. 0) must land exactly on the
    identity; scalar 1 on the point itself."""
    pts, _ = _case_points_scalars(2)
    nx, ny, nz, nt = _point_limbs(pts)
    kd = _digits_for([0, 1])
    got = horner_scan(nx, ny, nz, nt, kd, tile=2, interpret=True)

    ident = ed.identity_like(nx)
    lane0 = ed.Point(*(c[:, :1] for c in got))
    lane1 = ed.Point(*(c[:, 1:] for c in got))
    assert np.asarray(ed.equal(lane0, ed.Point(*(c[:, :1] for c in ident)))).all()
    assert np.asarray(
        ed.equal(lane1, ed.Point(nx[:, 1:], ny[:, 1:], nz[:, 1:], nt[:, 1:]))
    ).all()


def _test_corpus(n=8):
    pytest.importorskip("cryptography", reason="reference signer unavailable")
    from cryptography.hazmat.primitives import serialization
    from cryptography.hazmat.primitives.asymmetric.ed25519 import Ed25519PrivateKey

    msgs, sigs, keys = [], [], []
    for i in range(n):
        sk = Ed25519PrivateKey.from_private_bytes(bytes([i + 1] * 32))
        pk = sk.public_key().public_bytes(
            serialization.Encoding.Raw, serialization.PublicFormat.Raw
        )
        m = b"pallas-%d" % i
        msgs.append(m)
        sigs.append(sk.sign(m))
        keys.append(pk)
    sigs[3] = sigs[3][:32] + bytes(32)  # corrupt one S half
    return msgs, sigs, keys, [i != 3 for i in range(n)]


def test_full_verifier_parity_with_pallas_flag(monkeypatch):
    """End-to-end A/B on identical inputs: verify_batch with
    CTPU_PALLAS_SCAN=1 (interpret mode on CPU) returns the SAME verdict
    vector as the default XLA path, and both match the known
    accept/reject pattern (one tampered signature)."""
    import consensus_tpu.models.ed25519 as model

    msgs, sigs, keys, expected = _test_corpus()

    baseline = list(
        np.asarray(model.Ed25519BatchVerifier().verify_batch(msgs, sigs, keys))
    )

    monkeypatch.setenv("CTPU_PALLAS_SCAN", "1")
    monkeypatch.setenv("CTPU_PALLAS_TILE", "8")
    # A fresh jit so the flag is read at trace time (the module-level
    # kernel may already be compiled without the flag).
    fresh = jax.jit(model.verify_impl)
    monkeypatch.setattr(model, "_verify_kernel", fresh)
    verifier = model.Ed25519BatchVerifier()
    out = list(np.asarray(verifier.verify_batch(msgs, sigs, keys)))
    assert out == expected
    assert out == baseline


def test_misconfigured_tile_fails_loud(monkeypatch):
    """An opt-in whose batch cannot tile must ERROR, not silently fall
    back to XLA — a fallback would let the device A/B record a pure-XLA
    number under the pallas metric key."""
    import consensus_tpu.models.ed25519 as model

    monkeypatch.setenv("CTPU_PALLAS_SCAN", "1")
    monkeypatch.setenv("CTPU_PALLAS_TILE", "7")
    with pytest.raises(ValueError, match="does not tile"):
        model._pallas_scan_config(8)


def test_sharded_path_suppresses_pallas(monkeypatch):
    """The multi-chip shard_map path must keep tracing the XLA scan even
    with the env opt-in set (pallas-under-shard_map is unvalidated); the
    sharded verifier still produces correct verdicts with the flag on."""
    import consensus_tpu.models.ed25519 as model
    from consensus_tpu.parallel.sharding import ShardedEd25519Verifier, make_mesh

    monkeypatch.setenv("CTPU_PALLAS_SCAN", "1")
    # No CTPU_PALLAS_TILE: per-shard batches would tile fine, so only the
    # suppression keeps pallas out of the shard body.
    msgs, sigs, keys, expected = _test_corpus()
    mesh = make_mesh(jax.devices()[:2])
    out = list(
        np.asarray(ShardedEd25519Verifier(mesh=mesh).verify_batch(msgs, sigs, keys))
    )
    assert out == expected


# --- P-256 variant ---------------------------------------------------------


def _p256_case(n, seed=11):
    from consensus_tpu.ops import field_p256 as fp
    from consensus_tpu.ops import p256

    rng = np.random.default_rng(seed)
    pts, cur = [], (p256.GX, p256.GY)
    for _ in range(n):
        pts.append(cur)
        cur = p256._add_int(cur, (p256.GX, p256.GY))
    xs = np.stack([fp.int_to_limbs(x) for x, _ in pts], axis=1)
    ys = np.stack([fp.int_to_limbs(y) for _, y in pts], axis=1)
    scalars = [int.from_bytes(rng.bytes(32), "big") % p256.N for _ in range(n)]
    return jnp.asarray(xs), jnp.asarray(ys), scalars


def _p256_xla_reference(qx, qy, u2_digits):
    from consensus_tpu.ops import p256

    q = p256.affine_like(qx, qy)
    table = p256.multiples_table(q, 9)
    lanes = jnp.arange(9, dtype=jnp.int32)[:, None]

    def step(acc, w):
        d = w - 8
        oh2 = (jnp.abs(d)[None] == lanes).astype(jnp.float32)
        for _ in range(4):
            acc = p256.double(acc)
        t = p256.table_lookup(table, oh2)
        t = p256.select(d < 0, p256.negate(t), t)
        acc = p256.add(acc, t)
        return acc, None

    acc, _ = jax.lax.scan(step, p256.identity_like(qx), u2_digits)
    return acc


def test_pallas_p256_scan_matches_xla_reference():
    from consensus_tpu.models.ecdsa_p256 import _scalars_to_signed_window_digits
    from consensus_tpu.ops import field_p256 as fp
    from consensus_tpu.ops import p256
    from consensus_tpu.ops.pallas_scan import horner_scan_p256

    n = 4
    qx, qy, scalars = _p256_case(n)
    kd = jnp.asarray(
        _scalars_to_signed_window_digits(scalars).astype(np.int32)
    )
    got = horner_scan_p256(qx, qy, kd, tile=2, interpret=True)
    want = _p256_xla_reference(qx, qy, kd)
    # Projective equality: X1 Z2 == X2 Z1 and Y1 Z2 == Y2 Z1.
    eq_x = fp.eq(fp.mul(got.x, want.z), fp.mul(want.x, got.z))
    eq_y = fp.eq(fp.mul(got.y, want.z), fp.mul(want.y, got.z))
    match = np.asarray(eq_x & eq_y)
    assert match.all(), f"projective mismatch at lanes {np.where(~match)[0]}"


def test_full_p256_verifier_parity_with_pallas_flag(monkeypatch):
    """End-to-end A/B on identical inputs for the P-256 family."""
    import consensus_tpu.models.ecdsa_p256 as model
    pytest.importorskip("cryptography", reason="reference signer unavailable")
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import ec

    n = 4
    msgs, sigs, keys = [], [], []
    for i in range(n):
        sk = ec.derive_private_key(i + 12345, ec.SECP256R1())
        pk = sk.public_key().public_bytes(
            serialization.Encoding.X962, serialization.PublicFormat.UncompressedPoint
        )
        m = b"p256-pallas-%d" % i
        msgs.append(m)
        sigs.append(
            model.raw_signature_from_der(sk.sign(m, ec.ECDSA(hashes.SHA256())))
        )
        keys.append(pk)
    sigs[1] = bytes(32) + sigs[1][32:]  # r = 0: invalid
    expected = [True, False, True, True]

    baseline = list(
        np.asarray(
            model.EcdsaP256BatchVerifier(min_device_batch=1).verify_batch(
                msgs, sigs, keys
            )
        )
    )

    monkeypatch.setenv("CTPU_PALLAS_SCAN", "1")
    monkeypatch.setenv("CTPU_PALLAS_TILE", "4")
    fresh = jax.jit(model.verify_impl)
    monkeypatch.setattr(model, "_verify_kernel", fresh)
    out = list(
        np.asarray(
            model.EcdsaP256BatchVerifier(min_device_batch=1).verify_batch(
                msgs, sigs, keys
            )
        )
    )
    assert out == expected
    assert out == baseline


# --- tracing thread-safety -------------------------------------------------


def test_concurrent_tracing_from_two_threads_is_safe():
    """Two threads tracing ``horner_scan`` at DIFFERENT shapes concurrently:
    each trace swaps the ``ops.ed25519`` module globals inside the
    ``_inject_consts`` window, and without the module-level lock
    (``pallas_scan._INJECT_LOCK``) one thread's trace can capture the other
    thread's injected stand-ins — or the first ``finally`` can restore the
    originals mid-swap under the second's feet.  Both traces must produce
    the same accumulator as the XLA reference computed single-threaded."""
    import threading

    cases = {}
    for n in (2, 4):
        pts, scalars = _case_points_scalars(n, seed=23 + n)
        neg = [((fe.P - x) % fe.P, y) for x, y in pts]
        cases[n] = (_point_limbs(neg), _digits_for(scalars))
    # References BEFORE the race: _xla_reference reads the same module
    # globals the inject window swaps, so it must not run concurrently.
    refs = {
        n: _xla_reference(*limbs, kd) for n, (limbs, kd) in cases.items()
    }

    results, errors = {}, []
    barrier = threading.Barrier(2)

    def worker(n):
        try:
            limbs, kd = cases[n]
            barrier.wait(timeout=30)
            results[n] = horner_scan(*limbs, kd, tile=2, interpret=True)
        except Exception as exc:  # surfaced below; a hang fails via join
            errors.append((n, exc))

    threads = [
        threading.Thread(target=worker, args=(n,), name=f"trace-{n}")
        for n in cases
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    assert not errors, errors
    assert set(results) == set(cases), "a tracing thread never finished"
    for n, (limbs, kd) in cases.items():
        match = np.asarray(ed.equal(results[n], refs[n]))
        assert match.all(), (
            f"n={n}: concurrent trace diverged at lanes {np.where(~match)[0]}"
        )
