"""RequestPool + Batcher tests on a simulated clock: cascade firing order,
back-pressure parking, dedup, prune, and early/timed batch completion.

Parity model: reference internal/bft/requestpool_test.go and batcher_test.go.
"""

import pytest

from consensus_tpu.api.deps import RequestInspector
from consensus_tpu.core import Batcher, PoolOptions, RequestPool
from consensus_tpu.runtime import SimScheduler
from consensus_tpu.types import RequestInfo


class ByteInspector(RequestInspector):
    """request bytes "client:reqid|payload" -> RequestInfo."""

    def request_id(self, raw_request: bytes) -> RequestInfo:
        head = raw_request.split(b"|", 1)[0].decode()
        client, _, rid = head.partition(":")
        return RequestInfo(client_id=client, request_id=rid)


class RecordingHandler:
    def __init__(self):
        self.events = []

    def on_request_timeout(self, raw, info):
        self.events.append(("forward", info.request_id))

    def on_leader_fwd_request_timeout(self, raw, info):
        self.events.append(("complain", info.request_id))

    def on_auto_remove_timeout(self, info):
        self.events.append(("auto-remove", info.request_id))


def req(i: int, pad: int = 0) -> bytes:
    return f"c:{i}|".encode() + b"x" * pad


def make_pool(sched, **opt_kw):
    handler = RecordingHandler()
    opts = PoolOptions(
        pool_size=opt_kw.pop("pool_size", 4),
        submit_timeout=opt_kw.pop("submit_timeout", 1.0),
        forward_timeout=opt_kw.pop("forward_timeout", 2.0),
        complain_timeout=opt_kw.pop("complain_timeout", 20.0),
        auto_remove_timeout=opt_kw.pop("auto_remove_timeout", 60.0),
        **opt_kw,
    )
    pool = RequestPool(sched, ByteInspector(), opts, timeout_handler=handler)
    return pool, handler


def test_submit_dedup_and_fifo_order():
    s = SimScheduler()
    pool, _ = make_pool(s)
    results = []
    pool.submit(req(1), results.append)
    pool.submit(req(2), results.append)
    pool.submit(req(1), results.append)  # duplicate
    assert results == [None, None, "request already exists"]
    assert pool.next_requests(10, 10**6) == [req(1), req(2)]


def test_cascade_fires_in_order_forward_complain_remove():
    s = SimScheduler()
    pool, handler = make_pool(s)
    pool.submit(req(7))
    s.advance(2.0)  # forward timeout
    assert handler.events == [("forward", "7")]
    s.advance(20.0)  # + complain timeout
    assert handler.events == [("forward", "7"), ("complain", "7")]
    s.advance(60.0)  # + auto-remove timeout
    assert handler.events == [
        ("forward", "7"),
        ("complain", "7"),
        ("auto-remove", "7"),
    ]
    assert pool.count == 0


def test_remove_cancels_cascade():
    s = SimScheduler()
    pool, handler = make_pool(s)
    pool.submit(req(1))
    assert pool.remove_request(RequestInfo("c", "1"))
    s.advance(1000.0)
    assert handler.events == []


def test_stop_and_restart_timers():
    s = SimScheduler()
    pool, handler = make_pool(s)
    pool.submit(req(1))
    pool.stop_timers()
    s.advance(100.0)
    assert handler.events == []  # frozen during view change
    pool.restart_timers()
    s.advance(2.0)
    assert handler.events == [("forward", "1")]


def test_full_pool_parks_then_admits_on_space():
    s = SimScheduler()
    pool, _ = make_pool(s, pool_size=2)
    results = {}
    pool.submit(req(1), lambda e: results.update(r1=e))
    pool.submit(req(2), lambda e: results.update(r2=e))
    pool.submit(req(3), lambda e: results.update(r3=e))
    s.advance(0.5)
    assert "r3" not in results  # parked
    pool.remove_request(RequestInfo("c", "1"))
    s.advance(0.0)
    assert results["r3"] is None
    assert pool.count == 2


def test_full_pool_submit_times_out():
    s = SimScheduler()
    pool, _ = make_pool(s, pool_size=1, submit_timeout=1.0)
    results = []
    pool.submit(req(1))
    pool.submit(req(2), results.append)
    s.advance(1.1)
    assert results == ["submit timed out: pool is full"]


def test_deleted_requests_resubmittable_after_retention():
    s = SimScheduler()
    pool, _ = make_pool(s)
    pool.submit(req(1))
    pool.remove_request(RequestInfo("c", "1"))
    results = []
    pool.submit(req(1), results.append)
    assert results == ["request already exists"]  # still in dedup window
    s.advance(6.0)  # past DELETED_RETENTION_SECONDS
    pool.submit(req(1), results.append)
    assert results == ["request already exists", None]


def test_oversized_request_rejected():
    s = SimScheduler()
    pool, _ = make_pool(s, request_max_bytes=16)
    results = []
    pool.submit(b"c:1|" + b"y" * 100, results.append)
    assert results and "exceeds max" in results[0]


def test_prune_drops_failing_requests():
    s = SimScheduler()
    pool, _ = make_pool(s)
    for i in range(3):
        pool.submit(req(i))
    pool.prune(lambda raw: raw != req(1))
    assert pool.next_requests(10, 10**6) == [req(0), req(2)]


def test_next_requests_respects_count_and_bytes():
    s = SimScheduler()
    pool, _ = make_pool(s, pool_size=10, request_max_bytes=1000)
    for i in range(5):
        pool.submit(req(i, pad=100))
    assert len(pool.next_requests(3, 10**6)) == 3
    batch = pool.next_requests(10, 250)
    assert len(batch) == 2  # ~104 bytes each; the first always fits
    assert len(pool.next_requests(10, 1)) == 1


def test_batcher_immediate_when_pool_full_enough():
    s = SimScheduler()
    pool, _ = make_pool(s, pool_size=10)
    b = Batcher(s, pool, batch_max_count=2, batch_max_bytes=10**6, batch_max_interval=0.05)
    pool.submit(req(1))
    pool.submit(req(2))
    got = []
    b.next_batch(got.append)
    assert got == [[req(1), req(2)]]


def test_batcher_interval_returns_partial_batch():
    s = SimScheduler()
    pool, _ = make_pool(s, pool_size=10)
    b = Batcher(s, pool, batch_max_count=5, batch_max_bytes=10**6, batch_max_interval=0.05)
    pool.submit(req(1))
    got = []
    b.next_batch(got.append)
    assert got == []
    s.advance(0.05)
    assert got == [[req(1)]]


def test_batcher_completes_early_when_pool_tops_up():
    s = SimScheduler()
    pool_holder = {}

    def on_submitted():
        pool_holder["batcher"].pool_changed()

    opts = PoolOptions(pool_size=10)
    pool = RequestPool(s, ByteInspector(), opts, on_submitted=on_submitted)
    b = Batcher(s, pool, batch_max_count=2, batch_max_bytes=10**6, batch_max_interval=5.0)
    pool_holder["batcher"] = b
    got = []
    pool.submit(req(1))
    b.next_batch(got.append)
    assert got == []
    pool.submit(req(2))  # tops up to batch_max_count
    assert got == [[req(1), req(2)]]
    assert s.now() < 5.0  # did not wait for the interval


def test_batcher_close_unblocks_with_empty_and_reset_reopens():
    s = SimScheduler()
    pool, _ = make_pool(s, pool_size=10)
    b = Batcher(s, pool, batch_max_count=2, batch_max_bytes=10**6, batch_max_interval=1.0)
    got = []
    b.next_batch(got.append)
    b.close()
    assert got == [[]]
    s.advance(2.0)
    assert got == [[]]  # timer was cancelled
    b.reset()
    pool.submit(req(1))
    pool.submit(req(2))
    b.next_batch(got.append)
    assert got == [[], [req(1), req(2)]]


def test_batcher_rejects_concurrent_requests():
    s = SimScheduler()
    pool, _ = make_pool(s)
    b = Batcher(s, pool, batch_max_count=2, batch_max_bytes=10**6, batch_max_interval=1.0)
    b.next_batch(lambda _: None)
    with pytest.raises(RuntimeError):
        b.next_batch(lambda _: None)


def test_pool_close_fails_parked_submissions():
    s = SimScheduler()
    pool, _ = make_pool(s, pool_size=1)
    results = []
    pool.submit(req(1))
    pool.submit(req(2), results.append)
    pool.close()
    assert results == ["pool closed"]
    pool.submit(req(3), results.append)
    assert results[-1] == "pool closed"


def test_delivered_while_parked_request_not_readmitted():
    # A request parked behind a full pool gets delivered via the leader in
    # the meantime: the batch removal must block its re-admission (else it
    # lingers forever and its cascade triggers a spurious complaint).
    s = SimScheduler()
    pool, handler = make_pool(s, pool_size=2, submit_timeout=30.0)
    pool.submit(req(1))
    pool.submit(req(2))
    parked = []
    pool.submit(req(3), parked.append)  # parked: pool is full
    assert parked == []

    # The leader's batch [1, 2, 3] commits; all three are removed — 3 was
    # never admitted here but must still be blocked from re-admission.
    removed = pool.remove_requests(
        [RequestInfo("c", "1"), RequestInfo("c", "2"), RequestInfo("c", "3")]
    )
    assert removed == 2
    assert parked == ["request already exists"]
    assert pool.count == 0
    s.advance(100.0)
    assert handler.events == [], "stale parked request fired its cascade"


def test_deleted_refresh_keeps_gc_order():
    # Refreshing a dedup entry must move it to the back of the retention
    # queue, or the GC's stop-at-first-fresh scan strands expired entries.
    s = SimScheduler()
    pool, _ = make_pool(s, pool_size=4, auto_remove_timeout=1000.0)
    pool.submit(req(1))
    pool.remove_request(RequestInfo("c", "1"))  # deleted at t=0
    s.advance(3.0)
    pool.submit(req(2))
    pool.remove_request(RequestInfo("c", "2"))  # deleted at t=3
    s.advance(1.0)
    pool.remove_request(RequestInfo("c", "1"))  # refresh at t=4 (absent key)
    s.advance(4.5)  # t=8.5: entry 2 (t=3) is expired, entry 1 (t=4) is not
    results = []
    pool.submit(req(2), results.append)  # triggers GC; 2 must be admittable
    assert results == [None], f"expired dedup entry was retained: {results}"


def test_prune_batch_validates_pool_in_one_call():
    """maybe_prune_revoked_requests drains the re-validation burst through
    verify_requests_batch — ONE batch call for the whole pool, not the
    reference's per-request loop (reference controller.go:733-746)."""
    from consensus_tpu.core.pool import PoolOptions, RequestPool
    from consensus_tpu.runtime.scheduler import SimScheduler
    from consensus_tpu.testing.app import ByteInspector, make_request

    sched = SimScheduler()
    pool = RequestPool(sched, ByteInspector(), PoolOptions(pool_size=100))
    for i in range(10):
        pool.submit(make_request("c", i))  # admission is synchronous
    assert len(pool._fifo) == 10

    calls = []

    def keep_batch(raws):
        calls.append(len(raws))
        # Drop odd-indexed requests.
        return [i % 2 == 0 for i in range(len(raws))]

    pool.prune_batch(keep_batch)
    assert calls == [10], "expected exactly one whole-pool batch call"
    assert len(pool._fifo) == 5
