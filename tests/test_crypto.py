"""TPU crypto engine tests (run on the CPU JAX backend): field arithmetic
against Python big-int, RFC 8032 vectors, batch verification against the
``cryptography`` package, the Verifier-port adapter, and the coalescer.
"""

import hashlib
import random

import numpy as np
import pytest

import jax.numpy as jnp

from consensus_tpu.models import (
    BatchCoalescer,
    Ed25519BatchVerifier,
    Ed25519Signer,
    Ed25519VerifierMixin,
)
from consensus_tpu.ops import ed25519 as ed
from consensus_tpu.ops import field25519 as fe
from consensus_tpu.runtime import SimScheduler
from consensus_tpu.types import Proposal, Signature


def limbs_of(values):
    # Device layout: limbs leading, batch trailing.
    return jnp.asarray(np.stack([fe.int_to_limbs(v) for v in values], axis=1))


def ints_of(arr):
    frozen = np.asarray(fe.freeze(arr))
    return [fe.limbs_to_int(frozen[:, i]) for i in range(frozen.shape[1])]


class TestField:
    def test_mul_add_sub_match_bigint(self):
        rng = random.Random(7)
        a_vals = [rng.randrange(fe.P) for _ in range(16)] + [0, 1, fe.P - 1, fe.P - 19]
        b_vals = [rng.randrange(fe.P) for _ in range(16)] + [fe.P - 1, 0, fe.P - 1, 2]
        a, b = limbs_of(a_vals), limbs_of(b_vals)
        assert ints_of(fe.mul(a, b)) == [(x * y) % fe.P for x, y in zip(a_vals, b_vals)]
        assert ints_of(fe.add(a, b)) == [(x + y) % fe.P for x, y in zip(a_vals, b_vals)]
        assert ints_of(fe.sub(a, b)) == [(x - y) % fe.P for x, y in zip(a_vals, b_vals)]

    def test_deep_mul_chain_stays_exact(self):
        # Repeated squaring: any normalization bug compounds and is caught.
        rng = random.Random(9)
        vals = [rng.randrange(fe.P) for _ in range(4)]
        x = limbs_of(vals)
        want = vals
        for _ in range(50):
            x = fe.mul(x, x)
            want = [w * w % fe.P for w in want]
        assert ints_of(x) == want

    def test_mixed_op_chains_with_borrows(self):
        # Long random add/sub/mul chains: exercises the negative-limb
        # (borrow) representations the parallel relaxed carries produce.
        rng = random.Random(11)
        vals = [rng.randrange(fe.P) for _ in range(8)]
        other = [rng.randrange(fe.P) for _ in range(8)]
        x, y = limbs_of(vals), limbs_of(other)
        wx, wy = list(vals), list(other)
        for step in range(60):
            op = step % 3
            if op == 0:
                x = fe.sub(x, y)
                wx = [(a - b) % fe.P for a, b in zip(wx, wy)]
            elif op == 1:
                x = fe.mul(x, y)
                wx = [(a * b) % fe.P for a, b in zip(wx, wy)]
            else:
                y = fe.sub(y, x)
                wy = [(b - a) % fe.P for a, b in zip(wx, wy)]
        assert ints_of(x) == wx and ints_of(y) == wy

    def test_freeze_handles_borrowed_negatives(self):
        # sub(0, small) yields a weakly-reduced value with negative limbs;
        # freeze must still canonicalize it.
        zero = limbs_of([0, 0, 0])
        small = limbs_of([1, 19, fe.P - 1])
        d = fe.sub(zero, small)
        assert ints_of(d) == [(fe.P - 1), (fe.P - 19), 1]


    def test_raw_ops_stay_exact_at_bound(self):
        # One raw add/sub level feeding mul must stay bit-exact: drive the
        # worst-case limb magnitudes the curve formulas produce.
        rng = random.Random(21)
        vals = [rng.randrange(fe.P) for _ in range(8)]
        others = [rng.randrange(fe.P) for _ in range(8)]
        x, y = limbs_of(vals), limbs_of(others)
        for _ in range(10):
            s = fe.add_raw(x, y)        # <= 680 per limb
            d = fe.sub_raw(x, y)        # in [-345, 600]
            prod = fe.mul(s, d)         # raw x raw multiply
            want = [((a + b) * (a - b)) % fe.P for a, b in zip(vals, others)]
            assert ints_of(prod) == want
            x, vals = prod, want
            y = fe.mul(y, y)
            others = [b * b % fe.P for b in others]

    def test_square_matches_mul(self):
        rng = random.Random(23)
        vals = [rng.randrange(fe.P) for _ in range(8)] + [0, 1, fe.P - 1]
        x = limbs_of(vals)
        assert ints_of(fe.square(x)) == ints_of(fe.mul(x, x)) == [
            v * v % fe.P for v in vals
        ]


    def test_exactness_at_synthetic_limb_extremes(self):
        # Drive mul/square at the DOCUMENTED limb bounds directly (random
        # canonical inputs never reach them): raw-level operands at +-680 /
        # -345..600 per limb, squaring at its 500 bound.
        def arr(limb_values):
            a = np.tile(np.array(limb_values, dtype=np.float32)[:, None], (1, 2))
            return jnp.asarray(a)

        def as_int(a):
            col = np.asarray(a, dtype=np.int64)[:, 0]
            return sum(int(col[i]) << (8 * i) for i in range(32))

        hi = arr([680] * 32)                      # max add_raw output
        lo = arr([-345, 600] * 16)                # extreme sub_raw output
        want = (as_int(hi) * as_int(lo)) % fe.P
        assert ints_of(fe.mul(hi, lo))[0] == want

        sq_in = arr([500, -500] * 16)             # square() bound
        want_sq = (as_int(sq_in) ** 2) % fe.P
        assert ints_of(fe.square(sq_in))[0] == want_sq

        # Reduction domain: _weak_reduce must handle the worst fold output.
        big = arr([2**21] * 32)
        assert ints_of(fe.add(big, big * 0))[0] == as_int(big) % fe.P

    def test_invert(self):
        vals = [3, 12345, fe.P - 2, 2**200 + 7]
        inv = fe.invert(limbs_of(vals))
        assert ints_of(inv) == [pow(v, fe.P - 2, fe.P) for v in vals]

    def test_freeze_canonicalizes(self):
        # p and 2p-1 etc. must freeze to their canonical residues.
        raw = [fe.P, fe.P + 5, 2 * fe.P - 1, 0, 1]
        arr = jnp.asarray(np.stack([fe.int_to_limbs(v) for v in raw], axis=1))
        assert ints_of(arr) == [v % fe.P for v in raw]


class TestPoints:
    def test_base_point_on_curve_and_order(self):
        # 2B computed by add(B, B) and double(B) must agree.
        b = ed.base_point(())
        d1 = ed.double(b)
        d2 = ed.add(b, b)
        assert bool(ed.equal(d1, d2))

    def test_identity_is_neutral(self):
        b = ed.base_point(())
        assert bool(ed.equal(ed.add(b, ed.identity(())), b))

    def test_negation_cancels(self):
        b = ed.base_point(())
        assert bool(ed.equal(ed.add(b, ed.negate(b)), ed.identity(())))

    def test_decompress_base_point(self):
        # Compressed base point: y with sign bit of x (x_B is even -> 0).
        y = ed._BY
        point, valid = ed.decompress(limbs_of([y]), jnp.asarray([0]))
        assert bool(valid[0])
        assert ints_of(point.x)[0] == ed._BX

    def test_decompress_rejects_non_square(self):
        # y = 2 gives u/v that is not a QR for edwards25519.
        point, valid = ed.decompress(limbs_of([2]), jnp.asarray([0]))
        assert not bool(valid[0])


def make_sigs(n, msg_prefix=b"m"):
    pytest.importorskip("cryptography", reason="reference signer unavailable")
    from cryptography.hazmat.primitives import serialization
    from cryptography.hazmat.primitives.asymmetric.ed25519 import Ed25519PrivateKey

    msgs, sigs, keys = [], [], []
    for i in range(n):
        sk = Ed25519PrivateKey.generate()
        pk = sk.public_key().public_bytes(
            serialization.Encoding.Raw, serialization.PublicFormat.Raw
        )
        m = msg_prefix + b"-%d" % i
        msgs.append(m)
        sigs.append(sk.sign(m))
        keys.append(pk)
    return msgs, sigs, keys


class TestBatchVerifier:
    def test_rfc8032_vectors(self):
        # RFC 8032 §7.1 test vectors 1-3.
        vectors = [
            (  # TEST 1: empty message
                "9d61b19deffd5a60ba844af492ec2cc44449c5697b326919703bac031cae7f60",
                "d75a980182b10ab7d54bfed3c964073a0ee172f3daa62325af021a68f707511a",
                "",
                "e5564300c360ac729086e2cc806e828a84877f1eb8e5d974d873e06522490155"
                "5fb8821590a33bacc61e39701cf9b46bd25bf5f0595bbe24655141438e7a100b",
            ),
            (  # TEST 2: one byte
                "4ccd089b28ff96da9db6c346ec114e0f5b8a319f35aba624da8cf6ed4fb8a6fb",
                "3d4017c3e843895a92b70aa74d1b7ebc9c982ccf2ec4968cc0cd55f12af4660c",
                "72",
                "92a009a9f0d4cab8720e820b5f642540a2b27b5416503f8fb3762223ebdb69da"
                "085ac1e43e15996e458f3613d0f11d8c387b2eaeb4302aeeb00d291612bb0c00",
            ),
            (  # TEST 3: two bytes
                "c5aa8df43f9f837bedb7442f31dcb7b166d38535076f094b85ce3a2e0b4458f7",
                "fc51cd8e6218a1a38da47ed00230f0580816ed13ba3303ac5deb911548908025",
                "af82",
                "6291d657deec24024827e69c3abe01a30ce548a284743a445e3680d7db5ac3ac"
                "18ff9b538d16f290ae67f760984dc6594a7c15e9716ed28dc027beceea1ec40a",
            ),
        ]
        msgs = [bytes.fromhex(m) for _, _, m, _ in vectors]
        keys = [bytes.fromhex(pk) for _, pk, _, _ in vectors]
        sigs = [bytes.fromhex(s) for _, _, _, s in vectors]
        ok = Ed25519BatchVerifier().verify_batch(msgs, sigs, keys)
        assert ok.all()

    def test_valid_batch_and_each_corruption_mode(self):
        msgs, sigs, keys = make_sigs(8)
        v = Ed25519BatchVerifier()
        assert v.verify_batch(msgs, sigs, keys).all()

        bad = list(sigs)
        bad[0] = bytes([sigs[0][0] ^ 1]) + sigs[0][1:]      # flipped R byte
        bad[1] = sigs[1][:32] + bytes(32)                   # S = 0
        bad[2] = b"short"                                   # malformed
        bad[3] = sigs[3][:63] + bytes([sigs[3][63] ^ 0x40])  # flipped S bit
        ok = v.verify_batch(msgs, bad, keys)
        assert not ok[:4].any() and ok[4:].all()

        wrong_msg = [b"x" + m for m in msgs]
        assert not v.verify_batch(wrong_msg, sigs, keys).any()

        swapped = keys[1:] + keys[:1]
        assert not v.verify_batch(msgs, sigs, swapped).any()

    def test_high_s_rejected(self):
        # S >= L must be rejected even if the curve equation would hold.
        from consensus_tpu.models.ed25519 import L

        msgs, sigs, keys = make_sigs(1)
        s = int.from_bytes(sigs[0][32:], "little")
        high_s = s + L
        forged = sigs[0][:32] + high_s.to_bytes(32, "little")
        ok = Ed25519BatchVerifier().verify_batch(msgs, [forged], keys)
        assert not ok[0]

    def test_pow2_padding_returns_exact_length(self):
        msgs, sigs, keys = make_sigs(5)
        ok = Ed25519BatchVerifier(pad_pow2=True).verify_batch(msgs, sigs, keys)
        assert ok.shape == (5,) and ok.all()

    def test_host_fallback_matches_device(self):
        msgs, sigs, keys = make_sigs(4)
        bad = list(sigs)
        bad[2] = bytes(64)
        device = Ed25519BatchVerifier(min_device_batch=1).verify_batch(msgs, bad, keys)
        host = Ed25519BatchVerifier(min_device_batch=100).verify_batch(msgs, bad, keys)
        assert (device == host).all()

    def test_host_and_device_agree_on_edge_case_vectors(self):
        """Known adversarial classes where Ed25519 verifiers diverge
        (non-canonical encodings, S >= L, small-order components): in BFT a
        vote's validity must not depend on which path checked it, so the
        host fallback applies the device kernel's strict pre-checks
        (ADVICE r2: models/ed25519.py:246)."""
        from consensus_tpu.models.ed25519 import L
        from consensus_tpu.ops.field25519 import P

        msgs, sigs, keys = make_sigs(8)
        # 0: non-canonical R (y >= p): p + 1 little-endian, sign bit clear.
        sigs[0] = (P + 1).to_bytes(32, "little") + sigs[0][32:]
        # 1: non-canonical A (y >= p).
        keys[1] = (P + 2).to_bytes(32, "little")
        # 2: S = L exactly (malleability boundary).
        sigs[2] = sigs[2][:32] + L.to_bytes(32, "little")
        # 3: S = L - 1 but otherwise-wrong signature (range-valid, invalid).
        sigs[3] = sigs[3][:32] + (L - 1).to_bytes(32, "little")
        # 4: small-order A: identity point (y=1, x=0).
        keys[4] = (1).to_bytes(32, "little")
        # 5: small-order R: identity encoding as R.
        sigs[5] = (1).to_bytes(32, "little") + sigs[5][32:]
        # 6: A with y = p - 1 but sign bit set (may be a non-square x^2).
        keys[6] = bytes(31) + b"\x80"  # y=0, sign=1
        # 7: left valid as a control.
        device = Ed25519BatchVerifier(min_device_batch=1).verify_batch(msgs, sigs, keys)
        host = Ed25519BatchVerifier(min_device_batch=100).verify_batch(msgs, sigs, keys)
        assert (device == host).all(), (device, host)
        assert device[7] and not device[:3].any()


class _Ed25519OnlyVerifier(Ed25519VerifierMixin):
    """Concrete mixin instance for the signature-path tests."""

    def verify_proposal(self, proposal):
        return []

    def verify_request(self, raw_request):
        raise NotImplementedError

    def verification_sequence(self):
        return 0

    def requests_from_proposal(self, proposal):
        return []


class TestVerifierPort:
    def test_sign_proposal_then_batch_verify_quorum(self):
        signers = {i: Ed25519Signer(i) for i in (1, 2, 3, 4)}
        verifier = _Ed25519OnlyVerifier(
            {i: s.public_bytes for i, s in signers.items()}
        )
        proposal = Proposal(payload=b"batch", metadata=b"md")
        sigs = [signers[i].sign_proposal(proposal, b"aux-%d" % i) for i in (2, 3, 4)]
        results = verifier.verify_consenter_sigs_batch(sigs, proposal)
        assert results == [b"aux-2", b"aux-3", b"aux-4"]

        # Tampered aux breaks the binding (the signature covers it).
        tampered = Signature(id=2, value=sigs[0].value, msg=b"aux-x")
        assert verifier.verify_consenter_sigs_batch([tampered], proposal) == [None]
        # Signature over one proposal does not verify another.
        other = Proposal(payload=b"other")
        assert verifier.verify_consenter_sigs_batch(sigs, other) == [None] * 3

    def test_unknown_signer_rejected(self):
        signer = Ed25519Signer(9)
        verifier = _Ed25519OnlyVerifier({1: Ed25519Signer(1).public_bytes})
        proposal = Proposal(payload=b"p")
        sig = signer.sign_proposal(proposal)
        assert verifier.verify_consenter_sigs_batch([sig], proposal) == [None]

    def test_verify_signature_raw_path(self):
        signer = Ed25519Signer(3)
        verifier = _Ed25519OnlyVerifier({3: signer.public_bytes})
        data = b"view-data-bytes"
        sig = Signature(id=3, value=signer.sign(data), msg=data)
        verifier.verify_signature(sig)  # must not raise
        with pytest.raises(ValueError):
            verifier.verify_signature(Signature(id=3, value=bytes(64), msg=data))


class TestPowChain:
    def test_addition_chain_matches_binary_ladder_and_bigint(self):
        """pow_2_252_m3 (11-mul chain) == pow_const == python pow, incl.
        edge cases 0, 1, p-1, sqrt(-1)."""
        import jax
        import numpy as np

        from consensus_tpu.ops import field25519 as fe

        rng = np.random.default_rng(7)
        vals = [int.from_bytes(rng.bytes(32), "little") % fe.P for _ in range(4)]
        vals += [0, 1, fe.P - 1, fe.SQRT_M1]
        arr = np.stack([fe.int_to_limbs(v) for v in vals]).T.astype(np.float32)
        x = jax.numpy.asarray(arr)
        got = np.asarray(fe.freeze(jax.jit(fe.pow_2_252_m3)(x)))
        for i, v in enumerate(vals):
            assert fe.limbs_to_int(got[:, i]) == pow(v, (fe.P - 5) // 8, fe.P)


class TestCoalescer:
    def test_merges_submissions_into_one_batch(self):
        s = SimScheduler()
        calls = []

        def run(items):
            calls.append(list(items))
            return [x * 2 for x in items]

        c = BatchCoalescer(s, run, window=0.002, max_batch=100)
        got = {}
        c.submit([1, 2], lambda r: got.update(a=list(r)))
        c.submit([3], lambda r: got.update(b=list(r)))
        assert calls == []  # window open, nothing flushed yet
        s.advance(0.002)
        assert calls == [[1, 2, 3]]
        assert got == {"a": [2, 4], "b": [6]}

    def test_max_batch_flushes_early(self):
        s = SimScheduler()
        calls = []
        c = BatchCoalescer(s, lambda items: (calls.append(len(items)), items)[1],
                           window=10.0, max_batch=4)
        c.submit([1, 2], lambda r: None)
        c.submit([3, 4], lambda r: None)
        assert calls == [4]  # flushed without waiting for the window
        assert s.now() == 0.0

    def test_empty_submission_completes_immediately(self):
        s = SimScheduler()
        c = BatchCoalescer(s, lambda items: items, window=1.0)
        out = []
        c.submit([], out.append)
        assert out == [[]]


class TestThreadCoalescer:
    """Cross-thread coalescer (shared-device deployments): merges concurrent
    verify_batch calls from replica threads into single engine launches."""

    class _Fake:
        def __init__(self):
            self.calls = []

        def verify_batch(self, msgs, sigs, keys):
            import numpy as np

            self.calls.append(len(msgs))
            # valid iff sig == b"good"
            return np.array([s == b"good" for s in sigs], dtype=bool)

    def _make(self, **kw):
        from consensus_tpu.models import ThreadCoalescingVerifier

        fake = self._Fake()
        return fake, ThreadCoalescingVerifier(fake, **kw)

    def test_concurrent_callers_merge_and_get_their_slices(self):
        import threading

        fake, v = self._make(window=0.05, max_batch=30)
        results = {}

        def worker(i, sigs):
            results[i] = list(
                v.verify_batch([b"m"] * len(sigs), sigs, [b"k"] * len(sigs))
            )

        patterns = {
            0: [b"good"] * 10,
            1: [b"bad"] * 10,
            2: [b"good", b"bad"] * 5,
        }
        threads = [
            threading.Thread(target=worker, args=(i, p))
            for i, p in patterns.items()
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=5.0)
        # One merged launch (max_batch reached), per-caller slices correct.
        assert fake.calls == [30]
        assert results[0] == [True] * 10
        assert results[1] == [False] * 10
        assert results[2] == [True, False] * 5
        v.close()

    def test_hard_cap_splits_whole_submissions(self):
        import threading

        fake, v = self._make(window=0.01, max_batch=10, hard_cap=15)
        done = []

        def worker():
            done.append(v.verify_batch([b"m"] * 10, [b"good"] * 10, [b"k"] * 10).all())

        threads = [threading.Thread(target=worker) for _ in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=5.0)
        # 10 + 10 > hard_cap 15: two launches, submissions never split.
        assert fake.calls == [10, 10]
        assert done == [True, True]
        v.close()

    def test_engine_error_propagates_to_every_waiter(self):
        import threading

        from consensus_tpu.models import ThreadCoalescingVerifier

        class _Boom:
            def verify_batch(self, m, s, k):
                raise RuntimeError("device fell over")

        v = ThreadCoalescingVerifier(_Boom(), window=0.01, max_batch=4)
        errors = []

        def worker():
            try:
                v.verify_batch([b"m"], [b"s"], [b"k"])
            except RuntimeError as e:
                errors.append(f"{e} / cause: {e.__cause__}")

        threads = [threading.Thread(target=worker) for _ in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=5.0)
        # Each waiter gets its OWN wrapper exception (a shared instance
        # raised from N threads would interleave tracebacks), chaining the
        # original engine failure as __cause__.
        assert len(errors) == 2
        assert all("device fell over" in e for e in errors)
        v.close()

    def test_oversized_submission_is_chunked_not_overlaunched(self):
        fake, v = self._make(window=0.005, max_batch=8, hard_cap=8)
        out = v.verify_batch([b"m"] * 20, [b"good"] * 19 + [b"bad"], [b"k"] * 20)
        assert len(out) == 20
        assert out[:19].all() and not out[19]
        assert max(fake.calls) <= 8  # never beyond the compiled shape
        v.close()

    def test_short_engine_result_errors_instead_of_validating(self):
        import numpy as np
        import pytest

        from consensus_tpu.models import ThreadCoalescingVerifier

        class _Short:
            def verify_batch(self, m, s, k):
                return np.ones(len(m) - 1, dtype=bool)

        v = ThreadCoalescingVerifier(_Short(), window=0.005, max_batch=4)
        with pytest.raises(RuntimeError) as exc_info:
            v.verify_batch([b"m"] * 2, [b"s"] * 2, [b"k"] * 2)
        assert isinstance(exc_info.value.__cause__, ValueError)
        v.close()

    def test_closed_coalescer_rejects_submissions(self):
        import pytest

        fake, v = self._make(window=0.01)
        v.close()
        with pytest.raises(RuntimeError):
            v.verify_batch([b"m"], [b"s"], [b"k"])


class TestWedgedDeviceEscapeHatch:
    """A wedged device (hung TPU tunnel) must not block the replica loop:
    waiters fall back to the engine's host path within ``wait_timeout`` and
    subsequent submissions skip the device queue entirely (VERDICT r3 #3)."""

    class _Hung:
        """Engine whose device path never returns (wedged tunnel) but whose
        host path works."""

        def __init__(self):
            import threading

            self.never = threading.Event()
            self.host_calls = 0

        def verify_batch(self, msgs, sigs, keys):
            self.never.wait()  # wedged forever

        def verify_host(self, msgs, sigs, keys):
            import numpy as np

            self.host_calls += 1
            return np.array([s == b"good" for s in sigs], dtype=bool)

    def test_hung_engine_falls_back_to_host_and_marks_suspect(self):
        import time

        from consensus_tpu.models import ThreadCoalescingVerifier

        fake = self._Hung()
        v = ThreadCoalescingVerifier(fake, window=0.005, wait_timeout=0.15)
        start = time.monotonic()
        out = v.verify_batch([b"m"] * 3, [b"good", b"bad", b"good"], [b"k"] * 3)
        first = time.monotonic() - start
        assert list(out) == [True, False, True]
        assert first < 5.0  # escaped the hang, did not wait forever
        assert v.device_suspect
        # Second call: straight to host, no wait_timeout stall.
        start = time.monotonic()
        out2 = v.verify_batch([b"m"], [b"good"], [b"k"])
        assert time.monotonic() - start < 0.1
        assert out2[0]
        assert fake.host_calls >= 2
        v.close()

    def test_fast_device_error_is_served_by_host_fallback(self):
        from consensus_tpu.models import ThreadCoalescingVerifier

        class _Flaky(self._Hung):
            def verify_batch(self, msgs, sigs, keys):
                raise RuntimeError("device fell over")

        fake = _Flaky()
        v = ThreadCoalescingVerifier(fake, window=0.005, wait_timeout=5.0)
        # No exception: the flusher serves the flush from the host path.
        out = v.verify_batch([b"m"] * 2, [b"good", b"bad"], [b"k"] * 2)
        assert list(out) == [True, False]
        assert v.device_suspect
        v.close()

    def test_probe_recovers_device_after_transient_failure(self):
        import time

        import numpy as np

        from consensus_tpu.models import ThreadCoalescingVerifier

        class _Transient:
            def __init__(self):
                self.fail = True
                self.device_calls = 0

            def verify_batch(self, msgs, sigs, keys):
                self.device_calls += 1
                if self.fail:
                    raise RuntimeError("transient device error")
                return np.array([s == b"good" for s in sigs], dtype=bool)

            def verify_host(self, msgs, sigs, keys):
                return np.array([s == b"good" for s in sigs], dtype=bool)

        fake = _Transient()
        v = ThreadCoalescingVerifier(fake, window=0.005, wait_timeout=5.0)
        v._probe_interval = 0.0  # probe on every suspect-mode call
        assert list(v.verify_batch([b"m"], [b"good"], [b"k"])) == [True]
        assert v.device_suspect
        fake.fail = False
        # Suspect-mode call host-verifies AND enqueues a no-wait probe; the
        # flusher's successful probe flush clears the flag.
        assert list(v.verify_batch([b"m"], [b"good"], [b"k"])) == [True]
        deadline = time.monotonic() + 5.0
        while v.device_suspect and time.monotonic() < deadline:
            time.sleep(0.01)
        assert not v.device_suspect, "successful probe flush should clear suspect"
        before = fake.device_calls
        assert list(v.verify_batch([b"m"], [b"good"], [b"k"])) == [True]
        assert fake.device_calls > before  # back on the device path
        v.close()

    def test_flush_error_reaching_a_waiter_is_retried_on_host_not_raised(self):
        """Regression: when the device flush fails AND the flusher's own
        host attempt hits a transient, the error lands on the waiter —
        which used to raise it out of ``verify_batch``.  With a host twin
        available that is a degrade, not a decision-killer: the waiter
        retries host-side on its own thread and the call completes."""
        import numpy as np

        from consensus_tpu.models import ThreadCoalescingVerifier

        class _DoubleFault:
            def __init__(self):
                self.host_calls = 0

            def verify_batch(self, msgs, sigs, keys):
                raise RuntimeError("device fell over")

            def verify_host(self, msgs, sigs, keys):
                self.host_calls += 1
                if self.host_calls == 1:
                    raise RuntimeError("host transient")
                return np.array([s == b"good" for s in sigs], dtype=bool)

        fake = _DoubleFault()
        v = ThreadCoalescingVerifier(fake, window=0.005, wait_timeout=5.0)
        out = v.verify_batch([b"m"] * 2, [b"good", b"bad"], [b"k"] * 2)
        assert list(out) == [True, False]
        assert fake.host_calls == 2  # flusher's failed try, waiter's retry
        assert v.device_suspect
        v.close()

    def test_coalescers_share_suspect_state_per_engine(self):
        """Two coalescers over the SAME engine share one EngineHealth entry
        (the process-wide registry): a wedge seen by one routes the other
        host-side immediately, without its own wait_timeout stall."""
        from consensus_tpu.models import ThreadCoalescingVerifier

        fake = self._Hung()
        a = ThreadCoalescingVerifier(fake, window=0.005, wait_timeout=0.15)
        b = ThreadCoalescingVerifier(fake, window=0.005, wait_timeout=60.0)
        assert a.health is b.health
        out = a.verify_batch([b"m"], [b"good"], [b"k"])  # wedges, abandons
        assert list(out) == [True]
        assert a.device_suspect and b.device_suspect
        # b answers from host instantly — no 60s flush wait.
        import time

        start = time.monotonic()
        assert list(b.verify_batch([b"m"], [b"bad"], [b"k"])) == [False]
        assert time.monotonic() - start < 5.0
        fake.never.set()  # unwedge so close() doesn't ride out wait_timeout
        a.close()
        b.close()

    def test_probe_pacing_uses_injected_scheduler_clock(self):
        """Suspect re-probes ride the protocol clock when the embedder
        hands one over; only the schedulerless sidecar path reads the
        audited wall clock."""
        from consensus_tpu.models import ThreadCoalescingVerifier
        from consensus_tpu.runtime.scheduler import SimScheduler

        sched = SimScheduler()
        fake = self._Hung()
        v = ThreadCoalescingVerifier(
            fake, window=0.005, wait_timeout=0.15, scheduler=sched
        )
        assert v._probe_clock == sched.now
        v.close()


class TestSharding:
    def test_sharded_matches_single_device(self):
        import jax

        from consensus_tpu.parallel import ShardedEd25519Verifier, make_mesh

        msgs, sigs, keys = make_sigs(12)
        bad = list(sigs)
        bad[5] = bytes(64)
        mesh = make_mesh()
        assert mesh.devices.size == 8  # conftest forces the virtual mesh
        sharded = ShardedEd25519Verifier(mesh).verify_batch(msgs, bad, keys)
        single = Ed25519BatchVerifier().verify_batch(msgs, bad, keys)
        assert (sharded == single).all()
        assert sharded.sum() == 11 and not sharded[5]

    def test_graft_entry_contract(self):
        import importlib
        import sys

        sys.path.insert(0, "/root/repo")
        g = importlib.import_module("__graft_entry__")
        import jax

        fn, args = g.entry()
        out = jax.jit(fn)(*args)
        assert out.shape == (8,) and bool(out[0]) and not bool(out[1])
        g.dryrun_multichip(8)
