"""Quorum, leader selection, blacklist — table-driven.

Coverage model: reference internal/bft/util_test.go (TestQuorum:135,
TestGetLeaderId:165, TestBlacklist:20).
"""

import pytest

from consensus_tpu.utils import (
    compute_blacklist_update,
    compute_quorum,
    get_leader_id,
    prune_blacklist,
)


class TestQuorum:
    # (n, expected_q, expected_f)
    TABLE = [
        (1, 1, 0),
        (2, 2, 0),
        (3, 2, 0),
        (4, 3, 1),
        (5, 4, 1),
        (6, 4, 1),
        (7, 5, 2),
        (8, 6, 2),
        (9, 6, 2),
        (10, 7, 3),
        (11, 8, 3),
        (12, 8, 3),
        (13, 9, 4),
        (22, 15, 7),
        (100, 67, 33),
    ]

    @pytest.mark.parametrize("n,q,f", TABLE)
    def test_table(self, n, q, f):
        assert compute_quorum(n) == (q, f)

    def test_intersection_property(self):
        # Any two quorums of size q among n nodes intersect in >= f+1 nodes.
        for n in range(1, 50):
            q, f = compute_quorum(n)
            assert 2 * q - n >= f + 1

    def test_invalid(self):
        with pytest.raises(ValueError):
            compute_quorum(0)


class TestLeaderSelection:
    NODES = [11, 22, 33, 44]

    def test_static_per_view(self):
        for view in range(10):
            assert (
                get_leader_id(view, 4, self.NODES, leader_rotation=False)
                == self.NODES[view % 4]
            )

    def test_rotation_advances_with_decisions(self):
        # decisions_per_leader=2: leadership hops every 2 decisions.
        got = [
            get_leader_id(
                0, 4, self.NODES,
                leader_rotation=True, decisions_in_view=d, decisions_per_leader=2,
            )
            for d in range(8)
        ]
        assert got == [11, 11, 22, 22, 33, 33, 44, 44]

    def test_rotation_skips_blacklisted(self):
        leader = get_leader_id(
            1, 4, self.NODES,
            leader_rotation=True, decisions_in_view=0, decisions_per_leader=1,
            blacklist=[22, 33],
        )
        assert leader == 44

    def test_all_blacklisted_raises(self):
        with pytest.raises(RuntimeError):
            get_leader_id(
                0, 4, self.NODES,
                leader_rotation=True, decisions_per_leader=1, blacklist=self.NODES,
            )


class TestBlacklist:
    NODES = [1, 2, 3, 4, 5, 6, 7]  # n=7 -> f=2

    def test_view_change_blacklists_skipped_leaders(self):
        # View moved 1 -> 3: leaders of views 1 and 2 get blacklisted
        # (unless one of them is the current leader).
        bl = compute_blacklist_update(
            prev_view=1, prev_seq=5, prev_decisions_in_view=0, prev_blacklist=[],
            current_view=3, current_leader=4,
            n=7, f=2, nodes=self.NODES,
            leader_rotation=True, decisions_per_leader=1000, prepares_from={},
        )
        # leaders of views 1 and 2 (decisions offset 1, dpl=1000): nodes[1]=2, nodes[2]=3.
        assert bl == [2, 3]

    def test_same_view_redemption(self):
        # 3 distinct signers (> f=2) vouch for node 2 -> redeemed.
        bl = compute_blacklist_update(
            prev_view=0, prev_seq=9, prev_decisions_in_view=3, prev_blacklist=[2, 5],
            current_view=0, current_leader=1,
            n=7, f=2, nodes=self.NODES,
            leader_rotation=True, decisions_per_leader=1,
            prepares_from={3: [2], 4: [2], 6: [2, 5], 7: []},
        )
        assert bl == [5]

    def test_capped_at_f(self):
        bl = compute_blacklist_update(
            prev_view=0, prev_seq=3, prev_decisions_in_view=0, prev_blacklist=[1, 2],
            current_view=2, current_leader=5,
            n=7, f=2, nodes=self.NODES,
            leader_rotation=True, decisions_per_leader=1000, prepares_from={},
        )
        assert len(bl) <= 2
        # oldest entries evicted first
        assert 1 not in bl

    def test_prune_removes_departed_nodes(self):
        assert prune_blacklist([9, 2], {}, 2, self.NODES) == [2]

    def test_prune_empty(self):
        assert prune_blacklist([], {1: [2]}, 2, self.NODES) == []
