"""Tests for the deterministic scheduler — ordering, cancellation, virtual
time, and the realtime variant's thread handoff."""

import threading

import pytest

from consensus_tpu.runtime import RealtimeScheduler, SimScheduler


def test_same_time_events_fire_in_scheduling_order():
    s = SimScheduler()
    out = []
    s.call_later(1.0, lambda: out.append("a"))
    s.call_later(1.0, lambda: out.append("b"))
    s.call_later(0.5, lambda: out.append("first"))
    s.run_until_idle()
    assert out == ["first", "a", "b"]
    assert s.now() == 1.0


def test_advance_runs_only_due_events_and_moves_clock_exactly():
    s = SimScheduler()
    out = []
    s.call_later(1.0, lambda: out.append(1))
    s.call_later(2.0, lambda: out.append(2))
    n = s.advance(1.5)
    assert n == 1 and out == [1]
    assert s.now() == 1.5
    s.advance(0.5)
    assert out == [1, 2] and s.now() == 2.0


def test_cancel_prevents_firing():
    s = SimScheduler()
    out = []
    h = s.call_later(1.0, lambda: out.append("x"))
    s.call_later(2.0, lambda: out.append("y"))
    h.cancel()
    assert h.cancelled
    s.run_until_idle()
    assert out == ["y"]


def test_handler_reschedules_itself():
    s = SimScheduler()
    ticks = []

    def tick():
        ticks.append(s.now())
        if len(ticks) < 3:
            s.call_later(10.0, tick)

    s.call_later(10.0, tick)
    s.run_until_idle()
    assert ticks == [10.0, 20.0, 30.0]


def test_post_runs_at_current_time_in_fifo_order():
    s = SimScheduler(start=5.0)
    out = []
    s.post(lambda: out.append("a"))
    s.post(lambda: out.append("b"))
    s.run_until_idle()
    assert out == ["a", "b"]
    assert s.now() == 5.0  # zero-delay events don't move time


def test_run_until_predicate():
    s = SimScheduler()
    out = []
    for i in range(10):
        s.call_later(float(i), lambda i=i: out.append(i))
    assert s.run_until(lambda: len(out) == 3)
    assert out == [0, 1, 2]
    assert not s.run_until(lambda: len(out) == 99, max_time=100.0)


def test_exception_in_handler_does_not_stop_the_world():
    s = SimScheduler()
    out = []
    s.call_later(1.0, lambda: (_ for _ in ()).throw(RuntimeError("boom")))
    s.call_later(2.0, lambda: out.append("survived"))
    s.run_until_idle()
    assert out == ["survived"]


def test_livelock_guard():
    s = SimScheduler()

    def forever():
        s.post(forever)

    s.post(forever)
    with pytest.raises(RuntimeError):
        s.run_until_idle(max_events=100)


def test_determinism_across_runs():
    def scenario():
        s = SimScheduler()
        out = []
        s.call_later(1.0, lambda: (out.append("t1"), s.post(lambda: out.append("p1"))))
        s.call_later(1.0, lambda: out.append("t2"))
        s.call_later(0.5, lambda: s.call_later(0.5, lambda: out.append("nested")))
        s.run_until_idle()
        return out

    assert scenario() == scenario()


def test_realtime_scheduler_executes_on_worker_thread():
    rt = RealtimeScheduler()
    rt.start()
    try:
        done = threading.Event()
        seen = {}

        def job():
            seen["thread"] = threading.current_thread().name
            done.set()

        rt.post(job)
        assert done.wait(timeout=5.0)
        assert seen["thread"] == "consensus-runtime"

        # Delayed + cancelled timers.
        fired = threading.Event()
        h = rt.call_later(30.0, fired.set)
        h.cancel()
        done2 = threading.Event()
        rt.call_later(0.01, done2.set)
        assert done2.wait(timeout=5.0)
        assert not fired.is_set()
    finally:
        rt.stop()
