"""Verification sidecar: n replica processes sharing one device through a
socket front (SURVEY §7 step 9; VERDICT r3 #2 deployment shape).

These tests run server + clients in one process (threads stand in for the
replica processes — the socket boundary is identical); the cross-process
path is exercised by benchmarks/chain_crypto_mp.py.
"""

import threading

import numpy as np
import pytest

from consensus_tpu.net.sidecar import (
    SidecarVerifierClient,
    VerifySidecarServer,
    decode_request,
    encode_request,
)

SECRET = b"test-shared-secret"


class FakeEngine:
    """Valid iff sig == b"good"; counts launches."""

    def __init__(self):
        self.calls = []
        self.lock = threading.Lock()

    def verify_batch(self, msgs, sigs, keys):
        with self.lock:
            self.calls.append(len(msgs))
        return np.array([s == b"good" for s in sigs], dtype=bool)

    def verify_host(self, msgs, sigs, keys):
        return self.verify_batch(msgs, sigs, keys)


def test_request_codec_round_trip():
    msgs = [b"alpha", b"", b"x" * 300]
    sigs = [b"s1", b"good", b"s3"]
    keys = [b"k" * 32, b"", b"q" * 65]
    out = decode_request(encode_request(msgs, sigs, keys))
    assert out == (msgs, sigs, keys)


def test_request_codec_rejects_trailing_bytes():
    buf = encode_request([b"m"], [b"s"], [b"k"]) + b"JUNK"
    with pytest.raises(ValueError):
        decode_request(buf)


@pytest.fixture(params=["tcp", "unix"])
def server_address(request, tmp_path):
    if request.param == "tcp":
        return ("127.0.0.1", 0)
    return str(tmp_path / "sidecar.sock")


def test_round_trip_over_socket(server_address):
    engine = FakeEngine()
    server = VerifySidecarServer(server_address, engine, auth_secret=SECRET)
    server.start()
    try:
        client = SidecarVerifierClient(server.address, auth_secret=SECRET)
        out = client.verify_batch(
            [b"m1", b"m2", b"m3"], [b"good", b"bad", b"good"], [b"k"] * 3
        )
        assert list(out) == [True, False, True]
        # Second request rides the same connection.
        out2 = client.verify_batch([b"m"], [b"good"], [b"k"])
        assert list(out2) == [True]
        client.close()
    finally:
        server.stop()


def test_concurrent_clients_all_get_correct_slices(server_address):
    """Many client processes (threads here; the socket boundary is the same)
    with interleaved requests — every caller gets exactly its own results."""
    engine = FakeEngine()
    server = VerifySidecarServer(server_address, engine, auth_secret=SECRET)
    server.start()
    results = {}
    try:
        def worker(i):
            client = SidecarVerifierClient(server.address, auth_secret=SECRET)
            pattern = [b"good" if (i + j) % 2 == 0 else b"bad" for j in range(20)]
            out = client.verify_batch([b"m"] * 20, pattern, [b"k"] * 20)
            results[i] = (pattern, list(out))
            client.close()

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10.0)
        assert len(results) == 6
        for pattern, out in results.values():
            assert out == [s == b"good" for s in pattern]
    finally:
        server.stop()


def test_sidecar_coalesces_processes_into_one_launch():
    """The deployment thesis: wrap the engine in a ThreadCoalescingVerifier
    and concurrent requests from different connections merge into ONE
    engine launch."""
    from consensus_tpu.models import ThreadCoalescingVerifier

    engine = FakeEngine()
    coalescer = ThreadCoalescingVerifier(engine, window=0.05, max_batch=40)
    server = VerifySidecarServer(("127.0.0.1", 0), coalescer, auth_secret=SECRET)
    server.start()
    results = {}
    try:
        def worker(i):
            client = SidecarVerifierClient(server.address, auth_secret=SECRET)
            out = client.verify_batch([b"m"] * 10, [b"good"] * 10, [b"k"] * 10)
            results[i] = out.all()
            client.close()

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10.0)
        assert all(results.values())
        # 4 x 10 sigs hit max_batch=40: one merged launch.
        assert engine.calls == [40]
    finally:
        coalescer.close()
        server.stop()


def test_engine_error_is_served_as_error_not_disconnect():
    class Boom:
        def verify_batch(self, m, s, k):
            raise RuntimeError("kernel exploded")

    server = VerifySidecarServer(("127.0.0.1", 0), Boom(), auth_secret=SECRET)
    server.start()
    try:
        client = SidecarVerifierClient(server.address, auth_secret=SECRET)
        with pytest.raises(RuntimeError, match="kernel exploded"):
            client.verify_batch([b"m"], [b"s"], [b"k"])
        # The connection survives an engine error (next request still works
        # at the framing level — it errors again, but over the same link).
        with pytest.raises(RuntimeError):
            client.verify_batch([b"m"], [b"s"], [b"k"])
        client.close()
    finally:
        server.stop()


def test_dead_sidecar_falls_back_to_local_engine():
    """VERDICT r3 #3 applied to the process boundary: an unreachable
    sidecar must not wedge the replica — with a local_engine the client
    fails over to host verification."""
    local = FakeEngine()
    client = SidecarVerifierClient(
        ("127.0.0.1", 1), local_engine=local, connect_timeout=0.2
    )
    out = client.verify_batch([b"m", b"m"], [b"good", b"bad"], [b"k"] * 2)
    assert list(out) == [True, False]
    assert local.calls == [2]


def test_dead_sidecar_without_local_engine_raises():
    client = SidecarVerifierClient(("127.0.0.1", 1), connect_timeout=0.2)
    with pytest.raises(OSError):
        client.verify_batch([b"m"], [b"s"], [b"k"])


def test_server_death_mid_flight_fails_over():
    """Kill the server while requests are pending: waiters get a connection
    error and (with a local engine) the batch is still answered."""
    import time

    class Slow:
        def verify_batch(self, m, s, k):
            time.sleep(5.0)
            return np.ones(len(m), dtype=bool)

    local = FakeEngine()
    server = VerifySidecarServer(("127.0.0.1", 0), Slow(), auth_secret=SECRET)
    server.start()
    client = SidecarVerifierClient(
        server.address, local_engine=local, request_timeout=30.0,
        auth_secret=SECRET,
    )
    out = {}

    def worker():
        out["r"] = client.verify_batch([b"m"], [b"good"], [b"k"])

    t = threading.Thread(target=worker)
    t.start()
    time.sleep(0.3)  # request in flight on the server's slow engine
    client.close()  # simulates the link dying
    t.join(timeout=10.0)
    assert not t.is_alive()
    assert list(out["r"]) == [True]  # answered by the local fallback
    server.stop()


def test_send_failure_falls_back_without_deadlock(monkeypatch):
    """A failed SEND (sidecar died; EPIPE) must drop the socket and fall
    back locally — regression: _drop_socket used to be called while holding
    the client lock it re-acquires, wedging every later verify call."""
    import consensus_tpu.net.sidecar as sc

    local = FakeEngine()
    server = VerifySidecarServer(("127.0.0.1", 0), FakeEngine(), auth_secret=SECRET)
    server.start()
    client = SidecarVerifierClient(server.address, local_engine=local, auth_secret=SECRET)
    try:
        assert list(client.verify_batch([b"m"], [b"good"], [b"k"])) == [True]

        orig = sc._write_frame

        def boom(sock, req_id, payload):
            raise OSError("broken pipe")

        monkeypatch.setattr(sc, "_write_frame", boom)
        out = {}

        def worker(key):
            out[key] = list(client.verify_batch([b"m"], [b"bad"], [b"k"]))

        t1 = threading.Thread(target=worker, args=("a",))
        t1.start()
        t1.join(timeout=5.0)
        assert not t1.is_alive(), "client deadlocked on send failure"
        assert out["a"] == [False]

        # A second call must not block on a held lock either, and once
        # sends work again the client reconnects to the sidecar.
        t2 = threading.Thread(target=worker, args=("b",))
        t2.start()
        t2.join(timeout=5.0)
        assert not t2.is_alive(), "client deadlocked after socket drop"
        monkeypatch.setattr(sc, "_write_frame", orig)
        assert list(client.verify_batch([b"m"], [b"good"], [b"k"])) == [True]
    finally:
        client.close()
        server.stop()


def test_wedged_sidecar_marks_suspect_and_probes_back():
    """A TIMED-OUT request (wedged sidecar, hung device call) must not cost
    every later call the full request_timeout: the client marks the sidecar
    suspect, answers from the local engine immediately, and a background
    probe restores sidecar mode once it answers again."""
    import time

    gate = threading.Event()

    class Gated:
        """Blocks until the gate opens (wedged), then serves normally."""

        def verify_batch(self, m, s, k):
            if not gate.wait(timeout=30.0):
                raise RuntimeError("gate never opened")
            return np.array([x == b"good" for x in s], dtype=bool)

    local = FakeEngine()
    server = VerifySidecarServer(("127.0.0.1", 0), Gated(), auth_secret=SECRET)
    server.start()
    client = SidecarVerifierClient(
        server.address, local_engine=local, request_timeout=0.3,
        probe_interval=0.05, auth_secret=SECRET,
    )
    try:
        # First call: stalls request_timeout, falls back, marks suspect.
        out = client.verify_batch([b"m"], [b"good"], [b"k"])
        assert list(out) == [True]
        assert client._suspect

        # Later calls answer locally with NO timeout stall.
        start = time.monotonic()
        out = client.verify_batch([b"m"], [b"bad"], [b"k"])
        assert time.monotonic() - start < 0.2
        assert list(out) == [False]

        # Unwedge the server: the probe clears the flag and sidecar mode
        # resumes.
        gate.set()
        deadline = time.monotonic() + 5.0
        while client._suspect and time.monotonic() < deadline:
            time.sleep(0.02)
        assert not client._suspect, "probe never cleared the suspect flag"
        out = client.verify_batch([b"m"], [b"good"], [b"k"])
        assert list(out) == [True]
    finally:
        client.close()
        server.stop()


# -- hardening (ADVICE r4 / VERDICT r4 #6) ---------------------------------


def test_tcp_server_without_secret_refuses_to_start():
    """Unauthenticated TCP ingress is a free-verification + DoS surface:
    the server refuses the configuration outright."""
    server = VerifySidecarServer(("127.0.0.1", 0), FakeEngine())
    with pytest.raises(ValueError, match="auth_secret"):
        server.start()


def test_wrong_secret_client_is_rejected():
    """A peer that cannot HMAC the nonce is dropped before any frame is
    read; with a local engine the replica still gets its answer."""
    local = FakeEngine()
    remote = FakeEngine()
    server = VerifySidecarServer(("127.0.0.1", 0), remote, auth_secret=SECRET)
    server.start()
    try:
        client = SidecarVerifierClient(
            server.address, local_engine=local, auth_secret=b"not-the-secret",
            request_timeout=2.0,
        )
        out = client.verify_batch([b"m"], [b"good"], [b"k"])
        assert list(out) == [True]
        assert local.calls == [1]       # served by the fallback
        assert remote.calls == []       # never reached the engine
        client.close()
    finally:
        server.stop()


def test_secretless_client_cannot_use_authed_server():
    """A client that skips the handshake entirely never gets service (its
    first frame header is consumed as a bad HMAC answer and the connection
    is closed)."""
    local = FakeEngine()
    remote = FakeEngine()
    server = VerifySidecarServer(("127.0.0.1", 0), remote, auth_secret=SECRET)
    server.start()
    try:
        client = SidecarVerifierClient(
            server.address, local_engine=local, request_timeout=2.0,
        )
        out = client.verify_batch([b"m"], [b"good"], [b"k"])
        assert list(out) == [True]
        assert remote.calls == []
        client.close()
    finally:
        server.stop()


def test_flood_is_bounded_per_connection():
    """max_inflight bounds concurrent worker threads for one connection:
    a flood of pipelined requests backpressures into the socket instead of
    spawning unbounded threads — and every request is still answered."""
    import time

    class Gauge:
        """Tracks peak concurrent verify calls."""

        def __init__(self):
            self.lock = threading.Lock()
            self.live = 0
            self.peak = 0

        def verify_batch(self, m, s, k):
            with self.lock:
                self.live += 1
                self.peak = max(self.peak, self.live)
            time.sleep(0.02)  # hold the slot so concurrency is observable
            with self.lock:
                self.live -= 1
            return np.ones(len(m), dtype=bool)

    gauge = Gauge()
    server = VerifySidecarServer(
        ("127.0.0.1", 0), gauge, auth_secret=SECRET, max_inflight=4
    )
    server.start()
    try:
        client = SidecarVerifierClient(server.address, auth_secret=SECRET)
        outs = {}

        def worker(i):
            outs[i] = client.verify_batch([b"m"], [b"good"], [b"k"]).all()

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(24)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=20.0)
        assert len(outs) == 24 and all(outs.values())
        assert gauge.peak <= 4, f"flood exceeded max_inflight: {gauge.peak}"
        client.close()
    finally:
        server.stop()


def test_oversized_frame_drops_connection_not_server():
    """A frame above max_frame closes that connection; the server keeps
    serving well-behaved peers."""
    import socket as socket_mod
    import struct as struct_mod

    engine = FakeEngine()
    server = VerifySidecarServer(
        ("127.0.0.1", 0), engine, auth_secret=SECRET, max_frame=1024
    )
    server.start()
    try:
        import os as os_mod

        from consensus_tpu.net.sidecar import (
            _CLIENT_PROOF,
            _SERVER_PROOF,
            _hmac256,
            _recv_exact,
        )

        raw = socket_mod.create_connection(tuple(server.address), timeout=5.0)
        raw.settimeout(5.0)
        server_nonce = _recv_exact(raw, 32)
        client_nonce = os_mod.urandom(32)
        raw.sendall(
            client_nonce
            + _hmac256(SECRET, _CLIENT_PROOF, server_nonce, client_nonce)
        )
        proof = _recv_exact(raw, 32)
        assert proof == _hmac256(SECRET, _SERVER_PROOF, server_nonce, client_nonce)
        raw.sendall(struct_mod.pack(">IQ", 1 << 20, 7))  # oversized header
        assert raw.recv(1) == b""  # server hung up (max_frame guard)
        raw.close()

        client = SidecarVerifierClient(server.address, auth_secret=SECRET)
        assert list(client.verify_batch([b"m"], [b"good"], [b"k"])) == [True]
        client.close()
    finally:
        server.stop()


def test_drop_socket_spares_waiters_on_newer_socket():
    """Regression (ADVICE r4): a stale reader thread's _drop_socket must
    only fail waiters registered on ITS socket, not fresh requests on the
    reconnected one."""
    client = SidecarVerifierClient(("127.0.0.1", 1))
    old_sock, new_sock = object(), object()
    old_waiter = {"event": threading.Event(), "body": None, "sock": old_sock}
    new_waiter = {"event": threading.Event(), "body": None, "sock": new_sock}
    client._pending = {1: old_waiter, 2: new_waiter}
    client._sock = new_sock

    class _Closeable:
        def close(self):
            pass

    old = _Closeable()
    old_waiter["sock"] = old
    client._drop_socket(old)
    assert old_waiter["event"].is_set()          # stale waiter failed
    assert not new_waiter["event"].is_set()      # fresh waiter untouched
    assert client._pending == {2: new_waiter}
    assert client._sock is new_sock              # current socket kept


def test_blocked_send_times_out_and_fails_over():
    """Regression (ADVICE r4 medium): a sidecar that accepts but never
    READS must not wedge the sender forever — the socket send timeout
    surfaces, the client marks the sidecar suspect, and the local engine
    answers.  Other verify calls must not be blocked behind the stalled
    send (the send happens outside the client lock)."""
    import socket as socket_mod
    import time

    listener = socket_mod.socket(socket_mod.AF_INET, socket_mod.SOCK_STREAM)
    listener.bind(("127.0.0.1", 0))
    listener.listen(1)
    addr = listener.getsockname()
    local = FakeEngine()
    # No auth (server never reads, so the handshake would stall): use a
    # secretless client against a raw listener.
    client = SidecarVerifierClient(
        addr, local_engine=local, request_timeout=1.0, probe_interval=60.0,
    )
    try:
        big = b"x" * (4 * 1024 * 1024)
        out = {}

        def stalled():
            out["a"] = client.verify_batch([big] * 8, [b"good"] * 8, [b"k"] * 8)

        t = threading.Thread(target=stalled)
        start = time.monotonic()
        t.start()
        t.join(timeout=15.0)
        assert not t.is_alive(), "blocked send never surfaced"
        assert list(out["a"]) == [True] * 8  # answered by the fallback
        # Suspect mode: the next call answers locally without re-stalling.
        start = time.monotonic()
        assert list(client.verify_batch([b"m"], [b"bad"], [b"k"])) == [False]
        assert time.monotonic() - start < 0.5
    finally:
        client.close()
        listener.close()


def test_in_path_forger_cannot_mint_verdicts():
    """A relay that passes the handshake through (it cannot compute the
    session key) and then forges an 'all valid' response must NOT be
    believed: the frame MAC fails, the connection drops, and the replica
    falls back to local verification — forged input never becomes a
    consensus verdict."""
    import socket as socket_mod
    import struct as struct_mod

    engine = FakeEngine()
    server = VerifySidecarServer(("127.0.0.1", 0), engine, auth_secret=SECRET)
    server.start()

    relay = socket_mod.socket(socket_mod.AF_INET, socket_mod.SOCK_STREAM)
    relay.bind(("127.0.0.1", 0))
    relay.listen(1)

    stop = threading.Event()

    def mitm():
        victim, _ = relay.accept()
        upstream = socket_mod.create_connection(tuple(server.address), timeout=5.0)
        victim.settimeout(5.0)
        upstream.settimeout(5.0)
        try:
            # Relay the handshake verbatim: server nonce down, client
            # nonce+proof up, server proof down.  The relay learns nothing
            # usable — the session key needs the shared secret.
            victim.sendall(upstream.recv(32))
            up = b""
            while len(up) < 64:
                up += victim.recv(64 - len(up))
            upstream.sendall(up)
            victim.sendall(upstream.recv(32))
            # Swallow the victim's first request, then FORGE "1 valid".
            victim.recv(65536)
            forged = b"\x00" + b"\x01"
            victim.sendall(struct_mod.pack(">IQ", len(forged), 0) + forged
                           + b"\x00" * 16)  # garbage MAC
            stop.wait(5.0)
        except OSError:
            pass
        finally:
            victim.close()
            upstream.close()

    t = threading.Thread(target=mitm, daemon=True)
    t.start()
    local = FakeEngine()
    client = SidecarVerifierClient(
        relay.getsockname(), local_engine=local, auth_secret=SECRET,
        request_timeout=3.0,
    )
    try:
        out = client.verify_batch([b"m"], [b"bad"], [b"k"])
        # The honest answer (invalid) from the LOCAL engine — never the
        # forged "valid" verdict.
        assert list(out) == [False]
        assert local.calls == [1]
    finally:
        stop.set()
        client.close()
        relay.close()
        server.stop()


def test_idle_connection_survives_io_timeout():
    """The server's per-connection io_timeout bounds SENDS to a non-reading
    peer; an idle (but healthy) connection must NOT be dropped by it — the
    read loop treats frame-boundary timeouts as idle and keeps waiting."""
    import time

    engine = FakeEngine()
    server = VerifySidecarServer(
        ("127.0.0.1", 0), engine, auth_secret=SECRET, io_timeout=0.2
    )
    server.start()
    try:
        client = SidecarVerifierClient(server.address, auth_secret=SECRET)
        assert list(client.verify_batch([b"m"], [b"good"], [b"k"])) == [True]
        time.sleep(1.0)  # several io_timeout periods of silence
        assert list(client.verify_batch([b"m"], [b"bad"], [b"k"])) == [False]
        client.close()
    finally:
        server.stop()


# -- multi-tenant verification service --------------------------------------


TENANTS = {"alpha": b"alpha-secret", "beta": b"beta-secret",
           "gamma": b"gamma-secret", "delta": b"delta-secret"}


def _tenant_client(address, tenant, **kw):
    return SidecarVerifierClient(
        address, auth_secret=TENANTS[tenant], tenant=tenant, **kw
    )


def test_tenant_handshake_round_trip_and_wrong_secret_rejected():
    """Each connection authenticates AS a tenant; a wrong per-tenant secret
    never gets service, and the legacy shared-secret client still works on
    a server configured with both."""
    engine = FakeEngine()
    server = VerifySidecarServer(
        ("127.0.0.1", 0), engine, auth_secret=SECRET, tenants=TENANTS,
        wave_window=0.002,
    )
    server.start()
    try:
        for tenant in ("alpha", "beta"):
            client = _tenant_client(server.address, tenant)
            out = client.verify_batch([b"m", b"m"], [b"good", b"bad"], [b"k"] * 2)
            assert list(out) == [True, False]
            client.close()
        legacy = SidecarVerifierClient(server.address, auth_secret=SECRET)
        assert list(legacy.verify_batch([b"m"], [b"good"], [b"k"])) == [True]
        legacy.close()

        local = FakeEngine()
        impostor = SidecarVerifierClient(
            server.address, auth_secret=b"beta-secret", tenant="alpha",
            local_engine=local, request_timeout=2.0,
        )
        assert list(impostor.verify_batch([b"m"], [b"good"], [b"k"])) == [True]
        assert local.calls == [1], "impostor must be served by its fallback only"
        impostor.close()
    finally:
        server.stop()


def test_four_tenants_share_one_wave_vs_four_private_sidecars():
    """The multi-tenant thesis (pinned metric + test): four tenants'
    concurrent quorum-sized sweeps on ONE shared server coalesce into fewer
    engine launches than four private sidecars serving the same load."""
    from consensus_tpu.metrics import (
        SIDECAR_WAVE_LAUNCHES_KEY,
        SIDECAR_WAVE_SIGNATURES_KEY,
        SIDECAR_WAVE_TENANTS_KEY,
        InMemoryProvider,
        Metrics,
    )
    from consensus_tpu.obs.kernels import TenantAccounting

    def drive(clients):
        """Submit one 10-signature sweep per client, concurrently."""
        outs = {}

        def worker(i, c):
            outs[i] = c.verify_batch([b"m"] * 10, [b"good"] * 10, [b"k"] * 10)

        threads = [
            threading.Thread(target=worker, args=(i, c))
            for i, c in enumerate(clients)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10.0)
        assert len(outs) == len(clients)
        for out in outs.values():
            assert out.all()

    # Shared multi-tenant server: one wave former, one engine.
    provider = InMemoryProvider()
    metrics = Metrics(provider, label_names=("tenant",))
    accounting = TenantAccounting()
    shared_engine = FakeEngine()
    server = VerifySidecarServer(
        ("127.0.0.1", 0), shared_engine, tenants=TENANTS,
        wave_window=0.05, metrics=metrics.sidecar, tenant_accounting=accounting,
    )
    server.start()
    clients = [_tenant_client(server.address, t) for t in sorted(TENANTS)]
    try:
        drive(clients)
    finally:
        for c in clients:
            c.close()
        server.stop()
    shared_launches = len(shared_engine.calls)

    # Four private sidecars: one engine each, same concurrent load.
    private_engines = [FakeEngine() for _ in range(4)]
    servers = [
        VerifySidecarServer(("127.0.0.1", 0), e, auth_secret=SECRET)
        for e in private_engines
    ]
    for s in servers:
        s.start()
    clients = [
        SidecarVerifierClient(s.address, auth_secret=SECRET) for s in servers
    ]
    try:
        drive(clients)
    finally:
        for c in clients:
            c.close()
        for s in servers:
            s.stop()
    private_launches = sum(len(e.calls) for e in private_engines)

    assert private_launches == 4
    assert shared_launches < private_launches, (
        f"shared server did not coalesce: {shared_launches} launches"
    )
    # The pinned metrics agree with the engine's own count.
    dump = provider.dump()
    assert dump[SIDECAR_WAVE_LAUNCHES_KEY]["value"] == shared_launches
    assert dump[SIDECAR_WAVE_SIGNATURES_KEY]["value"] == 40
    assert dump[SIDECAR_WAVE_TENANTS_KEY]["value"] >= 4
    # Per-tenant kernel attribution: every tenant rode its 10 signatures.
    snap = accounting.snapshot()
    assert set(snap) == set(TENANTS)
    for stats in snap.values():
        assert stats["signatures"] == 10 and stats["waves"] >= 1


def test_admission_reject_is_structured_and_never_stalls_other_tenants():
    """A tenant over its queue limit gets an IMMEDIATE structured status-2
    reject (tenant id, queue depth, limit); a concurrent honest tenant's
    wave still launches and completes.  With a local engine the rejected
    tenant falls back locally WITHOUT marking the sidecar suspect."""
    import time

    from consensus_tpu.metrics import (
        SIDECAR_ADMISSION_REJECTS_KEY,
        InMemoryProvider,
        Metrics,
    )
    from consensus_tpu.net.sidecar import TenantAdmissionReject

    provider = InMemoryProvider()
    metrics = Metrics(provider, label_names=("tenant",))
    engine = FakeEngine()
    server = VerifySidecarServer(
        ("127.0.0.1", 0), engine, tenants=TENANTS,
        wave_window=0.02, tenant_queue_limit=16, metrics=metrics.sidecar,
    )
    server.start()
    flooder = _tenant_client(server.address, "alpha")
    honest = _tenant_client(server.address, "beta")
    try:
        outs = {}

        def honest_worker():
            outs["beta"] = honest.verify_batch(
                [b"m"] * 8, [b"good"] * 8, [b"k"] * 8
            )

        t = threading.Thread(target=honest_worker)
        t.start()
        start = time.monotonic()
        with pytest.raises(TenantAdmissionReject) as exc:
            flooder.verify_batch([b"m"] * 20, [b"good"] * 20, [b"k"] * 20)
        reject_latency = time.monotonic() - start
        t.join(timeout=10.0)
        assert outs["beta"].all(), "honest tenant stalled behind the reject"
        assert exc.value.tenant == "alpha"
        assert exc.value.limit == 16
        assert reject_latency < 5.0, "reject must not wait out a stall budget"
        assert not flooder._suspect, "admission reject must not mark suspect"
        assert provider.dump()[SIDECAR_ADMISSION_REJECTS_KEY]["value"] >= 1

        # With a local engine the over-quota tenant degrades gracefully.
        local = FakeEngine()
        fallback = _tenant_client(
            server.address, "alpha", local_engine=local,
        )
        out = fallback.verify_batch([b"m"] * 20, [b"good"] * 20, [b"k"] * 20)
        assert out.all() and local.calls == [20]
        assert not fallback._suspect
        fallback.close()
    finally:
        flooder.close()
        honest.close()
        server.stop()


def test_give_up_queued_raises_structured_sidecar_stall():
    """The client give-up path (budget spent behind a stalled sender,
    wire never touched) must raise the STRUCTURED SidecarQueueStall —
    tenant id, local queue depth, expired budget — and still satisfy the
    legacy QueueStallTimeout isinstance contract."""
    from consensus_tpu.net.sidecar import QueueStallTimeout, SidecarQueueStall

    engine = FakeEngine()
    server = VerifySidecarServer(
        ("127.0.0.1", 0), engine, tenants=TENANTS, wave_window=0.002,
    )
    server.start()
    client = _tenant_client(server.address, "gamma", request_timeout=0.3)
    try:
        # Prime the connection, then hold the write lock so the next call
        # burns its whole budget queued behind a "stalled sender".
        assert client.verify_batch([b"m"], [b"good"], [b"k"]).all()
        client._wlock.acquire()
        try:
            with pytest.raises(QueueStallTimeout) as exc:
                client.verify_batch([b"m"], [b"good"], [b"k"])
        finally:
            client._wlock.release()
        stall = exc.value
        assert isinstance(stall, SidecarQueueStall)
        assert stall.tenant == "gamma"
        assert stall.deadline == pytest.approx(0.3)
        assert stall.queue_depth == 0  # nothing else was in flight
        assert not client._suspect, "queue stall must not mark suspect"
    finally:
        client.close()
        server.stop()


def test_tenant_mode_requires_secret():
    with pytest.raises(ValueError, match="tenant mode requires"):
        SidecarVerifierClient(("127.0.0.1", 1), tenant="alpha")


def test_tenant_isolation_under_chaos_flood():
    """Satellite of the multi-tenant PR: a flooding tenant hammering the
    shared verification service with over-quota sweeps is admission-rejected
    (status 2, bounded queue) while an honest tenant's REAL-crypto consensus
    cluster — running a lossy, delayed, byzantine chaos schedule THROUGH the
    shared sidecar — keeps committing, and the obs ``verify_collapse``
    detector stays silent for every honest node: the flood never starves
    their verify launches."""
    from consensus_tpu.config import ObsConfig
    from consensus_tpu.models import Ed25519BatchVerifier
    from consensus_tpu.net.sidecar import TenantAdmissionReject
    from consensus_tpu.testing.chaos import (
        ChaosAction,
        ChaosEngine,
        ChaosSchedule,
    )

    server = VerifySidecarServer(
        ("127.0.0.1", 0),
        Ed25519BatchVerifier(min_device_batch=10**9),
        tenants={"honest": b"honest-secret", "flood": b"flood-secret"},
        wave_window=0.001,
        tenant_queue_limit=64,
    )
    server.start()

    stop = threading.Event()
    rejects = [0]

    def flood():
        client = SidecarVerifierClient(
            server.address, auth_secret=b"flood-secret", tenant="flood",
            request_timeout=5.0,
        )
        try:
            while not stop.is_set():
                try:
                    client.verify_batch(
                        [b"junk"] * 100, [bytes(64)] * 100, [bytes(32)] * 100
                    )
                except TenantAdmissionReject:
                    rejects[0] += 1
                except Exception:
                    pass
        finally:
            client.close()

    flooder = threading.Thread(target=flood, daemon=True)
    flooder.start()
    try:
        def honest_engine():
            return SidecarVerifierClient(
                server.address, auth_secret=b"honest-secret", tenant="honest",
                local_engine=Ed25519BatchVerifier(min_device_batch=10**9),
            )

        # Loss, delay, and a signature-corrupting byzantine node — but no
        # partition/crash, so any verify_collapse firing could only come
        # from the flood starving honest verify launches.
        schedule = ChaosSchedule(
            seed=23,
            n=4,
            actions=(
                ChaosAction(at=20.0, kind="loss",
                            args={"a": 1, "b": 3, "p": 0.1}),
                ChaosAction(at=30.0, kind="byzantine",
                            args={"node": 4, "rate": 0.5}),
                ChaosAction(at=60.0, kind="delay",
                            args={"a": 2, "b": 4, "d": 0.5}),
                ChaosAction(at=90.0, kind="heal"),
            ),
        )
        result = ChaosEngine(
            schedule, crypto="ed25519", engine_factory=honest_engine,
            obs=ObsConfig(enabled=True, sample_interval=5.0),
        ).run()
    finally:
        stop.set()
        flooder.join(timeout=10.0)
        server.stop()

    assert result.ok, result.violation
    assert rejects[0] > 0, "the flooding tenant was never admission-rejected"
    collapse = [a for a in result.anomalies if a.kind == "verify_collapse"]
    assert not collapse, f"flood starved honest verify launches: {collapse}"


def test_wrong_secret_handshake_flood_never_starves_honest_tenants():
    """ISSUE 20 companion to the admission flood above: this flood never
    AUTHENTICATES — every connection fails the handshake proof outright
    (an outsider guessing secrets, not a tenant over quota).  The hardened
    listener guard strikes each failure as ``bad_hello`` and the honest
    tenant's verifies keep succeeding throughout."""
    from consensus_tpu.net.framing import ListenerGuard
    from consensus_tpu.testing.adversary import AdversarialPeer

    # Honest clients share 127.0.0.1 with the flood, so keep the strike
    # limit above the flood volume: the defense under test here is the
    # strike accounting + per-connection shedding, not the ban.
    guard = ListenerGuard(
        name="sidecar", handshake_timeout=0.5, strike_limit=10_000
    )
    server = VerifySidecarServer(
        ("127.0.0.1", 0), FakeEngine(), auth_secret=SECRET, tenants=TENANTS,
        wave_window=0.002, guard=guard,
    )
    server.start()
    stop = threading.Event()
    flood_events = [0]

    def flood():
        adv = AdversarialPeer(server.address, "sidecar", close_wait=5.0)
        while not stop.is_set():
            try:
                adv.wrong_hmac_flood(1)
                flood_events[0] += 1
            except OSError:
                pass

    flooder = threading.Thread(target=flood, daemon=True)
    flooder.start()
    try:
        client = _tenant_client(server.address, "alpha")
        try:
            for i in range(25):
                pattern = [b"good" if j % 2 else b"bad" for j in range(8)]
                out = client.verify_batch([b"m"] * 8, pattern, [b"k"] * 8)
                assert list(out) == [s == b"good" for s in pattern], (
                    f"honest verify {i} corrupted under handshake flood"
                )
        finally:
            client.close()
    finally:
        stop.set()
        flooder.join(timeout=10.0)
        server.stop()

    assert flood_events[0] > 0, "the flood never ran"
    # Every failed proof was booked as a bad_hello strike, exactly once.
    assert guard.stats.malformed >= flood_events[0]
    assert guard.stats.bans == 0  # under the limit by construction
