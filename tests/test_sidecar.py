"""Verification sidecar: n replica processes sharing one device through a
socket front (SURVEY §7 step 9; VERDICT r3 #2 deployment shape).

These tests run server + clients in one process (threads stand in for the
replica processes — the socket boundary is identical); the cross-process
path is exercised by benchmarks/chain_crypto_mp.py.
"""

import threading

import numpy as np
import pytest

from consensus_tpu.net.sidecar import (
    SidecarVerifierClient,
    VerifySidecarServer,
    decode_request,
    encode_request,
)


class FakeEngine:
    """Valid iff sig == b"good"; counts launches."""

    def __init__(self):
        self.calls = []
        self.lock = threading.Lock()

    def verify_batch(self, msgs, sigs, keys):
        with self.lock:
            self.calls.append(len(msgs))
        return np.array([s == b"good" for s in sigs], dtype=bool)

    def verify_host(self, msgs, sigs, keys):
        return self.verify_batch(msgs, sigs, keys)


def test_request_codec_round_trip():
    msgs = [b"alpha", b"", b"x" * 300]
    sigs = [b"s1", b"good", b"s3"]
    keys = [b"k" * 32, b"", b"q" * 65]
    out = decode_request(encode_request(msgs, sigs, keys))
    assert out == (msgs, sigs, keys)


def test_request_codec_rejects_trailing_bytes():
    buf = encode_request([b"m"], [b"s"], [b"k"]) + b"JUNK"
    with pytest.raises(ValueError):
        decode_request(buf)


@pytest.fixture(params=["tcp", "unix"])
def server_address(request, tmp_path):
    if request.param == "tcp":
        return ("127.0.0.1", 0)
    return str(tmp_path / "sidecar.sock")


def test_round_trip_over_socket(server_address):
    engine = FakeEngine()
    server = VerifySidecarServer(server_address, engine)
    server.start()
    try:
        client = SidecarVerifierClient(server.address)
        out = client.verify_batch(
            [b"m1", b"m2", b"m3"], [b"good", b"bad", b"good"], [b"k"] * 3
        )
        assert list(out) == [True, False, True]
        # Second request rides the same connection.
        out2 = client.verify_batch([b"m"], [b"good"], [b"k"])
        assert list(out2) == [True]
        client.close()
    finally:
        server.stop()


def test_concurrent_clients_all_get_correct_slices(server_address):
    """Many client processes (threads here; the socket boundary is the same)
    with interleaved requests — every caller gets exactly its own results."""
    engine = FakeEngine()
    server = VerifySidecarServer(server_address, engine)
    server.start()
    results = {}
    try:
        def worker(i):
            client = SidecarVerifierClient(server.address)
            pattern = [b"good" if (i + j) % 2 == 0 else b"bad" for j in range(20)]
            out = client.verify_batch([b"m"] * 20, pattern, [b"k"] * 20)
            results[i] = (pattern, list(out))
            client.close()

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10.0)
        assert len(results) == 6
        for pattern, out in results.values():
            assert out == [s == b"good" for s in pattern]
    finally:
        server.stop()


def test_sidecar_coalesces_processes_into_one_launch():
    """The deployment thesis: wrap the engine in a ThreadCoalescingVerifier
    and concurrent requests from different connections merge into ONE
    engine launch."""
    from consensus_tpu.models import ThreadCoalescingVerifier

    engine = FakeEngine()
    coalescer = ThreadCoalescingVerifier(engine, window=0.05, max_batch=40)
    server = VerifySidecarServer(("127.0.0.1", 0), coalescer)
    server.start()
    results = {}
    try:
        def worker(i):
            client = SidecarVerifierClient(server.address)
            out = client.verify_batch([b"m"] * 10, [b"good"] * 10, [b"k"] * 10)
            results[i] = out.all()
            client.close()

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10.0)
        assert all(results.values())
        # 4 x 10 sigs hit max_batch=40: one merged launch.
        assert engine.calls == [40]
    finally:
        coalescer.close()
        server.stop()


def test_engine_error_is_served_as_error_not_disconnect():
    class Boom:
        def verify_batch(self, m, s, k):
            raise RuntimeError("kernel exploded")

    server = VerifySidecarServer(("127.0.0.1", 0), Boom())
    server.start()
    try:
        client = SidecarVerifierClient(server.address)
        with pytest.raises(RuntimeError, match="kernel exploded"):
            client.verify_batch([b"m"], [b"s"], [b"k"])
        # The connection survives an engine error (next request still works
        # at the framing level — it errors again, but over the same link).
        with pytest.raises(RuntimeError):
            client.verify_batch([b"m"], [b"s"], [b"k"])
        client.close()
    finally:
        server.stop()


def test_dead_sidecar_falls_back_to_local_engine():
    """VERDICT r3 #3 applied to the process boundary: an unreachable
    sidecar must not wedge the replica — with a local_engine the client
    fails over to host verification."""
    local = FakeEngine()
    client = SidecarVerifierClient(
        ("127.0.0.1", 1), local_engine=local, connect_timeout=0.2
    )
    out = client.verify_batch([b"m", b"m"], [b"good", b"bad"], [b"k"] * 2)
    assert list(out) == [True, False]
    assert local.calls == [2]


def test_dead_sidecar_without_local_engine_raises():
    client = SidecarVerifierClient(("127.0.0.1", 1), connect_timeout=0.2)
    with pytest.raises(OSError):
        client.verify_batch([b"m"], [b"s"], [b"k"])


def test_server_death_mid_flight_fails_over():
    """Kill the server while requests are pending: waiters get a connection
    error and (with a local engine) the batch is still answered."""
    import time

    class Slow:
        def verify_batch(self, m, s, k):
            time.sleep(5.0)
            return np.ones(len(m), dtype=bool)

    local = FakeEngine()
    server = VerifySidecarServer(("127.0.0.1", 0), Slow())
    server.start()
    client = SidecarVerifierClient(
        server.address, local_engine=local, request_timeout=30.0
    )
    out = {}

    def worker():
        out["r"] = client.verify_batch([b"m"], [b"good"], [b"k"])

    t = threading.Thread(target=worker)
    t.start()
    time.sleep(0.3)  # request in flight on the server's slow engine
    client.close()  # simulates the link dying
    t.join(timeout=10.0)
    assert not t.is_alive()
    assert list(out["r"]) == [True]  # answered by the local fallback
    server.stop()


def test_send_failure_falls_back_without_deadlock(monkeypatch):
    """A failed SEND (sidecar died; EPIPE) must drop the socket and fall
    back locally — regression: _drop_socket used to be called while holding
    the client lock it re-acquires, wedging every later verify call."""
    import consensus_tpu.net.sidecar as sc

    local = FakeEngine()
    server = VerifySidecarServer(("127.0.0.1", 0), FakeEngine())
    server.start()
    client = SidecarVerifierClient(server.address, local_engine=local)
    try:
        assert list(client.verify_batch([b"m"], [b"good"], [b"k"])) == [True]

        orig = sc._write_frame

        def boom(sock, req_id, payload):
            raise OSError("broken pipe")

        monkeypatch.setattr(sc, "_write_frame", boom)
        out = {}

        def worker(key):
            out[key] = list(client.verify_batch([b"m"], [b"bad"], [b"k"]))

        t1 = threading.Thread(target=worker, args=("a",))
        t1.start()
        t1.join(timeout=5.0)
        assert not t1.is_alive(), "client deadlocked on send failure"
        assert out["a"] == [False]

        # A second call must not block on a held lock either, and once
        # sends work again the client reconnects to the sidecar.
        t2 = threading.Thread(target=worker, args=("b",))
        t2.start()
        t2.join(timeout=5.0)
        assert not t2.is_alive(), "client deadlocked after socket drop"
        monkeypatch.setattr(sc, "_write_frame", orig)
        assert list(client.verify_batch([b"m"], [b"good"], [b"k"])) == [True]
    finally:
        client.close()
        server.stop()


def test_wedged_sidecar_marks_suspect_and_probes_back():
    """A TIMED-OUT request (wedged sidecar, hung device call) must not cost
    every later call the full request_timeout: the client marks the sidecar
    suspect, answers from the local engine immediately, and a background
    probe restores sidecar mode once it answers again."""
    import time

    gate = threading.Event()

    class Gated:
        """Blocks until the gate opens (wedged), then serves normally."""

        def verify_batch(self, m, s, k):
            if not gate.wait(timeout=30.0):
                raise RuntimeError("gate never opened")
            return np.array([x == b"good" for x in s], dtype=bool)

    local = FakeEngine()
    server = VerifySidecarServer(("127.0.0.1", 0), Gated())
    server.start()
    client = SidecarVerifierClient(
        server.address, local_engine=local, request_timeout=0.3,
        probe_interval=0.05,
    )
    try:
        # First call: stalls request_timeout, falls back, marks suspect.
        out = client.verify_batch([b"m"], [b"good"], [b"k"])
        assert list(out) == [True]
        assert client._suspect

        # Later calls answer locally with NO timeout stall.
        start = time.monotonic()
        out = client.verify_batch([b"m"], [b"bad"], [b"k"])
        assert time.monotonic() - start < 0.2
        assert list(out) == [False]

        # Unwedge the server: the probe clears the flag and sidecar mode
        # resumes.
        gate.set()
        deadline = time.monotonic() + 5.0
        while client._suspect and time.monotonic() < deadline:
            time.sleep(0.02)
        assert not client._suspect, "probe never cleared the suspect flag"
        out = client.verify_batch([b"m"], [b"good"], [b"k"])
        assert list(out) == [True]
    finally:
        client.close()
        server.stop()
