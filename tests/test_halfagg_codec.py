"""Wire/WAL codec coverage for half-aggregated quorum certs: the
standalone tag-15 message, the v2 cert-carrying envelopes (PrePrepare,
SyncChunk, ViewData), the v3 SavedCommit WAL record, malformed-cert
rejection, the lowest-lossless-version rule (cert_mode="full" traffic
stays bit-for-bit v1), and the ISSUE acceptance bar: half-agg cert bytes
<= 0.55x the full signature tuple at n=16 on the wire, WAL, and
sync-chunk paths.

Kept separate from test_wire.py, which needs the ``cryptography`` package
for its signing fixtures; nothing here does.
"""

import pytest

from consensus_tpu.types import Proposal, QuorumCert, Signature
from consensus_tpu.wire import (
    Commit,
    PreparesFrom,
    PrePrepare,
    Prepare,
    ProposedRecord,
    SavedCommit,
    SyncChunk,
    ViewData,
)
from consensus_tpu.wire.codec import (
    CodecError,
    decode_message,
    decode_saved,
    decode_view_data,
    encode_message,
    encode_prepares_from,
    encode_saved,
    encode_view_data,
    encoded_cert_size,
)

N = 16  # the quorum size the byte-ratio acceptance bar is pinned at


def make_cert(n=N, aux=None):
    """A structurally-valid QuorumCert and its full-tuple twin, carrying
    the aux payload commit signatures actually ride (the prepare-sender
    voter list), identical across signers so the cert dedups it."""
    if aux is None:
        aux = encode_prepares_from(PreparesFrom(ids=tuple(range(1, n + 1))))
    full = tuple(
        Signature(id=i + 1, value=bytes([i + 1]) * 64, msg=aux)
        for i in range(n)
    )
    half = QuorumCert(
        signer_ids=tuple(range(1, n + 1)),
        rs=tuple(bytes([i + 1]) * 32 for i in range(n)),
        s_agg=bytes(32),
        aux_table=(aux,),
        aux_index=(0,) * n,
    )
    return full, half


PROPOSAL = Proposal(payload=b"p", header=b"h", metadata=b"m")


def test_standalone_quorum_cert_round_trips_as_tag_15():
    _, half = make_cert(4)
    buf = encode_message(half)
    assert buf[0] == 2  # a cert on the wire is inherently v2
    assert buf[2] == 15
    assert decode_message(buf) == half


def test_pre_prepare_with_cert_rides_v2_and_round_trips():
    full, half = make_cert(4)
    for cert in (half, full):
        pp = PrePrepare(
            view=1, seq=2, proposal=PROPOSAL, prev_commit_signatures=cert
        )
        buf = encode_message(pp)
        assert buf[0] == (2 if isinstance(cert, QuorumCert) else 1)
        assert decode_message(buf) == pp


def test_sync_chunk_mixes_cert_formats_on_v2():
    """A catch-up chunk from a ledger whose cert_mode flipped mid-history
    carries BOTH formats; one QuorumCert anywhere lifts the chunk to v2."""
    full, half = make_cert(4)
    chunk = SyncChunk(
        from_seq=1, height=2,
        decisions=(PROPOSAL, PROPOSAL),
        quorum_certs=(full, half),
    )
    buf = encode_message(chunk)
    assert buf[0] == 2
    decoded = decode_message(buf)
    assert decoded == chunk
    assert isinstance(decoded.quorum_certs[0], tuple)
    assert isinstance(decoded.quorum_certs[1], QuorumCert)


def test_view_data_cert_proof_round_trips():
    full, half = make_cert(4)
    for cert in (half, full):
        vd = ViewData(
            next_view=3, last_decision=PROPOSAL,
            last_decision_signatures=cert,
        )
        buf = encode_view_data(vd)
        assert buf[0] == (2 if isinstance(cert, QuorumCert) else 1)
        assert decode_view_data(buf) == vd


def test_saved_commit_cert_needs_v3_and_round_trips():
    _, half = make_cert(4)
    commit = Commit(view=0, seq=1, digest="d", signature=Signature(id=1))
    with_cert = SavedCommit(commit=commit, cert=half)
    buf = encode_saved(with_cert)
    assert buf[0] == 3
    assert decode_saved(buf) == with_cert
    # Cert-free records keep their seed version: full-mode WALs are
    # bit-for-bit unchanged by the half-agg feature existing.
    plain = encode_saved(SavedCommit(commit=commit))
    assert plain[0] < 3
    assert decode_saved(plain) == SavedCommit(commit=commit)


def test_full_mode_wire_stays_bit_for_bit_v1():
    full, _ = make_cert(4)
    pp = PrePrepare(view=0, seq=1, proposal=PROPOSAL,
                    prev_commit_signatures=full)
    chunk = SyncChunk(from_seq=1, height=1, decisions=(PROPOSAL,),
                      quorum_certs=(full,))
    for msg in (pp, chunk):
        assert encode_message(msg)[0] == 1


def test_malformed_cert_bodies_rejected():
    _, half = make_cert(4)
    buf = bytearray(encode_message(half))
    buf[3] = 7  # cert bodies are length-framed fields; corrupt the first
    with pytest.raises(CodecError):
        decode_message(bytes(buf))
    with pytest.raises(CodecError):
        decode_message(encode_message(half)[:-3])  # truncated body
    # Parallel-field length mismatch refuses to even encode.
    with pytest.raises(CodecError):
        encode_message(
            QuorumCert(signer_ids=(1, 2), rs=(b"\x00" * 32,),
                       s_agg=bytes(32), aux_table=(b"",), aux_index=(0, 0))
        )
    # aux_index out of range is caught at decode time.
    bad = QuorumCert(signer_ids=(1,), rs=(b"\x00" * 32,), s_agg=bytes(32),
                     aux_table=(b"",), aux_index=(3,))
    with pytest.raises(CodecError):
        decode_message(encode_message(bad))


# --- the 0.55x byte acceptance bar at n=16 ---------------------------------


def test_cert_field_bytes_at_most_055x_full_at_n16():
    full, half = make_cert(N)
    assert encoded_cert_size(half) <= 0.55 * encoded_cert_size(full)


def _carrier_delta(build):
    """Cert-byte contribution to a carrier: encoded size with the cert
    minus the size with an empty cert — isolates the cert payload from
    the unrelated message framing."""
    return len(build(make_cert(N)[0])) - len(build(())), \
        len(build(make_cert(N)[1])) - len(build(()))


def test_wire_pre_prepare_cert_bytes_at_most_055x():
    def build(cert):
        return encode_message(PrePrepare(
            view=0, seq=1, proposal=PROPOSAL, prev_commit_signatures=cert
        ))

    full_delta, half_delta = _carrier_delta(build)
    assert half_delta <= 0.55 * full_delta


def test_wal_proposed_record_cert_bytes_at_most_055x():
    def build(cert):
        return encode_saved(ProposedRecord(
            pre_prepare=PrePrepare(view=0, seq=1, proposal=PROPOSAL,
                                   prev_commit_signatures=cert),
            prepare=Prepare(view=0, seq=1, digest="d"),
        ))

    full_delta, half_delta = _carrier_delta(build)
    assert half_delta <= 0.55 * full_delta


def test_sync_chunk_cert_bytes_at_most_055x():
    def build(cert):
        return encode_message(SyncChunk(
            from_seq=1, height=1, decisions=(PROPOSAL,),
            quorum_certs=(cert,),
        ))

    full_delta, half_delta = _carrier_delta(build)
    assert half_delta <= 0.55 * full_delta


def test_wal_saved_commit_cert_cheaper_than_full_tuple_wire():
    """The cert-bearing SavedCommit twin (decide-time WAL record) must
    cost less than 0.55x what persisting the full tuple would."""
    full, half = make_cert(N)
    commit = Commit(view=0, seq=1, digest="d", signature=Signature(id=1))
    base = len(encode_saved(SavedCommit(commit=commit)))
    with_cert = len(encode_saved(SavedCommit(commit=commit, cert=half)))
    assert with_cert - base <= 0.55 * encoded_cert_size(full)
