"""Scenario matrix: restarts x leader rotation x blacklist churn.

Parity model (reference test/basic_test.go):
TestRestartFollowers:152, TestLeaderProposeAfterRestartWithoutSync:1328,
TestRotateAndViewChange:1600, TestMigrateToBlacklistAndBackAgain:1716,
TestNodeInFlightFails:1834, TestBlacklistMultipleViewChanges:2091,
TestNodeInFlightThenViewChange:2215, TestFollowerStateTransfer:1051.

Each scenario asserts no-fork safety and liveness after the churn settles.
"""

from consensus_tpu.testing import Cluster, make_request
from consensus_tpu.wire import Commit, HeartBeat, Prepare, PrePrepare

FAST = {
    "request_forward_timeout": 1.0,
    "request_complain_timeout": 4.0,
    "request_auto_remove_timeout": 120.0,
    "view_change_resend_interval": 2.0,
    "view_change_timeout": 10.0,
    "leader_heartbeat_timeout": 20.0,
}


def test_restart_followers_one_by_one():
    """Each follower restarts in turn between decisions; every restarted
    node recovers its position from the WAL and keeps delivering.  Parity:
    basic_test.go:152 (TestRestartFollowers)."""
    cluster = Cluster(4, config_tweaks=FAST)
    cluster.start()

    cluster.submit_to_all(make_request("c", 0))
    assert cluster.run_until_ledger(1, max_time=300.0)

    for i, follower in enumerate((2, 3, 4)):
        cluster.nodes[follower].restart()
        cluster.scheduler.advance(30.0)
        cluster.submit_to_all(make_request("c", i + 1))
        assert cluster.run_until_ledger(i + 2, max_time=600.0), (
            f"ordering stalled after restarting follower {follower}"
        )
    cluster.assert_ledgers_consistent()


def test_leader_proposes_after_restart_without_sync():
    """The leader restarts between decisions with nothing to catch up on:
    it must resume proposing straight from its WAL/checkpoint state (no
    sync detour required — nobody moved past it).  Parity:
    basic_test.go:1328 (TestLeaderProposeAfterRestartWithoutSync)."""
    cluster = Cluster(4, config_tweaks=FAST)
    cluster.start()
    cluster.submit_to_all(make_request("c", 0))
    assert cluster.run_until_ledger(1, max_time=300.0)

    cluster.nodes[1].restart()
    cluster.scheduler.advance(30.0)
    cluster.submit_to_all(make_request("c", 1))
    assert cluster.run_until_ledger(2, max_time=600.0), (
        "restarted leader did not resume proposing"
    )
    cluster.assert_ledgers_consistent()


def test_rotate_and_view_change():
    """Leader rotation every decision + a crashed replica: rotation keeps
    handing leadership to the dead node, each time forcing a view change,
    and the cluster still makes steady progress; the node catches up after
    restart.  Parity: basic_test.go:1600 (TestRotateAndViewChange)."""
    cluster = Cluster(
        4, config_tweaks=dict(FAST, decisions_per_leader=1), leader_rotation=True
    )
    cluster.start()
    cluster.submit_to_all(make_request("c", 0))
    assert cluster.run_until_ledger(1, max_time=300.0)

    cluster.nodes[4].crash()
    survivors = [1, 2, 3]
    for i in range(1, 5):
        cluster.submit_to_all(make_request("c", i))
        assert cluster.run_until_ledger(
            i + 1, node_ids=survivors, max_time=900.0
        ), f"rotation+view-change stalled at block {i}"

    cluster.nodes[4].restart()
    cluster.scheduler.advance(120.0)
    cluster.submit_to_all(make_request("c", 9))
    assert cluster.run_until_ledger(6, node_ids=survivors, max_time=900.0)
    cluster.scheduler.advance(120.0)
    assert len(cluster.nodes[4].app.ledger) >= 5, "restarted node did not catch up"
    cluster.assert_ledgers_consistent()


def test_blacklist_churn_across_multiple_view_changes():
    """n=7 rotation with one crashed replica across MANY rotation cycles:
    repeated view changes accrue/maintain the blacklist without wedging
    rotation or forking.  Parity: basic_test.go:2091
    (TestBlacklistMultipleViewChanges), compressed."""
    cluster = Cluster(
        7, config_tweaks=dict(FAST, decisions_per_leader=1), leader_rotation=True
    )
    cluster.start()
    cluster.submit_to_all(make_request("c", 0))
    assert cluster.run_until_ledger(1, max_time=600.0)

    cluster.nodes[3].crash()
    survivors = [1, 2, 4, 5, 6, 7]
    # Two full rotation cycles with the dead replica in the schedule.
    for i in range(1, 15):
        cluster.submit_to_all(make_request("c", i))
        assert cluster.run_until_ledger(
            i + 1, node_ids=survivors, max_time=900.0
        ), f"blacklist churn stalled at block {i}"
    cluster.assert_ledgers_consistent()


def test_in_flight_proposal_when_leader_fails_before_any_commit():
    """The leader gets a proposal prepared on the followers but dies before
    ANY commit lands: the view change must either re-commit it (if f+1
    prepared) or drop it — consistently — and the next leader orders new
    work.  Parity: basic_test.go:1834 (TestNodeInFlightFails)."""
    cluster = Cluster(4, config_tweaks=FAST)
    cluster.start()
    cluster.submit_to_all(make_request("c", 0))
    assert cluster.run_until_ledger(1, max_time=300.0)

    # Block every Commit: the next proposal can prepare but never commit.

    def drop_all_commits(sender, target, msg):
        if isinstance(msg, Commit):
            return None
        return msg

    cluster.network.mutate_send = drop_all_commits
    cluster.submit_to_all(make_request("c", 1))
    cluster.scheduler.advance(6.0)  # enough for pre-prepare + prepares
    assert all(len(n.app.ledger) == 1 for n in cluster.nodes.values())

    cluster.nodes[1].crash()
    cluster.network.mutate_send = None

    # Survivors: the prepared in-flight proposal resolves through the view
    # change, then ordering continues.
    assert cluster.run_until_ledger(2, node_ids=[2, 3, 4], max_time=900.0), (
        "in-flight proposal did not resolve after leader failure"
    )
    cluster.submit_to_all(make_request("c", 2))
    assert cluster.run_until_ledger(3, node_ids=[2, 3, 4], max_time=900.0)
    cluster.assert_ledgers_consistent()


def test_in_flight_partial_prepare_then_view_change():
    """Only SOME followers saw the in-flight proposal's prepares when the
    leader dies (prepares to one follower dropped): check_in_flight must
    still resolve consistently across the survivors.  Parity:
    basic_test.go:2215 (TestNodeInFlightThenViewChange)."""

    cluster = Cluster(4, config_tweaks=FAST)
    cluster.start()
    cluster.submit_to_all(make_request("c", 0))
    assert cluster.run_until_ledger(1, max_time=300.0)

    def drop_commits_and_prepares_to_4(sender, target, msg):
        if isinstance(msg, Commit):
            return None
        if target == 4 and isinstance(msg, Prepare):
            return None
        return msg

    cluster.network.mutate_send = drop_commits_and_prepares_to_4
    cluster.submit_to_all(make_request("c", 1))
    cluster.scheduler.advance(6.0)

    cluster.nodes[1].crash()
    cluster.network.mutate_send = None
    assert cluster.run_until_ledger(2, node_ids=[2, 3, 4], max_time=900.0), (
        "partially-prepared in-flight proposal did not resolve"
    )
    cluster.submit_to_all(make_request("c", 2))
    assert cluster.run_until_ledger(3, node_ids=[2, 3, 4], max_time=900.0)
    cluster.assert_ledgers_consistent()


def test_follower_state_transfer_from_far_behind():
    """A follower down through MANY decisions rejoins and state-transfers
    the whole gap, then participates in new quorums.  Parity:
    basic_test.go:1051 (TestFollowerStateTransfer)."""
    cluster = Cluster(4, config_tweaks=FAST)
    cluster.start()
    cluster.submit_to_all(make_request("c", 0))
    assert cluster.run_until_ledger(1, max_time=300.0)

    cluster.nodes[4].crash()
    for i in range(1, 8):
        cluster.submit_to_all(make_request("c", i))
        assert cluster.run_until_ledger(i + 1, node_ids=[1, 2, 3], max_time=600.0)

    cluster.nodes[4].restart()
    cluster.scheduler.advance(120.0)
    # Stop node 3: further quorums need the freshly-synced node 4.
    cluster.nodes[3].crash()
    cluster.submit_to_all(make_request("c", 99))
    assert cluster.run_until_ledger(9, node_ids=[1, 2, 4], max_time=900.0), (
        "state-transferred follower is not participating in quorums"
    )
    assert len(cluster.nodes[4].app.ledger) >= 9
    cluster.assert_ledgers_consistent()


def test_leader_excludes_one_follower():
    """The leader's link to ONE follower is cut (pairwise): the excluded
    follower must detect it is being left behind (heartbeat gap) and catch
    up through its peers while the cluster keeps ordering.  Parity:
    basic_test.go:891 (TestLeaderExclusion)."""
    cluster = Cluster(4, config_tweaks=FAST)
    cluster.start()
    cluster.submit_to_all(make_request("c", 0))
    assert cluster.run_until_ledger(1, max_time=300.0)

    cluster.network.disconnect_pair(1, 4)
    for i in range(1, 6):
        cluster.submit_to_all(make_request("c", i))
        assert cluster.run_until_ledger(
            i + 1, node_ids=[1, 2, 3], max_time=600.0
        )
    # Node 4 hears prepares/commits from 2 and 3 (and heartbeat gaps) and
    # must close the distance without the leader's direct traffic.
    assert cluster.scheduler.run_until(
        lambda: len(cluster.nodes[4].app.ledger) >= 6, max_time=900.0
    ), "excluded follower never caught up"
    cluster.assert_ledgers_consistent()


def test_leader_catches_up_without_full_sync():
    """The leader proposes seq 2 but every Commit addressed to IT is lost;
    the followers deliver.  After a restart the leader restores its
    prepared state from the WAL and closes the gap.  Parity:
    basic_test.go:1258 (TestLeaderCatchUpWithoutSync)."""

    cluster = Cluster(4, config_tweaks=FAST)
    cluster.start()
    cluster.submit_to_all(make_request("c", 0))
    assert cluster.run_until_ledger(1, max_time=300.0)

    def drop_commits_to_leader(sender, target, msg):
        if target == 1 and isinstance(msg, Commit):
            return None
        return msg

    cluster.network.mutate_send = drop_commits_to_leader
    cluster.submit_to_all(make_request("c", 1))
    assert cluster.run_until_ledger(2, node_ids=[2, 3, 4], max_time=600.0), (
        "followers failed to deliver while the leader was commit-starved"
    )
    assert len(cluster.nodes[1].app.ledger) == 1

    cluster.network.mutate_send = None
    cluster.nodes[1].restart()
    assert cluster.scheduler.run_until(
        lambda: len(cluster.nodes[1].app.ledger) >= 2, max_time=900.0
    ), "restarted leader never recovered the commit-starved decision"
    cluster.submit_to_all(make_request("c", 2))
    assert cluster.run_until_ledger(3, max_time=900.0)
    cluster.assert_ledgers_consistent()


def test_behind_follower_heartbeat_gap_triggers_sync():
    """A follower whose ordering traffic is filtered (but that still sees
    heartbeats) must notice the leader's sequence running ahead and sync —
    without a restart.  Parity: basic_test.go:925/971
    (TestCatchingUpWithSyncAssisted / Autonomous)."""

    cluster = Cluster(4, config_tweaks=FAST)
    cluster.start()
    cluster.submit_to_all(make_request("c", 0))
    assert cluster.run_until_ledger(1, max_time=300.0)

    def starve_4(sender, target, msg):
        if target == 4 and isinstance(msg, (PrePrepare, Prepare, Commit)):
            return None
        return msg

    cluster.network.mutate_send = starve_4
    for i in range(1, 4):
        cluster.submit_to_all(make_request("c", i))
        assert cluster.run_until_ledger(
            i + 1, node_ids=[1, 2, 3], max_time=600.0
        )
    assert len(cluster.nodes[4].app.ledger) == 1

    cluster.network.mutate_send = None
    assert cluster.scheduler.run_until(
        lambda: len(cluster.nodes[4].app.ledger) >= 4, max_time=900.0
    ), "starved follower never synced from the heartbeat gap"
    cluster.assert_ledgers_consistent()


def test_restart_after_view_change_lands_in_current_view():
    """A node that slept through a view change restarts with pre-change
    state; its sync returns decisions stamped with the OLD view (nothing
    was ordered in the new one yet), so the state-transfer round must carry
    it into the CURRENT view before it can participate.  Parity:
    basic_test.go:2742 (TestFetchStateWhenSyncReturnsPrevView)."""
    cluster = Cluster(4, config_tweaks=FAST)
    cluster.start()
    cluster.submit_to_all(make_request("c", 0))
    assert cluster.run_until_ledger(1, max_time=300.0)

    # Node 4 sleeps through everything from here.
    cluster.nodes[4].crash()

    # Depose leader 1 WITHOUT killing it (mute its heartbeats): 1, 2 and 3
    # can then complete the view change — and no decision lands in the new
    # view, so every synced decision stays stamped with view 0.
    view_before = cluster.nodes[2].consensus.controller.curr_view_number

    def mute_leader_heartbeats(sender, target, msg):
        if sender == 1 and isinstance(msg, HeartBeat):
            return None
        return msg

    cluster.network.mutate_send = mute_leader_heartbeats
    assert cluster.scheduler.run_until(
        lambda: cluster.nodes[2].consensus.controller.curr_view_number
        > view_before,
        max_time=600.0,
    ), "view change away from the muted leader never completed"
    cluster.network.mutate_send = None

    # Restart node 4: its sync returns only view-0 decisions; the state
    # transfer must still land it in the CURRENT view.
    cluster.nodes[4].restart()
    cluster.scheduler.advance(120.0)

    # Crash node 1: the quorum for new work is now {2, 3, 4}, so progress
    # proves node 4 made it into the post-change view.
    cluster.nodes[1].crash()
    cluster.submit_to_all(make_request("c", 1))
    assert cluster.scheduler.run_until(
        lambda: all(
            len(cluster.nodes[i].app.ledger) >= 2 for i in (2, 3, 4)
        ),
        max_time=900.0,
    ), "restarted node never joined the post-view-change quorum"
    cluster.assert_ledgers_consistent()
