"""WAL tests: round trips, segment rollover, truncation, corruption
detection, and the torn-write property test (truncate the tail segment at
every byte offset, repair, and confirm a valid prefix survives).

Parity model: reference pkg/wal/writeaheadlog_test.go (temp-dir file I/O,
CRC corruption injection, torn-write repair, segment rollover).
"""

import os

import pytest

from consensus_tpu.wal import (
    QUARANTINE_DIRNAME,
    CorruptLogError,
    WALError,
    WalScrubber,
    WriteAheadLog,
    initialize_and_read_all,
    quarantine,
    repair,
)


def entries_of(n, size=24):
    return [bytes([i % 256]) * size for i in range(1, n + 1)]


def test_create_append_read_round_trip(tmp_path):
    d = str(tmp_path / "wal")
    wal = WriteAheadLog.create(d)
    data = entries_of(10)
    for e in data:
        wal.append(e)
    assert wal.read_all() == data
    wal.close()
    # Reopen and continue appending.
    wal2 = WriteAheadLog.open_(d)
    wal2.append(b"after-reopen")
    assert wal2.read_all() == data + [b"after-reopen"]
    wal2.close()


def test_create_refuses_existing_log(tmp_path):
    d = str(tmp_path / "wal")
    WriteAheadLog.create(d).close()
    with pytest.raises(WALError):
        WriteAheadLog.create(d)


def test_segment_rollover_preserves_entries(tmp_path):
    d = str(tmp_path / "wal")
    wal = WriteAheadLog.create(d, segment_max_bytes=256)
    data = entries_of(40)
    for e in data:
        wal.append(e)
    segments = [f for f in os.listdir(d) if f.endswith(".wal")]
    assert len(segments) > 3, "expected multiple segments"
    assert wal.read_all() == data
    wal.close()
    assert WriteAheadLog.open_(d).read_all() == data


def test_truncate_to_drops_older_segments(tmp_path):
    d = str(tmp_path / "wal")
    wal = WriteAheadLog.create(d, segment_max_bytes=256)
    for e in entries_of(30):
        wal.append(e)
    before = len([f for f in os.listdir(d) if f.endswith(".wal")])
    wal.append(b"stable-point", truncate_to=True)
    after = len([f for f in os.listdir(d) if f.endswith(".wal")])
    assert after < before
    # A restore point retires everything before it — even records that share
    # its segment (reference pkg/wal/writeaheadlog.go:549-551).
    assert wal.read_all() == [b"stable-point"]
    wal.append(b"next")
    assert wal.read_all() == [b"stable-point", b"next"]
    wal.close()
    # Reopened log reads the same surviving suffix.
    assert WriteAheadLog.open_(d).read_all() == [b"stable-point", b"next"]


def test_bit_flip_detected(tmp_path):
    d = str(tmp_path / "wal")
    wal = WriteAheadLog.create(d)
    for e in entries_of(5):
        wal.append(e)
    wal.close()
    seg = sorted(f for f in os.listdir(d) if f.endswith(".wal"))[0]
    path = os.path.join(d, seg)
    buf = bytearray(open(path, "rb").read())
    buf[len(buf) // 2] ^= 0xFF
    open(path, "wb").write(bytes(buf))
    with pytest.raises(CorruptLogError):
        WriteAheadLog(d).read_all()


def test_torn_write_repair_at_every_offset(tmp_path):
    # Property test: crash mid-write at any byte boundary must leave a log
    # that repairs to an intact prefix of what was appended.
    d = str(tmp_path / "wal")
    wal = WriteAheadLog.create(d)
    data = entries_of(6, size=10)
    for e in data:
        wal.append(e)
    wal.close()
    seg = sorted(f for f in os.listdir(d) if f.endswith(".wal"))[-1]
    path = os.path.join(d, seg)
    full = open(path, "rb").read()

    for cut in range(len(full)):
        open(path, "wb").write(full[:cut])
        wal2, entries = initialize_and_read_all(d)
        wal2.close()
        assert entries == data[: len(entries)], f"not a prefix at cut={cut}"
        # The repaired log must accept new appends.
        wal3 = WriteAheadLog.open_(d)
        wal3.append(b"post-repair")
        assert wal3.read_all() == entries + [b"post-repair"]
        wal3.close()
        # Restore for the next iteration.
        for f in os.listdir(d):
            if f.endswith(".bak"):
                os.unlink(os.path.join(d, f))
        open(path, "wb").write(full)


def test_torn_write_across_segments(tmp_path):
    d = str(tmp_path / "wal")
    wal = WriteAheadLog.create(d, segment_max_bytes=200)
    data = entries_of(12, size=16)
    for e in data:
        wal.append(e)
    wal.close()
    segs = sorted(f for f in os.listdir(d) if f.endswith(".wal"))
    assert len(segs) >= 2
    # Tear the last segment down to one byte.
    last = os.path.join(d, segs[-1])
    open(last, "r+b").truncate(1)
    wal2, entries = initialize_and_read_all(d)
    assert entries == data[: len(entries)]
    assert len(entries) > 0
    wal2.close()


def test_repair_noop_on_healthy_log(tmp_path):
    d = str(tmp_path / "wal")
    wal = WriteAheadLog.create(d)
    for e in entries_of(3):
        wal.append(e)
    wal.close()
    repair(d)
    assert WriteAheadLog.open_(d).read_all() == entries_of(3)


def test_initialize_creates_fresh_log(tmp_path):
    d = str(tmp_path / "wal")
    wal, entries = initialize_and_read_all(d)
    assert entries == []
    wal.append(b"x")
    assert wal.read_all() == [b"x"]
    wal.close()
    wal2, entries2 = initialize_and_read_all(d)
    assert entries2 == [b"x"]
    wal2.close()


def test_append_after_close_fails(tmp_path):
    d = str(tmp_path / "wal")
    wal = WriteAheadLog.create(d)
    wal.close()
    with pytest.raises(WALError):
        wal.append(b"x")


def test_corrupt_anchor_length_detected_not_crash(tmp_path):
    # A bit-flip in an anchor's length field must surface as CorruptLogError
    # (repairable), not a raw struct.error.
    d = str(tmp_path / "wal")
    wal = WriteAheadLog.create(d)
    wal.append(b"x" * 8)
    wal.close()
    seg = sorted(f for f in os.listdir(d) if f.endswith(".wal"))[0]
    path = os.path.join(d, seg)
    buf = bytearray(open(path, "rb").read())
    buf[0] = 2  # anchor payload length 6 -> 2
    open(path, "wb").write(bytes(buf))
    with pytest.raises(CorruptLogError):
        WriteAheadLog(d).read_all()


def test_non_tail_corruption_refuses_auto_repair(tmp_path):
    # Damage in a fully-fsynced earlier segment is data loss, not a torn
    # tail: repair must refuse rather than silently discard durable records.
    d = str(tmp_path / "wal")
    wal = WriteAheadLog.create(d, segment_max_bytes=200)
    for e in entries_of(12, size=16):
        wal.append(e)
    wal.close()
    segs = sorted(f for f in os.listdir(d) if f.endswith(".wal"))
    assert len(segs) >= 3
    mid = os.path.join(d, segs[1])
    buf = bytearray(open(mid, "rb").read())
    buf[len(buf) // 2] ^= 0xFF
    open(mid, "wb").write(bytes(buf))
    with pytest.raises(WALError):
        repair(d)


def test_group_commit_batches_fsyncs_and_fires_callbacks(tmp_path):
    from unittest import mock

    from consensus_tpu.runtime import SimScheduler

    s = SimScheduler()
    d = str(tmp_path / "wal")
    wal = WriteAheadLog.create(d, group_commit_window=0.002, scheduler=s)
    durable = []
    with mock.patch("os.fsync") as fsync:
        fsync.reset_mock()
        for i in range(10):
            wal.append(b"e%d" % i, on_durable=lambda i=i: durable.append(i))
        assert durable == []  # nothing durable before the window closes
        group_syncs_before = fsync.call_count
        s.advance(0.002)
        # One fsync covered all ten appends.
        assert fsync.call_count == group_syncs_before + 1
    assert durable == list(range(10))
    # Records are intact and readable.
    assert wal.read_all() == [b"e%d" % i for i in range(10)]
    wal.close()


def test_group_commit_close_flushes_pending(tmp_path):
    from consensus_tpu.runtime import SimScheduler

    s = SimScheduler()
    d = str(tmp_path / "wal")
    wal = WriteAheadLog.create(d, group_commit_window=1.0, scheduler=s)
    durable = []
    wal.append(b"x", on_durable=lambda: durable.append("x"))
    wal.close()  # window never elapsed: close must make it durable
    assert durable == ["x"]
    assert WriteAheadLog.open_(d).read_all() == [b"x"]


def test_default_mode_callback_fires_synchronously(tmp_path):
    d = str(tmp_path / "wal")
    wal = WriteAheadLog.create(d)
    durable = []
    wal.append(b"x", on_durable=lambda: durable.append("x"))
    assert durable == ["x"]
    wal.close()


def test_group_commit_truncate_flushes_before_dropping_history(tmp_path):
    from unittest import mock

    from consensus_tpu.runtime import SimScheduler

    s = SimScheduler()
    d = str(tmp_path / "wal")
    wal = WriteAheadLog.create(d, segment_max_bytes=200,
                               group_commit_window=1.0, scheduler=s)
    for e in entries_of(12, size=16):
        wal.append(e)
    calls = []
    real_fsync = os.fsync
    with mock.patch("os.fsync", side_effect=lambda fd: (calls.append("fsync"), real_fsync(fd))):
        with mock.patch("os.unlink", side_effect=lambda p: calls.append("unlink")):
            wal.append(b"restore-point", truncate_to=True)
    assert "fsync" in calls and "unlink" in calls
    assert calls.index("fsync") < calls.index("unlink"), (
        "history deleted before the restore point was durable"
    )
    wal.close()


def test_group_commit_config_validation(tmp_path):
    import pytest as _pytest

    with _pytest.raises(ValueError):
        WriteAheadLog(str(tmp_path / "a"), group_commit_window=0.1)
    with _pytest.raises(ValueError):
        WriteAheadLog(str(tmp_path / "b"), group_commit_window=0.1,
                      scheduler=object(), sync=False)
    d = str(tmp_path / "c")
    wal = WriteAheadLog.create(d, sync=False)
    with _pytest.raises(WALError):
        wal.append(b"x", on_durable=lambda: None)


def test_group_commit_waiter_exception_does_not_strand_others(tmp_path):
    from consensus_tpu.runtime import SimScheduler

    s = SimScheduler()
    wal = WriteAheadLog.create(str(tmp_path / "wal"),
                               group_commit_window=0.01, scheduler=s)
    fired = []
    wal.append(b"a", on_durable=lambda: (_ for _ in ()).throw(RuntimeError("boom")))
    wal.append(b"b", on_durable=lambda: fired.append("b"))
    wal.append(b"c", on_durable=lambda: fired.append("c"))
    s.advance(0.01)
    assert fired == ["b", "c"]
    wal.close()


def test_group_commit_truncate_cancels_stale_timer(tmp_path):
    from unittest import mock

    from consensus_tpu.runtime import SimScheduler

    s = SimScheduler()
    wal = WriteAheadLog.create(str(tmp_path / "wal"),
                               group_commit_window=0.01, scheduler=s)
    wal.append(b"x")
    wal.append(b"checkpoint", truncate_to=True)  # eager flush cancels timer
    real_fsync = os.fsync
    with mock.patch("os.fsync", side_effect=real_fsync) as fsync:
        s.advance(0.05)  # the stale timer must NOT fire an extra fsync
        assert fsync.call_count == 0
    wal.close()


def test_group_commit_fsync_failure_retries_without_false_durability(tmp_path):
    from unittest import mock

    from consensus_tpu.runtime import SimScheduler

    s = SimScheduler()
    wal = WriteAheadLog.create(str(tmp_path / "wal"),
                               group_commit_window=0.01, scheduler=s)
    durable = []
    wal.append(b"x", on_durable=lambda: durable.append("x"))
    real_fsync = os.fsync
    calls = {"n": 0}

    def flaky(fd):
        calls["n"] += 1
        if calls["n"] == 1:
            raise OSError(28, "No space left on device")
        return real_fsync(fd)

    with mock.patch("os.fsync", side_effect=flaky):
        s.advance(0.01)
        assert durable == []  # failed fsync must not report durability
        s.advance(0.02)  # retry window
    assert durable == ["x"]
    wal.close()


def test_group_commit_cluster_defers_broadcasts_until_durable(tmp_path):
    # End to end: replicas on REAL group-commit WALs still order correctly —
    # the protocol's sends ride on_durable, so nothing is ever said that is
    # not remembered (persist-before-broadcast under batched fsyncs).
    from consensus_tpu.consensus import Consensus
    from consensus_tpu.testing import Cluster
    from consensus_tpu.testing.app import make_request

    cluster = Cluster(4)
    # Swap every node's WAL for a real group-commit log on disk.
    for node_id, node in cluster.nodes.items():
        wal_dir = str(tmp_path / f"wal-{node_id}")

        def start_with_real_wal(node=node, wal_dir=wal_dir):
            comm = cluster.network.register(node.node_id, node._on_message)
            wal = WriteAheadLog.create(
                wal_dir, group_commit_window=0.002, scheduler=cluster.scheduler
            )
            node.consensus = Consensus(
                config=node.config,
                scheduler=cluster.scheduler,
                comm=comm,
                application=node.app,
                assembler=node.app,
                wal=wal,
                signer=node.app,
                verifier=node.app,
                request_inspector=node.app.inspector,
                synchronizer=node.app,
            )
            node.consensus.start()
            node.running = True

        node.start = start_with_real_wal
    cluster.start()
    for i in range(3):
        cluster.submit_to_all(make_request("gc", i))
        assert cluster.run_until_ledger(i + 1, max_time=300.0), f"block {i} stalled"
    cluster.assert_ledgers_consistent()


# --- explicit open contract, repair idempotence -----------------------------


def test_open_default_raises_on_torn_tail(tmp_path):
    d = str(tmp_path / "wal")
    wal = WriteAheadLog.create(d)
    for e in entries_of(4):
        wal.append(e)
    wal.close()
    seg = sorted(f for f in os.listdir(d) if f.endswith(".wal"))[-1]
    path = os.path.join(d, seg)
    full = open(path, "rb").read()
    open(path, "wb").write(full[:-5])
    # repair=False (the default) surfaces the tear to the caller.
    with pytest.raises(CorruptLogError):
        WriteAheadLog.open_(d)
    # repair=True chops the tail and opens the intact prefix.
    wal2 = WriteAheadLog.open_(d, repair=True)
    entries = wal2.read_all()
    assert entries == entries_of(4)[: len(entries)]
    wal2.append(b"post-repair")
    assert wal2.read_all()[-1] == b"post-repair"
    wal2.close()


def test_open_repair_still_refuses_non_tail_corruption(tmp_path):
    d = str(tmp_path / "wal")
    wal = WriteAheadLog.create(d, segment_max_bytes=200)
    for e in entries_of(12, size=16):
        wal.append(e)
    wal.close()
    segs = sorted(f for f in os.listdir(d) if f.endswith(".wal"))
    mid = os.path.join(d, segs[1])
    buf = bytearray(open(mid, "rb").read())
    buf[len(buf) // 2] ^= 0xFF
    open(mid, "wb").write(bytes(buf))
    # Durable records damaged at rest: repair=True must NOT silently chop.
    with pytest.raises(WALError):
        WriteAheadLog.open_(d, repair=True)


def test_repair_idempotent_with_two_consecutive_torn_frames(tmp_path):
    # Regression: a crash can leave MORE than one partial frame at the tail
    # (a torn group write).  One repair pass must remove the whole damaged
    # suffix, and a second pass must be a no-op — not find fresh damage.
    d = str(tmp_path / "wal")
    wal = WriteAheadLog.create(d)
    for e in entries_of(4):
        wal.append(e)
    wal.close()
    seg = sorted(f for f in os.listdir(d) if f.endswith(".wal"))[-1]
    path = os.path.join(d, seg)
    full = open(path, "rb").read()
    # Fabricate two torn frames: a header claiming more payload than exists,
    # followed by a second truncated header fragment.
    import struct as _struct

    torn_a = _struct.pack("<II", 64, 0xDEAD) + b"\x01\x00partial"
    torn_b = _struct.pack("<I", 99)[:3]
    with open(path, "ab") as f:
        f.write(torn_a + torn_b)
    repair(d)
    assert WriteAheadLog.open_(d).read_all() == entries_of(4)
    before = open(path, "rb").read()
    repair(d)  # idempotent: second pass finds a healthy log
    assert open(path, "rb").read() == before
    assert WriteAheadLog.open_(d).read_all() == entries_of(4)
    # The pre-repair bytes were preserved for forensics.
    assert any(f.endswith(".bak") for f in os.listdir(d))


def test_initialize_and_read_all_repairs_double_tear(tmp_path):
    d = str(tmp_path / "wal")
    wal = WriteAheadLog.create(d)
    for e in entries_of(3):
        wal.append(e)
    wal.close()
    seg = sorted(f for f in os.listdir(d) if f.endswith(".wal"))[-1]
    path = os.path.join(d, seg)
    import struct as _struct

    with open(path, "ab") as f:
        f.write(_struct.pack("<II", 1 << 20, 0) + b"\x01\x00x")
        f.write(b"\x07\x00")
    wal2, entries = initialize_and_read_all(d)
    assert entries == entries_of(3)
    wal2.append(b"alive")
    assert wal2.read_all() == entries_of(3) + [b"alive"]
    wal2.close()


# --- quarantine -------------------------------------------------------------


def test_quarantine_preserves_mid_segment_intact_prefix(tmp_path):
    d = str(tmp_path / "wal")
    wal = WriteAheadLog.create(d)
    for e in entries_of(6):
        wal.append(e)
    wal.close()
    seg = sorted(f for f in os.listdir(d) if f.endswith(".wal"))[0]
    path = os.path.join(d, seg)
    buf = bytearray(open(path, "rb").read())
    # Flip a byte inside the LAST record's payload (entries are 24-byte
    # frames padded to 8: the final 6 bytes are CRC-exempt padding, so
    # target 10 bytes back from the end) — a whole-record prefix precedes
    # the damage.
    buf[len(buf) - 10] ^= 0x10
    open(path, "wb").write(bytes(buf))
    probe = WriteAheadLog(d)
    with pytest.raises(CorruptLogError) as exc:
        probe.read_all()
    moved = quarantine(d, exc.value)
    assert moved, "damaged suffix should have been set aside"
    qdir = os.path.join(d, QUARANTINE_DIRNAME)
    assert sorted(os.listdir(qdir)) == sorted(moved)
    # The intact prefix survived in place and the log reopens cleanly.
    reopened = WriteAheadLog.open_(d)
    entries = reopened.read_all()
    assert entries == entries_of(6)[: len(entries)]
    assert len(entries) >= 1
    reopened.close()


def test_boot_quarantine_books_metrics_exactly_once(tmp_path):
    from consensus_tpu.metrics import InMemoryProvider, Metrics

    d = str(tmp_path / "wal")
    wal = WriteAheadLog.create(d, segment_max_bytes=200)
    for e in entries_of(12, size=16):
        wal.append(e)
    wal.close()
    segs = sorted(f for f in os.listdir(d) if f.endswith(".wal"))
    mid = os.path.join(d, segs[1])
    buf = bytearray(open(mid, "rb").read())
    buf[len(buf) // 2] ^= 0xFF
    open(mid, "wb").write(bytes(buf))
    # Non-tail corruption + quarantine_corrupt: boot survives with amnesia
    # recorded instead of raising.
    wal2, entries = initialize_and_read_all(d, quarantine_corrupt=True)
    assert wal2.recovery is not None
    assert wal2.recovery.intact_entries == len(entries)
    # Metrics attach AFTER boot (the facade wires them later): the pinned
    # quarantine counter books once, and only once, on attach.
    metrics = Metrics(InMemoryProvider())
    wal2.attach_metrics(metrics.wal)
    assert metrics.wal.quarantines.value == 1
    wal2.attach_metrics(metrics.wal)
    assert metrics.wal.quarantines.value == 1
    wal2.close()


def test_boot_without_quarantine_flag_still_raises(tmp_path):
    d = str(tmp_path / "wal")
    wal = WriteAheadLog.create(d, segment_max_bytes=200)
    for e in entries_of(12, size=16):
        wal.append(e)
    wal.close()
    segs = sorted(f for f in os.listdir(d) if f.endswith(".wal"))
    mid = os.path.join(d, segs[1])
    buf = bytearray(open(mid, "rb").read())
    buf[len(buf) // 2] ^= 0xFF
    open(mid, "wb").write(bytes(buf))
    with pytest.raises(WALError):
        initialize_and_read_all(d)


# --- the scrubber -----------------------------------------------------------


def test_scrubber_clean_passes_book_runs_and_records(tmp_path):
    from consensus_tpu.metrics import InMemoryProvider, Metrics
    from consensus_tpu.runtime import SimScheduler

    s = SimScheduler()
    d = str(tmp_path / "wal")
    wal, _ = initialize_and_read_all(d)
    for e in entries_of(5):
        wal.append(e)
    metrics = Metrics(InMemoryProvider())
    scrubber = WalScrubber(wal, s, interval=10.0, metrics=metrics.wal)
    scrubber.start()
    s.advance(35.0)
    assert scrubber.runs == 3  # one pass per elapsed interval
    assert metrics.wal.scrub_runs.value == 3
    assert metrics.wal.scrub_records.value == 15
    assert metrics.wal.scrub_corruptions.value == 0
    scrubber.stop()
    s.advance(50.0)
    assert scrubber.runs == 3  # stopped: no further passes
    wal.close()


def test_scrubber_detection_invokes_callback_once_per_pass(tmp_path):
    from consensus_tpu.runtime import SimScheduler

    s = SimScheduler()
    d = str(tmp_path / "wal")
    wal, _ = initialize_and_read_all(d)
    for e in entries_of(5):
        wal.append(e)
    seg = sorted(f for f in os.listdir(d) if f.endswith(".wal"))[0]
    path = os.path.join(d, seg)
    buf = bytearray(open(path, "rb").read())
    buf[len(buf) // 2] ^= 0x01
    open(path, "wb").write(bytes(buf))
    detections = []
    scrubber = WalScrubber(wal, s, interval=1.0,
                           on_corruption=detections.append)
    err = scrubber.scrub_now()
    assert err is not None and detections == [err]
    # The callback is expected to quarantine; doing so makes later passes
    # clean again.
    wal.quarantine_corrupt(err)
    assert scrubber.scrub_now() is None
    assert len(detections) == 1
    wal.close()


def test_scrubber_rejects_nonpositive_interval(tmp_path):
    from consensus_tpu.runtime import SimScheduler

    d = str(tmp_path / "wal")
    wal, _ = initialize_and_read_all(d)
    with pytest.raises(ValueError):
        WalScrubber(wal, SimScheduler(), interval=0.0)
    wal.close()


# --- bench.py wal family ----------------------------------------------------


def test_bench_wal_family_record():
    """The host-side ``wal`` bench family must produce a well-formed record
    whose trace-determined fields are pinned: the group-commit run drains
    one burst per fsync, and the quarantine recovery comes back on a
    non-empty strict prefix (the amnesia case, measured not assumed).
    Calls bench_wal() in-process so the last-good trail is untouched."""
    import sys

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, repo_root)
    try:
        import bench
    finally:
        sys.path.remove(repo_root)

    rec = bench.bench_wal()
    assert rec["metric"] == "wal_append_throughput"
    assert rec["unit"] == "appends/sec"
    assert rec["value"] > 0
    assert rec["entries"] == bench.WAL_ENTRIES
    # Trace-determined: one data fsync per full burst (rolls excepted).
    assert rec["group_commit_ratio"] >= bench.WAL_GROUP_BURST / 2
    assert rec["recovery_intact_ms"] > 0
    assert rec["recovery_quarantine_ms"] > 0
    assert 0 < rec["recovered_prefix"] < bench.WAL_ENTRIES
