"""WAL tests: round trips, segment rollover, truncation, corruption
detection, and the torn-write property test (truncate the tail segment at
every byte offset, repair, and confirm a valid prefix survives).

Parity model: reference pkg/wal/writeaheadlog_test.go (temp-dir file I/O,
CRC corruption injection, torn-write repair, segment rollover).
"""

import os

import pytest

from consensus_tpu.wal import (
    CorruptLogError,
    WALError,
    WriteAheadLog,
    initialize_and_read_all,
    repair,
)


def entries_of(n, size=24):
    return [bytes([i % 256]) * size for i in range(1, n + 1)]


def test_create_append_read_round_trip(tmp_path):
    d = str(tmp_path / "wal")
    wal = WriteAheadLog.create(d)
    data = entries_of(10)
    for e in data:
        wal.append(e)
    assert wal.read_all() == data
    wal.close()
    # Reopen and continue appending.
    wal2 = WriteAheadLog.open_(d)
    wal2.append(b"after-reopen")
    assert wal2.read_all() == data + [b"after-reopen"]
    wal2.close()


def test_create_refuses_existing_log(tmp_path):
    d = str(tmp_path / "wal")
    WriteAheadLog.create(d).close()
    with pytest.raises(WALError):
        WriteAheadLog.create(d)


def test_segment_rollover_preserves_entries(tmp_path):
    d = str(tmp_path / "wal")
    wal = WriteAheadLog.create(d, segment_max_bytes=256)
    data = entries_of(40)
    for e in data:
        wal.append(e)
    segments = [f for f in os.listdir(d) if f.endswith(".wal")]
    assert len(segments) > 3, "expected multiple segments"
    assert wal.read_all() == data
    wal.close()
    assert WriteAheadLog.open_(d).read_all() == data


def test_truncate_to_drops_older_segments(tmp_path):
    d = str(tmp_path / "wal")
    wal = WriteAheadLog.create(d, segment_max_bytes=256)
    for e in entries_of(30):
        wal.append(e)
    before = len([f for f in os.listdir(d) if f.endswith(".wal")])
    wal.append(b"stable-point", truncate_to=True)
    after = len([f for f in os.listdir(d) if f.endswith(".wal")])
    assert after < before
    # A restore point retires everything before it — even records that share
    # its segment (reference pkg/wal/writeaheadlog.go:549-551).
    assert wal.read_all() == [b"stable-point"]
    wal.append(b"next")
    assert wal.read_all() == [b"stable-point", b"next"]
    wal.close()
    # Reopened log reads the same surviving suffix.
    assert WriteAheadLog.open_(d).read_all() == [b"stable-point", b"next"]


def test_bit_flip_detected(tmp_path):
    d = str(tmp_path / "wal")
    wal = WriteAheadLog.create(d)
    for e in entries_of(5):
        wal.append(e)
    wal.close()
    seg = sorted(f for f in os.listdir(d) if f.endswith(".wal"))[0]
    path = os.path.join(d, seg)
    buf = bytearray(open(path, "rb").read())
    buf[len(buf) // 2] ^= 0xFF
    open(path, "wb").write(bytes(buf))
    with pytest.raises(CorruptLogError):
        WriteAheadLog(d).read_all()


def test_torn_write_repair_at_every_offset(tmp_path):
    # Property test: crash mid-write at any byte boundary must leave a log
    # that repairs to an intact prefix of what was appended.
    d = str(tmp_path / "wal")
    wal = WriteAheadLog.create(d)
    data = entries_of(6, size=10)
    for e in data:
        wal.append(e)
    wal.close()
    seg = sorted(f for f in os.listdir(d) if f.endswith(".wal"))[-1]
    path = os.path.join(d, seg)
    full = open(path, "rb").read()

    for cut in range(len(full)):
        open(path, "wb").write(full[:cut])
        wal2, entries = initialize_and_read_all(d)
        wal2.close()
        assert entries == data[: len(entries)], f"not a prefix at cut={cut}"
        # The repaired log must accept new appends.
        wal3 = WriteAheadLog.open_(d)
        wal3.append(b"post-repair")
        assert wal3.read_all() == entries + [b"post-repair"]
        wal3.close()
        # Restore for the next iteration.
        for f in os.listdir(d):
            if f.endswith(".bak"):
                os.unlink(os.path.join(d, f))
        open(path, "wb").write(full)


def test_torn_write_across_segments(tmp_path):
    d = str(tmp_path / "wal")
    wal = WriteAheadLog.create(d, segment_max_bytes=200)
    data = entries_of(12, size=16)
    for e in data:
        wal.append(e)
    wal.close()
    segs = sorted(f for f in os.listdir(d) if f.endswith(".wal"))
    assert len(segs) >= 2
    # Tear the last segment down to one byte.
    last = os.path.join(d, segs[-1])
    open(last, "r+b").truncate(1)
    wal2, entries = initialize_and_read_all(d)
    assert entries == data[: len(entries)]
    assert len(entries) > 0
    wal2.close()


def test_repair_noop_on_healthy_log(tmp_path):
    d = str(tmp_path / "wal")
    wal = WriteAheadLog.create(d)
    for e in entries_of(3):
        wal.append(e)
    wal.close()
    repair(d)
    assert WriteAheadLog.open_(d).read_all() == entries_of(3)


def test_initialize_creates_fresh_log(tmp_path):
    d = str(tmp_path / "wal")
    wal, entries = initialize_and_read_all(d)
    assert entries == []
    wal.append(b"x")
    assert wal.read_all() == [b"x"]
    wal.close()
    wal2, entries2 = initialize_and_read_all(d)
    assert entries2 == [b"x"]
    wal2.close()


def test_append_after_close_fails(tmp_path):
    d = str(tmp_path / "wal")
    wal = WriteAheadLog.create(d)
    wal.close()
    with pytest.raises(WALError):
        wal.append(b"x")


def test_corrupt_anchor_length_detected_not_crash(tmp_path):
    # A bit-flip in an anchor's length field must surface as CorruptLogError
    # (repairable), not a raw struct.error.
    d = str(tmp_path / "wal")
    wal = WriteAheadLog.create(d)
    wal.append(b"x" * 8)
    wal.close()
    seg = sorted(f for f in os.listdir(d) if f.endswith(".wal"))[0]
    path = os.path.join(d, seg)
    buf = bytearray(open(path, "rb").read())
    buf[0] = 2  # anchor payload length 6 -> 2
    open(path, "wb").write(bytes(buf))
    with pytest.raises(CorruptLogError):
        WriteAheadLog(d).read_all()


def test_non_tail_corruption_refuses_auto_repair(tmp_path):
    # Damage in a fully-fsynced earlier segment is data loss, not a torn
    # tail: repair must refuse rather than silently discard durable records.
    d = str(tmp_path / "wal")
    wal = WriteAheadLog.create(d, segment_max_bytes=200)
    for e in entries_of(12, size=16):
        wal.append(e)
    wal.close()
    segs = sorted(f for f in os.listdir(d) if f.endswith(".wal"))
    assert len(segs) >= 3
    mid = os.path.join(d, segs[1])
    buf = bytearray(open(mid, "rb").read())
    buf[len(buf) // 2] ^= 0xFF
    open(mid, "wb").write(bytes(buf))
    with pytest.raises(WALError):
        repair(d)


def test_group_commit_batches_fsyncs_and_fires_callbacks(tmp_path):
    from unittest import mock

    from consensus_tpu.runtime import SimScheduler

    s = SimScheduler()
    d = str(tmp_path / "wal")
    wal = WriteAheadLog.create(d, group_commit_window=0.002, scheduler=s)
    durable = []
    with mock.patch("os.fsync") as fsync:
        fsync.reset_mock()
        for i in range(10):
            wal.append(b"e%d" % i, on_durable=lambda i=i: durable.append(i))
        assert durable == []  # nothing durable before the window closes
        group_syncs_before = fsync.call_count
        s.advance(0.002)
        # One fsync covered all ten appends.
        assert fsync.call_count == group_syncs_before + 1
    assert durable == list(range(10))
    # Records are intact and readable.
    assert wal.read_all() == [b"e%d" % i for i in range(10)]
    wal.close()


def test_group_commit_close_flushes_pending(tmp_path):
    from consensus_tpu.runtime import SimScheduler

    s = SimScheduler()
    d = str(tmp_path / "wal")
    wal = WriteAheadLog.create(d, group_commit_window=1.0, scheduler=s)
    durable = []
    wal.append(b"x", on_durable=lambda: durable.append("x"))
    wal.close()  # window never elapsed: close must make it durable
    assert durable == ["x"]
    assert WriteAheadLog.open_(d).read_all() == [b"x"]


def test_default_mode_callback_fires_synchronously(tmp_path):
    d = str(tmp_path / "wal")
    wal = WriteAheadLog.create(d)
    durable = []
    wal.append(b"x", on_durable=lambda: durable.append("x"))
    assert durable == ["x"]
    wal.close()


def test_group_commit_truncate_flushes_before_dropping_history(tmp_path):
    from unittest import mock

    from consensus_tpu.runtime import SimScheduler

    s = SimScheduler()
    d = str(tmp_path / "wal")
    wal = WriteAheadLog.create(d, segment_max_bytes=200,
                               group_commit_window=1.0, scheduler=s)
    for e in entries_of(12, size=16):
        wal.append(e)
    calls = []
    real_fsync = os.fsync
    with mock.patch("os.fsync", side_effect=lambda fd: (calls.append("fsync"), real_fsync(fd))):
        with mock.patch("os.unlink", side_effect=lambda p: calls.append("unlink")):
            wal.append(b"restore-point", truncate_to=True)
    assert "fsync" in calls and "unlink" in calls
    assert calls.index("fsync") < calls.index("unlink"), (
        "history deleted before the restore point was durable"
    )
    wal.close()


def test_group_commit_config_validation(tmp_path):
    import pytest as _pytest

    with _pytest.raises(ValueError):
        WriteAheadLog(str(tmp_path / "a"), group_commit_window=0.1)
    with _pytest.raises(ValueError):
        WriteAheadLog(str(tmp_path / "b"), group_commit_window=0.1,
                      scheduler=object(), sync=False)
    d = str(tmp_path / "c")
    wal = WriteAheadLog.create(d, sync=False)
    with _pytest.raises(WALError):
        wal.append(b"x", on_durable=lambda: None)


def test_group_commit_waiter_exception_does_not_strand_others(tmp_path):
    from consensus_tpu.runtime import SimScheduler

    s = SimScheduler()
    wal = WriteAheadLog.create(str(tmp_path / "wal"),
                               group_commit_window=0.01, scheduler=s)
    fired = []
    wal.append(b"a", on_durable=lambda: (_ for _ in ()).throw(RuntimeError("boom")))
    wal.append(b"b", on_durable=lambda: fired.append("b"))
    wal.append(b"c", on_durable=lambda: fired.append("c"))
    s.advance(0.01)
    assert fired == ["b", "c"]
    wal.close()


def test_group_commit_truncate_cancels_stale_timer(tmp_path):
    from unittest import mock

    from consensus_tpu.runtime import SimScheduler

    s = SimScheduler()
    wal = WriteAheadLog.create(str(tmp_path / "wal"),
                               group_commit_window=0.01, scheduler=s)
    wal.append(b"x")
    wal.append(b"checkpoint", truncate_to=True)  # eager flush cancels timer
    real_fsync = os.fsync
    with mock.patch("os.fsync", side_effect=real_fsync) as fsync:
        s.advance(0.05)  # the stale timer must NOT fire an extra fsync
        assert fsync.call_count == 0
    wal.close()


def test_group_commit_fsync_failure_retries_without_false_durability(tmp_path):
    from unittest import mock

    from consensus_tpu.runtime import SimScheduler

    s = SimScheduler()
    wal = WriteAheadLog.create(str(tmp_path / "wal"),
                               group_commit_window=0.01, scheduler=s)
    durable = []
    wal.append(b"x", on_durable=lambda: durable.append("x"))
    real_fsync = os.fsync
    calls = {"n": 0}

    def flaky(fd):
        calls["n"] += 1
        if calls["n"] == 1:
            raise OSError(28, "No space left on device")
        return real_fsync(fd)

    with mock.patch("os.fsync", side_effect=flaky):
        s.advance(0.01)
        assert durable == []  # failed fsync must not report durability
        s.advance(0.02)  # retry window
    assert durable == ["x"]
    wal.close()


def test_group_commit_cluster_defers_broadcasts_until_durable(tmp_path):
    # End to end: replicas on REAL group-commit WALs still order correctly —
    # the protocol's sends ride on_durable, so nothing is ever said that is
    # not remembered (persist-before-broadcast under batched fsyncs).
    from consensus_tpu.consensus import Consensus
    from consensus_tpu.testing import Cluster
    from consensus_tpu.testing.app import make_request

    cluster = Cluster(4)
    # Swap every node's WAL for a real group-commit log on disk.
    for node_id, node in cluster.nodes.items():
        wal_dir = str(tmp_path / f"wal-{node_id}")

        def start_with_real_wal(node=node, wal_dir=wal_dir):
            comm = cluster.network.register(node.node_id, node._on_message)
            wal = WriteAheadLog.create(
                wal_dir, group_commit_window=0.002, scheduler=cluster.scheduler
            )
            node.consensus = Consensus(
                config=node.config,
                scheduler=cluster.scheduler,
                comm=comm,
                application=node.app,
                assembler=node.app,
                wal=wal,
                signer=node.app,
                verifier=node.app,
                request_inspector=node.app.inspector,
                synchronizer=node.app,
            )
            node.consensus.start()
            node.running = True

        node.start = start_with_real_wal
    cluster.start()
    for i in range(3):
        cluster.submit_to_all(make_request("gc", i))
        assert cluster.run_until_ledger(i + 1, max_time=300.0), f"block {i} stalled"
    cluster.assert_ledgers_consistent()
