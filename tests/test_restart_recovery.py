"""Crash/restart recovery: PersistedState restore-into-phase and full-cluster
restart scenarios over surviving WAL content.

Parity model: reference internal/bft/state_test.go + test/basic_test.go
restart scenarios (e.g. TestRestartFollower).
"""

from consensus_tpu.core.state import InFlightData, PersistedState
from consensus_tpu.core.view import Phase
from consensus_tpu.testing import Cluster, MemWAL, make_request
from consensus_tpu.types import Proposal, Signature
from consensus_tpu.wire import (
    Commit,
    PrePrepare,
    Prepare,
    ProposedRecord,
    SavedCommit,
    SavedNewView,
    SavedViewChange,
    ViewChange,
    ViewMetadata,
    encode_saved,
    encode_view_metadata,
)


class ViewStub:
    """Just the fields PersistedState.restore touches."""

    def __init__(self, proposal_sequence=0):
        self.phase = None
        self.number = 0
        self.proposal_sequence = proposal_sequence
        self.decisions_in_view = 0
        self.in_flight_proposal = None
        self.my_commit_signature = None
        self._curr_prepare_sent = None
        self._curr_commit_sent = None


def proposal_at(view, seq, decisions=0):
    md = ViewMetadata(view_id=view, latest_sequence=seq, decisions_in_view=decisions)
    return Proposal(payload=b"p", metadata=encode_view_metadata(md))


def proposed_record(view, seq):
    prop = proposal_at(view, seq)
    pp = PrePrepare(view=view, seq=seq, proposal=prop)
    return ProposedRecord(
        pre_prepare=pp, prepare=Prepare(view=view, seq=seq, digest=prop.digest())
    )


def test_restore_into_proposed():
    backing = []
    wal = MemWAL(backing)
    record = proposed_record(view=2, seq=5)
    wal.append(encode_saved(record), truncate_to=True)
    state = PersistedState(wal, InFlightData(), entries=wal.entries)
    v = ViewStub()
    state.restore(v)
    assert v.phase == Phase.PROPOSED
    assert v.number == 2 and v.proposal_sequence == 5
    assert v.in_flight_proposal == record.pre_prepare.proposal
    assert v._curr_prepare_sent.assist  # re-broadcast marked as assist


def test_restore_into_prepared_resurrects_signature():
    backing = []
    wal = MemWAL(backing)
    record = proposed_record(view=1, seq=3)
    wal.append(encode_saved(record), truncate_to=True)
    sig = Signature(id=7, value=b"v", msg=b"aux")
    commit = Commit(
        view=1, seq=3, digest=record.pre_prepare.proposal.digest(), signature=sig
    )
    wal.append(encode_saved(SavedCommit(commit=commit)))
    state = PersistedState(wal, InFlightData(), entries=wal.entries)
    v = ViewStub(proposal_sequence=3)
    state.restore(v)
    assert v.phase == Phase.PREPARED
    assert v.my_commit_signature == sig
    assert v._curr_commit_sent.assist


def test_restore_skips_already_committed_sequence():
    backing = []
    wal = MemWAL(backing)
    record = proposed_record(view=1, seq=3)
    wal.append(encode_saved(record), truncate_to=True)
    commit = Commit(
        view=1, seq=3, digest=record.pre_prepare.proposal.digest(),
        signature=Signature(id=7),
    )
    wal.append(encode_saved(SavedCommit(commit=commit)))
    state = PersistedState(wal, InFlightData(), entries=wal.entries)
    v = ViewStub(proposal_sequence=4)  # already delivered seq 3
    state.restore(v)
    assert v.phase == Phase.COMMITTED


def test_load_new_view_and_view_change_records():
    backing = []
    wal = MemWAL(backing)
    state = PersistedState(wal, InFlightData(), entries=[])
    assert state.load_new_view_if_applicable() is None
    assert state.load_view_change_if_applicable() is None

    wal.append(encode_saved(SavedViewChange(view_change=ViewChange(next_view=4))))
    state = PersistedState(wal, InFlightData(), entries=wal.entries)
    assert state.load_view_change_if_applicable() == ViewChange(next_view=4)
    assert state.load_new_view_if_applicable() is None

    wal.append(
        encode_saved(
            SavedNewView(view_metadata=ViewMetadata(view_id=4, latest_sequence=9))
        )
    )
    state = PersistedState(wal, InFlightData(), entries=wal.entries)
    assert state.load_new_view_if_applicable() == (4, 9)


def test_follower_restart_rejoins_and_catches_up():
    cluster = Cluster(4)
    cluster.start()
    for i in range(3):
        cluster.submit_to_all(make_request("c", i))
        assert cluster.run_until_ledger(i + 1)

    follower = cluster.nodes[4]
    follower.crash()
    # Cluster keeps ordering without it (3 of 4 is a quorum).
    for i in range(3, 6):
        cluster.submit_to_all(make_request("c", i))
        assert cluster.run_until_ledger(i + 1, node_ids=[1, 2, 3])

    follower.restart()
    # The restarted node syncs (heartbeat seq-gap or new traffic) and the
    # next decisions include it.
    for i in range(6, 8):
        cluster.submit_to_all(make_request("c", i))
        assert cluster.run_until_ledger(i + 1, node_ids=[1, 2, 3], max_time=300.0)
    cluster.scheduler.advance(120.0)  # let the gap detection + sync play out
    assert len(follower.app.ledger) >= 6
    cluster.assert_ledgers_consistent()


def test_whole_cluster_restart_resumes_ordering():
    cluster = Cluster(4)
    cluster.start()
    for i in range(3):
        cluster.submit_to_all(make_request("c", i))
        assert cluster.run_until_ledger(i + 1)
    for node in cluster.nodes.values():
        node.crash()
    for node in cluster.nodes.values():
        node.start()
    for i in range(3, 6):
        cluster.submit_to_all(make_request("c", i))
        assert cluster.run_until_ledger(i + 1, max_time=300.0), f"block {i} stalled after restart"
    cluster.assert_ledgers_consistent()


def test_restart_during_view_change_rejoins_it():
    # A replica that crashes after voting to change views must, on restart,
    # restore the pending ViewChange from its WAL and rejoin (reference
    # consensus.go:464-504 + the viewchanger Restore path).
    FAST = {
        "request_forward_timeout": 1.0,
        "request_complain_timeout": 4.0,
        "request_auto_remove_timeout": 60.0,
        "view_change_resend_interval": 2.0,
        "view_change_timeout": 10.0,
    }
    cluster = Cluster(4, config_tweaks=FAST)
    cluster.start()
    cluster.submit_to_all(make_request("c", 0))
    assert cluster.run_until_ledger(1)

    # Kill the leader; let complaints fire and the view change start.
    cluster.nodes[1].crash()
    cluster.submit_to_all(make_request("c", 1))

    # Wait for node 4 to *persist* its ViewChange vote (the join step, which
    # happens once quorum-1 peers voted), then crash it mid-change.
    from consensus_tpu.wire import SavedViewChange, decode_saved

    def vote_saved():
        return any(
            isinstance(decode_saved(e), SavedViewChange)
            for e in cluster.nodes[4].wal_backing
        )

    assert cluster.scheduler.run_until(vote_saved, max_time=120.0), (
        "view-change vote never persisted"
    )
    cluster.nodes[4].crash()
    cluster.scheduler.advance(1.0)
    cluster.nodes[4].restart()

    # The restarted node rejoins the change; with it back, 3 of 4 are alive
    # and the new view must order the pending request.
    assert cluster.run_until_ledger(2, node_ids=[2, 3, 4], max_time=600.0), (
        "restarted node failed to rejoin the view change"
    )
    cluster.assert_ledgers_consistent()
    assert cluster.nodes[4].consensus.controller.curr_view_number >= 1
