"""Crash/restart recovery: PersistedState restore-into-phase and full-cluster
restart scenarios over surviving WAL content.

Parity model: reference internal/bft/state_test.go + test/basic_test.go
restart scenarios (e.g. TestRestartFollower).
"""

import dataclasses

from consensus_tpu.core.state import InFlightData, PersistedState
from consensus_tpu.core.view import Phase
from consensus_tpu.testing import Cluster, MemWAL, make_request
from consensus_tpu.types import Proposal, Signature
from consensus_tpu.wire import (
    Commit,
    PrePrepare,
    Prepare,
    ProposedRecord,
    SavedCommit,
    SavedNewView,
    SavedViewChange,
    ViewChange,
    ViewMetadata,
    encode_saved,
    encode_view_metadata,
)


class ViewStub:
    """Just the fields PersistedState.restore touches."""

    class _Verifier:
        def requests_from_proposal(self, proposal):
            return []

    def __init__(self, proposal_sequence=0, self_id=2, leader_id=1, number=0):
        self.phase = None
        self.number = number
        self.proposal_sequence = proposal_sequence
        self.decisions_in_view = 0
        self.in_flight_proposal = None
        self.in_flight_requests = ()
        self.my_commit_signature = None
        self._curr_prepare_sent = None
        self._curr_commit_sent = None
        self.self_id = self_id
        self.leader_id = leader_id
        self.endorsement_blocked = False
        self.reverify_calls = []
        self._verifier = self._Verifier()

    def _verify_proposal(self, proposal, prev_commits):
        # Only consulted when restoring a record persisted BEFORE its
        # verification completed (verified=False — the leader's
        # reveal-before-verify path).
        self.reverify_calls.append((proposal, tuple(prev_commits)))
        if proposal.payload.startswith(b"BAD"):
            raise ValueError("rejected on restore")
        return []


def proposal_at(view, seq, decisions=0):
    md = ViewMetadata(view_id=view, latest_sequence=seq, decisions_in_view=decisions)
    return Proposal(payload=b"p", metadata=encode_view_metadata(md))


def proposed_record(view, seq):
    prop = proposal_at(view, seq)
    pp = PrePrepare(view=view, seq=seq, proposal=prop)
    return ProposedRecord(
        pre_prepare=pp, prepare=Prepare(view=view, seq=seq, digest=prop.digest())
    )


def test_restore_into_proposed():
    backing = []
    wal = MemWAL(backing)
    record = proposed_record(view=2, seq=5)
    wal.append(encode_saved(record), truncate_to=True)
    state = PersistedState(wal, InFlightData(), entries=wal.entries)
    v = ViewStub()
    state.restore(v)
    assert v.phase == Phase.PROPOSED
    assert v.number == 2 and v.proposal_sequence == 5
    assert v.in_flight_proposal == record.pre_prepare.proposal
    assert v._curr_prepare_sent.assist  # re-broadcast marked as assist


def test_restore_into_prepared_resurrects_signature():
    backing = []
    wal = MemWAL(backing)
    record = proposed_record(view=1, seq=3)
    wal.append(encode_saved(record), truncate_to=True)
    sig = Signature(id=7, value=b"v", msg=b"aux")
    commit = Commit(
        view=1, seq=3, digest=record.pre_prepare.proposal.digest(), signature=sig
    )
    wal.append(encode_saved(SavedCommit(commit=commit)))
    state = PersistedState(wal, InFlightData(), entries=wal.entries)
    v = ViewStub(proposal_sequence=3)
    state.restore(v)
    assert v.phase == Phase.PREPARED
    assert v.my_commit_signature == sig
    assert v._curr_commit_sent.assist


def test_restore_skips_already_committed_sequence():
    backing = []
    wal = MemWAL(backing)
    record = proposed_record(view=1, seq=3)
    wal.append(encode_saved(record), truncate_to=True)
    commit = Commit(
        view=1, seq=3, digest=record.pre_prepare.proposal.digest(),
        signature=Signature(id=7),
    )
    wal.append(encode_saved(SavedCommit(commit=commit)))
    state = PersistedState(wal, InFlightData(), entries=wal.entries)
    v = ViewStub(proposal_sequence=4)  # already delivered seq 3
    state.restore(v)
    assert v.phase == Phase.COMMITTED


def test_load_new_view_and_view_change_records():
    backing = []
    wal = MemWAL(backing)
    state = PersistedState(wal, InFlightData(), entries=[])
    assert state.load_new_view_if_applicable() is None
    assert state.load_view_change_if_applicable() is None

    wal.append(encode_saved(SavedViewChange(view_change=ViewChange(next_view=4))))
    state = PersistedState(wal, InFlightData(), entries=wal.entries)
    assert state.load_view_change_if_applicable() == ViewChange(next_view=4)
    assert state.load_new_view_if_applicable() is None

    wal.append(
        encode_saved(
            SavedNewView(view_metadata=ViewMetadata(view_id=4, latest_sequence=9))
        )
    )
    state = PersistedState(wal, InFlightData(), entries=wal.entries)
    assert state.load_new_view_if_applicable() == (4, 9)


def test_follower_restart_rejoins_and_catches_up():
    cluster = Cluster(4)
    cluster.start()
    for i in range(3):
        cluster.submit_to_all(make_request("c", i))
        assert cluster.run_until_ledger(i + 1)

    follower = cluster.nodes[4]
    follower.crash()
    # Cluster keeps ordering without it (3 of 4 is a quorum).
    for i in range(3, 6):
        cluster.submit_to_all(make_request("c", i))
        assert cluster.run_until_ledger(i + 1, node_ids=[1, 2, 3])

    follower.restart()
    # The restarted node syncs (heartbeat seq-gap or new traffic) and the
    # next decisions include it.
    for i in range(6, 8):
        cluster.submit_to_all(make_request("c", i))
        assert cluster.run_until_ledger(i + 1, node_ids=[1, 2, 3], max_time=300.0)
    cluster.scheduler.advance(120.0)  # let the gap detection + sync play out
    assert len(follower.app.ledger) >= 6
    cluster.assert_ledgers_consistent()


def test_whole_cluster_restart_resumes_ordering():
    cluster = Cluster(4)
    cluster.start()
    for i in range(3):
        cluster.submit_to_all(make_request("c", i))
        assert cluster.run_until_ledger(i + 1)
    for node in cluster.nodes.values():
        node.crash()
    for node in cluster.nodes.values():
        node.start()
    for i in range(3, 6):
        cluster.submit_to_all(make_request("c", i))
        assert cluster.run_until_ledger(i + 1, max_time=300.0), f"block {i} stalled after restart"
    cluster.assert_ledgers_consistent()


def test_restart_during_view_change_rejoins_it():
    # A replica that crashes after voting to change views must, on restart,
    # restore the pending ViewChange from its WAL and rejoin (reference
    # consensus.go:464-504 + the viewchanger Restore path).
    FAST = {
        "request_forward_timeout": 1.0,
        "request_complain_timeout": 4.0,
        "request_auto_remove_timeout": 60.0,
        "view_change_resend_interval": 2.0,
        "view_change_timeout": 10.0,
    }
    cluster = Cluster(4, config_tweaks=FAST)
    cluster.start()
    cluster.submit_to_all(make_request("c", 0))
    assert cluster.run_until_ledger(1)

    # Kill the leader; let complaints fire and the view change start.
    cluster.nodes[1].crash()
    cluster.submit_to_all(make_request("c", 1))

    # Wait for node 4 to *persist* its ViewChange vote (the join step, which
    # happens once quorum-1 peers voted), then crash it mid-change.
    from consensus_tpu.wire import SavedViewChange, decode_saved

    def vote_saved():
        return any(
            isinstance(decode_saved(e), SavedViewChange)
            for e in cluster.nodes[4].wal_backing
        )

    assert cluster.scheduler.run_until(vote_saved, max_time=120.0), (
        "view-change vote never persisted"
    )
    cluster.nodes[4].crash()
    cluster.scheduler.advance(1.0)
    cluster.nodes[4].restart()

    # The restarted node rejoins the change; with it back, 3 of 4 are alive
    # and the new view must order the pending request.
    assert cluster.run_until_ledger(2, node_ids=[2, 3, 4], max_time=600.0), (
        "restarted node failed to rejoin the view change"
    )
    cluster.assert_ledgers_consistent()
    assert cluster.nodes[4].consensus.controller.curr_view_number >= 1


def test_restore_unverified_record_reverifies_before_arming_prepare():
    """A ProposedRecord with verified=False (the leader's reveal-before-
    verify path persists before verification completes,
    view.py::_try_process_proposal) must be re-verified on restore before
    the prepare endorsement is re-armed.  The flag — not the restored
    view's leader identity, which can differ from pp.view's after a
    truncated view change — is the discriminator."""
    wal = MemWAL([])
    record = dataclasses.replace(proposed_record(view=2, seq=5), verified=False)
    wal.append(encode_saved(record), truncate_to=True)
    state = PersistedState(wal, InFlightData(), entries=wal.entries)
    v = ViewStub(self_id=1, leader_id=1)
    state.restore(v)
    assert v.reverify_calls  # re-verified the unverified record
    assert v.phase == Phase.PROPOSED
    assert v._curr_prepare_sent is not None
    assert not v.endorsement_blocked


def test_restore_unverified_bad_proposal_stays_pinned_but_never_endorses():
    wal = MemWAL([])
    md = ViewMetadata(view_id=2, latest_sequence=5, decisions_in_view=0)
    prop = Proposal(payload=b"BAD", metadata=encode_view_metadata(md))
    pp = PrePrepare(view=2, seq=5, proposal=prop)
    record = ProposedRecord(
        pre_prepare=pp,
        prepare=Prepare(view=2, seq=5, digest=prop.digest()),
        verified=False,
    )
    wal.append(encode_saved(record), truncate_to=True)
    state = PersistedState(wal, InFlightData(), entries=wal.entries)
    v = ViewStub(self_id=1, leader_id=1)
    state.restore(v)
    # Pinned to the proposal (no equivocation) but the prepare is NOT armed
    # and the PREPARED transition is blocked: prepares and commits are
    # endorsements and the record never implied verification.
    assert v.in_flight_proposal == prop
    assert v.phase == Phase.PROPOSED
    assert v._curr_prepare_sent is None
    assert v.endorsement_blocked


def test_restore_verified_record_does_not_reverify():
    """A verified=True record was only ever written after verification
    succeeded — restore must NOT re-verify (a reconfiguration could have
    bumped the verification sequence and false-fail a legitimate record),
    regardless of whether we were the leader of that view."""
    wal = MemWAL([])
    record = proposed_record(view=2, seq=5)  # verified=True default
    assert record.verified
    wal.append(encode_saved(record), truncate_to=True)
    state = PersistedState(wal, InFlightData(), entries=wal.entries)
    v = ViewStub(self_id=1, leader_id=1)  # even as the view's own leader
    state.restore(v)
    assert v.reverify_calls == []
    assert v._curr_prepare_sent is not None


def test_mark_proposed_verified_upgrades_memory_and_wal_tail():
    """After the leader's deferred verification succeeds, the in-memory
    record flips to verified (so a mid-run reseed skips the re-verify) AND
    — since the unverified record is still the WAL tail — an upgraded copy
    is appended, so a CRASH-restore skips the spurious re-verify too
    (ADVICE r3: verifier state advancing between write and restore would
    otherwise false-fail and depose a leader that had already verified)."""
    from consensus_tpu.wire import decode_saved

    wal = MemWAL([])
    record = dataclasses.replace(proposed_record(view=2, seq=5), verified=False)
    state = PersistedState(wal, InFlightData(), entries=wal.entries)
    state.save(record)
    state.mark_proposed_verified(2, 5)

    v = ViewStub(number=2, proposal_sequence=5)
    state.reseed_if_inflight_matches(v)
    assert v.reverify_calls == []  # memory copy is verified: no re-verify
    assert v._curr_prepare_sent is not None
    disk = decode_saved(wal.entries[-1])
    assert disk.verified  # upgraded copy appended at the tail

    # Crash-restore over the upgraded WAL: no re-verification either.
    state_reborn = PersistedState(wal, InFlightData(), entries=wal.entries)
    v_reborn = ViewStub(self_id=1, leader_id=1)
    state_reborn.restore(v_reborn)
    assert v_reborn.reverify_calls == []
    assert v_reborn.phase == Phase.PROPOSED
    assert v_reborn._curr_prepare_sent is not None

    # A non-matching (view, seq) must not flip anything.
    state2 = PersistedState(MemWAL([]), InFlightData(), entries=[])
    state2.save(dataclasses.replace(proposed_record(view=3, seq=9), verified=False))
    state2.mark_proposed_verified(3, 8)
    v2 = ViewStub(number=3, proposal_sequence=9)
    state2.reseed_if_inflight_matches(v2)
    assert v2.reverify_calls  # still unverified: reseed re-verifies


def test_mark_proposed_verified_skips_wal_upgrade_when_not_tail():
    """The verified-upgrade append must never clobber a record that
    followed the proposal: if anything else was saved since (here a
    ViewChange vote), the upgrade is memory-only and the WAL tail keeps
    its meaning for restore."""
    from consensus_tpu.wire import decode_saved

    wal = MemWAL([])
    record = dataclasses.replace(proposed_record(view=2, seq=5), verified=False)
    state = PersistedState(wal, InFlightData(), entries=wal.entries)
    state.save(record)
    state.save(SavedViewChange(view_change=ViewChange(next_view=3)))
    state.mark_proposed_verified(2, 5)
    tail = decode_saved(wal.entries[-1])
    assert isinstance(tail, SavedViewChange)  # tail untouched
    # Memory copy still flipped: mid-run reseeds skip the re-verify.
    v = ViewStub(number=2, proposal_sequence=5)
    state.reseed_if_inflight_matches(v)
    assert v.reverify_calls == []


def test_restore_time_reverify_upgrades_wal_for_second_crash():
    """Crash #1 restores an unverified tail and re-verifies successfully:
    that success must be persisted (upgraded tail record) so crash #2 does
    NOT re-verify again — double-crash protection for the ADVICE-r3 fix
    (without seeding _last_written from the restored tail, only mid-run
    verification successes were upgraded on disk)."""
    wal = MemWAL([])
    record = dataclasses.replace(proposed_record(view=2, seq=5), verified=False)
    wal.append(encode_saved(record), truncate_to=True)

    # Crash #1: restore re-verifies (verified=False tail) and succeeds.
    state1 = PersistedState(wal, InFlightData(), entries=list(wal.entries))
    v1 = ViewStub(self_id=1, leader_id=1)
    state1.restore(v1)
    assert v1.reverify_calls, "premise: first restore re-verifies"

    from consensus_tpu.wire import decode_saved

    assert decode_saved(wal.entries[-1]).verified, (
        "restore-time verification success was not persisted"
    )

    # Crash #2: the upgraded tail restores with NO re-verification.
    state2 = PersistedState(wal, InFlightData(), entries=list(wal.entries))
    v2 = ViewStub(self_id=1, leader_id=1)
    state2.restore(v2)
    assert v2.reverify_calls == []
    assert v2.phase == Phase.PROPOSED


def test_boot_view_honors_in_flight_wal_tail():
    """A tail pre-prepare from view 8 proves view 8 was installed before
    the crash even when the SavedNewView record was truncated away by the
    proposal append itself — boot must start there, not in the
    checkpoint's stale view (seed-3428 chaos wedge: restored replicas
    idled in view 1 holding view-8 proposal records)."""
    from consensus_tpu.core.state import InFlightData, PersistedState
    from consensus_tpu.testing.app import MemWAL
    from consensus_tpu.types import Proposal
    from consensus_tpu.wire import (
        PrePrepare,
        Prepare,
        ProposedRecord,
        SavedCommit,
        Commit,
        ViewMetadata,
        encode_saved,
        encode_view_metadata,
    )
    from consensus_tpu.types import Signature

    md = ViewMetadata(view_id=8, latest_sequence=5, decisions_in_view=2)
    proposal = Proposal(payload=b"p", metadata=encode_view_metadata(md))
    rec = ProposedRecord(
        pre_prepare=PrePrepare(view=8, seq=5, proposal=proposal),
        prepare=Prepare(view=8, seq=5, digest=proposal.digest()),
    )
    entries = [encode_saved(rec)]
    state = PersistedState(MemWAL(list(entries)), InFlightData(), entries=entries)
    assert state.load_in_flight_view_if_applicable() == (8, 2)

    # Behind our own commit too.
    commit = SavedCommit(commit=Commit(
        view=8, seq=5, digest=proposal.digest(),
        signature=Signature(id=1, value=b"v"),
    ))
    entries2 = [encode_saved(rec), encode_saved(commit)]
    state2 = PersistedState(MemWAL(list(entries2)), InFlightData(), entries=entries2)
    assert state2.load_in_flight_view_if_applicable() == (8, 2)

    # Not when something else ends the log.
    from consensus_tpu.wire import SavedViewChange, ViewChange

    entries3 = entries2 + [encode_saved(SavedViewChange(view_change=ViewChange(next_view=9)))]
    state3 = PersistedState(MemWAL(list(entries3)), InFlightData(), entries=entries3)
    assert state3.load_in_flight_view_if_applicable() is None


def test_boot_restores_buried_view_change_vote_from_endorsement_tail():
    """The buried-vote restore gap: a crash right after ``_commit_in_flight``
    persists its endorsement leaves the log ending ``[SavedViewChange,
    ProposedRecord, SavedCommit]``.  Before the backward scan in
    ``load_view_change_if_applicable`` the loader looked only at the LAST
    record, returned None, and the restarted replica forgot it had voted
    for the pending view change — this test fails against that version."""
    vote = ViewChange(next_view=3)
    rec = proposed_record(view=2, seq=5)
    sc = SavedCommit(
        commit=Commit(
            view=2, seq=5, digest=rec.pre_prepare.proposal.digest(),
            signature=Signature(id=2, value=b"s"),
        )
    )
    svc = SavedViewChange(view_change=vote)
    full = [encode_saved(svc), encode_saved(rec), encode_saved(sc)]
    # Crash AFTER the second endorsement append -> [vote, proposed, commit];
    # crash BETWEEN the two appends -> [vote, proposed].  Both must surface
    # the vote.
    for entries in (full, full[:2]):
        state = PersistedState(
            MemWAL(list(entries)), InFlightData(), entries=list(entries)
        )
        assert state.load_view_change_if_applicable() == vote, entries

    # The scan must NOT hallucinate a vote under ordinary tails: a normal
    # decide path ends [ProposedRecord, SavedCommit] (the proposal append
    # truncated everything before it) and a fresh proposal ends with just
    # the ProposedRecord.
    for entries in (
        [encode_saved(rec), encode_saved(sc)],
        [encode_saved(rec)],
        [encode_saved(sc)],
    ):
        state = PersistedState(
            MemWAL(list(entries)), InFlightData(), entries=list(entries)
        )
        assert state.load_view_change_if_applicable() is None, entries


def test_boot_with_buried_vote_starts_at_vote_target_view():
    """consensus.py::_set_view_and_seq with the endorsement tail: the
    embedded ProposedRecord deliberately keeps the proposal's ORIGINAL view
    stamp (restamping would fork the attestation from the commit signature
    already minted over it — peers match it by equality in
    ``check_in_flight``).  Safe because the original view is <= the vote's
    target, so once the buried vote is restored the in-flight-tail check
    cannot drag the boot view backwards — pinned here."""
    from consensus_tpu.consensus import Consensus

    vote = ViewChange(next_view=9)
    rec = proposed_record(view=8, seq=5)  # endorsement stamped with view 8
    sc = SavedCommit(
        commit=Commit(
            view=8, seq=5, digest=rec.pre_prepare.proposal.digest(),
            signature=Signature(id=2, value=b"s"),
        )
    )
    entries = [
        encode_saved(SavedViewChange(view_change=vote)),
        encode_saved(rec),
        encode_saved(sc),
    ]
    shell = Consensus.__new__(Consensus)  # only .state is consulted
    shell.state = PersistedState(
        MemWAL(list(entries)), InFlightData(), entries=list(entries)
    )
    # Checkpoint says view 8, seq 5: the vote must win the restore point.
    view, seq, dec = Consensus._set_view_and_seq(shell, 8, 5, 2)
    assert view == 9, "boot view must be the buried vote's target"
    assert seq == 5
    assert shell._restore_view_change == vote
