"""The Fabric-orderer-shaped embedder (examples/fabric_orderer.py, BASELINE
config 5): all ten ports in the orderer's shape — envelope inspector,
block-cutting assembler, hash-chained delivery, Ed25519 consenter sigs —
ordering correctly on the sim cluster."""

import hashlib

from examples.fabric_orderer import (
    _HEADER,
    ENVELOPE_BYTES,
    FabricShapedOrderer,
    _OrdererVerifier,
    make_envelope,
    parse_envelope,
)

from consensus_tpu.models import Ed25519Signer
from consensus_tpu.models.ed25519 import Ed25519BatchVerifier
from consensus_tpu.testing import Cluster


def test_envelope_round_trip():
    raw = make_envelope("mychannel", 42)
    assert len(raw) == ENVELOPE_BYTES
    info = parse_envelope(raw)
    assert info.client_id == "mychannel"
    assert info.request_id == "42"


def test_fabric_shaped_cluster_orders_hash_chained_blocks():
    cluster = Cluster(4)
    engine = Ed25519BatchVerifier(min_device_batch=10**9)
    signers = {i: Ed25519Signer(i) for i in cluster.nodes}
    keys = {i: s.public_bytes for i, s in signers.items()}
    for node_id, node in cluster.nodes.items():
        node.app = FabricShapedOrderer(
            node_id, cluster, signers[node_id],
            _OrdererVerifier(keys, engine=engine),
        )
    cluster.start()

    for i in range(3):
        cluster.submit_to_all(make_envelope("demo", i))
        assert cluster.run_until_ledger(i + 1, max_time=300.0), f"block {i} stalled"
    cluster.assert_ledgers_consistent()

    # Every replica's ledger is a valid hash chain of Fabric-shaped blocks
    # carrying real consenter signatures.
    for node in cluster.nodes.values():
        prev = b"\0" * 32
        for d in node.app.ledger:
            number, count, prev_hash, data_hash = _HEADER.unpack(d.proposal.header)
            assert prev_hash == prev
            assert hashlib.sha256(d.proposal.payload).digest() == data_hash
            assert len(d.signatures) >= 3  # quorum of consenter sigs
            prev = hashlib.sha256(d.proposal.header).digest()
