"""First-class dynamic reconfiguration (PR 9): membership epochs on the
wire, joining-node bootstrap, epoch-aware invariants, and churn chaos.

Covers the full elastic-membership surface:

* :mod:`consensus_tpu.membership` units — config validation, the epoch
  timeline arithmetic (the change decision is certified by the committee it
  retires), idempotent recording, ``ever_removed``;
* ``EpochTagged`` wire envelope — codec round-trip, nesting rejected on
  both the encode and decode paths;
* the facade's epoch gate — a removed-but-live node's continued traffic is
  dropped AND counted at every survivor, never causing an honest view
  change, while the zombie itself is nudged into sync by the higher-epoch
  traffic it receives and self-evicts;
* reconfiguration learned through the SYNC path (``Controller._do_sync``'s
  reconfig branch), not just commit-path delivery;
* eviction of the leader with ``pipeline_depth > 1`` — in-flight slots
  above the change are abandoned and re-proposed, no fork;
* :class:`~consensus_tpu.membership.JoinBootstrap` — retry/backoff spacing,
  backoff reset when the epoch advances mid-join, and the cluster-level
  join-through-injected-unreachability scenario;
* the seeded ``SENTINEL_STALE_MEMBERSHIP`` bug — a replica ignoring a
  committed reconfiguration keeps the retired committee certifying, which
  the epoch-aware invariant monitor must catch as ``epoch-cert`` and ddmin
  must shrink to a minimal churn schedule;
* churn chaos schedules (``generate(churn=True)``) — vocabulary gating and
  byte-identical replay;
* the ``membership_churn`` anomaly detector — edge-triggered firing.
"""

import struct

import pytest

import consensus_tpu.core.controller as controller_mod
from consensus_tpu.config import ObsConfig
from consensus_tpu.membership import (
    JoinBootstrap,
    MembershipConfig,
    MembershipDirectory,
)
from consensus_tpu.metrics import (
    MEMBERSHIP_JOIN_ATTEMPTS_KEY,
    MEMBERSHIP_JOIN_RETRIES_KEY,
    InMemoryProvider,
    Metrics,
)
from consensus_tpu.obs.detectors import DetectorBank, DetectorThresholds
from consensus_tpu.runtime.scheduler import SimScheduler
from consensus_tpu.testing import (
    Cluster,
    install_reconfig_hook,
    make_request,
    reconfig_request,
)
from consensus_tpu.testing.chaos import (
    CHURN_KINDS,
    ChaosAction,
    ChaosEngine,
    ChaosSchedule,
    shrink,
)
from consensus_tpu.testing.invariants import InvariantMonitor
from consensus_tpu.wire import EpochTagged, HeartBeat
from consensus_tpu.wire import codec as codec_mod
from consensus_tpu.wire.codec import CodecError, decode_message, encode_message

FAST = {
    "request_forward_timeout": 1.0,
    "request_complain_timeout": 4.0,
    "request_auto_remove_timeout": 120.0,
    "view_change_resend_interval": 2.0,
    "view_change_timeout": 10.0,
    "leader_heartbeat_timeout": 20.0,
}


@pytest.fixture
def stale_membership_bug():
    controller_mod.SENTINEL_STALE_MEMBERSHIP = True
    try:
        yield
    finally:
        controller_mod.SENTINEL_STALE_MEMBERSHIP = False


def _install_metrics(cluster):
    """Per-node InMemoryProvider metrics, installed BEFORE start so the
    Consensus builds wire them (same move the obs sampler makes)."""
    for node in cluster.nodes.values():
        node.metrics = Metrics(InMemoryProvider())


# --- membership units ------------------------------------------------------


def test_membership_config_sorts_and_derives_quorum():
    cfg = MembershipConfig(epoch=0, nodes=(4, 1, 3, 2))
    assert cfg.nodes == (1, 2, 3, 4)
    assert cfg.n == 4 and cfg.quorum == 3 and cfg.f == 1
    assert 3 in cfg and 9 not in cfg
    cfg.validate()
    # Two configs with the same member set compare equal regardless of
    # input order.
    assert cfg == MembershipConfig(epoch=0, nodes=(1, 2, 3, 4))


@pytest.mark.parametrize(
    "epoch,nodes",
    [
        (-1, (1, 2, 3, 4)),  # negative epoch
        (0, ()),  # empty membership
        (0, (0, 1, 2)),  # non-positive id
        (0, (1, 2, 2, 3)),  # duplicate id
    ],
)
def test_membership_config_validate_rejects(epoch, nodes):
    with pytest.raises(ValueError):
        MembershipConfig(epoch=epoch, nodes=nodes).validate()


def test_membership_directory_timeline_and_idempotence():
    directory = MembershipDirectory([1, 2, 3, 4])
    assert directory.current_epoch == 0
    assert directory.membership_at(None).epoch == 0

    # Grow at seq 5: the change decision itself is certified by the OLD
    # committee, so epoch 1 takes over at seq 6.
    grown = directory.record_change("d-grow", 5, (1, 2, 3, 4, 5))
    assert grown.epoch == 1 and grown.nodes == (1, 2, 3, 4, 5)
    assert directory.membership_at(5).epoch == 0
    assert directory.membership_at(6).epoch == 1
    assert directory.current_epoch == 1

    # Idempotent: a sync replay of the same digest returns the recorded
    # config and opens no new epoch.
    again = directory.record_change("d-grow", 5, (1, 2, 3, 4, 5))
    assert again is grown and directory.current_epoch == 1

    shrunk = directory.record_change("d-shrink", 9, (1, 2, 3, 4))
    assert shrunk.epoch == 2
    assert directory.membership_at(9).epoch == 1
    assert directory.membership_at(10).epoch == 2
    assert directory.ever_removed() == {5}
    assert directory.config_for_epoch(1) == grown
    assert directory.config_for_epoch(7) is None

    change = directory.changes[-1]
    assert change.removed == (5,) and change.added == ()
    assert "-5" in str(change)


# --- EpochTagged wire envelope ---------------------------------------------


def test_epoch_tagged_codec_round_trip():
    for inner in (HeartBeat(view=3, seq=17), HeartBeat(view=0)):
        tagged = EpochTagged(epoch=42, msg=inner)
        decoded = decode_message(encode_message(tagged))
        assert decoded == tagged
        assert decoded.epoch == 42 and decoded.msg == inner


def test_epoch_tagged_rejects_nesting_on_encode():
    nested = EpochTagged(epoch=2, msg=EpochTagged(epoch=1, msg=HeartBeat(view=0)))
    with pytest.raises(CodecError):
        encode_message(nested)


def test_epoch_tagged_rejects_nesting_on_decode():
    # The writer refuses to produce nested bytes, so hand-frame them: an
    # outer tag-14 envelope whose blob is ITSELF an EpochTagged encoding.
    inner = encode_message(EpochTagged(epoch=1, msg=HeartBeat(view=0)))
    forged = (
        bytes([codec_mod._VERSION, codec_mod._DOMAIN_WIRE, 14])
        + struct.pack(">Q", 2)
        + struct.pack(">I", len(inner))
        + inner
    )
    with pytest.raises(CodecError):
        decode_message(forged)


# --- removed-node traffic: dropped, counted, never a view change -----------


def test_removed_node_traffic_dropped_counted_and_zombie_self_evicts():
    """Partition node 5, evict it, heal: the zombie keeps transmitting at
    epoch 0.  Every survivor must drop-and-count that traffic at ingress
    (no honest view change), and the epoch-1 traffic the zombie receives
    must nudge it into sync, where it learns its own eviction and shuts
    down."""
    cluster = Cluster(5, config_tweaks=dict(FAST, epoch_tagging=True))
    install_reconfig_hook(cluster)
    _install_metrics(cluster)
    cluster.start()
    cluster.submit_to_all(make_request("c", 0))
    assert cluster.run_until_ledger(1)

    cluster.network.partition([5])
    cluster.submit_to_all(reconfig_request("rm5", [1, 2, 3, 4]))
    assert cluster.run_until_ledger(2, node_ids=[1, 2, 3, 4], max_time=300.0)
    cluster.scheduler.advance(30.0)

    survivors = [1, 2, 3, 4]
    for i in survivors:
        assert cluster.nodes[i].consensus.membership_epoch == 1
        assert cluster.nodes[i].metrics.membership.epoch.value == 1
    # The zombie never learned: still serving epoch 0.
    z = cluster.nodes[5]
    assert z.consensus is not None and z.consensus.membership_epoch == 0
    views_before = {
        i: cluster.nodes[i].consensus.controller.curr_view_number
        for i in survivors
    }

    # Inject an epoch-1 message straight into the zombie's ingress while it
    # is still partitioned: the gate must drop-and-count it, and — because
    # the SENDER is ahead — nudge the controller into sync.
    nudges = []
    orig_sync = z.consensus.controller.sync
    z.consensus.controller.sync = lambda: (nudges.append(1), orig_sync())[0]
    z.consensus.handle_message(1, EpochTagged(epoch=1, msg=HeartBeat(view=0)))
    cluster.scheduler.advance(1.0)
    z.consensus.controller.sync = orig_sync
    assert z.metrics.membership.count_stale_epoch_dropped.value == 1
    assert nudges, "sender-ahead stale traffic did not nudge sync"

    cluster.network.heal()
    cluster.scheduler.advance(150.0)

    # The zombie's epoch-0 sends were dropped AND counted at ingress.
    dropped = sum(
        cluster.nodes[i].metrics.membership.count_stale_epoch_dropped.value
        for i in survivors
    )
    assert dropped > 0, "survivors never counted the zombie's stale traffic"
    # Its complaints never reached a collector: no honest view change.
    for i in survivors:
        assert (
            cluster.nodes[i].consensus.controller.curr_view_number
            == views_before[i]
        ), f"removed node's traffic provoked a view change on {i}"
    # The zombie caught up through sync after the heal — learned its own
    # eviction and shut itself down.
    assert z.consensus is None or not z.consensus._running, (
        "zombie never learned of its eviction through the sync nudge"
    )
    z.running = False

    cluster.submit_to_all(make_request("c", 1))
    assert cluster.run_until_ledger(3, node_ids=survivors, max_time=300.0)
    cluster.assert_ledgers_consistent()


# --- reconfig learned through the sync path --------------------------------


def test_reconfig_learned_via_sync_path():
    """Node 4 is partitioned while the rest of the cluster orders an
    eviction (of node 5).  It must learn the reconfiguration through
    ``Controller._do_sync``'s reconfig branch — not commit-path delivery —
    adopt epoch 1, and participate in quorums afterwards."""
    cluster = Cluster(5, config_tweaks=FAST)
    install_reconfig_hook(cluster)
    cluster.start()
    cluster.submit_to_all(make_request("c", 0))
    assert cluster.run_until_ledger(1)

    cluster.network.partition([4])
    # {1,2,3,5} is exactly the old quorum of 4 — the evictee participates
    # in ordering its own eviction.  Submit only to the connected nodes:
    # node 4 must never hold the admin request, or it would re-forward it
    # after the heal and the leader would order a SECOND (idempotent but
    # epoch-bumping) membership change.
    for i in (1, 2, 3, 5):
        cluster.nodes[i].submit(reconfig_request("rm5", [1, 2, 3, 4]))
    assert cluster.run_until_ledger(2, node_ids=[1, 2, 3], max_time=300.0)
    cluster.scheduler.advance(30.0)
    n5 = cluster.nodes[5].consensus
    assert n5 is None or not n5._running, "evicted node 5 did not shut down"
    cluster.nodes[5].running = False

    # The post-change committee {1,2,3,4} has quorum 3; the three connected
    # members keep ordering while 4 is still dark.
    cluster.submit_to_all(make_request("c", 1))
    assert cluster.run_until_ledger(3, node_ids=[1, 2, 3], max_time=300.0)
    assert cluster.nodes[4].consensus.membership_epoch == 0
    assert len(cluster.nodes[4].app.ledger) == 1

    # Heal: node 4 detects the gap, syncs, and the LAST reconfig seen in
    # the synced chunk surfaces through _do_sync's reconfig branch.
    cluster.network.heal()
    cluster.scheduler.advance(150.0)
    assert cluster.nodes[4].consensus.membership_epoch == 1, (
        "sync-learned reconfiguration was not applied"
    )
    assert len(cluster.nodes[4].app.ledger) >= 3

    # Node 4 must now COUNT: crash node 3, so the epoch-1 quorum (3 of
    # {1,2,3,4}) cannot form without node 4.
    cluster.nodes[3].crash()
    cluster.submit_to_all(make_request("c", 2))
    assert cluster.run_until_ledger(4, node_ids=[1, 2, 4], max_time=600.0), (
        "sync-joined node 4 did not participate in the post-change quorum"
    )
    cluster.assert_ledgers_consistent()


# --- evicting the leader under pipelining ----------------------------------


def test_remove_leader_with_pipelined_slots():
    """Evict the CURRENT LEADER while ``pipeline_depth=3`` keeps multiple
    slots in flight: slots above the change decision are abandoned at the
    rebuild (their pool reservations released) and re-proposed under the
    new epoch — every submitted request still commits exactly once, no
    fork."""
    cluster = Cluster(5, config_tweaks=dict(FAST, pipeline_depth=3))
    install_reconfig_hook(cluster)
    cluster.start()
    cluster.submit_to_all(make_request("c", 0))
    assert cluster.run_until_ledger(1)

    # Fill the pipeline and slip the eviction of leader 1 into the stream.
    for i in range(1, 4):
        cluster.submit_to_all(make_request("c", i))
    cluster.submit_to_all(reconfig_request("rm1", [2, 3, 4, 5]))
    for i in range(4, 7):
        cluster.submit_to_all(make_request("c", i))

    survivors = [2, 3, 4, 5]
    # Everything submitted must eventually commit on the survivors: 1
    # warmup + 6 payloads + the reconfig = 8 decisions (batching may pack
    # several requests per decision, so require the REQUESTS, not a height).
    def all_committed():
        for i in survivors:
            payloads = b"|".join(
                d.proposal.payload for d in cluster.nodes[i].app.ledger
            )
            if not all(
                make_request("c", k) in payloads for k in range(7)
            ):
                return False
        return True

    assert cluster.scheduler.run_until(all_committed, max_time=900.0), (
        "requests in flight across the eviction were lost"
    )
    cluster.scheduler.advance(30.0)
    n1 = cluster.nodes[1].consensus
    assert n1 is None or not n1._running, "evicted ex-leader did not shut down"
    cluster.nodes[1].running = False
    for i in survivors:
        assert cluster.nodes[i].consensus.membership_epoch == 1

    # The rebuilt pool must accept and order NEW work (reservations from
    # the abandoned slots were released, not leaked).
    cluster.submit_to_all(make_request("d", 0))
    target = len(cluster.nodes[2].app.ledger) + 1
    assert cluster.run_until_ledger(target, node_ids=survivors, max_time=600.0)
    cluster.assert_ledgers_consistent()
    # No request committed twice.
    for i in survivors:
        digests = [d.proposal.digest() for d in cluster.nodes[i].app.ledger]
        assert len(digests) == len(set(digests))


# --- JoinBootstrap: retry / backoff ----------------------------------------


def test_join_bootstrap_backoff_spacing_and_epoch_reset():
    sched = SimScheduler()
    attempts_at = []
    state = {"done": False, "epoch": 0}
    provider = InMemoryProvider()
    metrics = Metrics(provider)
    jb = JoinBootstrap(
        sched,
        sync=lambda: attempts_at.append(sched.now()),
        caught_up=lambda: state["done"],
        current_epoch=lambda: state["epoch"],
        metrics=metrics.membership,
        initial_delay=2.0,
        max_delay=16.0,
        backoff=2.0,
    )
    jb.start()
    # Exponential spacing: attempts at 0, +2, +4, +8 ...
    sched.advance(13.0)
    assert attempts_at == [0.0, 2.0, 6.0]
    assert jb.attempts == 3 and jb.retries == 2

    # The membership epoch advances mid-join: the delay resets to the
    # initial value at the NEXT attempt (t=14), so the one after comes at
    # t=16 instead of t=30.
    state["epoch"] = 1
    sched.advance(4.0)  # t=17
    assert attempts_at == [0.0, 2.0, 6.0, 14.0, 16.0]

    # Catching up finishes the driver without another counted attempt.
    state["done"] = True
    sched.advance(10.0)
    assert jb.done
    assert jb.attempts == 5 and jb.retries == 4
    assert provider.value(MEMBERSHIP_JOIN_ATTEMPTS_KEY) == 5
    assert provider.value(MEMBERSHIP_JOIN_RETRIES_KEY) == 4


def test_join_bootstrap_stop_cancels_future_attempts():
    sched = SimScheduler()
    calls = []
    jb = JoinBootstrap(
        sched, sync=lambda: calls.append(sched.now()), caught_up=lambda: False
    )
    jb.start()
    sched.advance(0.5)
    assert len(calls) == 1
    jb.stop()
    sched.advance(600.0)
    assert len(calls) == 1 and jb.done


def test_added_node_bootstraps_through_injected_unreachability():
    """The DSL-visible join scenario from the acceptance bar: a node
    admitted by a grow decision boots while UNREACHABLE, keeps re-probing
    on backoff (counted into the pinned join metrics), and completes the
    wire sync promptly once the network heals — then counts in quorums."""
    cluster = Cluster(4, config_tweaks=FAST, obs=ObsConfig(enabled=True))
    install_reconfig_hook(cluster)
    cluster.start()
    cluster.submit_to_all(make_request("c", 0))
    assert cluster.run_until_ledger(1)

    cluster.submit_to_all(reconfig_request("add5", [1, 2, 3, 4, 5]))
    assert cluster.run_until_ledger(2, node_ids=[1, 2, 3, 4], max_time=300.0)
    cluster.scheduler.advance(5.0)

    # Admit node 5 behind a partition: every sync probe fails.
    cluster.network.partition([5])
    node5 = cluster.add_node(5)
    jb = node5.join_bootstrap
    assert jb is not None
    cluster.scheduler.advance(30.0)
    assert not jb.done
    assert jb.attempts >= 3 and jb.retries >= 2, (
        f"join did not keep retrying under unreachability: {jb.attempts}"
    )
    assert len(node5.app.ledger) == 0

    # Heal: the next backoff probe syncs the chain and the reconfig lifts
    # the joiner to the current epoch.
    cluster.network.heal()
    assert cluster.scheduler.run_until(lambda: jb.done, max_time=120.0), (
        "join bootstrap never completed after the heal"
    )
    assert node5.consensus.membership_epoch == 1
    assert len(node5.app.ledger) >= 2
    assert (
        node5.metrics.membership.count_join_attempts.value == jb.attempts
    )
    assert node5.metrics.membership.count_join_retries.value == jb.retries >= 2

    # Joined quorums for real: with node 4 down, epoch-1 quorum (4 of 5)
    # cannot form without node 5.
    cluster.nodes[4].crash()
    cluster.submit_to_all(make_request("c", 1))
    target = len(cluster.nodes[1].app.ledger) + 1
    assert cluster.run_until_ledger(
        target, node_ids=[1, 2, 3, 5], max_time=600.0
    ), "joiner did not participate in the post-join quorum"
    cluster.assert_ledgers_consistent()


# --- the seeded sentinel: stale membership ---------------------------------


def test_sentinel_stale_membership_caught_as_epoch_cert(stale_membership_bug):
    """With the sentinel armed every replica IGNORES the eviction decision:
    the retired committee keeps certifying.  Crashing node 4 first forces
    every later cert to include evicted node 5 — the epoch-aware monitor
    must flag those certs as ``epoch-cert`` violations naming the evictee."""
    cluster = Cluster(5, config_tweaks=FAST)
    install_reconfig_hook(cluster)
    monitor = InvariantMonitor(cluster)
    cluster.start()
    cluster.submit_to_all(make_request("c", 0))
    assert cluster.run_until_ledger(1)
    assert not monitor.violations

    # Down node 4: any further quorum (4 of 5) must include node 5.
    cluster.nodes[4].crash()
    cluster.submit_to_all(reconfig_request("rm5", [1, 2, 3, 4]))
    alive = [1, 2, 3, 5]
    assert cluster.run_until_ledger(2, node_ids=alive, max_time=300.0)
    # The change decision itself is certified by the OLD committee — legal.
    assert cluster.membership_directory.current_epoch == 1
    assert not monitor.violations

    # The bug: nobody rebuilt, node 5 keeps signing.  The next decision is
    # certified above the change by a retired committee.
    cluster.submit_to_all(make_request("c", 1))
    assert cluster.run_until_ledger(3, node_ids=alive, max_time=300.0)
    assert monitor.first is not None
    assert monitor.first.invariant == "epoch-cert"
    assert "previously removed: [5]" in monitor.first.detail
    with pytest.raises(Exception):
        monitor.assert_clean()


def test_sentinel_shrinks_to_minimal_churn_repro(stale_membership_bug):
    """A churn chaos schedule seeded with the stale-membership bug fails
    with ``epoch-cert``; ddmin must converge to a minimal reproducer that
    still contains the essential ``remove_node`` action."""
    schedule = ChaosSchedule(
        seed=0,
        n=5,
        actions=(
            ChaosAction(at=30.0, kind="crash", args={"node": 4}),
            ChaosAction(at=60.0, kind="remove_node", args={"node": 5}),
        ),
    )
    small, result = shrink(schedule, invariant="epoch-cert", max_runs=20)
    assert result.violation is not None
    assert result.violation.invariant == "epoch-cert"
    kinds = [a.kind for a in small.actions]
    assert "remove_node" in kinds
    assert len(small.actions) <= 2


# --- churn chaos schedules -------------------------------------------------


def test_generate_without_churn_has_no_churn_vocabulary():
    for seed in range(10):
        schedule = ChaosSchedule.generate(seed, n=4, steps=12)
        assert not any(a.kind in CHURN_KINDS for a in schedule.actions)


def test_churn_chaos_run_is_deterministic_and_clean():
    schedule = ChaosSchedule.generate(2, n=4, steps=12, churn=True)
    assert any(a.kind in CHURN_KINDS for a in schedule.actions), (
        "pinned seed 2 no longer draws churn actions — pick another seed"
    )
    results = [ChaosEngine(schedule).run() for _ in range(2)]
    assert results[0].ok, results[0].violation
    assert results[0].event_log == results[1].event_log, (
        "churn chaos run diverged across replays"
    )
    assert results[0].ledgers == results[1].ledgers


# --- membership_churn anomaly detector -------------------------------------


def test_membership_churn_detector_fires_in_churn_chaos_run():
    """End-to-end: two membership changes inside the churn window, observed
    through the sampler's health snapshots, fire the detector on the
    surviving members."""
    schedule = ChaosSchedule(
        seed=5,
        n=4,
        actions=(
            ChaosAction(at=40.0, kind="add_node", args={"node": 5}),
            ChaosAction(at=120.0, kind="remove_node", args={"node": 5}),
        ),
    )
    engine = ChaosEngine(schedule, obs=ObsConfig(enabled=True))
    result = engine.run()
    assert result.ok, result.violation
    counts = engine.cluster.sampler.anomaly_counts()
    assert counts.get("membership_churn", 0) >= 1, counts


def test_membership_churn_detector_fires_edge_triggered():
    bank = DetectorBank(DetectorThresholds(churn_epochs=2, churn_window=100.0))

    def ev(t, epoch):
        return bank.evaluate(t, {1: {"running": True, "epoch": epoch}})

    assert ev(0.0, 0) == []
    assert ev(10.0, 1) == []  # one change: below threshold
    fired = ev(20.0, 2)  # second change inside the window
    assert [a.kind for a in fired] == ["membership_churn"]
    assert fired[0].node == 1 and "epoch" in fired[0].detail
    # Latched while the condition holds: no re-fire.
    assert ev(30.0, 2) == []
    # Window expires -> latch clears -> a fresh burst fires again.
    assert ev(140.0, 2) == []
    assert ev(150.0, 3) == []
    assert [a.kind for a in ev(160.0, 4)] == ["membership_churn"]
