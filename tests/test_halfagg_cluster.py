"""Half-aggregated quorum certs threaded through the protocol: an
end-to-end cluster running ``Configuration.cert_mode="half-agg"`` with
real Ed25519, WAL restore of the cert-bearing SavedCommit twin, wire
catch-up serving half-agg certs, catch-up over a ledger whose cert
format flipped mid-history, the pinned cert byte counters, and the
mixed-cert-mode contradiction guard on the multi-batch port.

Runs on the aggregator's host big-int twin (``min_device_batch=10**9``
engines) — the device-kernel side of the same surfaces is pinned in
test_halfagg.py.
"""

import struct

from consensus_tpu.metrics import (
    CERT_AGGREGATE_LAUNCHES_KEY,
    CERT_BYTES_PER_CERT_KEY,
    CERT_FALLBACK_BISECTIONS_KEY,
    NET_CERT_BYTES_KEY,
    SYNC_CERT_BYTES_KEY,
    WAL_CERT_BYTES_KEY,
    InMemoryProvider,
    Metrics,
)
from consensus_tpu.models import Ed25519BatchVerifier, Ed25519Signer, Ed25519VerifierMixin
from consensus_tpu.sync import (
    InProcessSyncTransport,
    LedgerDecisionStore,
    LedgerSynchronizer,
    SyncServer,
)
from consensus_tpu.testing import Cluster, make_request, pack_batch
from consensus_tpu.testing.crypto_app import CryptoApp
from consensus_tpu.types import Decision, Proposal, QuorumCert
from consensus_tpu.wire import SavedCommit, ViewMetadata, encode_view_metadata
from consensus_tpu.wire.codec import decode_saved


class _SigVerifier(Ed25519VerifierMixin):
    def verify_proposal(self, proposal):
        raise NotImplementedError  # app half lives in CryptoApp

    def verify_request(self, raw):
        raise NotImplementedError

    def verification_sequence(self):
        return 0

    def requests_from_proposal(self, proposal):
        return []


def _halfagg_cluster(n=4, *, seed=0, cert_mode="half-agg"):
    tweaks = {} if cert_mode is None else {"cert_mode": cert_mode}
    cluster = Cluster(n, seed=seed, config_tweaks=tweaks)
    engine = Ed25519BatchVerifier(min_device_batch=10**9)  # host twin
    signers = {i: Ed25519Signer(i, bytes([i + 1]) * 32) for i in cluster.nodes}
    keys = {i: s.public_bytes for i, s in signers.items()}
    for node_id, node in cluster.nodes.items():
        node.metrics = Metrics(InMemoryProvider())
        node.app = CryptoApp(
            node_id, cluster, signers[node_id], _SigVerifier(keys, engine=engine)
        )
    return cluster


def test_halfagg_cluster_orders_with_aggregate_certs():
    cluster = _halfagg_cluster()
    cluster.start()
    for i in range(3):
        cluster.submit_to_all(make_request("c", i))
        assert cluster.run_until_ledger(i + 1, max_time=300.0), f"block {i} stalled"
    cluster.assert_ledgers_consistent()

    for node in cluster.nodes.values():
        for decision in node.app.ledger:
            cert = decision.signatures
            assert isinstance(cert, QuorumCert), "half-agg mode must decide certs"
            assert len(set(cert.signer_ids)) >= 3
            assert node.app.verify_aggregate_cert(cert, decision.proposal) is not None

    # Pinned accounting on the leader: every decide aggregated (one MSM
    # check each), WAL'd the compact twin, and broadcast cert bytes in the
    # next pre-prepare; the self-check never fell back.
    p = cluster.nodes[1].metrics.provider
    assert p.value(CERT_AGGREGATE_LAUNCHES_KEY) >= 3
    assert p.value(WAL_CERT_BYTES_KEY) > 0
    assert p.value(NET_CERT_BYTES_KEY) > 0
    assert p.observations(CERT_BYTES_PER_CERT_KEY)
    assert p.value(CERT_FALLBACK_BISECTIONS_KEY) == 0


def test_full_mode_stays_tuple_and_counts_nothing():
    cluster = _halfagg_cluster(cert_mode=None)
    cluster.start()
    cluster.submit_to_all(make_request("c", 0))
    assert cluster.run_until_ledger(1, max_time=300.0)
    for node in cluster.nodes.values():
        for decision in node.app.ledger:
            assert not isinstance(decision.signatures, QuorumCert)
        p = node.metrics.provider
        assert p.value(CERT_AGGREGATE_LAUNCHES_KEY) == 0
        assert p.value(WAL_CERT_BYTES_KEY) == 0
        assert p.value(NET_CERT_BYTES_KEY) == 0


def test_halfagg_saved_commit_survives_wal_restart():
    """The decide-time SavedCommit twin (cert attached, saved v3) must be
    on disk and the node must restart cleanly from a WAL containing it."""
    cluster = _halfagg_cluster()
    cluster.start()
    for i in range(2):
        cluster.submit_to_all(make_request("c", i))
        assert cluster.run_until_ledger(i + 1, max_time=300.0)

    node = cluster.nodes[2]
    cert_records = [
        rec for rec in (decode_saved(e) for e in node.wal_backing)
        if isinstance(rec, SavedCommit) and rec.cert is not None
    ]
    assert cert_records, "no cert-bearing SavedCommit twin reached the WAL"
    for rec in cert_records:
        assert isinstance(rec.cert, QuorumCert)
        assert len(set(rec.cert.signer_ids)) >= 3

    node.restart()
    cluster.submit_to_all(make_request("c", 2))
    assert cluster.run_until_ledger(3, max_time=300.0), (
        "restart from a v3 cert-bearing WAL wedged the node"
    )
    cluster.assert_ledgers_consistent()


def test_crashed_node_catches_up_over_halfagg_certs():
    """Wire catch-up in half-agg mode: the sync server serves QuorumCerts,
    the client verifies them through the aggregate path (one MSM check per
    cert) and accounts the synced cert bytes."""
    cluster = _halfagg_cluster()
    cluster.start()
    cluster.submit_to_all(make_request("c", 0))
    assert cluster.run_until_ledger(1, max_time=300.0)

    cluster.nodes[4].crash()
    for i in range(1, 3):
        cluster.submit_to_all(make_request("c", i))
        assert cluster.run_until_ledger(i + 1, node_ids=[1, 2, 3], max_time=300.0)

    cluster.nodes[4].start()
    assert cluster.run_until_ledger(3, max_time=600.0), "catch-up stalled"
    cluster.assert_ledgers_consistent()
    synced = cluster.nodes[4].app.ledger
    assert all(isinstance(d.signatures, QuorumCert) for d in synced)
    assert cluster.nodes[4].metrics.provider.value(SYNC_CERT_BYTES_KEY) > 0


# --- catch-up over a ledger with BOTH cert formats -------------------------


def _signed_chain(length, signers, keys, engine, *, halfagg_from):
    """A decision chain whose cert format flips mid-history (the shape a
    ledger has after ``cert_mode`` changed at a membership epoch boundary):
    positions < halfagg_from carry full signature tuples, the rest carry
    half-aggregated QuorumCerts built from the same signatures."""
    verifier = _SigVerifier(keys, engine=engine)
    chain = []
    for seq in range(1, length + 1):
        proposal = Proposal(
            payload=pack_batch([make_request("chain", seq)]),
            header=struct.pack(">Q", seq - 1),
            metadata=encode_view_metadata(
                ViewMetadata(view_id=0, latest_sequence=seq, decisions_in_view=seq)
            ),
        )
        sigs = tuple(
            signers[i].sign_proposal(proposal, b"aux") for i in (1, 3, 4)
        )
        if seq >= halfagg_from:
            cert = verifier.aggregate_cert(proposal, sigs)
            assert cert is not None
            chain.append(Decision(proposal=proposal, signatures=cert))
        else:
            chain.append(Decision(proposal=proposal, signatures=sigs))
    return chain


class _CountingVerifier:
    def __init__(self, inner):
        self.inner = inner
        self.calls = 0
        self.kinds = []

    def verify_consenter_sigs_multi_batch(self, groups):
        self.calls += 1
        self.kinds.append({isinstance(c, QuorumCert) for _, c in groups})
        return self.inner.verify_consenter_sigs_multi_batch(groups)


class _OpenNetwork:
    def node_ids(self):
        return [1, 2, 3, 4]

    def reachable(self, a, b):
        return True


def test_sync_catchup_over_mixed_cert_format_ledger():
    engine = Ed25519BatchVerifier(min_device_batch=10**9)
    signers = {i: Ed25519Signer(i, bytes([i + 1]) * 32) for i in (1, 2, 3, 4)}
    keys = {i: s.public_bytes for i, s in signers.items()}
    chain = _signed_chain(12, signers, keys, engine, halfagg_from=7)

    servers = {p: SyncServer(LedgerDecisionStore(list(chain))) for p in (1, 3, 4)}
    transport = InProcessSyncTransport(2, _OpenNetwork(), servers)
    counting = _CountingVerifier(_SigVerifier(keys, engine=engine))
    provider = InMemoryProvider()
    ledger = []
    client = LedgerSynchronizer(
        node_id=2,
        store=LedgerDecisionStore(ledger),
        transport=transport,
        verifier=counting,
        nodes=(1, 2, 3, 4),
        metrics=Metrics(provider).sync,
    )
    response = client.sync()

    assert len(ledger) == 12
    assert [d.proposal.digest() for d in ledger] == [
        d.proposal.digest() for d in chain
    ]
    # Formats survive the round trip: the pre-flip era stays full tuples,
    # the post-flip era stays compact.
    assert all(not isinstance(d.signatures, QuorumCert) for d in ledger[:6])
    assert all(isinstance(d.signatures, QuorumCert) for d in ledger[6:])
    assert response.latest.proposal.digest() == chain[-1].proposal.digest()
    # One chunk (12 < window), partitioned into one homogeneous multi-batch
    # call per cert format — never a mixed group.
    assert counting.calls == 2
    assert all(len(k) == 1 for k in counting.kinds)
    assert provider.value(SYNC_CERT_BYTES_KEY) > 0


def test_multi_batch_rejects_mixed_cert_modes():
    import pytest

    engine = Ed25519BatchVerifier(min_device_batch=10**9)
    signers = {i: Ed25519Signer(i, bytes([i + 1]) * 32) for i in (1, 2, 3, 4)}
    keys = {i: s.public_bytes for i, s in signers.items()}
    verifier = _SigVerifier(keys, engine=engine)
    proposal = Proposal(payload=b"x")
    sigs = tuple(signers[i].sign_proposal(proposal, b"") for i in (1, 2, 3))
    cert = verifier.aggregate_cert(proposal, sigs)
    assert cert is not None
    with pytest.raises(ValueError, match="contradict"):
        verifier.verify_consenter_sigs_multi_batch(
            [(proposal, sigs), (proposal, cert)]
        )
