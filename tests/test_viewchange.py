"""View-change tests: CheckInFlight condition tables, last-decision
validation, and full-cluster leader-failure scenarios including the
in-flight re-commit via the embedded PREPARED view.

Parity model: reference internal/bft/viewchanger_test.go
(TestCheckInFlight*:1667,1745, TestCommitInFlight:1907) and
test/basic_test.go failover scenarios.
"""

import pytest

from consensus_tpu.core.viewchanger import (
    check_in_flight,
    validate_in_flight,
    validate_last_decision,
)
from consensus_tpu.testing import Cluster, make_request
from consensus_tpu.types import Proposal, Signature
from consensus_tpu.wire import Commit, ViewData, ViewMetadata, encode_view_metadata

# n=4: f=1, quorum=3
F, QUORUM = 1, 3

FAST = {
    "request_forward_timeout": 1.0,
    "request_complain_timeout": 4.0,
    "request_auto_remove_timeout": 60.0,
    "view_change_resend_interval": 2.0,
    "view_change_timeout": 10.0,
}


def proposal_at(seq, view=0, payload=b"p"):
    md = ViewMetadata(view_id=view, latest_sequence=seq)
    return Proposal(payload=payload, metadata=encode_view_metadata(md))


def vd(last_seq=None, in_flight=None, prepared=False, next_view=1):
    last = proposal_at(last_seq) if last_seq is not None else Proposal()
    return ViewData(
        next_view=next_view,
        last_decision=last,
        in_flight_proposal=in_flight,
        in_flight_prepared=prepared,
    )


class TestCheckInFlight:
    def test_no_in_flight_anywhere_condition_b(self):
        msgs = [vd(last_seq=5) for _ in range(3)]
        ok, none_in_flight, proposal = check_in_flight(msgs, F, QUORUM)
        assert ok and none_in_flight and proposal is None

    def test_prepared_in_flight_agreed_condition_a(self):
        p = proposal_at(6, payload=b"inflight")
        msgs = [
            vd(last_seq=5, in_flight=p, prepared=True),
            vd(last_seq=5, in_flight=p, prepared=True),
            vd(last_seq=5),
        ]
        ok, none_in_flight, proposal = check_in_flight(msgs, F, QUORUM)
        assert ok and not none_in_flight and proposal == p

    def test_single_prepared_witness_not_enough_for_a_but_b_holds(self):
        # One prepared witness (< f+1): condition A fails; but the other
        # quorum of no-in-flight messages satisfies B.
        p = proposal_at(6)
        msgs = [
            vd(last_seq=5, in_flight=p, prepared=True),
            vd(last_seq=5),
            vd(last_seq=5),
            vd(last_seq=5),
        ]
        ok, none_in_flight, proposal = check_in_flight(msgs, F, QUORUM)
        assert ok and none_in_flight

    def test_not_prepared_in_flight_counts_as_none(self):
        p = proposal_at(6)
        msgs = [
            vd(last_seq=5, in_flight=p, prepared=False),
            vd(last_seq=5, in_flight=p, prepared=False),
            vd(last_seq=5),
        ]
        ok, none_in_flight, _ = check_in_flight(msgs, F, QUORUM)
        assert ok and none_in_flight

    def test_stale_sequence_in_flight_ignored(self):
        stale = proposal_at(3)  # expected sequence is 6
        msgs = [
            vd(last_seq=5, in_flight=stale, prepared=True),
            vd(last_seq=5),
            vd(last_seq=5),
        ]
        ok, none_in_flight, _ = check_in_flight(msgs, F, QUORUM)
        assert ok and none_in_flight

    def test_undecided_when_prepared_but_quorum_contradicts(self):
        # Two different prepared proposals at the expected sequence: each has
        # f+1 prepared witnesses? No -- one each, so neither satisfies A2,
        # and only 1 message says no-in-flight, so B fails too.
        p1 = proposal_at(6, payload=b"a")
        p2 = proposal_at(6, payload=b"b")
        msgs = [
            vd(last_seq=5, in_flight=p1, prepared=True),
            vd(last_seq=5, in_flight=p2, prepared=True),
            vd(last_seq=5),
        ]
        ok, _, _ = check_in_flight(msgs, F, QUORUM)
        assert not ok

    def test_expected_sequence_uses_max_last_decision(self):
        # One reporter is a decision ahead: expected in-flight seq follows
        # *its* last decision.
        p = proposal_at(7)
        msgs = [
            vd(last_seq=6, in_flight=p, prepared=True),
            vd(last_seq=6, in_flight=p, prepared=True),
            vd(last_seq=5),
        ]
        ok, none_in_flight, proposal = check_in_flight(msgs, F, QUORUM)
        assert ok and not none_in_flight and proposal == p


class BatchVerifier:
    """Counts batch calls; accepts sigs whose value matches 'sig-<id>'."""

    def __init__(self):
        self.batch_calls = 0

    def verify_consenter_sigs_batch(self, signatures, proposal):
        self.batch_calls += 1
        return [
            sig.msg if sig.value == b"sig-%d" % sig.id else None
            for sig in signatures
        ]


class TestValidateLastDecision:
    def sigs(self, ids):
        return tuple(Signature(id=i, value=b"sig-%d" % i) for i in ids)

    def test_genesis_passes_without_signatures(self):
        data = ViewData(next_view=1, last_decision=Proposal())
        assert validate_last_decision(data, QUORUM, BatchVerifier()) == 0

    def test_quorum_of_valid_signatures_passes_in_one_batch(self):
        v = BatchVerifier()
        data = ViewData(
            next_view=1,
            last_decision=proposal_at(5),
            last_decision_signatures=self.sigs([1, 2, 3]),
        )
        assert validate_last_decision(data, QUORUM, v) == 5
        assert v.batch_calls == 1

    def test_too_few_signatures_rejected(self):
        data = ViewData(
            next_view=1,
            last_decision=proposal_at(5),
            last_decision_signatures=self.sigs([1, 2]),
        )
        with pytest.raises(ValueError):
            validate_last_decision(data, QUORUM, BatchVerifier())

    def test_duplicate_signers_dont_count_twice(self):
        data = ViewData(
            next_view=1,
            last_decision=proposal_at(5),
            last_decision_signatures=self.sigs([1, 2]) + self.sigs([2]),
        )
        with pytest.raises(ValueError):
            validate_last_decision(data, QUORUM, BatchVerifier())

    def test_forged_signature_rejected(self):
        sigs = self.sigs([1, 2]) + (Signature(id=3, value=b"forged"),)
        data = ViewData(
            next_view=1, last_decision=proposal_at(5), last_decision_signatures=sigs
        )
        with pytest.raises(ValueError):
            validate_last_decision(data, QUORUM, BatchVerifier())

    def test_decision_from_future_view_rejected(self):
        data = ViewData(
            next_view=1,
            last_decision=proposal_at(5, view=1),
            last_decision_signatures=self.sigs([1, 2, 3]),
        )
        with pytest.raises(ValueError):
            validate_last_decision(data, QUORUM, BatchVerifier())


class TestValidateInFlight:
    def test_none_ok(self):
        validate_in_flight(None, 5)

    def test_sequence_must_follow_last_decision(self):
        validate_in_flight(proposal_at(6), 5)
        with pytest.raises(ValueError):
            validate_in_flight(proposal_at(7), 5)
        with pytest.raises(ValueError):
            validate_in_flight(Proposal(payload=b"no-md"), 5)


# --- full-cluster failure scenarios ---------------------------------------


def test_leader_crash_triggers_view_change_and_ordering_resumes():
    cluster = Cluster(4, config_tweaks=FAST)
    cluster.start()
    cluster.submit_to_all(make_request("c", 0))
    assert cluster.run_until_ledger(1)

    # Kill the leader of view 0 (node 1).
    cluster.nodes[1].crash()
    cluster.submit_to_all(make_request("c", 1))
    # forward (1s) -> complain (4s) -> view change -> new leader orders.
    assert cluster.run_until_ledger(2, node_ids=[2, 3, 4], max_time=600.0), (
        "view change did not restore ordering"
    )
    cluster.assert_ledgers_consistent()
    for node_id in (2, 3, 4):
        assert cluster.nodes[node_id].consensus.controller.curr_view_number >= 1


def test_view_change_commits_in_flight_proposal():
    # Stage: all commits are dropped, so every replica reaches PREPARED but
    # nobody decides. Then the leader dies. The view change must agree on
    # the in-flight proposal (condition A) and re-commit it in the new view.
    cluster = Cluster(4, config_tweaks=FAST)
    cluster.start()
    cluster.network.lose_messages = lambda target, sender, msg: isinstance(msg, Commit)
    cluster.submit_to_all(make_request("c", 0))

    def all_prepared():
        from consensus_tpu.core.view import Phase

        count = 0
        for node in cluster.nodes.values():
            c = node.consensus.controller
            if c.curr_view is not None and c.curr_view.phase == Phase.PREPARED:
                count += 1
        return count >= 3

    assert cluster.scheduler.run_until(all_prepared, max_time=60.0)
    assert all(len(n.app.ledger) == 0 for n in cluster.nodes.values())

    cluster.nodes[1].crash()
    cluster.network.lose_messages = None  # commits flow again

    assert cluster.run_until_ledger(1, node_ids=[2, 3, 4], max_time=600.0), (
        "in-flight proposal was not committed by the view change"
    )
    cluster.assert_ledgers_consistent()
    # The committed decision is the original in-flight proposal.
    from consensus_tpu.testing.app import unpack_batch

    for node_id in (2, 3, 4):
        ledger = cluster.nodes[node_id].app.ledger
        assert len(ledger) >= 1
        assert make_request("c", 0) in unpack_batch(ledger[0].proposal.payload)


def test_ordering_continues_after_two_successive_leader_crashes():
    cluster = Cluster(7, config_tweaks=FAST)  # f=2: tolerate two crashes
    cluster.start()
    cluster.submit_to_all(make_request("c", 0))
    assert cluster.run_until_ledger(1)

    cluster.nodes[1].crash()
    cluster.submit_to_all(make_request("c", 1))
    alive = [2, 3, 4, 5, 6, 7]
    assert cluster.run_until_ledger(2, node_ids=alive, max_time=600.0)

    cluster.nodes[2].crash()
    cluster.submit_to_all(make_request("c", 2))
    alive = [3, 4, 5, 6, 7]
    assert cluster.run_until_ledger(3, node_ids=alive, max_time=900.0)
    cluster.assert_ledgers_consistent()


def test_heartbeat_timeout_triggers_view_change_without_requests():
    # No client traffic at all: a silent leader must still be deposed via
    # the heartbeat path.
    cluster = Cluster(4, config_tweaks=dict(FAST, leader_heartbeat_timeout=8.0))
    cluster.start()
    # Let the cluster settle, then kill the leader.
    cluster.scheduler.advance(2.0)
    cluster.nodes[1].crash()
    ok = cluster.scheduler.run_until(
        lambda: all(
            cluster.nodes[i].consensus.controller.curr_view_number >= 1
            for i in (2, 3, 4)
        ),
        max_time=600.0,
    )
    assert ok, "heartbeat timeout did not depose the silent leader"
    # And the new view still orders requests.
    cluster.submit_to_all(make_request("c", 0))
    assert cluster.run_until_ledger(1, node_ids=[2, 3, 4], max_time=300.0)


# --- view-changer unit harness (crash-restore path) ------------------------


class _VCStubController:
    def __init__(self):
        self.aborted = []
        self.changed = []
        self.delivered = []
        self.synced = 0

    def abort_view(self, view):
        self.aborted.append(view)

    def view_changed(self, view, seq):
        self.changed.append((view, seq))

    def sync(self):
        self.synced += 1

    def deliver(self, proposal, signatures):
        from consensus_tpu.types import Reconfig

        self.delivered.append((proposal, tuple(signatures)))
        return Reconfig()

    def maybe_prune_revoked_requests(self):
        pass


class _VCStubTimer:
    def __init__(self):
        self.stopped = 0
        self.restarted = 0

    def stop_timers(self):
        self.stopped += 1

    def restart_timers(self):
        self.restarted += 1

    def remove_request(self, info):
        return True

    def remove_requests(self, infos):
        return 0


class _VCComm:
    def __init__(self):
        self.broadcasts = []
        self.sent = []

    def broadcast(self, msg):
        self.broadcasts.append(msg)

    def send(self, target, msg):
        self.sent.append((target, msg))


def _make_vc(view=0):
    from consensus_tpu.core.state import InFlightData, PersistedState
    from consensus_tpu.core.viewchanger import ViewChanger
    from consensus_tpu.runtime import SimScheduler
    from consensus_tpu.testing import MemWAL
    from consensus_tpu.types import Checkpoint

    class TrivialSigner:
        def sign(self, data):
            return b"sig-2"

        def sign_proposal(self, proposal, aux=b""):
            return Signature(id=2, value=b"sig-2", msg=aux)

    sched = SimScheduler()
    comm = _VCComm()
    controller = _VCStubController()
    timer = _VCStubTimer()
    in_flight = InFlightData()
    state = PersistedState(MemWAL([]), in_flight, entries=[])
    vc = ViewChanger(
        scheduler=sched,
        self_id=2,
        n=4,
        nodes=(1, 2, 3, 4),
        comm=comm,
        signer=TrivialSigner(),
        verifier=BatchVerifier2(),
        checkpoint=Checkpoint(),
        in_flight=in_flight,
        state=state,
        controller=controller,
        requests_timer=timer,
        synchronizer=controller,
        application=controller,
        leader_rotation=False,
        decisions_per_leader=0,
    )
    return vc, sched, comm, controller, timer


class BatchVerifier2(BatchVerifier):
    def verify_signature(self, signature):
        if signature.value != b"sig-%d" % signature.id:
            raise ValueError("bad")

    def requests_from_proposal(self, proposal):
        return []


def test_restore_pending_view_change_rejoins_and_rearms():
    # A replica that crashed after persisting its ViewChange vote must, on
    # restart, re-broadcast the vote, arm the timeout, and send ViewData to
    # the next leader (reference: the Restore channel + '|| restore' join).
    from consensus_tpu.wire import SignedViewData as SVD, ViewChange as VC

    vc, sched, comm, controller, timer = _make_vc()
    vc.start(0, restore_view_change=VC(next_view=0))
    sched.advance(0.5)  # run the posted restore event; stay below timeouts

    vc_msgs = [m for m in comm.broadcasts if isinstance(m, VC)]
    assert vc_msgs and vc_msgs[0].next_view == 1, "must re-broadcast the vote"
    assert vc._check_timeout, "view-change timeout must be armed"
    assert vc.curr_view == 1
    # ViewData went to the next leader (node 2 = ourselves? leader of view 1
    # without rotation is nodes[1 % 4] = 2) -- we ARE the next leader, so the
    # vote is registered locally instead of sent.
    assert vc._view_data_votes.get(2) is not None
    vc.stop()


def test_restore_resend_fires_until_quorum():
    from consensus_tpu.wire import ViewChange as VC

    vc, sched, comm, controller, timer = _make_vc()
    vc.start(0, restore_view_change=VC(next_view=0))
    sched.run_until(lambda: False, max_time=11.0)  # let resend ticks fire
    vc_msgs = [m for m in comm.broadcasts if isinstance(m, VC)]
    assert len(vc_msgs) >= 2, "vote must be re-broadcast on the resend timer"
    vc.stop()


class TestCheckInFlightReferenceTable:
    """The reference's full CheckInFlight decision table, ported case by
    case.  Parity: reference viewchanger_test.go:1667-1745
    (TestCheckInFlightNoProposal) and :1745-1905 (TestCheckInFlightWithProposal).
    n=4, f=1, quorum=3; last decision at seq 1, expected in-flight seq 2."""

    def _expected(self):
        return proposal_at(2, payload=b"expected")

    def _old(self):
        # "Old in flight" = the last decision itself (seq 1 != expected 2).
        return proposal_at(1)

    def run_case(self, msgs):
        return check_in_flight(msgs, F, QUORUM)

    # --- no-proposal outcomes (all must return ok) ----------------------

    def test_all_without_in_flight(self):
        ok, no, prop = self.run_case([vd(last_seq=1) for _ in range(4)])
        assert (ok, no, prop) == (True, True, None)

    def test_all_with_old_in_flight(self):
        msgs = [vd(last_seq=1, in_flight=self._old()) for _ in range(4)]
        ok, no, prop = self.run_case(msgs)
        assert (ok, no, prop) == (True, True, None)

    def test_quorum_without_one_with_unprepared_expected(self):
        msgs = [vd(last_seq=1) for _ in range(4)]
        msgs[0] = vd(last_seq=1, in_flight=self._expected())
        ok, no, prop = self.run_case(msgs)
        assert (ok, no, prop) == (True, True, None)

    def test_all_old_one_with_unprepared_expected(self):
        msgs = [vd(last_seq=1, in_flight=self._old()) for _ in range(4)]
        msgs[0] = vd(last_seq=1, in_flight=self._expected())
        ok, no, prop = self.run_case(msgs)
        assert (ok, no, prop) == (True, True, None)

    def test_mix_of_none_old_and_unprepared_expected(self):
        msgs = [
            vd(last_seq=1, in_flight=self._old()),
            vd(last_seq=1, in_flight=self._old()),
            vd(last_seq=1, in_flight=self._expected()),
            vd(last_seq=1),
        ]
        ok, no, prop = self.run_case(msgs)
        assert (ok, no, prop) == (True, True, None)

    def test_two_unprepared_expected_still_condition_b(self):
        msgs = [
            vd(last_seq=1, in_flight=self._old()),
            vd(last_seq=1),
            vd(last_seq=1, in_flight=self._expected()),
            vd(last_seq=1, in_flight=self._expected()),
        ]
        ok, no, prop = self.run_case(msgs)
        assert (ok, no, prop) == (True, True, None)

    # --- with-proposal outcomes -----------------------------------------

    def test_all_prepared_expected(self):
        exp = self._expected()
        msgs = [vd(last_seq=1, in_flight=exp, prepared=True) for _ in range(4)]
        ok, no, prop = self.run_case(msgs)
        assert (ok, no, prop) == (True, False, exp)

    def test_quorum_prepared_expected_one_without(self):
        exp = self._expected()
        msgs = [vd(last_seq=1, in_flight=exp, prepared=True) for _ in range(4)]
        msgs[0] = vd(last_seq=1)
        ok, no, prop = self.run_case(msgs)
        assert (ok, no, prop) == (True, False, exp)

    def test_quorum_prepared_expected_one_with_old(self):
        exp = self._expected()
        msgs = [vd(last_seq=1, in_flight=exp, prepared=True) for _ in range(4)]
        msgs[0] = vd(last_seq=1, in_flight=self._old(), prepared=True)
        ok, no, prop = self.run_case(msgs)
        assert (ok, no, prop) == (True, False, exp)

    def test_quorum_prepared_expected_one_with_different(self):
        exp = self._expected()
        different = proposal_at(2, payload=b"different")
        msgs = [vd(last_seq=1, in_flight=exp, prepared=True) for _ in range(4)]
        msgs[0] = vd(last_seq=1, in_flight=different, prepared=True)
        ok, no, prop = self.run_case(msgs)
        assert (ok, no, prop) == (True, False, exp)

    def test_one_prepared_expected_carried_by_quorum_one_different(self):
        exp = self._expected()
        different = proposal_at(2, payload=b"different-header")
        msgs = [
            vd(last_seq=1, in_flight=different),
            vd(last_seq=1, in_flight=exp),
            vd(last_seq=1, in_flight=exp),
            vd(last_seq=1, in_flight=exp, prepared=True),
        ]
        ok, no, prop = self.run_case(msgs)
        assert (ok, no, prop) == (True, False, exp)

    def test_all_expected_but_none_prepared(self):
        exp = self._expected()
        msgs = [vd(last_seq=1, in_flight=exp, prepared=False) for _ in range(4)]
        ok, no, prop = self.run_case(msgs)
        assert (ok, no, prop) == (True, True, None)

    def test_split_prepared_no_quorum_on_any(self):
        exp = self._expected()
        different = proposal_at(2, payload=b"split")
        msgs = [
            vd(last_seq=1, in_flight=exp, prepared=True),
            vd(last_seq=1, in_flight=exp, prepared=True),
            vd(last_seq=1, in_flight=different, prepared=True),
            vd(last_seq=1, in_flight=different, prepared=True),
        ]
        ok, no, prop = self.run_case(msgs)
        assert (ok, no, prop) == (False, False, None)

    def test_single_prepared_witness_rest_empty_condition_b(self):
        msgs = [vd(last_seq=1) for _ in range(4)]
        msgs[2] = vd(last_seq=1, in_flight=self._expected(), prepared=True)
        ok, no, prop = self.run_case(msgs)
        assert (ok, no, prop) == (True, True, None)

    def test_three_way_split_not_enough_for_anything(self):
        """Sub-f+1 prepared splits stall the change — DELIBERATELY.  A
        supersession rule discarding the lower-view attestation is sound
        crash-only but unsound with f byzantine (a commit-quorum member
        can deny its signature and fabricate a higher-view claim, forking
        a committed sequence); without carried prepare certificates the
        stall is the safe outcome, as in the reference."""
        exp = self._expected()
        other_view = proposal_at(2, view=1, payload=b"expected")
        other_vseq = Proposal(
            payload=b"expected",
            metadata=exp.metadata,
            verification_sequence=5,
        )
        msgs = [
            vd(last_seq=1),
            vd(last_seq=1, in_flight=other_vseq, prepared=True),
            vd(last_seq=1, in_flight=exp, prepared=True),
            vd(last_seq=1, in_flight=other_view, prepared=True),
        ]
        ok, no, prop = self.run_case(msgs)
        assert (ok, no, prop) == (False, False, None)

    def test_same_view_split_still_unresolvable(self):
        """All-same-view single-witness splits likewise keep waiting."""
        exp = self._expected()
        a = proposal_at(2, payload=b"a")
        b = proposal_at(2, payload=b"b")
        msgs = [
            vd(last_seq=1),
            vd(last_seq=1, in_flight=a, prepared=True),
            vd(last_seq=1, in_flight=exp, prepared=True),
            vd(last_seq=1, in_flight=b, prepared=True),
        ]
        ok, no, prop = self.run_case(msgs)
        assert (ok, no, prop) == (False, False, None)


class TestAdversarialViewChangeInputs:
    """Bad SignedViewData / NewView matrices driven through the public
    process paths.  Parity: reference viewchanger_test.go (bad-ViewData and
    validateNewViewMsg cases around :500-1100)."""

    def _signed_vd(self, signer, data, *, forge=False):
        from consensus_tpu.wire import SignedViewData, encode_view_data

        raw = encode_view_data(data)
        value = b"sig-%d" % (signer if not forge else signer + 1)
        return SignedViewData(signer=signer, raw_view_data=raw, signature=value)

    def _start_change(self, vc, sched):
        from consensus_tpu.wire import ViewChange as VC

        # Bring the changer into "collecting ViewData for view 1" as the
        # next leader (self_id 2 leads view 1 without rotation).
        vc.start(0)
        for sender in (1, 3, 4):
            vc.handle_message(sender, VC(next_view=1))
        sched.advance(0.1)

    def test_view_data_to_non_leader_ignored(self):
        vc, sched, comm, controller, timer = _make_vc()
        # Without any view change, we are NOT the leader of view 0
        # (leader of view 0 is node 1); a stray ViewData must be dropped.
        data = vd(last_seq=0, next_view=0)
        vc.handle_message(3, self._signed_vd(3, data))
        assert vc._view_data_votes.get(3) is None
        vc.stop()

    def test_view_data_with_forged_signature_rejected(self):
        vc, sched, comm, controller, timer = _make_vc()
        self._start_change(vc, sched)
        data = vd(last_seq=0, next_view=1)
        vc.handle_message(3, self._signed_vd(3, data, forge=True))
        assert vc._view_data_votes.get(3) is None
        vc.stop()

    def test_view_data_signer_sender_mismatch_rejected(self):
        vc, sched, comm, controller, timer = _make_vc()
        self._start_change(vc, sched)
        data = vd(last_seq=0, next_view=1)
        # Node 4 relays node 3's (validly signed) ViewData: must not count
        # as node 4's vote, and must not count for 3 either (sender binding).
        vc.handle_message(4, self._signed_vd(3, data))
        assert vc._view_data_votes.get(4) is None
        assert vc._view_data_votes.get(3) is None
        vc.stop()

    def test_view_data_for_wrong_next_view_rejected(self):
        vc, sched, comm, controller, timer = _make_vc()
        self._start_change(vc, sched)
        data = vd(last_seq=0, next_view=3)  # we are collecting for view 1
        vc.handle_message(3, self._signed_vd(3, data))
        assert vc._view_data_votes.get(3) is None
        vc.stop()

    def test_new_view_with_undecodable_view_data_rejected(self):
        from consensus_tpu.wire import NewView, SignedViewData

        vc, sched, comm, controller, timer = _make_vc()
        self._start_change(vc, sched)
        bad = NewView(signed_view_data=(
            SignedViewData(signer=1, raw_view_data=b"\xff\xff", signature=b"sig-1"),
        ))
        before = controller.changed[:]
        vc._process_new_view(bad)
        assert controller.changed == before
        vc.stop()

    def test_new_view_duplicate_signers_not_counted_twice(self):
        from consensus_tpu.wire import NewView

        vc, sched, comm, controller, timer = _make_vc()
        self._start_change(vc, sched)
        data = vd(last_seq=0, next_view=1)
        svd3 = self._signed_vd(3, data)
        bad = NewView(signed_view_data=(svd3, svd3, svd3))  # 1 unique < quorum
        before = controller.changed[:]
        vc._process_new_view(bad)
        assert controller.changed == before
        vc.stop()

    def test_new_view_with_quorum_of_valid_view_data_installs(self):
        from consensus_tpu.wire import NewView

        vc, sched, comm, controller, timer = _make_vc()
        self._start_change(vc, sched)
        # Genesis ViewData (empty last decision) matches our checkpoint.
        data = vd(next_view=1)
        nv = NewView(signed_view_data=tuple(
            self._signed_vd(s, data) for s in (1, 3, 4)
        ))
        vc._process_new_view(nv)
        assert controller.changed, "quorum NewView must install the view"
        assert vc.real_view == 1
        vc.stop()


class TestViewDataLastDecisionPaths:
    """The new leader's last-decision walk inside ViewData validation —
    behind / equal / one-ahead / far-ahead senders.  Parity: reference
    viewchanger.go:535-666 via viewchanger_test.go (TestCommitLastDecision
    :1133, the "greater last decision sequence", "last decision not equal"
    and "nil last decision" rows of TestBadViewDataMessage:479)."""

    def _signed_vd(self, signer, data):
        from consensus_tpu.wire import SignedViewData, encode_view_data

        return SignedViewData(
            signer=signer,
            raw_view_data=encode_view_data(data),
            signature=b"sig-%d" % signer,
        )

    def _collecting_vc(self):
        from consensus_tpu.wire import ViewChange as VC

        vc, sched, comm, controller, timer = _make_vc()
        vc.start(0)
        for sender in (1, 3, 4):
            vc.handle_message(sender, VC(next_view=1))
        sched.advance(0.1)
        return vc, sched, comm, controller

    def _sigs(self, ids):
        return tuple(Signature(id=i, value=b"sig-%d" % i) for i in ids)

    def test_one_ahead_last_decision_is_delivered_then_counted(self):
        """A sender exactly one decision ahead: the new leader validates the
        carried quorum, DELIVERS that decision itself, and the vote counts.
        Parity: reference TestCommitLastDecision (viewchanger_test.go:1133)."""
        vc, sched, comm, controller = self._collecting_vc()
        decision = proposal_at(1)  # our checkpoint is genesis (seq 0)
        data = ViewData(
            next_view=1,
            last_decision=decision,
            last_decision_signatures=self._sigs([1, 3, 4]),
        )
        vc.handle_message(3, self._signed_vd(3, data))
        assert controller.delivered, "one-ahead decision was not delivered"
        assert controller.delivered[0][0] == decision
        assert vc._view_data_votes.get(3) is not None, "vote did not count"
        vc.stop()

    def test_far_ahead_last_decision_rejected_without_delivery(self):
        """More than one ahead: this leader may lack the config to validate
        the gap — the vote is rejected and NOTHING is delivered (liveness
        comes from the view-change timeout's sync path, a documented
        deviation from the reference's immediate Sync call)."""
        vc, sched, comm, controller = self._collecting_vc()
        data = ViewData(
            next_view=1,
            last_decision=proposal_at(2),  # two ahead of genesis
            last_decision_signatures=self._sigs([1, 3, 4]),
        )
        vc.handle_message(3, self._signed_vd(3, data))
        assert not controller.delivered
        assert vc._view_data_votes.get(3) is None
        vc.stop()

    def test_one_ahead_with_invalid_quorum_not_delivered(self):
        """One ahead but the carried signature set does not form a valid
        quorum: the decision must NOT be delivered (a forged 'ahead'
        ViewData would otherwise inject a block)."""
        vc, sched, comm, controller = self._collecting_vc()
        data = ViewData(
            next_view=1,
            last_decision=proposal_at(1),
            last_decision_signatures=self._sigs([1, 3]),  # quorum-1
        )
        vc.handle_message(3, self._signed_vd(3, data))
        assert not controller.delivered
        assert vc._view_data_votes.get(3) is None
        vc.stop()

    def test_nil_last_decision_rejected(self):
        vc, sched, comm, controller = self._collecting_vc()
        data = ViewData(next_view=1, last_decision=None)
        vc.handle_message(3, self._signed_vd(3, data))
        assert vc._view_data_votes.get(3) is None
        vc.stop()

    def test_same_seq_different_decision_rejected(self):
        """Equal sequence but a DIFFERENT decision than ours: reject (one of
        us is provably wrong; counting the vote could seed a fork)."""
        vc, sched, comm, controller = self._collecting_vc()
        mine = proposal_at(3, payload=b"mine")
        vc._checkpoint.set(mine, [])
        theirs = proposal_at(3, payload=b"theirs")
        data = ViewData(
            next_view=1,
            last_decision=theirs,
            last_decision_signatures=self._sigs([1, 3, 4]),
        )
        vc.handle_message(3, self._signed_vd(3, data))
        assert vc._view_data_votes.get(3) is None
        # And the matching decision DOES count.
        data_ok = ViewData(
            next_view=1,
            last_decision=mine,
            last_decision_signatures=self._sigs([1, 3, 4]),
        )
        vc.handle_message(3, self._signed_vd(3, data_ok))
        assert vc._view_data_votes.get(3) is not None
        vc.stop()


class TestViewChangeTimeoutBackoff:
    """Timeout escalation with exponential backoff + resend liveness aids.
    Parity: reference viewchanger_test.go (TestViewChangerTimeout:1009,
    TestBackOff:1067, TestResendViewChangeMessage:954)."""

    def test_timeout_syncs_escalates_and_backs_off(self):
        from consensus_tpu.wire import ViewChange as VC

        vc, sched, comm, controller, timer = _make_vc()
        vc.start(0)
        vc.start_view_change(0, stop_view=True)  # nobody joins: stalls
        assert vc._check_timeout and vc._backoff_factor == 1
        start_broadcasts = len(comm.broadcasts)

        sched.advance(vc._vc_timeout + 1.5)  # first timeout window
        assert controller.synced == 1, "timeout must trigger a sync"
        assert vc._backoff_factor == 2, "backoff factor must grow"
        # The timeout RE-REQUESTS the same next view (escalating the target
        # is the f+1 jump rule's job, as in the reference); the restarted
        # change broadcast a fresh ViewChange vote.
        assert vc.next_view == 1
        assert len(comm.broadcasts) > start_broadcasts

        # Each timeout ROUND restarts its clock (round 5): the next
        # deadline is (time of last timeout) + 2T, so rounds genuinely
        # lengthen T, 2T, 3T...  (Measuring from the ORIGINAL start — the
        # reference's viewchanger.go:370-372 shape — makes deadlines land
        # at t0+T, t0+2T, ... = a CONSTANT cadence where the multiplier
        # does nothing except run away during long storms; observed at
        # backoff 150+ = a 1,500 s post-heal recovery stall.)
        sched.advance(vc._vc_timeout * 1.0)
        assert controller.synced == 1, "backoff window fired too early"
        # ...but 2T past the previous timeout does fire.
        sched.advance(vc._vc_timeout * 1.2)
        assert controller.synced == 2
        assert vc._backoff_factor == 3
        vc.stop()

    def test_resend_rebroadcasts_pending_vote(self):
        from consensus_tpu.wire import ViewChange as VC

        vc, sched, comm, controller, timer = _make_vc()
        vc.start(0)
        vc.start_view_change(0, stop_view=True)
        votes_before = sum(
            1 for m in comm.broadcasts
            if isinstance(m, VC) and m.next_view == 1
        )
        sched.advance(vc._resend_timeout + 1.5)  # below the vc timeout
        votes_after = sum(
            1 for m in comm.broadcasts
            if isinstance(m, VC) and m.next_view == 1
        )
        assert votes_after > votes_before, "pending vote was not re-sent"
        vc.stop()

    def test_successful_change_resets_backoff(self):
        from consensus_tpu.wire import NewView, SignedViewData, ViewChange as VC, encode_view_data

        vc, sched, comm, controller, timer = _make_vc()
        vc.start(0)
        vc.start_view_change(0, stop_view=True)
        sched.advance(vc._vc_timeout + 1.5)  # one escalation
        assert vc._backoff_factor == 2

        # Now let the change to view 2 complete: quorum of votes, then the
        # NewView from leader 3 (view 2 % 4 -> node 3).
        for sender in (1, 3, 4):
            vc.handle_message(sender, VC(next_view=2))
        data = ViewData(next_view=2, last_decision=Proposal())
        nv = NewView(signed_view_data=tuple(
            SignedViewData(
                signer=s,
                raw_view_data=encode_view_data(data),
                signature=b"sig-%d" % s,
            )
            for s in (1, 3, 4)
        ))
        vc._process_new_view(nv)
        assert controller.changed, "view change did not complete"
        assert vc._backoff_factor == 1, "completion must reset the backoff"
        vc.stop()


class TestNewViewMalformedMatrix:
    """Follower-side NewView + remaining ViewData malformed-input rows.

    Parity: reference viewchanger_test.go TestBadNewViewMessage:702 (wrong
    leader / wrong view / invalid signature / different last decision /
    sync / invalid last decision sequence / last decision not set /
    deliver / not enough) and the TestBadViewDataMessage:479 rows not yet
    mirrored elsewhere in this file (genesis-behind, wrong last decision
    view, behind sender)."""

    def _svd(self, signer, data, *, sig=None):
        from consensus_tpu.wire import SignedViewData, encode_view_data

        return SignedViewData(
            signer=signer,
            raw_view_data=encode_view_data(data),
            signature=sig if sig is not None else b"sig-%d" % signer,
        )

    def _collecting_vc(self):
        """Node 2 collecting for view 1 (it leads view 1, no rotation)."""
        from consensus_tpu.wire import ViewChange as VC

        vc, sched, comm, controller, timer = _make_vc()
        vc.start(0)
        for sender in (1, 3, 4):
            vc.handle_message(sender, VC(next_view=1))
        sched.advance(0.1)
        return vc, sched, comm, controller

    def _nv(self, data_by_signer):
        from consensus_tpu.wire import NewView

        return NewView(signed_view_data=tuple(
            self._svd(s, d) if not isinstance(d, tuple) else self._svd(s, d[0], sig=d[1])
            for s, d in data_by_signer
        ))

    def _sigs(self, ids):
        return tuple(Signature(id=i, value=b"sig-%d" % i) for i in ids)

    # -- NewView rows (reference TestBadNewViewMessage) ---------------------

    def test_new_view_from_non_leader_sender_ignored(self):
        """reference row "wrong leader": the NewView sender must be the
        expected leader of the current view; others are dropped before any
        content validation."""
        from consensus_tpu.wire import NewView

        vc, sched, comm, controller, timer = _make_vc()
        vc.start(0)  # leader of view 0 is node 1
        data = vd(next_view=0)
        nv = NewView(signed_view_data=tuple(
            self._svd(s, data) for s in (1, 3, 4)
        ))
        vc.handle_message(3, nv)  # not the leader
        assert not controller.changed
        vc.handle_message(1, nv)  # the leader: same content installs
        assert controller.changed
        vc.stop()

    def test_new_view_with_wrong_embedded_view_rejected(self):
        """reference row "wrong view": embedded ViewData for a different
        next view than the one being installed."""
        vc, sched, comm, controller = self._collecting_vc()
        data = vd(last_seq=0, next_view=2)
        vc._process_new_view(self._nv([(1, data), (3, data), (4, data)]))
        assert not controller.changed
        vc.stop()

    def test_new_view_with_forged_signature_rejected(self):
        """reference row "invalid signature"."""
        vc, sched, comm, controller = self._collecting_vc()
        data = vd(next_view=1)
        nv = self._nv([(1, data), (3, (data, b"sig-99")), (4, data)])
        vc._process_new_view(nv)
        assert not controller.changed
        vc.stop()

    def test_new_view_same_seq_different_decision_rejected(self):
        """reference row "different last decision": an embedded last
        decision at OUR sequence that isn't our decision proves a fork
        candidate — the whole NewView is refused."""
        vc, sched, comm, controller = self._collecting_vc()
        vc._checkpoint.set(proposal_at(1, payload=b"mine"), [])
        data = ViewData(
            next_view=1, last_decision=proposal_at(1, payload=b"theirs")
        )
        vc._process_new_view(self._nv([(1, data), (3, data), (4, data)]))
        assert not controller.changed
        vc.stop()

    def test_new_view_far_ahead_last_decision_triggers_sync(self):
        """reference row "sync": a last decision more than one ahead means
        we're behind — request a sync instead of installing."""
        vc, sched, comm, controller = self._collecting_vc()
        data = ViewData(
            next_view=1,
            last_decision=proposal_at(2),
            last_decision_signatures=self._sigs([1, 3, 4]),
        )
        before = controller.synced
        vc._process_new_view(self._nv([(1, data), (3, data), (4, data)]))
        assert controller.synced == before + 1
        assert not controller.changed
        vc.stop()

    def test_new_view_last_decision_view_ge_next_view_rejected(self):
        """reference row "invalid last decision sequence": a last decision
        claiming a view >= the view being installed is impossible."""
        vc, sched, comm, controller = self._collecting_vc()
        data = ViewData(
            next_view=1, last_decision=proposal_at(1, view=1),
            last_decision_signatures=self._sigs([1, 3, 4]),
        )
        vc._process_new_view(self._nv([(1, data), (3, data), (4, data)]))
        assert not controller.changed
        assert not controller.delivered
        vc.stop()

    def test_new_view_missing_last_decision_rejected(self):
        """reference row "last decision not set"."""
        vc, sched, comm, controller = self._collecting_vc()
        data = ViewData(next_view=1, last_decision=None)
        vc._process_new_view(self._nv([(1, data), (3, data), (4, data)]))
        assert not controller.changed
        vc.stop()

    def test_new_view_one_ahead_delivers_then_installs(self):
        """reference row "deliver" (happy variant): a NewView carrying a
        one-ahead decision with a valid quorum is delivered by US first,
        then the re-walk finds us caught up and installs."""
        vc, sched, comm, controller = self._collecting_vc()
        # Mimic the real application (the controller): deliver advances the
        # checkpoint — the re-walk loop terminates through it.
        orig_deliver = controller.deliver

        def deliver(proposal, signatures):
            out = orig_deliver(proposal, signatures)
            vc._checkpoint.set(proposal, tuple(signatures))
            return out

        controller.deliver = deliver
        decision = proposal_at(1)
        data = ViewData(
            next_view=1,
            last_decision=decision,
            last_decision_signatures=self._sigs([1, 3, 4]),
        )
        vc._process_new_view(self._nv([(1, data), (3, data), (4, data)]))
        assert [p for p, _ in controller.delivered] == [decision]
        assert controller.changed, "caught-up follower must install"
        vc.stop()

    def test_new_view_one_ahead_bad_signature_delivers_but_no_install(self):
        """reference row "deliver" (exact variant): the carried decision
        quorum is valid so it IS delivered, but the embedding ViewData's
        own signature is bad — no install."""
        vc, sched, comm, controller = self._collecting_vc()
        orig_deliver = controller.deliver

        def deliver(proposal, signatures):
            out = orig_deliver(proposal, signatures)
            vc._checkpoint.set(proposal, tuple(signatures))
            return out

        controller.deliver = deliver
        decision = proposal_at(1)
        data = ViewData(
            next_view=1,
            last_decision=decision,
            last_decision_signatures=self._sigs([1, 3, 4]),
        )
        nv = self._nv([
            (1, (data, b"sig-99")), (3, (data, b"sig-99")), (4, (data, b"sig-99")),
        ])
        vc._process_new_view(nv)
        assert [p for p, _ in controller.delivered] == [decision]
        assert not controller.changed
        vc.stop()

    def test_new_view_below_quorum_valid_rejected(self):
        """reference row "not enough": fewer distinct valid ViewData
        entries than the quorum."""
        vc, sched, comm, controller = self._collecting_vc()
        data = vd(next_view=1)
        vc._process_new_view(self._nv([(1, data), (3, data)]))  # 2 < 3
        assert not controller.changed
        vc.stop()

    def test_new_view_behind_entries_still_count(self):
        """Counter-row: entries BEHIND us are fine inside a NewView (the
        reference accepts them in validateNewViewMsg — only the new
        leader's ViewData path rejects behind senders)."""
        vc, sched, comm, controller = self._collecting_vc()
        vc._checkpoint.set(proposal_at(1), [])
        mine = ViewData(next_view=1, last_decision=proposal_at(1))
        behind = vd(next_view=1)  # genesis last decision, seq 0 < our 1
        nv = self._nv([(1, behind), (3, behind), (4, mine)])
        vc._process_new_view(nv)
        assert controller.changed
        vc.stop()

    # -- remaining ViewData rows (reference TestBadViewDataMessage) ---------

    def test_view_data_genesis_while_leader_ahead_rejected(self):
        """reference row "genesis": a genesis last decision when the leader
        has already decided something — the sender is behind."""
        vc, sched, comm, controller = self._collecting_vc()
        vc._checkpoint.set(proposal_at(2), [])
        vc.handle_message(3, self._svd(3, vd(next_view=1)))
        assert vc._view_data_votes.get(3) is None
        vc.stop()

    def test_view_data_last_decision_view_ge_next_view_rejected(self):
        """reference row "wrong last decision view"."""
        vc, sched, comm, controller = self._collecting_vc()
        data = ViewData(
            next_view=1, last_decision=proposal_at(1, view=1),
            last_decision_signatures=self._sigs([1, 3, 4]),
        )
        vc.handle_message(3, self._svd(3, data))
        assert vc._view_data_votes.get(3) is None
        assert not controller.delivered
        vc.stop()

    def test_view_data_behind_sender_rejected(self):
        """reference row adjacency ("the last decision seq is lower"):
        a sender whose last decision trails the leader's checkpoint cannot
        vouch for the new view's starting state."""
        vc, sched, comm, controller = self._collecting_vc()
        vc._checkpoint.set(proposal_at(2), [])
        data = ViewData(
            next_view=1, last_decision=proposal_at(1),
            last_decision_signatures=self._sigs([1, 3, 4]),
        )
        vc.handle_message(3, self._svd(3, data))
        assert vc._view_data_votes.get(3) is None
        vc.stop()


def test_decision_already_synced_not_delivered_again_from_view_data():
    """Deliver-twice guard: a decision already obtained via sync (the
    checkpoint advanced past it) must NOT be re-delivered when a ViewData
    carries the same decision — it counts as an equal-sequence vote with
    zero deliveries.  Parity: reference controller_test.go
    TestDeliverTwiceOnceFromSyncAndOnceFromViewData:1196 (there the guard
    is the checkpoint update; same guard here via _extract_current_
    sequence reading the checkpoint the sync path set)."""
    from consensus_tpu.wire import SignedViewData, ViewChange as VC, encode_view_data

    vc, sched, comm, controller, timer = _make_vc()
    vc.start(0)
    for sender in (1, 3, 4):
        vc.handle_message(sender, VC(next_view=1))
    sched.advance(0.1)

    # A sync (not shown) delivered decision seq 1 and set the checkpoint.
    decision = proposal_at(1)
    vc._checkpoint.set(decision, [Signature(id=i, value=b"sig-%d" % i) for i in (1, 3, 4)])

    data = ViewData(
        next_view=1,
        last_decision=decision,
        last_decision_signatures=tuple(
            Signature(id=i, value=b"sig-%d" % i) for i in (1, 3, 4)
        ),
    )
    svd = SignedViewData(
        signer=3, raw_view_data=encode_view_data(data), signature=b"sig-3"
    )
    vc.handle_message(3, svd)
    assert controller.delivered == [], "already-synced decision re-delivered"
    assert vc._view_data_votes.get(3) is not None, "equal-seq vote must count"
    vc.stop()


def test_laggard_help_refires_on_vote_resend():
    """The laggard-help broadcast must fire on EVERY resend of a sender's
    latest vote (reference util.go sendRecv: `next == nv.n[sender]`), not
    once per (view, sender) — the first help can be lost to the same
    fault that diverged the views in the first place.  Regression for the
    seed-1234 chaos wedge: three replicas collecting for views 19/22/23
    (no two alike) never converge if help cannot re-fire after a heal."""
    from consensus_tpu.wire import ViewChange as VC

    vc, sched, comm, controller, timer = _make_vc()
    vc.start(0)
    # Shape the changer like a post-chaos survivor: installed view 1,
    # then advanced to curr 2 and started collecting for 3.
    vc.curr_view = 2
    vc.real_view = 1
    vc.next_view = 3

    def helps():
        return [
            m for m in comm.broadcasts
            if isinstance(m, VC) and m.next_view == 2
        ]

    vc.handle_message(3, VC(next_view=2))  # laggard vote: real < 2 < curr+1
    assert len(helps()) == 1, "first laggard vote must trigger help"
    # An IMMEDIATE duplicate is rate-limited (helps are broadcasts other
    # helpers react to; unthrottled re-fires amplify exponentially).
    vc.handle_message(3, VC(next_view=2))
    assert len(helps()) == 1, "immediate duplicate must be throttled"
    # The laggard's periodic resend (a resend-interval later) re-fires.
    sched.advance(vc._resend_timeout + 0.1)
    vc.handle_message(3, VC(next_view=2))
    assert len(helps()) == 2, "help must re-fire on the periodic resend"
    sched.advance(vc._resend_timeout + 0.1)

    # A newer vote from the same sender retires the old one: resending the
    # stale view no longer triggers help.
    vc.handle_message(3, VC(next_view=3))
    before = len(helps())
    vc.handle_message(3, VC(next_view=2))
    assert len(helps()) == before, "stale (non-latest) votes must not help"
    vc.stop()


class TestEmbeddedInFlightViewSafety:
    """The two halves of the seed-1144/1427 chaos-hunt FORK (round 5):
    an embedded in-flight commit view that (a) survived into the next view
    change and delivered a stale decision after that view re-proposed the
    same sequence, and (b) minted a commit signature with no persisted
    endorsement, so later ViewData stopped attesting the prepared proposal
    and CheckInFlight concluded "no in-flight"."""

    def _vc_with_embedded(self):
        from consensus_tpu.wire import ViewChange as VC

        vc, sched, comm, controller, timer = _make_vc()
        vc.start(0)
        for sender in (1, 3, 4):
            vc.handle_message(sender, VC(next_view=1))
        sched.advance(0.1)
        proposal = proposal_at(1, view=0, payload=b"in-flight")
        vc._commit_in_flight(proposal)
        assert vc._in_flight_view is not None, "embedded view must start"
        return vc, sched, comm, controller, proposal

    def test_embedded_commit_is_persisted_before_broadcast(self):
        """Signing the embedded commit is an ENDORSEMENT: the standard
        [proposed, commit] WAL tail must exist before the signature can
        leave the process, and InFlightData must mark it prepared."""
        from consensus_tpu.wire import ProposedRecord, SavedCommit

        vc, sched, comm, controller, proposal = self._vc_with_embedded()
        # PersistedState wraps the MemWAL; decode the WAL's entries.
        from consensus_tpu.wire import decode_saved

        records = [decode_saved(e) for e in vc._state._wal.entries]
        assert any(
            isinstance(r, ProposedRecord) and r.pre_prepare.proposal == proposal
            for r in records
        ), "embedded endorsement missing its ProposedRecord"
        assert any(
            isinstance(r, SavedCommit)
            and r.commit.digest == proposal.digest()
            for r in records
        ), "embedded endorsement missing its SavedCommit"
        assert vc._in_flight.proposal() == proposal
        assert vc._in_flight.is_prepared()
        vc.stop()

    def test_view_data_attests_embedded_endorsement(self):
        """After starting the embedded commit, every ViewData this replica
        produces must attest (proposal, prepared=True) — a later view
        change must adopt the proposal, not re-propose the sequence."""
        from consensus_tpu.wire import decode_view_data

        vc, sched, comm, controller, proposal = self._vc_with_embedded()
        svd = vc._prepare_view_data()
        vd_out = decode_view_data(svd.raw_view_data)
        assert vd_out.in_flight_proposal == proposal
        assert vd_out.in_flight_prepared is True
        vc.stop()

    def test_advancing_view_change_aborts_embedded_view(self):
        """Joining the NEXT view change must abort a live embedded view —
        the reference's blocking commitInFlightProposal defer-aborts it on
        every exit path; event-driven concurrency must not let it deliver
        a stale decision after the next view re-proposes the sequence."""
        from consensus_tpu.wire import ViewChange as VC

        vc, sched, comm, controller, proposal = self._vc_with_embedded()
        embedded = vc._in_flight_view
        for sender in (1, 3, 4):
            vc.handle_message(sender, VC(next_view=2))
        sched.advance(0.1)
        assert vc._in_flight_view is None, "embedded view survived the advance"
        assert embedded.stopped, "embedded view not aborted"
        vc.stop()

    def test_inform_new_view_aborts_embedded_view(self):
        vc, sched, comm, controller, proposal = self._vc_with_embedded()
        embedded = vc._in_flight_view
        vc.inform_new_view(5)
        assert vc._in_flight_view is None
        assert embedded.stopped
        vc.stop()


class TestCheckInFlightUnpreparedArguments:
    """Round-5 rule (seed-1268 chaos livelock): an UNPREPARED attestation
    of a different proposal at the expected sequence counts as NO-ARGUMENT
    for condition A — it already counts as "no prepared in-flight" for
    condition B, and it carries no commit signature, so it cannot endanger
    the prepared candidate."""

    def _p(self, view, payload):
        return proposal_at(2, view=view, payload=payload)

    def test_split_mixed_view_attestations_resolve_to_prepared(self):
        """The exact seed-1268 shape: two replicas prepared P@v10, the
        other two hold later views' unprepared proposals at the same
        sequence — the prepared proposal must be adopted."""
        p10 = self._p(10, b"p10")
        msgs = [
            vd(last_seq=1, in_flight=self._p(16, b"p16")),          # unprepared
            vd(last_seq=1, in_flight=self._p(13, b"p13")),          # unprepared
            vd(last_seq=1, in_flight=p10, prepared=True),
            vd(last_seq=1, in_flight=p10, prepared=True),
        ]
        ok, no, prop = check_in_flight(msgs, F, QUORUM)
        assert (ok, no, prop) == (True, False, p10)

    def test_two_prepared_still_argue(self):
        """PREPARED attestations of different proposals still contradict:
        either might hide a commit quorum, so the change must wait."""
        a = self._p(10, b"a")
        b = self._p(12, b"b")
        msgs = [
            vd(last_seq=1, in_flight=a, prepared=True),
            vd(last_seq=1, in_flight=a, prepared=True),
            vd(last_seq=1, in_flight=b, prepared=True),
            vd(last_seq=1, in_flight=b, prepared=True),
        ]
        ok, no, prop = check_in_flight(msgs, F, QUORUM)
        assert (ok, no, prop) == (False, False, None)


def test_f_plus_one_far_ahead_senders_trigger_sync():
    """Round-5 rule (seed-1144 chaos livelock): ONE far-ahead ViewData
    sender might be lying (reject, like the reference), but f+1 DISTINCT
    far-ahead senders contain an honest one — the collecting leader is
    provably behind and must sync instead of waiting for a view-change
    timeout that vote-driven joins keep resetting."""
    from consensus_tpu.wire import SignedViewData, ViewChange as VC, encode_view_data

    vc, sched, comm, controller, timer = _make_vc()
    vc.start(0)
    for sender in (1, 3, 4):
        vc.handle_message(sender, VC(next_view=1))
    sched.advance(0.1)

    def far_ahead_svd(sender):
        data = ViewData(
            next_view=1,
            last_decision=proposal_at(5),  # 5 >> our 0 + 1
            last_decision_signatures=tuple(
                Signature(id=i, value=b"sig-%d" % i) for i in (1, 3, 4)
            ),
        )
        return SignedViewData(
            signer=sender,
            raw_view_data=encode_view_data(data),
            signature=b"sig-%d" % sender,
        )

    before = controller.synced
    vc.handle_message(3, far_ahead_svd(3))
    assert controller.synced == before, "one sender must not trigger sync"
    assert vc._view_data_votes.get(3) is None  # still rejected
    vc.handle_message(3, far_ahead_svd(3))  # duplicate sender: still one
    assert controller.synced == before
    vc.handle_message(4, far_ahead_svd(4))  # f+1 distinct senders
    assert controller.synced == before + 1, "f+1 far-ahead senders must sync"
    vc.stop()
