"""Scenario matrix: catch-up concurrent with view changes, stale-sync
fetch-state recovery, and blacklist redemption.

Parity model (reference test/basic_test.go):
TestCatchingUpWithViewChange:567, TestFetchStateWhenSyncReturnsPrevView:2742,
TestBlacklistAndRedemption:1978.

Every scenario asserts no-fork safety plus post-heal liveness.
"""

from consensus_tpu.testing import Cluster, make_request
from consensus_tpu.wire import decode_view_metadata

FAST = {
    "request_forward_timeout": 1.0,
    "request_complain_timeout": 4.0,
    "request_auto_remove_timeout": 120.0,
    "view_change_resend_interval": 2.0,
    "view_change_timeout": 10.0,
    "leader_heartbeat_timeout": 20.0,
}


def test_catching_up_while_view_change_runs():
    """A node that missed a decision rejoins at the same moment the leader
    is partitioned away: its catch-up (sync of block 1) and the cluster's
    view change run concurrently, and both must land — the laggard ends up
    with every block, the new view orders the next request, no fork.
    Parity: basic_test.go:567 (TestCatchingUpWithViewChange)."""
    cluster = Cluster(4, config_tweaks=FAST)
    cluster.start()

    # Node 4 misses the first decision entirely.
    cluster.network.partition([4])
    cluster.submit_to_all(make_request("c", 0))
    assert cluster.run_until_ledger(1, node_ids=[1, 2, 3], max_time=300.0)

    # Swap the partition: node 4 heals exactly as leader 1 drops out.
    cluster.network.heal()
    cluster.network.partition([1])

    # New requests reach only 2, 3, 4 — the view change (complaint
    # cascade) and node 4's catch-up must interleave without stalling.
    for node_id in (2, 3, 4):
        cluster.nodes[node_id].submit(make_request("c", 1))
    assert cluster.run_until_ledger(2, node_ids=[2, 3, 4], max_time=900.0), (
        "catch-up + view change did not converge"
    )
    assert [d.proposal for d in cluster.nodes[4].app.ledger[:1]] == [
        d.proposal for d in cluster.nodes[2].app.ledger[:1]
    ], "laggard caught up with a different block 1"
    cluster.assert_ledgers_consistent()


def test_stale_sync_resolved_by_fetching_cluster_state():
    """A deposed ex-leader rejoins after TWO view changes that decided
    nothing new: its Synchronizer has nothing to add (the ledger is already
    current), so only the fetch-state exchange (StateTransferRequest →
    f+1 equal (view, seq) votes) can teach it the cluster's current view.
    It must adopt that view and participate in ordering again.
    Parity: basic_test.go:2742 (TestFetchStateWhenSyncReturnsPrevView);
    fetch-state: reference controller.go:707-716, statecollector.go:77-130."""
    cluster = Cluster(4, config_tweaks=FAST)
    cluster.start()

    # One decision in view 0 so every ledger is non-empty and current.
    cluster.submit_to_all(make_request("c", 0))
    assert cluster.run_until_ledger(1, max_time=300.0)

    # Depose leader 1 (view 0 -> 1, leader 2 takes over)...
    cluster.network.partition([1])
    cluster.submit_to_all(make_request("c", 1))
    assert cluster.run_until_ledger(2, node_ids=[2, 3, 4], max_time=900.0)

    # ...then heal 1 and depose leader 2 as well (view 1 -> 2, leader 3).
    # Node 1 rejoins behind on BOTH axes — one ledger entry (block 2) and
    # two views — so its recovery needs sync for the block and fetch-state
    # for the view.
    cluster.network.heal()
    cluster.network.partition([2])
    cluster.submit_to_all(make_request("c", 2))
    assert cluster.run_until_ledger(3, node_ids=[1, 3, 4], max_time=900.0), (
        "node 1 did not catch up (ledger) and adopt the cluster view"
    )

    # Node 1 now holds every decision; its view knowledge must allow it to
    # keep participating after node 2 heals too.
    cluster.network.heal()
    cluster.submit_to_all(make_request("c", 3))
    assert cluster.run_until_ledger(4, max_time=900.0)
    cluster.assert_ledgers_consistent()


def _latest_blacklist(node):
    md = decode_view_metadata(node.app.ledger[-1].proposal.metadata)
    return list(md.black_list)


def test_blacklist_redemption_restores_rotation_through_healed_node():
    """Rotation + blacklisting: a partitioned leader lands on the
    blacklist (decisions' metadata carries it); after it heals and keeps
    prepping decisions, >f observers vouch for it and the deterministic
    update REDEEMS it — later decisions carry an empty blacklist and
    rotation flows through the healed node again.
    Parity: basic_test.go:1978 (TestBlacklistAndRedemption);
    redemption rule: reference util.go:436-497."""
    n = 7
    cluster = Cluster(
        n,
        config_tweaks=dict(FAST, decisions_per_leader=1),
        leader_rotation=True,
    )
    cluster.start()

    # Leader 1 is partitioned before anything is ordered: the ensuing view
    # change (with rotation active) blacklists it.
    cluster.network.partition([1])
    healthy = [i for i in range(2, n + 1)]
    cluster.submit_to_all(make_request("c", 0))
    assert cluster.run_until_ledger(1, node_ids=healthy, max_time=900.0)
    assert 1 in _latest_blacklist(cluster.nodes[2]), (
        "partitioned ex-leader did not land on the blacklist"
    )

    # Heal node 1.  It catches up and its prepares start being observed;
    # within a handful of decisions the blacklist update must redeem it.
    cluster.network.heal()
    blocks = 1
    for i in range(1, 10):
        cluster.submit_to_all(make_request("c", i))
        blocks += 1
        assert cluster.run_until_ledger(blocks, max_time=900.0), (
            f"rotation stalled at block {blocks} after heal"
        )
        if not _latest_blacklist(cluster.nodes[2]):
            break
    assert not _latest_blacklist(cluster.nodes[2]), (
        "healed node was never redeemed from the blacklist"
    )

    # Liveness through a full rotation cycle INCLUDING node 1's turns.
    for i in range(10, 10 + n):
        cluster.submit_to_all(make_request("c", i))
        blocks += 1
        assert cluster.run_until_ledger(blocks, max_time=900.0), (
            f"rotation through the redeemed node stalled at block {blocks}"
        )
    cluster.assert_ledgers_consistent()
