"""SimNetwork adversary primitives: the byzantine-network knobs the chaos
engine drives (duplicate / reorder / stale replay), injected-event
accounting, hook composition on one link, and the heal()/partition()
edge cases the soak loops leaned on implicitly.
"""

import pytest

from consensus_tpu.runtime.scheduler import SimScheduler
from consensus_tpu.testing.network import INJECTED_EVENT_KINDS, SimNetwork


def _net(seed=0, ids=(1, 2, 3, 4)):
    sched = SimScheduler()
    net = SimNetwork(sched, seed=seed)
    inboxes = {}
    for nid in ids:
        inboxes[nid] = []
        net.register(
            nid,
            (lambda box: lambda s, p, r: box.append((sched.now(), s, p)))(
                inboxes[nid]
            ),
        )
    return sched, net, inboxes


# --- heal() must clear EVERY knob ------------------------------------------


def test_heal_clears_per_link_delay_overrides():
    """Regression: heal() cleared cuts/disconnects/loss but LEFT per-link
    delay overrides armed, so a 'healed' network kept a slow link forever
    — post-heal liveness assertions were running against residual
    adversary state."""
    sched, net, inboxes = _net()
    net.set_delay(1, 2, 5.0)
    net.heal()
    net.send(1, 2, b"x", is_request=True)
    sched.advance(0.01)
    assert inboxes[2], "message lost after heal"
    at, _, _ = inboxes[2][0]
    assert at == pytest.approx(net.default_delay), (
        f"delivered at {at}: the pre-heal delay override survived heal()"
    )


def test_heal_clears_byzantine_network_knobs_and_buffers():
    sched, net, inboxes = _net()
    net.set_duplicate(1, 2, 1.0)
    net.set_reorder(1, 2, 1.0)
    net.set_replay(1, 2, 1.0)
    net.send(1, 2, b"seed-capture", is_request=True)
    sched.advance(0.1)
    net.heal()
    before = dict(net.injected)
    inboxes[2].clear()
    net.send(1, 2, b"clean", is_request=True)
    sched.advance(0.1)
    assert [p for _, _, p in inboxes[2]] == [b"clean"]  # exactly once
    assert dict(net.injected) == before, "healed network still injecting"


# --- the byzantine-network primitives --------------------------------------


def test_duplicate_delivers_twice_and_counts():
    sched, net, inboxes = _net()
    net.set_duplicate(1, 2, 1.0)
    net.send(1, 2, b"m", is_request=True)
    sched.advance(0.1)
    assert [p for _, _, p in inboxes[2]] == [b"m", b"m"]
    assert net.injected["duplicated"] == 1


def test_reorder_lets_later_sends_overtake():
    sched, net, inboxes = _net()
    net.set_reorder(1, 2, 1.0)
    net.send(1, 2, b"first", is_request=True)  # held back 2-5x delay
    net.set_reorder(1, 2, 0.0)
    net.send(1, 2, b"second", is_request=True)
    sched.advance(0.1)
    assert [p for _, _, p in inboxes[2]] == [b"second", b"first"]
    assert net.injected["reordered"] == 1


def test_replay_redelivers_the_stalest_capture():
    sched, net, inboxes = _net()
    net.set_replay(1, 2, 1.0)
    net.send(1, 2, b"old", is_request=True)   # buffer empty: captured only
    net.send(1, 2, b"new", is_request=True)   # replays the stale b"old"
    sched.advance(0.1)
    payloads = sorted(p for _, _, p in inboxes[2])
    assert payloads == [b"new", b"old", b"old"]
    assert net.injected["replayed"] == 1


def test_unarmed_knobs_consume_no_rng():
    """Pinned soak/chaos seeds replay the exact rng stream the network
    consumed when they were recorded — the duplicate/reorder/replay knobs
    must draw NOTHING while unarmed, or every pre-existing seed shifts."""
    sched, net, _ = _net(seed=99)
    state = net.rng.getstate()
    for i in range(50):
        net.send(1, 2, b"m%d" % i, is_request=False)
    assert net.rng.getstate() == state


def test_injected_counter_covers_exactly_the_contract_kinds():
    sched, net, _ = _net()
    net.set_loss(1, 2, 1.0)
    net.send(1, 2, b"m", is_request=True)
    sched.advance(0.01)
    assert net.injected["dropped"] == 1
    assert set(net.injected) <= set(INJECTED_EVENT_KINDS)


# --- hook composition on one link ------------------------------------------


def test_mutate_lose_and_loss_compose_on_one_link():
    """All three per-message hooks armed on the SAME link: loss rolls
    first, mutate_send next (None vetoes), the receiver-side filter last —
    and every non-delivered message is accounted as an injected drop, so
    sent == delivered + injected regardless of which stage ate it."""
    sched, net, inboxes = _net(seed=5)
    net.set_loss(1, 2, 0.5)
    net.mutate_send = lambda s, t, m: None if m.startswith(b"veto") else m + b"|mut"
    net.lose_messages = lambda t, s, m: m.startswith(b"filter")
    sent = [b"m%d" % i for i in range(20)]
    sent += [b"veto-a", b"veto-b", b"filter-a", b"filter-b"]
    for m in sent:
        net.send(1, 2, m, is_request=True)
    sched.advance(0.1)
    delivered = [p for _, _, p in inboxes[2]]
    assert delivered, "loss p=0.5 cannot have eaten everything (seeded)"
    assert all(p.endswith(b"|mut") for p in delivered)
    assert not any(p.startswith(b"filter") for p in delivered)
    assert len(delivered) + net.injected["dropped"] == len(sent)

    # And the composition is deterministic: same seed, same survivors.
    sched2, net2, inboxes2 = _net(seed=5)
    net2.set_loss(1, 2, 0.5)
    net2.mutate_send = lambda s, t, m: None if m.startswith(b"veto") else m + b"|mut"
    net2.lose_messages = lambda t, s, m: m.startswith(b"filter")
    for m in sent:
        net2.send(1, 2, m, is_request=True)
    sched2.advance(0.1)
    assert [p for _, _, p in inboxes2[2]] == delivered


# --- partition vs crashed nodes --------------------------------------------


def test_partition_leaks_around_crashed_node_without_membership():
    """Documents the footgun the partition() docstring warns about: with
    no ``membership`` set, the boundary is computed over the LIVE
    registration set, so a node crashed (unregistered) at partition time
    gets no cut links — after it restarts, traffic to and from it tunnels
    straight through the 'partition'.  Cluster avoids this by setting
    membership to the full configured id set."""
    sched, net, inboxes = _net()
    net.unregister(3)  # crashed
    net.partition([1])  # cuts computed over live ids {1, 2, 4} only
    # The cut works against live nodes...
    net.send(1, 2, b"cut?", is_request=True)
    sched.advance(0.01)
    assert not inboxes[2]
    # ...but the restarted node was never cut: the partition leaks.
    net.register(3, lambda s, p, r: inboxes[3].append((sched.now(), s, p)))
    net.send(1, 3, b"leak", is_request=True)
    sched.advance(0.01)
    assert [p for _, _, p in inboxes[3]] == [b"leak"]

    # With membership set (what Cluster does), the same sequence is tight.
    sched, net, inboxes = _net()
    net.membership = [1, 2, 3, 4]
    net.unregister(3)
    net.partition([1])
    net.register(3, lambda s, p, r: inboxes[3].append((sched.now(), s, p)))
    net.send(1, 3, b"leak?", is_request=True)
    sched.advance(0.01)
    assert not inboxes[3]
